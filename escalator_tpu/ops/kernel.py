"""Batched scale-decision kernel: all nodegroups in one device program.

This replaces the reference's serial per-nodegroup loop
(/root/reference/pkg/controller/controller.go:416-445) and the O(pods) Go aggregation
loops (pkg/k8s/util.go:27-51) with:

- one integer segment-sum sweep over the flat pod array (requests per group),
- one masked segment-sum sweep over the flat node array (capacity + counts per group),
- vectorized float64 percent/delta math over the ``[G]`` group axis, bit-matching
  calcPercentUsage (pkg/controller/util.go:58-81) and calcScaleUpDelta
  (pkg/controller/util.go:13-46) including the math.MaxFloat64 scale-from-zero sentinel,
- ONE combined multi-key device sort producing both the scale-down
  (oldest-first, pkg/controller/sort.go:12-24) and untaint (newest-first,
  sort.go:27-39) orders for every group at once, segment-partitioned by
  offsets (lanes carry a selection-class major key; see decide()),
- the reaper eligibility mask (pkg/controller/scale_down.go:51-99) via a per-node
  pod-count segment sum.

Everything is fixed-shape and branch-free (jnp.where/select) except one deliberate
data-dependent branch: each ordering sort sits behind a ``lax.cond`` that skips the
full node-axis sort when its selection is empty (healthy clusters have no tainted
nodes most ticks). XLA compiles a single fused program per branch; jit caches on the
padded shapes chosen by the packer (`escalator_tpu.core.arrays.pack_cluster`).

Status codes mirror `escalator_tpu.core.semantics.DecisionStatus`, the golden model
this kernel is parity-tested against.
"""

from __future__ import annotations

import logging
from dataclasses import dataclass, fields
from functools import partial

from escalator_tpu.jaxconfig import ensure_x64

ensure_x64()

import jax
import jax.numpy as jnp
import numpy as np
from jax import tree_util

from escalator_tpu.core.arrays import NO_TAINT_TIME, ClusterArrays, GroupArrays, NodeArrays, PodArrays
from escalator_tpu.core.semantics import MAX_FLOAT64, DecisionStatus

tree_util.register_pytree_node(
    ClusterArrays, ClusterArrays.tree_flatten, ClusterArrays.tree_unflatten
)


@dataclass
class DecisionArrays:
    """Kernel outputs. ``[G]`` per-group decisions + ``[N]`` per-node selections."""

    status: jnp.ndarray            # int32 [G] DecisionStatus codes
    nodes_delta: jnp.ndarray       # int32 [G] the scaleNodeGroup decision value
    cpu_percent: jnp.ndarray       # float64 [G]
    mem_percent: jnp.ndarray       # float64 [G]
    cpu_request_milli: jnp.ndarray   # int64 [G]
    mem_request_bytes: jnp.ndarray   # int64 [G]
    cpu_capacity_milli: jnp.ndarray  # int64 [G]
    mem_capacity_bytes: jnp.ndarray  # int64 [G]
    num_pods: jnp.ndarray          # int32 [G]
    num_nodes: jnp.ndarray         # int32 [G]
    num_untainted: jnp.ndarray     # int32 [G]
    num_tainted: jnp.ndarray       # int32 [G]
    num_cordoned: jnp.ndarray      # int32 [G]
    # Node selections (global node indices):
    # scale-down victims: untainted nodes ordered (group asc, creation asc); group g's
    # victims occupy slots [untainted_offsets[g], untainted_offsets[g+1]).
    scale_down_order: jnp.ndarray   # int32 [N]
    untainted_offsets: jnp.ndarray  # int32 [G+1]
    # untaint candidates: tainted nodes ordered (group asc, creation desc)
    untaint_order: jnp.ndarray      # int32 [N]
    tainted_offsets: jnp.ndarray    # int32 [G+1]
    reap_mask: jnp.ndarray          # bool [N] eligible for deletion this tick
    node_pods_remaining: jnp.ndarray  # int32 [N] non-daemonset pods per node

    def tree_flatten(self):
        return [getattr(self, f.name) for f in fields(self)], None

    @classmethod
    def tree_unflatten(cls, aux, leaves):
        return cls(*leaves)


tree_util.register_pytree_node(
    DecisionArrays, DecisionArrays.tree_flatten, DecisionArrays.tree_unflatten
)

#: The [G] DecisionArrays columns the incremental path persists across ticks
#: (everything except the per-node selections, which are recomputed O(N)
#: elementwise each tick). Order matters nowhere; membership is the contract
#: delta_decide's scatter loop and the parity soak both iterate.
GROUP_DECISION_FIELDS = (
    "status", "nodes_delta", "cpu_percent", "mem_percent",
    "cpu_request_milli", "mem_request_bytes",
    "cpu_capacity_milli", "mem_capacity_bytes",
    "num_pods", "num_nodes", "num_untainted", "num_tainted", "num_cordoned",
)


@dataclass
class GroupAggregates:
    """Persistent device-resident aggregate state for the incremental decide
    (the round-8 tentpole): the exact integer sums ``aggregate_pods`` /
    ``aggregate_nodes`` produce, maintained by per-tick deltas from the
    scatter phase (ops.device_state) instead of an O(cluster) recompute.
    All sums are int64 — the R2 dtype-parity contract makes the delta
    maintenance drift-free by construction (no float accumulation anywhere).

    ``dirty`` marks groups whose decision may have changed since the last
    decide: any group an aggregate delta landed in, plus any group whose
    config/state row changed. ``delta_decide`` consumes (and clears) it.
    """

    cpu_req: jnp.ndarray              # int64 [G]
    mem_req: jnp.ndarray              # int64 [G]
    num_pods: jnp.ndarray             # int64 [G]
    cpu_cap: jnp.ndarray              # int64 [G]
    mem_cap: jnp.ndarray              # int64 [G]
    num_nodes: jnp.ndarray            # int64 [G]
    num_untainted: jnp.ndarray        # int64 [G]
    num_tainted: jnp.ndarray          # int64 [G]
    num_cordoned: jnp.ndarray         # int64 [G]
    node_pods_remaining: jnp.ndarray  # int64 [N]
    dirty: jnp.ndarray                # bool [G]

    def tree_flatten(self):
        return [getattr(self, f.name) for f in fields(self)], None

    @classmethod
    def tree_unflatten(cls, aux, leaves):
        return cls(*leaves)


tree_util.register_pytree_node(
    GroupAggregates, GroupAggregates.tree_flatten, GroupAggregates.tree_unflatten
)

_I32 = jnp.int32
_I64 = jnp.int64
_F64 = jnp.float64


#: Platforms the CPU-fallback auto-select has already logged for (the log is
#: one-time per process+platform; the decision itself repeats every call).
_AUTOSELECT_LOGGED: set = set()


def _resolve_impl_env(env: str, platform: "str | None") -> str:
    """CPU-fallback guard shared by the env-driven impl selectors (round 8):
    a deployment that pins ESCALATOR_TPU_KERNEL_IMPL=pallas for its TPU fleet
    and then lands on the CPU fallback (wedged tunnel, dev laptop, CI) would
    silently run interpreter-mode Pallas on the hot path — bench cfg9
    measured that path losing 5.8-120x to the XLA scatter sweep on every row
    on this chip. Auto-select "xla" there, with a ONE-TIME log naming the
    measured reason. ``pallas-force`` bypasses the guard (tests and debugging
    want interpreter Pallas on purpose) and resolves to "pallas" everywhere.
    Any other value — including the SET-but-empty string — passes through
    untouched, so decide()'s fail-fast ValueError contract is unchanged."""
    if env == "pallas-force":
        return "pallas"
    if env != "pallas":
        return env
    from escalator_tpu.jaxconfig import PALLAS_COMPILED_PLATFORMS

    if platform is None:
        import jax

        platform = jax.default_backend()
    if platform in PALLAS_COMPILED_PLATFORMS:
        return "pallas"
    if platform not in _AUTOSELECT_LOGGED:
        _AUTOSELECT_LOGGED.add(platform)
        logging.getLogger("escalator_tpu.kernel").warning(
            "ESCALATOR_TPU_KERNEL_IMPL=pallas on platform %r: auto-selecting "
            "impl='xla' — compiled Pallas exists only on %s, and bench cfg9 "
            "measured interpreter-mode Pallas 5.8-120x slower than the XLA "
            "scatter sweep on every row on this chip. Set "
            "ESCALATOR_TPU_KERNEL_IMPL=pallas-force to run it anyway.",
            platform, sorted(PALLAS_COMPILED_PLATFORMS))
    return "xla"


def default_impl(platform: "str | None" = None) -> str:
    """Aggregation-sweep selector from ESCALATOR_TPU_KERNEL_IMPL: "xla"
    (default, one scatter-add per column) or "pallas" (the fused MXU sweep).
    Read by every decider constructor that doesn't get an explicit ``impl`` —
    backends, the mesh-sharded and pod-axis deciders alike — so the env switch
    means the same thing everywhere. Invalid values fail fast in decide().

    A ``pallas`` env on a platform without compiled Pallas auto-selects
    "xla" with a one-time log (see :func:`_resolve_impl_env`); ``platform``
    defaults to the live jax backend and is only resolved when the env asks
    for pallas, so the common path never touches jax."""
    import os

    return _resolve_impl_env(
        os.environ.get("ESCALATOR_TPU_KERNEL_IMPL", "xla"), platform)


def native_tick_impl(platform: str) -> str:
    """Aggregation impl for the EVENT-DRIVEN NATIVE TICK specifically: the env
    override if set, else "pallas" on a TPU, else "xla".

    The native store reuses freed slots across groups, so its layout churns
    into group-interleaved lanes — exactly the case the Pallas sorted-MXU path
    was built for, and where it measured 1.57x faster than XLA scatter on a
    v5e chip (bench cfg9, churned_interleaved row; see ops/pallas_kernel.py).
    The repack backends keep the XLA default: on small group-contiguous
    layouts the scatter path measured faster. The platform check shares
    ``jaxconfig.PALLAS_COMPILED_PLATFORMS`` with
    ``pallas_kernel._use_interpret``: compiled Pallas exists only there — any
    other platform (cpu, gpu) would silently get interpreter-mode Pallas on
    the hot path, far slower than the scatter sweep it replaces.

    An env var that is SET but empty falls through to decide()'s fail-fast
    ValueError, same as ``default_impl`` — the knob misconfigured must not
    behave differently across backends. A ``pallas`` env on a platform
    without compiled Pallas auto-selects "xla" with a one-time log
    (:func:`_resolve_impl_env`); ``pallas-force`` overrides that guard."""
    import os

    from escalator_tpu.jaxconfig import PALLAS_COMPILED_PLATFORMS

    env = os.environ.get("ESCALATOR_TPU_KERNEL_IMPL")
    if env is not None:
        return _resolve_impl_env(env, platform)
    return "pallas" if platform in PALLAS_COMPILED_PLATFORMS else "xla"


def _segsum(values, segment_ids, num_segments):
    return jax.ops.segment_sum(values, segment_ids, num_segments=num_segments)


def node_pods_remaining_sweep(p: PodArrays, node_group: jnp.ndarray, N: int):
    """The per-node pod-count half of :func:`aggregate_pods` (the
    same-group filter of controller.go:259), callable on its own: the
    incremental scatter path re-runs JUST this O(P) sweep on the rare tick a
    node lane's group column changes (pods pointing at that node flip their
    contribution without appearing in the delta batch — see
    ops.device_state._scatter_update_aggs). Returns int64 ``[N]``."""
    pvalid = p.valid
    pod_node = jnp.where(pvalid & (p.node >= 0), p.node, 0)
    pod_on_node_w = (
        pvalid
        & (p.node >= 0)
        # a pod only counts for its own group's node-info map (the reference
        # builds the map from group-filtered pod+node lists, controller.go:259)
        & (p.group == node_group[jnp.clip(p.node, 0, N - 1)])
    )
    return _segsum(pod_on_node_w.astype(_I64), pod_node, N)


def aggregate_pods(p: PodArrays, node_group: jnp.ndarray, G: int, N: int,
                   impl: str = "xla"):
    """Per-group pod-request sums + per-node pod counts — the O(P) sweep
    (replaces pkg/k8s/util.go:27-38). Separable from the node sweep so the
    pod-axis-sharded path (parallel/podaxis.py) can psum partial results:
    every output is a plain sum over pods, so partial sums over pod shards
    combine exactly.

    node_group is the full ``[N]`` node->group vector (needed for the
    same-group pod filter of node_pods_remaining, controller.go:259).
    Returns (cpu_req[G] i64, mem_req[G] i64, num_pods[G] i64,
    node_pods_remaining[N] i64) — callers downcast counts.
    """
    pvalid = p.valid
    pgroup = jnp.where(pvalid, p.group, 0)
    pw = pvalid.astype(_I64)

    if impl == "pallas":
        from escalator_tpu.ops import pallas_kernel

        pod_sums = pallas_kernel.fused_segment_sums(
            pgroup,
            pvalid,
            {"cpu_req": p.cpu_milli * pw, "mem_req": p.mem_bytes * pw},
            {"num_pods": pvalid},
            num_segments=G,
        )
        cpu_req = pod_sums["cpu_req"]
        mem_req = pod_sums["mem_req"]
        num_pods = pod_sums["num_pods"]
    else:
        cpu_req = _segsum(p.cpu_milli * pw, pgroup, G)
        mem_req = _segsum(p.mem_bytes * pw, pgroup, G)
        num_pods = _segsum(pw, pgroup, G)
    node_pods_remaining = node_pods_remaining_sweep(p, node_group, N)
    return cpu_req, mem_req, num_pods, node_pods_remaining


def aggregate_nodes(n: NodeArrays, G: int, impl: str = "xla"):
    """Per-group node capacity sums and partition counts — the O(N) sweep
    (replaces pkg/k8s/util.go:41-51 and filterNodes counting). Pure sums, so
    node-shard partials also combine by addition."""
    nvalid = n.valid
    ngroup = jnp.where(nvalid, n.group, 0)
    untainted_sel = nvalid & ~n.tainted & ~n.cordoned
    tainted_sel = nvalid & n.tainted & ~n.cordoned
    cordoned_sel = nvalid & n.cordoned
    uw = untainted_sel.astype(_I64)

    if impl == "pallas":
        from escalator_tpu.ops import pallas_kernel

        node_sums = pallas_kernel.fused_segment_sums(
            ngroup,
            nvalid,
            {"cpu_cap": n.cpu_milli * uw, "mem_cap": n.mem_bytes * uw},
            {
                "num_nodes": nvalid,
                "num_untainted": untainted_sel,
                "num_tainted": tainted_sel,
                "num_cordoned": cordoned_sel,
            },
            num_segments=G,
        )
        return (
            node_sums["cpu_cap"],
            node_sums["mem_cap"],
            node_sums["num_nodes"],
            node_sums["num_untainted"],
            node_sums["num_tainted"],
            node_sums["num_cordoned"],
        )
    return (
        _segsum(n.cpu_milli * uw, ngroup, G),
        _segsum(n.mem_bytes * uw, ngroup, G),
        _segsum(nvalid.astype(_I64), ngroup, G),
        _segsum(uw, ngroup, G),
        _segsum(tainted_sel.astype(_I64), ngroup, G),
        _segsum(cordoned_sel.astype(_I64), ngroup, G),
    )


# ---------------------------------------------------------------------------
# Incremental decide (round 8): persistent aggregates + dirty-group compaction
# ---------------------------------------------------------------------------


def compute_aggregates(cluster: ClusterArrays, impl: str = "xla") -> GroupAggregates:
    """Full O(cluster) recompute of the persistent aggregate state — the
    bootstrap (first tick / cache rebuild) and the periodic refresh audit's
    reference. Exactly the sums :func:`decide` computes when ``aggregates``
    is not injected, so a :class:`GroupAggregates` maintained by deltas is
    REQUIRED to stay bit-equal to this function's output (integer sums
    commute and associate exactly; there is no float anywhere)."""
    g = cluster.groups
    n = cluster.nodes
    G = g.valid.shape[0]
    N = n.valid.shape[0]
    cpu_req, mem_req, num_pods, npr = aggregate_pods(
        cluster.pods, n.group, G, N, impl)
    cpu_cap, mem_cap, nn, nu, nt, nc = aggregate_nodes(n, G, impl)
    return GroupAggregates(
        cpu_req=cpu_req, mem_req=mem_req, num_pods=num_pods,
        cpu_cap=cpu_cap, mem_cap=mem_cap, num_nodes=nn,
        num_untainted=nu, num_tainted=nt, num_cordoned=nc,
        node_pods_remaining=npr,
        dirty=jnp.zeros(G, bool),
    )


compute_aggregates_jit = jax.jit(compute_aggregates, static_argnames=("impl",))


def aggregates_tuple(aggs: GroupAggregates):
    """Adapter: a maintained :class:`GroupAggregates` as the
    ``(pod_aggs, node_aggs)`` tuple :func:`decide` accepts via its
    ``aggregates=`` parameter — an incremental caller's ORDERED/full ticks
    skip the O(cluster) sweeps too, paying only the [G] math + [N] tail."""
    return (
        (aggs.cpu_req, aggs.mem_req, aggs.num_pods, aggs.node_pods_remaining),
        (aggs.cpu_cap, aggs.mem_cap, aggs.num_nodes, aggs.num_untainted,
         aggs.num_tainted, aggs.num_cordoned),
    )


_MIN_DIRTY_BUCKET = 8


def dirty_indices(dirty_mask, min_bucket: int = _MIN_DIRTY_BUCKET):
    """Host-side dirty-row compaction: int32 ``[D]`` indices of set rows,
    padded to a power-of-two bucket (min ``min_bucket``, capped at G) so the
    delta-decide jit compiles a handful of shapes as churn fluctuates — the
    same bounded-retrace policy as the lane buckets in ops.device_state.
    Pad entries are ``G`` (one past the last row): gathers clip them onto a
    real row whose result is then DISCARDED by the ``mode="drop"`` scatter.
    """
    dirty_mask = np.asarray(dirty_mask)
    idx = np.nonzero(dirty_mask)[0]
    G = int(dirty_mask.shape[0])
    bucket = min(G, max(min_bucket, 1 << max(len(idx) - 1, 0).bit_length()))
    bucket = max(bucket, len(idx))  # G below the min bucket: never truncate
    out = np.full(bucket, G, np.int32)
    out[: len(idx)] = idx
    return out


#: the four arms of the reference's threshold switch
#: (pkg/controller/controller.go:332-351), in the order the switch tests
#: them — :func:`explain_decide`'s ``threshold_branch`` indexes this tuple.
EXPLAIN_THRESHOLD_BRANCHES = (
    "scale_down_fast",   # max_percent < taint_lower (controller.go:334)
    "scale_down_slow",   # max_percent < taint_upper (controller.go:338)
    "scale_up",          # max_percent > scale_up_threshold (controller.go:343)
    "hold",              # inside the deadband: no arm fired
)

#: the status priority cascade's exit arms (exit order of
#: controller.go:192-397) — :func:`explain_decide`'s ``status_branch``
#: indexes this tuple; index 7 means no early exit fired (threshold switch
#: decided, status OK or ERR_NEG_DELTA folded in by arm 6).
EXPLAIN_STATUS_BRANCHES = (
    "invalid_or_empty",  # unregistered group, or zero nodes AND zero pods
    "below_min",         # num_nodes < min_nodes (controller.go:233)
    "above_max",         # num_nodes > max_nodes (controller.go:244)
    "forced_min",        # untainted < min_nodes: forced scale-up
    "div_zero",          # capacity zero with untainted nodes present
    "locked",            # group locked: delta passes through requested
    "neg_delta",         # scale-up arm computed a negative delta
    "threshold_switch",  # none fired: the threshold switch's verdict stands
)


def group_decision_terms(g: GroupArrays, cpu_req, mem_req, cpu_cap, mem_cap,
                         num_pods, num_nodes, num_untainted):
    """The per-group decision calculus with every intermediate NAMED — the
    single implementation behind :func:`group_decision_math` (which extracts
    the 8 committed outputs) and :func:`explain_decide` (which re-emits the
    full term dict for the provenance layer). The body is the verbatim
    decision core; returning references to the intermediates adds no ops, so
    the traced program of every pre-existing caller is unchanged (the
    standing jaxpr-byte-identity gate covers this).

    Returns a dict of per-group arrays; keys are stable API for the explain
    surface (observability/provenance.py glossaries map them back to the
    reference's util.go/controller.go lines)."""
    # ---- percent usage (pkg/controller/util.go:58-81) ----
    # Memory percent uses MilliValue (= bytes*1000) in the reference; replicate the
    # exact int64->float64 conversion order for bit-parity.
    mem_req_milli = mem_req * 1000
    mem_cap_milli = mem_cap * 1000
    all_zero = (
        (cpu_req == 0) & (mem_req_milli == 0) & (cpu_cap == 0) & (mem_cap_milli == 0)
        & (num_untainted == 0)
    )
    zero_cap = (cpu_cap == 0) | (mem_cap_milli == 0)
    from_zero = zero_cap & (num_untainted == 0) & ~all_zero
    div_zero = zero_cap & (num_untainted > 0) & ~all_zero

    safe_cpu_cap = jnp.where(cpu_cap == 0, 1, cpu_cap).astype(_F64)
    safe_mem_cap = jnp.where(mem_cap_milli == 0, 1, mem_cap_milli).astype(_F64)
    cpu_pct = jnp.where(
        all_zero | div_zero,
        0.0,
        jnp.where(from_zero, MAX_FLOAT64, cpu_req.astype(_F64) / safe_cpu_cap * 100.0),
    )
    mem_pct = jnp.where(
        all_zero | div_zero,
        0.0,
        jnp.where(
            from_zero, MAX_FLOAT64, mem_req_milli.astype(_F64) / safe_mem_cap * 100.0
        ),
    )

    # ---- scale-up delta (pkg/controller/util.go:13-46) ----
    # A non-positive threshold can't occur on validated config (the reference's
    # ValidateNodeGroup rejects it, node_group.go:96); guard anyway so NaN/Inf from
    # /0 can never masquerade as a valid delta — it becomes ERR_NEG_DELTA, matching
    # the golden model's deterministic ValueError.
    bad_thr = g.scale_up_thr <= 0
    thr = jnp.where(bad_thr, 1, g.scale_up_thr).astype(_F64)
    cached_cpu = g.cached_cpu_milli
    cached_mem_milli = g.cached_mem_bytes * 1000
    no_cache = (cached_cpu == 0) | (cached_mem_milli == 0)
    safe_cached_cpu = jnp.where(cached_cpu == 0, 1, cached_cpu).astype(_F64)
    safe_cached_mem = jnp.where(cached_mem_milli == 0, 1, cached_mem_milli).astype(_F64)

    fz_cpu = jnp.ceil(cpu_req.astype(_F64) / safe_cached_cpu / thr * 100.0)
    fz_mem = jnp.ceil(mem_req_milli.astype(_F64) / safe_cached_mem / thr * 100.0)
    # Operation order matters for bit-parity: Go computes percentageNeeded first
    # (util.go:33-37), i.e. n * ((pct - thr) / thr), NOT (n * (pct - thr)) / thr.
    nrm_cpu = jnp.ceil(num_untainted.astype(_F64) * ((cpu_pct - thr) / thr))
    nrm_mem = jnp.ceil(num_untainted.astype(_F64) * ((mem_pct - thr) / thr))

    needed = jnp.where(
        from_zero,
        jnp.where(no_cache, 1.0, jnp.maximum(fz_cpu, fz_mem)),
        jnp.maximum(nrm_cpu, nrm_mem),
    )
    # Go: delta := int(math.Max(...)) — truncation toward zero of an integral float.
    # Clamped to int32 like the golden model's MAX_DELTA (semantics.py).
    up_delta = jnp.trunc(needed)
    neg_delta = (up_delta < 0) | bad_thr

    # ---- threshold switch (pkg/controller/controller.go:332-351) ----
    max_pct = jnp.maximum(cpu_pct, mem_pct)
    down_fast = max_pct < g.taint_lower.astype(_F64)
    down_slow = ~down_fast & (max_pct < g.taint_upper.astype(_F64))
    scale_up = ~down_fast & ~down_slow & (max_pct > g.scale_up_thr.astype(_F64))

    switch_delta = jnp.where(
        down_fast,
        -g.fast_rate.astype(_I64),
        jnp.where(
            down_slow,
            -g.slow_rate.astype(_I64),
            jnp.where(
                scale_up,
                jnp.clip(up_delta, -(2.0**31), 2.0**31 - 1).astype(_I64),
                0,
            ),
        ),
    )

    # ---- status priority cascade (exit order of controller.go:192-397) ----
    empty = (num_nodes == 0) & (num_pods == 0)
    below_min = num_nodes < g.min_nodes
    above_max = num_nodes > g.max_nodes
    forced_min = num_untainted < g.min_nodes
    invalid = ~g.valid

    conds = [
        invalid | empty,
        below_min,
        above_max,
        forced_min,
        div_zero,
        g.locked,
        scale_up & neg_delta,
    ]
    status_choices = [
        jnp.int32(DecisionStatus.NOOP_EMPTY),
        jnp.int32(DecisionStatus.ERR_BELOW_MIN),
        jnp.int32(DecisionStatus.ERR_ABOVE_MAX),
        jnp.int32(DecisionStatus.FORCED_MIN_SCALE_UP),
        jnp.int32(DecisionStatus.ERR_DIV_ZERO),
        jnp.int32(DecisionStatus.LOCKED),
        jnp.int32(DecisionStatus.ERR_NEG_DELTA),
    ]
    status = jnp.select(conds, status_choices, jnp.int32(DecisionStatus.OK))

    zero32 = jnp.zeros((), _I32)
    delta_choices = [
        jnp.broadcast_to(zero32, status.shape),
        jnp.broadcast_to(zero32, status.shape),
        jnp.broadcast_to(zero32, status.shape),
        (g.min_nodes - num_untainted).astype(_I32),
        jnp.broadcast_to(zero32, status.shape),
        g.requested_nodes,
        jnp.broadcast_to(zero32, status.shape),
    ]
    nodes_delta = jnp.select(conds, delta_choices, switch_delta.astype(_I32))

    # Percent outputs: statuses that exit before the percent calc report 0 (matches the
    # metrics the reference would have emitted — none — represented as 0 here).
    pct_computed = ~(invalid | empty | below_min | above_max | forced_min | div_zero)
    cpu_pct_out = jnp.where(pct_computed, cpu_pct, 0.0)
    mem_pct_out = jnp.where(pct_computed, mem_pct, 0.0)

    # Request/capacity sums: the reference exits on empty/below-min/above-max
    # BEFORE aggregating (controller.go:233-255 precede util.go:27-51), so the
    # golden model reports zeros there; the batched kernel computes sums for
    # every group unconditionally and must mask them to match. (Counts stay:
    # they come from the filter pass, which runs before the bounds checks.)
    # Found by the 10x concurrency soak — the 1x soak never drove a group
    # past max_nodes, so this path went uncompared for three rounds.
    pre_agg_exit = invalid | empty | below_min | above_max
    zero64 = jnp.int64(0)
    cpu_req = jnp.where(pre_agg_exit, zero64, cpu_req)
    mem_req = jnp.where(pre_agg_exit, zero64, mem_req)
    cpu_cap = jnp.where(pre_agg_exit, zero64, cpu_cap)
    mem_cap = jnp.where(pre_agg_exit, zero64, mem_cap)

    return {
        # the 8 committed outputs (the masked sums carry the column names)
        "status": status,
        "nodes_delta": nodes_delta,
        "cpu_percent": cpu_pct_out,
        "mem_percent": mem_pct_out,
        "cpu_request_milli": cpu_req,
        "mem_request_bytes": mem_req,
        "cpu_capacity_milli": cpu_cap,
        "mem_capacity_bytes": mem_cap,
        # percent-usage terms (util.go:58-81)
        "cpu_percent_raw": cpu_pct,
        "mem_percent_raw": mem_pct,
        "max_percent": max_pct,
        # scale-up delta derivation (util.go:13-46)
        "from_zero_cpu_needed": fz_cpu,
        "from_zero_mem_needed": fz_mem,
        "percentage_needed_cpu": nrm_cpu,
        "percentage_needed_mem": nrm_mem,
        "nodes_needed": needed,
        "up_delta": up_delta,
        "switch_delta": switch_delta,
        # gates, in evaluation order
        "gate_all_zero": all_zero,
        "gate_from_zero": from_zero,
        "gate_div_zero": div_zero,
        "gate_no_cache": no_cache,
        "gate_bad_threshold": bad_thr,
        "gate_neg_delta": neg_delta,
        "gate_down_fast": down_fast,
        "gate_down_slow": down_slow,
        "gate_scale_up": scale_up,
        "gate_empty": empty,
        "gate_below_min": below_min,
        "gate_above_max": above_max,
        "gate_forced_min": forced_min,
        "gate_invalid": invalid,
        "gate_locked": g.locked,
        "gate_pct_computed": pct_computed,
        "gate_pre_agg_exit": pre_agg_exit,
    }


def group_decision_math(g: GroupArrays, cpu_req, mem_req, cpu_cap, mem_cap,
                        num_pods, num_nodes, num_untainted):
    """The per-group decision core — percent usage (pkg/controller/util.go:
    58-81), scale-up delta (util.go:13-46), threshold switch
    (controller.go:332-351) and the status priority cascade — as ONE
    shape-polymorphic elementwise function: :func:`decide` runs it on the
    full ``[G]`` rows, :func:`delta_decide` on a compacted ``[D]`` dirty
    batch. Single implementation (:func:`group_decision_terms`) so the two
    paths cannot drift; every op is elementwise, so the same int64/float64
    inputs produce bit-identical outputs at either shape.

    ``cpu_req``/``mem_req``/``cpu_cap``/``mem_cap`` are the int64 aggregate
    sums; counts are int32. Returns ``(status, nodes_delta, cpu_percent,
    mem_percent, cpu_req_masked, mem_req_masked, cpu_cap_masked,
    mem_cap_masked)`` — the masked sums apply the reference's
    pre-aggregation-exit zeroing (controller.go:233-255)."""
    t = group_decision_terms(g, cpu_req, mem_req, cpu_cap, mem_cap,
                             num_pods, num_nodes, num_untainted)
    return (t["status"], t["nodes_delta"], t["cpu_percent"], t["mem_percent"],
            t["cpu_request_milli"], t["mem_request_bytes"],
            t["cpu_capacity_milli"], t["mem_capacity_bytes"])


def explain_decide(g: GroupArrays, cpu_req, mem_req, cpu_cap, mem_cap,
                   num_pods, num_nodes, num_untainted,
                   num_tainted, num_cordoned):
    """The explain kernel: re-run the decision calculus and emit EVERY term
    by name — the 13 persistent decision columns reconstructed (the
    provenance layer bit-cross-checks these against the committed columns;
    any mismatch is itself a finding), plus the derivation terms, gate
    booleans, the active threshold branch and the active status-cascade arm.

    The reconstruction shares :func:`group_decision_terms` with the live
    paths, so a mismatch can only mean the AGGREGATES drifted (stale cache,
    missed dirty mark) — exactly the class of bug the cross-check exists to
    catch. The two branch codes are explain-only extras computed OUTSIDE the
    shared core so the live programs gain no dead equations:

    - ``threshold_branch`` indexes :data:`EXPLAIN_THRESHOLD_BRANCHES` — the
      controller.go:332-351 arm that fired (exactly one, by construction:
      the three gates are mutually exclusive and "hold" is their complement).
    - ``status_branch`` indexes :data:`EXPLAIN_STATUS_BRANCHES` — the first
      status-cascade arm that fired, 7 when none did.

    Config echoes ride along so one gather explains a decision without a
    second trip for the thresholds it was judged against."""
    t = group_decision_terms(g, cpu_req, mem_req, cpu_cap, mem_cap,
                             num_pods, num_nodes, num_untainted)
    threshold_branch = jnp.where(
        t["gate_down_fast"], jnp.int32(0),
        jnp.where(t["gate_down_slow"], jnp.int32(1),
                  jnp.where(t["gate_scale_up"], jnp.int32(2), jnp.int32(3))))
    cascade = [
        t["gate_invalid"] | t["gate_empty"],
        t["gate_below_min"],
        t["gate_above_max"],
        t["gate_forced_min"],
        t["gate_div_zero"],
        t["gate_locked"],
        t["gate_scale_up"] & t["gate_neg_delta"],
    ]
    status_branch = jnp.select(
        cascade, [jnp.int32(i) for i in range(7)], jnp.int32(7))
    return {
        **t,
        # counts echoed so the dict reconstructs all 13 decision columns
        "num_pods": num_pods,
        "num_nodes": num_nodes,
        "num_untainted": num_untainted,
        "num_tainted": num_tainted,
        "num_cordoned": num_cordoned,
        # explain-only branch codes
        "threshold_branch": threshold_branch,
        "status_branch": status_branch,
        # config echoes (the thresholds the decision was judged against)
        "cfg_scale_up_threshold": g.scale_up_thr,
        "cfg_taint_lower": g.taint_lower,
        "cfg_taint_upper": g.taint_upper,
        "cfg_fast_rate": g.fast_rate,
        "cfg_slow_rate": g.slow_rate,
        "cfg_min_nodes": g.min_nodes,
        "cfg_max_nodes": g.max_nodes,
        "cfg_cached_cpu_milli": g.cached_cpu_milli,
        "cfg_cached_mem_bytes": g.cached_mem_bytes,
    }


_explain_decide_raw = jax.jit(explain_decide)


def explain_decide_jit(g: GroupArrays, cpu_req, mem_req, cpu_cap, mem_cap,
                       num_pods, num_nodes, num_untainted,
                       num_tainted, num_cordoned):
    """Jitted :func:`explain_decide` with the same wedged-transport guard as
    :func:`decide_jit` (debug-explain is a raw-library surface when replaying
    offline). READ-ONLY by design: no donation — explaining a decision must
    never invalidate the state that produced it."""
    from escalator_tpu.jaxconfig import ensure_responsive_accelerator

    ensure_responsive_accelerator()
    return _explain_decide_raw(g, cpu_req, mem_req, cpu_cap, mem_cap,
                               num_pods, num_nodes, num_untainted,
                               num_tainted, num_cordoned)


def _node_offsets(sel, ngroup, G):
    """Per-group window offsets for a node selection class ([G+1] int32)."""
    counts = _segsum(sel.astype(_I64), ngroup, G)
    return jnp.concatenate(
        [jnp.zeros(1, _I64), jnp.cumsum(counts)]
    ).astype(_I32)


def _reap_eligibility(n: NodeArrays, g: GroupArrays, ngroup, tainted_sel,
                      node_pods_remaining, now_sec):
    """Reaper mask (pkg/controller/scale_down.go:51-99), O(N) elementwise —
    shared by decide() and the delta path. ``node_pods_remaining`` is i32."""
    has_tt = n.taint_time_sec != NO_TAINT_TIME
    age = now_sec.astype(_I64) - n.taint_time_sec
    return (
        tainted_sel
        & ~n.no_delete
        & has_tt
        & (age > g.soft_grace_sec[ngroup])
        & ((node_pods_remaining == 0) | (age > g.hard_grace_sec[ngroup]))
    )


def decide(
    cluster: ClusterArrays,
    now_sec: jnp.ndarray,
    impl: str = "xla",
    aggregates=None,
    with_orders: bool = True,
) -> DecisionArrays:
    """Evaluate every nodegroup's scale decision. Pure; shapes static; jit-safe.

    impl selects the aggregation sweep: "xla" = one scatter-add per column
    (jax.ops.segment_sum); "pallas" = the fused windowed one-hot-matmul MXU
    kernel (ops.pallas_kernel), which self-sorts group-interleaved lanes on
    device and falls back to the scatter path only for out-of-range values or
    sub-lane-per-group pathology. Outputs are bit-identical either way.

    aggregates optionally injects precomputed (pod_aggs, node_aggs) from
    :func:`aggregate_pods`/:func:`aggregate_nodes` — used by the pod-axis
    sharded path, which psums shard-partial sums into exactly these values,
    and by the incremental path's ordered/full ticks, which feed the
    persistently maintained :class:`GroupAggregates` through
    :func:`aggregates_tuple` (so even drain ticks skip the O(cluster)
    sweeps).

    with_orders=False (static) skips the combined node-ordering sort — the
    decide tail's dominant cost (~12 ms per 50k-node sort on the CPU
    fallback) — and returns input-order permutations in the two order
    fields, which are then NOT the documented selection orders. Every other
    field is bit-identical to the with_orders=True program. This is the
    light half of the lazy-orders tick protocol (:func:`lazy_orders_decide`):
    the reference only ever sorts inside an executor that consumes the
    order (taintOldestN, pkg/controller/scale_down.go:171; untaintNewestN,
    scale_up.go:118), so a tick that taints/untaints/reaps nothing never
    pays for ordering. Public callers keep the default; every array backend
    (native, repack jax, and the sharded three via order-free decider
    variants) runs the protocol, while the decider factories' ORDERED
    outputs remain the sharded-vs-single bit-parity contract and the gRPC
    plugin always ships full orders. One scoped exception: the pod-axis
    decider's block-sharded busy tail (ops.order_tail) guarantees bit-
    parity per offset WINDOW — the documented consumer contract — while
    the unspecified region beyond the windows may differ (its docstring
    carries the argument)."""
    if impl not in ("xla", "pallas"):
        raise ValueError(f"unknown aggregation impl {impl!r}")
    g: GroupArrays = cluster.groups
    p: PodArrays = cluster.pods
    n: NodeArrays = cluster.nodes
    G = g.valid.shape[0]
    N = n.valid.shape[0]

    # ---- aggregation (replaces pkg/k8s/util.go:27-51 per-group loops) ----
    if aggregates is None:
        pod_aggs = aggregate_pods(p, n.group, G, N, impl)
        node_aggs = aggregate_nodes(n, G, impl)
    else:
        pod_aggs, node_aggs = aggregates
    cpu_req, mem_req, num_pods64, node_pods_remaining64 = pod_aggs
    cpu_cap, mem_cap, nn64, nu64, nt64, nc64 = node_aggs
    num_pods = num_pods64.astype(_I32)
    num_nodes = nn64.astype(_I32)
    num_untainted = nu64.astype(_I32)
    num_tainted = nt64.astype(_I32)
    num_cordoned = nc64.astype(_I32)

    # shared selection-classification seam (ops.order_tail) so the pod-axis
    # block-sharded tail sorts with exactly these masks/keys
    from escalator_tpu.ops.order_tail import node_selection_masks

    ngroup, untainted_sel, tainted_sel = node_selection_masks(
        n.valid, n.group, n.tainted, n.cordoned
    )

    # ---- per-group decision math (the shared elementwise core; the delta
    # path runs the SAME function on a compacted dirty batch) ----
    (status, nodes_delta, cpu_pct_out, mem_pct_out,
     cpu_req, mem_req, cpu_cap, mem_cap) = group_decision_math(
        g, cpu_req, mem_req, cpu_cap, mem_cap,
        num_pods, num_nodes, num_untainted,
    )

    # ---- selections (pkg/controller/sort.go; scale_up.go:118; scale_down.go:171) ----
    # emptiest_first groups rank victims by pod count before age; elsewhere the
    # primary key is 0, reducing to the reference's oldest-first order exactly.
    # BOTH orderings come out of ONE 4-key lax.sort (round 5; previously one
    # sort each): every lane carries a class major — tainted first, untainted
    # second, everything else last — so the tainted block sorts
    # (group asc, creation desc) at the front, which IS untaint_order, and
    # the untainted block sorts (group asc, primary, creation asc) right
    # after it; rolling the tainted block to the tail yields
    # scale_down_order. Consumers only read the offsets windows, and those
    # are bit-identical to the two-sort formulation (the per-class keys and
    # iota tie-break reproduce each old sort's order exactly); the tail
    # regions beyond the windows are unspecified contract either way. The
    # [N] sort is the decide tail's dominant cost (measured ~12 ms per
    # 50k-node sort on the CPU fallback), so a taint-churn tick — both
    # selections non-empty, the busy case — now pays it once, not twice.
    # When BOTH selections are empty (all nodes cordoned/invalid) lax.cond
    # skips the sort entirely; under vmap cond lowers to select and both
    # branches run, the trivial branch being a free iota.
    victim_primary = jnp.where(
        g.emptiest[ngroup], node_pods_remaining64, jnp.int64(0)
    )
    # the +0*ngroup ties the constant iota to the inputs' sharding variance:
    # under shard_map the sorted branch is device-varying and cond requires
    # both branches to match (XLA folds the zero away)
    trivial_order = jnp.arange(N, dtype=_I32) + ngroup.astype(_I32) * 0

    def _combined_order(_):
        # key construction + the single 4-key sort live in ops.order_tail so
        # the grid's per-block tail and the pod-axis block-sharded tail run
        # literally the same ordering program as this replicated one
        from escalator_tpu.ops.order_tail import combined_order_sort

        iota = jax.lax.iota(_I64, N)
        _, perm = combined_order_sort(
            ngroup, tainted_sel, untainted_sel, victim_primary,
            n.creation_ns, G, iota,
        )
        return perm.astype(_I32)

    untainted_offsets = _node_offsets(untainted_sel, ngroup, G)
    tainted_offsets = _node_offsets(tainted_sel, ngroup, G)
    if with_orders:
        untaint_order = jax.lax.cond(
            jnp.any(untainted_sel | tainted_sel),
            _combined_order,
            lambda _: trivial_order,
            None,
        )
        # untainted block starts right after the tainted block in the
        # combined permutation; the roll is an O(N) gather, ~free next to
        # the sort
        scale_down_order = jnp.roll(untaint_order, -tainted_offsets[G])
    else:
        untaint_order = trivial_order
        scale_down_order = trivial_order

    # ---- reaper eligibility (pkg/controller/scale_down.go:51-99) ----
    node_pods_remaining = node_pods_remaining64.astype(_I32)
    reap_mask = _reap_eligibility(
        n, g, ngroup, tainted_sel, node_pods_remaining, now_sec)

    return DecisionArrays(
        status=status,
        nodes_delta=nodes_delta,
        cpu_percent=cpu_pct_out,
        mem_percent=mem_pct_out,
        cpu_request_milli=cpu_req,
        mem_request_bytes=mem_req,
        cpu_capacity_milli=cpu_cap,
        mem_capacity_bytes=mem_cap,
        num_pods=num_pods,
        num_nodes=num_nodes,
        num_untainted=num_untainted,
        num_tainted=num_tainted,
        num_cordoned=num_cordoned,
        scale_down_order=scale_down_order,
        untainted_offsets=untainted_offsets,
        untaint_order=untaint_order,
        tainted_offsets=tainted_offsets,
        reap_mask=reap_mask,
        node_pods_remaining=node_pods_remaining,
    )


_decide_jit_raw = jax.jit(decide, static_argnames=("impl", "with_orders"))


def decide_jit(cluster: ClusterArrays, now_sec, impl: str = "xla",
               aggregates=None, with_orders: bool = True):
    """Jitted entry point; backend chosen by JAX (TPU when present, else CPU)
    — the CPU fallback is the same traced program, keeping parity guarantees
    cheap (SURVEY.md §7). Signature mirrors :func:`decide`.

    Guarded against a wedged accelerator transport at the first dispatch:
    raw library use (``pack_cluster`` → ``decide_jit``, no CLI/backend in
    between — the verify doc's surface 1) never crosses the construction-site
    guards in ``make_backend``/cli/sim/plugin, and a wedged first dispatch
    would hang forever (observed 2026-07-31: 400 s with zero progress). The
    probe result is cached process-wide and fast-paths when backends are
    already live or the platform is cpu-pinned, so steady-state overhead is
    one cached check per call; under an outer trace (the bench's vmapped
    decide) the guard runs once at trace time."""
    from escalator_tpu.jaxconfig import ensure_responsive_accelerator

    ensure_responsive_accelerator()
    return _decide_jit_raw(cluster, now_sec, impl=impl, aggregates=aggregates,
                           with_orders=with_orders)


def _delta_decide_core(groups: GroupArrays, nodes: NodeArrays,
                       aggs: GroupAggregates, prev_cols, dirty_idx, now_sec):
    """The incremental decide body (round-8 tentpole), shape-agnostic over
    the dirty-batch width ``D`` — shared by :func:`delta_decide_jit` (single
    device) and ``parallel.grid.make_grid_delta_decider`` (per group block).

    ``prev_cols`` is the persistent decision state: the ``[G]`` columns of
    the last decide, as a tuple in ``GROUP_DECISION_FIELDS`` order.
    ``dirty_idx`` is the host-compacted ``[D]`` dirty-row batch
    (:func:`dirty_indices`): pad entries are ``G``, clipped on gather and
    dropped on scatter, so padding rows cost flops but never write.

    The decision math runs ONLY on the ``[D]`` gathered rows — the same
    :func:`group_decision_math` ops :func:`decide` runs on all ``[G]`` rows,
    so scattered results are bit-identical to a full recompute given exact
    aggregates. The ``[N]`` elementwise tail (selection masks, window
    offsets, reaper mask, pods-remaining cast) is recomputed every tick: it
    is the only part of the output that depends on ``now_sec``, and it is
    O(N) elementwise with no sort — the ordering sorts stay exclusive to
    the ordered/full path (this is the lazy-orders LIGHT program's shape:
    order fields are input-order placeholders, no window may be read).

    Returns ``(DecisionArrays, GroupAggregates)`` — the aggregates with the
    processed dirty rows cleared."""
    from escalator_tpu.ops.order_tail import node_selection_masks

    G = groups.valid.shape[0]
    N = nodes.valid.shape[0]
    safe_idx = jnp.clip(dirty_idx, 0, G - 1)
    take = lambda a: jnp.take(a, safe_idx, axis=0)  # noqa: E731

    g_d = GroupArrays(
        **{f.name: take(getattr(groups, f.name)) for f in fields(GroupArrays)}
    )
    num_pods_d = take(aggs.num_pods).astype(_I32)
    num_nodes_d = take(aggs.num_nodes).astype(_I32)
    num_untainted_d = take(aggs.num_untainted).astype(_I32)
    (status_d, delta_d, cpu_pct_d, mem_pct_d,
     cpu_req_d, mem_req_d, cpu_cap_d, mem_cap_d) = group_decision_math(
        g_d, take(aggs.cpu_req), take(aggs.mem_req),
        take(aggs.cpu_cap), take(aggs.mem_cap),
        num_pods_d, num_nodes_d, num_untainted_d,
    )
    updates = {
        "status": status_d,
        "nodes_delta": delta_d,
        "cpu_percent": cpu_pct_d,
        "mem_percent": mem_pct_d,
        "cpu_request_milli": cpu_req_d,
        "mem_request_bytes": mem_req_d,
        "cpu_capacity_milli": cpu_cap_d,
        "mem_capacity_bytes": mem_cap_d,
        "num_pods": num_pods_d,
        "num_nodes": num_nodes_d,
        "num_untainted": num_untainted_d,
        "num_tainted": take(aggs.num_tainted).astype(_I32),
        "num_cordoned": take(aggs.num_cordoned).astype(_I32),
    }
    cols = dict(zip(GROUP_DECISION_FIELDS, prev_cols, strict=True))
    for name, val in updates.items():
        # pad rows (index G) drop; real rows overwrite the persistent column
        cols[name] = cols[name].at[dirty_idx].set(val, mode="drop")

    ngroup, untainted_sel, tainted_sel = node_selection_masks(
        nodes.valid, nodes.group, nodes.tainted, nodes.cordoned
    )
    # identical expression to decide()'s light trivial_order (the +0*ngroup
    # sharding-variance tie — see decide())
    trivial_order = jnp.arange(N, dtype=_I32) + ngroup.astype(_I32) * 0
    node_pods_remaining = aggs.node_pods_remaining.astype(_I32)
    out = DecisionArrays(
        scale_down_order=trivial_order,
        untainted_offsets=_node_offsets(untainted_sel, ngroup, G),
        untaint_order=trivial_order,
        tainted_offsets=_node_offsets(tainted_sel, ngroup, G),
        reap_mask=_reap_eligibility(
            nodes, groups, ngroup, tainted_sel, node_pods_remaining, now_sec),
        node_pods_remaining=node_pods_remaining,
        **cols,
    )
    aggs_out = GroupAggregates(
        cpu_req=aggs.cpu_req, mem_req=aggs.mem_req, num_pods=aggs.num_pods,
        cpu_cap=aggs.cpu_cap, mem_cap=aggs.mem_cap, num_nodes=aggs.num_nodes,
        num_untainted=aggs.num_untainted, num_tainted=aggs.num_tainted,
        num_cordoned=aggs.num_cordoned,
        node_pods_remaining=aggs.node_pods_remaining,
        dirty=aggs.dirty.at[dirty_idx].set(False, mode="drop"),
    )
    return out, aggs_out


@partial(jax.jit, donate_argnums=(1, 2))
def _delta_decide_raw(cluster: ClusterArrays, aggs: GroupAggregates,
                      prev_cols, dirty_idx, now_sec):
    return _delta_decide_core(cluster.groups, cluster.nodes, aggs, prev_cols,
                              dirty_idx, now_sec)


def delta_decide_jit(cluster: ClusterArrays, aggs: GroupAggregates,
                     prev_cols, dirty_idx, now_sec):
    """Jitted incremental decide: O(D + N) work instead of the full decide's
    O(P + N) sweeps — the steady-state tick when churn is small. The jit
    cache keys on the dirty bucket width ``D`` (power-of-two padded by
    :func:`dirty_indices`, so shapes stay few).

    DONATES ``aggs`` and ``prev_cols``: both are persistent device state and
    the returned values replace them — callers must drop their old
    references (ops.device_state.IncrementalDecider owns this protocol).
    Same wedged-transport guard as :func:`decide_jit`."""
    from escalator_tpu.jaxconfig import ensure_responsive_accelerator

    ensure_responsive_accelerator()
    return _delta_decide_raw(cluster, aggs, prev_cols, dirty_idx, now_sec)


@partial(jax.jit, static_argnums=(9,), donate_argnums=(1, 2, 5, 6, 7, 8))
def _ordered_delta_decide_raw(cluster: ClusterArrays, aggs: GroupAggregates,
                              prev_cols, dirty_idx, now_sec,
                              old_major, old_k1, old_k2, perm_old,
                              bucket: int):
    from escalator_tpu.ops.order_tail import _order_update_core

    out, aggs_out = _delta_decide_core(cluster.groups, cluster.nodes, aggs,
                                       prev_cols, dirty_idx, now_sec)
    order_state = _order_update_core(
        cluster.groups.emptiest, cluster.nodes.valid, cluster.nodes.group,
        cluster.nodes.tainted, cluster.nodes.cordoned,
        cluster.nodes.creation_ns, aggs_out.node_pods_remaining,
        old_major, old_k1, old_k2, perm_old, out.tainted_offsets, bucket)
    return out, aggs_out, order_state


def ordered_delta_decide_jit(cluster: ClusterArrays, aggs: GroupAggregates,
                             prev_cols, dirty_idx, now_sec,
                             old_major, old_k1, old_k2, perm_old,
                             bucket: int):
    """The steady ORDERED-incremental tick as ONE program: the
    :func:`delta_decide_jit` body plus ``order_tail._order_update_core``
    (key recompute + diff + on-device dirty compaction + rank-repair merge
    + scale-down roll) fused behind a single dispatch. Beyond dropping a
    synchronous dispatch from the tick, the fusion lets XLA CSE the [N]
    passes the two programs share — ``node_selection_masks`` and the
    pods-remaining cast feed both the decision tail and the sort keys.

    Returns ``(DecisionArrays, GroupAggregates, (major, k1, k2, perm,
    scale_down, count))`` — the first two exactly :func:`delta_decide_jit`'s
    (order fields still input-order placeholders; the CALLER grafts
    ``perm``/``scale_down`` in, after consulting ``count`` for the
    bucket-overflow / dirty-fraction fallback to the full key sort, see
    ``order_tail.order_update_jit``). DONATES ``aggs``, ``prev_cols``, and
    the old order state — all four are persistent device state replaced by
    the returned values."""
    from escalator_tpu.jaxconfig import ensure_responsive_accelerator

    ensure_responsive_accelerator()
    return _ordered_delta_decide_raw(cluster, aggs, prev_cols, dirty_idx,
                                     now_sec, old_major, old_k1, old_k2,
                                     perm_old, bucket)


# ---------------------------------------------------------------------------
# Fleet-scale decide (round 14): a leading cluster axis over the
# shape-polymorphic decision path — C independent tenants in ONE dispatch.
# ---------------------------------------------------------------------------


def fleet_decide(clusters: ClusterArrays, now_sec) -> DecisionArrays:
    """C-stacked multi-tenant decide: every leaf of ``clusters`` carries a
    leading cluster axis (``groups [C, G]``, ``pods [C, P]``, ``nodes
    [C, N]``; ragged tenants are packed into the shared ``(G, N, P)``
    buckets with their per-lane ``valid`` masks), and ``now_sec`` is int64
    ``[C]`` — each tenant decides at the timestamp its request carried.

    This is :func:`decide`'s light (``with_orders=False``) program vmapped
    over the cluster axis: every op in that program is elementwise or a
    segment-sum, so the batched lowering is one fused device program with
    NO cross-tenant data flow — each tenant's 13 decision columns are
    bit-identical to its standalone ``decide_jit(..., with_orders=False)``
    at the same bucket shapes (and, because the [G] math reads only exact
    integer aggregates, to its standalone decide at ANY padding). The
    ordering sorts stay out by design: the fleet service runs the lazy-
    orders protocol per tenant, re-dispatching a single-tenant ordered
    decide only for tenants whose decision consumes an order (see
    escalator_tpu/fleet/service.py)."""
    return jax.vmap(
        lambda c, t: decide(c, t, impl="xla", with_orders=False)
    )(clusters, now_sec)


_fleet_decide_jit_raw = jax.jit(fleet_decide)


def fleet_decide_jit(clusters: ClusterArrays, now_sec) -> DecisionArrays:
    """Jitted :func:`fleet_decide` with the same wedged-transport guard as
    :func:`decide_jit` (the fleet service is a raw-library surface too)."""
    from escalator_tpu.jaxconfig import ensure_responsive_accelerator

    ensure_responsive_accelerator()
    return _fleet_decide_jit_raw(clusters, now_sec)


#: Full per-tenant aggregate recompute over the cluster axis — the fleet
#: arenas' bootstrap/audit reference, exactly ``compute_aggregates`` per
#: tenant row (the maintained fleet aggregates must stay bit-equal to it).
fleet_compute_aggregates_jit = jax.jit(
    jax.vmap(lambda c: compute_aggregates(c, impl="xla")))


def fleet_dirty_bucket(widest: int, G: int,
                       min_bucket: int = _MIN_DIRTY_BUCKET) -> int:
    """THE shared dirty-row bucket policy for fleet batches: the widest
    tenant's power-of-two bucket, floored at ``min_bucket`` and capped at
    ``G`` — one place, imported by both the per-request compaction below
    and the engine's vectorized twin, so the two can never disagree on the
    jit cache key."""
    bucket = min(G, max(min_bucket, 1 << max(int(widest) - 1, 0).bit_length()))
    return max(bucket, int(widest))


def fleet_order_bucket(widest: int, rows: int, min_bucket: int = 1) -> int:
    """The :func:`fleet_dirty_bucket` policy applied to the ORDER-NEEDING
    tenant axis (round 18 batched order tails): the busiest shard's
    order-consuming tenant count, rounded to a power of two, floored at
    ``min_bucket`` and capped at ``rows`` (the shard's tenant rows + the
    scratch row, which pads the bucket with bitwise-inert no-ops). One
    place, shared by the engine and the jaxlint fixture, so the batched
    order-repair program compiles a handful of widths as drain pressure
    fluctuates — never one shape per batch."""
    bucket = min(int(rows),
                 max(min_bucket, 1 << max(int(widest) - 1, 0).bit_length()))
    return max(bucket, int(widest))


def fleet_dirty_indices(dirty_masks, G: int, min_bucket: int = _MIN_DIRTY_BUCKET):
    """Per-tenant dirty-row compaction into ONE shared ``[T, D]`` bucket:
    the fleet analog of :func:`dirty_indices`, padded to the widest
    tenant's power-of-two bucket (:func:`fleet_dirty_bucket`) so the
    batched delta program compiles a handful of ``D`` widths as churn
    fluctuates — a per-tenant bucket would retrace on every batch whose
    tenants disagree. Pad entries are ``G`` (dropped on scatter), exactly
    the single-tenant convention."""
    counts = [int(np.count_nonzero(np.asarray(m))) for m in dirty_masks]
    bucket = fleet_dirty_bucket(max(counts, default=0), G, min_bucket)
    out = np.full((len(dirty_masks), bucket), G, np.int32)
    for t, mask in enumerate(dirty_masks):
        idx = np.nonzero(np.asarray(mask))[0]
        out[t, : len(idx)] = idx
    return out


def fleet_dirty_indices_stacked(dirty, G: int,
                                min_bucket: int = _MIN_DIRTY_BUCKET):
    """Vectorized twin of :func:`fleet_dirty_indices` over an already
    stacked bool mask ``[..., G]`` (any leading batch axes): one stable
    argsort instead of a Python loop — the sharded engine assembles
    ``[S, T, G]`` masks and a per-entry loop at C=10k would dominate the
    host path. Bit-identical output (stable sort keeps ascending index
    order among dirty lanes), same :func:`fleet_dirty_bucket` width."""
    dirty = np.asarray(dirty, bool)
    lead = dirty.shape[:-1]
    flat = dirty.reshape(-1, G)
    counts = flat.sum(axis=1)
    bucket = fleet_dirty_bucket(int(counts.max(initial=0)), G, min_bucket)
    order = np.argsort(~flat, axis=1, kind="stable")[:, :bucket]
    pos = np.arange(bucket)[None, :]
    out = np.where(pos < counts[:, None], order, G).astype(np.int32)
    return out.reshape(*lead, bucket)


def make_fleet_decide_sharded(mesh):
    """:func:`fleet_decide` partitioned ``[C/dev]`` over a device mesh:
    the stacked clusters (and per-tenant ``now_sec``) shard along the
    leading tenant axis with ``shard_map`` and each device runs the
    batched light decide on its rows alone. ``fleet_decide`` has ZERO
    cross-tenant data flow, so the sharded lowering contains no
    collectives (jaxlint-pinned at a 0-psum budget) and throughput scales
    with device count. ``C`` must divide by the mesh size; the fleet
    engine's power-of-two tenant buckets guarantee it. Returns the jitted
    callable (cache it — rebuilding per call would retrace)."""
    from jax.sharding import PartitionSpec
    from escalator_tpu.jaxconfig import shard_map

    spec = PartitionSpec(mesh.axis_names[0])
    return jax.jit(shard_map(
        fleet_decide, mesh=mesh, in_specs=(spec, spec), out_specs=spec))


def lazy_orders_decide(dispatch, tainted_any: bool):
    """The lazy-orders tick protocol: pay the node-ordering sort only when a
    consumer exists, mirroring the reference, which sorts exclusively inside
    the executors that read an order (taintOldestN scale_down.go:171,
    untaintNewestN scale_up.go:118) and therefore never sorts on a
    steady-state tick.

    ``dispatch(with_orders: bool) -> DecisionArrays`` runs one (blocking)
    decide — callers wrap their own resilience/timing around it. Orders are
    needed exactly when (a) tainted nodes exist (untaint executor + reaper
    both walk the tainted windows — the caller knows this pre-dispatch from
    its host-side state snapshot), or (b) some group decided to scale down
    (the taint executor walks the untainted windows — known only post-
    dispatch from nodes_delta, so that case re-dispatches WITH orders: two
    device round-trips on the tick a drain begins, zero sorts on every
    healthy tick). Returns ``(out, ordered)``; when ``ordered`` is False the
    two order fields are input-order placeholders and no window may be read.
    """
    if tainted_any:
        return dispatch(True), True
    out = dispatch(False)
    if bool((np.asarray(out.nodes_delta) < 0).any()):
        return dispatch(True), True
    return out, False
