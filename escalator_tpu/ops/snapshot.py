"""Device-state snapshot/restore: failover-grade persistence of the
incremental decide's truth.

Since round 8 the system of record for a running controller lives in device
HBM — the resident :class:`~escalator_tpu.core.arrays.ClusterArrays`, the
delta-maintained :class:`~escalator_tpu.ops.kernel.GroupAggregates`, the 13
persistent ``[G]`` decision columns, and (round 10) the persistent order
state. Until round 11 nothing survived a process death except a full
re-list + full recompute. This module is the persistence layer:

- **Freeze** (:func:`freeze_state`): ONE jitted device program of pure
  on-device copies — the same ``_fresh_buffer`` construction as the PR-5
  audit double buffer (``device_state._audit_snapshot``), extended to the
  decision columns and order state. No donation, no collectives, no host
  callbacks (jaxlint entry ``snapshot.freeze``): the live buffers stay
  valid and keep mutating under subsequent ticks while the frozen copy is
  serialized.
- **File format** (:func:`write_snapshot` / :func:`read_snapshot`): a
  single self-describing binary — JSON header (version, meta, per-leaf
  dtype/shape/offset/crc32) + raw column payload — written tmp + fsync +
  atomic rename, so a checkpoint racing a SIGKILL can never strand a
  half-written file where the standby will look. Every read validates
  magic, version, payload length and per-leaf crc32; any violation raises
  :class:`SnapshotCorruptError` and the caller falls back to a cold start.
- **Adopt** (:func:`restore_adopt`): the restore side's device program — a
  donated identity over the uploaded leaves. The donation is the point:
  the host-staged upload buffers become the resident state with zero extra
  HBM copies (jaxlint entry ``snapshot.restore_adopt`` verifies the
  aliasing survived lowering), so restore costs one H2D transfer, never a
  recompute.
- **Checkpoint cadence** (:class:`SnapshotWriter`): the tick thread pays
  only the freeze (an on-device copy program) and the D2H read of the
  frozen buffers; serialization + disk I/O run on a single worker thread,
  so a checkpoint tick never blocks on the filesystem.

The snapshot's *consistency* is inherited from the freeze point: callers
snapshot at a tick boundary (after reconcile, before the next scatter), so
the file is exactly the state a standby needs to warm-start in O(1) ticks —
adopt the resident state, then let the normal delta path fold in whatever
changed while the leader was dead. docs/ha.md carries the operator view.
"""

from __future__ import annotations

import json
import logging
import os
import time
import zlib
from dataclasses import fields
from typing import Any, Dict, Mapping, Optional, Tuple

import numpy as np

from escalator_tpu.jaxconfig import ensure_x64
from escalator_tpu.utils.atomicio import atomic_write

ensure_x64()

import jax
from jax import tree_util

log = logging.getLogger("escalator_tpu.snapshot")

#: file magic + format version. Version bumps whenever the leaf naming or
#: header schema changes incompatibly; readers reject unknown versions
#: (a standby must never adopt state it can misinterpret).
SNAPSHOT_MAGIC = b"ESCSNAP\n"
SNAPSHOT_VERSION = 1

#: the rolling checkpoint name a standby looks for (atomic-replace target)
LATEST_NAME = "state-latest.snap"


class SnapshotCorruptError(RuntimeError):
    """The snapshot file failed validation (bad magic/version, truncated
    payload, or a leaf whose bytes no longer match their recorded crc32).
    Callers treat this as 'no snapshot': cold start + flight dump."""


# ---------------------------------------------------------------------------
# Device programs
# ---------------------------------------------------------------------------


def _fresh_buffer(x):
    """An op XLA cannot alias back into the input buffer (no donation is
    declared) — shared construction with the audit double buffer
    (``device_state._audit_snapshot``)."""
    import jax.numpy as jnp

    if x.dtype == jnp.bool_:
        return x ^ False
    return x + jnp.zeros((), x.dtype)


@jax.jit
def _freeze_state(state_tree):
    """Freeze an arbitrary pytree of device arrays into fresh buffers: one
    device program, no host sync, no donation — the snapshot analog of the
    audit double buffer, generalized to (cluster, aggs, cols, order).
    Registered with jaxlint as ``snapshot.freeze``: zero collectives, zero
    host callbacks, donation explicitly ABSENT (aliasing an input would let
    the next tick's donating scatter corrupt the frozen copy mid-write)."""
    return tree_util.tree_map(_fresh_buffer, state_tree)


def freeze_state(state_tree):
    """Public freeze entry: dispatches :func:`_freeze_state` (async). The
    caller owns fencing — :meth:`SnapshotWriter.checkpoint` reads the frozen
    leaves back to host, which synchronizes naturally."""
    return _freeze_state(state_tree)


def _adopt_body(state_tree):
    """Adopt uploaded host buffers as the resident device state: a DONATED
    identity. XLA aliases every output to its donated input
    (``tf.aliasing_output`` — jaxlint entry ``snapshot.restore_adopt``
    verifies it survives lowering), so adoption moves zero bytes in HBM;
    the restore's only real cost is the H2D upload that staged the leaves.
    The donation also makes the handover explicit: after this call the
    staging references are dead and the returned tree is the single owner —
    exactly the protocol every other persistent-state program in
    ops/device_state.py follows."""
    return state_tree


_restore_adopt = jax.jit(_adopt_body, donate_argnums=(0,))


def restore_adopt(state_tree, device=None):
    """Device-put + adopt a host-side state tree; returns resident arrays.
    One H2D transfer, zero device-side copies (see :func:`_restore_adopt`)."""
    staged = (jax.device_put(state_tree, device) if device is not None
              else jax.device_put(state_tree))
    return _restore_adopt(staged)


def _tenant_row_freeze_body(shard_block, row):
    """Gather ONE tenant's row out of a fleet shard block (a pytree of
    ``[1, Cs+1, …]`` device arrays — the ``(aggs, prev_cols)`` slice from
    ``device_state.fleet_shard_local``). ``row`` is a traced int32 — the
    row INDEX is data, never a jit cache key, so migrating any tenant off
    any slot reuses one compiled program (jaxlint entry
    ``snapshot.tenant_row_freeze`` pins the retrace count). The gather
    outputs are fresh buffers by construction (no donation is declared and
    a dynamic-index gather cannot alias its operand), so the arena stays
    live and keeps mutating under subsequent micro-batches while the row
    copy is serialized — the same liveness contract as :func:`_freeze_state`."""
    return tree_util.tree_map(lambda a: a[0, row], shard_block)


_tenant_row_freeze = jax.jit(_tenant_row_freeze_body)


def _tenant_row_adopt_body(state_tree, shard, row, row_values):
    """Scatter one tenant's row values into the resident fleet arenas at
    ``[shard, row]``. The arena tree is DONATED: XLA aliases every output
    to its input and lowers the whole adopt to in-place dynamic-update-
    slices (jaxlint entry ``snapshot.tenant_row_adopt`` verifies the
    aliasing survives lowering), so adopting a migrated tenant costs one
    H2D upload of the row values — never an arena copy. ``shard``/``row``
    are traced int32s for the same no-retrace reason as the freeze side."""
    return tree_util.tree_map(
        lambda a, v: a.at[shard, row].set(v), state_tree, row_values)


_tenant_row_adopt = jax.jit(_tenant_row_adopt_body, donate_argnums=(0,))


def tenant_row_freeze(shard_block, row: int):
    """Public row-freeze entry: dispatches :func:`_tenant_row_freeze`
    (async; the caller's D2H read fences)."""
    return _tenant_row_freeze(shard_block, np.int32(row))


def tenant_row_adopt(state_tree, shard: int, row: int, row_values):
    """Public row-adopt entry: stages ``row_values`` on device and scatters
    them into the donated arena tree at ``[shard, row]``; returns the new
    resident tree (the input references are dead — donation)."""
    return _tenant_row_adopt(
        state_tree, np.int32(shard), np.int32(row),
        jax.device_put(row_values))


# ---------------------------------------------------------------------------
# Serialization: one self-describing binary file
# ---------------------------------------------------------------------------


def _leaf_bytes(arr: np.ndarray) -> bytes:
    return np.ascontiguousarray(arr).tobytes()


def write_snapshot(path: str, leaves: Mapping[str, np.ndarray],
                   meta: Optional[Dict[str, Any]] = None) -> str:
    """Serialize named leaves + meta to ``path`` atomically (tmp in the same
    directory + flush + fsync + rename — the crash-consistency recipe the
    flight recorder and the election lease share after round 11). Layout::

        ESCSNAP\\n  [8-byte big-endian header length]  [header JSON]  [payload]

    The header carries version, meta, and per-leaf (dtype, shape, offset,
    nbytes, crc32); the payload is the concatenated raw column bytes.
    Integer/bool round-trips are exact by construction; there are no float
    leaves anywhere in the persisted state except the two [G] percent
    columns, whose float64 bytes round-trip bit-exactly too."""
    header_raw, payload_parts = _serialize_parts(leaves, meta)

    def emit(f):
        f.write(SNAPSHOT_MAGIC)
        f.write(len(header_raw).to_bytes(8, "big"))
        f.write(header_raw)
        for raw in payload_parts:
            f.write(raw)

    return atomic_write(path, emit)


def _serialize_parts(leaves: Mapping[str, np.ndarray],
                     meta: Optional[Dict[str, Any]]):
    meta = dict(meta or {})
    header: Dict[str, Any] = {
        "version": SNAPSHOT_VERSION,
        "created_unix": round(time.time(), 3),
        "meta": meta,
        "leaves": [],
    }
    payload_parts = []
    offset = 0
    for key in sorted(leaves):
        arr = np.asarray(leaves[key])
        raw = _leaf_bytes(arr)
        header["leaves"].append({
            "key": key,
            "dtype": str(arr.dtype),
            "shape": list(arr.shape),
            "offset": offset,
            "nbytes": len(raw),
            "crc32": zlib.crc32(raw),
        })
        payload_parts.append(raw)
        offset += len(raw)
    header["payload_bytes"] = offset
    return json.dumps(header).encode(), payload_parts


def snapshot_to_bytes(leaves: Mapping[str, np.ndarray],
                      meta: Optional[Dict[str, Any]] = None) -> bytes:
    """The file format as an in-memory blob — the wire form a tenant-row
    migration ships over the plugin RPC. Byte-identical to what
    :func:`write_snapshot` puts on disk (same magic, header, crcs), so one
    validator (:func:`snapshot_from_bytes` / :func:`read_snapshot`) covers
    both transports."""
    header_raw, payload_parts = _serialize_parts(leaves, meta)
    return b"".join([SNAPSHOT_MAGIC, len(header_raw).to_bytes(8, "big"),
                     header_raw, *payload_parts])


def snapshot_from_bytes(
        blob: bytes, label: str = "<bytes>",
) -> Tuple[Dict[str, np.ndarray], Dict[str, Any]]:
    """Validate + decode an in-memory snapshot blob; raises
    :class:`SnapshotCorruptError` on any integrity violation, exactly like
    :func:`read_snapshot` (they share the parser)."""
    return _parse_snapshot(blob, label)


def read_snapshot(path: str) -> Tuple[Dict[str, np.ndarray], Dict[str, Any]]:
    """Load + validate a snapshot file. Returns ``(leaves, meta)``; raises
    :class:`SnapshotCorruptError` on ANY integrity violation (bad magic,
    unknown version, truncated header/payload, per-leaf crc mismatch) and
    ``FileNotFoundError`` when the file simply is not there — the two cases
    callers handle differently (corrupt dumps a flight record; absent is
    the normal first boot)."""
    with open(path, "rb") as f:
        blob = f.read()
    return _parse_snapshot(blob, path)


def _parse_snapshot(
        blob: bytes, path: str,
) -> Tuple[Dict[str, np.ndarray], Dict[str, Any]]:
    if not blob.startswith(SNAPSHOT_MAGIC):
        raise SnapshotCorruptError(f"{path}: bad magic")
    off = len(SNAPSHOT_MAGIC)
    if len(blob) < off + 8:
        raise SnapshotCorruptError(f"{path}: truncated header length")
    hlen = int.from_bytes(blob[off:off + 8], "big")
    off += 8
    if len(blob) < off + hlen:
        raise SnapshotCorruptError(f"{path}: truncated header")
    try:
        header = json.loads(blob[off:off + hlen])
    except ValueError as e:
        raise SnapshotCorruptError(f"{path}: unparseable header: {e}") from e
    if header.get("version") != SNAPSHOT_VERSION:
        raise SnapshotCorruptError(
            f"{path}: unsupported snapshot version {header.get('version')!r}"
            f" (reader supports {SNAPSHOT_VERSION})")
    payload = blob[off + hlen:]
    if len(payload) != int(header.get("payload_bytes", -1)):
        raise SnapshotCorruptError(
            f"{path}: payload is {len(payload)} bytes, header declares "
            f"{header.get('payload_bytes')} — truncated or overlong")
    leaves: Dict[str, np.ndarray] = {}
    for spec in header["leaves"]:
        raw = payload[spec["offset"]:spec["offset"] + spec["nbytes"]]
        if len(raw) != spec["nbytes"]:
            raise SnapshotCorruptError(
                f"{path}: leaf {spec['key']!r} truncated")
        if zlib.crc32(raw) != spec["crc32"]:
            raise SnapshotCorruptError(
                f"{path}: leaf {spec['key']!r} failed its crc32 check")
        leaves[spec["key"]] = np.frombuffer(
            raw, dtype=np.dtype(spec["dtype"])).reshape(spec["shape"]).copy()
    return leaves, dict(header.get("meta", {}))


def latest_path(directory: str) -> str:
    """The rolling checkpoint path a standby probes at warm start."""
    return os.path.join(directory, LATEST_NAME)


# ---------------------------------------------------------------------------
# Leaf naming: the (cluster, aggs, cols, order) <-> flat-dict contract
# ---------------------------------------------------------------------------


def state_to_leaves(cluster, aggs, prev_cols, order_state) -> Dict[str, np.ndarray]:
    """Flatten host-side (or frozen device) state into the named-leaf dict
    the file format serializes. Naming is THE restore contract:
    ``cluster.<section>.<field>``, ``aggs.<field>``, ``col.<name>`` (in
    ``kernel.GROUP_DECISION_FIELDS``), ``order.<major|k1|k2|perm>``
    (absent when no order state exists yet)."""
    from escalator_tpu.ops import kernel as _kernel

    leaves: Dict[str, np.ndarray] = {}
    for section in ("groups", "pods", "nodes"):
        soa = getattr(cluster, section)
        for f in fields(type(soa)):
            leaves[f"cluster.{section}.{f.name}"] = np.asarray(
                getattr(soa, f.name))
    for f in fields(type(aggs)):
        leaves[f"aggs.{f.name}"] = np.asarray(getattr(aggs, f.name))
    for name, col in zip(_kernel.GROUP_DECISION_FIELDS, prev_cols,
                         strict=True):
        leaves[f"col.{name}"] = np.asarray(col)
    if order_state is not None:
        from escalator_tpu.ops.order_tail import ORDER_STATE_FIELDS

        for name, col in zip(ORDER_STATE_FIELDS, order_state, strict=True):
            leaves[f"order.{name}"] = np.asarray(col)
    return leaves


def leaves_to_state(leaves: Mapping[str, np.ndarray]):
    """Inverse of :func:`state_to_leaves`: host-side ``(ClusterArrays,
    GroupAggregates, prev_cols tuple, order_state or None)``. A missing
    required leaf raises :class:`SnapshotCorruptError` with its name —
    mixed-version drift must be a named error, not a KeyError deep in jit."""
    from escalator_tpu.core.arrays import (
        ClusterArrays,
        GroupArrays,
        NodeArrays,
        PodArrays,
    )
    from escalator_tpu.ops import kernel as _kernel
    from escalator_tpu.ops.order_tail import ORDER_STATE_FIELDS

    def need(key: str) -> np.ndarray:
        try:
            return np.asarray(leaves[key])
        except KeyError:
            raise SnapshotCorruptError(
                f"snapshot is missing required leaf {key!r}") from None

    def soa(cls, section: str):
        return cls(**{f.name: need(f"cluster.{section}.{f.name}")
                      for f in fields(cls)})

    cluster = ClusterArrays(
        groups=soa(GroupArrays, "groups"),
        pods=soa(PodArrays, "pods"),
        nodes=soa(NodeArrays, "nodes"),
    )
    aggs = _kernel.GroupAggregates(
        **{f.name: need(f"aggs.{f.name}")
           for f in fields(_kernel.GroupAggregates)})
    prev_cols = tuple(
        need(f"col.{name}") for name in _kernel.GROUP_DECISION_FIELDS)
    order_state = None
    if any(k.startswith("order.") for k in leaves):
        order_state = tuple(
            need(f"order.{name}") for name in ORDER_STATE_FIELDS)
    return cluster, aggs, prev_cols, order_state


# ---------------------------------------------------------------------------
# Tenant-row format: one fleet tenant's arena row as a snapshot
# ---------------------------------------------------------------------------

#: ``meta["kind"]`` stamped on tenant-row snapshots. Adopters REQUIRE it: a
#: whole-decider snapshot fed to the row-adopt path (or vice versa) must be
#: a named rejection, not a shape error three layers down.
TENANT_ROW_KIND = "fleet-tenant-row"


def tenant_row_to_leaves(cluster, aggs_row, col_rows, dirty,
                         cache_arrays=None) -> Dict[str, np.ndarray]:
    """Flatten ONE fleet tenant's persistent state into named leaves:
    the host cluster twins (``cluster.<section>.<field>`` at the tenant's
    bucket shapes), the tenant's aggregates row (``aggs.<field>``, [G]),
    the 13 persistent decision columns (``col.<name>``, [G]), the pending
    dirty-group mask (``dirty``), and — when the tenant's digest fast path
    holds a cached answer — the cached decision arrays (``cache.<field>``).
    Scalar cache fields (digest/now/ordered/epoch validity) ride in the
    snapshot META, not as leaves: they are identity, not column data."""
    leaves = state_to_leaves(cluster, aggs_row, col_rows, None)
    leaves["dirty"] = np.asarray(dirty, bool)
    if cache_arrays is not None:
        for f in fields(type(cache_arrays)):
            leaves[f"cache.{f.name}"] = np.asarray(
                getattr(cache_arrays, f.name))
    return leaves


def leaves_to_tenant_row(leaves: Mapping[str, np.ndarray]):
    """Inverse of :func:`tenant_row_to_leaves`: ``(cluster, aggs_row,
    col_rows, dirty, cache_arrays_or_None)``. Missing required leaves raise
    :class:`SnapshotCorruptError` by name (same contract as
    :func:`leaves_to_state`)."""
    from escalator_tpu.ops import kernel as _kernel

    cluster, aggs_row, col_rows, _ = leaves_to_state(leaves)
    try:
        dirty = np.asarray(leaves["dirty"], bool)
    except KeyError:
        raise SnapshotCorruptError(
            "tenant-row snapshot is missing required leaf 'dirty'") from None
    cache_arrays = None
    if any(k.startswith("cache.") for k in leaves):
        try:
            cache_arrays = _kernel.DecisionArrays(**{
                f.name: np.asarray(leaves[f"cache.{f.name}"])
                for f in fields(_kernel.DecisionArrays)})
        except KeyError as e:
            raise SnapshotCorruptError(
                f"tenant-row snapshot has a partial decision cache "
                f"(missing {e.args[0]!r})") from None
    return cluster, aggs_row, col_rows, dirty, cache_arrays


def pad_cluster_leaves(leaves: Mapping[str, np.ndarray], pod_capacity: int,
                       node_capacity: int) -> Dict[str, np.ndarray]:
    """Slot-remap adopt for a capacity-grown restore target: extend the
    per-pod / per-node cluster leaves (and the lane-indexed order state) to
    the configured capacities. Slots keep their indices — the remap is the
    identity on every occupied slot, and every NEW slot is a hole (pad
    values, ``valid=False``), so an ingestion-ordered slot replay
    (``NativeStateStore`` warm restore) reproduces the snapshot's layout
    inside the larger store instead of falling back to a cold start.
    Order-state key columns pad with zeros and ``perm`` extends with the
    new lane indices: the padded lanes' stored keys may disagree with
    their recomputed keys, which the first ordered update detects and
    repairs (or full-sorts past) — self-healing, never silently wrong.
    Shrinking is NOT a remap this function performs: a smaller target
    cannot hold the occupied slots, and callers treat that as stale."""
    from escalator_tpu.ops.device_state import _NODE_PAD, _POD_PAD

    out = dict(leaves)

    def _grow(key: str, cap: int, pad_overrides: Mapping[str, int]) -> None:
        arr = np.asarray(out[key])
        old = arr.shape[0]
        if old == cap:
            return
        if old > cap:
            raise ValueError(
                f"{key}: snapshot capacity {old} exceeds target {cap} "
                f"(shrinking is a stale restore, not a remap)")
        field_name = key.rsplit(".", 1)[-1]
        pad = pad_overrides.get(field_name, 0)
        grown = np.full((cap,) + arr.shape[1:], pad, arr.dtype)
        grown[:old] = arr
        out[key] = grown

    for key in list(out):
        if key.startswith("cluster.pods."):
            _grow(key, pod_capacity, _POD_PAD)
        elif key.startswith("cluster.nodes."):
            _grow(key, node_capacity, _NODE_PAD)
    if "aggs.node_pods_remaining" in out:
        # the one node-axis aggregate column: holes carry no pods, zero pad
        _grow("aggs.node_pods_remaining", node_capacity, {})
    if "order.perm" in out:
        perm = np.asarray(out["order.perm"])
        old = perm.shape[0]
        if old < node_capacity:
            out["order.perm"] = np.concatenate(
                [perm, np.arange(old, node_capacity, dtype=perm.dtype)])
            for name in ("major", "k1", "k2"):
                col = np.asarray(out[f"order.{name}"])
                out[f"order.{name}"] = np.concatenate(
                    [col, np.zeros(node_capacity - old, col.dtype)])
    return out


# ---------------------------------------------------------------------------
# Periodic async checkpoints
# ---------------------------------------------------------------------------


class SnapshotWriter:
    """Rolling checkpoint writer for one :class:`IncrementalDecider`.

    ``maybe_checkpoint(inc)`` is called once per tick (backends do this
    right after the decide): on the cadence tick it freezes the decider's
    persistent state (an on-device copy program + the D2H read — the only
    on-path cost) and hands serialization + the atomic file write to a
    single worker thread, so the tick never blocks on disk. The write
    target is always :data:`LATEST_NAME` in ``directory`` via atomic
    replace: a standby probes exactly one path, and a kill at any moment
    leaves either the previous or the new checkpoint — never a torn one.

    ``every`` is a tick cadence (``0`` disables). The writer never raises
    into the tick: a failed write logs + counts, and the previous
    checkpoint stays valid."""

    def __init__(self, directory: str, every: int = 64):
        self.directory = directory
        self.every = int(every)
        self.path = latest_path(directory)
        self.checkpoints = 0
        self.failures = 0
        self._pool = None
        self._pending = None
        self._ticks_seen = 0
        #: host-side staged leaves held while a serialize+write is pending
        #: — accounted with the resource registry (kind="host") so a slow
        #: disk backing up checkpoint copies shows up as owner bytes, not
        #: as an unattributable RSS ramp
        self._staged_leaves = None
        from escalator_tpu.observability import resources

        resources.RESOURCES.register(
            "snapshot_writer_staging", self, lambda w: w._staged_leaves,
            kind="host")
        os.makedirs(directory, exist_ok=True)

    def maybe_checkpoint(self, inc, force: bool = False, extra=None) -> bool:
        """Checkpoint when the cadence says so (or ``force``). Returns True
        when a checkpoint was STARTED this call.

        ``extra`` is an optional zero-arg callable returning additional
        ``{name: np.ndarray}`` leaves merged into the snapshot — evaluated
        only when a checkpoint actually starts, so a caller can attach
        sidecar state (e.g. the native backend's slot->key tables, which
        make warm restore possible on an ingestion-ordered store) without
        paying its build cost on every tick. Sidecar names must not collide
        with the decider's own leaves; a prefix like ``store.`` keeps them
        out of :func:`leaves_to_state`'s required set."""
        self._ticks_seen += 1
        if not force and (
                self.every <= 0 or self._ticks_seen % self.every != 0):
            return False
        state = inc.snapshot_state()
        if state is None:   # nothing decided yet: nothing worth persisting
            return False
        leaves, meta = state
        if extra is not None:
            leaves = {**leaves, **extra()}
        self._submit(leaves, meta)
        return True

    def _submit(self, leaves: Dict[str, np.ndarray],
                meta: Dict[str, Any]) -> None:
        from concurrent.futures import ThreadPoolExecutor

        if self._pool is None:
            self._pool = ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="escalator-tpu-snapshot")
        if self._pending is not None and not self._pending.done():
            # a previous write still in flight at the next cadence point
            # (slow disk): finish it first so writes stay ordered and at
            # most one serialized copy of the state exists at a time
            self._drain_pending()
        self._staged_leaves = leaves
        self._pending = self._pool.submit(self._write, leaves, meta)

    def _write(self, leaves, meta) -> Optional[str]:
        from escalator_tpu.metrics import metrics

        try:
            path = write_snapshot(self.path, leaves, meta)
        except OSError as e:
            self.failures += 1
            log.error("snapshot checkpoint write failed: %s", e)
            return None
        finally:
            self._staged_leaves = None
        self.checkpoints += 1
        metrics.snapshot_checkpoints.inc()
        log.debug("snapshot checkpoint -> %s", path)
        return path

    def _drain_pending(self) -> None:
        if self._pending is not None:
            self._pending.result()
            self._pending = None

    def drain(self) -> None:
        """Block until any in-flight write lands (tests, clean shutdown)."""
        self._drain_pending()
