"""First-fit-decreasing bin packing on device: true per-pod placement feasibility.

The reference models capacity as a whole-group average and documents the resulting
single-instance-type assumption (docs/calculations.md:8,
docs/best-practices-issues-gotchas.md:36-38): it can say "utilisation is 120%" but
not "these pods actually FIT on those heterogeneous nodes". This kernel lifts that:
given each group's pods and its (heterogeneous) nodes' free capacity, FFD-place every
pod and report how many NEW nodes (of the group's template capacity) are needed for
the overflow — a packing-aware scale-up delta.

Formulation: pods sorted descending by dominant share, then a ``lax.scan`` over the
pod axis with the per-bin remaining-capacity vector as carry; ``vmap`` over groups.
One scan step is a [G, M] broadcast (fits-mask, first-fit argmax, masked subtract) —
fully vectorized across groups, so the sequential depth is pods-per-group, not
total pods.

Shapes: pods [G, P] (padded per group), bins [G, M] where the first slots are real
nodes and the trailing ``new_bin_budget`` slots are virtual new nodes of template
capacity.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

from escalator_tpu.jaxconfig import ensure_x64

ensure_x64()

import jax
import jax.numpy as jnp

_I32 = jnp.int32
_I64 = jnp.int64
_F64 = jnp.float64


@dataclass
class PackResult:
    assignment: jnp.ndarray        # int32 [G, P] bin index per pod, -1 unplaced
    new_nodes_needed: jnp.ndarray  # int32 [G] virtual bins actually used
    unplaced: jnp.ndarray          # int32 [G] pods that fit nowhere
    bins_remaining_cpu: jnp.ndarray  # int64 [G, M]
    bins_remaining_mem: jnp.ndarray  # int64 [G, M]

    def tree_flatten(self):
        return (
            [self.assignment, self.new_nodes_needed, self.unplaced,
             self.bins_remaining_cpu, self.bins_remaining_mem],
            None,
        )

    @classmethod
    def tree_unflatten(cls, aux, leaves):
        return cls(*leaves)


jax.tree_util.register_pytree_node(
    PackResult, PackResult.tree_flatten, PackResult.tree_unflatten
)


def _sort_pods_desc(pod_cpu, pod_mem, pod_valid, ref_cpu, ref_mem):
    """Order pods by descending dominant share (max of cpu/mem normalized by the
    group's template capacity); invalid pods last. Returns permutation [G, P]."""
    safe_ref_cpu = jnp.where(ref_cpu == 0, 1, ref_cpu).astype(_F64)[:, None]
    safe_ref_mem = jnp.where(ref_mem == 0, 1, ref_mem).astype(_F64)[:, None]
    dominant = jnp.maximum(
        pod_cpu.astype(_F64) / safe_ref_cpu, pod_mem.astype(_F64) / safe_ref_mem
    )
    key = jnp.where(pod_valid, -dominant, jnp.inf)
    return jnp.argsort(key, axis=1, stable=True)


@partial(jax.jit, static_argnames=("new_bin_budget",))
def ffd_pack(
    pod_cpu: jnp.ndarray,     # int64 [G, P] pod cpu requests (milli)
    pod_mem: jnp.ndarray,     # int64 [G, P] pod mem requests (bytes)
    pod_valid: jnp.ndarray,   # bool [G, P]
    bin_cpu: jnp.ndarray,     # int64 [G, M] free cpu per existing node
    bin_mem: jnp.ndarray,     # int64 [G, M]
    bin_valid: jnp.ndarray,   # bool [G, M]
    template_cpu: jnp.ndarray,  # int64 [G] new-node capacity (cached per-node)
    template_mem: jnp.ndarray,  # int64 [G]
    new_bin_budget: int,
) -> PackResult:
    """FFD-place each group's pods into its nodes + up to new_bin_budget virtual
    new nodes. Groups are packed simultaneously (vmap); within a group, placement
    is sequential FFD (scan)."""
    G, P = pod_cpu.shape
    M = bin_cpu.shape[1]

    # append virtual bins of template capacity
    vb_cpu = jnp.broadcast_to(template_cpu[:, None], (G, new_bin_budget))
    vb_mem = jnp.broadcast_to(template_mem[:, None], (G, new_bin_budget))
    all_cpu = jnp.concatenate([jnp.where(bin_valid, bin_cpu, -1), vb_cpu], axis=1)
    all_mem = jnp.concatenate([jnp.where(bin_valid, bin_mem, -1), vb_mem], axis=1)

    perm = _sort_pods_desc(
        pod_cpu, pod_mem, pod_valid, template_cpu, template_mem
    )
    sorted_cpu = jnp.take_along_axis(pod_cpu, perm, axis=1)
    sorted_mem = jnp.take_along_axis(pod_mem, perm, axis=1)
    sorted_valid = jnp.take_along_axis(pod_valid, perm, axis=1)

    def step(carry, xs):
        rem_cpu, rem_mem = carry            # [G, M+B]
        cpu, mem, valid = xs                # [G]
        fits = (rem_cpu >= cpu[:, None]) & (rem_mem >= mem[:, None])
        fits = fits & valid[:, None]
        any_fit = fits.any(axis=1)
        # first-fit: lowest bin index that fits
        chosen = jnp.argmax(fits, axis=1)
        place = any_fit & valid
        onehot = (
            jax.nn.one_hot(chosen, rem_cpu.shape[1], dtype=_I64)
            * place[:, None].astype(_I64)
        )
        rem_cpu = rem_cpu - onehot * cpu[:, None]
        rem_mem = rem_mem - onehot * mem[:, None]
        assigned = jnp.where(place, chosen.astype(_I32), jnp.int32(-1))
        return (rem_cpu, rem_mem), assigned

    (rem_cpu, rem_mem), assigned_sorted = jax.lax.scan(
        step,
        (all_cpu, all_mem),
        (sorted_cpu.T, sorted_mem.T, sorted_valid.T),
    )
    assigned_sorted = assigned_sorted.T       # [G, P] in sorted order

    # un-permute assignments back to input pod order
    inv = jnp.argsort(perm, axis=1, stable=True)
    assignment = jnp.take_along_axis(assigned_sorted, inv, axis=1)

    used_virtual = (
        (rem_cpu[:, M:] < vb_cpu) | (rem_mem[:, M:] < vb_mem)
    ).sum(axis=1).astype(_I32)
    unplaced = (
        (assignment < 0) & pod_valid
    ).sum(axis=1).astype(_I32)
    return PackResult(
        assignment=assignment,
        new_nodes_needed=used_virtual,
        unplaced=unplaced,
        bins_remaining_cpu=rem_cpu,
        bins_remaining_mem=rem_mem,
    )


def ffd_pack_reference(pods, bins, template, new_bin_budget):
    """Pure-Python FFD with identical tie-breaking — the golden model for tests.
    pods: list[(cpu, mem)]; bins: list[(cpu, mem)]; template: (cpu, mem).
    Single source of truth lives in core.semantics (the golden backend's
    packing-aware delta uses it without any array deps)."""
    from escalator_tpu.core.semantics import ffd_pack_pure

    return ffd_pack_pure(pods, bins, template, new_bin_budget)
