"""First-fit-decreasing bin packing on device: true per-pod placement feasibility.

The reference models capacity as a whole-group average and documents the resulting
single-instance-type assumption (docs/calculations.md:8,
docs/best-practices-issues-gotchas.md:36-38): it can say "utilisation is 120%" but
not "these pods actually FIT on those heterogeneous nodes". This kernel lifts that:
given each group's pods and its (heterogeneous) nodes' free capacity, FFD-place every
pod and report how many NEW nodes (of the group's template capacity) are needed for
the overflow — a packing-aware scale-up delta.

Blocked formulation (round 6; the original pod-at-a-time ``lax.scan`` measured
49.99 ms at the 2048-group bench shape on the CPU fallback — the whole 50 ms
tick budget, VERDICT r5 weak-point 3). Three changes, all parity-locked against
``core.semantics.ffd_pack_pure``:

1. **Host prep, not device sort.** The descending-dominant-share pod sort and
   both permutation gathers run in numpy (the device argsort + four
   ``take_along_axis`` gathers measured 46 ms of the old 108 ms on this rig;
   numpy does the same exact keys in ~8 ms). The float64 dominant-share key is
   computed with the identical IEEE expression, so the stable order — and
   therefore every placement — is bit-identical.

2. **Greedy-histogram prepass → run-block scan.** Adjacent sorted pods with
   IDENTICAL (cpu, mem) collapse into one run; a run of ``c`` identical pods
   admits a closed-form first-fit: bins fill left to right, bin ``j`` taking
   ``min(c_remaining, floor(rem_cpu/cpu), floor(rem_mem/mem))`` pods — exactly
   what placing them one-at-a-time does, in ONE scan step (cumsum over the bin
   axis, the bin-block sweep). The scan then runs over R runs instead of P
   pods: for the common production load — thousands of pods in a handful of
   replica shapes — R is the number of DISTINCT shapes and the sequential
   depth collapses by orders of magnitude.

3. **Adversarial fallback: a dtype-trimmed per-pod scan.** When the shapes
   don't compress (distinct-heavy loads fragment the runs; the prepass
   detects this from R vs P), a per-pod scan still runs — with the carry in
   float64 (mem) / float32 (cpu) when the inputs fit those types exactly
   (integers below 2**53 / 2**24; subtraction of integers stays exact), which
   cuts the scan's memory traffic ~40% on the CPU fallback. Inputs exceeding
   the exact ranges keep the int64 program — same math, never wrong, just
   slower.

Shapes: pods [G, P] (padded per group), bins [G, M] where the first slots are real
nodes and the trailing ``new_bin_budget`` slots are virtual new nodes of template
capacity.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import numpy as np

from escalator_tpu.jaxconfig import ensure_x64

ensure_x64()

import jax
import jax.numpy as jnp

_I32 = jnp.int32
_I64 = jnp.int64
_F64 = jnp.float64

#: exact-integer ranges for the trimmed-dtype per-pod scan (that scan only
#: compares and subtracts, both exact for integers inside these ranges)
_F32_EXACT = 1 << 24
_F64_EXACT = 1 << 53


@dataclass
class PackResult:
    assignment: jnp.ndarray        # int32 [G, P] bin index per pod, -1 unplaced
    new_nodes_needed: jnp.ndarray  # int32 [G] virtual bins actually used
    unplaced: jnp.ndarray          # int32 [G] pods that fit nowhere
    bins_remaining_cpu: jnp.ndarray  # int64 [G, M]
    bins_remaining_mem: jnp.ndarray  # int64 [G, M]

    def tree_flatten(self):
        return (
            [self.assignment, self.new_nodes_needed, self.unplaced,
             self.bins_remaining_cpu, self.bins_remaining_mem],
            None,
        )

    @classmethod
    def tree_unflatten(cls, aux, leaves):
        return cls(*leaves)


jax.tree_util.register_pytree_node(
    PackResult, PackResult.tree_flatten, PackResult.tree_unflatten
)


def _round_up_pow2(n: int, minimum: int = 4) -> int:
    size = max(n, minimum)
    return 1 << (size - 1).bit_length()


def _host_prep(pod_cpu, pod_mem, pod_valid, ref_cpu, ref_mem):
    """Numpy sort + run compression (the greedy-histogram prepass).

    The sort key is the SAME float64 expression the device kernel used —
    descending dominant share of the group template, invalid pods last,
    stable ties — so the placement order is bit-identical to the golden
    model's ``sorted(..., key=(-dominant, i))``.

    Returns ``(perm, inv, s_cpu, s_mem, s_valid, runs, R)`` where ``runs`` is
    ``(run_cpu, run_mem, run_count, run_start)`` as [G, R] arrays over maximal
    ADJACENT identical-(cpu, mem) runs of the sorted valid prefix, and the
    per-pod ``(run_id, rank)`` map needed to reconstruct assignments."""
    G, P = pod_cpu.shape
    safe_rc = np.where(ref_cpu == 0, 1, ref_cpu).astype(np.float64)[:, None]
    safe_rm = np.where(ref_mem == 0, 1, ref_mem).astype(np.float64)[:, None]
    dominant = np.maximum(
        pod_cpu.astype(np.float64) / safe_rc, pod_mem.astype(np.float64) / safe_rm
    )
    key = np.where(pod_valid, -dominant, np.inf)
    perm = np.argsort(key, axis=1, kind="stable")
    # inverse permutation by scatter (cheaper than a second argsort)
    inv = np.empty_like(perm)
    np.put_along_axis(inv, perm, np.broadcast_to(np.arange(P), (G, P)), axis=1)
    s_cpu = np.take_along_axis(pod_cpu, perm, axis=1)
    s_mem = np.take_along_axis(pod_mem, perm, axis=1)
    s_valid = np.take_along_axis(pod_valid, perm, axis=1)

    same = (
        (s_cpu[:, 1:] == s_cpu[:, :-1]) & (s_mem[:, 1:] == s_mem[:, :-1])
        & s_valid[:, 1:] & s_valid[:, :-1]
    )
    newrun = np.concatenate([s_valid[:, :1], s_valid[:, 1:] & ~same], axis=1)
    run_id = np.cumsum(newrun, axis=1) - 1
    run_id = np.where(s_valid, run_id, -1)
    n_runs = newrun.sum(axis=1)
    n_valid = s_valid.sum(axis=1).astype(np.int64)
    R = _round_up_pow2(int(n_runs.max()) if n_runs.size else 1)
    run_cpu = np.zeros((G, R), np.int64)
    run_mem = np.zeros((G, R), np.int64)
    run_start = np.zeros((G, R), np.int64)
    g_idx, p_idx = np.nonzero(newrun)
    r_idx = run_id[g_idx, p_idx]
    run_cpu[g_idx, r_idx] = s_cpu[g_idx, p_idx]
    run_mem[g_idx, r_idx] = s_mem[g_idx, p_idx]
    run_start[g_idx, r_idx] = p_idx
    # run lengths by differencing starts (padding runs pinned to the valid
    # end so their counts come out 0) — no np.add.at, it is slow at scale
    pad_runs = np.arange(R)[None, :] >= n_runs[:, None]
    run_start = np.where(pad_runs, n_valid[:, None], run_start)
    ends = np.concatenate([run_start[:, 1:], n_valid[:, None]], axis=1)
    run_count = ends - run_start
    return perm, inv, s_cpu, s_mem, s_valid, (
        run_cpu, run_mem, run_count, run_start, run_id
    ), R


def _virtual_bins(bin_cpu, bin_mem, bin_valid, template_cpu, template_mem,
                  new_bin_budget):
    G = bin_cpu.shape[0]
    vb_cpu = jnp.broadcast_to(template_cpu[:, None], (G, new_bin_budget))
    vb_mem = jnp.broadcast_to(template_mem[:, None], (G, new_bin_budget))
    all_cpu = jnp.concatenate([jnp.where(bin_valid, bin_cpu, -1), vb_cpu], axis=1)
    all_mem = jnp.concatenate([jnp.where(bin_valid, bin_mem, -1), vb_mem], axis=1)
    return all_cpu, all_mem, vb_cpu, vb_mem


def _pack_outputs(rem_cpu, rem_mem, assigned_sorted, inv, pod_valid,
                  template_cpu, template_mem, M: int, new_bin_budget: int):
    """Shared epilogue (traced inside both device programs): un-permute the
    sorted-order assignments and derive the overflow counts."""
    G = rem_cpu.shape[0]
    assignment = jnp.take_along_axis(assigned_sorted, inv, axis=1)
    vb_cpu = jnp.broadcast_to(template_cpu[:, None], (G, new_bin_budget))
    vb_mem = jnp.broadcast_to(template_mem[:, None], (G, new_bin_budget))
    used_virtual = (
        (rem_cpu[:, M:] < vb_cpu) | (rem_mem[:, M:] < vb_mem)
    ).sum(axis=1).astype(_I32)
    unplaced = ((assignment < 0) & pod_valid).sum(axis=1).astype(_I32)
    return assignment, used_virtual, unplaced


@partial(jax.jit, static_argnames=("new_bin_budget", "trim_dtypes"))
def _pack_pods_device(
    s_cpu, s_mem, s_valid,              # int64/bool [G, P] SORTED pods
    inv, pod_valid,
    bin_cpu, bin_mem, bin_valid,
    template_cpu, template_mem,
    new_bin_budget: int,
    trim_dtypes: bool,
):
    """Per-pod first-fit scan (the adversarial/no-compression path). One step
    per sorted pod: fits mask -> lowest-index bin -> masked subtract. With
    ``trim_dtypes`` the carry runs f32(cpu)/f64(mem) — exact for integer
    inputs below 2**24 / 2**53, checked by the caller — trading ~40% of the
    scan's memory traffic on the CPU fallback."""
    G, P = s_cpu.shape
    M = bin_cpu.shape[1]
    all_cpu, all_mem, _, _ = _virtual_bins(
        bin_cpu, bin_mem, bin_valid, template_cpu, template_mem, new_bin_budget
    )
    if trim_dtypes:
        cpu_t, mem_t = jnp.float32, _F64
    else:
        cpu_t, mem_t = _I64, _I64
    iota = jnp.arange(M + new_bin_budget, dtype=_I32)

    def step(carry, xs):
        rem_cpu, rem_mem = carry
        cpu, mem, valid = xs
        fits = (rem_cpu >= cpu[:, None]) & (rem_mem >= mem[:, None])
        chosen = jnp.argmax(fits, axis=1)
        place = fits.any(axis=1) & valid
        hit = (iota[None, :] == chosen[:, None]) & place[:, None]
        rem_cpu = jnp.where(hit, rem_cpu - cpu[:, None], rem_cpu)
        rem_mem = jnp.where(hit, rem_mem - mem[:, None], rem_mem)
        assigned = jnp.where(place, chosen.astype(_I32), jnp.int32(-1))
        return (rem_cpu, rem_mem), assigned

    (rem_cpu, rem_mem), assigned_sorted = jax.lax.scan(
        step,
        (all_cpu.astype(cpu_t), all_mem.astype(mem_t)),
        (s_cpu.T.astype(cpu_t), s_mem.T.astype(mem_t), s_valid.T),
    )
    rem_cpu = rem_cpu.astype(_I64)
    rem_mem = rem_mem.astype(_I64)
    assignment, used, unplaced = _pack_outputs(
        rem_cpu, rem_mem, assigned_sorted.T, inv, pod_valid,
        template_cpu, template_mem, M, new_bin_budget,
    )
    return assignment, used, unplaced, rem_cpu, rem_mem


@partial(jax.jit, static_argnames=("new_bin_budget",))
def _pack_runs_device(
    run_cpu, run_mem, run_count,        # int64 [G, R]
    run_start, run_id,                  # int64 [G, R] / [G, P]
    s_valid, inv, pod_valid,
    bin_cpu, bin_mem, bin_valid,
    template_cpu, template_mem,
    new_bin_budget: int,
):
    """Run-block first-fit scan (the histogram-compressed path). One step per
    run of identical pods: per-bin item capacity ``k = min(floor(rem/size))``
    (float64 division + integer off-by-one fixups, so the result is exact),
    then a cumsum over the bin axis fills bins left to right — which is
    EXACTLY what placing the run's pods one at a time does, since identical
    items always first-fit the lowest bin with room. Per-pod assignments come
    out of the take counts by a branchless binary search over each run's
    cumulative-take row (log2(M+B) flat gathers of [G, P] — never a
    [G, P, M+B] broadcast)."""
    G, R = run_cpu.shape
    P = run_id.shape[1]
    M = bin_cpu.shape[1]
    MB = M + new_bin_budget
    all_cpu, all_mem, _, _ = _virtual_bins(
        bin_cpu, bin_mem, bin_valid, template_cpu, template_mem, new_bin_budget
    )

    def step(carry, xs):
        rem_cpu, rem_mem = carry
        cpu, mem, count = xs            # int64 [G]
        c_col = count[:, None]
        fits1 = (rem_cpu >= cpu[:, None]) & (rem_mem >= mem[:, None])
        kc = jnp.trunc(
            rem_cpu.astype(_F64) / jnp.maximum(cpu, 1).astype(_F64)[:, None]
        ).astype(_I64)
        km = jnp.trunc(
            rem_mem.astype(_F64) / jnp.maximum(mem, 1).astype(_F64)[:, None]
        ).astype(_I64)
        kc = jnp.where(cpu[:, None] > 0, kc, c_col)
        km = jnp.where(mem[:, None] > 0, km, c_col)
        k = jnp.where(fits1, jnp.clip(jnp.minimum(kc, km), 0, c_col), 0)
        # float-division fixups: k must be the LARGEST k with k*size <= rem
        over = (k * cpu[:, None] > rem_cpu) | (k * mem[:, None] > rem_mem)
        k = k - over.astype(_I64)
        under = (
            ((k + 1) * cpu[:, None] <= rem_cpu)
            & ((k + 1) * mem[:, None] <= rem_mem)
            & (k + 1 <= c_col) & fits1
        )
        k = k + under.astype(_I64)
        k = jnp.where(fits1, jnp.clip(k, 0, c_col), 0)
        cum = jnp.cumsum(k, axis=1)
        take = jnp.clip(c_col - (cum - k), 0, k)
        rem_cpu = rem_cpu - take * cpu[:, None]
        rem_mem = rem_mem - take * mem[:, None]
        return (rem_cpu, rem_mem), take.astype(_I32)

    (rem_cpu, rem_mem), takes = jax.lax.scan(
        step, (all_cpu, all_mem), (run_cpu.T, run_mem.T, run_count.T)
    )

    # ---- per-pod assignment: binary search in each run's cumulative takes
    cumtake = jnp.cumsum(jnp.transpose(takes, (1, 0, 2)), axis=-1)  # [G,R,MB]
    flat = cumtake.reshape(-1)
    rid = jnp.where(run_id < 0, 0, run_id).astype(_I64)
    t_rank = (
        jnp.arange(P, dtype=_I64)[None, :]
        - jnp.take_along_axis(run_start, rid, axis=1)
    ).astype(_I32)
    row_base = (
        jnp.arange(G, dtype=_I64)[:, None] * (R * MB) + rid * MB
    )                                                               # [G, P]
    # pos = number of cumulative takes <= t_rank = the first-fit bin index
    pos = jnp.zeros((G, P), _I32)
    span = 1 << max(MB - 1, 0).bit_length()
    while span:
        cand = pos + span
        val = jnp.take(
            flat, row_base + jnp.clip(cand - 1, 0, MB - 1).astype(_I64),
            mode="clip",
        )
        pos = jnp.where((cand <= MB) & (val <= t_rank), cand, pos)
        span >>= 1
    total = jnp.take(flat, row_base + (MB - 1), mode="clip")
    placed = (t_rank < total) & s_valid
    assigned_sorted = jnp.where(placed, pos, jnp.int32(-1))

    assignment, used, unplaced = _pack_outputs(
        rem_cpu, rem_mem, assigned_sorted, inv, pod_valid,
        template_cpu, template_mem, M, new_bin_budget,
    )
    return assignment, used, unplaced, rem_cpu, rem_mem


def ffd_pack(
    pod_cpu,     # int64 [G, P] pod cpu requests (milli)
    pod_mem,     # int64 [G, P] pod mem requests (bytes)
    pod_valid,   # bool [G, P]
    bin_cpu,     # int64 [G, M] free cpu per existing node
    bin_mem,     # int64 [G, M]
    bin_valid,   # bool [G, M]
    template_cpu,  # int64 [G] new-node capacity (cached per-node)
    template_mem,  # int64 [G]
    new_bin_budget: int,
) -> PackResult:
    """FFD-place each group's pods into its nodes + up to new_bin_budget virtual
    new nodes. Groups are packed simultaneously; within a group, placement is
    sequential first-fit over the host-sorted pods — as a run-block scan when
    the histogram prepass compresses the load (R well under P), else as the
    per-pod scan (module docstring). Both are bit-exact vs
    ``core.semantics.ffd_pack_pure``; the jit cache keys on (P, R-bucket,
    budget) with R padded to powers of two."""
    pod_cpu = np.asarray(pod_cpu)
    pod_mem = np.asarray(pod_mem)
    pod_valid = np.asarray(pod_valid)
    template_cpu = np.asarray(template_cpu)
    template_mem = np.asarray(template_mem)
    P = pod_cpu.shape[1]

    perm, inv, s_cpu, s_mem, s_valid, runs, R = _host_prep(
        pod_cpu, pod_mem, pod_valid, template_cpu, template_mem
    )
    run_cpu, run_mem, run_count, run_start, run_id = runs

    if R <= max(P // 2, 1):
        assignment, used_virtual, unplaced, rem_cpu, rem_mem = _pack_runs_device(
            run_cpu, run_mem, run_count, run_start, run_id,
            s_valid, inv, pod_valid,
            bin_cpu, bin_mem, bin_valid, template_cpu, template_mem,
            new_bin_budget,
        )
    else:
        trim = bool(
            max(int(pod_cpu.max(initial=0)), int(np.asarray(bin_cpu).max(initial=0)),
                int(template_cpu.max(initial=0))) < _F32_EXACT
            and max(int(pod_mem.max(initial=0)), int(np.asarray(bin_mem).max(initial=0)),
                    int(template_mem.max(initial=0))) < _F64_EXACT
        )
        assignment, used_virtual, unplaced, rem_cpu, rem_mem = _pack_pods_device(
            s_cpu, s_mem, s_valid, inv, pod_valid,
            bin_cpu, bin_mem, bin_valid, template_cpu, template_mem,
            new_bin_budget, trim,
        )
    return PackResult(
        assignment=assignment,
        new_nodes_needed=used_virtual,
        unplaced=unplaced,
        bins_remaining_cpu=rem_cpu,
        bins_remaining_mem=rem_mem,
    )


def pack_compression_stats(pod_cpu, pod_mem, pod_valid, template_cpu,
                           template_mem) -> dict:
    """What the histogram prepass would do with this load (bench/diagnostic):
    padded scan length R vs pod axis P, and which scan program ffd_pack picks."""
    pod_cpu = np.asarray(pod_cpu)
    *_rest, R = _host_prep(
        pod_cpu, np.asarray(pod_mem), np.asarray(pod_valid),
        np.asarray(template_cpu), np.asarray(template_mem),
    )
    P = int(pod_cpu.shape[1])
    return {
        "scan_steps": R,
        "pod_axis": P,
        "path": "runs" if R <= max(P // 2, 1) else "pods",
    }


def ffd_pack_reference(pods, bins, template, new_bin_budget):
    """Pure-Python FFD with identical tie-breaking — the golden model for tests.
    pods: list[(cpu, mem)]; bins: list[(cpu, mem)]; template: (cpu, mem).
    Single source of truth lives in core.semantics (the golden backend's
    packing-aware delta uses it without any array deps)."""
    from escalator_tpu.core.semantics import ffd_pack_pure

    return ffd_pack_pure(pods, bins, template, new_bin_budget)
