"""``python -m escalator_tpu.analysis`` — the jaxlint CI gate.

Pins the CPU backend with 8 virtual devices BEFORE importing jax (this is
why ``analysis/__init__.py`` resolves its exports lazily — the package init
runs before this module, and an eager registry import there would drag jax
in ahead of the pin): the
analyzer's subject is the traced program structure, which is identical on
every backend, and the mesh entries need 8 devices to build (the same
environment tests/conftest.py pins, and the only configuration whose parity
math is bit-exact — TPU f64 emulation is not). A sitecustomize on some rigs
pins jax_platforms to the TPU tunnel, so the config is re-pinned after
import, exactly as the test conftest does.

Exit status: 0 when every finding is waived or absent, 1 otherwise —
suitable as a blocking CI step (`make analyze`).
"""

import argparse
import json
import os
import re
import sys


def _pin_cpu_mesh() -> None:
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    # OVERRIDE any existing device count rather than only appending when
    # absent: a leftover =2 from a bench run would silently skip every
    # multi-device entry — the whole R1 surface — while the gate reports
    # green. This process exists only to run the analyzer; it owns the flag.
    flags, n = re.subn(
        r"--xla_force_host_platform_device_count=\d+",
        "--xla_force_host_platform_device_count=8", flags,
    )
    if n == 0:
        flags = (flags + " --xla_force_host_platform_device_count=8").strip()
    os.environ["XLA_FLAGS"] = flags


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m escalator_tpu.analysis",
        description="jaxpr/HLO-level invariant analyzer (rules R1-R8) over "
                    "every registered kernel entry point",
    )
    parser.add_argument("--json", action="store_true",
                        help="machine-readable report on stdout")
    parser.add_argument("--entries", default=None,
                        help="comma-separated entry-name filter (fnmatch "
                             "patterns allowed)")
    parser.add_argument("--waivers", default=None,
                        help="extra waiver file (JSON list of "
                             "{rule, entry, reason})")
    parser.add_argument("--no-retrace", action="store_true",
                        help="skip rule R6's compile probes (fast mode for "
                             "inner-loop use; CI runs the full set)")
    parser.add_argument("--no-execute", action="store_true",
                        help="skip rule R7's transfer-guarded executions "
                             "(fast mode; CI runs the full set)")
    parser.add_argument("--list", action="store_true",
                        help="list registered entries and exit")
    parser.add_argument("--threadlint", action="store_true",
                        help="run the host-side concurrency analyzer "
                             "(rules T1-T4) instead of jaxlint — no jax "
                             "import, source-level, milliseconds")
    args = parser.parse_args(argv)

    if args.threadlint:
        return _threadlint_main(args)

    _pin_cpu_mesh()
    import jax

    jax.config.update("jax_platforms", "cpu")

    from escalator_tpu.analysis import default_registry, load_waivers, run_analysis

    entries = default_registry()
    if args.entries:
        import fnmatch

        patterns = [p.strip() for p in args.entries.split(",") if p.strip()]
        entries = [
            e for e in entries
            if any(fnmatch.fnmatch(e.name, p) for p in patterns)
        ]
        if not entries:
            print(f"no registry entry matches {args.entries!r}",
                  file=sys.stderr)
            return 2
    if args.list:
        for e in entries:
            print(f"{e.name:40s} {e.kind:10s} {e.module}")
        return 0

    extra = load_waivers(args.waivers) if args.waivers else None
    report = run_analysis(entries=entries, extra_waivers=extra,
                          with_retrace=not args.no_retrace,
                          with_execute=not args.no_execute)

    if args.json:
        print(json.dumps(report.to_dict(), indent=2))
    else:
        for er in report.entries:
            mark = {"ok": "ok", "skipped": "SKIP", "waived": "waived",
                    "findings": "FAIL", "error": "ERROR"}[er.status]
            line = f"[{mark:6s}] {er.name}"
            if er.status == "skipped":
                line += f"  ({er.info.get('reason', '')})"
            print(line)
            for f in er.findings:
                flag = "waived" if f.waived else f.rule
                print(f"    {flag}: {f.summary}")
                if f.detail:
                    print(f"        {f.detail}")
                if f.waived and f.waiver_reason:
                    print(f"        waiver: {f.waiver_reason}")
        n = len(report.unwaived)
        print(f"\n{n} unwaived finding(s) over {len(report.entries)} entries")
    # a skipped entry means a rule surface did not run — for a blocking gate
    # that is a failure, not a pass (belt to the XLA_FLAGS override's braces)
    skipped = [e.name for e in report.entries if e.status == "skipped"]
    if skipped:
        print(f"GATE INCOMPLETE: entries skipped: {', '.join(skipped)}",
              file=sys.stderr)
        return 1
    return 1 if report.unwaived else 0


def _threadlint_main(args) -> int:
    """The --threadlint half of the gate: pure AST analysis, so jax (and
    the cpu-mesh pin) never enters the process."""
    from escalator_tpu.analysis.threadlint import run_threadlint
    from escalator_tpu.analysis.waivers import load_waivers

    extra = (load_waivers(args.waivers, site_key="site")
             if args.waivers else None)
    report = run_threadlint(extra_waivers=extra)

    if args.json:
        print(json.dumps(report.to_dict(), indent=2))
    else:
        for f in report.findings:
            flag = "waived" if f.waived else f.rule
            print(f"[{flag:6s}] {f.site}:{f.line}  {f.summary}")
            if f.detail:
                print(f"        {f.detail}")
            if f.waived and f.waiver_reason:
                print(f"        waiver: {f.waiver_reason}")
        print(f"\n{len(report.unwaived)} unwaived finding(s) over "
              f"{len(report.modules)} covered modules")
    return 1 if report.unwaived else 0


if __name__ == "__main__":
    raise SystemExit(main())
