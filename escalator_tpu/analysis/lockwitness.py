"""The lock witness: runtime enforcement of the declared lock ranks.

Every lock in a threadlint-covered module is constructed through
:func:`make_lock` / :func:`make_rlock` / :func:`make_condition`, naming its
:mod:`~escalator_tpu.analysis.concurrency` contract. Disarmed (the default)
the factories return plain ``threading`` primitives — zero steady-state
overhead, one env read at construction. With ``ESCALATOR_TPU_LOCK_WITNESS=1``
they return ranked wrappers that keep a per-thread acquisition stack and
raise :class:`LockOrderViolation` BEFORE acquiring out of rank — the PR-11
deadlock class surfaces as a stack-carrying exception at the first inverted
acquisition instead of as a hung process. The check runs before the
underlying ``acquire`` precisely so an actual deadlock cannot swallow it.

Armed in the fleet soak, the pipelined-shutdown test and the chaos-soak CI
job (tests/test_threadlint.py, .github/workflows/ci.yml). Worker threads
often run under broad excepthooks, so every violation is ALSO appended to
:data:`VIOLATIONS` — tests assert that list is empty after a soak even if
the raising thread's exception went into a log.

stdlib-only: the fleet engine constructs its locks through this module on
every import, including in processes that must never load jax.
"""

from __future__ import annotations

import os
import threading
import traceback
from typing import List, Optional, Union

from escalator_tpu.analysis import concurrency

__all__ = [
    "LockOrderViolation",
    "VIOLATIONS",
    "armed",
    "make_lock",
    "make_rlock",
    "make_condition",
    "held_stack",
]

_ENV = "ESCALATOR_TPU_LOCK_WITNESS"


class LockOrderViolation(RuntimeError):
    """An acquisition out of declared rank order (see concurrency.py)."""


#: Every violation observed process-wide, newest last (the raise can be
#: swallowed by a worker thread's catch-all; this list cannot). Appends are
#: GIL-atomic; tests read it after joining their workers.
VIOLATIONS: List[dict] = []


def armed() -> bool:
    return os.environ.get(_ENV, "").lower() in ("1", "true", "yes")


class _PerThread(threading.local):
    def __init__(self) -> None:
        self.stack: List["_Ranked"] = []


_state = _PerThread()


def held_stack() -> List[str]:
    """Names of ranked locks the calling thread holds, outermost first."""
    return [r.name for r in _state.stack]


class _Ranked:
    """Shared rank bookkeeping for ranked locks and conditions."""

    def __init__(self, name: str, rank: int, kind: str) -> None:
        self.name = name
        self.rank = rank
        self.kind = kind

    # -- the witness check --------------------------------------------------
    def _check(self) -> None:
        stack = _state.stack
        if not stack:
            return
        top = stack[-1]
        if top is self and self.kind == "rlock":
            return  # declared-reentrant self-acquisition
        if self.rank > top.rank:
            return
        held = " -> ".join(f"{r.name}(rank {r.rank})" for r in stack)
        record = {
            "thread": threading.current_thread().name,
            "acquiring": self.name,
            "acquiring_rank": self.rank,
            "held": [r.name for r in stack],
            "stack": "".join(traceback.format_stack(limit=12)),
        }
        VIOLATIONS.append(record)
        raise LockOrderViolation(
            f"out-of-rank acquisition of {self.name!r} (rank {self.rank}) "
            f"while holding [{held}] in thread "
            f"{threading.current_thread().name!r} — the declared order is "
            "ascending ranks only (escalator_tpu/analysis/concurrency.py)"
        )

    def _push(self) -> None:
        _state.stack.append(self)

    def _pop(self) -> None:
        # release order can legally differ from acquire order (e.g.
        # ``with a, b:`` bodies that release a first); drop the newest
        # matching frame rather than asserting LIFO
        stack = _state.stack
        for i in range(len(stack) - 1, -1, -1):
            if stack[i] is self:
                del stack[i]
                return


class RankedLock(_Ranked):
    def __init__(self, name: str, rank: int, kind: str = "lock") -> None:
        super().__init__(name, rank, kind)
        self._lock: Union[threading.Lock, threading.RLock] = (
            threading.RLock() if kind == "rlock" else threading.Lock())

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        self._check()
        got = self._lock.acquire(blocking, timeout)
        if got:
            self._push()
        return got

    def release(self) -> None:
        self._lock.release()
        self._pop()

    def locked(self) -> bool:
        return self._lock.locked()

    def __enter__(self) -> "RankedLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()


class RankedCondition(_Ranked):
    """A ``threading.Condition`` with the same witness on its lock.

    ``wait`` keeps the frame on the per-thread stack even though the
    underlying lock is released for the duration: the waiting thread is
    blocked, so it cannot acquire anything else meanwhile, and keeping the
    frame preserves the rank context for the re-acquire on wakeup.
    """

    def __init__(self, name: str, rank: int) -> None:
        super().__init__(name, rank, "condition")
        self._cond = threading.Condition()

    def acquire(self, *args) -> bool:
        self._check()
        got = self._cond.acquire(*args)
        if got:
            self._push()
        return got

    def release(self) -> None:
        self._cond.release()
        self._pop()

    def __enter__(self) -> "RankedCondition":
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def wait(self, timeout: Optional[float] = None) -> bool:
        return self._cond.wait(timeout)

    def wait_for(self, predicate, timeout: Optional[float] = None):
        return self._cond.wait_for(predicate, timeout)

    def notify(self, n: int = 1) -> None:
        self._cond.notify(n)

    def notify_all(self) -> None:
        self._cond.notify_all()


def _contract(name: str, kind: str) -> concurrency.LockContract:
    try:
        c = concurrency.CONTRACTS_BY_NAME[name]
    except KeyError:
        raise KeyError(
            f"lock {name!r} has no contract — declare it (name, rank, "
            "holder, guarded attrs) in escalator_tpu/analysis/concurrency.py "
            "before constructing it"
        ) from None
    if c.kind != kind:
        raise TypeError(
            f"lock {name!r} is declared as a {c.kind}, constructed as a "
            f"{kind}")
    return c


def make_lock(name: str):
    """A ``threading.Lock`` bound to contract ``name`` (ranked when armed)."""
    c = _contract(name, "lock")
    if armed():
        return RankedLock(name, c.rank, "lock")
    return threading.Lock()


def make_rlock(name: str):
    c = _contract(name, "rlock")
    if armed():
        return RankedLock(name, c.rank, "rlock")
    return threading.RLock()


def make_condition(name: str):
    c = _contract(name, "condition")
    if armed():
        return RankedCondition(name, c.rank)
    return threading.Condition()
