"""threadlint: AST-level enforcement of the declared concurrency contracts.

jaxlint's sibling for the host path. The subject is the covered modules'
*source* (no imports, no jax, runs in milliseconds); the contract is
:mod:`escalator_tpu.analysis.concurrency`. Four rules:

T1  lock-order        — a ``with``-acquired lock whose body (directly, or
                        transitively through same-package calls resolved
                        via the AST call graph) acquires a lock of equal or
                        lower rank. The PR-11 deadlock class, statically.
T2  blocking-in-lock  — ``Condition.wait``/``wait_for`` without a timeout
                        anywhere (a wait IS a lock body), and zero-timeout
                        blocking calls (``Future.result()``, bare
                        ``Thread.join()``) or gRPC round-trips
                        (``*._stub.*``/``*.stub.*``/``*._channel.*``)
                        inside a lock body — a stuck peer or worker must
                        never extend a lock hold indefinitely.
T3  guarded writes    — assignment to a registry-declared guarded attribute
                        outside its owning lock's ``with`` body. ``__init__``
                        is exempt (no concurrent reference exists yet);
                        declared callee contracts (``ASSUME_HELD``) extend
                        the lexical context; the documented unlocked epoch
                        write carries an inline waiver.
T4  undeclared        — bare ``threading.Lock()``/``RLock()``/
                        ``Condition()`` construction in a covered module
                        (locks are constructed through
                        ``analysis.lockwitness`` so construction names a
                        contract and a rank), and ``threading.Thread``
                        spawns whose ``name=`` matches no declared
                        ThreadContract (or is absent).

Waivers, mirroring jaxlint's ledger: per-site inline
``# threadlint: waive[T3] reason`` comments (same line or the line above),
plus the ``THREAD_WAIVERS`` list in ``analysis/waivers.py``
(``{rule, site, reason}``, site an fnmatch pattern over
``path:qualname``). Waived findings stay in every report.

Known static limits (the runtime witness covers them): a manual
``lock.acquire()`` is checked as an acquisition against the lexical context
but does not open a tracked hold region, and per-path reachability is not
modeled — a callee's transitive acquisitions are charged to every call
site.
"""

from __future__ import annotations

import ast
import fnmatch
import os
from dataclasses import dataclass, field
from typing import Any, Dict, List, Mapping, Optional, Sequence, Tuple

from escalator_tpu.analysis import concurrency
from escalator_tpu.analysis.concurrency import (
    ASSUME_HELD,
    COVERED_MODULES,
    EXTERNAL_RECEIVERS,
    GRPC_RECEIVERS,
    LockContract,
    THREADS,
    resolve_lock,
)

__all__ = [
    "ThreadFinding",
    "ThreadlintReport",
    "run_threadlint",
]

_WAIVE_MARK = "# threadlint: waive["


@dataclass
class ThreadFinding:
    rule: str                 # "T1".."T4" (or "ERR" for unparsable source)
    site: str                 # "path:qualname"
    line: int
    summary: str
    detail: str = ""
    waived: bool = False
    waiver_reason: str = ""

    def to_dict(self) -> Dict[str, Any]:
        return {
            "rule": self.rule, "site": self.site, "line": self.line,
            "summary": self.summary, "detail": self.detail,
            "waived": self.waived, "waiver_reason": self.waiver_reason,
        }


@dataclass
class ThreadlintReport:
    findings: List[ThreadFinding]
    modules: List[str]

    @property
    def unwaived(self) -> List[ThreadFinding]:
        return [f for f in self.findings if not f.waived]

    def to_dict(self) -> Dict[str, Any]:
        return {
            "unwaived_findings": len(self.unwaived),
            "modules": self.modules,
            "contracts": [
                {"name": c.name, "rank": c.rank, "module": c.module,
                 "holder": c.holder, "kind": c.kind}
                for c in concurrency.CONTRACTS
            ],
            "findings": [f.to_dict() for f in self.findings],
        }


# ---------------------------------------------------------------------------
# Per-function event extraction
# ---------------------------------------------------------------------------


@dataclass
class _Event:
    kind: str                 # acquire|call|wait|block|grpc|write|construct|thread
    line: int
    held: Tuple[str, ...]     # contract names lexically held, outermost first
    data: Any = None


@dataclass
class _FuncInfo:
    module: str
    qualname: str
    events: List[_Event] = field(default_factory=list)


def _attr_chain(node: ast.AST) -> Optional[List[str]]:
    """['self', '_cv'] for ``self._cv``; None for non-trivial expressions."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return parts[::-1]
    return None


def _has_timeout(call: ast.Call) -> bool:
    if call.args:
        return True  # wait(0.1) / join(5.0) / result(3) positional
    return any(kw.arg == "timeout" for kw in call.keywords)


class _FunctionVisitor(ast.NodeVisitor):
    """Collects lock-relevant events with the lexical held-lock context."""

    def __init__(self, module: str, class_name: Optional[str],
                 qualname: str, out: _FuncInfo) -> None:
        self.module = module
        self.class_name = class_name
        self.out = out
        seeded = ASSUME_HELD.get((module, qualname), ())
        self.held: List[str] = list(seeded)

    # -- helpers ------------------------------------------------------------
    def _lock_of(self, node: ast.AST) -> Optional[LockContract]:
        chain = _attr_chain(node)
        if chain is None:
            return None
        return resolve_lock(self.module, self.class_name, ".".join(chain))

    def _emit(self, kind: str, line: int, data: Any = None) -> None:
        self.out.events.append(
            _Event(kind=kind, line=line, held=tuple(self.held), data=data))

    # -- structure ----------------------------------------------------------
    def visit_With(self, node: ast.With) -> None:
        pushed = 0
        for item in node.items:
            c = self._lock_of(item.context_expr)
            if c is not None:
                self._emit("acquire", item.context_expr.lineno, c.name)
                self.held.append(c.name)
                pushed += 1
            else:
                # still scan the context expression (it may contain calls)
                self.visit(item.context_expr)
        for stmt in node.body:
            self.visit(stmt)
        for _ in range(pushed):
            self.held.pop()

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        # nested defs run on their own schedule (worker closures): they are
        # indexed and analyzed separately with an empty context
        return

    visit_AsyncFunctionDef = visit_FunctionDef  # type: ignore[assignment]

    # -- writes -------------------------------------------------------------
    def _record_write_target(self, target: ast.AST) -> None:
        if isinstance(target, (ast.Tuple, ast.List)):
            for elt in target.elts:
                self._record_write_target(elt)
            return
        if (isinstance(target, ast.Attribute)
                and isinstance(target.value, ast.Name)
                and target.value.id == "self"):
            self._emit("write", target.lineno, target.attr)

    def visit_Assign(self, node: ast.Assign) -> None:
        for t in node.targets:
            self._record_write_target(t)
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        self._record_write_target(node.target)
        self.generic_visit(node)

    def visit_AnnAssign(self, node: ast.AnnAssign) -> None:
        if node.value is not None:
            self._record_write_target(node.target)
        self.generic_visit(node)

    # -- calls --------------------------------------------------------------
    def visit_Call(self, node: ast.Call) -> None:
        chain = _attr_chain(node.func)
        if chain:
            self._classify_call(node, chain)
        self.generic_visit(node)

    def _classify_call(self, node: ast.Call, chain: List[str]) -> None:
        line = node.lineno
        method = chain[-1]
        # threading primitive / thread construction (T4 surface)
        if len(chain) == 2 and chain[0] == "threading":
            if method in ("Lock", "RLock", "Condition"):
                self._emit("construct", line, method)
                return
            if method == "Thread":
                name = None
                for kw in node.keywords:
                    if kw.arg == "name" and isinstance(kw.value, ast.Constant):
                        name = kw.value.value
                self._emit("thread", line, name)
                return
        # manual acquire on a contracted lock: rank-check without a region
        if method in ("acquire", "release") and len(chain) >= 2:
            c = resolve_lock(self.module, self.class_name,
                             ".".join(chain[:-1]))
            if c is not None and method == "acquire":
                self._emit("acquire", line, c.name)
            if c is not None:
                return
        # condition waits: a wait without timeout blocks forever while
        # (by definition) holding the condition's lock
        if method in ("wait", "wait_for"):
            c = resolve_lock(self.module, self.class_name,
                             ".".join(chain[:-1]))
            if c is not None and c.kind == "condition":
                timed = (_has_timeout(node) if method == "wait"
                         else len(node.args) > 1
                         or any(kw.arg == "timeout" for kw in node.keywords))
                if not timed:
                    self._emit("wait", line, c.name)
                return
        # zero-timeout blocking primitives inside a lock body
        if method == "result" and not _has_timeout(node):
            self._emit("block", line, f"{'.'.join(chain)}()")
        elif method == "join" and not node.args and not node.keywords:
            # bare .join(): Thread.join-forever shape (str.join always
            # carries its iterable argument, so it never matches)
            self._emit("block", line, f"{'.'.join(chain)}()")
        # gRPC round-trips
        if len(chain) >= 2 and any(r in chain[:-1] for r in GRPC_RECEIVERS):
            self._emit("grpc", line, ".".join(chain))
        # call-graph edge
        callee = self._resolve_callee(chain)
        if callee is not None:
            self._emit("call", line, callee)

    def _resolve_callee(self, chain: List[str]) -> Optional[Tuple[str, str]]:
        if len(chain) == 1:
            return (self.module, chain[0])
        if len(chain) == 2 and chain[0] == "self" and self.class_name:
            return (self.module, f"{self.class_name}.{chain[1]}")
        if len(chain) >= 2 and chain[-2] in EXTERNAL_RECEIVERS:
            mod, cls = EXTERNAL_RECEIVERS[chain[-2]]
            return (mod, f"{cls}.{chain[-1]}")
        return None


# ---------------------------------------------------------------------------
# Module indexing
# ---------------------------------------------------------------------------


def _index_module(module: str, source: str) -> Dict[str, _FuncInfo]:
    tree = ast.parse(source, filename=module)
    funcs: Dict[str, _FuncInfo] = {}

    def collect(node: ast.AST, class_name: Optional[str],
                prefix: str) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qual = f"{prefix}{child.name}"
                info = _FuncInfo(module=module, qualname=qual)
                v = _FunctionVisitor(module, class_name, qual, info)
                for stmt in child.body:
                    v.visit(stmt)
                funcs[qual] = info
                # nested defs (worker closures): own context, own entry
                collect(child, class_name, f"{qual}.<locals>.")
            elif isinstance(child, ast.ClassDef):
                collect(child, child.name, f"{child.name}.")
            elif not isinstance(child, (ast.Import, ast.ImportFrom)):
                collect(child, class_name, prefix)

    collect(tree, None, "")
    # module-level statements (lock constructions at import time)
    top = _FuncInfo(module=module, qualname="<module>")
    v = _FunctionVisitor(module, None, "<module>", top)
    for stmt in tree.body:
        if not isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
            v.visit(stmt)
    funcs["<module>"] = top
    return funcs


# ---------------------------------------------------------------------------
# Transitive lock summaries (the T1 call graph)
# ---------------------------------------------------------------------------


class _Summaries:
    def __init__(self, index: Dict[Tuple[str, str], _FuncInfo]) -> None:
        self.index = index
        self.memo: Dict[Tuple[str, str], Dict[str, Tuple[str, ...]]] = {}

    def acquired(self, key: Tuple[str, str],
                 _seen: Optional[set] = None) -> Dict[str, Tuple[str, ...]]:
        """lock name -> call chain (qualnames) that reaches the acquisition,
        transitively from function ``key``. Cycle-safe, memoized."""
        if key in self.memo:
            return self.memo[key]
        seen = _seen if _seen is not None else set()
        if key in seen:
            return {}
        seen.add(key)
        info = self.index.get(key)
        out: Dict[str, Tuple[str, ...]] = {}
        if info is not None:
            for ev in info.events:
                if ev.kind == "acquire":
                    out.setdefault(ev.data, (info.qualname,))
                elif ev.kind == "call":
                    for name, chain in self.acquired(
                            tuple(ev.data), _seen=seen).items():
                        out.setdefault(name, (info.qualname,) + chain)
        seen.discard(key)
        if _seen is None:
            self.memo[key] = out
        return out


# ---------------------------------------------------------------------------
# The rules
# ---------------------------------------------------------------------------


def _rank(name: str) -> int:
    return concurrency.CONTRACTS_BY_NAME[name].rank


def _kind(name: str) -> str:
    return concurrency.CONTRACTS_BY_NAME[name].kind


def _check_function(info: _FuncInfo, summaries: _Summaries,
                    guarded_owner: Dict[Tuple[str, str], str],
                    findings: List[ThreadFinding]) -> None:
    site = f"{info.module}:{info.qualname}"
    for ev in info.events:
        if ev.kind == "acquire":
            for held in ev.held:
                if held == ev.data and _kind(held) == "rlock":
                    continue
                if _rank(ev.data) <= _rank(held):
                    findings.append(ThreadFinding(
                        rule="T1", site=site, line=ev.line,
                        summary=(
                            f"acquires {ev.data!r} (rank {_rank(ev.data)}) "
                            f"while holding {held!r} (rank {_rank(held)})"
                        ),
                        detail="declared order is strictly ascending ranks "
                               "(analysis/concurrency.py)",
                    ))
        elif ev.kind == "call" and ev.held:
            acq = summaries.acquired(tuple(ev.data))
            for name, chain in acq.items():
                for held in ev.held:
                    if name == held and _kind(held) == "rlock":
                        continue
                    if _rank(name) <= _rank(held):
                        findings.append(ThreadFinding(
                            rule="T1", site=site, line=ev.line,
                            summary=(
                                f"call while holding {held!r} (rank "
                                f"{_rank(held)}) transitively acquires "
                                f"{name!r} (rank {_rank(name)})"
                            ),
                            detail="via " + " -> ".join(chain),
                        ))
        elif ev.kind == "wait":
            findings.append(ThreadFinding(
                rule="T2", site=site, line=ev.line,
                summary=f"untimed wait on condition {ev.data!r}",
                detail="a wait without timeout pins the condition's lock "
                       "slot forever if the notify is lost; every "
                       "production wait is bounded and re-checks its "
                       "predicate",
            ))
        elif ev.kind == "block" and ev.held:
            findings.append(ThreadFinding(
                rule="T2", site=site, line=ev.line,
                summary=f"unbounded blocking call {ev.data} while holding "
                        f"{ev.held[-1]!r}",
                detail="held locks: " + ", ".join(ev.held),
            ))
        elif ev.kind == "grpc" and ev.held:
            findings.append(ThreadFinding(
                rule="T2", site=site, line=ev.line,
                summary=f"gRPC call {ev.data} inside a lock body "
                        f"(holding {ev.held[-1]!r})",
                detail="a stuck peer must never extend a lock hold; move "
                       "the round-trip outside the critical section",
            ))
        elif ev.kind == "write":
            owner = guarded_owner.get((info.module, ev.data))
            if owner is None:
                continue
            cls = concurrency.CONTRACTS_BY_NAME[owner].holder.split(".")[0]
            # only writes on the owning class count (same attr name on an
            # unrelated class in the same module is a different field)
            if not info.qualname.startswith(f"{cls}."):
                continue
            if info.qualname == f"{cls}.__init__":
                continue
            if owner in ev.held:
                continue
            findings.append(ThreadFinding(
                rule="T3", site=site, line=ev.line,
                summary=f"write to guarded attribute self.{ev.data} outside "
                        f"its owning lock {owner!r}",
                detail="declare the lock hold (with-block or ASSUME_HELD) "
                       "or waive the site inline with its argument",
            ))
        elif ev.kind == "construct":
            findings.append(ThreadFinding(
                rule="T4", site=site, line=ev.line,
                summary=f"bare threading.{ev.data}() in a covered module",
                detail="construct through analysis.lockwitness.make_* so "
                       "the lock declares a contract name and rank",
            ))
        elif ev.kind == "thread":
            if ev.data is None:
                findings.append(ThreadFinding(
                    rule="T4", site=site, line=ev.line,
                    summary="threading.Thread without a literal name= in a "
                            "covered module",
                    detail="name the thread and declare it in "
                           "concurrency.THREADS",
                ))
            elif not any(fnmatch.fnmatch(ev.data, t.name_pattern)
                         for t in THREADS):
                findings.append(ThreadFinding(
                    rule="T4", site=site, line=ev.line,
                    summary=f"undeclared worker thread {ev.data!r}",
                    detail="declare it in concurrency.THREADS with its "
                           "purpose",
                ))


# ---------------------------------------------------------------------------
# Waivers
# ---------------------------------------------------------------------------


def _apply_inline_waivers(findings: Sequence[ThreadFinding],
                          lines_by_module: Mapping[str, List[str]]) -> None:
    for f in findings:
        module = f.site.split(":", 1)[0]
        lines = lines_by_module.get(module, [])
        for ln in (f.line, f.line - 1):
            if 1 <= ln <= len(lines):
                text = lines[ln - 1]
                mark = f"{_WAIVE_MARK}{f.rule}]"
                idx = text.find(mark)
                if idx >= 0:
                    f.waived = True
                    f.waiver_reason = text[idx + len(mark):].strip() or \
                        "inline waiver"
                    break


def _apply_ledger_waivers(findings: Sequence[ThreadFinding],
                          waivers: Sequence[Mapping[str, str]]) -> None:
    for f in findings:
        if f.waived:
            continue
        for w in waivers:
            if w.get("rule") == f.rule and fnmatch.fnmatch(
                    f.site, w.get("site", "")):
                f.waived = True
                f.waiver_reason = w.get("reason", "")
                break


# ---------------------------------------------------------------------------
# Entry point
# ---------------------------------------------------------------------------


def run_threadlint(
    root: Optional[str] = None,
    sources: Optional[Mapping[str, str]] = None,
    extra_waivers: Optional[Sequence[Mapping[str, str]]] = None,
) -> ThreadlintReport:
    """Analyze the covered modules (plus/overridden-by ``sources``: a
    ``{repo-relative-path: source-text}`` mapping — how the mutation tests
    feed re-introduced bugs) and apply waivers."""
    from escalator_tpu.analysis.waivers import THREAD_WAIVERS

    if root is None:
        # analysis/ -> escalator_tpu/ -> repo root
        root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
    texts: Dict[str, str] = {}
    findings: List[ThreadFinding] = []
    modules = list(COVERED_MODULES)
    for extra_mod in (sources or {}):
        if extra_mod not in modules:
            modules.append(extra_mod)
    for module in modules:
        if sources and module in sources:
            texts[module] = sources[module]
            continue
        path = os.path.join(root, module)
        try:
            with open(path) as fh:
                texts[module] = fh.read()
        except OSError as e:
            findings.append(ThreadFinding(
                rule="ERR", site=f"{module}:<file>", line=0,
                summary=f"covered module unreadable: {e}",
            ))
    index: Dict[Tuple[str, str], _FuncInfo] = {}
    lines_by_module: Dict[str, List[str]] = {}
    for module, text in texts.items():
        lines_by_module[module] = text.splitlines()
        try:
            for qual, info in _index_module(module, text).items():
                index[(module, qual)] = info
        except SyntaxError as e:
            findings.append(ThreadFinding(
                rule="ERR", site=f"{module}:<parse>", line=e.lineno or 0,
                summary=f"covered module failed to parse: {e.msg}",
            ))
    guarded_owner: Dict[Tuple[str, str], str] = {}
    for c in concurrency.CONTRACTS:
        for attr in c.guarded:
            guarded_owner[(c.module, attr)] = c.name
    summaries = _Summaries(index)
    for info in index.values():
        _check_function(info, summaries, guarded_owner, findings)
    findings.sort(key=lambda f: (f.site, f.line, f.rule))
    _apply_inline_waivers(findings, lines_by_module)
    _apply_ledger_waivers(
        findings, list(THREAD_WAIVERS) + list(extra_waivers or []))
    return ThreadlintReport(findings=findings, modules=sorted(texts))
