"""Kernel registry: every public jitted/sharded entry point, with the
representative shapes and per-entry budgets the rules check against.

The reference gates merges on ``go vet`` + race + lint
(/root/reference/Makefile:13-17); the analog here has to know *what to
trace*. This registry is that list — one entry per public device program
(``ops/kernel.py``, ``ops/order_tail.py``, ``ops/binpack.py``,
``ops/device_state.py``, ``ops/simulate.py``, ``parallel/grid.py``,
``parallel/podaxis.py``, ``parallel/mesh.py``) with:

- a lazy ``build`` producing the callable + representative args (small,
  deterministic shapes; distinct sizes per axis so a sort over the global
  node axis cannot be confused with one over a block);
- ``global_axes``: the full pod/node axis sizes rule R1 treats as
  "replicated work if an in-mesh sort spans me";
- the declared output dtype contract (rule R2 — the float64/int64 parity
  surface of ``core/semantics.py``/``core/arrays.py``, enforced instead of
  documented);
- a pinned collective budget (rule R3 — a new ``psum`` on the hot path is a
  finding, not a silent regression);
- whether lowering must carry buffer donation (rule R5, the
  ``ops/device_state.py`` donate_argnums sites);
- a retrace budget + probe (rule R6 — compile-count across a two-tick
  sweep, catching static-argnum churn).

Entries are cheap to *declare*; everything expensive (tracing, lowering,
probing) happens lazily in the rule engine.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Mapping, Optional, Tuple

import numpy as np

from escalator_tpu.jaxconfig import ensure_x64

ensure_x64()

import jax
import jax.numpy as jnp

from escalator_tpu.core.arrays import (
    NO_TAINT_TIME,
    ClusterArrays,
    GroupArrays,
    NodeArrays,
    PodArrays,
)

NOW = np.int64(1_700_000_000)

# The gate traces one deterministic program set: aggregation impl is pinned
# to "xla" in every builder below so ESCALATOR_TPU_KERNEL_IMPL in the
# environment cannot change what the analyzer sees (the pallas sweep is a
# different — interpreter-mode on CPU — program; lint findings must not
# depend on a rig's env).

# Representative shapes. Deliberately pairwise-distinct (and distinct from
# any derived block size) so rule R1's "operand length == global axis
# length" match cannot alias: a block sort over [NB] lanes never equals the
# global [NODES], and neither equals [GROUPS] or [PODS].
GROUPS = 6
PODS = 168          # divisible by the 8-device mesh (podaxis shard_map)
NODES = 52
SHARD_GROUPS = 3    # per-shard sizes for the stacked (mesh/grid) layouts
SHARD_PODS = 40
SHARD_NODES = 16

#: The DecisionArrays dtype contract — the bit-parity surface documented in
#: core/arrays.py comments, now enforced. float64 percents and int64
#: request/capacity sums are the fields the golden model compares bit-exact.
DECISION_DTYPES: Dict[str, str] = {
    "status": "int32",
    "nodes_delta": "int32",
    "cpu_percent": "float64",
    "mem_percent": "float64",
    "cpu_request_milli": "int64",
    "mem_request_bytes": "int64",
    "cpu_capacity_milli": "int64",
    "mem_capacity_bytes": "int64",
    "num_pods": "int32",
    "num_nodes": "int32",
    "num_untainted": "int32",
    "num_tainted": "int32",
    "num_cordoned": "int32",
    "scale_down_order": "int32",
    "untainted_offsets": "int32",
    "untaint_order": "int32",
    "tainted_offsets": "int32",
    "reap_mask": "bool",
    "node_pods_remaining": "int32",
}

SWEEP_DTYPES: Dict[str, str] = {
    "post_cpu_percent": "float64",
    "post_mem_percent": "float64",
    "feasible": "bool",
    "min_feasible_delta": "int32",
}

#: The persistent-aggregate contract (round 8): every sum column is int64 —
#: the R2 dtype guarantee that makes delta maintenance drift-free (a float
#: column here would accumulate rounding and break the refresh audit).
AGGREGATE_DTYPES: Dict[str, str] = {
    "cpu_req": "int64",
    "mem_req": "int64",
    "num_pods": "int64",
    "cpu_cap": "int64",
    "mem_cap": "int64",
    "num_nodes": "int64",
    "num_untainted": "int64",
    "num_tainted": "int64",
    "num_cordoned": "int64",
    "node_pods_remaining": "int64",
    "dirty": "bool",
}

#: The explain-kernel contract (round 19): the 13 persistent decision
#: columns RECONSTRUCTED by ``kernel.explain_decide`` must carry exactly
#: the committed columns' dtypes — the provenance cross-check compares
#: them bit-for-bit, so a silent widening/demotion here would fabricate
#: mismatches (or worse, mask real ones). The two branch indices are the
#: attribution surface: small int32 selectors into the named arm tuples.
#: Derivation terms, gate booleans and config echoes ride along undeclared
#: (R2's contract is a subset check by design).
EXPLAIN_DTYPES: Dict[str, str] = {
    name: DECISION_DTYPES[name]
    for name in (
        "status", "nodes_delta", "cpu_percent", "mem_percent",
        "cpu_request_milli", "mem_request_bytes",
        "cpu_capacity_milli", "mem_capacity_bytes",
        "num_pods", "num_nodes", "num_untainted", "num_tainted",
        "num_cordoned",
    )
}
EXPLAIN_DTYPES["threshold_branch"] = "int32"
EXPLAIN_DTYPES["status_branch"] = "int32"


@dataclass
class TracedEntry:
    """What ``KernelEntry.build`` returns: the traceable callable plus the
    concrete representative arguments, and (optionally) the underlying
    jit-wrapped callable for lowering-level checks (rule R5)."""

    fn: Callable
    args: Tuple[Any, ...]
    jitted: Optional[Any] = None   # has .lower(*args) when donation is checked
    lower: Optional[Callable[[], Any]] = None  # overrides jitted.lower(*args)
                                               # (entries with static argnames)
    execute: Optional[Callable[[Tuple[Any, ...]], Any]] = None
    # overrides rule R7's execution: called with the device-placed arg tuple.
    # Needed only when neither ``fn`` nor ``jitted(*args)`` runs the compiled
    # program (e.g. ``fn`` is the EAGER impl and the jit takes static kwargs
    # absent from ``args``, as in ops/simulate.py).


def _identity(out: Any) -> Any:
    return out


@dataclass
class KernelEntry:
    name: str
    module: str
    kind: str                       # "jit" | "shard_map"
    build: Callable[[], TracedEntry]
    mapped: bool = False            # multi-device program: R1/R3 apply
    min_devices: int = 1
    global_axes: Mapping[str, int] = field(default_factory=dict)
    output_dtypes: Optional[Mapping[str, str]] = None
    output_select: Callable[[Any], Any] = _identity
    collective_budget: Optional[int] = None
    donate_expected: bool = False
    retrace_budget: Optional[int] = None
    retrace_probe: Optional[Callable[[], int]] = None
    #: R7 escape hatch: transfer directions this entry is ALLOWED to perform
    #: while executing ("host_to_device" / "device_to_host"). Empty means the
    #: entry must run fully device-resident under jax.transfer_guard.
    transfer_allow: Tuple[str, ...] = ()
    #: R8: name of the fenced=False observability span (observability/spans.py)
    #: this program runs under on the hot path. Entries claiming async overlap
    #: must lower to a program with no forced host sync (infeed/outfeed/
    #: host callbacks) — a sync op there silently serializes the overlap.
    overlap_span: Optional[str] = None


def representative_cluster(G: int = GROUPS, P: int = PODS, N: int = NODES,
                           seed: int = 0) -> ClusterArrays:
    """Deterministic small cluster with every lane class populated (tainted,
    cordoned, invalid, unassigned pods) so each traced program exercises its
    full branch surface."""
    rng = np.random.default_rng(seed)
    tainted = rng.random(N) < 0.25
    return ClusterArrays(
        groups=GroupArrays(
            min_nodes=rng.integers(0, 2, G).astype(np.int32),
            max_nodes=np.full(G, 10**6, np.int32),
            taint_lower=np.full(G, 30, np.int32),
            taint_upper=np.full(G, 45, np.int32),
            scale_up_thr=np.full(G, 70, np.int32),
            slow_rate=np.ones(G, np.int32),
            fast_rate=np.full(G, 3, np.int32),
            locked=rng.random(G) < 0.1,
            requested_nodes=rng.integers(0, 4, G).astype(np.int32),
            cached_cpu_milli=np.full(G, 4000, np.int64),
            cached_mem_bytes=np.full(G, 16 * 10**9, np.int64),
            soft_grace_sec=np.full(G, 300, np.int64),
            hard_grace_sec=np.full(G, 900, np.int64),
            emptiest=(np.arange(G) % 3 == 0),
            valid=np.ones(G, bool),
        ),
        pods=PodArrays(
            group=rng.integers(0, G, P).astype(np.int32),
            cpu_milli=rng.integers(0, 8000, P).astype(np.int64),
            mem_bytes=rng.integers(0, 32 * 10**9, P).astype(np.int64),
            node=rng.integers(-1, N, P).astype(np.int32),
            valid=rng.random(P) < 0.95,
        ),
        nodes=NodeArrays(
            group=rng.integers(0, G, N).astype(np.int32),
            cpu_milli=np.full(N, 4000, np.int64),
            mem_bytes=np.full(N, 16 * 10**9, np.int64),
            creation_ns=rng.integers(1, 10**12, N).astype(np.int64),
            tainted=tainted,
            cordoned=(~tainted) & (rng.random(N) < 0.05),
            no_delete=rng.random(N) < 0.02,
            taint_time_sec=np.where(
                tainted, int(NOW) - rng.integers(0, 2000, N), NO_TAINT_TIME
            ).astype(np.int64),
            valid=rng.random(N) < 0.97,
        ),
    )


def stacked_cluster(num_shards: int, G: int = SHARD_GROUPS,
                    P: int = SHARD_PODS, N: int = SHARD_NODES,
                    seed: int = 1) -> ClusterArrays:
    """Stacked [S, ...] cluster (the mesh/grid layout from
    ``mesh.pack_cluster_sharded``), built by stacking per-shard clusters."""
    shards = [
        representative_cluster(G, P, N, seed=seed + s) for s in range(num_shards)
    ]
    leaves = [c.tree_flatten()[0] for c in shards]
    stacked = [np.stack(parts) for parts in zip(*leaves, strict=True)]
    return ClusterArrays.tree_unflatten(None, stacked)


# ---------------------------------------------------------------------------
# Entry builders (all lazy: nothing traces or compiles at registry import)
# ---------------------------------------------------------------------------


def _build_kernel_decide() -> TracedEntry:
    from escalator_tpu.ops import kernel

    cluster = representative_cluster()
    fn = lambda c, t: kernel.decide(c, t)  # noqa: E731
    return TracedEntry(fn=fn, args=(cluster, NOW), jitted=kernel._decide_jit_raw)


def _probe_kernel_retraces() -> int:
    """Two ticks, ordered + light programs, same shapes: at most one compile
    per (with_orders,) variant. Shapes are registry-local, so a cold process
    observes exactly the budget; a warm one (tests) observes fewer."""
    from escalator_tpu.ops import kernel

    before = kernel._decide_jit_raw._cache_size()
    for seed in (11, 12):
        cluster = representative_cluster(seed=seed)
        for with_orders in (True, False):
            jax.block_until_ready(
                kernel._decide_jit_raw(cluster, NOW, with_orders=with_orders)
            )
    return kernel._decide_jit_raw._cache_size() - before


def _fleet_stacked_cluster(C: int, seed: int = 0) -> ClusterArrays:
    """[C, ...]-stacked tenants at the representative single-cluster shapes
    (each leaf gains a leading cluster axis — the fleet kernel layout)."""
    shards = [representative_cluster(seed=seed + c) for c in range(C)]
    leaves = [c.tree_flatten()[0] for c in shards]
    stacked = [np.stack(parts) for parts in zip(*leaves, strict=True)]
    return ClusterArrays.tree_unflatten(None, stacked)


_FLEET_C = 3


def _build_fleet_decide() -> TracedEntry:
    from escalator_tpu.ops import kernel

    cluster = _fleet_stacked_cluster(_FLEET_C)
    nows = np.full(_FLEET_C, NOW, np.int64)
    return TracedEntry(fn=kernel.fleet_decide, args=(cluster, nows),
                       jitted=kernel._fleet_decide_jit_raw)


def _probe_fleet_decide_retraces() -> int:
    """Two fleet batches, same stacked shapes, different tenant contents:
    exactly one compile — batch content is never a cache key."""
    from escalator_tpu.ops import kernel

    before = kernel._fleet_decide_jit_raw._cache_size()
    nows = np.full(_FLEET_C, NOW, np.int64)
    for seed in (61, 62):
        jax.block_until_ready(kernel._fleet_decide_jit_raw(
            _fleet_stacked_cluster(_FLEET_C, seed=seed), nows))
    return kernel._fleet_decide_jit_raw._cache_size() - before


def _fleet_step_args(seed: int = 27, row: int = 0):
    """Concrete fleet-step operands at tiny arena buckets, built with the
    SAME helpers the engine's dispatch uses (zero_state, _gather_padded,
    fleet_dirty_indices): one real tenant (a full-lane bootstrap batch)
    plus one scratch-row pad entry."""
    from escalator_tpu.fleet import service as fsvc
    from escalator_tpu.ops import device_state as ds
    from escalator_tpu.ops import kernel

    C, G, P, N = 2, GROUPS, 24, 12
    state = fsvc.zero_state(C, G, P, N)
    cluster = representative_cluster(G, P, N, seed=seed)
    B_pod = fsvc.delta_bucket(P)
    B_node = fsvc.delta_bucket(N)
    pi, pv = ds._gather_padded(cluster.pods, np.arange(P, dtype=np.int64),
                               B_pod, P, ds._POD_PAD)
    ni, nv = ds._gather_padded(cluster.nodes, np.arange(N, dtype=np.int64),
                               B_node, N, ds._NODE_PAD)
    pi0, pv0 = ds._gather_padded(fsvc._empty_pods(0), np.zeros(0, np.int64),
                                 B_pod, P, ds._POD_PAD)
    ni0, nv0 = ds._gather_padded(fsvc._empty_nodes(0), np.zeros(0, np.int64),
                                 B_node, N, ds._NODE_PAD)
    stack = lambda soas: type(soas[0])(  # noqa: E731
        **{f.name: np.stack([getattr(s, f.name) for s in soas])
           for f in dataclasses.fields(soas[0])})
    rows = np.array([row, C], np.int32)
    dirty = kernel.fleet_dirty_indices(
        [np.ones(G, bool), np.zeros(G, bool)], G)
    nows = np.array([NOW, 0], np.int64)
    return (*state, rows, stack([cluster.groups, fsvc._empty_groups(G)]),
            np.stack([pi, pi0]), stack([pv, pv0]),
            np.stack([ni, ni0]), stack([nv, nv0]), dirty, nows)


def _build_fleet_step() -> TracedEntry:
    from escalator_tpu.ops import device_state as ds

    args = _fleet_step_args()
    return TracedEntry(fn=ds._fleet_step_core, args=args,
                       jitted=ds._fleet_step)


def _probe_fleet_step_retraces() -> int:
    """Two micro-batches at the SAME bucket shapes but different tenant
    rows and contents (a tenant add/remove between batches changes row
    indices, never a shape): exactly one compile."""
    from escalator_tpu.ops import device_state as ds

    before = ds._fleet_step._cache_size()
    for seed, row in ((71, 0), (72, 1)):
        state_out, out = ds._fleet_step(*_fleet_step_args(seed=seed, row=row))
        jax.block_until_ready(out)
    return ds._fleet_step._cache_size() - before


_FLEET_SHARDS = 2


def _fleet_shard_mesh():
    from jax.sharding import Mesh

    from escalator_tpu.ops import device_state as ds

    return Mesh(np.array(jax.devices()[:_FLEET_SHARDS]),
                (ds.FLEET_SHARD_AXIS,))


def _fleet_step_sharded_args(seed: int = 27, rows=(0, 1)):
    """The fleet-step operands with a leading shard axis: two shards, each
    a real tenant + scratch pad entry — built by stacking the SAME
    single-shard fixture the unsharded entry analyzes."""
    from jax import tree_util

    parts = [_fleet_step_args(seed=seed + 10 * s, row=rows[s])
             for s in range(_FLEET_SHARDS)]
    return tree_util.tree_map(lambda *xs: np.stack(xs), *parts)


def _build_fleet_step_sharded() -> TracedEntry:
    from escalator_tpu.ops import device_state as ds

    fn = ds.make_fleet_step_sharded(_fleet_shard_mesh())
    return TracedEntry(fn=fn, args=_fleet_step_sharded_args(), jitted=fn)


def _probe_fleet_step_sharded_retraces() -> int:
    """Same contract as the unsharded probe, across the shard axis too:
    different rows/contents per shard, identical bucket shapes — one
    compile."""
    from escalator_tpu.ops import device_state as ds

    fn = ds.make_fleet_step_sharded(_fleet_shard_mesh())
    before = fn._cache_size()
    for seed, rows in ((81, (0, 1)), (82, (1, 0))):
        state_out, out = fn(*_fleet_step_sharded_args(seed=seed, rows=rows))
        jax.block_until_ready(out)
    return fn._cache_size() - before


def _fleet_step_drain_args(seed: int = 27, row: int = 0):
    """The fleet step fed DRAIN-shaped operands (round 18 streaming
    ingestion): a populated arena — one bootstrap step's output — plus a
    SPARSE packed delta batch at the same ``delta_bucket`` shapes, exactly
    what the tenant-drain apply path scatters when a client ships its
    store twin's dirty drain instead of a full frame. Same program and
    buckets as the bootstrap entry; this fixture pins the lint checks
    (donation, 0-psum, retrace) on the operand shape cfg17's steady state
    actually runs."""
    from jax import tree_util

    from escalator_tpu.fleet import service as fsvc
    from escalator_tpu.ops import device_state as ds
    from escalator_tpu.ops import kernel

    C, G, P, N = 2, GROUPS, 24, 12
    state_out, _out = ds._fleet_step(*_fleet_step_args(seed=seed, row=row))
    state = tree_util.tree_map(np.asarray, state_out)
    cluster = representative_cluster(G, P, N, seed=seed + 5)
    B_pod = fsvc.delta_bucket(P)
    B_node = fsvc.delta_bucket(N)
    pod_slots = np.array([1, 5, 9], np.int64)
    node_slots = np.array([2, 7], np.int64)
    pi, pv = ds._gather_padded(cluster.pods, pod_slots, B_pod, P, ds._POD_PAD)
    ni, nv = ds._gather_padded(cluster.nodes, node_slots, B_node, N,
                               ds._NODE_PAD)
    pi0, pv0 = ds._gather_padded(fsvc._empty_pods(0), np.zeros(0, np.int64),
                                 B_pod, P, ds._POD_PAD)
    ni0, nv0 = ds._gather_padded(fsvc._empty_nodes(0), np.zeros(0, np.int64),
                                 B_node, N, ds._NODE_PAD)
    stack = lambda soas: type(soas[0])(  # noqa: E731
        **{f.name: np.stack([getattr(s, f.name) for s in soas])
           for f in dataclasses.fields(soas[0])})
    touched = np.zeros(G, bool)
    touched[np.unique(cluster.pods.group[pod_slots])] = True
    touched[np.unique(cluster.nodes.group[node_slots])] = True
    dirty = kernel.fleet_dirty_indices([touched, np.zeros(G, bool)], G)
    rows = np.array([row, C], np.int32)
    nows = np.array([NOW + 60, 0], np.int64)
    return (*state, rows, stack([cluster.groups, fsvc._empty_groups(G)]),
            np.stack([pi, pi0]), stack([pv, pv0]),
            np.stack([ni, ni0]), stack([nv, nv0]), dirty, nows)


def _build_fleet_step_drain() -> TracedEntry:
    from escalator_tpu.ops import device_state as ds

    args = _fleet_step_drain_args()
    return TracedEntry(fn=ds._fleet_step_core, args=args,
                       jitted=ds._fleet_step)


def _probe_fleet_step_drain_retraces() -> int:
    """Two drain-shaped micro-batches with different dirty slots and
    contents at the same bucket shapes: the drain path must hit the same
    compiled program (slot indices are content, never a cache key)."""
    from escalator_tpu.ops import device_state as ds

    before = ds._fleet_step._cache_size()
    for seed, row in ((75, 0), (76, 1)):
        state_out, out = ds._fleet_step(
            *_fleet_step_drain_args(seed=seed, row=row))
        jax.block_until_ready(out)
    return ds._fleet_step._cache_size() - before


def _explain_decide_args(seed: int = 0):
    """Representative explain-kernel operands: the [G] group config rows
    plus randomized per-group aggregate columns at the EXACT dtypes the
    incremental/fleet callers feed (int64 sums, int32 counts)."""
    from escalator_tpu.ops import device_state as _ds  # noqa: F401
    # ^ registers the bare GroupArrays pytree the explain kernel takes

    rng = np.random.default_rng(seed + 900)
    G = GROUPS
    g = representative_cluster(seed=seed).groups
    i64 = lambda hi: rng.integers(0, hi, G).astype(np.int64)  # noqa: E731
    i32 = lambda hi: rng.integers(0, hi, G).astype(np.int32)  # noqa: E731
    return (g, i64(10**6), i64(10**12), i64(10**7), i64(10**13),
            i32(50), i32(20), i32(20), i32(5), i32(3))


def _build_explain_decide() -> TracedEntry:
    from escalator_tpu.ops import kernel

    return TracedEntry(fn=kernel.explain_decide, args=_explain_decide_args(),
                       jitted=kernel._explain_decide_raw)


def _probe_explain_decide_retraces() -> int:
    """Two explain calls at the same shapes, different group configs and
    aggregate contents: exactly one compile — explain is content-blind."""
    from escalator_tpu.ops import kernel

    before = kernel._explain_decide_raw._cache_size()
    for seed in (91, 92):
        jax.block_until_ready(
            kernel._explain_decide_raw(*_explain_decide_args(seed=seed)))
    return kernel._explain_decide_raw._cache_size() - before


def _explain_groups_args(seed: int = 0):
    """A resident single-cluster explain fixture: group rows plus a
    maintained :class:`GroupAggregates` at the incremental decider's
    shapes ([G] columns, [N+1] per-node remainders with the scratch
    lane)."""
    from escalator_tpu.ops import kernel

    rng = np.random.default_rng(seed + 910)
    G, N = GROUPS, NODES
    cluster = representative_cluster(seed=seed)
    i64 = lambda hi, n=G: rng.integers(0, hi, n).astype(np.int64)  # noqa: E731
    aggs = kernel.GroupAggregates(
        cpu_req=i64(10**6), mem_req=i64(10**12), num_pods=i64(50),
        cpu_cap=i64(10**7), mem_cap=i64(10**13), num_nodes=i64(20),
        num_untainted=i64(20), num_tainted=i64(5), num_cordoned=i64(3),
        node_pods_remaining=i64(8, N + 1),
        dirty=np.zeros(G, bool),
    )
    return (cluster.groups, aggs)


def _build_explain_groups() -> TracedEntry:
    from escalator_tpu.ops import device_state as ds

    return TracedEntry(fn=ds._explain_terms, args=_explain_groups_args(),
                       jitted=ds._explain_groups_core)


def _probe_explain_groups_retraces() -> int:
    from escalator_tpu.ops import device_state as ds

    before = ds._explain_groups_core._cache_size()
    for seed in (93, 94):
        jax.block_until_ready(
            ds._explain_groups_core(*_explain_groups_args(seed=seed)))
    return ds._explain_groups_core._cache_size() - before


def _explain_tenant_args(seed: int = 27, row: int = 0):
    """One fleet tenant's explain gather operands: the shard-local
    ``[1, C+1, …]`` group/aggregate/committed-column blocks after one real
    fleet step (the same populated-arena recipe as the drain fixture),
    plus the traced row index."""
    from jax import tree_util

    from escalator_tpu.ops import device_state as ds

    state_out, _out = ds._fleet_step(*_fleet_step_args(seed=seed))
    _pods, _nodes, groups, aggs, prev_cols = tree_util.tree_map(
        np.asarray, state_out)
    g_blk, a_blk, c_blk = tree_util.tree_map(
        lambda a: a[None], (groups, aggs, prev_cols))
    return (g_blk, a_blk, c_blk, np.int32(row))


def _build_explain_tenant_local() -> TracedEntry:
    from escalator_tpu.ops import device_state as ds

    return TracedEntry(fn=ds._explain_tenant_core.__wrapped__,
                       args=_explain_tenant_args(),
                       jitted=ds._explain_tenant_core)


def _probe_explain_tenant_retraces() -> int:
    """Two tenants on the same arena shapes, DIFFERENT row indices: one
    compile — ``row`` is traced content, so a single program serves every
    tenant of a shard (the property fleet explain's latency rests on)."""
    from escalator_tpu.ops import device_state as ds

    before = ds._explain_tenant_core._cache_size()
    for seed, row in ((95, 0), (96, 1)):
        jax.block_until_ready(
            ds._explain_tenant_core(*_explain_tenant_args(seed=seed, row=row)))
    return ds._explain_tenant_core._cache_size() - before


def _fleet_order_tail_args(seed: int = 27, rows=(0,)):
    """Batched order-repair operands: the resident arenas after one fleet
    step (real node/aggregate content) plus the order-needing tenant row
    vector, padded to ``kernel.fleet_order_bucket`` with the scratch row —
    exactly what ``FleetEngine._batched_order_tail`` feeds the fused
    dispatch."""
    from jax import tree_util

    from escalator_tpu.ops import device_state as ds
    from escalator_tpu.ops import kernel

    C = 2
    state_out, _out = ds._fleet_step(*_fleet_step_args(seed=seed))
    _pods, nodes, groups, aggs, _cols = tree_util.tree_map(
        np.asarray, state_out)
    T2 = kernel.fleet_order_bucket(len(rows), C + 1)
    row_vec = np.full(T2, C, np.int32)
    row_vec[: len(rows)] = rows
    return (nodes, groups, aggs, row_vec)


def _fleet_order_tail_sharded_args(seed: int = 27,
                                   rows_per_shard=((0,), (1,))):
    from jax import tree_util

    parts = [_fleet_order_tail_args(seed=seed + 10 * s,
                                    rows=rows_per_shard[s])
             for s in range(_FLEET_SHARDS)]
    return tree_util.tree_map(lambda *xs: np.stack(xs), *parts)


def _build_fleet_order_tail_sharded() -> TracedEntry:
    from escalator_tpu.ops import device_state as ds

    fn = ds.make_fleet_order_tail_sharded(_fleet_shard_mesh())
    return TracedEntry(fn=fn, args=_fleet_order_tail_sharded_args(),
                       jitted=fn)


def _probe_fleet_order_tail_sharded_retraces() -> int:
    """Different order-needing rows per shard (tenant membership moves
    between micro-batches), identical T2/N buckets: one compile."""
    from escalator_tpu.ops import device_state as ds

    fn = ds.make_fleet_order_tail_sharded(_fleet_shard_mesh())
    before = fn._cache_size()
    for seed, rows in ((91, ((0,), (1,))), (92, ((1,), (0,)))):
        out = fn(*_fleet_order_tail_sharded_args(seed=seed,
                                                 rows_per_shard=rows))
        jax.block_until_ready(out)
    return fn._cache_size() - before


def _build_fleet_decide_sharded() -> TracedEntry:
    fn = _fleet_decide_sharded_fn()
    cluster = _fleet_stacked_cluster(2 * _FLEET_SHARDS)
    nows = np.full(2 * _FLEET_SHARDS, NOW, np.int64)
    return TracedEntry(fn=fn, args=(cluster, nows), jitted=fn)


_fleet_decide_sharded_cache: list = []


def _fleet_decide_sharded_fn():
    from escalator_tpu.ops import kernel

    if not _fleet_decide_sharded_cache:
        _fleet_decide_sharded_cache.append(
            kernel.make_fleet_decide_sharded(_fleet_shard_mesh()))
    return _fleet_decide_sharded_cache[0]


def _probe_fleet_decide_sharded_retraces() -> int:
    fn = _fleet_decide_sharded_fn()
    before = fn._cache_size()
    nows = np.full(2 * _FLEET_SHARDS, NOW, np.int64)
    for seed in (91, 92):
        jax.block_until_ready(fn(
            _fleet_stacked_cluster(2 * _FLEET_SHARDS, seed=seed), nows))
    return fn._cache_size() - before


def _build_mesh_decider() -> TracedEntry:
    from escalator_tpu.parallel import mesh as pmesh

    m = pmesh.make_mesh()
    cluster = stacked_cluster(int(m.devices.size))
    decider = pmesh.make_sharded_decider(m, impl="xla")
    return TracedEntry(fn=decider, args=(cluster, NOW), jitted=decider)


def _build_fleet_decider() -> TracedEntry:
    from escalator_tpu.parallel import mesh as pmesh

    m = pmesh.make_mesh()
    cluster = stacked_cluster(int(m.devices.size))
    decider = pmesh.make_fleet_decider(m)
    return TracedEntry(fn=decider, args=(cluster, NOW), jitted=decider)


def _build_mesh_sweeper() -> TracedEntry:
    from escalator_tpu.parallel import mesh as pmesh

    m = pmesh.make_mesh()
    cluster = stacked_cluster(int(m.devices.size))
    sweeper = pmesh.make_sharded_sweeper(m, num_candidates=9)
    return TracedEntry(fn=sweeper, args=(cluster,), jitted=sweeper)


def _podaxis_fixture(seed: int = 0):
    from escalator_tpu.ops import order_tail
    from escalator_tpu.parallel import mesh as pmesh, podaxis

    m = pmesh.make_mesh()
    cluster = podaxis.pad_pods_for_mesh(representative_cluster(seed=seed), m)
    blocks = order_tail.assign_order_blocks(
        np.asarray(cluster.nodes.group),
        np.asarray(cluster.nodes.valid),
        int(m.devices.size),
        num_groups=GROUPS,
    )
    return m, cluster, blocks


def _build_podaxis_blocks() -> TracedEntry:
    from escalator_tpu.parallel import podaxis

    m, cluster, blocks = _podaxis_fixture()
    decider = podaxis.make_podaxis_decider(m, impl="xla")
    fn = lambda c, t, b: decider(c, t, b)  # noqa: E731
    return TracedEntry(fn=fn, args=(cluster, NOW, blocks), jitted=decider)


def _build_podaxis_light() -> TracedEntry:
    from escalator_tpu.parallel import podaxis

    m, cluster, _ = _podaxis_fixture()
    decider = podaxis.make_podaxis_decider(m, impl="xla", with_orders=False)
    fn = lambda c, t: decider(c, t)  # noqa: E731
    return TracedEntry(fn=fn, args=(cluster, NOW), jitted=decider)


def _build_podaxis_legacy() -> TracedEntry:
    """The strict full-array-parity replicated ordered program (multichip
    dryrun's contract): every device pays the full [N] sort. Kept on purpose;
    waiver-listed for R1 rather than lint-clean (see analysis/waivers.py)."""
    from escalator_tpu.parallel import podaxis

    m, cluster, _ = _podaxis_fixture()
    decider = podaxis.make_podaxis_decider(m, impl="xla")
    fn = lambda c, t: decider(c, t)  # noqa: E731  (no node_blocks)
    return TracedEntry(fn=fn, args=(cluster, NOW), jitted=decider)


def _probe_podaxis_retraces() -> int:
    """Fresh deciders, two block-sharded ticks + two light ticks: one compile
    per decider. Block maps are padded to a fixed width, exactly as a backend
    holding a high-water mark would, so the tick-to-tick block rebalance must
    not retrace."""
    from escalator_tpu.ops import order_tail
    from escalator_tpu.parallel import podaxis

    m, _, _ = _podaxis_fixture()
    ordered = podaxis.make_podaxis_decider(m, impl="xla")
    light = podaxis.make_podaxis_decider(m, impl="xla", with_orders=False)
    compiles = 0
    for decider, with_blocks in ((ordered, True), (light, False)):
        before = decider._cache_size()
        for seed in (21, 22):
            _, cluster, blocks = _podaxis_fixture(seed=seed)
            if with_blocks:
                blocks = order_tail.pad_order_blocks(blocks, NODES)
                out = decider(cluster, NOW, blocks)
            else:
                out = decider(cluster, NOW)
            jax.block_until_ready(out)
        compiles += decider._cache_size() - before
    return compiles


def _grid_fixture():
    from escalator_tpu.parallel import grid

    m = grid.make_grid_mesh(num_group_shards=4)
    cluster = grid.pad_stacked_pods_for_grid(stacked_cluster(4, seed=5), m)
    return m, cluster


def _build_grid_decider() -> TracedEntry:
    from escalator_tpu.parallel import grid

    m, cluster = _grid_fixture()
    decider = grid.make_grid_decider(m, impl="xla")
    return TracedEntry(fn=decider, args=(cluster, NOW), jitted=decider)


def _probe_grid_retraces() -> int:
    from escalator_tpu.parallel import grid

    m, _ = _grid_fixture()
    decider = grid.make_grid_decider(m, impl="xla")
    before = decider._cache_size()
    for seed in (31, 32):
        cluster = grid.pad_stacked_pods_for_grid(stacked_cluster(4, seed=seed), m)
        jax.block_until_ready(decider(cluster, NOW))
    return decider._cache_size() - before


def _build_order_tail() -> TracedEntry:
    from escalator_tpu.ops import order_tail

    m, cluster, blocks = _podaxis_fixture()
    tail = order_tail.make_sharded_order_tail(m)
    n = cluster.nodes
    ngroup, untainted_sel, tainted_sel = order_tail.node_selection_masks(
        np.asarray(n.valid), np.asarray(n.group), np.asarray(n.tainted),
        np.asarray(n.cordoned),
    )
    victim_primary = np.zeros(NODES, np.int64)
    fn = lambda g, t, u, v, c, b: tail(g, t, u, v, c, GROUPS, b)  # noqa: E731
    jitted = jax.jit(fn)
    return TracedEntry(
        fn=fn,
        args=(ngroup, tainted_sel, untainted_sel, victim_primary,
              np.asarray(n.creation_ns), blocks),
        jitted=jitted,
    )


def _scatter_fixture():
    from escalator_tpu.ops import device_state as ds

    cluster = representative_cluster(seed=7)
    pods = ds._pad_one_lane(cluster.pods, ds._POD_PAD)
    nodes = ds._pad_one_lane(cluster.nodes, ds._NODE_PAD)
    pod_slots = np.arange(0, 24, dtype=np.int64)
    node_slots = np.arange(0, 12, dtype=np.int64)
    pidx, pvals = ds._gather_padded(
        cluster.pods, pod_slots, ds._bucket(len(pod_slots)), PODS, ds._POD_PAD
    )
    nidx, nvals = ds._gather_padded(
        cluster.nodes, node_slots, ds._bucket(len(node_slots)), NODES,
        ds._NODE_PAD,
    )
    return cluster, pods, nodes, pidx, pvals, nidx, nvals


def _build_scatter_update() -> TracedEntry:
    from escalator_tpu.ops import device_state as ds

    cluster, pods, nodes, pidx, pvals, nidx, nvals = _scatter_fixture()
    args = (pods, nodes, cluster.groups, pidx, pvals, nidx, nvals)
    return TracedEntry(fn=ds._scatter_body, args=args, jitted=ds._scatter_update)


def _build_scatter_update_packed() -> TracedEntry:
    from escalator_tpu.ops import device_state as ds

    cluster, pods, nodes, pidx, pvals, nidx, nvals = _scatter_fixture()
    pod_buf = ds._pack_delta_bytes(pidx, pvals)
    node_buf = ds._pack_delta_bytes(nidx, nvals)
    pod_dts = ds._field_dtypes(cluster.pods)
    node_dts = ds._field_dtypes(cluster.nodes)
    fn = lambda p, n, g, pb, nb: ds._scatter_update_from_packed(  # noqa: E731
        p, n, g, pb, nb, pod_dts, node_dts
    )
    return TracedEntry(
        fn=fn,
        args=(pods, nodes, cluster.groups, pod_buf, node_buf),
        jitted=ds._scatter_update_from_packed,
        lower=lambda: ds._scatter_update_from_packed.lower(
            pods, nodes, cluster.groups, pod_buf, node_buf,
            pod_dts=pod_dts, node_dts=node_dts,
        ),
    )


def _build_scatter_update_decide() -> TracedEntry:
    from escalator_tpu.ops import device_state as ds

    cluster, pods, nodes, pidx, pvals, nidx, nvals = _scatter_fixture()
    fn = lambda p, n, g, pi, pv, ni, nv, t: ds._scatter_update_decide(  # noqa: E731
        p, n, g, pi, pv, ni, nv, t
    )
    args = (pods, nodes, cluster.groups, pidx, pvals, nidx, nvals,
            jnp.int64(NOW))
    return TracedEntry(fn=fn, args=args, jitted=ds._scatter_update_decide)


def _delta_fixture(seed: int = 15, dirty_rows=(0, 2, 4)):
    """Concrete incremental-decide state: persistent aggregates + decision
    columns from a real bootstrap, plus a compacted dirty batch."""
    from escalator_tpu.ops import kernel

    cluster = representative_cluster(seed=seed)
    aggs = kernel.compute_aggregates_jit(cluster)
    light = kernel._decide_jit_raw(cluster, NOW, with_orders=False)
    prev = tuple(getattr(light, f) for f in kernel.GROUP_DECISION_FIELDS)
    mask = np.zeros(GROUPS, bool)
    mask[list(dirty_rows)] = True
    idx = kernel.dirty_indices(mask)
    return cluster, aggs, prev, idx


def _build_delta_decide() -> TracedEntry:
    from escalator_tpu.ops import kernel

    cluster, aggs, prev, idx = _delta_fixture()
    fn = lambda c, a, p, i, t: kernel._delta_decide_core(  # noqa: E731
        c.groups, c.nodes, a, p, i, t)
    return TracedEntry(fn=fn, args=(cluster, aggs, prev, idx, NOW),
                       jitted=kernel._delta_decide_raw)


def _probe_delta_decide_retraces() -> int:
    """Two ticks in the SAME dirty bucket (different rows): the dirty-row
    contents must not be a cache key — exactly one compile. (Bucket-boundary
    behavior is pinned exactly in tests/test_retrace_budget.py; the registry
    shape G=6 caps the bucket at 6, so only one bucket exists here.)"""
    import jax

    from escalator_tpu.ops import kernel

    cluster, aggs, prev, _ = _delta_fixture(seed=41, dirty_rows=(1, 2))
    before = kernel._delta_decide_raw._cache_size()
    for rows in ((1, 2), (3, 5)):
        mask = np.zeros(GROUPS, bool)
        mask[list(rows)] = True
        out, aggs = kernel._delta_decide_raw(
            cluster, aggs, prev, kernel.dirty_indices(mask), NOW)
        jax.block_until_ready(out)
        prev = tuple(getattr(out, f) for f in kernel.GROUP_DECISION_FIELDS)
    return kernel._delta_decide_raw._cache_size() - before


def _build_scatter_update_aggs() -> TracedEntry:
    from escalator_tpu.core.arrays import ClusterArrays
    from escalator_tpu.ops import device_state as ds, kernel

    cluster, pods, nodes, pidx, pvals, nidx, nvals = _scatter_fixture()
    padded = ClusterArrays(groups=cluster.groups, pods=pods, nodes=nodes)
    aggs = kernel.compute_aggregates_jit(padded)
    args = (pods, nodes, cluster.groups, cluster.groups, pidx, pvals, nidx,
            nvals, aggs)
    return TracedEntry(fn=ds._scatter_update_aggs, args=args,
                       jitted=ds._scatter_update_aggs)


def _build_podaxis_delta_scatter() -> TracedEntry:
    from escalator_tpu.ops import kernel
    from escalator_tpu.parallel import podaxis

    m, cluster, _ = _podaxis_fixture(seed=17)
    aggs = kernel.compute_aggregates_jit(cluster)
    scat = podaxis.make_delta_scatter(m)
    B = 8
    P_ = int(cluster.pods.valid.shape[0])
    N_ = int(cluster.nodes.valid.shape[0])

    def take(soa, idx, oob):
        out = {}
        for f in soa.__dataclass_fields__:
            a = np.asarray(getattr(soa, f))
            v = np.zeros(B, a.dtype)
            sel = idx < oob
            v[sel] = a[idx[sel]]
            out[f] = v
        return type(soa)(**out)

    pidx = np.full(B, P_, np.int32)
    pidx[:3] = [1, 40, 100]
    nidx = np.full(B, N_, np.int32)
    nidx[:2] = [2, 11]
    pod_old = take(cluster.pods, pidx, P_)
    node_old = take(cluster.nodes, nidx, N_)
    args = (cluster.pods, cluster.nodes, cluster.groups, cluster.groups,
            pidx, pod_old, pod_old, nidx, node_old, node_old, aggs)
    return TracedEntry(fn=scat, args=args, jitted=scat)


def _build_grid_delta_decider() -> TracedEntry:
    import jax

    from escalator_tpu.ops import kernel
    from escalator_tpu.parallel import grid

    m, cluster = _grid_fixture()
    vaggs = jax.vmap(lambda c: kernel.compute_aggregates(c))(cluster)
    vlight = jax.vmap(
        lambda c: kernel.decide(c, NOW, with_orders=False))(cluster)
    prev = tuple(
        np.asarray(getattr(vlight, f)) for f in kernel.GROUP_DECISION_FIELDS)
    Gb = int(cluster.groups.valid.shape[1])
    idx = np.stack([
        kernel.dirty_indices(np.eye(1, Gb, s % Gb, dtype=bool)[0])
        for s in range(4)
    ])
    decider = grid.make_grid_delta_decider(m)
    args = (cluster.groups, cluster.nodes, vaggs, prev, idx, NOW)
    return TracedEntry(fn=decider, args=args, jitted=decider)


def _order_state_fixture(seed: int = 19):
    """Concrete round-10 order-state columns: keys + permutation from a real
    cluster (emptiest groups populated so victim_primary is non-trivial)."""
    from escalator_tpu.ops import kernel, order_tail

    cluster = representative_cluster(seed=seed)
    aggs = kernel.compute_aggregates_jit(cluster)
    cols = (
        jnp.asarray(cluster.groups.emptiest),
        jnp.asarray(cluster.nodes.valid),
        jnp.asarray(cluster.nodes.group),
        jnp.asarray(cluster.nodes.tainted),
        jnp.asarray(cluster.nodes.cordoned),
        jnp.asarray(cluster.nodes.creation_ns),
        aggs.node_pods_remaining,
    )
    major, k1, k2 = order_tail.order_keys_jit(*cols)
    perm = order_tail.order_sort_jit(major, k1, k2)
    return order_tail, cols, major, k1, k2, perm


def _order_dirty_bucket(n_dirty: int = 3):
    from escalator_tpu.ops import kernel

    mask = np.zeros(NODES, bool)
    mask[np.arange(n_dirty) * 7 % NODES] = True
    return kernel.dirty_indices(mask)


def _build_order_repair() -> TracedEntry:
    from escalator_tpu.ops import order_tail

    _, _, major, k1, k2, perm = _order_state_fixture()
    args = (np.asarray(perm).copy(), major, k1, k2, major, k1, k2,
            _order_dirty_bucket())
    return TracedEntry(fn=order_tail.order_repair_jit, args=args,
                       jitted=order_tail.order_repair_jit)


def _order_update_args(shift: int = 0):
    from escalator_tpu.ops import order_tail  # noqa: F401 (fixture import)

    _, cols, major, k1, k2, perm = _order_state_fixture(seed=23)
    offs = np.zeros(GROUPS + 1, np.int32)
    offs[-1] = shift
    return (*cols[:3], np.asarray(cols[3]) ^ (np.arange(NODES) % 13 == shift),
            *cols[4:], np.asarray(major).copy(), np.asarray(k1).copy(),
            np.asarray(k2).copy(), np.asarray(perm).copy(), offs, 8)


def _build_order_update() -> TracedEntry:
    from escalator_tpu.ops import order_tail

    *traced, bucket = _order_update_args()
    fn = lambda *a: order_tail.order_update_jit(*a, bucket)  # noqa: E731
    return TracedEntry(
        fn=fn, args=tuple(traced), jitted=order_tail.order_update_jit,
        lower=lambda: order_tail.order_update_jit.lower(*traced, bucket))


def _probe_order_update_retraces() -> int:
    """Two fused order updates in the SAME static bucket (different taint
    flips -> different dirty lanes): the dirty CONTENTS must not be a cache
    key — exactly one compile."""
    from escalator_tpu.ops import order_tail

    before = order_tail.order_update_jit._cache_size()
    for shift in (0, 1):
        jax.block_until_ready(
            order_tail.order_update_jit(*_order_update_args(shift)))
    return order_tail.order_update_jit._cache_size() - before


def _ordered_delta_fixture(seed: int = 31, dirty_rows=(1, 4)):
    """Delta fixture + a seeded order state over the SAME cluster — the
    fused ordered-incremental tick's full persistent-state surface."""
    from escalator_tpu.ops import order_tail

    cluster, aggs, prev, idx = _delta_fixture(seed=seed,
                                              dirty_rows=dirty_rows)
    major, k1, k2 = order_tail.order_keys_jit(
        jnp.asarray(cluster.groups.emptiest),
        jnp.asarray(cluster.nodes.valid), jnp.asarray(cluster.nodes.group),
        jnp.asarray(cluster.nodes.tainted),
        jnp.asarray(cluster.nodes.cordoned),
        jnp.asarray(cluster.nodes.creation_ns), aggs.node_pods_remaining)
    perm = order_tail.order_sort_jit(major, k1, k2)
    # device-resident COPIES (as production: the state lives on device and
    # is donated every tick — np inputs here would both alias the jit
    # outputs and flip the cache key's committed-ness, a spurious retrace)
    return (cluster, aggs, prev, idx,
            *(jnp.asarray(np.asarray(a).copy())
              for a in (major, k1, k2, perm)))


def _build_ordered_delta_decide() -> TracedEntry:
    from escalator_tpu.ops import kernel

    cluster, aggs, prev, idx, major, k1, k2, perm = _ordered_delta_fixture()
    args = (cluster, aggs, prev, idx, NOW, major, k1, k2, perm)
    fn = lambda c, a, p, i, t, m, x, y, q: (  # noqa: E731
        kernel._ordered_delta_decide_raw(c, a, p, i, t, m, x, y, q, 8))
    return TracedEntry(
        fn=fn, args=args, jitted=kernel._ordered_delta_decide_raw,
        lower=lambda: kernel._ordered_delta_decide_raw.lower(*args, 8))


def _probe_ordered_delta_retraces() -> int:
    """Two fused ordered ticks in the SAME statics (dirty bucket, order
    bucket) with different dirty rows: neither the dirty-row contents nor
    the order-state values may be a cache key — exactly one compile."""
    import jax

    from escalator_tpu.ops import kernel

    cluster, aggs, prev, idx, major, k1, k2, perm = _ordered_delta_fixture(
        seed=43, dirty_rows=(1, 2))
    before = kernel._ordered_delta_decide_raw._cache_size()
    for rows in ((1, 2), (3, 5)):
        mask = np.zeros(GROUPS, bool)
        mask[list(rows)] = True
        out, aggs, ostate = kernel._ordered_delta_decide_raw(
            cluster, aggs, prev, kernel.dirty_indices(mask), NOW,
            major, k1, k2, perm, 8)
        jax.block_until_ready(out)
        major, k1, k2, perm = ostate[:4]
        prev = tuple(getattr(out, f) for f in kernel.GROUP_DECISION_FIELDS)
    return kernel._ordered_delta_decide_raw._cache_size() - before


def _build_audit_snapshot() -> TracedEntry:
    from escalator_tpu.ops import device_state as ds, kernel

    cluster = representative_cluster(seed=27)
    aggs = kernel.compute_aggregates_jit(cluster)
    return TracedEntry(fn=ds._audit_snapshot, args=(cluster, aggs),
                       jitted=ds._audit_snapshot)


def _snapshot_state_fixture(seed: int = 31):
    """A full persisted-state tuple (cluster, aggs, decision columns, order
    state) — the snapshot freeze/restore programs' representative input."""
    from escalator_tpu.ops import kernel, order_tail

    cluster = representative_cluster(seed=seed)
    aggs = kernel.compute_aggregates_jit(cluster)
    out = kernel.decide_jit(cluster, NOW)
    cols = tuple(getattr(out, f) for f in kernel.GROUP_DECISION_FIELDS)
    n = cluster.nodes
    major, k1, k2 = order_tail.order_keys_jit(
        cluster.groups.emptiest, n.valid, n.group, n.tainted, n.cordoned,
        n.creation_ns, aggs.node_pods_remaining)
    perm = order_tail.order_sort_jit(major, k1, k2)
    return (cluster, aggs, cols, (major, k1, k2, perm))


def _build_snapshot_freeze() -> TracedEntry:
    from escalator_tpu.ops import snapshot as snaplib

    state = _snapshot_state_fixture()
    return TracedEntry(fn=snaplib._freeze_state, args=(state,),
                       jitted=snaplib._freeze_state)


def _build_snapshot_restore() -> TracedEntry:
    from escalator_tpu.ops import snapshot as snaplib

    state = _snapshot_state_fixture(seed=32)
    return TracedEntry(fn=snaplib._adopt_body, args=(state,),
                       jitted=snaplib._restore_adopt)


def _probe_snapshot_restore_retraces() -> int:
    """Two restores of same-shaped (different-valued) state trees: the leaf
    VALUES are never a cache key — a standby restoring repeatedly (restarts,
    replay runs) must hit the jit cache after the first adopt."""
    import jax

    from escalator_tpu.ops import snapshot as snaplib

    before = snaplib._restore_adopt._cache_size()
    for seed in (33, 34):
        state = jax.tree_util.tree_map(
            np.asarray, _snapshot_state_fixture(seed=seed))
        jax.block_until_ready(snaplib.restore_adopt(state))
    return snaplib._restore_adopt._cache_size() - before


def _tenant_row_fixture(seed: int = 35, row: int = 0):
    """Fleet arena operands for the tenant-row migration programs: the
    shard-local ``(aggs, prev_cols)`` block a freeze gathers from, the full
    ``(pods, nodes, groups, aggs, prev_cols)`` arena tree an adopt donates,
    and one tenant's arena-shaped row values — built with the SAME service
    helpers the engine's adopt path uses (``zero_state_sharded``,
    ``_repad``), so the analyzed programs see production's exact shapes."""
    from escalator_tpu.fleet import service as fsvc
    from escalator_tpu.ops import kernel

    C, G, P, N = 2, GROUPS, 24, 12
    state = fsvc.zero_state_sharded(1, C, G, P, N)
    cluster = representative_cluster(G, P, N, seed=seed)
    aggs = kernel.compute_aggregates_jit(cluster)
    out = kernel.decide_jit(cluster, NOW)

    def pad(a, w):
        a = np.asarray(a)
        full = np.zeros(w, a.dtype)
        full[:a.shape[0]] = a
        return full

    aggs_full = type(aggs)(**{
        f.name: pad(getattr(aggs, f.name),
                    N + 1 if f.name == "node_pods_remaining" else G)
        for f in dataclasses.fields(aggs)})
    cols = tuple(np.asarray(getattr(out, f))
                 for f in kernel.GROUP_DECISION_FIELDS)
    row_values = (fsvc._repad(cluster.pods, P + 1, fsvc._empty_pods),
                  fsvc._repad(cluster.nodes, N + 1, fsvc._empty_nodes),
                  cluster.groups, aggs_full, cols)

    def set_row(arena, v):
        blk = np.array(arena)
        blk[0, row] = v
        return blk

    _, _, _, aggs_ar, cols_ar = state
    aggs_blk = type(aggs_ar)(**{
        f.name: set_row(getattr(aggs_ar, f.name), getattr(aggs_full, f.name))
        for f in dataclasses.fields(aggs_ar)})
    cols_blk = tuple(set_row(a, v) for a, v in zip(cols_ar, cols,
                                                   strict=True))
    return (aggs_blk, cols_blk), state, row_values


def _build_tenant_row_freeze() -> TracedEntry:
    from escalator_tpu.ops import snapshot as snaplib

    shard_block, _state, _row_values = _tenant_row_fixture()
    return TracedEntry(fn=snaplib._tenant_row_freeze_body,
                       args=(shard_block, np.int32(0)),
                       jitted=snaplib._tenant_row_freeze)


def _probe_tenant_row_freeze_retraces() -> int:
    """Two row freezes off the SAME arena buckets at different rows with
    different tenant contents: the row INDEX is traced data, so migrating
    any tenant off any slot must reuse one compiled gather."""
    import jax

    from escalator_tpu.ops import snapshot as snaplib

    before = snaplib._tenant_row_freeze._cache_size()
    for seed, row in ((37, 0), (38, 1)):
        shard_block, _state, _row_values = _tenant_row_fixture(
            seed=seed, row=row)
        jax.block_until_ready(snaplib.tenant_row_freeze(shard_block, row))
    return snaplib._tenant_row_freeze._cache_size() - before


def _build_tenant_row_adopt() -> TracedEntry:
    from escalator_tpu.ops import snapshot as snaplib

    _blk, state, row_values = _tenant_row_fixture(seed=36)
    return TracedEntry(
        fn=snaplib._tenant_row_adopt_body,
        args=(state, np.int32(0), np.int32(0), row_values),
        jitted=snaplib._tenant_row_adopt)


def _probe_tenant_row_adopt_retraces() -> int:
    """Two adopts into the SAME arena buckets at different slots with
    different row values (two migrations landing on different rows):
    neither the slot index nor the row contents is a cache key — exactly
    one compile."""
    import jax

    from escalator_tpu.ops import snapshot as snaplib

    before = snaplib._tenant_row_adopt._cache_size()
    for seed, row in ((39, 0), (40, 1)):
        _blk, state, row_values = _tenant_row_fixture(seed=seed, row=row)
        jax.block_until_ready(snaplib.tenant_row_adopt(
            jax.device_put(state), 0, row, row_values))
    return snaplib._tenant_row_adopt._cache_size() - before


def _build_simulate_sweep() -> TracedEntry:
    from escalator_tpu.ops import simulate

    cluster = representative_cluster(seed=9)
    fn = lambda c: simulate.sweep_deltas(c, 9)  # noqa: E731
    return TracedEntry(
        fn=fn, args=(cluster,), jitted=simulate._sweep_deltas_raw,
        # fn is the EAGER impl (traceable, but host-dispatched op by op —
        # useless for R7) and the jit's num_candidates is a static kwarg
        execute=lambda a: simulate._sweep_deltas_raw(a[0], num_candidates=9),
    )


def _build_simulate_sweep_by_type() -> TracedEntry:
    from escalator_tpu.ops import simulate

    cluster = representative_cluster(seed=9)
    type_cpu = np.array([2000, 4000, 8000], np.int64)
    type_mem = np.array([8, 16, 32], np.int64) * 10**9
    fn = lambda c, tc, tm: simulate.sweep_deltas_by_type(c, tc, tm, 9)  # noqa: E731
    return TracedEntry(
        fn=fn, args=(cluster, type_cpu, type_mem),
        jitted=simulate._sweep_deltas_by_type_raw,
        execute=lambda a: simulate._sweep_deltas_by_type_raw(
            *a, num_candidates=9),
    )


def _binpack_fixture(distinct_heavy: bool):
    from escalator_tpu.ops import binpack

    G, P, M = 3, 32, 8
    rng = np.random.default_rng(13)
    if distinct_heavy:
        pod_cpu = rng.integers(1, 4000, (G, P)).astype(np.int64)
        pod_mem = rng.integers(1, 10**9, (G, P)).astype(np.int64)
    else:
        shapes = np.array([[500, 10**8], [1000, 2 * 10**8]], np.int64)
        pick = rng.integers(0, 2, (G, P))
        pod_cpu = shapes[pick, 0]
        pod_mem = shapes[pick, 1]
    pod_valid = rng.random((G, P)) < 0.9
    bin_cpu = np.full((G, M), 4000, np.int64)
    bin_mem = np.full((G, M), 16 * 10**9, np.int64)
    bin_valid = rng.random((G, M)) < 0.9
    template_cpu = np.full(G, 4000, np.int64)
    template_mem = np.full(G, 16 * 10**9, np.int64)
    prep = binpack._host_prep(pod_cpu, pod_mem, pod_valid, template_cpu,
                              template_mem)
    return (binpack, prep, pod_valid, bin_cpu, bin_mem, bin_valid,
            template_cpu, template_mem)


def _build_binpack_runs() -> TracedEntry:
    (binpack, prep, pod_valid, bin_cpu, bin_mem, bin_valid, template_cpu,
     template_mem) = _binpack_fixture(distinct_heavy=False)
    perm, inv, s_cpu, s_mem, s_valid, runs, R = prep
    run_cpu, run_mem, run_count, run_start, run_id = runs
    fn = lambda *a: binpack._pack_runs_device(*a, new_bin_budget=4)  # noqa: E731
    args = (run_cpu, run_mem, run_count, run_start, run_id, s_valid, inv,
            pod_valid, bin_cpu, bin_mem, bin_valid, template_cpu, template_mem)
    return TracedEntry(fn=fn, args=args, jitted=binpack._pack_runs_device)


def _build_binpack_pods() -> TracedEntry:
    """The dtype-trimmed per-pod fallback: its int64->float32 carry cast is
    deliberate and exactness-guarded (binpack module docstring) — registered
    so R2 provably does NOT confuse it with a float64 parity demotion."""
    (binpack, prep, pod_valid, bin_cpu, bin_mem, bin_valid, template_cpu,
     template_mem) = _binpack_fixture(distinct_heavy=True)
    perm, inv, s_cpu, s_mem, s_valid, runs, R = prep
    fn = lambda *a: binpack._pack_pods_device(  # noqa: E731
        *a, new_bin_budget=4, trim_dtypes=True
    )
    args = (s_cpu, s_mem, s_valid, inv, pod_valid, bin_cpu, bin_mem,
            bin_valid, template_cpu, template_mem)
    return TracedEntry(fn=fn, args=args, jitted=binpack._pack_pods_device)


_PACK_TUPLE_DTYPES: Dict[str, str] = {
    "0": "int32",   # assignment
    "1": "int32",   # new_nodes_needed / used_virtual
    "2": "int32",   # unplaced
    "3": "int64",   # bins_remaining_cpu
    "4": "int64",   # bins_remaining_mem
}


def default_registry() -> List[KernelEntry]:
    """The analyzed surface: every public device entry point, with budgets.

    Collective budgets are the audited per-tick counts on a 1-D mesh (a
    hybrid dcn/ici mesh stages each logical collective once per axis; the
    analyzer pins the 1-D program, the invariant that matters being "no NEW
    collective appears"). Retrace budgets are compiles per two-tick sweep.
    """
    e = KernelEntry
    return [
        e(
            name="kernel.decide",
            module="escalator_tpu.ops.kernel",
            kind="jit",
            build=_build_kernel_decide,
            global_axes={"pods": PODS, "nodes": NODES},
            output_dtypes=DECISION_DTYPES,
            collective_budget=0,
            retrace_budget=2,  # ordered + lazy-orders light program
            retrace_probe=_probe_kernel_retraces,
            overlap_span="decide",  # plugin/server.py unfenced device span
        ),
        e(
            name="mesh.sharded_decider",
            module="escalator_tpu.parallel.mesh",
            kind="shard_map",
            build=_build_mesh_decider,
            mapped=True,
            min_devices=8,
            global_axes={
                "pods": 8 * SHARD_PODS,
                "nodes": 8 * SHARD_NODES,
            },
            output_dtypes=DECISION_DTYPES,
            collective_budget=0,  # decisions are shard-local by construction
        ),
        e(
            name="mesh.fleet_decider",
            module="escalator_tpu.parallel.mesh",
            kind="shard_map",
            build=_build_fleet_decider,
            mapped=True,
            min_devices=8,
            global_axes={
                "pods": 8 * SHARD_PODS,
                "nodes": 8 * SHARD_NODES,
            },
            output_dtypes=DECISION_DTYPES,
            output_select=lambda out: out[0],
            collective_budget=1,  # ONE stacked fleet-totals psum
        ),
        e(
            name="mesh.sharded_sweeper",
            module="escalator_tpu.parallel.mesh",
            kind="shard_map",
            build=_build_mesh_sweeper,
            mapped=True,
            min_devices=8,
            global_axes={
                "pods": 8 * SHARD_PODS,
                "nodes": 8 * SHARD_NODES,
            },
            output_dtypes=SWEEP_DTYPES,
            collective_budget=0,
        ),
        e(
            name="podaxis.decider_blocks",
            module="escalator_tpu.parallel.podaxis",
            kind="shard_map",
            build=_build_podaxis_blocks,
            mapped=True,
            min_devices=8,
            global_axes={"pods": PODS, "nodes": NODES},
            output_dtypes=DECISION_DTYPES,
            # pod-sweep psum + sharded-tail class-count psum + reassembly psum
            collective_budget=3,
            retrace_budget=2,  # one compile each: block-sharded + light
            retrace_probe=_probe_podaxis_retraces,
        ),
        e(
            name="podaxis.decider_light",
            module="escalator_tpu.parallel.podaxis",
            kind="shard_map",
            build=_build_podaxis_light,
            mapped=True,
            min_devices=8,
            global_axes={"pods": PODS, "nodes": NODES},
            output_dtypes=DECISION_DTYPES,
            collective_budget=1,  # the pod-sweep psum only
        ),
        e(
            name="podaxis.decider_legacy_replicated",
            module="escalator_tpu.parallel.podaxis",
            kind="shard_map",
            build=_build_podaxis_legacy,
            mapped=True,
            min_devices=8,
            global_axes={"pods": PODS, "nodes": NODES},
            output_dtypes=DECISION_DTYPES,
            collective_budget=1,
        ),
        e(
            name="order_tail.sharded_tail",
            module="escalator_tpu.ops.order_tail",
            kind="shard_map",
            build=_build_order_tail,
            mapped=True,
            min_devices=8,
            global_axes={"nodes": NODES},
            output_dtypes={"0": "int32", "1": "int32"},
            collective_budget=2,  # class-count psum + reassembly psum
        ),
        e(
            name="grid.decider",
            module="escalator_tpu.parallel.grid",
            kind="shard_map",
            build=_build_grid_decider,
            mapped=True,
            min_devices=8,
            global_axes={
                "pods": 4 * SHARD_PODS,
                "nodes": 4 * SHARD_NODES,
            },
            output_dtypes=DECISION_DTYPES,
            collective_budget=1,  # ONE stacked [3G+N] psum over the pod axis
            retrace_budget=1,
            retrace_probe=_probe_grid_retraces,
        ),
        e(
            name="device_state.scatter_update",
            module="escalator_tpu.ops.device_state",
            kind="jit",
            build=_build_scatter_update,
            collective_budget=0,
            donate_expected=True,   # donate_argnums=(0, 1): resident pods/nodes
        ),
        e(
            name="device_state.scatter_update_packed",
            module="escalator_tpu.ops.device_state",
            kind="jit",
            build=_build_scatter_update_packed,
            collective_budget=0,
            donate_expected=True,
        ),
        e(
            name="device_state.scatter_update_decide",
            module="escalator_tpu.ops.device_state",
            kind="jit",
            build=_build_scatter_update_decide,
            global_axes={"pods": PODS, "nodes": NODES},
            output_dtypes=DECISION_DTYPES,
            output_select=lambda out: out[1],
            collective_budget=0,
            donate_expected=True,
        ),
        e(
            name="kernel.fleet_decide",
            module="escalator_tpu.ops.kernel",
            kind="jit",
            build=_build_fleet_decide,
            global_axes={"pods": PODS, "nodes": NODES},
            output_dtypes=DECISION_DTYPES,
            collective_budget=0,   # tenants are independent by construction
            retrace_budget=1,      # batch content is never a cache key
            retrace_probe=_probe_fleet_decide_retraces,
        ),
        e(
            name="device_state.fleet_step",
            module="escalator_tpu.ops.device_state",
            kind="jit",
            build=_build_fleet_step,
            global_axes={"pods": 24, "nodes": 12},
            output_dtypes=DECISION_DTYPES,
            output_select=lambda out: out[1],
            collective_budget=0,
            donate_expected=True,  # R5: the five fleet arenas replace in place
            retrace_budget=1,      # tenant add/remove moves row indices only
            retrace_probe=_probe_fleet_step_retraces,
        ),
        e(
            name="kernel.fleet_decide_sharded",
            module="escalator_tpu.ops.kernel",
            kind="shard_map",
            build=_build_fleet_decide_sharded,
            mapped=True,
            min_devices=_FLEET_SHARDS,
            global_axes={"pods": PODS, "nodes": NODES},
            output_dtypes=DECISION_DTYPES,
            collective_budget=0,   # tenants are shard-local by construction
            retrace_budget=1,
            retrace_probe=_probe_fleet_decide_sharded_retraces,
        ),
        e(
            name="device_state.fleet_step_sharded",
            module="escalator_tpu.ops.device_state",
            kind="shard_map",
            build=_build_fleet_step_sharded,
            mapped=True,
            min_devices=_FLEET_SHARDS,
            global_axes={"pods": 24, "nodes": 12},
            output_dtypes=DECISION_DTYPES,
            output_select=lambda out: out[1],
            collective_budget=0,   # per-shard bodies: zero cross-shard flow
            donate_expected=True,  # R5: donation survives the shard_map wrap
            retrace_budget=1,      # shard/row moves are content, not shape
            retrace_probe=_probe_fleet_step_sharded_retraces,
        ),
        e(
            name="device_state.fleet_step_drain",
            module="escalator_tpu.ops.device_state",
            kind="jit",
            build=_build_fleet_step_drain,
            global_axes={"pods": 24, "nodes": 12},
            output_dtypes=DECISION_DTYPES,
            output_select=lambda out: out[1],
            collective_budget=0,   # tenant drains are row-local scatters
            donate_expected=True,  # R5: same arenas as fleet_step
            retrace_budget=1,      # dirty slots are content, not shape
            retrace_probe=_probe_fleet_step_drain_retraces,
        ),
        e(
            name="device_state.fleet_order_tail_sharded",
            module="escalator_tpu.ops.device_state",
            kind="shard_map",
            build=_build_fleet_order_tail_sharded,
            mapped=True,
            min_devices=_FLEET_SHARDS,
            global_axes={"nodes": 12},
            output_dtypes={"0": "int32", "1": "int32"},
            collective_budget=0,    # per-shard vmap over resident rows
            donate_expected=False,  # read-only: arenas stay resident
            retrace_budget=1,       # row membership is content, not shape
            retrace_probe=_probe_fleet_order_tail_sharded_retraces,
        ),
        e(
            name="kernel.explain_decide",
            module="escalator_tpu.ops.kernel",
            kind="jit",
            build=_build_explain_decide,
            output_dtypes=EXPLAIN_DTYPES,
            collective_budget=0,    # [G] math only: no pod/node sweeps
            donate_expected=False,  # read-only: explaining a decision must
                                    # never invalidate the state behind it
            retrace_budget=1,       # group/aggregate CONTENT is never a key
            retrace_probe=_probe_explain_decide_retraces,
        ),
        e(
            name="device_state.explain_groups",
            module="escalator_tpu.ops.device_state",
            kind="jit",
            build=_build_explain_groups,
            output_dtypes=EXPLAIN_DTYPES,
            collective_budget=0,
            donate_expected=False,  # read-only: aggregates stay resident
            retrace_budget=1,
            retrace_probe=_probe_explain_groups_retraces,
        ),
        e(
            name="device_state.explain_tenant_local",
            module="escalator_tpu.ops.device_state",
            kind="jit",
            build=_build_explain_tenant_local,
            output_dtypes=EXPLAIN_DTYPES,
            output_select=lambda out: out[0],  # the term dict; the gathered
                                               # committed columns ride along
            collective_budget=0,    # a [0, row] slice of the LOCAL block:
                                    # no cross-device program by design
            donate_expected=False,  # read-only: arenas stay resident
            retrace_budget=1,       # row index is traced content, not shape
            retrace_probe=_probe_explain_tenant_retraces,
        ),
        e(
            name="kernel.delta_decide",
            module="escalator_tpu.ops.kernel",
            kind="jit",
            build=_build_delta_decide,
            global_axes={"pods": PODS, "nodes": NODES},
            output_dtypes=DECISION_DTYPES,
            output_select=lambda out: out[0],
            collective_budget=0,   # the lazy incremental path: zero psums
            donate_expected=True,  # persistent aggregates + decision columns
            retrace_budget=1,      # dirty CONTENTS are not a cache key
            retrace_probe=_probe_delta_decide_retraces,
            overlap_span="delta_decide",  # ops/device_state.py:1250
        ),
        e(
            name="device_state.scatter_update_aggs",
            module="escalator_tpu.ops.device_state",
            kind="jit",
            build=_build_scatter_update_aggs,
            output_dtypes=AGGREGATE_DTYPES,
            output_select=lambda out: out[1],
            collective_budget=0,
            donate_expected=True,  # resident pods/nodes + aggregate columns
        ),
        e(
            name="podaxis.delta_scatter",
            module="escalator_tpu.parallel.podaxis",
            kind="shard_map",
            build=_build_podaxis_delta_scatter,
            mapped=True,
            min_devices=8,
            global_axes={"pods": PODS, "nodes": NODES},
            output_dtypes=AGGREGATE_DTYPES,
            output_select=lambda out: out[1],
            collective_budget=0,   # replicated delta batch: no collectives
            donate_expected=True,
        ),
        e(
            name="grid.delta_decider",
            module="escalator_tpu.parallel.grid",
            kind="shard_map",
            build=_build_grid_delta_decider,
            mapped=True,
            min_devices=8,
            global_axes={
                "pods": 4 * SHARD_PODS,
                "nodes": 4 * SHARD_NODES,
            },
            output_dtypes=DECISION_DTYPES,
            output_select=lambda out: out[0],
            collective_budget=0,   # per-block math, dirty masks per shard
            donate_expected=True,
        ),
        e(
            name="order_tail.order_repair",
            module="escalator_tpu.ops.order_tail",
            kind="jit",
            build=_build_order_repair,
            global_axes={"nodes": NODES},
            output_dtypes={"out": "int32"},  # a single leaf: the permutation
            collective_budget=0,   # rank merge: searches + gathers, no psum
            donate_expected=True,  # the replaced permutation
        ),
        e(
            name="order_tail.order_update",
            module="escalator_tpu.ops.order_tail",
            kind="jit",
            build=_build_order_update,
            global_axes={"nodes": NODES},
            output_dtypes={"0": "int64", "1": "int64", "2": "int64",
                           "3": "int32", "4": "int32", "5": "int32"},
            collective_budget=0,   # keys + diff + compaction + merge + roll
            donate_expected=True,  # old key columns + replaced permutation
            retrace_budget=1,      # dirty-lane CONTENTS are not a cache key
            retrace_probe=_probe_order_update_retraces,
            overlap_span="order_repair",  # ops/device_state.py:1372
        ),
        e(
            name="kernel.ordered_delta_decide",
            module="escalator_tpu.ops.kernel",
            kind="jit",
            build=_build_ordered_delta_decide,
            global_axes={"pods": PODS, "nodes": NODES},
            output_dtypes=DECISION_DTYPES,
            output_select=lambda out: out[0],
            collective_budget=0,   # delta math + rank merge: zero psums
            donate_expected=True,  # aggs + decision columns + order state
            retrace_budget=1,      # dirty/order CONTENTS are not cache keys
            retrace_probe=_probe_ordered_delta_retraces,
            overlap_span="decide_ordered_incremental",  # device_state.py:1328
        ),
        e(
            name="device_state.audit_snapshot",
            module="escalator_tpu.ops.device_state",
            kind="jit",
            build=_build_audit_snapshot,
            output_dtypes=AGGREGATE_DTYPES,
            output_select=lambda out: out[1],
            collective_budget=0,
            # donation deliberately ABSENT (donate_expected=False): aliasing
            # an input here would let a later tick's scatter corrupt the
            # frozen double buffer the background audit reads
        ),
        e(
            name="snapshot.freeze",
            module="escalator_tpu.ops.snapshot",
            kind="jit",
            build=_build_snapshot_freeze,
            output_dtypes=AGGREGATE_DTYPES,
            output_select=lambda out: out[1],
            collective_budget=0,
            # donation deliberately ABSENT (donate_expected=False): the
            # freeze copies persisted state OUT of the live buffers, which
            # must stay valid for the ticks that keep mutating them — the
            # same contract as device_state.audit_snapshot
        ),
        e(
            name="snapshot.restore_adopt",
            module="escalator_tpu.ops.snapshot",
            kind="jit",
            build=_build_snapshot_restore,
            output_dtypes=AGGREGATE_DTYPES,
            output_select=lambda out: out[1],
            collective_budget=0,
            donate_expected=True,  # the uploaded staging buffers BECOME the
                                   # resident state: zero-copy adoption
            retrace_budget=1,      # restored VALUES are never a cache key
            retrace_probe=_probe_snapshot_restore_retraces,
        ),
        e(
            name="snapshot.tenant_row_freeze",
            module="escalator_tpu.ops.snapshot",
            kind="jit",
            build=_build_tenant_row_freeze,
            output_dtypes=AGGREGATE_DTYPES,
            output_select=lambda out: out[0],
            collective_budget=0,
            # donation deliberately ABSENT (donate_expected=False): the row
            # gather copies ONE tenant out of the live arenas, which keep
            # mutating under subsequent micro-batches while the row blob is
            # serialized — the same liveness contract as snapshot.freeze
            retrace_budget=1,      # the row INDEX is data, never a cache key
            retrace_probe=_probe_tenant_row_freeze_retraces,
        ),
        e(
            name="snapshot.tenant_row_adopt",
            module="escalator_tpu.ops.snapshot",
            kind="jit",
            build=_build_tenant_row_adopt,
            output_dtypes=AGGREGATE_DTYPES,
            output_select=lambda out: out[3],
            collective_budget=0,
            donate_expected=True,  # the arena tree is donated: the adopt
                                   # lowers to in-place dynamic-update-slices
                                   # — one H2D row upload, zero arena copies
            retrace_budget=1,      # slot index + row values: never cache keys
            retrace_probe=_probe_tenant_row_adopt_retraces,
        ),
        e(
            name="simulate.sweep_deltas",
            module="escalator_tpu.ops.simulate",
            kind="jit",
            build=_build_simulate_sweep,
            global_axes={"pods": PODS, "nodes": NODES},
            output_dtypes=SWEEP_DTYPES,
            collective_budget=0,
        ),
        e(
            name="simulate.sweep_deltas_by_type",
            module="escalator_tpu.ops.simulate",
            kind="jit",
            build=_build_simulate_sweep_by_type,
            global_axes={"pods": PODS, "nodes": NODES},
            output_dtypes={
                "0": "float64", "1": "float64", "2": "bool", "3": "int32",
            },
            collective_budget=0,
        ),
        e(
            name="binpack.pack_runs",
            module="escalator_tpu.ops.binpack",
            kind="jit",
            build=_build_binpack_runs,
            output_dtypes=_PACK_TUPLE_DTYPES,
            collective_budget=0,
        ),
        e(
            name="binpack.pack_pods_trimmed",
            module="escalator_tpu.ops.binpack",
            kind="jit",
            build=_build_binpack_pods,
            output_dtypes=_PACK_TUPLE_DTYPES,
            collective_budget=0,
        ),
    ]


def shape_tree_items(tree: Any, prefix: str = "") -> List[Tuple[str, Any]]:
    """Flatten an ``eval_shape`` result into (name, ShapeDtypeStruct) pairs:
    dataclass outputs name leaves by field, tuples by position — the names
    the dtype contracts in this registry use."""
    if dataclasses.is_dataclass(tree) and not isinstance(tree, type):
        out: List[Tuple[str, Any]] = []
        for f in dataclasses.fields(tree):
            sub = getattr(tree, f.name)
            sub_prefix = f"{prefix}.{f.name}" if prefix else f.name
            out.extend(shape_tree_items(sub, sub_prefix))
        return out
    if isinstance(tree, (tuple, list)):
        out = []
        for i, sub in enumerate(tree):
            sub_prefix = f"{prefix}.{i}" if prefix else str(i)
            out.extend(shape_tree_items(sub, sub_prefix))
        return out
    if isinstance(tree, dict):
        out = []
        for key in sorted(tree):
            sub_prefix = f"{prefix}.{key}" if prefix else str(key)
            out.extend(shape_tree_items(tree[key], sub_prefix))
        return out
    return [(prefix or "out", tree)]
