"""The concurrency contract registry: every lock, rank, thread and guarded
attribute on the host path, DECLARED — the single source both checkers read.

jaxlint made the device-side invariants machine-checked; the host-side
concurrency contracts (the PR-11 ``exec -> host(condition) -> device`` lock
order, the unlocked epoch read, the observability leaf locks) lived only in
comments and CHANGES.md war stories until this module. It is imported by

- ``analysis/threadlint.py`` — the static AST pass (rules T1-T4), and
- ``analysis/lockwitness.py`` — the runtime ranked-lock witness
  (``ESCALATOR_TPU_LOCK_WITNESS=1``),

and by every covered production module, whose locks are constructed through
:mod:`escalator_tpu.analysis.lockwitness` so construction itself names the
contract (rule T4 flags any bare ``threading.Lock()`` left behind).

This module must stay stdlib-only: the fleet engine imports it (via
lockwitness) at construction time, and a jax import here would defeat the
analysis CLI's pin-before-import dance AND put jax on the plugin server's
golden-only path.

Ranks
-----
Ranks ascend in acquisition order: a thread may only acquire a lock whose
rank is STRICTLY greater than every lock it already holds. The documented
FleetEngine order ``_exec_lock -> _host -> _device_lock`` (fleet/service.py
module docstring) becomes 20 -> 30 -> 40; the scheduler condition sits below
(rank 10: ``_reject`` emits a journal event while holding it, so the
journal — like every observability lock — ranks above the whole fleet
path); the observability locks are leaves that never nest with each other
(verified by threadlint T1 on every run).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

__all__ = [
    "LockContract",
    "ThreadContract",
    "CONTRACTS",
    "CONTRACTS_BY_NAME",
    "COVERED_MODULES",
    "THREADS",
    "ASSUME_HELD",
    "GRPC_RECEIVERS",
    "EXTERNAL_RECEIVERS",
    "resolve_lock",
]


@dataclass(frozen=True)
class LockContract:
    """One named lock/condition and its place in the global order.

    ``holder`` locates the attribute the contract binds to:
    ``"ClassName._attr"`` for instance locks, ``"_name"`` for module
    globals — always within ``module`` (repo-relative path).  ``guarded``
    lists instance attributes that may only be WRITTEN while this lock is
    held (rule T3); construction in ``__init__`` is exempt (no other thread
    can hold a reference yet).
    """

    name: str
    rank: int
    module: str
    holder: str
    kind: str                      # "lock" | "rlock" | "condition"
    doc: str
    guarded: Tuple[str, ...] = ()


@dataclass(frozen=True)
class ThreadContract:
    """One declared worker thread (rule T4 flags undeclared/unnamed ones).

    ``name_pattern`` is an fnmatch pattern over the ``name=`` passed to
    ``threading.Thread`` at the spawn site in ``module``.
    """

    name_pattern: str
    module: str
    doc: str


#: Repo-relative paths threadlint analyzes (the host-side concurrency
#: surface; k8s/, native/ and the controller keep their own single-threaded
#: or RLock-trivial disciplines and stay out of scope — see
#: docs/static-analysis.md).
COVERED_MODULES: Tuple[str, ...] = (
    "escalator_tpu/fleet/scheduler.py",
    "escalator_tpu/fleet/service.py",
    "escalator_tpu/fleet/router.py",
    "escalator_tpu/plugin/server.py",
    "escalator_tpu/plugin/client.py",
    "escalator_tpu/ops/snapshot.py",
    "escalator_tpu/chaos.py",
    "escalator_tpu/observability/flightrecorder.py",
    "escalator_tpu/observability/tail.py",
    "escalator_tpu/observability/histograms.py",
    "escalator_tpu/observability/journal.py",
    "escalator_tpu/observability/jaxmon.py",
    "escalator_tpu/observability/replay.py",
    "escalator_tpu/observability/resources.py",
    "escalator_tpu/observability/provenance.py",
)


CONTRACTS: List[LockContract] = [
    # -- the fleet path (the PR-11 deadlock class lives here) ---------------
    LockContract(
        name="scheduler.cv", rank=10,
        module="escalator_tpu/fleet/scheduler.py",
        holder="FleetScheduler._cv", kind="condition",
        doc="admission/batching condition: queues, inflight, staged slot, "
            "SLO windows. Ranks BELOW the engine locks and the journal: "
            "_reject emits a journal event while holding it, and the "
            "dispatch thread never calls the engine under it.",
        guarded=(
            "_queues", "_inflight", "_paused", "_closed", "_staged_slot",
            "_dispatch_windows", "_dispatch_busy_since", "_queued_classes",
            "admitted_total", "rejected_total", "deferred_total",
            "class_breaches", "_class_served", "_slo_windows",
            "_slo_burn_counts", "_slo_fast_streak", "_slo_escalated",
            "_cache_hit_ema",
        ),
    ),
    LockContract(
        name="router.state", rank=12,
        module="escalator_tpu/fleet/router.py",
        holder="PartitionRouter._lock", kind="lock",
        doc="the partition router's one lock: hash ring, override map, "
            "session registry, traffic counters, per-partition breaker "
            "state, journal cursors, migration holds. Pure container work "
            "only — NO gRPC round-trip ever runs under it (rule T2): every "
            "RPC helper snapshots what it needs, releases, calls, then "
            "reacquires to commit. Sits between scheduler.cv and the "
            "engine locks: a routed client may run in the same process as "
            "a partition (embedded tests), and the router never calls "
            "into scheduler/engine while holding it.",
        guarded=("_ring", "_overrides", "_sessions", "_known", "_traffic",
                 "_cursors", "_migrating", "_partitions"),
    ),
    LockContract(
        name="engine.exec", rank=20,
        module="escalator_tpu/fleet/service.py",
        holder="FleetEngine._exec_lock", kind="lock",
        doc="serializes execute/compact (fleet/service.py docstring: "
            "exec -> host -> device).",
    ),
    LockContract(
        name="engine.host", rank=30,
        module="escalator_tpu/fleet/service.py",
        holder="FleetEngine._host", kind="condition",
        doc="twins/slots/staged batch + the drain condition; grow/compact "
            "wait on it, execute's epoch check deliberately does NOT take "
            "it (the documented unlocked read, waived at site).",
        guarded=("_staged", "_epoch"),
    ),
    LockContract(
        name="engine.device", rank=40,
        module="escalator_tpu/fleet/service.py",
        holder="FleetEngine._device_lock", kind="lock",
        doc="the resident arena swap (self._state donation window).",
        guarded=("_state",),
    ),
    # -- the serving shell --------------------------------------------------
    LockContract(
        name="server.stats", rank=50,
        module="escalator_tpu/plugin/server.py",
        holder="_ComputeService._stats_lock", kind="lock",
        doc="served-tick counters on the gRPC worker pool; leaf.",
        guarded=("_last_decide_unix", "_ticks_served"),
    ),
    # -- observability leaves (never nest with each other; each protects one
    #    ring/dict and calls nothing lock-taking while held) ----------------
    LockContract(
        name="recorder.ring", rank=60,
        module="escalator_tpu/observability/flightrecorder.py",
        holder="FlightRecorder._lock", kind="lock",
        doc="the flight-recorder deque; record_timeline releases before "
            "the root-complete fan-out runs.",
    ),
    LockContract(
        name="tail.watchdog", rank=62,
        module="escalator_tpu/observability/tail.py",
        holder="TailWatchdog._lock", kind="lock",
        doc="tail-breach rate-limit claims + worker handoff; the journal "
            "event and the profiler arm run OUTSIDE it.",
        guarded=("_last_dump_mono", "_worker"),
    ),
    LockContract(
        name="histograms.set", rank=64,
        module="escalator_tpu/observability/histograms.py",
        holder="HistogramSet._lock", kind="lock",
        doc="the series dict; observe() releases it before recording into "
            "the series lock (no nesting, sequential).",
    ),
    LockContract(
        name="histograms.series", rank=66,
        module="escalator_tpu/observability/histograms.py",
        holder="LogHistogram._lock", kind="lock",
        doc="one log-bucket series; pure counter math under it.",
    ),
    LockContract(
        name="journal.ring", rank=68,
        module="escalator_tpu/observability/journal.py",
        holder="OpsJournal._lock", kind="lock",
        doc="the ops-event ring. Ranks above scheduler.cv because _reject "
            "journals while holding the cv.",
    ),
    LockContract(
        name="jaxmon.state", rank=70,
        module="escalator_tpu/observability/jaxmon.py",
        holder="_lock", kind="lock",
        doc="compile/transfer counters + the compile ring (module global).",
    ),
    LockContract(
        name="replay.ring", rank=72,
        module="escalator_tpu/observability/replay.py",
        holder="TickInputLog._lock", kind="lock",
        doc="the tick-input replay ring.",
    ),
    LockContract(
        name="resources.caps", rank=74,
        module="escalator_tpu/observability/resources.py",
        holder="_caps_lock", kind="lock",
        doc="the probed-capabilities memo (module global).",
    ),
    LockContract(
        name="resources.memwatch", rank=76,
        module="escalator_tpu/observability/resources.py",
        holder="MemoryWatchdog._lock", kind="lock",
        doc="growth-window samples + dump rate limit; the registry sample "
            "and the journal event run OUTSIDE it.",
        guarded=("_last_dump_mono", "_worker"),
    ),
    LockContract(
        name="resources.registry", rank=78,
        module="escalator_tpu/observability/resources.py",
        holder="ResourceRegistry._lock", kind="lock",
        doc="registered-buffer weakref table; metadata walks only.",
    ),
    LockContract(
        name="resources.profiler", rank=80,
        module="escalator_tpu/observability/resources.py",
        holder="ProfileCapture._lock", kind="lock",
        doc="profiler-capture state machine; stop runs on its own worker.",
    ),
    LockContract(
        name="provenance.history", rank=82,
        module="escalator_tpu/observability/provenance.py",
        holder="DecisionHistory._lock", kind="lock",
        doc="the per-key decision-history rings (LRU dict of deques); "
            "push/history/keys do pure container work under it.",
        guarded=("_rings", "_seq"),
    ),
    LockContract(
        name="provenance.flaps", rank=84,
        module="escalator_tpu/observability/provenance.py",
        holder="FlapWatchdog._lock", kind="lock",
        doc="flap debounce/rate-limit claims + worker handoff; the journal "
            "event, metrics and the dump run OUTSIDE it (same shape as "
            "tail.watchdog).",
        guarded=("_last_dump_mono", "_last_flap", "_worker", "_totals",
                 "flaps", "dumps"),
    ),
    LockContract(
        name="provenance.mismatch", rank=86,
        module="escalator_tpu/observability/provenance.py",
        holder="_mismatch_lock", kind="lock",
        doc="explain-mismatch totals + dump rate limit (module global); "
            "list-cell mutations only, nothing lock-taking under it.",
    ),
    LockContract(
        name="provenance.explainers", rank=88,
        module="escalator_tpu/observability/provenance.py",
        holder="_explainers_lock", kind="lock",
        doc="the live-explainer weakref table; resolution copies under it "
            "and calls the provider after release.",
    ),
    LockContract(
        name="chaos.rules", rank=90,
        module="escalator_tpu/chaos.py",
        holder="ChaosMonkey._lock", kind="lock",
        doc="armed fault sites; hooks fire from tick/gRPC/audit threads "
            "alike, possibly while holding any production lock — highest "
            "rank so should_fire can be called from anywhere.",
    ),
]

CONTRACTS_BY_NAME: Dict[str, LockContract] = {c.name: c for c in CONTRACTS}

_BY_SITE: Dict[Tuple[str, str], LockContract] = {
    (c.module, c.holder): c for c in CONTRACTS
}

if len(CONTRACTS_BY_NAME) != len(CONTRACTS):
    raise RuntimeError("duplicate lock contract names")
if len({c.rank for c in CONTRACTS}) != len(CONTRACTS):
    raise RuntimeError("duplicate lock contract ranks")


#: Declared worker threads in the covered modules. Rule T4 requires every
#: ``threading.Thread(...)`` spawn in a covered module to carry a ``name=``
#: matching one of these patterns — an anonymous thread is an undeclared
#: concurrency surface exactly like an unranked lock.
THREADS: List[ThreadContract] = [
    ThreadContract("escalator-tpu-fleet-prep",
                   "escalator_tpu/fleet/scheduler.py",
                   "pipelined prep stage: stages batch N+1 while N runs"),
    ThreadContract("escalator-tpu-fleet-dispatch",
                   "escalator_tpu/fleet/scheduler.py",
                   "pipelined dispatch stage: executes staged batches"),
    ThreadContract("escalator-tpu-fleet",
                   "escalator_tpu/fleet/scheduler.py",
                   "single-stage batcher loop (pipelining off)"),
    ThreadContract("escalator-slo-profile",
                   "escalator_tpu/fleet/scheduler.py",
                   "one-shot SLO-escalation profiler arm"),
    ThreadContract("escalator-router-rebalance",
                   "escalator_tpu/fleet/router.py",
                   "SLO-burn rebalancer loop (daemon, migrates hot tenants "
                   "off burning partitions)"),
    ThreadContract("escalator-tail-dump",
                   "escalator_tpu/observability/tail.py",
                   "tail-breach dump serializer (daemon, off the tick)"),
    ThreadContract("escalator-flap-dump",
                   "escalator_tpu/observability/provenance.py",
                   "group-flap dump serializer (daemon, off the tick)"),
    ThreadContract("escalator-memory-dump",
                   "escalator_tpu/observability/resources.py",
                   "memory-breach dump serializer (daemon, off the tick)"),
    ThreadContract("escalator-profile-stop",
                   "escalator_tpu/observability/resources.py",
                   "profiler stop worker (jax.profiler.stop_trace blocks)"),
]


#: Functions whose CALLERS own a declared lock for them: the body is
#: analyzed as if the named locks were held (rules T1/T3 context). This is
#: a contract statement, not a waiver — the witness enforces it at runtime
#: and a new unlocked caller shows up as a T3 finding on the callee's
#: writes. Keys are ``(module, qualname)``.
ASSUME_HELD: Dict[Tuple[str, str], Tuple[str, ...]] = {
    # _dispatch holds engine.device when it swaps self._state; _init_state
    # is called from inside that with-block (and from __init__/rebuild,
    # both under the same lock).
    ("escalator_tpu/fleet/service.py", "FleetEngine._init_state"):
        ("engine.device",),
    # the prep path: prepare_batch opens `with obs.span("fleet_prep"),
    # self._host:` and everything it calls — tenant registration, bucket
    # growth, the staged-batch drain wait — runs under that condition.
    ("escalator_tpu/fleet/service.py", "FleetEngine._grow"):
        ("engine.host",),
    ("escalator_tpu/fleet/service.py", "FleetEngine._register"):
        ("engine.host",),
    ("escalator_tpu/fleet/service.py", "FleetEngine._ensure_buckets"):
        ("engine.host",),
    ("escalator_tpu/fleet/service.py", "FleetEngine._await_staged_drain"):
        ("engine.host",),
    # compact's drain-then-lock loop calls this only from inside
    # `with self._exec_lock, self._host:` (fleet/service.py compact()).
    ("escalator_tpu/fleet/service.py", "FleetEngine._compact_locked"):
        ("engine.exec", "engine.host"),
    # admission helpers: submit() holds the cv around every _reject and the
    # batcher loops hold it around _take_batch (the journal event inside
    # _reject is why journal.ring ranks above scheduler.cv).
    ("escalator_tpu/fleet/scheduler.py", "FleetScheduler._reject"):
        ("scheduler.cv",),
    ("escalator_tpu/fleet/scheduler.py", "FleetScheduler._take_batch"):
        ("scheduler.cv",),
}


#: Attribute-chain tails that mark a call as a gRPC round-trip (rule T2:
#: never inside a lock body — a stuck peer would turn a lock hold into a
#: cluster-wide stall). ``client`` covers the router path (round 20):
#: ``part.client.<rpc>`` / ``self.client.<rpc>`` are ComputeClient
#: round-trips, so any such call under ``router.state`` — or any other
#: contract lock — is a T2 finding.
GRPC_RECEIVERS: Tuple[str, ...] = ("_stub", "stub", "_channel", "client")


#: Cross-module singleton receivers the T1 call graph resolves: a call
#: ``RECV.method(...)`` (any attribute path ending in RECV) binds to
#: ``(module, class)`` so lock acquisitions inside the callee are charged
#: to the calling context.
EXTERNAL_RECEIVERS: Dict[str, Tuple[str, str]] = {
    "JOURNAL": ("escalator_tpu/observability/journal.py", "OpsJournal"),
    "RECORDER": ("escalator_tpu/observability/flightrecorder.py",
                 "FlightRecorder"),
    "WATCHDOG": ("escalator_tpu/observability/tail.py", "TailWatchdog"),
    "PHASES": ("escalator_tpu/observability/histograms.py", "HistogramSet"),
    "TICKS": ("escalator_tpu/observability/histograms.py", "HistogramSet"),
    "RESOURCES": ("escalator_tpu/observability/resources.py",
                  "ResourceRegistry"),
    "MEMORY_WATCHDOG": ("escalator_tpu/observability/resources.py",
                        "MemoryWatchdog"),
    "PROFILER": ("escalator_tpu/observability/resources.py",
                 "ProfileCapture"),
    "MONKEY": ("escalator_tpu/chaos.py", "ChaosMonkey"),
    "INPUT_LOG": ("escalator_tpu/observability/replay.py", "TickInputLog"),
}


def resolve_lock(module: str, scope_class: Optional[str],
                 attr_expr: str) -> Optional[LockContract]:
    """Map a lock expression at an AST site to its contract.

    ``attr_expr`` is either ``self.X`` (resolved against ``scope_class`` in
    ``module``) or a bare module-global name. Returns None for expressions
    no contract covers (threadlint treats acquiring an unknown lock inside
    a covered module as a T4 finding at the construction site, not here).
    """
    if attr_expr.startswith("self.") and scope_class:
        return _BY_SITE.get((module, f"{scope_class}.{attr_expr[5:]}"))
    return _BY_SITE.get((module, attr_expr))
