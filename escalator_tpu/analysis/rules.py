"""jaxlint rule engine: six jaxpr/HLO-level invariants, each a regression
class this repo has already paid for once (or documented only in comments).

R1  replicated-heavy-op   — a ``sort``/``argsort``/``scan`` spanning a full
                            global pod/node axis inside a multi-device
                            program: the PR-1 busy-tail bug class (a full
                            ``[N]`` ordering sort replicated on every device
                            of the pod-axis mesh, 0.23x scaling).
R2  dtype-parity contract — parity-critical float64/int64 outputs declared
                            in ``core/`` must stay those dtypes end to end;
                            no f64->f32/f16/bf16 demotion anywhere in the
                            traced program; x64 must be on at trace time.
R3  collective hygiene    — every collective names bound mesh axes only, and
                            each entry's collective count stays within its
                            pinned budget (a NEW collective on the hot path
                            fails loudly instead of shipping).
R4  host-sync hazard      — no ``io_callback``/``pure_callback``/debug
                            callbacks inside decider programs (a host
                            round-trip per tick would dwarf the kernel).
R5  donation verification — every ``donate_argnums`` site actually lowers
                            with buffer aliasing (``ops/device_state.py``'s
                            O(changes) resident-update path silently becomes
                            O(cluster) HBM traffic if a refactor drops it).
R6  retrace budget        — each registered entry compiles at most its
                            pinned number of times across a two-tick
                            representative sweep (catches static-argnum /
                            weak-type churn that melts the jit cache).
R7  transfer hygiene      — every entry executes fully device-resident under
                            ``jax.transfer_guard("disallow")``: a stray host
                            scalar fed back into a jit (a debug ``float(x)``
                            that survives review) becomes an implicit
                            host->device transfer per tick, flagged here
                            instead of shipping. Per-entry escapes via
                            ``KernelEntry.transfer_allow``. Execution costs a
                            compile per entry, so R7 runs only
                            ``with_execute=True`` (the CLI gate / CI); the
                            tier-1 clean-tree test stays trace-only.
R8  host-sync-in-span     — entries declaring an ``overlap_span`` (they run
                            under a fenced=False device span, i.e. the host
                            path counts on async dispatch overlap) must lower
                            to a program with no forced host sync — infeed/
                            outfeed/host callbacks there would silently
                            serialize the overlap the span accounting
                            advertises.

Findings carry the nesting path from the walker, so "where is this sort"
is answered in the report, not by re-deriving the trace.
"""

from __future__ import annotations

import fnmatch
from dataclasses import dataclass, field
from typing import Any, Dict, List, Mapping, Optional, Sequence

from escalator_tpu.analysis.registry import (
    KernelEntry,
    TracedEntry,
    shape_tree_items,
)
from escalator_tpu.analysis.walker import EqnSite, iter_sites

#: Collective primitives (jaxpr names) R3 audits. ``psum2`` is what a real
#: ``psum`` becomes under shard_map's replication-checker rewrite
#: (check_rep/check_vma on); ``pbroadcast`` is deliberately ABSENT — the
#: rewrite inserts it as a zero-communication replication annotation (113 of
#: them in the mesh decider trace), not a data-moving collective.
COLLECTIVE_PRIMITIVES = frozenset({
    "psum", "psum2", "all_gather", "all_to_all", "ppermute", "pmin", "pmax",
    "psum_scatter", "reduce_scatter", "pgather",
})

#: Host-callback primitives R4 forbids inside device entry points.
CALLBACK_PRIMITIVES = frozenset({
    "pure_callback", "io_callback", "debug_callback", "callback",
})

#: Float demotion targets R2 flags when fed from float64.
_DEMOTED_FLOATS = ("float32", "float16", "bfloat16")

#: Lowering/compilation markers proving buffer donation survived (R5).
_LOWERED_ALIAS_MARKERS = ("tf.aliasing_output", "jax.buffer_donor")
_COMPILED_ALIAS_MARKER = "input_output_alias"

#: Lowered-text markers of a forced host round-trip (R8): any of these inside
#: a program that claims fenced=False overlap means the device blocks on the
#: host mid-program. Checked against the StableHLO ``as_text()`` dump.
_HOST_SYNC_MARKERS = (
    "infeed", "outfeed", "send_to_host", "recv_from_host",
    "SendToHost", "RecvFromHost", "callback",
)

#: The two guarded transfer directions R7 can disallow per entry.
_TRANSFER_DIRECTIONS = ("host_to_device", "device_to_host")


@dataclass
class Finding:
    rule: str            # "R1".."R6", or "ERR" for analysis failures
    entry: str
    summary: str
    detail: str = ""
    waived: bool = False
    waiver_reason: str = ""

    def to_dict(self) -> Dict[str, Any]:
        return {
            "rule": self.rule,
            "entry": self.entry,
            "summary": self.summary,
            "detail": self.detail,
            "waived": self.waived,
            "waiver_reason": self.waiver_reason,
        }


@dataclass
class EntryReport:
    name: str
    status: str                      # "ok" | "findings" | "skipped" | "error"
    findings: List[Finding] = field(default_factory=list)
    info: Dict[str, Any] = field(default_factory=dict)


@dataclass
class AnalysisReport:
    entries: List[EntryReport]
    x64_enabled: bool

    @property
    def findings(self) -> List[Finding]:
        return [f for e in self.entries for f in e.findings]

    @property
    def unwaived(self) -> List[Finding]:
        return [f for f in self.findings if not f.waived]

    def to_dict(self) -> Dict[str, Any]:
        return {
            "x64_enabled": self.x64_enabled,
            "unwaived_findings": len(self.unwaived),
            "entries": [
                {
                    "name": e.name,
                    "status": e.status,
                    "info": e.info,
                    "findings": [f.to_dict() for f in e.findings],
                }
                for e in self.entries
            ],
        }


def apply_waivers(findings: Sequence[Finding],
                  waivers: Sequence[Mapping[str, str]]) -> None:
    """Mark findings matching a waiver (rule exact, entry fnmatch pattern).
    Waived findings stay in the report — visible, just not gate-failing."""
    for f in findings:
        for w in waivers:
            if w.get("rule") == f.rule and fnmatch.fnmatch(
                f.entry, w.get("entry", "")
            ):
                f.waived = True
                f.waiver_reason = w.get("reason", "")
                break


# ---------------------------------------------------------------------------
# Individual rules (pure functions over the walked equation stream)
# ---------------------------------------------------------------------------


def _sort_span(eqn) -> Optional[int]:
    """Length of the sorted dimension for a sort eqn (None for non-sorts)."""
    if eqn.primitive.name != "sort":
        return None
    dim = int(eqn.params.get("dimension", 0))
    shape = tuple(eqn.invars[0].aval.shape)
    if not shape:
        return None
    return int(shape[dim])


def rule_replicated_heavy(entry: KernelEntry,
                          sites: Sequence[EqnSite]) -> List[Finding]:
    """R1: in a multi-device entry, a sort/scan spanning a full registered
    global axis runs whole on every device holding it — the replicated-tail
    class. Sharded programs sort block-sized operands, which never equal the
    global axis length (the registry picks pairwise-distinct shapes).

    Scope is the ENTRY (entry.mapped), never site.mapped: the bug class this
    exists for — the legacy pod-axis ordered program's full-[N] sort — sits
    OUTSIDE any shard_map body (replicated node arrays, SPMD jit), so
    filtering sites by shard_map nesting would blind the rule to its
    flagship detection (the mutation test in tests/test_jaxlint.py pins
    this)."""
    if not entry.mapped or not entry.global_axes:
        return []
    findings = []
    for site in sites:
        span: Optional[int] = None
        if site.primitive == "sort":
            span = _sort_span(site.eqn)
        elif site.primitive == "scan":
            span = int(site.eqn.params.get("length", 0))
        if span is None or span <= 1:
            continue
        for axis_name, size in entry.global_axes.items():
            if span == size:
                findings.append(Finding(
                    rule="R1",
                    entry=entry.name,
                    summary=(
                        f"{site.primitive} spans the full global {axis_name} "
                        f"axis ({span} lanes) in a multi-device program"
                    ),
                    detail=(
                        f"at {site.pretty_path()}; every device pays the "
                        f"whole O({axis_name} log {axis_name}) op — shard it "
                        "by group block (ops.order_tail) or waive the legacy "
                        "path explicitly"
                    ),
                ))
    return findings


def rule_dtype_parity(entry: KernelEntry, sites: Sequence[EqnSite],
                      out_shapes: Any) -> List[Finding]:
    """R2: output dtype contract + no float64 demotion inside the program.
    ``out_shapes`` is the ShapeDtypeStruct pytree from the engine's single
    trace (make_jaxpr(..., return_shape=True)) — no second trace here."""
    findings = []
    if entry.output_dtypes is not None:
        selected = entry.output_select(out_shapes)
        actual = dict(shape_tree_items(selected))
        for name, want in entry.output_dtypes.items():
            got = actual.get(name)
            if got is None:
                findings.append(Finding(
                    rule="R2", entry=entry.name,
                    summary=f"declared parity output {name!r} missing from "
                            "the traced output tree",
                    detail=f"traced outputs: {sorted(actual)}",
                ))
            elif str(got.dtype) != want:
                findings.append(Finding(
                    rule="R2", entry=entry.name,
                    summary=(
                        f"parity output {name!r} is {got.dtype}, contract "
                        f"says {want}"
                    ),
                    detail="the float64/int64 bit-parity contract of "
                           "core/semantics.py is enforced, not advisory",
                ))
    for site in sites:
        if site.primitive != "convert_element_type":
            continue
        src = str(site.eqn.invars[0].aval.dtype)
        dst = str(site.eqn.params.get("new_dtype", ""))
        if src == "float64" and dst in _DEMOTED_FLOATS:
            findings.append(Finding(
                rule="R2", entry=entry.name,
                summary=f"float64 value demoted to {dst} mid-program",
                detail=f"at {site.pretty_path()}; parity math must stay f64 "
                       "end to end",
            ))
    return findings


def rule_collective_hygiene(entry: KernelEntry,
                            sites: Sequence[EqnSite]) -> List[Finding]:
    """R3: collectives name bound mesh axes; count stays within budget."""
    findings = []
    count = 0
    for site in sites:
        if site.primitive not in COLLECTIVE_PRIMITIVES:
            continue
        count += 1
        axes = site.eqn.params.get("axes",
                                   site.eqn.params.get("axis_name", ()))
        if not isinstance(axes, (tuple, list)):
            axes = (axes,)
        if not axes:
            findings.append(Finding(
                rule="R3", entry=entry.name,
                summary=f"{site.primitive} with no named axis",
                detail=f"at {site.pretty_path()}",
            ))
            continue
        for ax in axes:
            if not isinstance(ax, str):
                findings.append(Finding(
                    rule="R3", entry=entry.name,
                    summary=(
                        f"{site.primitive} over positional axis {ax!r} — "
                        "collectives must name a mesh axis"
                    ),
                    detail=f"at {site.pretty_path()}",
                ))
            elif site.bound_axes and ax not in site.bound_axes:
                findings.append(Finding(
                    rule="R3", entry=entry.name,
                    summary=f"{site.primitive} names axis {ax!r} not bound "
                            "by any enclosing mesh",
                    detail=f"at {site.pretty_path()}; bound axes: "
                           f"{sorted(site.bound_axes)}",
                ))
    if entry.collective_budget is not None and count > entry.collective_budget:
        findings.append(Finding(
            rule="R3", entry=entry.name,
            summary=(
                f"{count} collectives traced, budget is "
                f"{entry.collective_budget} — a new collective joined the "
                "hot path"
            ),
            detail="raise the pinned budget in analysis/registry.py only "
                   "with a bench number justifying the extra round-trip",
        ))
    return findings


def rule_host_sync(entry: KernelEntry,
                   sites: Sequence[EqnSite]) -> List[Finding]:
    """R4: no host callbacks inside device entry points."""
    return [
        Finding(
            rule="R4", entry=entry.name,
            summary=f"host callback primitive {site.primitive} inside a "
                    "decider program",
            detail=f"at {site.pretty_path()}; a host round-trip per tick "
                   "dwarfs the kernel (SURVEY.md §7 host<->device path)",
        )
        for site in sites
        if site.primitive in CALLBACK_PRIMITIVES
    ]


def rule_donation(entry: KernelEntry, traced: TracedEntry) -> List[Finding]:
    """R5: the lowered program actually carries buffer aliasing."""
    if not entry.donate_expected:
        return []
    if traced.jitted is None or not hasattr(traced.jitted, "lower"):
        return [Finding(
            rule="R5", entry=entry.name,
            summary="entry declares donation but exposes no lowerable jit "
                    "callable",
            detail="registry bug: pass the jit-wrapped function as "
                   "TracedEntry.jitted (or a lower thunk)",
        )]
    lowered = (traced.lower() if traced.lower is not None
               else traced.jitted.lower(*traced.args))
    text = lowered.as_text()
    if any(marker in text for marker in _LOWERED_ALIAS_MARKERS):
        return []
    # Some jax versions only materialize aliasing at compile time; check the
    # compiled HLO before declaring the donation dropped.
    try:
        compiled_text = lowered.compile().as_text()
    except Exception:  # pragma: no cover - backend-specific compile failure
        compiled_text = ""
    if _COMPILED_ALIAS_MARKER in compiled_text:
        return []
    return [Finding(
        rule="R5", entry=entry.name,
        summary="no input/output buffer alias in the lowered program — "
                "donation was silently dropped",
        detail="ops/device_state.py's O(changes) resident update becomes "
               "O(cluster) HBM traffic without donation; check "
               "donate_argnums and that donated/returned avals still match",
    )]


def _lowered_text(traced: TracedEntry) -> str:
    lowered = (traced.lower() if traced.lower is not None
               else traced.jitted.lower(*traced.args))
    return lowered.as_text()


def _place_args(traced: TracedEntry) -> Any:
    """Device-commit the representative args, exactly as production holds
    them (resident buffers), so R7 flags only transfers the PROGRAM forces —
    never the fixture's own numpy staging."""
    import jax

    return jax.device_put(traced.args)


def _r7_execute(traced: TracedEntry, placed: Any) -> None:
    """Run the compiled program once on device-resident args. ``execute``
    overrides; otherwise prefer the jit wrapper (``fn`` may be an eager body
    or a host-working public wrapper), falling back to ``fn`` when the jit
    takes static kwargs absent from ``args`` (a TypeError at binding, before
    any tracing or transfer happens)."""
    import jax

    if traced.execute is not None:
        out = traced.execute(placed)
    elif traced.lower is not None or traced.jitted is None:
        out = traced.fn(*placed)   # fn carries the static args / is the jit
    else:
        try:
            out = traced.jitted(*placed)
        except TypeError:
            out = traced.fn(*placed)
    jax.block_until_ready(out)


def rule_transfer_hygiene(entry: KernelEntry,
                          traced: TracedEntry) -> List[Finding]:
    """R7: the entry executes fully device-resident under transfer guards."""
    import jax

    findings = []
    for direction in entry.transfer_allow:
        if direction not in _TRANSFER_DIRECTIONS:
            findings.append(Finding(
                rule="R7", entry=entry.name,
                summary=f"unknown transfer_allow direction {direction!r}",
                detail=f"valid directions: {_TRANSFER_DIRECTIONS}",
            ))
    if findings:
        return findings
    try:
        placed = _place_args(traced)
    except Exception as exc:
        return [Finding(
            rule="ERR", entry=entry.name,
            summary=f"R7 device placement failed: {type(exc).__name__}",
            detail=str(exc)[:500],
        )]
    h2d = ("allow" if "host_to_device" in entry.transfer_allow
           else "disallow")
    d2h = ("allow" if "device_to_host" in entry.transfer_allow
           else "disallow")
    try:
        with jax.transfer_guard_host_to_device(h2d), \
                jax.transfer_guard_device_to_host(d2h):
            _r7_execute(traced, placed)
    except Exception as exc:
        msg = str(exc)
        if "transfer" in msg.lower():
            return [Finding(
                rule="R7", entry=entry.name,
                summary="entry forces a guarded transfer while executing "
                        "on device-resident args",
                detail=msg[:500] + " — a host value leaked into the hot "
                       "path (stray float()/np coercion feeding a jit?); "
                       "keep it resident or declare "
                       "KernelEntry.transfer_allow with a bench note",
            )]
        return [Finding(
            rule="ERR", entry=entry.name,
            summary=f"R7 execution failed: {type(exc).__name__}",
            detail=msg[:500],
        )]
    return []


def rule_overlap_host_sync(entry: KernelEntry,
                           traced: TracedEntry) -> List[Finding]:
    """R8: a program running under a fenced=False span must not lower with
    forced host sync — the span accounting claims async overlap."""
    if entry.overlap_span is None:
        return []
    try:
        text = _lowered_text(traced)
    except Exception as exc:
        return [Finding(
            rule="ERR", entry=entry.name,
            summary=f"R8 lowering failed: {type(exc).__name__}",
            detail=str(exc)[:500],
        )]
    hits = sorted({m for m in _HOST_SYNC_MARKERS if m in text})
    if not hits:
        return []
    return [Finding(
        rule="R8", entry=entry.name,
        summary=(
            f"host-sync op(s) {hits} lowered into a program running under "
            f"the fenced=False span {entry.overlap_span!r}"
        ),
        detail="the host path overlaps this dispatch (observability/spans.py "
               "fenced flag); a forced host round-trip serializes it — drop "
               "the callback or fence the span explicitly",
    )]


def rule_retrace_budget(entry: KernelEntry, compiles: int) -> List[Finding]:
    """R6: compile count across the representative two-tick sweep."""
    if entry.retrace_budget is None or compiles <= entry.retrace_budget:
        return []
    return [Finding(
        rule="R6", entry=entry.name,
        summary=(
            f"{compiles} compiles across the two-tick sweep, budget is "
            f"{entry.retrace_budget} — retrace storm"
        ),
        detail="same shapes must hit the jit cache; look for static-argnum "
               "churn, weak-type flips, or python-object hash instability",
    )]


# ---------------------------------------------------------------------------
# Engine
# ---------------------------------------------------------------------------


def analyze_entry(entry: KernelEntry, with_retrace: bool = True,
                  with_execute: bool = False) -> EntryReport:
    """Run every applicable rule on one registry entry. Failures to build or
    trace are loud ERR findings, never silent skips — an entry that stops
    tracing is exactly the refactor this gate exists to catch."""
    import jax

    if entry.min_devices > len(jax.devices()):
        return EntryReport(
            name=entry.name, status="skipped",
            info={"reason": f"needs {entry.min_devices} devices, have "
                            f"{len(jax.devices())}"},
        )
    try:
        traced = entry.build()
        closed, out_shapes = jax.make_jaxpr(
            traced.fn, return_shape=True
        )(*traced.args)
        sites = list(iter_sites(closed))
    except Exception as exc:
        return EntryReport(
            name=entry.name, status="error",
            findings=[Finding(
                rule="ERR", entry=entry.name,
                summary=f"entry failed to build/trace: {type(exc).__name__}",
                detail=str(exc)[:500],
            )],
        )
    findings: List[Finding] = []
    findings += rule_replicated_heavy(entry, sites)
    findings += rule_dtype_parity(entry, sites, out_shapes)
    findings += rule_collective_hygiene(entry, sites)
    findings += rule_host_sync(entry, sites)
    compiles: Optional[int] = None
    try:
        findings += rule_donation(entry, traced)
        findings += rule_overlap_host_sync(entry, traced)
        if with_execute:
            findings += rule_transfer_hygiene(entry, traced)
        if with_retrace and entry.retrace_probe is not None:
            compiles = entry.retrace_probe()
            findings += rule_retrace_budget(entry, compiles)
    except Exception as exc:
        findings.append(Finding(
            rule="ERR", entry=entry.name,
            summary=f"lowering/probe failed: {type(exc).__name__}",
            detail=str(exc)[:500],
        ))
    info = {
        "equations": len(sites),
        "collectives": sum(
            1 for s in sites if s.primitive in COLLECTIVE_PRIMITIVES
        ),
        "sorts": [
            {"span": _sort_span(s.eqn), "path": s.pretty_path()}
            for s in sites if s.primitive == "sort"
        ],
    }
    if compiles is not None:
        info["retrace_compiles"] = compiles
    return EntryReport(
        name=entry.name,
        status="findings" if findings else "ok",
        findings=findings,
        info=info,
    )


def run_analysis(entries: Optional[Sequence[KernelEntry]] = None,
                 extra_waivers: Optional[Sequence[Mapping[str, str]]] = None,
                 with_retrace: bool = True,
                 with_execute: bool = False) -> AnalysisReport:
    """Analyze ``entries`` (default: the full registry) and apply waivers.

    The gate condition is ``not report.unwaived``: waived findings print but
    do not fail. x64-at-trace-time (the R2 precondition) is checked once,
    globally — every kernel module calls ``jaxconfig.ensure_x64`` before
    tracing, and this asserts that stays true in whatever process embeds the
    analyzer."""
    import jax

    from escalator_tpu.analysis.registry import default_registry
    from escalator_tpu.analysis.waivers import WAIVERS

    if entries is None:
        entries = default_registry()
    reports = [analyze_entry(e, with_retrace=with_retrace,
                             with_execute=with_execute) for e in entries]
    x64 = bool(jax.config.jax_enable_x64)
    if not x64:
        reports.append(EntryReport(
            name="<global>", status="findings",
            findings=[Finding(
                rule="R2", entry="<global>",
                summary="jax_enable_x64 is OFF at analysis time",
                detail="the float64/int64 parity contract cannot hold; "
                       "jaxconfig.ensure_x64 must run before any trace",
            )],
        ))
    waivers = list(WAIVERS) + list(extra_waivers or [])
    all_findings = [f for r in reports for f in r.findings]
    apply_waivers(all_findings, waivers)
    for r in reports:
        if r.findings and all(f.waived for f in r.findings):
            r.status = "waived"
    return AnalysisReport(entries=reports, x64_enabled=x64)
