"""The waiver list: findings we keep ON PURPOSE, with the argument attached.

A waiver matches (rule exact, entry as an fnmatch pattern) and carries a
mandatory reason — the analyzer prints waived findings in every run, so the
debt stays visible instead of vanishing into a disabled check. Additional
waivers can be supplied at the CLI (``--waivers extra.json``, a JSON list of
objects with the same three keys) for downstream embedders; the in-tree list
below is the repo's own ledger and changes only by PR.
"""

from __future__ import annotations

import json
from typing import Dict, List

#: rule -> entry-pattern -> reason. The ONLY in-tree waiver is the legacy
#: replicated ordered program: parallel/podaxis.py keeps a full-[N]-sort
#: path for raw callers that want strict full-array bit-parity (the
#: multichip dryrun's contract); the production busy tick passes
#: ``node_blocks`` and runs the block-sharded tail instead. See
#: docs/performance.md ("waiver-listed, not lint-clean") and
#: ops/order_tail.py for the exactness argument.
WAIVERS: List[Dict[str, str]] = [
    {
        "rule": "R1",
        "entry": "podaxis.decider_legacy_replicated",
        "reason": (
            "intentional: strict full-array bit-parity path (multichip "
            "dryrun); hot ticks use node_blocks + the block-sharded tail"
        ),
    },
]


#: threadlint's half of the ledger, same shape with ``site`` (an fnmatch
#: pattern over ``path:qualname``) in place of ``entry``. Site-precise
#: waivers live INLINE at the flagged line (``# threadlint: waive[T3] …``)
#: — this list is for whole-function debt only, and starts (and should
#: stay) empty: the one documented exception, the unlocked epoch write in
#: the fleet engine's dispatch-failure rebuild, is waived at its site where
#: the deadlock argument already lives as a comment.
THREAD_WAIVERS: List[Dict[str, str]] = []


def load_waivers(path: str, site_key: str = "entry") -> List[Dict[str, str]]:
    """Load an external waiver file (JSON list of {rule, entry, reason};
    threadlint passes ``site_key="site"`` for its {rule, site, reason})."""
    with open(path) as f:
        data = json.load(f)
    if not isinstance(data, list):
        raise ValueError(f"{path}: waiver file must be a JSON list")
    for i, w in enumerate(data):
        if not isinstance(w, dict) or not {"rule", site_key, "reason"} <= set(w):
            raise ValueError(
                f"{path}[{i}]: each waiver needs rule, {site_key}, and "
                "reason keys"
            )
    return data
