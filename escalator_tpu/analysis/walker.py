"""Jaxpr walker: a flat, context-carrying equation stream for the rule engine.

``ruff``/``mypy`` see Python; the hazards that have actually cost this repo
performance and parity live one level down, in the traced program — a full
``[N]`` sort replicated on every device (PR 1's 0.23x busy tick), a
``float64`` parity output silently demoted, a collective sneaking onto the
hot path. Those are visible only in the jaxpr, so the analyzer walks it.

:func:`iter_sites` yields every equation of a traced entry — including the
equations of every sub-jaxpr reachable through ``pjit``/``shard_map``/
``pmap``/``scan``/``while``/``cond`` (and any other higher-order primitive:
descent is generic over jaxpr-valued params, so a new jax version's control
flow shows up instead of silently hiding) — tagged with the context the
rules need:

- ``path``: human-readable nesting trail for findings ("where is this sort");
- ``mapped``: whether the site sits inside a ``shard_map``/``pmap`` body —
  diagnostic context only. Rule R1 deliberately does NOT filter on it: in an
  SPMD jit program over a mesh, replicated work also lives OUTSIDE the
  shard_map bodies (the legacy pod-axis sort R1 exists to catch traces at
  ``pjit:decide_podaxis/cond``, with no shard_map frame above it), so R1
  keys off the ENTRY being multi-device, not the site;
- ``bound_axes``: the mesh/pmap axis names in scope, so collective hygiene
  can check that every ``psum`` names an axis that is actually bound.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Iterator, Tuple

#: Higher-order primitives that put their body on every device of a mesh —
#: inside these, a full-global-axis sort/scan is replicated work (rule R1).
MAPPED_PRIMITIVES = ("shard_map", "xla_pmap", "pmap")


@dataclass(frozen=True)
class EqnSite:
    """One equation plus the walking context the rules match against."""

    eqn: Any                      # jax.core.JaxprEqn
    path: Tuple[str, ...]         # nesting trail, outermost first
    mapped: bool                  # inside a shard_map/pmap body
    bound_axes: frozenset         # mesh/pmap axis names in scope

    @property
    def primitive(self) -> str:
        return self.eqn.primitive.name

    def pretty_path(self) -> str:
        return "/".join(self.path) if self.path else "<top>"


def _label(eqn) -> str:
    """Short label for the nesting trail: primitive name, plus the wrapped
    function's name for pjit (that is what a human greps for)."""
    name = eqn.primitive.name
    fn = eqn.params.get("name")
    if name == "pjit" and isinstance(fn, str):
        return f"pjit:{fn}"
    return name


def _axes_of(eqn) -> frozenset:
    """Axis names a mapped primitive binds for its body."""
    name = eqn.primitive.name
    if name == "shard_map":
        mesh = eqn.params.get("mesh")
        if mesh is not None and hasattr(mesh, "axis_names"):
            return frozenset(str(a) for a in mesh.axis_names)
        return frozenset()
    axis = eqn.params.get("axis_name")
    if axis is None:
        return frozenset()
    if isinstance(axis, (tuple, list)):
        return frozenset(str(a) for a in axis)
    return frozenset((str(axis),))


def _sub_jaxprs(eqn) -> Iterator[Any]:
    """Every jaxpr-valued param of ``eqn`` (generic descent: params named
    ``jaxpr``, ``branches``, ``cond_jaxpr``/``body_jaxpr``, ``call_jaxpr``,
    and anything a future primitive invents all match structurally)."""
    for val in eqn.params.values():
        vals = val if isinstance(val, (tuple, list)) else (val,)
        for v in vals:
            inner = getattr(v, "jaxpr", None)
            if inner is not None and hasattr(inner, "eqns"):
                yield inner               # ClosedJaxpr -> its Jaxpr
            elif hasattr(v, "eqns"):
                yield v                   # raw Jaxpr


def _walk(jaxpr, path: Tuple[str, ...], mapped: bool,
          bound_axes: frozenset) -> Iterator[EqnSite]:
    for eqn in jaxpr.eqns:
        yield EqnSite(eqn=eqn, path=path, mapped=mapped, bound_axes=bound_axes)
        sub_mapped = mapped or eqn.primitive.name in MAPPED_PRIMITIVES
        sub_axes = bound_axes | _axes_of(eqn)
        sub_path = path + (_label(eqn),)
        for sub in _sub_jaxprs(eqn):
            yield from _walk(sub, sub_path, sub_mapped, sub_axes)


def iter_sites(closed_jaxpr) -> Iterator[EqnSite]:
    """Yield an :class:`EqnSite` for every equation reachable from a traced
    entry (``jax.make_jaxpr(fn)(*args)``), sub-jaxprs included."""
    yield from _walk(closed_jaxpr.jaxpr, (), False, frozenset())


def count_primitives(closed_jaxpr) -> dict:
    """primitive name -> occurrence count over the whole nested program
    (diagnostic output for ``--json``; also handy in tests)."""
    counts: dict = {}
    for site in iter_sites(closed_jaxpr):
        counts[site.primitive] = counts.get(site.primitive, 0) + 1
    return counts
