"""jaxlint: jaxpr/HLO-level invariant analysis for every kernel entry point.

The reference blocks merges on ``go vet`` + race detector + lint
(/root/reference/Makefile:13-17). This package is the JAX equivalent for the
hazard classes ruff/mypy cannot see — replicated heavy ops, silent dtype
demotion, broken buffer donation, collective creep, retrace storms — run as
``python -m escalator_tpu.analysis`` (text or ``--json``; nonzero exit on
unwaived findings), ``make analyze``, a CI job, and the
``tests/test_jaxlint.py`` gate.

Layout: ``registry`` (what to trace: entries + shapes + budgets),
``walker`` (the context-carrying jaxpr equation stream), ``rules`` (R1-R8 +
engine), ``waivers`` (the visible-debt ledger).

Exports resolve LAZILY (PEP 562): ``python -m escalator_tpu.analysis``
executes this module before ``__main__`` gets a chance to pin the
cpu/8-device environment, so nothing here may import jax eagerly — the
registry (and through it jax) loads on first attribute access, which in the
CLI happens only after ``_pin_cpu_mesh`` has run.
"""

from typing import Any

_EXPORTS = {
    "KernelEntry": "escalator_tpu.analysis.registry",
    "TracedEntry": "escalator_tpu.analysis.registry",
    "default_registry": "escalator_tpu.analysis.registry",
    "representative_cluster": "escalator_tpu.analysis.registry",
    "stacked_cluster": "escalator_tpu.analysis.registry",
    "AnalysisReport": "escalator_tpu.analysis.rules",
    "EntryReport": "escalator_tpu.analysis.rules",
    "Finding": "escalator_tpu.analysis.rules",
    "analyze_entry": "escalator_tpu.analysis.rules",
    "run_analysis": "escalator_tpu.analysis.rules",
    "WAIVERS": "escalator_tpu.analysis.waivers",
    "THREAD_WAIVERS": "escalator_tpu.analysis.waivers",
    "load_waivers": "escalator_tpu.analysis.waivers",
    "ThreadFinding": "escalator_tpu.analysis.threadlint",
    "ThreadlintReport": "escalator_tpu.analysis.threadlint",
    "run_threadlint": "escalator_tpu.analysis.threadlint",
    "LockOrderViolation": "escalator_tpu.analysis.lockwitness",
}

__all__ = sorted(_EXPORTS)


def __getattr__(name: str) -> Any:
    try:
        module_name = _EXPORTS[name]
    except KeyError:
        raise AttributeError(
            f"module {__name__!r} has no attribute {name!r}"
        ) from None
    import importlib

    return getattr(importlib.import_module(module_name), name)


def __dir__() -> list:
    return sorted(set(globals()) | set(_EXPORTS))
