"""Fault injection for the chaos soak suite: forced failures in the REAL stack.

The round-11 robustness work (snapshot/restore, replay, plugin retries,
audit-worker hardening) is only trustworthy if the failure paths are driven
through the production code, not through mocks of it. This module is the
injection layer: a handful of named *sites* compiled into the hot code
(``plugin/client.py`` RPC attempts, the incremental decider's audit kick and
audit worker, the controller tick, the election renew loop), each a single
dictionary lookup when disarmed — measured sub-100 ns, invisible next to the
spans already on those paths.

Arming is programmatic (``CHAOS.arm("plugin_rpc", times=3)`` — the soak
tests) or env-driven for subprocess scenarios::

    ESCALATOR_TPU_CHAOS="tick_wedge:times=1,delay=30;plugin_rpc:every=2"

Rule knobs: ``times`` (fire at most N times; default unlimited), ``every``
(fire on every K-th eligible call), ``after`` (skip the first N calls),
``delay`` (sleep seconds when firing — the wedge injector), plus free-form
params the site interprets (e.g. ``code=unavailable`` for the RPC site).

Every firing increments ``escalator_tpu_chaos_injections_total{site}`` and
annotates the current flight-recorder timeline (``chaos=<site>``), so an
injected fault is always visible in metrics AND in the tick record — the
soak's "every injected fault visible" acceptance bar is checked against
exactly these two surfaces.

Sites in the production tree (grep ``CHAOS.`` to enumerate):

- ``plugin_rpc``      — raise a synthetic retryable RpcError before an RPC
  attempt (plugin/client.ComputeClient); ``code=`` picks the status.
- ``audit_mismatch``  — corrupt one maintained aggregate column right before
  the cadence audit kicks (ops/device_state.IncrementalDecider.decide), so
  the audit must detect + raise/repair a REAL divergence.
- ``audit_worker``    — raise inside the background-audit worker thread
  after the snapshot gate is released (worker-death path; reconcile must
  degrade to the synchronous audit, never deadlock or crash the tick).
- ``tick_wedge``      — sleep ``delay`` seconds at tick start
  (controller.Controller.run_once): drives the watchdog's
  crash-to-restart + flight dump end to end.
- ``lease_renew``     — raise from the renew loop's CAS
  (k8s/election.LeaderElector): lease loss mid-tick; deposition after the
  renew deadline.
- ``router_partition`` — raise the plugin_rpc-style synthetic RpcError on a
  routed decide (fleet/router.PartitionRouter.decide_stream): a partition
  "kill" that drives the breaker → checkpoint fail_over → replay ladder
  without killing a process; ``partition=`` scopes the blast, ``code=``
  picks the status.
"""

from __future__ import annotations

import logging
import os
import time
from dataclasses import dataclass, field
from typing import Dict, Optional

from escalator_tpu.analysis import lockwitness

log = logging.getLogger("escalator_tpu.chaos")


class ChaosInjected(RuntimeError):
    """Default exception an armed site raises (sites that need a typed
    error — the RPC hook — construct their own from the rule params)."""

    def __init__(self, site: str):
        super().__init__(f"chaos: injected fault at site {site!r}")
        self.site = site


@dataclass
class ChaosRule:
    """One armed site. Counters mutate under the monkey's lock."""

    site: str
    times: Optional[int] = None    # fire at most N times (None = unlimited)
    every: int = 1                 # fire on every K-th eligible call
    after: int = 0                 # skip the first N calls entirely
    delay_sec: float = 0.0         # sleep when firing (the wedge injector)
    params: Dict[str, str] = field(default_factory=dict)
    calls: int = 0
    fired: int = 0


class ChaosMonkey:
    """Process-global registry of armed fault sites (thread-safe: hooks run
    on tick, gRPC worker, audit worker and renew threads alike)."""

    def __init__(self) -> None:
        self._lock = lockwitness.make_lock("chaos.rules")
        self._rules: Dict[str, ChaosRule] = {}
        self._armed = False   # lock-free fast path for the disarmed case

    # -- configuration ------------------------------------------------------
    def arm(self, site: str, *, times: Optional[int] = None, every: int = 1,
            after: int = 0, delay_sec: float = 0.0,
            **params: str) -> ChaosRule:
        rule = ChaosRule(site=site, times=times, every=max(1, int(every)),
                         after=max(0, int(after)), delay_sec=float(delay_sec),
                         params={k: str(v) for k, v in params.items()})
        with self._lock:
            self._rules[site] = rule
            self._armed = True
        log.warning("chaos: armed site %r (%s)", site, rule)
        return rule

    def disarm(self, site: Optional[str] = None) -> None:
        with self._lock:
            if site is None:
                self._rules.clear()
            else:
                self._rules.pop(site, None)
            self._armed = bool(self._rules)

    def fired(self, site: str) -> int:
        with self._lock:
            rule = self._rules.get(site)
            return rule.fired if rule else 0

    def params(self, site: str) -> Dict[str, str]:
        with self._lock:
            rule = self._rules.get(site)
            return dict(rule.params) if rule else {}

    # -- firing -------------------------------------------------------------
    def should_fire(self, site: str) -> bool:
        """One eligible call at ``site``: True when the armed rule elects to
        fire now. Counts the firing, emits the metric and the flight-record
        annotation — callers then fail however the site fails (raise, sleep,
        corrupt). The disarmed fast path is one attribute read."""
        if not self._armed:
            return False
        with self._lock:
            rule = self._rules.get(site)
            if rule is None:
                return False
            rule.calls += 1
            if rule.calls <= rule.after:
                return False
            if (rule.calls - rule.after) % rule.every != 0:
                return False
            if rule.times is not None and rule.fired >= rule.times:
                return False
            rule.fired += 1
            delay = rule.delay_sec
        self._note_fired(site)
        if delay > 0:
            log.warning("chaos: site %r sleeping %.1fs", site, delay)
            time.sleep(delay)
        return True

    def inject(self, site: str) -> None:
        """The raise-form hook: fire (sleep included) and raise
        :class:`ChaosInjected`. Sites that need a typed error call
        :meth:`should_fire` and construct their own."""
        if self.should_fire(site):
            raise ChaosInjected(site)

    @staticmethod
    def _note_fired(site: str) -> None:
        # both surfaces are best-effort: a broken metrics registry must not
        # turn an injected fault into a DIFFERENT fault
        try:
            from escalator_tpu.metrics import metrics

            metrics.chaos_injections.labels(site).inc()
        except Exception:  # noqa: BLE001
            pass
        try:
            from escalator_tpu import observability as obs

            obs.annotate(chaos=site)
        except Exception:  # noqa: BLE001
            pass
        try:
            # third surface (round 17): the ops journal — a chaos run's
            # firings are discrete events an operator replays against the
            # tick ring ("what happened around tick N" includes "we shot it")
            from escalator_tpu.observability import journal

            journal.JOURNAL.event("chaos-fired", site=site)
        except Exception:  # noqa: BLE001
            pass
        log.warning("chaos: fired site %r", site)


#: the process-wide monkey every hook site consults
CHAOS = ChaosMonkey()


def install_from_env(env: Optional[str] = None) -> int:
    """Parse ``ESCALATOR_TPU_CHAOS`` (``site:k=v,k=v;site2:...``) and arm the
    monkey. Returns the number of rules armed; malformed specs fail fast
    (a chaos run silently doing nothing is worse than a crash)."""
    spec = env if env is not None else os.environ.get("ESCALATOR_TPU_CHAOS", "")
    spec = spec.strip()
    if not spec:
        return 0
    count = 0
    for part in spec.split(";"):
        part = part.strip()
        if not part:
            continue
        site, _, raw = part.partition(":")
        site = site.strip()
        if not site:
            raise ValueError(f"ESCALATOR_TPU_CHAOS: empty site in {part!r}")
        kwargs: Dict[str, str] = {}
        if raw.strip():
            for kv in raw.split(","):
                k, sep, v = kv.partition("=")
                if not sep:
                    raise ValueError(
                        f"ESCALATOR_TPU_CHAOS: expected k=v, got {kv!r}")
                kwargs[k.strip()] = v.strip()
        times = int(kwargs.pop("times")) if "times" in kwargs else None
        every = int(kwargs.pop("every", "1"))
        after = int(kwargs.pop("after", "0"))
        delay = float(kwargs.pop("delay", "0"))
        CHAOS.arm(site, times=times, every=every, after=after,
                  delay_sec=delay, **kwargs)
        count += 1
    return count


# arm from the environment at import: the subprocess scenarios (watchdog
# wedge under chaos) configure the monkey before any controller code runs
install_from_env()
