"""Dense structure-of-arrays cluster state — the host<->device boundary.

The reference walks Go object graphs per nodegroup, serially
(/root/reference/pkg/controller/controller.go:416-445, pkg/k8s/util.go:27-51). The TPU
build instead packs the whole cluster into flat, fixed-shape arrays once per tick and
evaluates *all* nodegroups in one device program:

- pods:  flat ``[P]`` arrays tagged with a group id (segment-sum replaces the per-pod Go
  loop at pkg/k8s/util.go:27-38);
- nodes: flat ``[N]`` arrays tagged with a group id plus taint/cordon/no-delete flags and
  creation/taint timestamps (replaces filterNodes at pkg/controller/controller.go:120-154
  and the sort-based selection at pkg/controller/sort.go);
- groups: ``[G]`` config+state vectors.

Shapes are padded to caller-chosen capacities so jit traces once (no recompilation storms
as cluster size fluctuates — SURVEY.md §7 "raggedness"). Padding entries carry
``valid=False`` and are masked inside the kernel.

All quantities are int64 (cpu milli-cores, memory bytes, unix nanoseconds). The decision
percent math is float64 for bit-parity with the reference's Go float64 math — on TPU
these are tiny ``[G]``-shaped ops, so f64 emulation costs nothing next to the ``[P]``
segment sums, which stay integer.
"""

from __future__ import annotations

from dataclasses import dataclass, fields
from typing import Optional, Sequence, Tuple

import numpy as np

from escalator_tpu.core import semantics
from escalator_tpu.k8s import types as k8s

#: Sentinel for "no taint timestamp" in node_taint_time_sec.
NO_TAINT_TIME = np.int64(-(2**62))


@dataclass
class GroupArrays:
    """Per-nodegroup config + cross-tick state, ``[G]``-shaped."""

    min_nodes: np.ndarray          # int32
    max_nodes: np.ndarray          # int32
    taint_lower: np.ndarray        # int32
    taint_upper: np.ndarray        # int32
    scale_up_thr: np.ndarray       # int32
    slow_rate: np.ndarray          # int32
    fast_rate: np.ndarray          # int32
    locked: np.ndarray             # bool
    requested_nodes: np.ndarray    # int32
    cached_cpu_milli: np.ndarray   # int64
    cached_mem_bytes: np.ndarray   # int64
    soft_grace_sec: np.ndarray     # int64
    hard_grace_sec: np.ndarray     # int64
    emptiest: np.ndarray           # bool: scale_down_selection == emptiest_first
    valid: np.ndarray              # bool


@dataclass
class PodArrays:
    """Flat pod state, ``[P]``-shaped. Pods are pre-filtered per group the way the
    reference's filtered listers are (pkg/controller/node_group.go:218-275), so
    daemonset/static/other-group pods never enter these arrays for a group."""

    group: np.ndarray        # int32
    cpu_milli: np.ndarray    # int64 (computed pod resource request)
    mem_bytes: np.ndarray    # int64
    node: np.ndarray         # int32 global node index, -1 if unscheduled/unknown
    valid: np.ndarray        # bool


@dataclass
class NodeArrays:
    """Flat node state, ``[N]``-shaped."""

    group: np.ndarray           # int32
    cpu_milli: np.ndarray       # int64 allocatable
    mem_bytes: np.ndarray       # int64 allocatable
    creation_ns: np.ndarray     # int64
    tainted: np.ndarray         # bool (dry-mode packing maps the taint tracker here)
    cordoned: np.ndarray        # bool
    no_delete: np.ndarray       # bool (atlassian.com/no-delete annotation non-empty)
    taint_time_sec: np.ndarray  # int64, NO_TAINT_TIME if absent/unparseable
    valid: np.ndarray           # bool


@dataclass
class ClusterArrays:
    groups: GroupArrays
    pods: PodArrays
    nodes: NodeArrays

    @property
    def num_groups(self) -> int:
        return int(self.groups.valid.shape[0])

    def tree_flatten(self):
        leaves = (
            [getattr(self.groups, f.name) for f in fields(GroupArrays)]
            + [getattr(self.pods, f.name) for f in fields(PodArrays)]
            + [getattr(self.nodes, f.name) for f in fields(NodeArrays)]
        )
        return leaves, None

    @classmethod
    def tree_unflatten(cls, aux, leaves):
        ng = len(fields(GroupArrays))
        npd = len(fields(PodArrays))
        g = GroupArrays(*leaves[:ng])
        p = PodArrays(*leaves[ng : ng + npd])
        n = NodeArrays(*leaves[ng + npd :])
        return cls(g, p, n)


def _pad_to(n: int, pad: Optional[int]) -> int:
    if pad is None:
        return max(n, 1)
    if pad < n:
        raise ValueError(f"padded capacity {pad} < actual size {n}")
    return max(pad, 1)


def pack_groups(
    config_states: Sequence[Tuple[semantics.GroupConfig, semantics.GroupState]],
    pad_groups: Optional[int] = None,
) -> GroupArrays:
    """[G] group config+state vectors — the single source of truth for the
    GroupConfig/GroupState -> GroupArrays field mapping (used by pack_cluster and
    the event-driven native backend alike)."""
    G = len(config_states)
    GP = _pad_to(G, pad_groups)
    g = GroupArrays(
        min_nodes=np.zeros(GP, np.int32),
        max_nodes=np.zeros(GP, np.int32),
        taint_lower=np.zeros(GP, np.int32),
        taint_upper=np.zeros(GP, np.int32),
        scale_up_thr=np.ones(GP, np.int32),  # avoid /0 on padding lanes
        slow_rate=np.zeros(GP, np.int32),
        fast_rate=np.zeros(GP, np.int32),
        locked=np.zeros(GP, bool),
        requested_nodes=np.zeros(GP, np.int32),
        cached_cpu_milli=np.zeros(GP, np.int64),
        cached_mem_bytes=np.zeros(GP, np.int64),
        soft_grace_sec=np.zeros(GP, np.int64),
        hard_grace_sec=np.zeros(GP, np.int64),
        emptiest=np.zeros(GP, bool),
        valid=np.zeros(GP, bool),
    )
    for gi, (config, state) in enumerate(config_states):
        g.min_nodes[gi] = config.min_nodes
        g.max_nodes[gi] = config.max_nodes
        g.taint_lower[gi] = config.taint_lower_percent
        g.taint_upper[gi] = config.taint_upper_percent
        g.scale_up_thr[gi] = config.scale_up_percent
        g.slow_rate[gi] = config.slow_removal_rate
        g.fast_rate[gi] = config.fast_removal_rate
        g.locked[gi] = state.locked
        g.requested_nodes[gi] = state.requested_nodes
        g.cached_cpu_milli[gi] = state.cached_cpu_milli
        g.cached_mem_bytes[gi] = state.cached_mem_bytes
        g.soft_grace_sec[gi] = config.soft_delete_grace_sec
        g.hard_grace_sec[gi] = config.hard_delete_grace_sec
        g.emptiest[gi] = config.scale_down_selection == "emptiest_first"
        g.valid[gi] = True
    return g


def pack_cluster(
    group_inputs: Sequence[
        Tuple[
            Sequence[k8s.Pod],
            Sequence[k8s.Node],
            semantics.GroupConfig,
            semantics.GroupState,
        ]
    ],
    dry_mode_flags: Optional[Sequence[bool]] = None,
    taint_trackers: Optional[Sequence[Sequence[str]]] = None,
    pad_pods: Optional[int] = None,
    pad_nodes: Optional[int] = None,
    pad_groups: Optional[int] = None,
) -> ClusterArrays:
    """Pack per-group object state into dense arrays.

    Also refreshes each group's cached node capacity from its first listed node, the
    way scaleNodeGroup does before computing (reference: controller.go:208-211) — that
    cross-tick cache stays host-side state, mutated here.

    In dry mode for a group, taint/cordon flags take the reference's dry-mode view:
    membership of the in-memory taint tracker defines "tainted" and nothing is treated
    as cordoned (reference: controller.go:126-138).
    """
    G = len(group_inputs)
    total_pods = sum(len(p) for p, *_ in group_inputs)
    total_nodes = sum(len(n) for _, n, *_ in group_inputs)
    P = _pad_to(total_pods, pad_pods)
    N = _pad_to(total_nodes, pad_nodes)

    # refresh cached capacity BEFORE packing group rows (controller.go:208-211)
    for _pods, nodes, _config, state in group_inputs:
        if nodes:
            state.cached_cpu_milli = nodes[0].cpu_allocatable_milli
            state.cached_mem_bytes = nodes[0].mem_allocatable_bytes

    g = pack_groups(
        [(config, state) for _, _, config, state in group_inputs], pad_groups
    )
    p = PodArrays(
        group=np.zeros(P, np.int32),
        cpu_milli=np.zeros(P, np.int64),
        mem_bytes=np.zeros(P, np.int64),
        node=np.full(P, -1, np.int32),
        valid=np.zeros(P, bool),
    )
    n = NodeArrays(
        group=np.zeros(N, np.int32),
        cpu_milli=np.zeros(N, np.int64),
        mem_bytes=np.zeros(N, np.int64),
        creation_ns=np.zeros(N, np.int64),
        tainted=np.zeros(N, bool),
        cordoned=np.zeros(N, bool),
        no_delete=np.zeros(N, bool),
        taint_time_sec=np.full(N, NO_TAINT_TIME, np.int64),
        valid=np.zeros(N, bool),
    )

    pi = 0
    ni = 0
    for gi, (pods, nodes, _config, _state) in enumerate(group_inputs):
        dry = bool(dry_mode_flags[gi]) if dry_mode_flags is not None else False
        tracker = set(taint_trackers[gi]) if taint_trackers is not None else set()

        node_index = {}
        for node in nodes:
            n.group[ni] = gi
            n.cpu_milli[ni] = node.cpu_allocatable_milli
            n.mem_bytes[ni] = node.mem_allocatable_bytes
            n.creation_ns[ni] = node.creation_time_ns
            taint = k8s.get_to_be_removed_taint(node)
            if dry:
                n.tainted[ni] = node.name in tracker
                n.cordoned[ni] = False
            else:
                n.tainted[ni] = taint is not None
                n.cordoned[ni] = node.unschedulable
            n.no_delete[ni] = bool(
                node.annotations.get(k8s.NODE_ESCALATOR_IGNORE_ANNOTATION)
            )
            if taint is not None:
                try:
                    n.taint_time_sec[ni] = int(taint.value)
                except ValueError:
                    pass
            n.valid[ni] = True
            node_index[node.name] = ni
            ni += 1

        for pod in pods:
            req = k8s.compute_pod_resource_request(pod)
            p.group[pi] = gi
            p.cpu_milli[pi] = req.cpu_milli
            p.mem_bytes[pi] = req.mem_bytes
            p.node[pi] = node_index.get(pod.node_name, -1)
            p.valid[pi] = True
            pi += 1

    return ClusterArrays(groups=g, pods=p, nodes=n)
