"""Golden (pure-Python) reference semantics of the scale decision.

This module reproduces, bit-for-bit in IEEE float64, the per-nodegroup decision math of
the reference controller:

- percent usage (reference: /root/reference/pkg/controller/util.go:58-81), including the
  all-zero fast path and the math.MaxFloat64 scale-up-from-zero sentinel;
- scale-up delta (reference: pkg/controller/util.go:13-46), both the normal
  ``ceil(nodeCount*(percent-threshold)/threshold)`` case and the scale-from-zero case
  using cached per-node capacity;
- the full decision switch of ``scaleNodeGroup``
  (reference: pkg/controller/controller.go:192-397): bounds checks, forced min scale-up,
  scale lock, threshold dispatch;
- scale-down victim selection / untaint ordering (reference: pkg/controller/sort.go,
  scale_up.go:118-163, scale_down.go:171-205) and the reaper eligibility rule
  (reference: pkg/controller/scale_down.go:51-99).

It is the parity contract for the batched JAX kernel (`escalator_tpu.ops.kernel`): the
kernel's outputs are tested element-wise against this module on randomized and golden
inputs. Keep this module dependency-free (stdlib only) so it can run anywhere as the CPU
fallback of last resort.
"""

from __future__ import annotations

import enum
import math
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

from escalator_tpu.k8s import types as k8s

# Go's math.MaxFloat64 — used as the scale-up-from-zero sentinel
# (reference: pkg/controller/util.go:71-73).
MAX_FLOAT64 = 1.7976931348623157e308

# Scale-up deltas are clamped to int32 range (the executor re-clamps to max_nodes
# anyway; only inputs describing >2^31 nodes could ever notice). Keeps the golden
# model and the int32 device kernel in exact agreement.
MAX_DELTA = 2**31 - 1


class DecisionStatus(enum.IntEnum):
    """Terminal state of one nodegroup evaluation. Mirrors the control-flow exits of
    scaleNodeGroup (reference: pkg/controller/controller.go:192-397)."""

    OK = 0                    # normal path: nodes_delta holds the decision
    NOOP_EMPTY = 1            # 0 nodes and 0 pods -> do nothing (controller.go:233-236)
    ERR_BELOW_MIN = 2         # node count < min (controller.go:238-246)
    ERR_ABOVE_MAX = 3         # node count > max (controller.go:247-255)
    FORCED_MIN_SCALE_UP = 4   # untainted < min -> immediate scale up (controller.go:281-294)
    LOCKED = 5                # scale lock held -> return requested nodes (controller.go:317-323)
    ERR_DIV_ZERO = 6          # zero capacity with >0 untainted nodes (util.go:75)
    ERR_NEG_DELTA = 7         # negative scale-up delta (util.go:42-44)


@dataclass
class GroupConfig:
    """Per-nodegroup decision inputs that come from configuration.
    Mirrors the fields of NodeGroupOptions the decision math reads
    (reference: pkg/controller/node_group.go:20-52)."""

    min_nodes: int = 0
    max_nodes: int = 0
    taint_lower_percent: int = 0
    taint_upper_percent: int = 0
    scale_up_percent: int = 0
    slow_removal_rate: int = 0
    fast_removal_rate: int = 0
    soft_delete_grace_sec: int = 0
    hard_delete_grace_sec: int = 0
    #: scale-down victim ordering: "oldest_first" (reference behavior,
    #: sort.go:12-24) or "emptiest_first" (fewest non-daemonset pods first,
    #: ties oldest-first — the selection method the reference's
    #: node-termination doc names as future work and never shipped)
    scale_down_selection: str = "oldest_first"
    #: replace the average-based scale-up delta with a first-fit-decreasing
    #: packing count: "do these pods actually FIT, and how many template nodes
    #: does the overflow need". Lifts the whole-group-average /
    #: single-instance-type assumption the reference documents
    #: (docs/calculations.md:8, docs/best-practices-issues-gotchas.md:36-38)
    packing_aware: bool = False
    #: max virtual new nodes the packing pass may propose per tick (static
    #: kernel shape; the executor's max_nodes clamp still applies after)
    packing_budget: int = 128


@dataclass
class GroupState:
    """Cross-tick mutable state the decision reads.
    Mirrors NodeGroupState (reference: pkg/controller/controller.go:28-44)."""

    locked: bool = False
    requested_nodes: int = 0
    cached_cpu_milli: int = 0     # cached per-node cpu allocatable (controller.go:208-211)
    cached_mem_bytes: int = 0


@dataclass
class Decision:
    status: DecisionStatus
    nodes_delta: int = 0          # the value scaleNodeGroup would compute (pre-execution)
    cpu_percent: float = 0.0
    mem_percent: float = 0.0
    # Aggregates, for metrics parity (controller.go:275-278)
    cpu_request_milli: int = 0
    mem_request_bytes: int = 0
    cpu_capacity_milli: int = 0
    mem_capacity_bytes: int = 0
    num_untainted: int = 0
    num_tainted: int = 0
    num_cordoned: int = 0
    num_nodes: int = 0
    num_pods: int = 0


def calc_percent_usage(
    cpu_request_milli: int,
    mem_request_milli: int,
    cpu_capacity_milli: int,
    mem_capacity_milli: int,
    num_untainted_nodes: int,
) -> Tuple[float, float]:
    """Percent usage for cpu+mem (reference: pkg/controller/util.go:58-81).

    Raises ZeroDivisionError where the reference returns the divide-by-zero error.
    NOTE: arguments are *milli* values (memory milli = bytes*1000) so the float64
    rounding matches the reference exactly.
    """
    if (
        cpu_request_milli == 0
        and mem_request_milli == 0
        and cpu_capacity_milli == 0
        and mem_capacity_milli == 0
        and num_untainted_nodes == 0
    ):
        return 0.0, 0.0

    if cpu_capacity_milli == 0 or mem_capacity_milli == 0:
        if num_untainted_nodes == 0:
            return MAX_FLOAT64, MAX_FLOAT64
        raise ZeroDivisionError("cannot divide by zero in percent calculation")

    cpu_percent = float(cpu_request_milli) / float(cpu_capacity_milli) * 100
    mem_percent = float(mem_request_milli) / float(mem_capacity_milli) * 100
    return cpu_percent, mem_percent


def calc_scale_up_delta(
    num_untainted_nodes: int,
    cpu_percent: float,
    mem_percent: float,
    cpu_request_milli: int,
    mem_request_milli: int,
    cached_cpu_milli: int,
    cached_mem_milli: int,
    scale_up_threshold_percent: int,
) -> int:
    """Nodes to add so util drops below the threshold
    (reference: pkg/controller/util.go:13-46).

    Raises ValueError for a negative delta (the reference's error path) and for a
    non-positive threshold (the reference can never reach this code with one —
    ValidateNodeGroup rejects it at startup, pkg/controller/node_group.go:96 — and
    its float math would otherwise produce machine-dependent garbage; we fail
    deterministically instead). Memory arguments are milli values (bytes*1000) for
    float64 parity. The result is clamped to MAX_DELTA (int32) to match the device
    kernel; the executor clamps to max_nodes regardless.
    """
    if scale_up_threshold_percent <= 0:
        raise ValueError("non-positive scale up threshold")
    threshold = float(scale_up_threshold_percent)

    if cpu_percent == MAX_FLOAT64 or mem_percent == MAX_FLOAT64:
        # Scale up from zero. Without cached capacity, add one node to learn it.
        if cached_cpu_milli == 0 or cached_mem_milli == 0:
            return 1
        nodes_needed_cpu = math.ceil(
            float(cpu_request_milli) / float(cached_cpu_milli) / threshold * 100
        )
        nodes_needed_mem = math.ceil(
            float(mem_request_milli) / float(cached_mem_milli) / threshold * 100
        )
    else:
        pct_needed_cpu = (cpu_percent - threshold) / threshold
        pct_needed_mem = (mem_percent - threshold) / threshold
        nodes_needed_cpu = math.ceil(float(num_untainted_nodes) * pct_needed_cpu)
        nodes_needed_mem = math.ceil(float(num_untainted_nodes) * pct_needed_mem)

    delta = int(max(nodes_needed_cpu, nodes_needed_mem))
    if delta < 0:
        raise ValueError("negative scale up delta")
    return min(delta, MAX_DELTA)


# ---------------------------------------------------------------------------
# Node filtering (reference: pkg/controller/controller.go:120-154)
# ---------------------------------------------------------------------------


def filter_nodes(
    nodes: Sequence[k8s.Node],
    dry_mode: bool = False,
    taint_tracker: Optional[Sequence[str]] = None,
) -> Tuple[List[k8s.Node], List[k8s.Node], List[k8s.Node]]:
    """Split nodes into (untainted, tainted, cordoned).

    In dry mode the in-memory taint tracker substitutes for real taints and cordoned
    nodes are NOT separated (reference: controller.go:126-138 — the dry-mode branch
    never checks Unschedulable).
    """
    untainted: List[k8s.Node] = []
    tainted: List[k8s.Node] = []
    cordoned: List[k8s.Node] = []
    tracker = set(taint_tracker or ())
    for node in nodes:
        if dry_mode:
            if node.name in tracker:
                tainted.append(node)
            else:
                untainted.append(node)
        else:
            if node.unschedulable:
                cordoned.append(node)
                continue
            if k8s.get_to_be_removed_taint(node) is None:
                untainted.append(node)
            else:
                tainted.append(node)
    return untainted, tainted, cordoned


# ---------------------------------------------------------------------------
# Full per-group decision (reference: pkg/controller/controller.go:192-397)
# ---------------------------------------------------------------------------


def evaluate_node_group(
    pods: Sequence[k8s.Pod],
    nodes: Sequence[k8s.Node],
    config: GroupConfig,
    state: GroupState,
    dry_mode: bool = False,
    taint_tracker: Optional[Sequence[str]] = None,
) -> Decision:
    """Pure decision part of scaleNodeGroup: everything between the lister reads and
    the ScaleUp/ScaleDown dispatch. Mutates ``state.cached_*`` the way the reference
    caches node capacity (controller.go:208-211)."""
    pods = list(pods)
    nodes = list(nodes)

    if nodes:
        state.cached_cpu_milli = nodes[0].cpu_allocatable_milli
        state.cached_mem_bytes = nodes[0].mem_allocatable_bytes

    untainted, tainted, cordoned = filter_nodes(nodes, dry_mode, taint_tracker)

    base = dict(
        num_untainted=len(untainted),
        num_tainted=len(tainted),
        num_cordoned=len(cordoned),
        num_nodes=len(nodes),
        num_pods=len(pods),
    )

    if len(nodes) == 0 and len(pods) == 0:
        return Decision(DecisionStatus.NOOP_EMPTY, **base)
    if len(nodes) < config.min_nodes:
        return Decision(DecisionStatus.ERR_BELOW_MIN, **base)
    if len(nodes) > config.max_nodes:
        return Decision(DecisionStatus.ERR_ABOVE_MAX, **base)

    mem_request, cpu_request = k8s.calculate_pods_requests_total(pods)
    mem_capacity, cpu_capacity = k8s.calculate_nodes_capacity_total(untainted)
    base.update(
        cpu_request_milli=cpu_request,
        mem_request_bytes=mem_request,
        cpu_capacity_milli=cpu_capacity,
        mem_capacity_bytes=mem_capacity,
    )

    if len(untainted) < config.min_nodes:
        return Decision(
            DecisionStatus.FORCED_MIN_SCALE_UP,
            nodes_delta=config.min_nodes - len(untainted),
            **base,
        )

    try:
        cpu_percent, mem_percent = calc_percent_usage(
            cpu_request, mem_request * 1000, cpu_capacity, mem_capacity * 1000,
            len(untainted),
        )
    except ZeroDivisionError:
        return Decision(DecisionStatus.ERR_DIV_ZERO, **base)
    base.update(cpu_percent=cpu_percent, mem_percent=mem_percent)

    if state.locked:
        return Decision(DecisionStatus.LOCKED, nodes_delta=state.requested_nodes, **base)

    max_percent = max(cpu_percent, mem_percent)
    nodes_delta = 0
    if max_percent < float(config.taint_lower_percent):
        nodes_delta = -config.fast_removal_rate
    elif max_percent < float(config.taint_upper_percent):
        nodes_delta = -config.slow_removal_rate
    elif max_percent > float(config.scale_up_percent):
        try:
            nodes_delta = calc_scale_up_delta(
                len(untainted),
                cpu_percent,
                mem_percent,
                cpu_request,
                mem_request * 1000,
                state.cached_cpu_milli,
                state.cached_mem_bytes * 1000,
                config.scale_up_percent,
            )
        except ValueError:
            return Decision(DecisionStatus.ERR_NEG_DELTA, **base)

    if config.packing_aware and nodes_delta >= 0:
        # Packing-aware groups replace the average-based delta whenever the
        # switch did not choose scale-DOWN: FFD-repack all pods into the
        # untainted nodes' capacity and count the template-node overflow.
        # Catches both averaging failure modes — headroom-triggered scale-ups
        # whose pods actually fit (delta shrinks to 0), and under-threshold
        # fragmentation where a pod fits nowhere (delta grows from 0).
        nodes_delta = packing_scale_up_delta(pods, untainted, config, state)

    return Decision(DecisionStatus.OK, nodes_delta=nodes_delta, **base)


def ffd_pack_pure(pods, bins, template, new_bin_budget: int):
    """First-fit-decreasing with deterministic tie-breaking — the golden model
    for ``ops.binpack.ffd_pack`` (the device kernel is parity-tested against
    this). pods: [(cpu, mem)]; bins: [(cpu, mem)] free capacity; template:
    (cpu, mem) capacity of a prospective new node. Returns (assignment,
    new_bins_used, unplaced). Pure Python, no array deps: usable by the
    dependency-free golden backend."""
    ref_cpu = template[0] or 1
    ref_mem = template[1] or 1
    order = sorted(
        range(len(pods)),
        key=lambda i: (-max(pods[i][0] / ref_cpu, pods[i][1] / ref_mem), i),
    )
    capacity = [list(b) for b in bins] + [
        [template[0], template[1]] for _ in range(new_bin_budget)
    ]
    assignment = [-1] * len(pods)
    for i in order:
        cpu, mem = pods[i]
        for bi, (bc, bm) in enumerate(capacity):
            if bc >= cpu and bm >= mem:
                capacity[bi][0] -= cpu
                capacity[bi][1] -= mem
                assignment[i] = bi
                break
    used_virtual = sum(
        1
        for bi in range(len(bins), len(capacity))
        if capacity[bi][0] < template[0] or capacity[bi][1] < template[1]
    )
    unplaced = sum(1 for a in assignment if a < 0)
    return assignment, used_virtual, unplaced


def packing_scale_up_delta(
    pods: Sequence[k8s.Pod],
    untainted: Sequence[k8s.Node],
    config: GroupConfig,
    state: GroupState,
) -> int:
    """The packing-aware delta: FFD-place every pod of the group into the
    untainted nodes' allocatable capacity plus up to ``packing_budget`` virtual
    nodes of the cached template capacity; the delta is virtual-nodes-used plus
    one per pod that fits nowhere (a pod larger than the template conservatively
    claims a node — adding more identical nodes cannot help it, mirroring the
    reference's +1 no-cache convention, pkg/controller/util.go:20-24)."""
    if not pods:
        return 0
    template = (state.cached_cpu_milli, state.cached_mem_bytes)
    if template[0] == 0 or template[1] == 0:
        # no cached capacity to size virtual nodes: reference convention is
        # "request one and find out" (calcScaleUpDelta's no-cache branch)
        return 1
    reqs = []
    for p in pods:
        r = k8s.compute_pod_resource_request(p)
        reqs.append((r.cpu_milli, r.mem_bytes))
    bins = [
        (n.cpu_allocatable_milli, n.mem_allocatable_bytes) for n in untainted
    ]
    _, used_virtual, unplaced = ffd_pack_pure(
        reqs, bins, template, config.packing_budget
    )
    return used_virtual + unplaced


# ---------------------------------------------------------------------------
# Ordering / selection (reference: pkg/controller/sort.go, scale_up.go, scale_down.go)
# ---------------------------------------------------------------------------


def nodes_oldest_first(nodes: Sequence[k8s.Node]) -> List[int]:
    """Indices of nodes ordered oldest creation time first — scale-down victim order
    (reference: pkg/controller/sort.go:12-24). Ties break by input index, making the
    order deterministic (the reference uses an unstable sort; order under exact-tie
    timestamps is unspecified there)."""
    return sorted(range(len(nodes)), key=lambda i: (nodes[i].creation_time_ns, i))


def nodes_newest_first(nodes: Sequence[k8s.Node]) -> List[int]:
    """Indices of nodes ordered newest creation time first — untaint order
    (reference: pkg/controller/sort.go:27-39)."""
    return sorted(range(len(nodes)), key=lambda i: (-nodes[i].creation_time_ns, i))


def nodes_emptiest_first(
    nodes: Sequence[k8s.Node], pods_remaining: Sequence[int]
) -> List[int]:
    """Indices ordered by (non-daemonset pod count asc, creation asc, index) —
    the eviction-minimizing scale-down order (``scale_down_selection:
    emptiest_first``). No reference implementation exists; its node-termination
    doc lists alternative selection methods as future work."""
    return sorted(
        range(len(nodes)),
        key=lambda i: (pods_remaining[i], nodes[i].creation_time_ns, i),
    )


def reap_eligible(
    tainted_nodes: Sequence[k8s.Node],
    node_info_map: Dict[str, Tuple[Optional[k8s.Node], List[k8s.Pod]]],
    soft_grace_sec: int,
    hard_grace_sec: int,
    now_unix_sec: int,
) -> List[int]:
    """Indices of tainted nodes eligible for deletion this tick
    (reference: pkg/controller/scale_down.go:51-99):
    not annotated no-delete, taint timestamp readable, past the soft grace period AND
    (empty of non-daemonset pods OR past the hard grace period). Comparisons are
    strict ``>`` as in the reference."""
    out: List[int] = []
    for i, node in enumerate(tainted_nodes):
        if node.annotations.get(k8s.NODE_ESCALATOR_IGNORE_ANNOTATION):
            continue
        try:
            tainted_time = k8s.get_to_be_removed_time(node)
        except ValueError:
            continue
        if tainted_time is None:
            continue
        age = now_unix_sec - tainted_time
        if age > soft_grace_sec and (
            k8s.node_empty(node, node_info_map) or age > hard_grace_sec
        ):
            out.append(i)
    return out


def clamp_scale_down(num_untainted: int, nodes_to_remove: int, min_nodes: int) -> int:
    """Clamp a scale-down so untainted-after >= min
    (reference: pkg/controller/scale_down.go:143-158). Returns the clamped count;
    raises ValueError when untainted is already below min (the reference's abort)."""
    if num_untainted - nodes_to_remove < min_nodes:
        nodes_to_remove = num_untainted - min_nodes
        if nodes_to_remove < 0:
            raise ValueError(
                "the number of nodes is less than specified minimum; taking no action"
            )
    return nodes_to_remove


def calculate_nodes_to_add(nodes_to_add: int, target_size: int, max_nodes: int) -> int:
    """Clamp a provider scale-up to the group max
    (reference: pkg/controller/scale_up.go:48-55)."""
    if target_size + nodes_to_add > max_nodes:
        nodes_to_add = max_nodes - target_size
    return nodes_to_add
