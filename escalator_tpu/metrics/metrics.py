"""Prometheus metrics — the same `escalator_*` metric names as the reference
(/root/reference/pkg/metrics/metrics.go:12-230) so existing dashboards (e.g. the
shipped Grafana board, docs/grafana-dashboard.json) keep working, plus
`escalator_tpu_*` additions for the device solver."""

from __future__ import annotations

import threading
import socketserver
from wsgiref.simple_server import WSGIServer, make_server


class _ThreadingWSGIServer(socketserver.ThreadingMixIn, WSGIServer):
    """One thread per connection: a stalled metrics scrape must never block
    the /healthz the kubelet's liveness probe depends on."""

    daemon_threads = True

from prometheus_client import (
    CollectorRegistry,
    Counter,
    Gauge,
    Histogram,
    make_wsgi_app,
)

NAMESPACE = "escalator"

#: Dedicated registry: keeps tests hermetic and avoids surprise default-registry
#: collisions in embedding processes.
registry = CollectorRegistry()

_BUCKETS = tuple(float(60 * i) for i in range(1, 30))  # 60..1740s, 60s buckets
_NG = ["node_group"]

run_count = Counter(
    "run_count", "Number of times the controller has checked for cluster state",
    namespace=NAMESPACE, registry=registry,
)
last_tick_age_seconds = Gauge(
    "last_tick_age_seconds",
    "Seconds since the last completed controller tick (-1 before the first; "
    "the same freshness signal /readyz gates on)",
    namespace="escalator_tpu", registry=registry,
)
last_tick_age_seconds.set(-1)
node_group_nodes_untainted = Gauge(
    "node_group_untainted_nodes",
    "nodes considered by specific node groups that are untainted",
    _NG, namespace=NAMESPACE, registry=registry,
)
node_group_nodes_tainted = Gauge(
    "node_group_tainted_nodes",
    "nodes considered by specific node groups that are tainted",
    _NG, namespace=NAMESPACE, registry=registry,
)
node_group_nodes_cordoned = Gauge(
    "node_group_cordoned_nodes",
    "nodes considered by specific node groups that are cordoned",
    _NG, namespace=NAMESPACE, registry=registry,
)
node_group_nodes = Gauge(
    "node_group_nodes", "nodes considered by specific node groups",
    _NG, namespace=NAMESPACE, registry=registry,
)
node_group_pods = Gauge(
    "node_group_pods", "pods considered by specific node groups",
    _NG, namespace=NAMESPACE, registry=registry,
)
node_group_pods_evicted = Counter(
    "node_group_pods_evicted", "pods evicted during a scale down",
    _NG, namespace=NAMESPACE, registry=registry,
)
node_group_mem_percent = Gauge(
    "node_group_mem_percent", "percentage of util of memory",
    _NG, namespace=NAMESPACE, registry=registry,
)
node_group_cpu_percent = Gauge(
    "node_group_cpu_percent", "percentage of util of cpu",
    _NG, namespace=NAMESPACE, registry=registry,
)
node_group_mem_request = Gauge(
    "node_group_mem_request", "byte value of node request mem",
    _NG, namespace=NAMESPACE, registry=registry,
)
node_group_cpu_request = Gauge(
    "node_group_cpu_request", "milli value of node request cpu",
    _NG, namespace=NAMESPACE, registry=registry,
)
node_group_mem_capacity = Gauge(
    "node_group_mem_capacity", "byte value of node capacity mem",
    _NG, namespace=NAMESPACE, registry=registry,
)
node_group_cpu_capacity = Gauge(
    "node_group_cpu_capacity", "milli value of node capacity cpu",
    _NG, namespace=NAMESPACE, registry=registry,
)
node_group_taint_event = Gauge(
    "node_group_taint_event", "indicates a scale down event",
    _NG, namespace=NAMESPACE, registry=registry,
)
node_group_untaint_event = Gauge(
    "node_group_untaint_event", "indicates a scale up event",
    _NG, namespace=NAMESPACE, registry=registry,
)
node_group_scale_lock = Gauge(
    "node_group_scale_lock", "indicates if the nodegroup is locked from scaling",
    _NG, namespace=NAMESPACE, registry=registry,
)
node_group_scale_lock_duration = Histogram(
    "node_group_scale_lock_duration",
    "indicates how long the nodegroup is locked from scaling",
    _NG, namespace=NAMESPACE, registry=registry, buckets=_BUCKETS,
)
node_group_scale_lock_check_was_locked = Counter(
    "node_group_scale_lock_check_was_locked",
    "indicates how many checks of the nodegroup scale lock were done whilst the lock"
    " was held",
    _NG, namespace=NAMESPACE, registry=registry,
)
node_group_scale_delta = Gauge(
    "node_group_scale_delta", "indicates current scale delta",
    _NG, namespace=NAMESPACE, registry=registry,
)
node_group_node_registration_lag = Histogram(
    "node_group_node_registration_lag",
    "indicates how long nodes take to register in kube from instantiation in the"
    " nodegroup",
    _NG, namespace=NAMESPACE, registry=registry, buckets=_BUCKETS,
)
_CP = ["cloud_provider", "id", "node_group"]
cloud_provider_min_size = Gauge(
    "cloud_provider_min_size", "current cloud provider minimum size",
    _CP, namespace=NAMESPACE, registry=registry,
)
cloud_provider_max_size = Gauge(
    "cloud_provider_max_size", "current cloud provider maximum size",
    _CP, namespace=NAMESPACE, registry=registry,
)
cloud_provider_target_size = Gauge(
    "cloud_provider_target_size", "current cloud provider target size",
    _CP, namespace=NAMESPACE, registry=registry,
)
cloud_provider_size = Gauge(
    "cloud_provider_size", "current cloud provider size",
    _CP, namespace=NAMESPACE, registry=registry,
)

# --- TPU-native additions (no reference equivalent) -------------------------
solver_decide_latency = Histogram(
    "solver_decide_latency_seconds",
    "device latency of the batched scale-decision kernel",
    ["backend"], namespace="escalator_tpu", registry=registry,
    buckets=(0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0),
)
solver_pack_latency = Histogram(
    "solver_pack_latency_seconds",
    "host latency of packing cluster state into device arrays",
    ["backend"], namespace="escalator_tpu", registry=registry,
    buckets=(0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0),
)
solver_packing_latency = Histogram(
    "solver_packing_latency_seconds",
    "latency of the packing-aware FFD delta pass (packing_aware groups only)",
    namespace="escalator_tpu", registry=registry,
    buckets=(0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0),
)

# --- observability layer (tick flight recorder, escalator_tpu.observability) -
tick_phase_latency = Histogram(
    "tick_phase_seconds",
    "per-phase device-fenced tick latency from the span timeline "
    "(phase label is the span leaf name: pack, scatter, delta_decide, "
    "decide_ordered, unpack, ...)",
    ["backend", "phase"], namespace="escalator_tpu", registry=registry,
    buckets=(0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025,
             0.05, 0.1, 0.25, 0.5, 1.0),
)
tick_overlap_saved = Histogram(
    "tick_overlap_saved_seconds",
    "host work hidden under an in-flight (overlapped, unfenced) decide "
    "dispatch per tick — the latency a fully-fenced tick would have added "
    "back; an upper bound when the device finished inside the host window",
    ["backend"], namespace="escalator_tpu", registry=registry,
    buckets=(0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025,
             0.05, 0.1, 0.25),
)
incremental_audit_mismatch = Counter(
    "incremental_audit_mismatch_total",
    "refresh audits where the maintained incremental aggregates diverged "
    "from a from-scratch recompute (each one also triggers a flight-record "
    "dump); alert on any increase",
    namespace="escalator_tpu", registry=registry,
)
flight_recorder_dumps = Counter(
    "flight_recorder_dumps_total",
    "automatic flight-recorder incident dumps, by trigger",
    ["reason"], namespace="escalator_tpu", registry=registry,
)
audit_worker_failures = Counter(
    "audit_worker_failures_total",
    "background refresh-audit worker threads that died with an exception "
    "(each one degrades that audit to the synchronous form and dumps the "
    "flight recorder); alert on any increase",
    namespace="escalator_tpu", registry=registry,
)

# --- failover-grade state (round 11: snapshot/restore, replay, chaos) --------
plugin_fallback = Counter(
    "plugin_fallback_total",
    "remote-plugin decides that fell back to the local backend, by gRPC "
    "status code (circuit-open = served from the pinned fallback without "
    "attempting the RPC)",
    ["code"], namespace="escalator_tpu", registry=registry,
)
plugin_rpc_retries = Counter(
    "plugin_rpc_retries_total",
    "individual plugin RPC attempts retried after a retryable failure "
    "(each decide may contribute several; fallbacks count separately)",
    namespace="escalator_tpu", registry=registry,
)
snapshot_checkpoints = Counter(
    "snapshot_checkpoints_total",
    "device-state snapshots checkpointed to disk (atomic write completed)",
    namespace="escalator_tpu", registry=registry,
)
snapshot_restores = Counter(
    "snapshot_restores_total",
    "device-state restore attempts by outcome: warm (snapshot adopted), "
    "corrupt (validation failed, cold start + flight dump), stale "
    "(incompatible shapes/meta, cold start)",
    ["outcome"], namespace="escalator_tpu", registry=registry,
)
chaos_injections = Counter(
    "chaos_injections_total",
    "faults fired by the chaos injection layer (escalator_tpu.chaos), by "
    "site — nonzero only in fault-injection runs",
    ["site"], namespace="escalator_tpu", registry=registry,
)
# --- fleet decision service (round 14: multi-tenant continuous batching) -----
fleet_batch_size = Histogram(
    "fleet_batch_size",
    "tenants coalesced into one fleet micro-batch (= one device dispatch); "
    "a p50 stuck at 1 under load means coalescing is not happening — check "
    "the scheduler flush knobs",
    namespace="escalator_tpu", registry=registry,
    buckets=(1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024),
)
fleet_admission_rejects = Counter(
    "fleet_admission_rejects_total",
    "fleet decide requests rejected at admission, by reason (queue-full = "
    "bounded queue overflowed -> RESOURCE_EXHAUSTED + retry-after, "
    "tenant-inflight = per-tenant in-flight cap hit, invalid-tenant = "
    "malformed/unknown tenant id -> INVALID_ARGUMENT)",
    ["reason"], namespace="escalator_tpu", registry=registry,
)
fleet_tenant_count = Gauge(
    "fleet_tenant_count",
    "tenants currently resident in the fleet decision arenas",
    namespace="escalator_tpu", registry=registry,
)
fleet_arena_grows = Counter(
    "fleet_arena_grow_total",
    "fleet arena bucket growths (any of the G/P/N/C buckets doubled) — "
    "each one is an O(arena) host copy AND a step change in resident HBM; "
    "a steady rate means the sizing knobs are wrong for the workload",
    namespace="escalator_tpu", registry=registry,
)
fleet_arena_compacts = Counter(
    "fleet_arena_compact_total",
    "fleet arena compactions (live tenants repacked, tenant axis shrunk) — "
    "the post-mass-eviction HBM reclaim",
    namespace="escalator_tpu", registry=registry,
)
fleet_batch_deferred = Counter(
    "fleet_batch_deferred_total",
    "queued fleet requests skipped by the one-request-per-tenant rule "
    "during batch assembly (they keep their queue position for the next "
    "batch) — a high rate relative to admissions means one tenant is "
    "submitting faster than the flush cadence",
    namespace="escalator_tpu", registry=registry,
)
fleet_overlap_saved_ms = Counter(
    "fleet_batch_overlap_saved_ms_total",
    "milliseconds of fleet host prep (diff/pack/twin adoption) that ran "
    "while another batch's device program was in flight — the pipelined "
    "scheduler's recorder-proven overlap win, summed across batches; flat "
    "at 0 means the scheduler is running unpipelined or the device "
    "programs finish before prep starts",
    namespace="escalator_tpu", registry=registry,
)
fleet_slo_budget_burn = Gauge(
    "fleet_slo_budget_burn",
    "per-priority-class SLO error-budget burn rate over the rolling check "
    "window: the fraction of requests over the class's p99_target_ms "
    "divided by the 1% a p99 SLO allows (1.0 = burning exactly the "
    "allotment; >= 14.4 is the fast-burn page threshold — the scheduler "
    "journals an escalation and, with ESCALATOR_TPU_TAIL_PROFILE=1, arms "
    "a profiler capture)",
    ["klass"], namespace="escalator_tpu", registry=registry,
)
fleet_cache_hits = Counter(
    "fleet_cache_hits_total",
    "fleet decide requests answered from the per-tenant input-digest cache "
    "(round 18) without entering the micro-batch: the request's packed "
    "sections (or empty delta frame) hashed equal to the tenant's last "
    "dispatched input at the same now_sec, so the persistent decision "
    "columns answer bit-identically — the mostly-idle-fleet fast path",
    ["klass"], namespace="escalator_tpu", registry=registry,
)
fleet_tail_batch_size = Histogram(
    "fleet_tail_batch_size",
    "order-consuming tenants repaired by ONE batched order-tail dispatch "
    "after a fleet micro-batch (round 18; replaces the per-tenant 55 ms "
    "O(arena) re-dispatch) — a p50 stuck at 1 under scale-down-heavy load "
    "just means few tenants need orders per batch, not a regression",
    namespace="escalator_tpu", registry=registry,
    buckets=(1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024),
)
fleet_group_flaps = Counter(
    "fleet_group_flaps_total",
    "scale-decision oscillations flagged by the provenance flap watchdog "
    "(observability/provenance.py): a (tenant, group) whose nodes_delta "
    "sign alternated (klass=delta_sign) or whose status toggled between "
    "two codes (klass=status_churn) at least ESCALATOR_TPU_FLAP_MIN_"
    "ALTERNATIONS times within the ESCALATOR_TPU_FLAP_WINDOW most recent "
    "decisions — each increment also lands a group-flap journal event and "
    "(rate-limited) a reason=\"flap\" flight dump naming the groups with "
    "their explanations; a sustained oscillation re-counts once per full "
    "window, not once per tick",
    ["klass"], namespace="escalator_tpu", registry=registry,
)
provenance_explain_mismatches = Counter(
    "provenance_explain_mismatches_total",
    "explain-kernel cross-check failures: (group, column) cells where the "
    "decision calculus re-derived from the resident aggregates was NOT "
    "bit-equal to the committed decision columns (dirty groups excluded — "
    "their committed columns are legitimately one decision behind). The "
    "explain path shares the kernel's math core, so any increment means "
    "the persistent aggregates drifted from the committed answer — a "
    "stale-cache/missed-dirty bug class, never expected in production; "
    "each burst also journals explain-mismatch and (rate-limited) dumps",
    namespace="escalator_tpu", registry=registry,
)
# --- partition router (round 20: horizontal scale-out) ----------------------
router_migrations = Counter(
    "router_migrations_total",
    "warm tenant migrations driven by the partition router, by outcome "
    "(ok = snapshot->evict->adopt completed and the override pinned; "
    "error = the sequence aborted — the tenant stays where the last "
    "completed step left it, journal has the detail)",
    ["outcome"], namespace="escalator_tpu", registry=registry,
)
router_breaker_trips = Counter(
    "router_breaker_trips_total",
    "per-partition circuit-breaker openings in the router (consecutive "
    "forwarding failures reached the threshold): the partition leaves the "
    "ring and its tenants fail over to the survivors",
    ["partition"], namespace="escalator_tpu", registry=registry,
)
router_failover_rehomes = Counter(
    "router_failover_rehomes_total",
    "tenants re-homed by a partition failover, by outcome (warm = rolling "
    "checkpoint adopted on the survivor, digest continuity holds from the "
    "checkpointed columns; cold = no usable checkpoint — full-frame "
    "resync, first decision recomputes from the client twin)",
    ["outcome"], namespace="escalator_tpu", registry=registry,
)
fleet_class_p99_breach = Counter(
    "fleet_class_p99_breach_total",
    "per-priority-class SLO breach checks that found the class's RECENT "
    "request p99 above its declared p99_target_ms — evaluated on a "
    "served-request cadence over a rolling window (samples since the "
    "last check), so a sustained breach counts repeatedly while it "
    "lasts and the counter goes quiet one window after recovery",
    ["klass"], namespace="escalator_tpu", registry=registry,
)

jax_compile_seconds = Histogram(
    "jax_compile_seconds",
    "XLA backend-compile durations observed via jax.monitoring (a warm "
    "steady state observes none; per-tick compiles mean retrace churn)",
    namespace="escalator_tpu", registry=registry,
    buckets=(0.01, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0),
)
jax_compile_events = Counter(
    "jax_compile_events_total",
    "XLA backend compiles observed via jax.monitoring",
    namespace="escalator_tpu", registry=registry,
)
jax_transfer_events = Counter(
    "jax_transfer_events_total",
    "host<->device transfer events observed via jax.monitoring (this jax "
    "version emits none; populated on runtimes that do)",
    namespace="escalator_tpu", registry=registry,
)


# --- tail-latency truth (round 13: streaming log-bucket histograms) ----------
class _TailHistogramCollector:
    """Pull-time export of the observability layer's streaming log-bucket
    histograms (observability/histograms.py: base-1.25 buckets, 1 µs..10 s)
    as NATIVE Prometheus histograms:

    - ``escalator_tpu_tick_phase_hist_seconds{backend,phase}`` — fine-bucket
      per-phase series. The coarse pre-round-13
      ``escalator_tpu_tick_phase_seconds`` histogram above stays exported
      unchanged for dashboard compatibility; this family adds the bucket
      resolution (25% worst-case quantile error at any magnitude) that
      p999 queries actually need.
    - ``escalator_tpu_tick_e2e_seconds{root}`` — the root end-to-end tick
      series, keyed by root timeline name (the tail watchdog's comparison
      population and the source of the plugin health tail fields).

    Collected lazily so a process that never completed a timeline exports
    empty families at zero cost.
    """

    def collect(self):
        from prometheus_client.core import HistogramMetricFamily

        from escalator_tpu.observability import histograms

        phase_fam = HistogramMetricFamily(
            "escalator_tpu_tick_phase_hist_seconds",
            "per-phase device-fenced tick latency, fine log-bucket "
            "(base-1.25) streaming histogram — same completed-timeline feed "
            "as escalator_tpu_tick_phase_seconds, finer tail resolution",
            labels=["backend", "phase"],
        )
        for (backend, phase), h in histograms.PHASES.items():
            phase_fam.add_metric([backend, phase],
                                 buckets=[(ub, float(c))
                                          for ub, c in h.cumulative_buckets()],
                                 sum_value=h.sum_seconds)
        yield phase_fam
        tick_fam = HistogramMetricFamily(
            "escalator_tpu_tick_e2e_seconds",
            "end-to-end root tick latency by root timeline name, fine "
            "log-bucket streaming histogram (the tail watchdog's rolling-p99 "
            "population)",
            labels=["root"],
        )
        for (root,), h in histograms.TICKS.items():
            tick_fam.add_metric([root],
                                buckets=[(ub, float(c))
                                         for ub, c in h.cumulative_buckets()],
                                sum_value=h.sum_seconds)
        yield tick_fam
        stage_fam = HistogramMetricFamily(
            "escalator_tpu_fleet_stage_seconds",
            "per-request fleet journey stage latency by priority class "
            "(admission = queue wait, batch_assembly, dispatch = the fused "
            "device program, ordered_tail, unpack — the five sum to the "
            "request e2e; 'service' is the derived everything-after-queue "
            "series the health split reads), fine log-bucket streaming "
            "histogram fed from the scheduler's respond-side journeys",
            labels=["klass", "stage"],
        )
        for (klass, stage), h in histograms.STAGES.items():
            stage_fam.add_metric([klass, stage],
                                 buckets=[(ub, float(c))
                                          for ub, c in
                                          h.cumulative_buckets()],
                                 sum_value=h.sum_seconds)
        yield stage_fam


registry.register(_TailHistogramCollector())


# --- device resource observatory (round 15: HBM/arena accounting) ------------
class _DeviceResourceCollector:
    """Pull-time export of the buffer-accounting registry
    (observability/resources.py):

    - ``escalator_tpu_device_buffer_bytes{owner}`` — live bytes per
      registered owner of persistent device state (resident cluster,
      aggregates, decision/order columns, audit double buffer, fleet
      arenas). Collected at scrape time from array METADATA — no device
      sync, and retired owners (a dead decider) vanish instead of
      flatlining at their last value.
    - ``escalator_tpu_device_memory_bytes_in_use{device}`` /
      ``..._peak_bytes{device}`` — the runtime allocator's own view where
      ``memory_stats()`` reports (TPU runtimes that support it); series
      simply absent on runtimes that return nothing (this rig's CPU), per
      the explicit-"unsupported" degrade contract.
    """

    def collect(self):
        from prometheus_client.core import GaugeMetricFamily

        from escalator_tpu.observability import resources

        owner_fam = GaugeMetricFamily(
            "escalator_tpu_device_buffer_bytes",
            "live bytes of registered persistent device-state owners "
            "(buffer-accounting registry; metadata-derived, no device sync)",
            labels=["owner"],
        )
        try:
            for owner, row in sorted(resources.RESOURCES.snapshot().items()):
                owner_fam.add_metric([owner], float(row["nbytes"]))
        except Exception:  # noqa: BLE001 - a scrape must never crash
            pass
        yield owner_fam
        in_use = GaugeMetricFamily(
            "escalator_tpu_device_memory_bytes_in_use",
            "runtime allocator bytes_in_use per device (absent where "
            "memory_stats() is unsupported)",
            labels=["device"],
        )
        peak = GaugeMetricFamily(
            "escalator_tpu_device_memory_peak_bytes",
            "runtime allocator peak_bytes_in_use per device (absent where "
            "memory_stats() is unsupported)",
            labels=["device"],
        )
        try:
            mem = resources.device_memory()
            if "unsupported" not in mem:
                for dev, stats in sorted(mem.items()):
                    if "bytes_in_use" in stats:
                        in_use.add_metric([dev], float(stats["bytes_in_use"]))
                    if "peak_bytes_in_use" in stats:
                        peak.add_metric([dev],
                                        float(stats["peak_bytes_in_use"]))
        except Exception:  # noqa: BLE001
            pass
        yield in_use
        yield peak


registry.register(_DeviceResourceCollector())


# --- decision provenance (round 19: flap watchdog / explain observatory) -----
class _ProvenanceCollector:
    """Pull-time export of the flap watchdog's bounded hot list:

    - ``escalator_tpu_provenance_top_flapping{key,group}`` — cumulative
      flap incidents for the currently worst-oscillating (tenant, group)
      pairs, top-5 only (the full per-group distribution would be an
      unbounded label surface; the flight dumps carry the long tail).

    Collected from in-memory counters at scrape time — zero cost on the
    tick path, empty family on a flap-free process.
    """

    def collect(self):
        from prometheus_client.core import GaugeMetricFamily

        from escalator_tpu.observability import provenance

        fam = GaugeMetricFamily(
            "escalator_tpu_provenance_top_flapping",
            "cumulative flap incidents for the top-5 oscillating "
            "(history key, group) pairs (bounded label surface; dumps "
            "carry the rest)",
            labels=["key", "group"],
        )
        try:
            for row in provenance.FLAPS.top_flapping():
                fam.add_metric([str(row["key"]), str(row["group"])],
                               float(row["flaps"]))
        except Exception:  # noqa: BLE001 - a scrape must never crash
            pass
        yield fam


registry.register(_ProvenanceCollector())


def start(address: str = "0.0.0.0:8080", readiness=None) -> WSGIServer:
    """Serve /metrics on a background thread (reference: metrics.go:260-268),
    plus /healthz (process liveness: 200 whenever the server answers) and
    /readyz (200 only when the optional ``readiness`` callable returns
    ``(True, detail)``, else 503 with the detail — the reference's bare mux
    has neither, so its Deployment can't distinguish a live standby from a
    wedged leader). Returns the server (call .shutdown() to stop)."""
    host, _, port = address.rpartition(":")
    app = make_wsgi_app(registry)

    def route(environ, start_response):
        path = environ.get("PATH_INFO")
        if path == "/metrics":
            return app(environ, start_response)
        if path == "/healthz":
            start_response("200 OK", [("Content-Type", "text/plain")])
            return [b"ok"]
        if path == "/readyz":
            if readiness is None:
                ok, detail = True, "ok"
            else:
                try:
                    ok, detail = readiness()
                except Exception as e:  # a crashing check is "not ready"
                    ok, detail = False, f"readiness check failed: {e}"
            start_response("200 OK" if ok else "503 Service Unavailable",
                           [("Content-Type", "text/plain")])
            return [detail.encode()]
        start_response("404 Not Found", [("Content-Type", "text/plain")])
        return [b"not found"]

    server = make_server(host or "0.0.0.0", int(port), route,
                         server_class=_ThreadingWSGIServer)
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    return server
