"""CLI / process entry — mirror of /root/reference/cmd/main.go.

Same flag surface (loglevel, logfmt, address, scaninterval, nodegroups, drymode,
cloud-provider, leader-elect family), plus TPU-build additions: ``--backend`` selects
the compute backend (auto/jax/sharded-jax/golden) and ``--sim-state`` runs the
controller against an in-memory cluster loaded from YAML — the drivable surface when
no apiserver is present (and the framework's shadow-testing facility alongside
``--drymode``).

Sim-state YAML schema::

    nodes:
      - name: n1
        labels: {customer: buildeng}
        cpu_milli: 4000
        mem_bytes: 16000000000
        creation_time_ns: 0
        tainted_at: 1700000000   # optional -> escalator taint with this timestamp
        cordoned: false
    pods:
      - name: p1
        node_name: n1            # optional
        cpu_milli: 500
        mem_bytes: 1000000000
        node_selector: {customer: buildeng}
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import signal
import sys
import threading
import time
from typing import List, Optional

import yaml

from escalator_tpu import __version__
from escalator_tpu.controller import controller as ctl
from escalator_tpu.controller import node_group as ngmod
from escalator_tpu.controller.backend import make_backend
from escalator_tpu.k8s import types as k8s
from escalator_tpu.k8s.client import load_incluster, load_kubeconfig
from escalator_tpu.k8s.election import (
    FileResourceLock,
    LeaderElectionConfig,
    LeaderElector,
)
from escalator_tpu.metrics import metrics
from escalator_tpu.testsupport.cloud_provider import MockBuilder, MockCloudProvider, MockNodeGroup
from escalator_tpu.utils.tracing import TickTracer, start_profiler_server

log = logging.getLogger("escalator_tpu")


def debug_dump_main(argv: List[str]) -> int:
    """``escalator-tpu debug-dump``: pull the flight-recorder ring from a
    running compute plugin (the ``Dump`` RPC) and print/write it — the
    on-demand end of the tick flight recorder (docs/observability.md). The
    controller process itself dumps automatically on wedge/audit incidents;
    this subcommand is for a live look without waiting for one."""
    p = argparse.ArgumentParser(
        prog="escalator-tpu debug-dump",
        description="dump the flight recorder of a running compute plugin",
    )
    p.add_argument("--plugin-address", default="127.0.0.1:50551",
                   help="compute plugin address (same as --plugin-address"
                        " on the controller)")
    p.add_argument("--output", default="-",
                   help="file path for the JSON dump, or - for stdout")
    p.add_argument("--timeout", type=float, default=10.0)
    args = p.parse_args(argv)
    from escalator_tpu.plugin.client import ComputeClient

    client = ComputeClient(args.plugin_address, timeout_sec=args.timeout)
    try:
        doc = client.dump()
    finally:
        client.close()
    text = json.dumps(doc, indent=1)
    if args.output == "-":
        print(text)
    else:
        with open(args.output, "w") as f:
            f.write(text + "\n")
        print(f"flight record ({doc.get('depth', 0)} ticks) -> {args.output}")
    return 0


def debug_trace_main(argv: List[str]) -> int:
    """``escalator-tpu debug-trace``: render a flight-recorder dump (or a
    live plugin's ring over the ``Dump`` RPC) to Chrome trace-event /
    Perfetto JSON — open the output at https://ui.perfetto.dev or
    chrome://tracing. Nested phases become duration events, unfenced
    overlap dispatches sit on their own track, and a plugin-routed decide's
    grafted server spans render under the caller's rpc span, so one trace
    shows client + server (docs/observability.md, tail-latency section).
    Exit status: 0 on success, 2 when the dump cannot be read/fetched."""
    p = argparse.ArgumentParser(
        prog="escalator-tpu debug-trace",
        description="render a flight dump to Perfetto trace-event JSON",
    )
    src = p.add_mutually_exclusive_group(required=True)
    src.add_argument("--dump",
                     help="flight-recorder dump JSON (debug-dump output or"
                          " an incident/tail dump)")
    src.add_argument("--plugin-address",
                     help="fetch the live ring from a running compute"
                          " plugin instead of a file")
    p.add_argument("--output", default="-",
                   help="file path for the trace JSON, or - for stdout")
    p.add_argument("--timeout", type=float, default=10.0)
    args = p.parse_args(argv)
    from escalator_tpu.observability import traceexport

    if args.dump:
        try:
            with open(args.dump) as f:
                doc = json.load(f)
        except (OSError, ValueError) as e:
            print(f"cannot read dump: {e}", file=sys.stderr)
            return 2
    else:
        from escalator_tpu.plugin.client import ComputeClient

        client = ComputeClient(args.plugin_address, timeout_sec=args.timeout)
        try:
            doc = client.dump()
        except Exception as e:  # noqa: BLE001 - any transport failure: exit 2
            print(f"cannot fetch dump from {args.plugin_address}: {e}",
                  file=sys.stderr)
            return 2
        finally:
            client.close()
    trace = traceexport.trace_from_dump(doc)
    text = json.dumps(trace, indent=1)
    if args.output == "-":
        print(text)
    else:
        with open(args.output, "w") as f:
            f.write(text + "\n")
        slices = sum(1 for e in trace["traceEvents"] if e.get("ph") == "X")
        print(f"trace ({len(doc.get('ticks', []))} ticks, {slices} slices)"
              f" -> {args.output}")
    return 0


def debug_replay_main(argv: List[str]) -> int:
    """``escalator-tpu debug-replay``: re-execute a dumped flight-recorder
    ring OFFLINE, bit-exactly, against a device-state snapshot — the
    post-incident half of deterministic record/replay (docs/ha.md). The
    dump must carry recorded tick inputs (run the controller with
    ESCALATOR_TPU_RECORD_INPUTS=1), and the snapshot must be a checkpoint
    at or before the ring's first recorded tick (the cadence checkpoints
    from --snapshot-dir qualify). Exit status: 0 when every replayed tick
    reproduced its recorded crc32 decision digest, 1 on any divergence,
    2 when the bundle cannot be replayed at all."""
    p = argparse.ArgumentParser(
        prog="escalator-tpu debug-replay",
        description="re-execute a dumped tick ring bit-exactly offline",
    )
    p.add_argument("--dump", required=True,
                   help="flight-recorder dump JSON carrying tick_inputs "
                        "(debug-dump output, or an incident dump)")
    p.add_argument("--snapshot", required=True,
                   help="device-state snapshot file (.snap) at or before "
                        "the ring's first recorded tick")
    p.add_argument("--output", default="-",
                   help="file path for the JSON replay report, or - for"
                        " stdout")
    args = p.parse_args(argv)
    from escalator_tpu.observability import replay
    from escalator_tpu.ops.snapshot import SnapshotCorruptError

    try:
        with open(args.dump) as f:
            doc = json.load(f)
    except (OSError, ValueError) as e:
        # a missing/truncated dump is "bundle not replayable" (exit 2),
        # exactly like a corrupt snapshot below — never exit 1, which is
        # reserved for a tick that replayed and DIVERGED
        print(f"cannot read dump: {e}", file=sys.stderr)
        return 2
    entries = doc.get("tick_inputs")
    if not entries:
        print("dump carries no tick_inputs — record with "
              "ESCALATOR_TPU_RECORD_INPUTS=1 and re-dump", file=sys.stderr)
        return 2
    try:
        report = replay.replay_ring(entries, snapshot_path=args.snapshot)
    except (ValueError, OSError, SnapshotCorruptError) as e:
        # a corrupt snapshot / missing file / ring gap is "bundle not
        # replayable" (exit 2) — exit 1 is reserved for a tick that
        # replayed but DIVERGED, and the two must never be conflated
        print(f"replay failed: {e}", file=sys.stderr)
        return 2
    text = json.dumps(report, indent=1)
    if args.output == "-":
        print(text)
    else:
        with open(args.output, "w") as f:
            f.write(text + "\n")
        print(f"replay report ({report['replayed']} ticks, "
              f"{len(report['divergent'])} divergent) -> {args.output}")
    if not report["ok"]:
        print(f"DIVERGENCE: {len(report['divergent'])} of "
              f"{report['replayed']} replayed ticks did not reproduce their "
              "recorded digest", file=sys.stderr)
        return 1
    return 0


def debug_journal_main(argv: List[str]) -> int:
    """``escalator-tpu debug-journal``: print the ops event journal — the
    bounded ring of discrete operator events (tenant lifecycle, admission
    rejects, SLO burns, chaos firings, watchdog breaches) every flight dump
    embeds — from a dump file or a live plugin (the ``Journal`` RPC).
    "What happened around tick N" becomes one query instead of log
    archaeology: filter by kind (``--kind``), by sequence (``--since``),
    or take the last N (``--tail``). Exit status: 0 on success (an empty
    journal prints a note and still exits 0), 2 when the source cannot be
    read/fetched."""
    p = argparse.ArgumentParser(
        prog="escalator-tpu debug-journal",
        description="print the ops event journal of a dump or live plugin",
    )
    src = p.add_mutually_exclusive_group(required=True)
    src.add_argument("--dump",
                     help="flight-recorder dump JSON (debug-dump output or"
                          " an incident/tail dump) carrying a journal"
                          " section")
    src.add_argument("--plugin-address",
                     help="fetch the live journal from a running compute"
                          " plugin instead of a file")
    p.add_argument("--kind", action="append", default=None,
                   help="only events of these kinds — a comma-separated"
                        " list, repeatable (e.g. --kind"
                        " admission-reject,slo-breach --kind group-flap)")
    p.add_argument("--since", type=int, default=0,
                   help="only events with seq > SINCE")
    p.add_argument("--tail", type=int, default=0,
                   help="only the last N (after the other filters)")
    p.add_argument("--json", action="store_true",
                   help="emit the events as JSON instead of text lines")
    p.add_argument("--timeout", type=float, default=10.0)
    args = p.parse_args(argv)
    if args.dump:
        try:
            with open(args.dump) as f:
                dump_doc = json.load(f)
        except (OSError, ValueError) as e:
            print(f"cannot read dump: {e}", file=sys.stderr)
            return 2
        doc = dump_doc.get("journal") or {"events": [],
                                          "total_recorded": 0,
                                          "capacity": 0}
    else:
        from escalator_tpu.plugin.client import ComputeClient

        client = ComputeClient(args.plugin_address, timeout_sec=args.timeout)
        try:
            doc = client.journal(since_seq=args.since)
        except Exception as e:  # noqa: BLE001 - any transport failure: exit 2
            print(f"cannot fetch journal from {args.plugin_address}: {e}",
                  file=sys.stderr)
            return 2
        finally:
            client.close()
    all_events = doc.get("events") or []
    # the wrap note reads the UNfiltered ring: "events aged out" is a
    # property of the ring, not of whatever filter the operator applied
    wrapped_to = (all_events[0]["seq"] - 1
                  if all_events and all_events[0].get("seq", 1) > 1 else 0)
    events = all_events
    if args.since:
        events = [e for e in events if e.get("seq", 0) > args.since]
    if args.kind:
        # each --kind is a comma-separated list; blanks (trailing commas,
        # ",,") drop silently, a kind absent from the UNfiltered ring warns
        # — a typo'd kind must not read as "nothing happened"
        wanted = {k.strip() for spec in args.kind
                  for k in spec.split(",") if k.strip()}
        present = {e.get("kind") for e in all_events}
        unknown = sorted(wanted - present)
        if unknown:
            known = ", ".join(sorted(k for k in present if k)) or "(none)"
            print(f"warning: no events of kind(s) {', '.join(unknown)} in "
                  f"this journal (kinds present: {known})", file=sys.stderr)
        events = [e for e in events if e.get("kind") in wanted]
    if args.tail > 0:
        events = events[-args.tail:]
    if args.json:
        print(json.dumps({"capacity": doc.get("capacity"),
                          "total_recorded": doc.get("total_recorded"),
                          "events": events}, indent=1))
        return 0
    total = doc.get("total_recorded", 0)
    print(f"ops journal: {len(events)} event(s) shown, "
          f"{total} recorded lifetime (ring capacity "
          f"{doc.get('capacity', '?')})")
    if wrapped_to and not args.since:
        print(f"  (ring wrapped: events 1..{wrapped_to} aged out)")
    for ev in events:
        ts = time.strftime("%H:%M:%S",
                           time.localtime(ev.get("time_unix", 0)))
        rest = " ".join(
            f"{k}={ev[k]}" for k in sorted(ev)
            if k not in ("seq", "kind", "time_unix"))
        print(f"[{ev.get('seq', '?'):>5}] {ts} {ev.get('kind', '?'):<22}"
              f" {rest}".rstrip())
    return 0


def _parse_groups(spec: "str | None") -> "List[int] | None":
    """``--groups 0,3,7`` -> [0, 3, 7] (None passes through)."""
    if spec is None:
        return None
    return [int(g) for g in spec.split(",") if g.strip()]


def _render_explanations(docs: list) -> None:
    """Text rendering of per-group explanation documents (the
    debug-explain human surface; --json carries the full docs)."""
    for d in docs:
        mm = d.get("mismatches")
        flag = f"  ** MISMATCH ({len(mm)} field(s)) **" if mm else ""
        stale = " [stale: pending delta]" if d.get("stale") else ""
        delta = int(d.get("nodes_delta", 0))
        print(f"group {d['group']}: {d.get('status_name', d.get('status'))}"
              f" delta={delta:+d} branch={d.get('threshold_branch')}"
              f"/{d.get('status_branch')}{stale}{flag}")
        t = d.get("terms") or {}
        cfg = d.get("config") or {}
        if t:
            line = ", ".join(
                f"{k}={t[k]}" for k in (
                    "cpu_percent", "mem_percent", "max_percent",
                    "percentage_needed", "num_nodes", "num_untainted",
                    "num_tainted", "num_cordoned") if k in t)
            print(f"    terms: {line}")
        if cfg:
            line = ", ".join(
                f"{k.removeprefix('cfg_')}={cfg[k]}" for k in (
                    "cfg_scale_up_threshold", "cfg_taint_lower",
                    "cfg_taint_upper", "cfg_min_nodes", "cfg_max_nodes")
                if k in cfg)
            print(f"    config: {line}")
        gates = [k for k, v in (d.get("gates") or {}).items() if v]
        if gates:
            print(f"    gates: {', '.join(sorted(gates))}")
        if d.get("scale_down_candidates"):
            print("    scale-down candidates (node slots, oldest-first): "
                  f"{d['scale_down_candidates']}")
        for m in mm or ():
            print(f"    mismatch {m['field']}: explained={m['explained']}"
                  f" committed={m['committed']}")


def _load_explanation_docs(path: str, tenant: "str | None") -> list:
    """Explanation documents from any carrier debug-explain produces or a
    flight dump embeds: a bare doc list, a ``debug-explain --json`` /
    Explain-RPC response (``explanations`` list), a replay report, a
    dump's ``provenance.explanations`` map (keyed by tenant), or a
    ``reason="flap"`` dump's ``flap.explanations`` (the offending groups,
    as captured when the watchdog fired). Raises ValueError with a named
    reason when the file carries none."""
    with open(path) as f:
        doc = json.load(f)
    if isinstance(doc, list):
        return doc
    ex = doc.get("explanations")
    if ex is None and isinstance(doc.get("provenance"), dict):
        ex = doc["provenance"].get("explanations")
    if ex is None and isinstance(doc.get("flap"), dict):
        ex = doc["flap"].get("explanations")
    if isinstance(ex, list):
        return ex
    if isinstance(ex, dict) and ex:
        if tenant is not None:
            if tenant not in ex:
                raise ValueError(
                    f"no explanations for tenant {tenant!r} in {path}"
                    f" (has: {', '.join(sorted(ex))})")
            return ex[tenant]
        if len(ex) == 1:
            return next(iter(ex.values()))
        raise ValueError(
            f"{path} carries explanations for several tenants"
            f" ({', '.join(sorted(ex))}) — pass --tenant")
    raise ValueError(f"{path} carries no explanation documents")


def debug_explain_main(argv: List[str]) -> int:
    """``escalator-tpu debug-explain``: WHY did this group scale — the
    decision provenance observatory's operator end (docs/observability.md).
    Prints per-group explanation documents: every named term of the
    decision calculus, the ONE controller.go:332-351 threshold arm that
    fired, the status-cascade arm, gate booleans, config echoes, scale-down
    victim candidates, and the bit-cross-check against the committed
    decision columns (a mismatch is itself a finding).

    Three sources:

    - ``--plugin-address [--tenant T]``: live, over the ``Explain`` RPC —
      re-derived from the server's resident arenas. Without ``--tenant``
      the known history keys + provenance health print (discovery).
    - ``--dump FILE``: the ``provenance`` section an incident/tail dump
      embeds (explanations as captured at dump time).
    - ``--replay --dump FILE --snapshot SNAP``: offline — re-execute the
      dump's recorded tick ring bit-exactly from the snapshot
      (debug-replay's machinery) and explain the FINAL state; the same
      answer the live server would have given at that tick.

    Exit status: 0 clean, 1 when any explanation carries a cross-check
    mismatch or the replay diverged, 2 when the source cannot be
    read/fetched."""
    p = argparse.ArgumentParser(
        prog="escalator-tpu debug-explain",
        description="explain a tenant's scale decisions term by term",
    )
    src = p.add_mutually_exclusive_group(required=True)
    src.add_argument("--plugin-address",
                     help="live source: a running compute plugin's Explain"
                          " RPC")
    src.add_argument("--dump",
                     help="offline source: a flight dump's provenance"
                          " section (with --replay: its recorded tick"
                          " ring)")
    p.add_argument("--replay", action="store_true",
                   help="re-execute the dump's tick_inputs from --snapshot"
                        " and explain the final replayed state")
    p.add_argument("--snapshot",
                   help="device-state snapshot (.snap) for --replay")
    p.add_argument("--tenant",
                   help="tenant id / history key (live: omit to list known"
                        " keys)")
    p.add_argument("--groups",
                   help="comma-separated group indices (default: all)")
    p.add_argument("--json", action="store_true",
                   help="emit the full documents as JSON instead of text")
    p.add_argument("--timeout", type=float, default=10.0)
    args = p.parse_args(argv)
    groups = _parse_groups(args.groups)

    if args.replay:
        if not args.dump or not args.snapshot:
            print("--replay needs both --dump and --snapshot",
                  file=sys.stderr)
            return 2
        from escalator_tpu.observability import replay
        from escalator_tpu.ops.snapshot import SnapshotCorruptError

        try:
            with open(args.dump) as f:
                doc = json.load(f)
        except (OSError, ValueError) as e:
            print(f"cannot read dump: {e}", file=sys.stderr)
            return 2
        entries = doc.get("tick_inputs")
        if not entries:
            print("dump carries no tick_inputs — record with "
                  "ESCALATOR_TPU_RECORD_INPUTS=1 and re-dump",
                  file=sys.stderr)
            return 2
        try:
            report = replay.replay_ring(
                entries, snapshot_path=args.snapshot,
                explain=True, explain_groups=groups)
        except (ValueError, OSError, SnapshotCorruptError) as e:
            print(f"replay failed: {e}", file=sys.stderr)
            return 2
        docs = report["explanations"]
        if args.json:
            print(json.dumps(report, indent=1))
        else:
            print(f"replayed {report['replayed']} tick(s) from tick "
                  f"{report['base_tick']} "
                  f"({len(report['divergent'])} divergent); explaining "
                  f"tick {report['explain_tick']}:")
            _render_explanations(docs)
        bad = (not report["ok"]
               or any(d.get("mismatches") for d in docs))
        return 1 if bad else 0

    if args.dump:
        try:
            docs = _load_explanation_docs(args.dump, args.tenant)
        except (OSError, ValueError) as e:
            print(f"cannot read explanations: {e}", file=sys.stderr)
            return 2
        if groups is not None:
            docs = [d for d in docs if d.get("group") in set(groups)]
        if args.json:
            print(json.dumps({"explanations": docs}, indent=1))
        else:
            _render_explanations(docs)
        return 1 if any(d.get("mismatches") for d in docs) else 0

    from escalator_tpu.plugin.client import ComputeClient

    client = ComputeClient(args.plugin_address, timeout_sec=args.timeout)
    try:
        doc = client.explain(args.tenant, groups=groups)
    except Exception as e:  # noqa: BLE001 - any transport failure: exit 2
        print(f"cannot fetch explanation from {args.plugin_address}: {e}",
              file=sys.stderr)
        return 2
    finally:
        client.close()
    if args.json:
        print(json.dumps(doc, indent=1))
        if args.tenant is None:
            return 0
        docs = doc.get("explanations") or []
        return 1 if any(d.get("mismatches") for d in docs) else 0
    if args.tenant is None:
        keys = doc.get("keys") or []
        health = doc.get("health") or {}
        print(f"decision history keys ({len(keys)}): "
              f"{', '.join(keys) or '(none yet)'}")
        mm_total = health.get("explain_mismatches_total", 0)
        print(f"flaps={health.get('flaps_total', 0)} "
              f"flap_dumps={health.get('flap_dumps', 0)} "
              f"explain_mismatches={mm_total}")
        for row in health.get("top_flapping") or []:
            print(f"  flapping: {row['key']} group {row['group']}: "
                  f"{row['flaps']} flap(s)")
        return 0
    docs = doc.get("explanations") or []
    print(f"tenant {doc.get('key')}: {len(docs)} group(s)")
    _render_explanations(docs)
    hist = doc.get("history") or []
    if hist:
        recent = hist[-8:]
        print(f"history ({len(hist)} tick(s), last {len(recent)}):")
        for h in recent:
            print(f"  tick {h['tick']}: status={h['status']}"
                  f" delta={h['nodes_delta']}")
    for fl in doc.get("flaps") or []:
        print(f"  flap: group {fl.get('group')} klass={fl.get('klass')}"
              f" at tick {fl.get('tick')}")
    return 1 if any(d.get("mismatches") for d in docs) else 0


def debug_decision_diff_main(argv: List[str]) -> int:
    """``escalator-tpu debug-decision-diff``: decision forensics between
    TWO explanation snapshots of the same tenant — which groups' decisions
    changed, and what moved them, attributed term by term ("max_percent
    crossed taint_upper (82.1 -> 91.4, threshold 90.0)"). Each side is any
    explanation carrier: ``debug-explain --json`` output, a flight dump
    with a provenance section, or a ``--replay`` report. Exit status: like
    diff(1) — 0 when no group's decision changed, 1 when changes were
    found, 2 when a source cannot be read."""
    p = argparse.ArgumentParser(
        prog="escalator-tpu debug-decision-diff",
        description="attribute decision changes between two explanation "
                    "snapshots term by term",
    )
    p.add_argument("a", help="explanation carrier A (JSON file)")
    p.add_argument("b", help="explanation carrier B (JSON file)")
    p.add_argument("--tenant",
                   help="tenant id when a carrier holds several tenants'"
                        " explanations")
    p.add_argument("--json", action="store_true",
                   help="emit the diff document as JSON instead of text")
    args = p.parse_args(argv)
    from escalator_tpu.observability import provenance

    try:
        da = _load_explanation_docs(args.a, args.tenant)
        db = _load_explanation_docs(args.b, args.tenant)
    except (OSError, ValueError) as e:
        print(f"cannot read explanations: {e}", file=sys.stderr)
        return 2
    res = provenance.diff_explanations(da, db)
    if args.json:
        print(json.dumps(res, indent=1))
        return 1 if res["changed"] else 0
    changed = res["changed"]
    print(f"decision diff: {len(changed)} group(s) changed, "
          f"{res['unchanged_groups']} unchanged"
          + (f", only in A: {res['only_in_a']}" if res["only_in_a"] else "")
          + (f", only in B: {res['only_in_b']}" if res["only_in_b"] else ""))
    for ch in changed:
        sa, sb = ch["status"]
        na, nb = ch["nodes_delta"]
        ba, bb = ch["threshold_branch"]
        print(f"group {ch['group']}: {sa} -> {sb}, delta {na:+d} -> {nb:+d}"
              f" (branch {ba} -> {bb})")
        for note in ch["attribution"]:
            print(f"    because: {note}")
        for term, (va, vb) in sorted(ch["term_deltas"].items()):
            print(f"    term {term}: {va} -> {vb}")
    return 1 if changed else 0


def debug_compiles_main(argv: List[str]) -> int:
    """``escalator-tpu debug-compiles``: the compile observatory's operator
    end — print the recent-compile ring from a flight dump (or a live
    plugin via the ``Dump`` RPC), grouped by attributed jaxlint registry
    entry, with each entry's retrace pin and a BUST flag where the observed
    count exceeds it. A warm steady-state process shows an empty ring; a
    populated one names which entry retraced, under which tick phase —
    the runtime answer to "what is the device compiling and why".
    Exit status: 0 on success, 2 when the dump cannot be read/fetched."""
    p = argparse.ArgumentParser(
        prog="escalator-tpu debug-compiles",
        description="attribute recent XLA compiles against the jaxlint "
                    "retrace pins",
    )
    src = p.add_mutually_exclusive_group(required=True)
    src.add_argument("--dump",
                     help="flight-recorder dump JSON (debug-dump output or"
                          " an incident/tail dump)")
    src.add_argument("--plugin-address",
                     help="fetch the live ring from a running compute"
                          " plugin instead of a file")
    p.add_argument("--json", action="store_true",
                   help="emit the attribution rows as JSON instead of text")
    p.add_argument("--timeout", type=float, default=10.0)
    args = p.parse_args(argv)
    from escalator_tpu.observability import jaxmon

    if args.dump:
        try:
            with open(args.dump) as f:
                doc = json.load(f)
        except (OSError, ValueError) as e:
            print(f"cannot read dump: {e}", file=sys.stderr)
            return 2
    else:
        from escalator_tpu.plugin.client import ComputeClient

        client = ComputeClient(args.plugin_address, timeout_sec=args.timeout)
        try:
            doc = client.dump()
        except Exception as e:  # noqa: BLE001 - any transport failure: exit 2
            print(f"cannot fetch dump from {args.plugin_address}: {e}",
                  file=sys.stderr)
            return 2
        finally:
            client.close()
    ring = doc.get("compiles") or []
    rows = jaxmon.attribute_compiles(ring)
    mon = doc.get("jaxmon") or {}
    if args.json:
        print(json.dumps({"jaxmon": mon, "attribution": rows,
                          "ring": ring}, indent=1))
        return 0
    print(f"compiles (lifetime): {int(mon.get('compile_events', 0))} "
          f"({mon.get('compile_seconds', 0.0):.3f}s); "
          f"ring holds {len(ring)} recent")
    if not rows:
        print("ring empty — no recent compiles (warm steady state)")
        return 0
    for row in rows:
        pin = row.get("retrace_budget")
        flag = " BUST" if row.get("bust") else ""
        pin_txt = f" pin={pin}{flag}" if pin is not None else ""
        print(f"- {row['key']}: {row['count']} compile(s), "
              f"{row['total_sec']:.3f}s{pin_txt}")
        for path in row["paths"]:
            print(f"    under: {path}")
    return 0


def debug_profile_main(argv: List[str]) -> int:
    """``escalator-tpu debug-profile``: capture a jax profiler trace of a
    running compute plugin's next K decides (the ``Profile`` RPC) and
    write the TensorBoard/XPlane artifact locally — the profiler-native
    sibling of ``debug-trace``'s Perfetto export, and the way ROADMAP item
    3's TPU campaign gets an on-chip profile of the programs it times.
    Load the output with ``tensorboard --logdir <output>`` (or drop the
    ``.trace.json.gz`` into Perfetto). Exit status: 0 on success, 2 when
    the capture cannot run (unreachable/pre-round-15 plugin, platform
    without the profiler)."""
    p = argparse.ArgumentParser(
        prog="escalator-tpu debug-profile",
        description="capture a jax profiler trace of a running plugin's "
                    "next K ticks",
    )
    p.add_argument("--plugin-address", default="127.0.0.1:50551",
                   help="compute plugin address (same as --plugin-address"
                        " on the controller)")
    p.add_argument("--ticks", type=int, default=4,
                   help="root ticks to wrap the trace around")
    p.add_argument("--output", default="escalator-tpu-profile",
                   help="directory for the trace files (created)")
    p.add_argument("--timeout", type=float, default=60.0,
                   help="capture window bound in seconds — on expiry the "
                        "partial trace still ships")
    args = p.parse_args(argv)
    from escalator_tpu.plugin.client import ComputeClient

    client = ComputeClient(args.plugin_address, timeout_sec=10.0)
    try:
        res = client.profile(ticks=args.ticks, timeout_sec=args.timeout)
    except Exception as e:  # noqa: BLE001 - transport/UNIMPLEMENTED: exit 2
        print(f"cannot profile {args.plugin_address}: {e}", file=sys.stderr)
        return 2
    finally:
        client.close()
    if not res.get("ok"):
        reason = (res.get("unsupported") or
                  ("a capture is already in flight" if res.get("busy")
                   else "unknown"))
        print(f"profiler capture unavailable: {reason}", file=sys.stderr)
        return 2
    files = res.get("files") or {}
    out_root = os.path.abspath(args.output)
    for rel, blob in files.items():
        # the server controls these names: confine every write to the
        # output directory (a hostile peer sending "../../..." paths must
        # not overwrite operator files)
        path = os.path.abspath(os.path.join(out_root, rel))
        if not path.startswith(out_root + os.sep):
            print(f"skipping unsafe path from server: {rel!r}",
                  file=sys.stderr)
            continue
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        with open(path, "wb") as f:
            f.write(blob)
    note = " (timed out: partial capture)" if res.get("timed_out") else ""
    print(f"profiler trace: {res.get('ticks_captured', 0)} tick(s), "
          f"{len(files)} file(s), {res.get('total_bytes', 0)} bytes -> "
          f"{args.output}{note}")
    print(f"view with: tensorboard --logdir {args.output}")
    return 0 if files else 2


def debug_partitions_main(argv: List[str]) -> int:
    """``escalator-tpu debug-partitions``: the scale-out operator view — a
    throwaway :class:`PartitionRouter` over the named partitions renders the
    aggregated ``health()`` doc (per-partition fleet health, breaker state,
    tenant placement, override pins) as a table or JSON. Read-only: the
    router here never routes a decide, so breakers stay closed and nothing
    is migrated. Exit status: 0 when every partition answered, 2 when any
    is unreachable (its row says so)."""
    p = argparse.ArgumentParser(
        prog="escalator-tpu debug-partitions",
        description="render aggregated health across fleet partitions",
    )
    p.add_argument("--partition", action="append", required=True,
                   metavar="NAME=ADDR", dest="partitions",
                   help="a partition as name=host:port (repeatable)")
    p.add_argument("--json", action="store_true",
                   help="emit the full aggregated health doc as JSON")
    p.add_argument("--timeout", type=float, default=10.0)
    args = p.parse_args(argv)
    spec = {}
    for item in args.partitions:
        name, sep, addr = item.partition("=")
        if not sep or not name or not addr:
            print(f"bad --partition {item!r}: expected NAME=ADDR",
                  file=sys.stderr)
            return 2
        spec[name] = addr
    from escalator_tpu.fleet.router import PartitionRouter

    router = PartitionRouter(spec, timeout_sec=args.timeout)
    try:
        doc = router.health()
    finally:
        router.close()
    if args.json:
        print(json.dumps(doc, indent=1, default=str))
        return 0 if doc.get("ok") else 2
    parts = doc.get("partitions", {})
    rows = []
    for name in sorted(parts):
        pdoc = parts[name]
        if not pdoc.get("ok", True):
            rows.append((name, spec.get(name, "?"), "UNREACHABLE",
                         "-", "-", str(pdoc.get("error", ""))[:48]))
            continue
        fleet = pdoc.get("fleet") or {}
        classes = fleet.get("classes") or {}
        burn = max((float(c.get("slo_burn", 0.0) or 0.0)
                    for c in classes.values()), default=0.0)
        rows.append((name, spec.get(name, "?"), "ok",
                     str(fleet.get("tenants", pdoc.get("tenants", "?"))),
                     str(fleet.get("queue_depth",
                                   pdoc.get("queue_depth", "?"))),
                     f"burn={burn:.2f}"))
    widths = [max(len(r[i]) for r in rows + [
        ("PARTITION", "ADDRESS", "STATE", "TENANTS", "QUEUE", "NOTES")])
        for i in range(6)]
    header = ("PARTITION", "ADDRESS", "STATE", "TENANTS", "QUEUE", "NOTES")
    for row in [header] + rows:
        print("  ".join(c.ljust(w) for c, w in zip(row, widths)).rstrip())
    agg = doc.get("aggregate") or {}
    print(f"\naggregate: {agg.get('partitions', len(parts))} partition(s), "
          f"{agg.get('tenants', '?')} tenant(s), "
          f"queue_depth={agg.get('queue_depth', '?')}; "
          f"down={doc.get('down') or []}; "
          f"overrides={len(doc.get('overrides') or {})}")
    return 0 if doc.get("ok") else 2


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="escalator-tpu",
        description="TPU-native batch-optimized cluster autoscaler",
    )
    p.add_argument("--loglevel", default="info",
                   choices=["debug", "info", "warn", "error"],
                   help="log level (reference: cmd/main.go:30)")
    p.add_argument("--logfmt", default="ascii", choices=["ascii", "json"],
                   help="log format")
    p.add_argument("--address", default=":8080",
                   help="address:port for the /metrics endpoint")
    p.add_argument("--scaninterval", default="60s",
                   help="how often the cluster is reevaluated")
    p.add_argument("--nodegroups", required=True,
                   help="path to the nodegroups YAML config")
    p.add_argument("--drymode", action="store_true",
                   help="skip all mutations, track taints in memory")
    p.add_argument("--aws-assume-role-arn", default="",
                   help="AWS role arn to assume at startup (aws provider only,"
                        " reference: cmd/main.go:38)")
    p.add_argument("--aws-region", default="",
                   help="AWS region override (defaults to the SDK chain)")
    p.add_argument("--cloud-provider", default="sim", choices=["sim", "aws"],
                   help="cloud provider backend")
    p.add_argument("--kubeconfig", default="",
                   help="kubeconfig path (out-of-cluster mode)")
    p.add_argument("--incluster", action="store_true",
                   help="connect to the apiserver from inside the cluster"
                        " (serviceaccount token; reference: cmd/main.go:62-66)")
    p.add_argument("--sim-state", default="",
                   help="YAML cluster state for in-memory simulation mode")
    p.add_argument("--backend", default="auto",
                   choices=["auto", "jax", "incremental-jax", "sharded-jax",
                            "grid-jax", "podaxis-jax", "golden", "native",
                            "grpc"],
                   help="compute backend for the scale decision (native ="
                        " event-driven C++ state store + jax kernel, add"
                        " ESCALATOR_TPU_INCREMENTAL_DECIDE=1 for the"
                        " delta-maintained decide; incremental-jax = repack"
                        " backend with host-diffed O(churn) device work;"
                        " grpc = remote compute plugin; podaxis-jax ="
                        " pod-axis sharding for one dominant giant group;"
                        " grid-jax = 2-D groups x pods mesh for few huge"
                        " groups)")
    p.add_argument("--plugin-address", default="127.0.0.1:50551",
                   help="compute plugin address for --backend grpc")
    p.add_argument("--snapshot-dir", default="",
                   help="directory for rolling device-state checkpoints; a"
                        " restarted/promoted controller warm-starts from the"
                        " latest one (incremental backends; docs/ha.md)")
    p.add_argument("--snapshot-every", type=int, default=64,
                   help="checkpoint cadence in ticks for --snapshot-dir")
    p.add_argument("--once", action="store_true",
                   help="run a single tick and exit (prints per-group deltas)")
    p.add_argument("--profile-dir", default="",
                   help="capture an XLA profiler trace of the first ticks to this"
                        " directory (TensorBoard-loadable)")
    p.add_argument("--profile-ticks", type=int, default=5,
                   help="number of ticks to include in the profiler trace")
    p.add_argument("--profiler-port", type=int, default=0,
                   help="start the live jax profiler server on this port")
    p.add_argument("--tick-watchdog", dest="tick_watchdog",
                   action=argparse.BooleanOptionalAction, default=True,
                   help="exit when ticks stall far past the scan interval "
                        "(a wedged leader must crash-to-restart so its "
                        "Lease lapses and a standby promotes; readiness "
                        "alone cannot fail over a controller)")
    p.add_argument("--leader-elect", action="store_true")
    p.add_argument("--leader-elect-lock-file", default="/tmp/escalator-tpu.lease",
                   help="lease file for sim/file election (apiserver-backed"
                        " clients elect over a k8s Lease instead)")
    p.add_argument("--leader-elect-lease-namespace", default="kube-system",
                   help="namespace of the election Lease object")
    p.add_argument("--leader-elect-lease-name", default="escalator-tpu",
                   help="name of the election Lease object")
    p.add_argument("--leader-elect-lease-duration", default="15s")
    p.add_argument("--leader-elect-renew-deadline", default="10s")
    p.add_argument("--leader-elect-retry-period", default="2s")
    p.add_argument("--version", action="version", version=__version__)
    return p


def setup_logging(level: str, fmt: str) -> None:
    lvl = {"debug": logging.DEBUG, "info": logging.INFO,
           "warn": logging.WARNING, "error": logging.ERROR}[level]
    if fmt == "json":
        handler = logging.StreamHandler()

        class JsonFormatter(logging.Formatter):
            def format(self, record):
                return json.dumps({
                    "level": record.levelname.lower(),
                    "msg": record.getMessage(),
                    "logger": record.name,
                    "time": self.formatTime(record),
                })

        handler.setFormatter(JsonFormatter())
        logging.basicConfig(level=lvl, handlers=[handler])
    else:
        logging.basicConfig(
            level=lvl,
            format="%(asctime)s %(levelname)s %(name)s: %(message)s",
        )


def setup_node_groups(path: str) -> List[ngmod.NodeGroupOptions]:
    """Load + validate, fail-fast on problems (reference: cmd/main.go:94-121)."""
    with open(path) as f:
        node_groups = ngmod.unmarshal_node_group_options(f)
    for ng in node_groups:
        problems = ngmod.validate_node_group(ng)
        if problems:
            for problem in problems:
                log.error("nodegroup %r: %s", ng.name, problem)
            raise SystemExit(
                f"nodegroup {ng.name!r} failed validation with "
                f"{len(problems)} problem(s)"
            )
        log.info("valid nodegroup: %s", ng.name)
    if not node_groups:
        raise SystemExit("no nodegroups defined in config")
    return node_groups


def load_sim_state(path: str) -> "EventfulClient":
    from escalator_tpu.k8s.cache import EventfulClient

    with open(path) as f:
        doc = yaml.safe_load(f) or {}
    nodes = []
    for spec in doc.get("nodes", []) or []:
        taints = []
        if spec.get("tainted_at") is not None:
            taints.append(k8s.Taint(
                key=k8s.TO_BE_REMOVED_BY_AUTOSCALER_KEY,
                value=str(int(spec["tainted_at"])),
            ))
        nodes.append(k8s.Node(
            name=spec["name"],
            labels=dict(spec.get("labels", {})),
            annotations=dict(spec.get("annotations", {})),
            cpu_allocatable_milli=int(spec.get("cpu_milli", 0)),
            mem_allocatable_bytes=int(spec.get("mem_bytes", 0)),
            creation_time_ns=int(spec.get("creation_time_ns", 0)),
            unschedulable=bool(spec.get("cordoned", False)),
            taints=taints,
            provider_id=spec.get("provider_id", spec["name"]),
        ))
    pods = []
    for spec in doc.get("pods", []) or []:
        pods.append(k8s.Pod(
            name=spec["name"],
            namespace=spec.get("namespace", "default"),
            node_name=spec.get("node_name", ""),
            containers=[k8s.ResourceRequests(
                cpu_milli=int(spec.get("cpu_milli", 0)),
                mem_bytes=int(spec.get("mem_bytes", 0)),
            )],
            node_selector=dict(spec.get("node_selector", {})),
            owner_kind=spec.get("owner_kind", ""),
        ))
    return EventfulClient(nodes=nodes, pods=pods)


def setup_cloud_provider(args, node_groups, client) -> MockBuilder:
    """Reference: cmd/main.go:68-91. The sim provider mirrors current cluster
    state; AWS requires its SDK (gated)."""
    if args.cloud_provider == "aws":
        from escalator_tpu.cloudprovider.aws.builder import AWSBuilder

        return AWSBuilder(
            node_groups,
            region=args.aws_region,
            assume_role_arn=args.aws_assume_role_arn,
        )
    provider = MockCloudProvider()
    for ng in node_groups:
        group_nodes = [
            n for n in client.list_nodes()
            if n.labels.get(ng.label_key) == ng.label_value
        ]
        provider.register_node_group(MockNodeGroup(
            ng.cloud_provider_group_name, ng.name,
            min_size=ng.min_nodes, max_size=max(ng.max_nodes, len(group_nodes)),
            target_size=len(group_nodes),
        ))
    return MockBuilder(provider)


def main(argv: Optional[List[str]] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    # subcommand dispatch ahead of the flag parser (the controller surface
    # keeps its reference-mirroring flags-only shape; debug tooling hangs off
    # a leading verb)
    if argv and argv[0] == "debug-dump":
        return debug_dump_main(argv[1:])
    if argv and argv[0] == "debug-trace":
        return debug_trace_main(argv[1:])
    if argv and argv[0] == "debug-replay":
        return debug_replay_main(argv[1:])
    if argv and argv[0] == "debug-journal":
        return debug_journal_main(argv[1:])
    if argv and argv[0] == "debug-explain":
        return debug_explain_main(argv[1:])
    if argv and argv[0] == "debug-decision-diff":
        return debug_decision_diff_main(argv[1:])
    if argv and argv[0] == "debug-compiles":
        return debug_compiles_main(argv[1:])
    if argv and argv[0] == "debug-profile":
        return debug_profile_main(argv[1:])
    if argv and argv[0] == "debug-partitions":
        return debug_partitions_main(argv[1:])
    args = build_parser().parse_args(argv)
    setup_logging(args.loglevel, args.logfmt)

    if args.snapshot_dir:
        # the env pair is how backends (constructed behind make_backend's
        # parameterless kinds) discover the checkpoint config; the native
        # path below also receives it explicitly
        os.environ["ESCALATOR_TPU_SNAPSHOT_DIR"] = args.snapshot_dir
        os.environ["ESCALATOR_TPU_SNAPSHOT_EVERY"] = str(args.snapshot_every)

    node_groups = setup_node_groups(args.nodegroups)

    if args.sim_state:
        client = load_sim_state(args.sim_state)
    elif args.kubeconfig:
        client = load_kubeconfig(args.kubeconfig)
        log.info("connected to apiserver via kubeconfig; informer caches synced")
    elif args.incluster or args.cloud_provider == "aws":
        client = load_incluster()
        log.info("connected to in-cluster apiserver; informer caches synced")
    else:
        raise SystemExit(
            "no cluster source: pass --sim-state for simulation mode,"
            " --kubeconfig for out-of-cluster, or --incluster"
        )

    builder = setup_cloud_provider(args, node_groups, client)

    server = None
    controller_ref: dict = {}
    if not args.once:
        host, _, port = args.address.rpartition(":")

        def _stale_limit(c):
            """Single source of the tick-staleness policy: readiness fails
            at this age; the watchdog exits at twice it."""
            return 3 * c.opts.scan_interval_sec + 60

        def _tick_age():
            """Seconds since the last completed tick; -1 before the first
            (or while awaiting leadership). The single freshness source for
            both /readyz and the exported gauge."""
            c = controller_ref.get("controller")
            if c is None or c.last_tick_completed_sec is None:
                return -1.0
            return c.clock.now() - c.last_tick_completed_sec

        def _readiness():
            """k8s readiness: not-ready while awaiting leadership (the
            controller isn't constructed yet on standbys) and when ticks go
            stale — a wedged device dispatch or stuck provider call stops
            run_once from completing, which is exactly what should pull a
            replica out of rotation. Liveness (/healthz) stays green either
            way: standbys and wedged-but-recovering leaders must not be
            restarted by the kubelet."""
            c = controller_ref.get("controller")
            age = _tick_age()
            if age < 0:
                return False, ("no tick completed yet" if c is not None
                               else "awaiting leadership / controller not started")
            limit = _stale_limit(c)
            if age > limit:
                return False, f"last tick {age:.0f}s ago (limit {limit:.0f}s)"
            return True, f"ok (last tick {age:.0f}s ago)"

        metrics.last_tick_age_seconds.set_function(_tick_age)
        server = metrics.start(f"{host or '0.0.0.0'}:{port}",
                               readiness=_readiness)
        log.info("metrics listening on %s", args.address)

    stop_event = threading.Event()

    def on_signal(signum, frame):
        log.info("signal received, stopping")
        stop_event.set()

    try:
        signal.signal(signal.SIGINT, on_signal)
        signal.signal(signal.SIGTERM, on_signal)
    except ValueError:
        pass  # not the main thread (tests)

    elector = None
    if args.leader_elect:
        deposed = threading.Event()
        # apiserver-backed clients elect over a real k8s Lease
        # (reference: pkg/k8s/election.go:57-76); sim mode uses the file lock
        from escalator_tpu.k8s.restclient import ApiserverClient, LeaseResourceLock

        if isinstance(client, ApiserverClient):
            resource_lock = LeaseResourceLock(
                client.transport,
                namespace=args.leader_elect_lease_namespace,
                name=args.leader_elect_lease_name,
                lease_duration_sec=ngmod.parse_duration(
                    args.leader_elect_lease_duration),
            )
        else:
            resource_lock = FileResourceLock(args.leader_elect_lock_file)
        elector = LeaderElector(
            resource_lock,
            LeaderElectionConfig(
                lease_duration_sec=ngmod.parse_duration(
                    args.leader_elect_lease_duration),
                renew_deadline_sec=ngmod.parse_duration(
                    args.leader_elect_renew_deadline),
                retry_period_sec=ngmod.parse_duration(
                    args.leader_elect_retry_period),
            ),
            # the Deployment sets POD_NAME via the downward API so the Lease
            # holder is readable as "which replica leads" (the reference uses
            # the pod hostname the same way, cmd/main.go:163); fall back to
            # the pid-uuid identity outside k8s
            identity=os.environ.get("POD_NAME") or None,
            on_deposed=deposed.set,
        )
        def _election_event(reason: str, message: str) -> None:
            """Election activity into the cluster event stream, like the
            reference's election broadcaster (cmd/main.go:166-170). Dry mode
            records nothing — shadow runs leave no trace in the cluster."""
            create = getattr(client, "create_event", None)
            if create is None or args.drymode:
                return
            try:
                create(k8s.Event(
                    reason=reason, message=message,
                    involved_kind="Lease",
                    involved_name=args.leader_elect_lease_name,
                    namespace=args.leader_elect_lease_namespace,
                    timestamp_sec=int(time.time()),
                ))
            except Exception as e:
                log.warning("failed to record election event: %s", e)

        log.info("awaiting leadership (%s)", elector.identity)
        if not elector.run():
            return 1
        log.info("became leader")
        _election_event(
            "LeaderElected", f"{elector.identity} became leader"
        )

        def watch_deposed():
            deposed.wait()
            # crash-to-restart HA (reference: cmd/main.go:147-154)
            log.critical("lost leadership lease; exiting")
            _election_event(
                "LeaderDeposed", f"{elector.identity} lost the leadership lease"
            )
            stop_event.set()

        threading.Thread(target=watch_deposed, daemon=True).start()

    if args.backend == "native":
        # a wedged accelerator transport must degrade to XLA-CPU, not hang
        # the control loop at the first dispatch (same kernels, same
        # decisions). The make_backend kinds probe inside make_backend;
        # native is constructed directly here, so it probes here. grpc needs
        # no probe: its heavy compute is remote, and the only local jax use
        # (the packing post-pass) runs fine on whatever answers later.
        from escalator_tpu.jaxconfig import ensure_responsive_accelerator

        ensure_responsive_accelerator()
        from escalator_tpu.controller.native_backend import make_native_backend

        backend = make_native_backend(
            client, node_groups,
            snapshot_dir=args.snapshot_dir or None,
            snapshot_every=args.snapshot_every)
    elif args.backend == "grpc":
        from escalator_tpu.plugin.client import GrpcBackend

        backend = GrpcBackend(args.plugin_address)
    else:
        backend = make_backend(args.backend)

    if args.profiler_port:
        start_profiler_server(args.profiler_port)

    tracer = TickTracer(args.profile_dir or None, args.profile_ticks)
    controller = ctl.Controller(
        ctl.Opts(
            client=client,
            node_groups=node_groups,
            cloud_provider_builder=builder,
            scan_interval_sec=ngmod.parse_duration(args.scaninterval) or 60.0,
            dry_mode=args.drymode,
            backend=backend,
            tracer=tracer,
        ),
        stop_event=stop_event,
    )
    controller_ref["controller"] = controller

    if not args.once and args.tick_watchdog:
        # A wedged tick (hung provider call, wedged device dispatch) leaves
        # lease renewal healthy on its own thread: standbys never promote and
        # /readyz 503 has no operational effect on a controller that serves
        # no traffic. Crash-to-restart is the remediation, same as the
        # deposed path (reference: cmd/main.go:147-154) — the restart clears
        # the wedge or hands leadership to a standby.
        # env override is for tests/ops tuning; the default keeps the limit
        # far above any healthy inter-tick gap (2x the /readyz staleness
        # limit, so readiness always fires first)
        exit_limit = (float(os.environ.get(
            "ESCALATOR_TPU_WATCHDOG_LIMIT_SEC", 0))
            or 2 * _stale_limit(controller))
        watchdog_start = time.time()

        def tick_watchdog():
            while not stop_event.wait(min(30.0, exit_limit / 4)):
                last = controller.last_tick_completed_sec
                age = time.time() - (last if last is not None
                                     else watchdog_start)
                if age > exit_limit:
                    log.critical(
                        "no tick completed for %.0fs (limit %.0fs); exiting "
                        "so a standby can take over", age, exit_limit)
                    # the ticks leading up to the wedge are exactly what the
                    # post-mortem needs — dump before the crash-to-restart
                    from escalator_tpu.observability import dump_on_incident

                    dump_path = dump_on_incident("wedge")
                    if dump_path:
                        log.critical("flight record dumped to %s", dump_path)
                    try:
                        if elector is not None:
                            elector.stop()  # stop renewing; Lease lapses
                    finally:
                        os._exit(70)
            # stop requested: a WEDGED tick still never returns, and outside
            # k8s nothing sends SIGKILL — escalate instead of disarming. A
            # clean shutdown exits the interpreter (killing this daemon
            # thread) long before the grace elapses.
            time.sleep(60)
            log.critical("shutdown did not complete within 60s; forcing exit")
            from escalator_tpu.observability import dump_on_incident

            dump_on_incident("shutdown-wedge")
            os._exit(70)

        threading.Thread(target=tick_watchdog, daemon=True).start()

    if args.once:
        controller.run_once()
        tracer.close()
        deltas = {
            name: state.scale_delta
            for name, state in controller.node_groups.items()
        }
        provider = controller.cloud_provider
        targets = {
            ng.name(): ng.target_size() for ng in provider.node_groups()
        }
        print(json.dumps({"deltas": deltas, "provider_targets": targets}))
        return 0

    try:
        controller.run_forever(run_immediately=True)
    finally:
        tracer.close()
        if server is not None:
            server.shutdown()
        stop_client = getattr(client, "stop", None)
        if callable(stop_client):
            stop_client()  # stop informer list+watch threads
    return 0


if __name__ == "__main__":
    sys.exit(main())
