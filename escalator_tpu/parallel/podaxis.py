"""Pod-axis sharding: the sequence-parallelism analog (SURVEY.md §5).

``parallel.mesh`` shards the NODEGROUP axis — perfect when there are many
groups, useless when one giant group holds most of the pods (a single
million-pod `default` group saturates one device while the rest idle; the
reference degrades the same way, one serial O(P) Go loop,
/root/reference/pkg/k8s/util.go:27-38). This module shards the POD axis
instead, the way sequence parallelism splits a long sequence:

- the flat ``[P]`` pod arrays are split across the mesh devices (any split —
  no group locality required, sums are order-free);
- each device segment-sums its local pod shard into full ``[G]`` / ``[N]``
  partials (requests per group, pods per node);
- one ``jax.lax.psum`` over the mesh combines the partials — integer sums,
  so the result is **bit-identical** to the single-device kernel;
- the small replicated tail (``[G]`` percent/threshold math, ``[N]`` node
  selections) runs identically on every device.

Node arrays ride along replicated: N is orders of magnitude smaller than P
(50k nodes vs 1M pods), and the selections need global argsorts anyway.

**When this wins — the measured cost model** (bench cfg8, VERDICT r3 item 3).
Per tick, with S devices:

    total(S) = sweep(P)/S + psum(3G+N) + tail(N)

where ``sweep`` is the sharded O(P) pod segment-sum (the only term that
scales), ``psum`` is ONE stacked [3G+N] collective, and ``tail`` is the
replicated O(N log N) decide tail (percent math + two [N] argsorts), which on
real chips costs the same wall-clock as on one device (each chip computes it
concurrently). So on real hardware the best case is
``total(inf) -> tail(N)``: pod-axis sharding pays off only while the pod
sweep DOMINATES the node tail, i.e. **P >> N** (giant default group, few
nodes). At the bench shape (1M pods / 50k nodes, CPU) the split is
sweep ~20 ms vs tail ~30 ms (tail measured after the one-pass multi-key
``lax.sort`` fusion in ops.kernel) — sharding can cut at most the 20, never
the 30; shapes with fewer nodes or more pods shift the ceiling up.

On this repo's 1-physical-core bench rig the virtual devices timeshare one
core, so the replicated tail SERIALIZES S-fold instead of running
concurrently: measured cfg8 8-dev total = 261 ms vs 61 ms single-device
(sweep-only 19 ms, tail 242 ms ~= 8 x the single-device tail — the S-fold
serialization, exactly). That 0.23x "speedup" is the rig artifact the cost
model predicts, not a property of the design; the sharded sweep itself
(19 ms for 1M lanes over 8 shards) is the term that rides ICI on real
chips. The bench reports the curve, the
phase split, and the confound note side by side so neither reading is
possible by accident.

Composes with the group-axis path: use ``mesh.ShardedJaxBackend`` for many
groups, this for ONE dominant giant group; both produce the same
DecisionArrays contract. For the in-between regime — a FEW huge groups —
``parallel.grid`` shards both axes at once (2-D groups x pods mesh): nodes
shard by group block so the ``tail(N)`` term above becomes ``tail(N/Sg)``
instead of replicating, which is exactly the loss this module's cost model
documents (bench cfg8 measured the replicated tail at 165 of 182 ms; the
grid's 8x1 layout cut it ~7x on the same rig and went 1.46x FASTER than
single-device where this module's pure pod-axis split ran 0.28x).

ROUND 6 — the busy tick no longer replicates its sort. The ``tail(N)``
term above had one remaining ordered-path consumer: a busy/drain tick
needs the combined node-ordering sort, and this module used to run it
whole on every device. The ordered decider now accepts a per-tick
``node_blocks`` map (``ops.order_tail.assign_order_blocks``) and runs the
sort GROUP-BLOCK-SHARDED — each device sorts its own contiguous-group
block and one psum reassembles the permutation, so the busy-tick cost
model becomes ``sweep(P)/S + psum + light_tail(N) + sort(N/S_blocks)``;
a single giant group degenerates to ONE device paying ``sort(N)`` while
the rest skip via ``lax.cond`` (see ops/order_tail.py for the exactness
argument and bench cfg8's busy/steady/legacy rows for the measurements).
"""

from __future__ import annotations

import dataclasses
from functools import partial

import numpy as np

from escalator_tpu.jaxconfig import ensure_x64, shard_map

ensure_x64()

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from escalator_tpu.core.arrays import ClusterArrays, PodArrays
from escalator_tpu.ops import device_state as _ds  # noqa: F401  (registers SoA pytrees)
from escalator_tpu.ops import kernel, order_tail


def _pod_spec(mesh: Mesh) -> P:
    names = tuple(mesh.axis_names)
    return P(names if len(names) > 1 else names[0])


def pad_pods_for_mesh(cluster: ClusterArrays, mesh: Mesh) -> ClusterArrays:
    """Pad the pod axis to a multiple of the mesh size (shard_map needs equal
    shards). Padding lanes are valid=False; masked inside the kernel."""
    ndev = int(mesh.devices.size)
    P_ = int(cluster.pods.valid.shape[0])
    pad = (-P_) % ndev
    if pad == 0:
        return cluster
    p = cluster.pods
    pods = PodArrays(
        group=np.concatenate([p.group, np.zeros(pad, p.group.dtype)]),
        cpu_milli=np.concatenate([p.cpu_milli, np.zeros(pad, p.cpu_milli.dtype)]),
        mem_bytes=np.concatenate([p.mem_bytes, np.zeros(pad, p.mem_bytes.dtype)]),
        node=np.concatenate([p.node, np.full(pad, -1, p.node.dtype)]),
        valid=np.concatenate([p.valid, np.zeros(pad, bool)]),
    )
    return ClusterArrays(groups=cluster.groups, pods=pods, nodes=cluster.nodes)


def place(cluster: ClusterArrays, mesh: Mesh) -> ClusterArrays:
    """Device-put with the pod axis sharded over the mesh, everything else
    replicated — so the big transfer is split across devices too."""
    pod_sharding = NamedSharding(mesh, _pod_spec(mesh))
    repl = NamedSharding(mesh, P())
    put = lambda soa, sh: type(soa)(
        **{
            f: jax.device_put(getattr(soa, f), sh)
            for f in soa.__dataclass_fields__
        }
    )
    return ClusterArrays(
        groups=put(cluster.groups, repl),
        pods=put(cluster.pods, pod_sharding),
        nodes=put(cluster.nodes, repl),
    )


def _build_pod_sweep(mesh: Mesh, impl: str, G: int, N: int):
    """The sharded O(P) pod sweep: local partial segment-sums + ONE stacked
    [3G+N] psum (the _FLEET_FIELDS trick from parallel.mesh — one collective,
    not one per field; int64 sums, so concatenating before the reduction is
    exact). Shared by the decider and the phase benchmark."""
    names = tuple(mesh.axis_names)
    pod_spec = _pod_spec(mesh)

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(pod_spec, P()),
        out_specs=P(),
        # pallas_call (impl="pallas") cannot express varying-mesh-axes
        # metadata yet; the psum in the body establishes replication
        check_vma=False,
    )
    def pod_sweep(pods: PodArrays, node_group):
        partials = kernel.aggregate_pods(pods, node_group, G, N, impl)
        flat = jnp.concatenate([x.reshape(-1) for x in partials])
        for ax in reversed(names):
            flat = jax.lax.psum(flat, ax)
        return flat[:G], flat[G : 2 * G], flat[2 * G : 3 * G], flat[3 * G :]

    return pod_sweep


def make_podaxis_decider(mesh: Mesh, impl: str | None = None,
                         with_orders: bool = True):
    """jitted ``(cluster, now_sec, node_blocks=None) -> DecisionArrays`` with
    the O(P) pod sweep sharded over the mesh and combined with psum.
    Bit-identical to ``kernel.decide`` on the same cluster (integer partial
    sums commute); when ``node_blocks`` is given, bit-identical on every
    non-order field and on every ordering WINDOW (the kernel's documented
    selection contract), while the unspecified region beyond the windows may
    differ — see ops.order_tail.

    ``impl`` defaults to ESCALATOR_TPU_KERNEL_IMPL (ops.kernel.default_impl).
    The pod axis length must be a multiple of the mesh size
    (:func:`pad_pods_for_mesh`). ``with_orders=False`` is the lazy-orders
    light variant (kernel.decide docstring) — this path's replicated decide
    tail IS the node sort, so the light program removes its dominant
    replicated term entirely on steady ticks.

    ``node_blocks`` (ordered variant only) is the ``[S, Nb]`` contiguous-
    group block map from ``order_tail.assign_order_blocks``: the busy-tick
    fix (round 6). With it, the combined ordering sort runs GROUP-BLOCK-
    SHARDED — each device sorts only its block's ``[Nb]`` lanes (devices
    whose block holds no selected lane skip the sort entirely) instead of
    every device replicating the full ``[N]`` sort, which bench cfg8
    measured at 218 of 241 ms on the 8-virtual-device rig. Without it the
    legacy replicated ordered program runs (kept for raw callers that want
    strict full-array bit-parity, e.g. the multichip dryrun)."""
    if impl is None:
        impl = kernel.default_impl()
    tail = order_tail.make_sharded_order_tail(mesh) if with_orders else None

    @jax.jit
    def decide_podaxis(cluster: ClusterArrays, now_sec,
                       node_blocks=None) -> kernel.DecisionArrays:
        G = cluster.groups.valid.shape[0]
        N = cluster.nodes.valid.shape[0]
        pod_sweep = _build_pod_sweep(mesh, impl, G, N)
        pod_aggs = pod_sweep(cluster.pods, cluster.nodes.group)
        node_aggs = kernel.aggregate_nodes(cluster.nodes, G, impl)
        if not with_orders or node_blocks is None:
            return kernel.decide(
                cluster, now_sec, impl=impl, aggregates=(pod_aggs, node_aggs),
                with_orders=with_orders,
            )
        # block-sharded ordering: run the LIGHT decide (no replicated sort),
        # then splice in the sharded tail's permutations
        out = kernel.decide(
            cluster, now_sec, impl=impl, aggregates=(pod_aggs, node_aggs),
            with_orders=False,
        )
        n = cluster.nodes
        ngroup, untainted_sel, tainted_sel = order_tail.node_selection_masks(
            n.valid, n.group, n.tainted, n.cordoned
        )
        victim_primary = jnp.where(
            cluster.groups.emptiest[ngroup], pod_aggs[3], jnp.int64(0)
        )
        untaint_order, scale_down_order = tail(
            ngroup, tainted_sel, untainted_sel, victim_primary,
            n.creation_ns, G, node_blocks,
        )
        return dataclasses.replace(
            out, untaint_order=untaint_order, scale_down_order=scale_down_order
        )

    return decide_podaxis


def make_delta_scatter(mesh: Mesh):
    """Round-8 incremental state maintenance for the pod-axis layout: keep
    the placed cluster RESIDENT across ticks (killing this backend's
    documented O(cluster) per-tick re-place) and scatter a tiny replicated
    delta batch into it while maintaining replicated per-device
    :class:`kernel.GroupAggregates` — with ZERO collectives.

    The batch carries ``(idx, old_vals, new_vals)`` for the touched lanes
    (host-diff style, ops.controller.backend._changed_slots economics): the
    old values ride in the batch precisely so no device ever has to gather
    another shard's lanes — each device scatters the in-range slice of the
    pod batch into its own shard (global index minus the shard offset;
    out-of-range and pad lanes drop), applies the full replicated node
    batch, and folds the identical aggregate deltas from the replicated
    batch into its own aggregate copy. Dirty masks therefore live per
    shard/device and stay bitwise-identical by construction. Steady ticks
    then run ``kernel.delta_decide_jit`` on the resident cluster (the delta
    program never reads the pod axis — aggregates are persistent), and
    ordered/drain ticks run the existing block-sharded ordered decider with
    ``aggregates=kernel.aggregates_tuple(aggs)``.

    Returns jitted ``(pods, nodes, groups_old, groups_new, pidx, pod_old,
    pod_new, nidx, node_old, node_new, aggs) -> (cluster, aggs,
    node_group_changed)`` — same argument shape as
    ``device_state._scatter_update_aggs`` plus the old-value batches.
    ``node_group_changed`` (a replicated scalar bool) is the one exact-
    correction case the zero-collective program cannot absorb: a node
    lane's group column changed, so pods OUTSIDE the batch moved their
    pods-remaining contribution — the caller must re-derive the aggregates
    with the sharded full sweep on that (rare) tick. Pad lanes use
    ``idx = len(axis)`` (out of range everywhere) with identical old/new
    values. Donates the resident pod/node columns and the aggregates."""
    from dataclasses import fields as _fields

    from escalator_tpu.ops import device_state as ds
    from escalator_tpu.ops.kernel import GroupAggregates

    names = tuple(mesh.axis_names)
    pod_spec = _pod_spec(mesh)
    soa_spec = lambda cls, spec: cls(  # noqa: E731
        **{f: spec for f in cls.__dataclass_fields__})
    from escalator_tpu.core.arrays import GroupArrays, NodeArrays

    cluster_spec = ClusterArrays(
        groups=soa_spec(GroupArrays, P()),
        pods=soa_spec(PodArrays, pod_spec),
        nodes=soa_spec(NodeArrays, P()),
    )
    repl_aggs = GroupAggregates(*([P()] * 11))

    @partial(jax.jit, donate_argnums=(0, 1, 10))
    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(soa_spec(PodArrays, pod_spec), soa_spec(NodeArrays, P()),
                  soa_spec(GroupArrays, P()), soa_spec(GroupArrays, P()), P(),
                  soa_spec(PodArrays, P()), soa_spec(PodArrays, P()), P(),
                  soa_spec(NodeArrays, P()), soa_spec(NodeArrays, P()),
                  repl_aggs),
        out_specs=(cluster_spec, repl_aggs, P()),
        # the pod scatter writes device-varying lanes from replicated
        # values; replication of every P() output is established by
        # construction (identical math on identical replicated inputs), a
        # pattern the checker cannot express — same waiver as the pod sweep
        check_vma=False,
    )
    def delta_scatter(pods, nodes, groups_old, groups_new, pidx, pod_old,
                      pod_new, nidx, node_old, node_new, aggs):
        shard_len = pods.valid.shape[0]
        G = groups_new.valid.shape[0]
        N = nodes.valid.shape[0]
        linear = jnp.int32(0)
        for nm in names:
            linear = linear * int(mesh.shape[nm]) + jax.lax.axis_index(nm)
        start = linear * shard_len
        # negative indices WRAP in jax (mode="drop" only drops past-the-end),
        # so lanes owned by earlier shards must be mapped to an explicit
        # out-of-bounds sentinel, not left negative
        in_shard = (pidx >= start) & (pidx < start + shard_len)
        local_idx = jnp.where(in_shard, pidx - start, shard_len)
        pods2 = type(pods)(**{
            f.name: getattr(pods, f.name).at[local_idx].set(
                getattr(pod_new, f.name), mode="drop")
            for f in _fields(pods)
        })
        nodes2 = type(nodes)(**{
            f.name: getattr(nodes, f.name).at[nidx].set(
                getattr(node_new, f.name), mode="drop")
            for f in _fields(nodes)
        })
        deltas, touched, ng_changed = ds.aggregate_lane_deltas(
            pod_old, pod_new, node_old, node_new,
            nodes.group, nodes2.group, G, N,
        )
        # the node-group-change correction is HOST-level here (the flag in
        # the return; an in-program re-sweep would need the full pod axis
        # and so a psum), so the incremental npr is folded unconditionally
        aggs2 = ds.fold_aggregate_deltas(
            aggs, deltas, touched,
            ds.group_rows_changed(groups_old, groups_new),
            aggs.node_pods_remaining + deltas["node_pods_remaining"],
        )
        out_cluster = ClusterArrays(
            groups=groups_new, pods=pods2, nodes=nodes2)
        return out_cluster, aggs2, ng_changed

    return delta_scatter


def time_pod_sweep(mesh: Mesh, cluster: ClusterArrays, _timeit,
                   impl: str | None = None) -> float:
    """Median ms of the sharded pod sweep ALONE (no decide tail) — the phase
    split bench cfg8 reports: on real chips the sweep scales with devices
    while the replicated tail is constant-time; on virtual shared-core
    devices the tail serializes S-fold (see the module crossover note)."""
    if impl is None:
        impl = kernel.default_impl()
    G = int(cluster.groups.valid.shape[0])
    N = int(cluster.nodes.valid.shape[0])
    sweep = jax.jit(_build_pod_sweep(mesh, impl, G, N))
    med, _ = _timeit(
        lambda: jax.block_until_ready(sweep(cluster.pods, cluster.nodes.group))
    )
    return med
