"""Mesh sharding of the nodegroup axis — the framework's distributed backend.

The reference processes nodegroups serially in one Go process
(/root/reference/pkg/controller/controller.go:416-445) and has no collective layer at
all (SURVEY.md §2.7). Here the nodegroup axis is the parallel axis: decisions are
embarrassingly parallel across groups, so we shard groups across a
``jax.sharding.Mesh`` with ``shard_map`` and run the batched kernel on each shard's
local block. Pods/nodes are routed to their group's shard at pack time, so the device
program needs **no cross-device communication** for decisions; only the optional
fleet-wide aggregates use ``psum``-style reductions (computed here from the per-shard
outputs). ICI/DCN scaling therefore comes for free: more devices, more nodegroup
shards.

This module is the TPU-native stand-in for what SURVEY.md §2.7 calls the "distributed
communication backend" slot, and the "sequence parallelism" analog (sharding the
100k-pod axis by way of its grouping).
"""

from __future__ import annotations

from functools import partial
from typing import List, Optional, Sequence, Tuple

import numpy as np

from escalator_tpu.jaxconfig import ensure_x64

ensure_x64()

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from escalator_tpu.core import semantics
from escalator_tpu.core.arrays import ClusterArrays, pack_cluster
from escalator_tpu.k8s import types as k8s
from escalator_tpu.ops.kernel import DecisionArrays, decide

GROUP_AXIS = "groups"


def make_mesh(devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """1-D mesh over the nodegroup axis. Multi-host: pass the global device list."""
    devs = list(devices) if devices is not None else jax.devices()
    return Mesh(np.array(devs), (GROUP_AXIS,))


def assign_shards(group_inputs, num_shards: int) -> List[List[int]]:
    """Greedy least-loaded (LPT) placement of groups onto shards by pod count.
    Returns, per shard, the sorted list of original group indices."""
    if num_shards < 1:
        raise ValueError("num_shards must be >= 1")
    assignment: List[List[int]] = [[] for _ in range(num_shards)]
    order = sorted(
        range(len(group_inputs)), key=lambda i: -len(group_inputs[i][0])
    )
    loads = [0] * num_shards
    for gi in order:
        s = loads.index(min(loads))
        assignment[s].append(gi)
        loads[s] += len(group_inputs[gi][0]) + 1
    for s in range(num_shards):
        assignment[s].sort()
    return assignment


def shard_capacity(group_inputs, assignment) -> Tuple[int, int, int]:
    """(max pods, max nodes, max groups) over shards for the given assignment."""
    max_pods = max(
        (sum(len(group_inputs[gi][0]) for gi in shard) for shard in assignment),
        default=0,
    )
    max_nodes = max(
        (sum(len(group_inputs[gi][1]) for gi in shard) for shard in assignment),
        default=0,
    )
    max_groups = max((len(shard) for shard in assignment), default=0)
    return max_pods, max_nodes, max_groups


def pack_cluster_sharded(
    group_inputs: Sequence[
        Tuple[
            Sequence[k8s.Pod],
            Sequence[k8s.Node],
            semantics.GroupConfig,
            semantics.GroupState,
        ]
    ],
    num_shards: int,
    pad_pods_per_shard: Optional[int] = None,
    pad_nodes_per_shard: Optional[int] = None,
    pad_groups_per_shard: Optional[int] = None,
    dry_mode_flags: Optional[Sequence[bool]] = None,
    taint_trackers: Optional[Sequence[Sequence[str]]] = None,
) -> Tuple[ClusterArrays, List[List[int]]]:
    """Distribute nodegroups onto ``num_shards`` shards (greedy least-loaded / LPT
    placement by pod count) and pack each shard with identical padded shapes,
    stacking to leaves with a leading shard axis.

    LPT keeps shard loads balanced when group sizes are skewed (the classic
    raggedness hazard, SURVEY.md §7). Returns the stacked arrays plus, per shard, the
    list of original group indices (shard-local group id -> caller's group index).
    """
    assignment = assign_shards(group_inputs, num_shards)

    max_pods = max(
        (sum(len(group_inputs[gi][0]) for gi in shard) for shard in assignment),
        default=0,
    )
    max_nodes = max(
        (sum(len(group_inputs[gi][1]) for gi in shard) for shard in assignment),
        default=0,
    )
    max_groups = max((len(shard) for shard in assignment), default=0)
    pad_pods = pad_pods_per_shard or max(max_pods, 1)
    pad_nodes = pad_nodes_per_shard or max(max_nodes, 1)
    pad_groups = pad_groups_per_shard or max(max_groups, 1)

    shards = [
        pack_cluster(
            [group_inputs[gi] for gi in shard],
            pad_pods=pad_pods,
            pad_nodes=pad_nodes,
            pad_groups=pad_groups,
            dry_mode_flags=(
                [dry_mode_flags[gi] for gi in shard] if dry_mode_flags else None
            ),
            taint_trackers=(
                [taint_trackers[gi] for gi in shard] if taint_trackers else None
            ),
        )
        for shard in assignment
    ]
    leaves = [c.tree_flatten()[0] for c in shards]
    stacked = [np.stack(parts) for parts in zip(*leaves)]
    return ClusterArrays.tree_unflatten(None, stacked), assignment


def make_sharded_decider(mesh: Mesh):
    """jitted ``(sharded_cluster, now_sec) -> DecisionArrays`` with the leading shard
    axis partitioned over the mesh. Local blocks may hold several shards (vmap'ed);
    no collectives are emitted — per-group decisions are shard-local by construction."""

    @jax.jit
    @partial(
        jax.shard_map,
        mesh=mesh,
        in_specs=(P(GROUP_AXIS), P()),
        out_specs=P(GROUP_AXIS),
    )
    def sharded_decide(cluster: ClusterArrays, now_sec) -> DecisionArrays:
        return jax.vmap(decide, in_axes=(0, None))(cluster, now_sec)

    return sharded_decide


def shard_cluster_arrays(cluster: ClusterArrays, mesh: Mesh) -> ClusterArrays:
    """Place stacked cluster arrays so the shard axis lives on the mesh devices."""
    sharding = NamedSharding(mesh, P(GROUP_AXIS))
    leaves, aux = cluster.tree_flatten()
    placed = [jax.device_put(leaf, sharding) for leaf in leaves]
    return ClusterArrays.tree_unflatten(aux, placed)


def fleet_totals(out: DecisionArrays) -> dict:
    """Fleet-wide aggregates over all shards/groups (the reference's global metrics
    analog). Computed as reductions over the sharded outputs — XLA turns these into
    psum-style collectives over ICI when the outputs are device-resident."""
    return {
        "pods": int(jnp.sum(out.num_pods)),
        "nodes": int(jnp.sum(out.num_nodes)),
        "untainted": int(jnp.sum(out.num_untainted)),
        "tainted": int(jnp.sum(out.num_tainted)),
        "cordoned": int(jnp.sum(out.num_cordoned)),
        "cpu_request_milli": int(jnp.sum(out.cpu_request_milli)),
        "mem_request_bytes": int(jnp.sum(out.mem_request_bytes)),
        "scale_up_groups": int(jnp.sum(out.nodes_delta > 0)),
        "scale_down_groups": int(jnp.sum(out.nodes_delta < 0)),
    }
