"""Mesh sharding of the nodegroup axis — the framework's distributed backend.

The reference processes nodegroups serially in one Go process
(/root/reference/pkg/controller/controller.go:416-445) and has no collective layer at
all (SURVEY.md §2.7). Here the nodegroup axis is the parallel axis: decisions are
embarrassingly parallel across groups, so we shard groups across a
``jax.sharding.Mesh`` with ``shard_map`` and run the batched kernel on each shard's
local block. Pods/nodes are routed to their group's shard at pack time, so the device
program needs **no cross-device communication** for decisions; only the optional
fleet-wide aggregates use ``psum``-style reductions (computed here from the per-shard
outputs). ICI/DCN scaling therefore comes for free: more devices, more nodegroup
shards.

This module is the TPU-native stand-in for what SURVEY.md §2.7 calls the "distributed
communication backend" slot, and the "sequence parallelism" analog (sharding the
100k-pod axis by way of its grouping).
"""

from __future__ import annotations

from functools import partial
from typing import List, Optional, Sequence, Tuple

import numpy as np

from escalator_tpu.jaxconfig import ensure_x64, guarded_devices, shard_map

ensure_x64()

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from escalator_tpu.core import semantics
from escalator_tpu.core.arrays import ClusterArrays, pack_cluster
from escalator_tpu.k8s import types as k8s
from escalator_tpu.ops.kernel import DecisionArrays, decide

GROUP_AXIS = "groups"

#: Hybrid mesh axis names: ``dcn`` spans hosts (slow data-center links), ``ici``
#: spans each host's chips (fast inter-chip interconnect).
DCN_AXIS = "dcn"
ICI_AXIS = "ici"


def make_mesh(devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """1-D mesh over the nodegroup axis. Multi-host: pass the global device list.
    The default device list rides the wedged-transport guard
    (jaxconfig.guarded_devices) — see that docstring."""
    devs = list(devices) if devices is not None else guarded_devices()
    return Mesh(np.array(devs), (GROUP_AXIS,))


def make_hybrid_mesh(
    devices: Optional[Sequence[jax.Device]] = None,
    num_hosts: Optional[int] = None,
) -> Mesh:
    """2-D ``(dcn, ici)`` mesh for multi-host fleets.

    The nodegroup shard axis is laid over BOTH axes (see ``_group_spec``), so
    neighbouring shards live on the same host: per-group decisions need no
    communication at all, and the fleet reductions ``psum`` over ``ici`` first
    (riding the fast intra-host interconnect) before the small cross-host ``dcn``
    hop — the layout recipe from the scaling-book playbook. Axis order matters:
    the trailing mesh axis gets the fastest links.

    ``num_hosts`` defaults to the number of distinct JAX processes (1 in
    single-host tests, the real host count under multi-process ``jax.distributed``
    initialisation — see ``parallel.distributed.initialize``).
    """
    devs = list(devices) if devices is not None else guarded_devices()
    if num_hosts is None:
        num_hosts = max(1, len({d.process_index for d in devs}))
    if len(devs) % num_hosts != 0:
        raise ValueError(
            f"{len(devs)} devices do not divide evenly over {num_hosts} hosts"
        )
    # Keep each host's devices contiguous on the ici axis. jax.devices() orders by
    # (process_index, local id); sort defensively for caller-provided lists.
    devs = sorted(devs, key=lambda d: (d.process_index, d.id))
    arr = np.array(devs).reshape(num_hosts, -1)
    # When the list spans real processes, every dcn row must be a single host —
    # otherwise the "ici = fast intra-host links" layout claim is silently false.
    # (Single-process device lists may be split into virtual hosts for testing.)
    real_hosts = len({d.process_index for d in devs})
    if real_hosts > 1:
        for row in arr:
            if len({d.process_index for d in row}) != 1:
                raise ValueError(
                    f"a dcn row would span processes: num_hosts={num_hosts} does "
                    f"not match the {real_hosts} distinct processes in the device "
                    "list (or per-host device counts are uneven)"
                )
    return Mesh(arr, (DCN_AXIS, ICI_AXIS))


def _group_spec(mesh: Mesh) -> P:
    """PartitionSpec placing the leading shard axis over ALL mesh axes (works for
    both the 1-D ``groups`` mesh and the 2-D ``(dcn, ici)`` hybrid mesh)."""
    names = tuple(mesh.axis_names)
    return P(names if len(names) > 1 else names[0])


def assign_shards(group_inputs, num_shards: int) -> List[List[int]]:
    """Greedy least-loaded (LPT) placement of groups onto shards by pod count.
    Returns, per shard, the sorted list of original group indices."""
    if num_shards < 1:
        raise ValueError("num_shards must be >= 1")
    assignment: List[List[int]] = [[] for _ in range(num_shards)]
    order = sorted(
        range(len(group_inputs)), key=lambda i: -len(group_inputs[i][0])
    )
    loads = [0] * num_shards
    for gi in order:
        s = loads.index(min(loads))
        assignment[s].append(gi)
        loads[s] += len(group_inputs[gi][0]) + 1
    for s in range(num_shards):
        assignment[s].sort()
    return assignment


def shard_capacity(group_inputs, assignment) -> Tuple[int, int, int]:
    """(max pods, max nodes, max groups) over shards for the given assignment."""
    max_pods = max(
        (sum(len(group_inputs[gi][0]) for gi in shard) for shard in assignment),
        default=0,
    )
    max_nodes = max(
        (sum(len(group_inputs[gi][1]) for gi in shard) for shard in assignment),
        default=0,
    )
    max_groups = max((len(shard) for shard in assignment), default=0)
    return max_pods, max_nodes, max_groups


def pack_cluster_sharded(
    group_inputs: Sequence[
        Tuple[
            Sequence[k8s.Pod],
            Sequence[k8s.Node],
            semantics.GroupConfig,
            semantics.GroupState,
        ]
    ],
    num_shards: int,
    pad_pods_per_shard: Optional[int] = None,
    pad_nodes_per_shard: Optional[int] = None,
    pad_groups_per_shard: Optional[int] = None,
    dry_mode_flags: Optional[Sequence[bool]] = None,
    taint_trackers: Optional[Sequence[Sequence[str]]] = None,
) -> Tuple[ClusterArrays, List[List[int]]]:
    """Distribute nodegroups onto ``num_shards`` shards (greedy least-loaded / LPT
    placement by pod count) and pack each shard with identical padded shapes,
    stacking to leaves with a leading shard axis.

    LPT keeps shard loads balanced when group sizes are skewed (the classic
    raggedness hazard, SURVEY.md §7). Returns the stacked arrays plus, per shard, the
    list of original group indices (shard-local group id -> caller's group index).
    """
    assignment = assign_shards(group_inputs, num_shards)

    max_pods = max(
        (sum(len(group_inputs[gi][0]) for gi in shard) for shard in assignment),
        default=0,
    )
    max_nodes = max(
        (sum(len(group_inputs[gi][1]) for gi in shard) for shard in assignment),
        default=0,
    )
    max_groups = max((len(shard) for shard in assignment), default=0)
    pad_pods = pad_pods_per_shard or max(max_pods, 1)
    pad_nodes = pad_nodes_per_shard or max(max_nodes, 1)
    pad_groups = pad_groups_per_shard or max(max_groups, 1)

    shards = [
        pack_cluster(
            [group_inputs[gi] for gi in shard],
            pad_pods=pad_pods,
            pad_nodes=pad_nodes,
            pad_groups=pad_groups,
            dry_mode_flags=(
                [dry_mode_flags[gi] for gi in shard] if dry_mode_flags else None
            ),
            taint_trackers=(
                [taint_trackers[gi] for gi in shard] if taint_trackers else None
            ),
        )
        for shard in assignment
    ]
    leaves = [c.tree_flatten()[0] for c in shards]
    stacked = [np.stack(parts) for parts in zip(*leaves, strict=True)]
    return ClusterArrays.tree_unflatten(None, stacked), assignment


def make_sharded_decider(mesh: Mesh, impl: Optional[str] = None,
                         with_orders: bool = True):
    """jitted ``(sharded_cluster, now_sec) -> DecisionArrays`` with the leading shard
    axis partitioned over the mesh (1-D or hybrid). Local blocks may hold several
    shards (vmap'ed); no collectives are emitted — per-group decisions are
    shard-local by construction. ``impl`` selects the aggregation sweep exactly
    as in ``ops.kernel.decide``; when omitted it follows ESCALATOR_TPU_KERNEL_IMPL
    (ops.kernel.default_impl), so the env switch reaches direct callers too.

    ``with_orders=False`` builds the lazy-orders LIGHT variant (see
    ``kernel.decide``): under vmap the ordered program's empty-selection
    ``cond`` lowers to ``select`` — both branches always run — so a static
    order-free variant is the only way a sharded steady-state tick skips its
    node sorts. Ordered outputs (the default) remain the sharded-vs-single
    bit-parity contract the tests and dryrun assert."""
    from escalator_tpu.ops.kernel import default_impl

    if impl is None:
        impl = default_impl()
    spec = _group_spec(mesh)

    @jax.jit
    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(spec, P()),
        out_specs=spec,
        # pallas_call (impl="pallas") cannot express varying-mesh-axes
        # metadata yet; outputs are shard-local so no replication is claimed
        check_vma=(impl != "pallas"),
    )
    def sharded_decide(cluster: ClusterArrays, now_sec) -> DecisionArrays:
        return jax.vmap(
            lambda c, t: decide(c, t, impl=impl, with_orders=with_orders),
            in_axes=(0, None),
        )(cluster, now_sec)

    return sharded_decide


#: Fleet-total field -> DecisionArrays source expression, shared by the device
#: (psum) and host (numpy) reduction paths so they cannot drift.
_FLEET_FIELDS = {
    "pods": lambda o: o.num_pods,
    "nodes": lambda o: o.num_nodes,
    "untainted": lambda o: o.num_untainted,
    "tainted": lambda o: o.num_tainted,
    "cordoned": lambda o: o.num_cordoned,
    "cpu_request_milli": lambda o: o.cpu_request_milli,
    "mem_request_bytes": lambda o: o.mem_request_bytes,
    "scale_up_groups": lambda o: (o.nodes_delta > 0).astype(jnp.int32),
    "scale_down_groups": lambda o: (o.nodes_delta < 0).astype(jnp.int32),
}


def make_fleet_decider(mesh: Mesh):
    """Like :func:`make_sharded_decider` but also returns fleet-wide totals reduced
    **inside** the device program with ``jax.lax.psum`` over the mesh axes. On a
    hybrid mesh the reduction is staged ``ici`` then ``dcn``, so the big per-chip
    partials combine over fast intra-host links and only one small vector crosses
    hosts — the layered-collective pattern the reference has no analog of (its
    "fleet view" is 25 Prometheus gauges scraped over HTTP, pkg/metrics/metrics.go).
    """
    spec = _group_spec(mesh)
    axis_names = tuple(mesh.axis_names)

    @jax.jit
    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(spec, P()),
        out_specs=(spec, P()),
    )
    def fleet_decide(cluster: ClusterArrays, now_sec):
        out = jax.vmap(decide, in_axes=(0, None))(cluster, now_sec)
        # one vector, one staged reduction — not one collective per field
        local = jnp.stack(
            [jnp.sum(get(out).astype(jnp.int64)) for get in _FLEET_FIELDS.values()]
        )
        if len(axis_names) > 1:
            # staged: fast axis first, then the cross-host hop
            local = jax.lax.psum(local, ICI_AXIS)
            local = jax.lax.psum(local, DCN_AXIS)
        else:
            local = jax.lax.psum(local, axis_names[0])
        totals = {name: local[i] for i, name in enumerate(_FLEET_FIELDS)}
        return out, totals

    return fleet_decide


def make_sharded_sweeper(mesh: Mesh, num_candidates: int):
    """jitted sharded what-if sweep (ops.simulate.sweep_deltas over the mesh):
    post-delta utilisation for every (group, candidate delta) pair, nodegroup
    axis sharded exactly like the decision path — capacity planning for the
    whole fleet in one device program (no reference analog)."""
    from escalator_tpu.ops.simulate import sweep_deltas

    spec = _group_spec(mesh)

    @jax.jit
    @partial(shard_map, mesh=mesh, in_specs=(spec,), out_specs=spec)
    def sharded_sweep(cluster: ClusterArrays):
        return jax.vmap(lambda c: sweep_deltas(c, num_candidates))(cluster)

    return sharded_sweep


def shard_cluster_arrays(cluster: ClusterArrays, mesh: Mesh) -> ClusterArrays:
    """Place stacked cluster arrays so the shard axis lives on the mesh devices."""
    sharding = NamedSharding(mesh, _group_spec(mesh))
    leaves, aux = cluster.tree_flatten()
    placed = [jax.device_put(leaf, sharding) for leaf in leaves]
    return ClusterArrays.tree_unflatten(aux, placed)


def fleet_totals(out: DecisionArrays) -> dict:
    """Fleet-wide aggregates over all shards/groups (the reference's global metrics
    analog). Computed as reductions over the sharded outputs — XLA turns these into
    psum-style collectives over ICI when the outputs are device-resident. For the
    in-program staged reduction, use :func:`make_fleet_decider`."""
    return {name: int(jnp.sum(get(out))) for name, get in _FLEET_FIELDS.items()}
