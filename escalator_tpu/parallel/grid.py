"""2-D grid sharding: nodegroup axis x pod axis in ONE mesh.

Round-4 measurement showed where each 1-D path stops scaling:

- ``parallel.mesh`` (group axis) shards EVERYTHING per group shard — but a
  single giant group saturates one device (its whole pod sweep and node sort
  land on one chip);
- ``parallel.podaxis`` (pod axis) shards the O(P) pod sweep — but replicates
  the node arrays, so the O(N log N) decide tail (the two grouped-order
  ``lax.sort`` passes over ``[N]``) runs whole on every device. Bench cfg8
  measured that tail at 165 ms of the 182 ms 8-device total: the sharded
  sweep was 17 ms and everything else was replicated tail.

This module shards BOTH axes at once over a 2-D ``(groups, pods)`` mesh:

- nodegroups are partitioned into ``Sg`` shards exactly as
  ``mesh.pack_cluster_sharded`` lays them out (leading shard axis);
- node and group arrays shard over the ``groups`` mesh axis only — each
  device holds the ``[N/Sg]`` nodes of its group block, so the decide tail
  (percent math, both grouped-order sorts, offsets, reaper mask) shards
  Sg-fold instead of replicating;
- pod arrays shard over BOTH axes ``[Sg, Pb/Sp]`` — each device sweeps
  ``P/(Sg*Sp)`` pod lanes;
- ONE ``jax.lax.psum`` over the ``pods`` axis (the stacked ``[3G+N]``
  single-collective trick from ``parallel.podaxis``) combines the pod
  partial sums; integer sums commute, so results are **bit-identical** to
  the single-device kernel on the same stacked cluster.

Cost model per tick, S = Sg*Sp devices (compare podaxis.py's, whose tail
term does not shard):

    total(Sg, Sp) = sweep(P)/(Sg*Sp) + psum(3*Gb + Nb) + tail(Nb)/1,
    where Gb = G/Sg, Nb = N/Sg   -> every term now shrinks with Sg.

Choosing the split: ``Sg`` as large as the group count allows (tail and
psum payload both shrink with Sg; decisions stay communication-free), ``Sp``
takes the rest when one group block's pod sweep still dominates (a giant
``default`` group). ``(Sg=S, Sp=1)`` degenerates to ``parallel.mesh``'s
layout; ``(Sg=1, Sp=S)`` to ``parallel.podaxis``'s.

Reference stakes: the serial O(P) aggregation loop this distributes is
/root/reference/pkg/k8s/util.go:27-38; the per-group sort the tail shards is
/root/reference/pkg/controller/sort.go:12-39; the reference runs both on one
CPU core per cluster with no distribution story at all (SURVEY.md §2.7).

Round 6: the combined-ordering sort this module's per-block tail runs (via
kernel.decide) was extracted to ``ops.order_tail.combined_order_sort``, and
the same group-block-sharding idea became a standalone tail
(``order_tail.make_sharded_order_tail``) that ``parallel.podaxis`` wires
into its ordered decider — this module and the pod-axis path now consume
literally the same ordering program, so their window semantics cannot
drift.
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Sequence

import numpy as np

from escalator_tpu.jaxconfig import ensure_x64, guarded_devices, shard_map

ensure_x64()

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from escalator_tpu.core.arrays import ClusterArrays, PodArrays
from escalator_tpu.ops import device_state as _ds  # noqa: F401  (registers SoA pytrees)
from escalator_tpu.ops import kernel
from escalator_tpu.parallel.mesh import GROUP_AXIS

POD_AXIS = "pods"


def make_grid_mesh(
    devices: Optional[Sequence[jax.Device]] = None,
    num_group_shards: Optional[int] = None,
) -> Mesh:
    """2-D ``(groups, pods)`` mesh. ``num_group_shards`` (Sg) defaults to the
    device count (pure group sharding, Sp=1); pass a divisor of the device
    count to give the pod axis the remaining factor.

    Multi-host note: keep each ``groups`` row within one host when possible —
    the per-tick psum then rides ICI; the ``groups`` axis needs no collective
    traffic at all, so it is the axis that can safely span DCN (the same
    layout logic as mesh.make_hybrid_mesh, scaling-book recipe)."""
    devs = list(devices) if devices is not None else guarded_devices()
    n = len(devs)
    sg = n if num_group_shards is None else int(num_group_shards)
    if sg < 1 or n % sg != 0:
        raise ValueError(f"num_group_shards={sg} must divide {n} devices")
    return Mesh(np.array(devs).reshape(sg, n // sg), (GROUP_AXIS, POD_AXIS))


def _cluster_specs() -> ClusterArrays:
    """Spec pytree matching ClusterArrays' flattened leaf structure (the
    cluster flattens its SoA fields inline, so each leaf needs its own spec):
    pods over both mesh axes, groups/nodes over the group axis only."""
    from escalator_tpu.core.arrays import GroupArrays, NodeArrays

    soa = lambda cls, spec: cls(**{f: spec for f in cls.__dataclass_fields__})
    return ClusterArrays(
        groups=soa(GroupArrays, P(GROUP_AXIS)),
        pods=soa(PodArrays, P(GROUP_AXIS, POD_AXIS)),
        nodes=soa(NodeArrays, P(GROUP_AXIS)),
    )


def pad_stacked_pods_for_grid(cluster: ClusterArrays, mesh: Mesh) -> ClusterArrays:
    """Pad the per-shard pod axis (dim 1 of the stacked ``[Sg, Pb]`` pod
    leaves) to a multiple of the ``pods`` mesh axis size; padding lanes are
    valid=False, masked inside the kernel. No-op when already aligned."""
    sp = int(mesh.shape[POD_AXIS])
    p = cluster.pods
    Pb = int(p.valid.shape[1])
    pad = (-Pb) % sp
    if pad == 0:
        return cluster
    width = ((0, 0), (0, pad))
    pods = PodArrays(
        group=np.pad(np.asarray(p.group), width),
        cpu_milli=np.pad(np.asarray(p.cpu_milli), width),
        mem_bytes=np.pad(np.asarray(p.mem_bytes), width),
        node=np.pad(np.asarray(p.node), width, constant_values=-1),
        valid=np.pad(np.asarray(p.valid), width, constant_values=False),
    )
    return ClusterArrays(groups=cluster.groups, pods=pods, nodes=cluster.nodes)


def place_grid(cluster: ClusterArrays, mesh: Mesh) -> ClusterArrays:
    """Device-put a stacked ``[Sg, ...]`` cluster with the grid layout: pods
    split over both mesh axes, groups/nodes over the group axis (each group
    block's nodes live only on its mesh row)."""
    cluster = pad_stacked_pods_for_grid(cluster, mesh)
    pod_sh = NamedSharding(mesh, P(GROUP_AXIS, POD_AXIS))
    row_sh = NamedSharding(mesh, P(GROUP_AXIS))
    put = lambda soa, sh: type(soa)(
        **{f: jax.device_put(getattr(soa, f), sh)
           for f in soa.__dataclass_fields__}
    )
    return ClusterArrays(
        groups=put(cluster.groups, row_sh),
        pods=put(cluster.pods, pod_sh),
        nodes=put(cluster.nodes, row_sh),
    )


def make_grid_decider(mesh: Mesh, impl: Optional[str] = None,
                      with_orders: bool = True):
    """jitted ``(stacked_cluster, now_sec) -> DecisionArrays`` over the 2-D
    grid. Outputs carry the leading shard axis (sharded over ``groups``,
    replicated over ``pods``) — the same contract as
    ``mesh.make_sharded_decider``, so backends consume either
    interchangeably. Bit-identical to ``vmap(kernel.decide)`` on the same
    stacked cluster (integer pod partials psum exactly; the tail runs
    locally per group block on its full node set).

    ``impl`` follows ESCALATOR_TPU_KERNEL_IMPL when omitted, as everywhere.
    The per-shard pod axis must be a multiple of the ``pods`` mesh axis
    (:func:`pad_stacked_pods_for_grid`). ``with_orders=False`` is the
    lazy-orders light variant (kernel.decide docstring): the grid's whole
    reason to exist is sharding the sort-dominated decide tail — the light
    program removes that tail entirely on steady ticks."""
    if impl is None:
        impl = kernel.default_impl()

    @jax.jit
    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(_cluster_specs(), P()),
        out_specs=P(GROUP_AXIS),
        # pallas_call cannot express varying-mesh-axes metadata yet (same
        # constraint as mesh.make_sharded_decider / podaxis)
        check_vma=(impl != "pallas"),
    )
    def grid_decide(cluster: ClusterArrays, now_sec) -> kernel.DecisionArrays:
        def one_block(c: ClusterArrays):
            G = c.groups.valid.shape[0]
            N = c.nodes.valid.shape[0]
            partials = kernel.aggregate_pods(c.pods, c.nodes.group, G, N, impl)
            # one stacked [3G+N] collective over the pod axis, not one per
            # field (the podaxis._build_pod_sweep trick); int64 -> exact
            flat = jnp.concatenate([x.reshape(-1) for x in partials])
            flat = jax.lax.psum(flat, POD_AXIS)
            pod_aggs = (flat[:G], flat[G:2 * G], flat[2 * G:3 * G], flat[3 * G:])
            node_aggs = kernel.aggregate_nodes(c.nodes, G, impl)
            return kernel.decide(
                c, now_sec, impl=impl, aggregates=(pod_aggs, node_aggs),
                with_orders=with_orders,
            )

        return jax.vmap(one_block)(cluster)

    return grid_decide


def make_grid_delta_decider(mesh: Mesh):
    """Round-8 incremental decide over the 2-D grid: jitted
    ``(stacked_groups, stacked_nodes, stacked_aggs, stacked_prev_cols,
    dirty_idx, now_sec) -> (stacked DecisionArrays, stacked
    GroupAggregates)`` where every input carries the grid's leading
    ``[Sg, ...]`` shard axis and ``dirty_idx`` is ``[Sg, D]`` — each group
    block's dirty rows compacted on the host per shard (pad entries = Gb,
    the block-local group capacity; same :func:`kernel.dirty_indices`
    policy, with D the max bucket across blocks so shapes agree).

    Dirty masks live per shard (``stacked_aggs.dirty[s]``), and every term
    is block-local: the compacted ``[D]`` decision math, the persistent
    column scatters, and the O(Nb) elementwise tail all run inside the
    block's mesh row with ZERO collectives — the lazy/steady incremental
    tick needs no pod axis at all (the aggregates are persistent; the pod
    sweep and its psum exist only on full-recompute ticks), which is the
    entire point. The body is literally ``kernel._delta_decide_core`` per
    block, so per-block outputs are bit-identical to the single-device
    delta path on the same block (tests/test_incremental_decide.py pins
    it). Aggregates and prev columns are donated (persistent device
    state, same protocol as ``kernel.delta_decide_jit``)."""
    from escalator_tpu.core.arrays import GroupArrays, NodeArrays
    from escalator_tpu.ops.kernel import GROUP_DECISION_FIELDS, GroupAggregates

    soa = lambda cls, spec: cls(**{f: spec for f in cls.__dataclass_fields__})
    row = P(GROUP_AXIS)
    in_specs = (
        soa(GroupArrays, row),
        soa(NodeArrays, row),
        GroupAggregates(*([row] * 11)),
        tuple(row for _ in GROUP_DECISION_FIELDS),
        row,
        P(),
    )

    @partial(jax.jit, donate_argnums=(2, 3))
    @partial(
        shard_map,
        mesh=mesh,
        in_specs=in_specs,
        out_specs=(row, row),
    )
    def grid_delta_decide(groups, nodes, aggs, prev_cols, dirty_idx, now_sec):
        def one_block(g, n, a, p, d):
            return kernel._delta_decide_core(g, n, a, p, d, now_sec)

        return jax.vmap(one_block)(groups, nodes, aggs, prev_cols, dirty_idx)

    return grid_delta_decide


def time_grid_phases(mesh: Mesh, cluster: ClusterArrays, _timeit,
                     impl: Optional[str] = None) -> dict:
    """Phase split for the bench (cfg8 grid rows): the sharded pod sweep +
    psum ALONE vs the full grid decide — the difference is the (now
    group-sharded) tail. Mirrors podaxis.time_pod_sweep's role."""
    if impl is None:
        impl = kernel.default_impl()

    @jax.jit
    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(_cluster_specs(), ),
        out_specs=P(GROUP_AXIS),
        check_vma=(impl != "pallas"),
    )
    def sweep_only(cluster: ClusterArrays):
        def one_block(c):
            G = c.groups.valid.shape[0]
            N = c.nodes.valid.shape[0]
            partials = kernel.aggregate_pods(c.pods, c.nodes.group, G, N, impl)
            flat = jnp.concatenate([x.reshape(-1) for x in partials])
            return jax.lax.psum(flat, POD_AXIS)

        return jax.vmap(one_block)(cluster)

    sweep_med, _ = _timeit(
        lambda: jax.block_until_ready(sweep_only(cluster)))
    decider = make_grid_decider(mesh, impl=impl)
    total_med, _ = _timeit(
        lambda: jax.block_until_ready(decider(cluster, jnp.int64(0))))
    return {"sweep_ms": round(sweep_med, 3),
            "total_ms": round(total_med, 3),
            "tail_ms": round(total_med - sweep_med, 3)}
