"""Multi-host runtime wiring: ``jax.distributed`` + hybrid-mesh construction.

The reference scales out only as active/passive HA — one leader process does all
the work, standbys wait on a Lease (/root/reference/pkg/k8s/election.go:25,
cmd/main.go:157-185). The TPU framework ADDS scale-out of the decision plane
itself: N hosts × M chips form a global ``(dcn, ici)`` mesh, the nodegroup axis is
sharded over all chips, and fleet reductions ride layered collectives
(``parallel.mesh.make_fleet_decider``). Leader election remains for the
side-effect executors (taints, cloud API calls must have one writer); the compute
plane needs no leader — every host runs the same SPMD program.

Single-host (or test) use never needs this module: ``make_mesh``/``make_hybrid_mesh``
work on whatever ``jax.devices()`` shows. Call :func:`initialize` once per process
before first device use to join a multi-host fleet.
"""

from __future__ import annotations

import logging
import os
from typing import Optional

log = logging.getLogger("escalator_tpu.parallel")


def initialize(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> bool:
    """Join this process to a multi-host JAX fleet.

    Arguments default from the standard env vars (``JAX_COORDINATOR_ADDRESS``,
    ``JAX_NUM_PROCESSES``, ``JAX_PROCESS_ID``); on TPU pods JAX can also infer all
    three from the platform metadata, in which case calling with no arguments is
    correct. Returns True when distributed mode was initialised, False when the
    configuration is absent (single-host mode — not an error).
    """
    import jax

    coordinator_address = coordinator_address or os.environ.get(
        "JAX_COORDINATOR_ADDRESS"
    )
    env_np = os.environ.get("JAX_NUM_PROCESSES")
    env_pid = os.environ.get("JAX_PROCESS_ID")
    if num_processes is None and env_np is not None:
        num_processes = int(env_np)
    if process_id is None and env_pid is not None:
        process_id = int(env_pid)

    if coordinator_address is None and num_processes is None:
        if process_id is not None:
            # A lone JAX_PROCESS_ID means a broken fleet template, not intentional
            # single-host mode: degrading silently would leave every OTHER host
            # blocked in jax.distributed.initialize waiting for this one to join.
            raise RuntimeError(
                "partial distributed configuration: process_id is set but "
                "coordinator_address/num_processes are missing (check "
                "JAX_COORDINATOR_ADDRESS / JAX_NUM_PROCESSES)"
            )
        log.debug("no distributed configuration; staying single-host")
        return False

    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
    log.info(
        "joined distributed fleet: process %s/%s, %d global devices",
        jax.process_index(),
        jax.process_count(),
        len(jax.devices()),
    )
    return True


def global_hybrid_mesh():
    """The fleet-wide ``(dcn, ici)`` mesh for this (possibly multi-process) runtime.

    Under ``initialize()`` each process sees the same global ``jax.devices()`` list;
    the mesh therefore has one ``dcn`` row per host and every process compiles the
    identical SPMD program (shard_map handles the local-device addressing).
    """
    from escalator_tpu.parallel.mesh import make_hybrid_mesh

    return make_hybrid_mesh()
