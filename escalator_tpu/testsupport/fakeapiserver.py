"""In-repo fake kube-apiserver: real wire semantics over stdlib http.server.

The restclient module speaks the k8s REST list+watch protocol; this server is
its test double — the analog of the reference testing its client paths against
the fake clientset's reactors (pkg/test/builder.go), except here the fake sits
on the OTHER side of real HTTP so the transport, chunked watch streaming,
resourceVersion bookkeeping, 409 conflicts and 410 relists are all exercised.

Implemented surface (what the controller + elector touch):

- ``GET /api/v1/{pods,nodes}`` — list (with fieldSelector) and chunked watch
  (``?watch=true&resourceVersion=N&timeoutSeconds=T``). A MODIFIED object that
  leaves a field-selector's match set is delivered as DELETED to that watcher,
  matching apiserver behavior for ``status.phase!=Succeeded`` informers.
- ``GET/PUT/DELETE /api/v1/nodes/{name}`` (and namespaced pods) — PUT enforces
  optimistic concurrency: a stale ``metadata.resourceVersion`` is 409.
- ``POST /api/v1/namespaces/{ns}/events`` — append to :attr:`events`.
- ``GET/POST/PUT .../coordination.k8s.io/v1/.../leases`` — Lease CRUD with the
  same resourceVersion CAS; POST of an existing lease is 409 AlreadyExists.
- Watches older than the retained history window get a 410 ERROR event
  (drives the client's relist path deterministically via ``compact_history``).
- Optional bearer-token auth (401 on mismatch) to exercise auth plumbing.
"""

from __future__ import annotations

import json
import threading
import time
from collections import deque
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Deque, Dict, List, Optional, Tuple
from urllib.parse import parse_qs, urlsplit


def _match_field_selector(selector: str, obj: dict) -> bool:
    """Supports the conjunctive =/!= grammar the reference informers use
    (pkg/k8s/cache.go:17: status.phase!=Succeeded,status.phase!=Failed)."""
    if not selector:
        return True
    for clause in selector.split(","):
        clause = clause.strip()
        if not clause:
            continue
        if "!=" in clause:
            path, want = clause.split("!=", 1)
            negate = True
        else:
            path, want = clause.split("=", 1)
            negate = False
        cur = obj
        for part in path.strip().split("."):
            cur = (cur or {}).get(part) if isinstance(cur, dict) else None
        value = "" if cur is None else str(cur)
        if negate and value == want:
            return False
        if not negate and value != want:
            return False
    return True


class _State:
    """Cluster state + watch history, guarded by one lock/condition."""

    def __init__(self, history_window: int = 4096):
        self.lock = threading.Lock()
        self.cond = threading.Condition(self.lock)
        self.rv = 0
        #: collection path -> {key -> obj}; keys are "ns/name" or "name"
        self.collections: Dict[str, Dict[str, dict]] = {
            "/api/v1/pods": {},
            "/api/v1/nodes": {},
        }
        self.leases: Dict[str, dict] = {}  # "ns/name" -> lease obj
        self.events: List[dict] = []
        #: (rv, collection, type, obj, prev_obj) — prev_obj drives selector
        #: transition logic for filtered watchers
        self.history: Deque[Tuple[int, str, str, dict, Optional[dict]]] = deque(
            maxlen=history_window
        )
        self.oldest_rv = 0  # watches at rv < oldest_rv get 410

    def next_rv(self) -> int:
        self.rv += 1
        return self.rv

    def apply(self, collection: str, etype: str, key: str, obj: dict,
              prev: Optional[dict]) -> dict:
        """Record a write under the lock; stamps resourceVersion, appends to
        watch history, wakes watchers."""
        rv = self.next_rv()
        obj.setdefault("metadata", {})["resourceVersion"] = str(rv)
        if etype == "DELETED":
            self.collections[collection].pop(key, None)
        else:
            self.collections[collection][key] = obj
        self.history.append((rv, collection, etype, obj, prev))
        self.cond.notify_all()
        return obj


class FakeApiserver:
    def __init__(self, token: str = "", history_window: int = 4096):
        self.state = _State(history_window=history_window)
        self.token = token
        handler = _make_handler(self)
        self.httpd = ThreadingHTTPServer(("127.0.0.1", 0), handler)
        self.httpd.daemon_threads = True
        self._thread = threading.Thread(
            target=self.httpd.serve_forever, daemon=True, name="fake-apiserver")

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> "FakeApiserver":
        self._thread.start()
        return self

    def stop(self) -> None:
        self.httpd.shutdown()
        self.httpd.server_close()

    @property
    def url(self) -> str:
        host, port = self.httpd.server_address[:2]
        return f"http://{host}:{port}"

    def __enter__(self) -> "FakeApiserver":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- python-side cluster manipulation (goes through the same write path
    # as HTTP, so watches fire) ---------------------------------------------
    @staticmethod
    def _key(obj: dict) -> str:
        meta = obj.get("metadata") or {}
        ns = meta.get("namespace")
        return f"{ns}/{meta['name']}" if ns else meta["name"]

    def put_object(self, collection: str, obj: dict) -> dict:
        obj = json.loads(json.dumps(obj))
        with self.state.lock:
            key = self._key(obj)
            prev = self.state.collections[collection].get(key)
            etype = "MODIFIED" if prev is not None else "ADDED"
            return self.state.apply(collection, etype, key, obj, prev)

    def delete_object(self, collection: str, key: str) -> bool:
        with self.state.lock:
            prev = self.state.collections[collection].get(key)
            if prev is None:
                return False
            self.state.apply(collection, "DELETED", key, dict(prev), prev)
            return True

    def add_node(self, obj: dict) -> dict:
        return self.put_object("/api/v1/nodes", obj)

    def add_pod(self, obj: dict) -> dict:
        return self.put_object("/api/v1/pods", obj)

    def set_pod_phase(self, namespace: str, name: str, phase: str) -> None:
        with self.state.lock:
            key = f"{namespace}/{name}"
            prev = self.state.collections["/api/v1/pods"].get(key)
            if prev is None:
                raise KeyError(key)
            obj = json.loads(json.dumps(prev))
            obj.setdefault("status", {})["phase"] = phase
            self.state.apply("/api/v1/pods", "MODIFIED", key, obj, prev)

    def compact_history(self) -> None:
        """Forget all watch history: any watch from an old resourceVersion now
        gets 410 Gone (deterministic trigger for the client's relist path)."""
        with self.state.lock:
            self.state.history.clear()
            self.state.oldest_rv = self.state.rv + 1

    @property
    def events(self) -> List[dict]:
        with self.state.lock:
            return list(self.state.events)

    def lease(self, namespace: str, name: str) -> Optional[dict]:
        with self.state.lock:
            obj = self.state.leases.get(f"{namespace}/{name}")
            return json.loads(json.dumps(obj)) if obj else None


def _make_handler(server: FakeApiserver):
    state = server.state

    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, fmt, *args):  # quiet
            pass

        # -- plumbing ------------------------------------------------------
        def _send_json(self, code: int, obj: dict) -> None:
            payload = json.dumps(obj).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(payload)))
            self.end_headers()
            self.wfile.write(payload)

        def _status(self, code: int, reason: str, message: str) -> None:
            self._send_json(code, {
                "kind": "Status", "apiVersion": "v1", "status": "Failure",
                "reason": reason, "message": message, "code": code,
            })

        def _read_body(self) -> dict:
            length = int(self.headers.get("Content-Length") or 0)
            raw = self.rfile.read(length) if length else b""
            return json.loads(raw) if raw else {}

        def _valid_lease(self, body: dict) -> bool:
            """coordination/v1 ValidateLeaseSpec: leaseDurationSeconds, if set,
            must be > 0 — a real apiserver 422s otherwise, so the fake must too
            (a 0 here once slipped through and would have livelocked election
            against a real cluster)."""
            spec = body.get("spec") or {}
            dur = spec.get("leaseDurationSeconds")
            if dur is not None and (not isinstance(dur, int) or dur <= 0):
                self._status(422, "Invalid",
                             "spec.leaseDurationSeconds must be greater than 0")
                return False
            return True

        def _authed(self) -> bool:
            if not server.token:
                return True
            got = self.headers.get("Authorization", "")
            if got == f"Bearer {server.token}":
                return True
            self._status(401, "Unauthorized", "bad bearer token")
            return False

        # -- routing -------------------------------------------------------
        def _route(self) -> Tuple[str, Optional[str], Optional[str], Dict[str, str]]:
            """Returns (collection, namespace, name, params). collection is the
            cluster-scoped canonical path ('/api/v1/pods', '/api/v1/nodes',
            'leases', 'events', or '')."""
            parts = urlsplit(self.path)
            params = {k: v[0] for k, v in parse_qs(parts.query).items()}
            seg = [s for s in parts.path.split("/") if s]
            # /api/v1/...
            if seg[:2] == ["api", "v1"]:
                rest = seg[2:]
                if rest[:1] == ["namespaces"] and len(rest) >= 3:
                    ns, kind = rest[1], rest[2]
                    name = rest[3] if len(rest) > 3 else None
                    if kind == "events":
                        return "events", ns, name, params
                    if kind == "pods":
                        return "/api/v1/pods", ns, name, params
                    return "", ns, name, params
                if rest[:1] == ["pods"]:
                    return "/api/v1/pods", None, rest[1] if len(rest) > 1 else None, params
                if rest[:1] == ["nodes"]:
                    return "/api/v1/nodes", None, rest[1] if len(rest) > 1 else None, params
            if seg[:2] == ["apis", "coordination.k8s.io"] and "leases" in seg:
                ns = seg[seg.index("namespaces") + 1] if "namespaces" in seg else "default"
                li = seg.index("leases")
                name = seg[li + 1] if len(seg) > li + 1 else None
                return "leases", ns, name, params
            return "", None, None, params

        # -- GET: single / list / watch ------------------------------------
        def do_GET(self) -> None:
            if not self._authed():
                return
            collection, ns, name, params = self._route()
            if collection == "leases":
                with state.lock:
                    obj = state.leases.get(f"{ns}/{name}")
                if obj is None:
                    self._status(404, "NotFound", f"lease {ns}/{name} not found")
                else:
                    self._send_json(200, obj)
                return
            if collection not in state.collections:
                self._status(404, "NotFound", f"no route {self.path}")
                return
            if name is not None:
                key = f"{ns}/{name}" if ns else name
                with state.lock:
                    obj = state.collections[collection].get(key)
                if obj is None:
                    self._status(404, "NotFound", f"{key} not found")
                else:
                    self._send_json(200, obj)
                return
            if params.get("watch") in ("true", "1"):
                self._watch(collection, ns, params)
                return
            selector = params.get("fieldSelector", "")
            with state.lock:
                items = [
                    o for k, o in sorted(state.collections[collection].items())
                    if _match_field_selector(selector, o)
                    and (ns is None or (o.get("metadata") or {}).get("namespace") == ns)
                ]
                rv = state.rv
            kind = "PodList" if collection.endswith("pods") else "NodeList"
            self._send_json(200, {
                "kind": kind, "apiVersion": "v1",
                "metadata": {"resourceVersion": str(rv)},
                "items": items,
            })

        def _watch(self, collection: str, ns: Optional[str],
                   params: Dict[str, str]) -> None:
            selector = params.get("fieldSelector", "")
            since = int(params.get("resourceVersion") or 0)
            timeout = float(params.get("timeoutSeconds") or 30)
            deadline = time.monotonic() + min(timeout, 120.0)

            def _matches(obj: Optional[dict]) -> bool:
                if obj is None:
                    return False
                if ns is not None and (obj.get("metadata") or {}).get("namespace") != ns:
                    return False
                return _match_field_selector(selector, obj)

            def _translate(etype: str, obj: dict, prev: Optional[dict]):
                """Field-selector transition semantics: entering the match set
                is ADDED, leaving it is DELETED (how the apiserver serves
                phase!=Succeeded watches)."""
                now_in, was_in = _matches(obj), _matches(prev)
                if etype == "DELETED":
                    return ("DELETED", obj) if was_in or now_in else None
                if etype == "ADDED":
                    return ("ADDED", obj) if now_in else None
                if now_in and was_in:
                    return ("MODIFIED", obj)
                if now_in:
                    return ("ADDED", obj)
                if was_in:
                    return ("DELETED", obj)
                return None

            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.send_header("Transfer-Encoding", "chunked")
            self.end_headers()

            def _write_event(etype: str, obj: dict) -> None:
                line = json.dumps({"type": etype, "object": obj}).encode() + b"\n"
                self.wfile.write(f"{len(line):x}\r\n".encode() + line + b"\r\n")
                self.wfile.flush()

            try:
                with state.lock:
                    if since and since < state.oldest_rv:
                        _write_event("ERROR", {
                            "kind": "Status", "code": 410, "reason": "Expired",
                            "message": f"resourceVersion {since} is too old",
                        })
                        self.wfile.write(b"0\r\n\r\n")
                        return
                    cursor = since
                    while True:
                        pending = [
                            h for h in state.history
                            if h[0] > cursor and h[1] == collection
                        ]
                        for rv, _, etype, obj, prev in pending:
                            out = _translate(etype, obj, prev)
                            cursor = rv
                            if out is not None:
                                state.lock.release()
                                try:
                                    _write_event(*out)
                                finally:
                                    state.lock.acquire()
                        remaining = deadline - time.monotonic()
                        if remaining <= 0:
                            break
                        state.cond.wait(min(remaining, 1.0))
                self.wfile.write(b"0\r\n\r\n")
            except (BrokenPipeError, ConnectionResetError):
                pass

        # -- writes --------------------------------------------------------
        def do_PUT(self) -> None:
            if not self._authed():
                return
            collection, ns, name, _ = self._route()
            body = self._read_body()
            if collection == "leases":
                if not self._valid_lease(body):
                    return
                key = f"{ns}/{name}"
                with state.lock:
                    current = state.leases.get(key)
                    if current is None:
                        self._status(404, "NotFound", f"lease {key} not found")
                        return
                    want_rv = (body.get("metadata") or {}).get("resourceVersion")
                    have_rv = (current.get("metadata") or {}).get("resourceVersion")
                    if want_rv is not None and str(want_rv) != str(have_rv):
                        self._status(409, "Conflict",
                                     f"resourceVersion {want_rv} != {have_rv}")
                        return
                    body.setdefault("metadata", {})["resourceVersion"] = str(
                        state.next_rv())
                    state.leases[key] = body
                self._send_json(200, body)
                return
            if collection not in state.collections or name is None:
                self._status(404, "NotFound", f"no route {self.path}")
                return
            key = f"{ns}/{name}" if ns else name
            with state.lock:
                current = state.collections[collection].get(key)
                if current is None:
                    self._status(404, "NotFound", f"{key} not found")
                    return
                want_rv = (body.get("metadata") or {}).get("resourceVersion")
                have_rv = (current.get("metadata") or {}).get("resourceVersion")
                if want_rv is not None and str(want_rv) != str(have_rv):
                    self._status(409, "Conflict",
                                 f"resourceVersion {want_rv} != {have_rv} for {key}")
                    return
                out = state.apply(collection, "MODIFIED", key, body, current)
            self._send_json(200, out)

        def do_POST(self) -> None:
            if not self._authed():
                return
            collection, ns, name, _ = self._route()
            body = self._read_body()
            if collection == "events":
                with state.lock:
                    body.setdefault("metadata", {})["resourceVersion"] = str(
                        state.next_rv())
                    state.events.append(body)
                self._send_json(201, body)
                return
            if collection == "leases":
                if not self._valid_lease(body):
                    return
                lease_name = (body.get("metadata") or {}).get("name", name)
                key = f"{ns}/{lease_name}"
                with state.lock:
                    if key in state.leases:
                        self._status(409, "AlreadyExists",
                                     f"lease {key} already exists")
                        return
                    body.setdefault("metadata", {})["resourceVersion"] = str(
                        state.next_rv())
                    state.leases[key] = body
                self._send_json(201, body)
                return
            if collection in state.collections:
                with state.lock:
                    meta = body.setdefault("metadata", {})
                    if ns:
                        meta.setdefault("namespace", ns)
                    key = (f"{meta.get('namespace')}/{meta['name']}"
                           if meta.get("namespace") else meta["name"])
                    if key in state.collections[collection]:
                        self._status(409, "AlreadyExists", f"{key} exists")
                        return
                    out = state.apply(collection, "ADDED", key, body, None)
                self._send_json(201, out)
                return
            self._status(404, "NotFound", f"no route {self.path}")

        def do_DELETE(self) -> None:
            if not self._authed():
                return
            collection, ns, name, _ = self._route()
            if collection not in state.collections or name is None:
                self._status(404, "NotFound", f"no route {self.path}")
                return
            key = f"{ns}/{name}" if ns else name
            with state.lock:
                prev = state.collections[collection].get(key)
                if prev is None:
                    self._status(404, "NotFound", f"{key} not found")
                    return
                state.apply(collection, "DELETED", key, dict(prev), prev)
            self._send_json(200, {"kind": "Status", "status": "Success"})

    return Handler
