"""In-memory mock cloud provider — mirror of the reference's test provider
(/root/reference/pkg/test/cloud_provider.go:14-176). Also used by the simulation /
dry-run tooling as a pure in-process provider."""

from __future__ import annotations

from typing import Dict, List, Optional

from escalator_tpu.cloudprovider import interface as cp
from escalator_tpu.k8s import types as k8s

PROVIDER_NAME = "test"


class MockInstance(cp.Instance):
    def __init__(self, instance_id: str = "", instantiation_time: float = 0.0):
        self._id = instance_id
        self._time = instantiation_time

    def instantiation_time(self) -> float:
        return self._time

    def id(self) -> str:
        return self._id


class MockNodeGroup(cp.NodeGroup):
    """Tracks target/actual size through increase/delete/decrease
    (reference: cloud_provider.go:81-176)."""

    def __init__(self, group_id: str, name: str, min_size: int, max_size: int,
                 target_size: int):
        self._id = group_id
        self._name = name
        self._min = min_size
        self._max = max_size
        self._target = target_size
        self._actual = target_size
        # test hooks
        self.increase_calls: List[int] = []
        self.deleted_nodes: List[str] = []

    def id(self) -> str:
        return self._id

    def name(self) -> str:
        return self._name

    def min_size(self) -> int:
        return self._min

    def max_size(self) -> int:
        return self._max

    def target_size(self) -> int:
        return self._target

    def size(self) -> int:
        return self._actual

    def _set_desired_size(self, new_size: int) -> None:
        self._target = new_size
        self._actual = new_size

    def increase_size(self, delta: int) -> None:
        self.increase_calls.append(delta)
        self._set_desired_size(self._target + delta)

    def delete_nodes(self, *nodes: k8s.Node) -> None:
        for node in nodes:
            self.deleted_nodes.append(node.name)
            self._set_desired_size(self._target - 1)

    def belongs(self, node: k8s.Node) -> bool:
        return False

    def decrease_target_size(self, delta: int) -> None:
        self._set_desired_size(self._target + delta)

    def nodes(self) -> List[str]:
        return []


class MockCloudProvider(cp.CloudProvider):
    def __init__(self):
        self._node_groups: Dict[str, MockNodeGroup] = {}
        self.refresh_count = 0
        self.fail_refreshes = 0  # fault injection: fail the next N refresh() calls

    def name(self) -> str:
        return PROVIDER_NAME

    def node_groups(self) -> List[cp.NodeGroup]:
        return list(self._node_groups.values())

    def get_node_group(self, group_id: str) -> Optional[MockNodeGroup]:
        return self._node_groups.get(group_id)

    def register_node_groups(self, *configs: cp.NodeGroupConfig) -> None:
        pass

    def register_node_group(self, node_group: MockNodeGroup) -> None:
        self._node_groups[node_group.id()] = node_group

    def refresh(self) -> None:
        self.refresh_count += 1
        if self.fail_refreshes > 0:
            self.fail_refreshes -= 1
            raise RuntimeError("injected refresh failure")

    def get_instance(self, node: k8s.Node) -> cp.Instance:
        return MockInstance(node.provider_id, 0.0)


class MockBuilder(cp.Builder):
    def __init__(self, provider: Optional[MockCloudProvider] = None):
        self.provider = provider or MockCloudProvider()
        self.build_count = 0

    def build(self) -> MockCloudProvider:
        self.build_count += 1
        return self.provider
