"""Deterministic two-group streaming-ingestion world — the ONE definition
of the event-vs-relist parity fixture shared by `bench.py --smoke` and
`tests/test_event_ingest_parity.py` (the smoke and the test suite must keep
asserting the same contract, so they must drive the same world).

Objects are explicitly named (the builders' global name counter would make
two separately-built worlds drift otherwise).
"""

from __future__ import annotations

from escalator_tpu.controller import node_group as ngmod
from escalator_tpu.core import semantics as sem
from escalator_tpu.k8s.cache import EventfulClient, GroupFilters
from escalator_tpu.testsupport.builders import (
    NodeOpts,
    PodOpts,
    build_test_node,
    build_test_pod,
)

GROUPS = ("alpha", "beta")
LABEL_KEY = "customer"


def stream_pod(name, group, cpu=500, mem=10**9, node=""):
    return build_test_pod(PodOpts(
        name=name, cpu=[cpu], mem=[mem],
        node_selector_key=LABEL_KEY, node_selector_value=group,
        node_name=node))


def stream_node(name, group, cpu=4000, mem=16 * 10**9, creation=1):
    return build_test_node(NodeOpts(
        name=name, cpu=cpu, mem=mem, label_key=LABEL_KEY, label_value=group,
        creation_time_ns=creation * 10**9))


def stream_filters(values=GROUPS):
    """One GroupFilters per group value — the same predicates the listers
    resolve with (controller.node_group)."""
    return [
        GroupFilters(
            name=v,
            pod_filter=ngmod.new_pod_affinity_filter_func(LABEL_KEY, v),
            node_filter=ngmod.new_node_label_filter_func(LABEL_KEY, v),
        )
        for v in values
    ]


def stream_configs(n):
    return [
        sem.GroupConfig(
            min_nodes=0, max_nodes=100, taint_lower_percent=30,
            taint_upper_percent=45, scale_up_percent=70,
            slow_removal_rate=1, fast_removal_rate=2,
            soft_delete_grace_sec=300, hard_delete_grace_sec=900,
        )
        for _ in range(n)
    ]


def stream_world(nodes_per_group=4, pods_per_group=14) -> EventfulClient:
    """EventfulClient holding the deterministic two-group world: per group,
    `nodes_per_group` nodes (distinct creation times) and `pods_per_group`
    pods bound round-robin onto them."""
    client = EventfulClient()
    for g, val in enumerate(GROUPS):
        for i in range(nodes_per_group):
            client.add_node(stream_node(
                f"{val}-n{i}", val, creation=10 * g + i + 1))
        for i in range(pods_per_group):
            client.add_pod(stream_pod(
                f"{val}-p{i}", val, node=f"{val}-n{i % nodes_per_group}"))
    return client
