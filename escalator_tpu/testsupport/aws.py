"""Fake AWS SDK clients at the boto3 dict-API level — mirror of the reference's
SDK-interface mocks (/root/reference/pkg/test/aws.go:12-96). Canned outputs/errors
per call, plus call recording for assertions."""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional


@dataclass
class FakeAutoScaling:
    """Mock of the autoscaling client surface the provider touches."""

    groups: Dict[str, Dict] = field(default_factory=dict)
    describe_error: Optional[Exception] = None
    set_desired_error: Optional[Exception] = None
    attach_error: Optional[Exception] = None
    calls: List = field(default_factory=list)

    def describe_auto_scaling_groups(self, AutoScalingGroupNames=None, **kw):
        self.calls.append(("describe_auto_scaling_groups", AutoScalingGroupNames))
        if self.describe_error is not None:
            raise self.describe_error
        names = AutoScalingGroupNames or list(self.groups)
        return {
            "AutoScalingGroups": [
                self.groups[n] for n in names if n in self.groups
            ]
        }

    def set_desired_capacity(self, AutoScalingGroupName, DesiredCapacity, **kw):
        self.calls.append(
            ("set_desired_capacity", AutoScalingGroupName, DesiredCapacity)
        )
        if self.set_desired_error is not None:
            raise self.set_desired_error
        self.groups[AutoScalingGroupName]["DesiredCapacity"] = DesiredCapacity
        return {}

    def terminate_instance_in_auto_scaling_group(
        self, InstanceId, ShouldDecrementDesiredCapacity, **kw
    ):
        self.calls.append(
            ("terminate_instance_in_auto_scaling_group", InstanceId,
             ShouldDecrementDesiredCapacity)
        )
        for g in self.groups.values():
            instances = g.get("Instances", [])
            for i, inst in enumerate(instances):
                if inst["InstanceId"] == InstanceId:
                    instances.pop(i)
                    if ShouldDecrementDesiredCapacity:
                        g["DesiredCapacity"] -= 1
                    return {"Activity": {"Description": f"terminated {InstanceId}"}}
        return {"Activity": {"Description": f"{InstanceId} not found"}}

    def attach_instances(self, AutoScalingGroupName, InstanceIds, **kw):
        self.calls.append(("attach_instances", AutoScalingGroupName, list(InstanceIds)))
        if self.attach_error is not None:
            raise self.attach_error
        g = self.groups[AutoScalingGroupName]
        g.setdefault("Instances", []).extend(
            {"InstanceId": i, "AvailabilityZone": "us-east-1a"} for i in InstanceIds
        )
        g["DesiredCapacity"] = g.get("DesiredCapacity", 0) + len(InstanceIds)
        return {}

    def create_or_update_tags(self, Tags, **kw):
        self.calls.append(("create_or_update_tags", Tags))
        for tag in Tags:
            g = self.groups.get(tag["ResourceId"])
            if g is not None:
                g.setdefault("Tags", []).append(
                    {"Key": tag["Key"], "Value": tag["Value"]}
                )
        return {}


@dataclass
class FakeEC2:
    """Mock of the ec2 client surface the provider touches."""

    instances: Dict[str, Dict] = field(default_factory=dict)
    fleet_instance_ids: List[str] = field(default_factory=list)
    fleet_errors: List[Dict] = field(default_factory=list)
    all_instances_ready: bool = True
    create_fleet_error: Optional[Exception] = None
    calls: List = field(default_factory=list)
    _fleet_counter: int = 0

    def create_fleet(self, **fleet_input):
        self.calls.append(("create_fleet", fleet_input))
        if self.create_fleet_error is not None:
            raise self.create_fleet_error
        ids = list(self.fleet_instance_ids)
        if not ids and not self.fleet_errors:
            count = fleet_input["TargetCapacitySpecification"]["TotalTargetCapacity"]
            ids = []
            for _ in range(count):
                self._fleet_counter += 1
                ids.append(f"i-fleet{self._fleet_counter:04d}")
        for i in ids:
            self.instances.setdefault(
                i,
                {"InstanceId": i, "LaunchTime": 0.0,
                 "State": {"Name": "running"}},
            )
        return {"Instances": [{"InstanceIds": ids}] if ids else [],
                "Errors": list(self.fleet_errors)}

    def describe_instance_status(self, InstanceIds, IncludeAllInstances=False, **kw):
        self.calls.append(("describe_instance_status", list(InstanceIds)))
        state = "running" if self.all_instances_ready else "pending"
        return {
            "InstanceStatuses": [
                {"InstanceId": i, "InstanceState": {"Name": state}}
                for i in InstanceIds
            ]
        }

    def describe_instances(self, InstanceIds, **kw):
        self.calls.append(("describe_instances", list(InstanceIds)))
        found = [self.instances[i] for i in InstanceIds if i in self.instances]
        return {"Reservations": [{"Instances": found}]} if found else {
            "Reservations": []
        }

    def terminate_instances(self, InstanceIds, **kw):
        self.calls.append(("terminate_instances", list(InstanceIds)))
        for i in InstanceIds:
            self.instances.pop(i, None)
        return {}


def make_asg(name: str, min_size=0, max_size=10, desired=0, instance_ids=(),
             vpc_zone_identifier="subnet-1,subnet-2", az="us-east-1a"):
    return {
        "AutoScalingGroupName": name,
        "MinSize": min_size,
        "MaxSize": max_size,
        "DesiredCapacity": desired,
        "VPCZoneIdentifier": vpc_zone_identifier,
        "Instances": [
            {"InstanceId": i, "AvailabilityZone": az} for i in instance_ids
        ],
        "Tags": [],
    }
