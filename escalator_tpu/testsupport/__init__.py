"""Shared test-harness utilities (SURVEY.md §2.6 analog)."""

def soak_scale() -> int:
    """Multiplier for the soak tests' event/tick volume, from
    ESCALATOR_TPU_SOAK_SCALE (default 1 — what CI runs). Thread counts are
    NOT scaled: intensity should grow linearly and comparably across the
    soaks. Invalid values fall back to 1 with a warning rather than failing
    collection for the whole pytest session."""
    import logging
    import os

    raw = os.environ.get("ESCALATOR_TPU_SOAK_SCALE", "1")
    try:
        return max(1, int(raw))
    except ValueError:
        logging.getLogger("escalator_tpu.testsupport").warning(
            "ignoring malformed ESCALATOR_TPU_SOAK_SCALE=%r", raw)
        return 1
