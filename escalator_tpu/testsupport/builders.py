"""Test object builders — the framework's equivalent of the reference's fake-cluster
generator (/root/reference/pkg/test/builder.go:104-296). Used by the test suite and the
benchmark harness to synthesize clusters of arbitrary size."""

from __future__ import annotations

import itertools
import time
from dataclasses import dataclass, field
from typing import List, Optional, Sequence

from escalator_tpu.k8s import types as k8s

_counter = itertools.count()


@dataclass
class NodeOpts:
    name: str = ""
    cpu: int = 0              # allocatable cpu milli
    mem: int = 0              # allocatable memory bytes
    label_key: str = "customer"
    label_value: str = "buildeng"
    creation_time_ns: int = 0
    tainted: bool = False
    taint_time_sec: Optional[int] = None
    cordoned: bool = False
    no_delete: bool = False


def build_test_node(opts: NodeOpts) -> k8s.Node:
    name = opts.name or f"n{next(_counter)}"
    taints: List[k8s.Taint] = []
    if opts.tainted:
        ts = opts.taint_time_sec if opts.taint_time_sec is not None else int(time.time())
        taints.append(
            k8s.Taint(key=k8s.TO_BE_REMOVED_BY_AUTOSCALER_KEY, value=str(ts))
        )
    annotations = {}
    if opts.no_delete:
        annotations[k8s.NODE_ESCALATOR_IGNORE_ANNOTATION] = "test"
    return k8s.Node(
        name=name,
        creation_time_ns=opts.creation_time_ns,
        cpu_allocatable_milli=opts.cpu,
        mem_allocatable_bytes=opts.mem,
        labels={opts.label_key: opts.label_value},
        annotations=annotations,
        taints=taints,
        unschedulable=opts.cordoned,
        provider_id=name,
    )


def build_test_nodes(amount: int, opts: NodeOpts) -> List[k8s.Node]:
    out = []
    for _ in range(amount):
        o = NodeOpts(**{**opts.__dict__, "name": ""})
        out.append(build_test_node(o))
    return out


@dataclass
class PodOpts:
    name: str = ""
    namespace: str = "default"
    cpu: Sequence[int] = field(default_factory=list)   # per-container cpu milli
    mem: Sequence[int] = field(default_factory=list)   # per-container mem bytes
    node_selector_key: str = ""
    node_selector_value: str = ""
    owner: str = ""
    node_affinity_key: str = ""
    node_affinity_value: str = ""
    node_affinity_op: str = k8s.NodeSelectorOperator.IN.value
    node_name: str = ""
    cpu_overhead: int = 0
    mem_overhead: int = 0
    init_containers_cpu: Sequence[int] = field(default_factory=list)
    init_containers_mem: Sequence[int] = field(default_factory=list)
    static: bool = False


def build_test_pod(opts: PodOpts) -> k8s.Pod:
    containers = [
        k8s.ResourceRequests(cpu_milli=c, mem_bytes=m)
        for c, m in zip(opts.cpu, opts.mem, strict=True)
    ]
    init_containers = [
        k8s.ResourceRequests(cpu_milli=c, mem_bytes=m)
        for c, m in zip(opts.init_containers_cpu, opts.init_containers_mem,
                        strict=True)
    ]
    overhead = None
    if opts.cpu_overhead > 0 or opts.mem_overhead > 0:
        overhead = k8s.ResourceRequests(
            cpu_milli=max(opts.cpu_overhead, 0), mem_bytes=max(opts.mem_overhead, 0)
        )
    node_selector = {}
    if opts.node_selector_key or opts.node_selector_value:
        node_selector[opts.node_selector_key] = opts.node_selector_value
    affinity = None
    if opts.node_affinity_key or opts.node_affinity_value:
        affinity = k8s.Affinity(
            has_node_affinity=True,
            node_affinity_required_terms=(
                k8s.NodeSelectorTerm(
                    match_expressions=(
                        k8s.NodeSelectorRequirement(
                            key=opts.node_affinity_key,
                            operator=opts.node_affinity_op,
                            values=(opts.node_affinity_value,),
                        ),
                    )
                ),
            ),
        )
    annotations = {}
    if opts.static:
        annotations[k8s.STATIC_POD_ANNOTATION] = "file"
    return k8s.Pod(
        name=opts.name or f"p{next(_counter)}",
        namespace=opts.namespace,
        node_name=opts.node_name,
        containers=containers,
        init_containers=init_containers,
        overhead=overhead,
        node_selector=node_selector,
        affinity=affinity,
        owner_kind=opts.owner,
        annotations=annotations,
    )


def build_test_pods(amount: int, opts: PodOpts) -> List[k8s.Pod]:
    out = []
    for i in range(amount):
        o = PodOpts(**{**opts.__dict__, "name": f"p{i}-{next(_counter)}"})
        out.append(build_test_pod(o))
    return out
