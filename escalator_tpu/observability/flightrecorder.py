"""Tick flight recorder: a fixed-size ring of the last N tick timelines.

The span layer (spans.py) produces one :class:`~escalator_tpu.observability.
spans.Timeline` per tick root; this module keeps the last N of them as
structured records — phase durations, backend/impl, dirty-group count,
refresh-audit outcome, decision digest, and the jax.monitoring compile /
transfer deltas that happened inside the tick — so the moments *before* an
incident are always reconstructible:

- **automatic dumps** on incidents: the tick watchdog dumps before its
  crash-to-restart exit (cli.py), and the incremental refresh audit dumps on
  a mismatch (ops/device_state.py) — the ring then carries exactly the ticks
  whose deltas diverged;
- **on-demand dumps**: ``escalator-tpu debug-dump`` (CLI) and the plugin's
  ``Dump`` method pull the same JSON from a live process.

The recorder is process-global and always on (a record is a small dict; the
ring is bounded by ``ESCALATOR_TPU_FLIGHT_RECORDER_SIZE``, default 256).
Recording happens in the root-complete hook, i.e. on the tick thread but
after all timed phases closed — it adds nothing to any phase duration.
:func:`install` also feeds the Prometheus per-phase histograms
(``escalator_tpu_tick_phase_seconds{backend,phase}``) from the same
completed timelines, so the metrics and the recorder can never disagree
about what a phase cost.
"""

from __future__ import annotations

import collections
import json
import os
import time
from typing import Any, Dict, List, Optional

from escalator_tpu.analysis import lockwitness
from escalator_tpu.observability import histograms, jaxmon, spans

DEFAULT_CAPACITY = int(os.environ.get("ESCALATOR_TPU_FLIGHT_RECORDER_SIZE",
                                      "256"))

#: timeline meta keys lifted verbatim into the tick record when present
_META_KEYS = ("backend", "impl", "ordered", "digest", "dirty_groups",
              "refresh_audit", "caller", "trace_id", "fallback",
              "fallback_code", "chaos", "restored", "restored_tick",
              "order_path", "order_dirty_lanes", "store", "relist_audit",
              "overlap_host_ms", "overlap_sync_wait_ms", "overlap_saved_ms",
              # fleet micro-batch attribution (round 14): which tenants one
              # fleet_batch dispatch decided for, and the batch width the
              # cfg17 one-dispatch proof sums against; round 16 adds the
              # mesh width the batch partitioned over
              "batch_size", "tenants", "fleet_tenants_resident",
              "fleet_shards",
              "fleet_batch_size", "fleet_ordered",
              # fleet arena lifecycle (round 15): a grow/compact inside a
              # batch annotates the record that paid for it
              "fleet_arena_grow", "fleet_arena_compact",
              # request journeys (round 17): the fleet_batch record carries
              # the batch's per-request journey LIST (the scheduler appends
              # each journey on the respond side, after this record is in
              # the ring — the list object is shared on purpose) plus the
              # monotonic-clock anchor of the record's root open, so the
              # trace exporter can lay journey slices out in record time
              "journeys", "journey_mono_t0")

#: stash key for the tick-open jaxmon snapshot (private to this module)
_MON0 = "_jaxmon_t0"


class FlightRecorder:
    """Bounded ring of tick records (thread-safe appends/snapshots)."""

    def __init__(self, capacity: int = DEFAULT_CAPACITY):
        self.capacity = int(capacity)
        self._ring: "collections.deque[Dict[str, Any]]" = collections.deque(
            maxlen=self.capacity)
        self._seq = 0
        self._lock = lockwitness.make_lock("recorder.ring")

    # -- recording ---------------------------------------------------------
    def record_timeline(self, tl: spans.Timeline) -> Dict[str, Any]:
        rec: Dict[str, Any] = {
            "root": tl.name,
            "time_unix": round(tl.wall_time, 3),
            "duration_ms": round(tl.duration_sec * 1e3, 4),
            "phases": [p.as_dict() for p in tl.phases],
        }
        for k in _META_KEYS:
            if tl.meta.get(k) is not None:
                rec[k] = tl.meta[k]
        mon0 = tl.meta.get(_MON0)
        if mon0 is not None:
            mon1 = jaxmon.snapshot()
            rec["compile_events"] = int(
                mon1["compile_events"] - mon0["compile_events"])
            rec["compile_seconds"] = round(
                mon1["compile_seconds"] - mon0["compile_seconds"], 6)
            rec["transfer_events"] = int(
                mon1["transfer_events"] - mon0["transfer_events"])
        with self._lock:
            self._seq += 1
            rec["seq"] = self._seq
            self._ring.append(rec)
        return rec

    # -- reading -----------------------------------------------------------
    @property
    def depth(self) -> int:
        with self._lock:
            return len(self._ring)

    @property
    def total_recorded(self) -> int:
        with self._lock:
            return self._seq

    def last(self) -> Optional[Dict[str, Any]]:
        with self._lock:
            return self._ring[-1] if self._ring else None

    def snapshot(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._ring)

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()

    # -- dumping -----------------------------------------------------------
    def as_dump(self, reason: str = "on-demand",
                extra: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
        """``extra`` merges additional top-level sections into the dump
        document (the tail watchdog's ``tail`` breach annotation)."""
        doc = {
            "flight_recorder": True,
            "reason": reason,
            "dumped_at_unix": round(time.time(), 3),
            "pid": os.getpid(),
            "capacity": self.capacity,
            "depth": self.depth,
            "total_recorded": self.total_recorded,
            "jaxmon": jaxmon.snapshot(),
            "tick_quantiles_ms": histograms.tick_quantiles_ms(),
            "ticks": self.snapshot(),
        }
        try:
            # device resource observatory (round 15): what the device was
            # HOLDING and COMPILING around the dumped ticks — per-owner
            # buffer accounting (+ allocator cross-check where supported)
            # and the attributed recent-compile ring
            from escalator_tpu.observability import resources

            doc["memory"] = resources.memory_section()
            ring = jaxmon.compile_ring()
            if ring:
                doc["compiles"] = ring
        except Exception:  # noqa: BLE001 - a dump must never fail on extras
            pass
        try:
            # ops event journal (round 17): the discrete-event ring rides
            # along in EVERY dump, so "what happened around tick N" —
            # tenant lifecycle, admission rejects, chaos firings, SLO
            # burns, watchdog breaches — is in the same artifact as the
            # tick timelines it happened around
            from escalator_tpu.observability import journal

            if journal.JOURNAL.depth:
                doc["journal"] = journal.JOURNAL.as_doc()
        except Exception:  # noqa: BLE001 - a dump must never fail on extras
            pass
        try:
            # decision provenance (round 19): flap/mismatch state, recent
            # decision history for the keys the incident names, and live
            # explanations for breaching tenants — the "why did it scale"
            # layer in the same artifact as the "how fast" timelines
            from escalator_tpu.observability import provenance

            sec = provenance.dump_section(extra)
            if sec:
                doc["provenance"] = sec
        except Exception:  # noqa: BLE001 - a dump must never fail on extras
            pass
        if extra:
            doc.update(extra)
        # deterministic replay (round 11): when tick-input recording is on,
        # every dump is a self-contained replay bundle — the recorded
        # (idx, old→new) batches ride along under "tick_inputs" and
        # `escalator-tpu debug-replay` re-executes them from a snapshot
        from escalator_tpu.observability import replay

        if replay.INPUT_LOG.depth:
            doc["tick_inputs"] = replay.INPUT_LOG.snapshot()
        return doc

    def dump(self, path: str, reason: str = "on-demand",
             extra: Optional[Dict[str, Any]] = None) -> str:
        """Write the dump JSON crash-consistently (the shared
        ``utils.atomicio.atomic_write`` recipe: an incident dump racing a
        SIGKILL — or a power cut, now that dumps are part of the failover
        story — must not strand a truncated or non-durable artifact)."""
        from escalator_tpu.utils.atomicio import atomic_write

        doc = self.as_dump(reason, extra=extra)

        def emit(f):
            json.dump(doc, f, indent=1)
            f.write("\n")

        return atomic_write(path, emit, mode="w")


#: the process-wide recorder every instrumented layer records into
RECORDER = FlightRecorder()

_installed = False


def _on_root_start(tl: spans.Timeline) -> None:
    # lazy jaxmon attach: only when jax is already in this process — a
    # golden-only controller must never import jax for its tick records
    import sys

    if "jax" in sys.modules and not jaxmon.installed():
        jaxmon.install()
    if jaxmon.installed():
        tl.meta[_MON0] = jaxmon.snapshot()


def _on_root_complete(tl: spans.Timeline) -> None:
    rec = RECORDER.record_timeline(tl)
    backend = str(rec.get("backend") or rec.get("root") or "unknown")
    # LEAF phases only: composite spans (the root, a backend's wrapper,
    # the controller's decide envelope) share leaf names with the spans
    # they contain ("decide" nests "decide"), and labeling both would
    # double-count the same wall time under one {backend, phase} series.
    # Composites stay in the recorder, where paths disambiguate them.
    # GRAFTED phases are skipped too: they are remote time already inside
    # the local rpc phase (counting both over-reports the tick), and the
    # remote process exports its own per-phase series for them.
    # ONE selection, consumed by both the histogram and Prometheus feeds —
    # the two series families must never diverge on what counts as a leaf.
    parents = {p["path"].rsplit("/", 1)[0] for p in rec["phases"]
               if "/" in p["path"]}
    leaves = [p for p in rec["phases"]
              if p["path"] not in parents and not p.get("remote")]
    try:
        # tail watchdog FIRST, against the series as of the PRIOR ticks: at
        # realistic sample counts p99 ~= max, so a breach folded in before
        # the comparison could never exceed its own p99. A breach schedules
        # a worker-thread dump, never blocking the tick path.
        from escalator_tpu.observability import tail

        tail.WATCHDOG.on_record(rec)
    except Exception:  # noqa: BLE001 - observability must never break ticks
        pass
    try:
        # streaming tail histograms (round 13): exact-quantile log-bucket
        # engine; the root duration lands in its own e2e series keyed by
        # root name (the tail watchdog's comparison population)
        for p in leaves:
            histograms.PHASES.observe((backend, p["name"]), p["ms"] / 1e3)
        histograms.TICKS.observe((str(rec.get("root") or "unknown"),),
                                 rec["duration_ms"] / 1e3)
    except Exception:  # noqa: BLE001 - observability must never break ticks
        pass
    try:
        from escalator_tpu.metrics import metrics

        for p in leaves:
            metrics.tick_phase_latency.labels(backend, p["name"]).observe(
                p["ms"] / 1e3)
    except Exception:  # noqa: BLE001 - metrics must never break the tick
        pass
    try:
        # decision provenance (round 19): drain the decisions the decide
        # paths staged on this timeline (already-host [G] columns, zero
        # extra sync) into the history rings + flap watchdog; a flap
        # schedules a worker-thread dump, never blocking the tick path
        from escalator_tpu.observability import provenance

        provenance.on_timeline(tl)
    except Exception:  # noqa: BLE001 - observability must never break ticks
        pass
    # device resource observatory (round 15): sample the registered buffer
    # totals for the leak watchdog (a metadata walk) and run the
    # profiler-capture countdown — both once per completed root tick, each
    # isolated so one failing can never starve the other
    try:
        from escalator_tpu.observability import resources
    except Exception:  # noqa: BLE001 - observability must never break ticks
        resources = None
    if resources is not None:
        try:
            resources.MEMORY_WATCHDOG.on_tick(rec)
        except Exception:  # noqa: BLE001
            pass
        try:
            resources.PROFILER.on_root_complete(rec)
        except Exception:  # noqa: BLE001
            pass


def install() -> None:
    """Hook the recorder into the span layer (idempotent; done at
    ``escalator_tpu.observability`` import)."""
    global _installed
    if _installed:
        return
    spans.on_root_start(_on_root_start)
    spans.on_root_complete(_on_root_complete)
    _installed = True


_incident_seq = 0


def dump_dir() -> str:
    """THE dump-directory resolution every incident artifact shares:
    ``ESCALATOR_TPU_DUMP_DIR``, falling back to the legacy
    ``ESCALATOR_TPU_FLIGHT_DUMP_DIR`` spelling, default cwd — one helper
    so flight dumps and the tail watchdog's profiler captures can never
    land in different directories."""
    return (os.environ.get("ESCALATOR_TPU_DUMP_DIR")
            or os.environ.get("ESCALATOR_TPU_FLIGHT_DUMP_DIR", "."))


def dump_on_incident(reason: str,
                     extra: Optional[Dict[str, Any]] = None) -> Optional[str]:
    """Best-effort incident dump (wedge watchdog, audit mismatch): write
    the ring to ``ESCALATOR_TPU_DUMP_DIR`` (falling back to the legacy
    ``ESCALATOR_TPU_FLIGHT_DUMP_DIR`` spelling, default cwd for compat)
    under a reason+pid+timestamp+seq name (seq disambiguates incidents
    landing in the same second — two same-named dumps would silently
    overwrite), bump the dump counter, and NEVER raise — an observability
    failure must not compound the incident. Returns the path, or None when
    the write failed. bench.py and the test suite point the env at a
    tmpdir so local runs stop littering the tree with
    ``escalator-tpu-flight-*.json`` debris."""
    global _incident_seq
    try:
        _incident_seq += 1
        out_dir = dump_dir()
        path = os.path.join(
            out_dir,
            f"escalator-tpu-flight-{reason}-{os.getpid()}-"
            f"{int(time.time())}-{_incident_seq}.json",
        )
        RECORDER.dump(path, reason=reason, extra=extra)
    except Exception:  # noqa: BLE001
        return None
    try:
        from escalator_tpu.metrics import metrics

        metrics.flight_recorder_dumps.labels(reason).inc()
    except Exception:  # noqa: BLE001
        pass
    return path
