"""Per-tick span timelines: the flight recorder's measurement substrate.

The reference's only timing signal is a per-run wall-time debug log
(pkg/controller/controller.go:448); this module is the Dapper-style
(Sigelman et al., 2010) replacement adapted to a single-process JAX control
loop: every tick is one *timeline* of named, nestable spans, and device
phases are **explicitly fenced** (``fence()`` calls ``jax.block_until_ready``
on the phase's output before the span closes) so a span's duration is device
time, not async-dispatch time.

Design constraints, in order:

- **Zero dependencies.** This module imports only the stdlib. ``fence``
  reaches jax through ``sys.modules`` — a golden-only deployment never pays
  a jax import for its timeline.
- **Strictly outside traced code.** Spans wrap jit *dispatch sites*; nothing
  here may run under a trace (no host callbacks, no primitives — the R4 ban
  and the jaxpr-byte-identity assertion in tests/test_observability.py lock
  this).
- **Negligible overhead.** A span is two ``perf_counter`` calls, a string
  join and a list append (~1-2 us); a steady tick carries < 10 spans. The
  measured bound (< 1% of a cfg14 steady tick) ships in bench.py's
  observability-overhead row. ``set_enabled(False)`` is the bench's
  control arm — spans become no-ops and no timeline is recorded.

Model: the first span opened on a thread with an empty stack becomes the
**root** of a new timeline (``Timeline``); nested ``span()`` calls record
phases whose ``path`` is the slash-joined name chain. When the root closes,
the timeline is handed to the registered completion hooks (the flight
recorder and the Prometheus per-phase histograms — see flightrecorder.py).
State is thread-local: concurrent ticks (a plugin server thread under a
client thread in-process, the concurrency soak) never interleave timelines.

Phases carry a ``fenced`` flag: True when the phase's duration is accurate —
either a host-only phase (``kind="host"``/``"rpc"``: the work is synchronous
by construction) or a device phase whose owner called :func:`fence` before
the span closed. An unfenced device phase measured only the async dispatch.
"""

from __future__ import annotations

import contextlib
import sys
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Iterator, List, Optional

__all__ = [
    "Phase", "Timeline", "span", "fence", "annotate", "add_phase", "graft",
    "current_path", "current_timeline", "on_root_start", "on_root_complete",
    "set_enabled", "enabled",
]

#: kinds whose phases are synchronous by construction (duration is accurate
#: without an explicit fence): host compute and blocking RPCs
_SYNC_KINDS = ("host", "rpc")


@dataclass
class Phase:
    """One completed span: a named slice of a tick's timeline."""

    name: str            # leaf name ("pack", "decide_light", ...)
    path: str            # slash-joined chain from the root ("jax/decide/pack")
    duration_sec: float
    kind: str = "host"   # "host" | "device" | "rpc"
    fenced: bool = True  # duration is device-accurate (see module docstring)
    #: start offset from the timeline root. For grafted remote phases the
    #: offset is relative to the REMOTE timeline's root (the peer shipped
    #: it); the trace exporter re-anchors it under the local rpc span.
    offset_sec: Optional[float] = None
    #: grafted from another process's timeline (that process exports its own
    #: Prometheus series for these — the local histograms skip them)
    remote: bool = False

    def as_dict(self) -> Dict[str, Any]:
        d = {
            "name": self.name,
            "path": self.path,
            "ms": round(self.duration_sec * 1e3, 4),
            "kind": self.kind,
            "fenced": self.fenced,
        }
        if self.offset_sec is not None:
            d["offset_ms"] = round(self.offset_sec * 1e3, 4)
        if self.remote:
            d["remote"] = True
        return d


@dataclass
class Timeline:
    """All phases of one root span (one tick), plus caller annotations."""

    name: str
    wall_time: float                      # time.time() at root open
    t0: float                             # perf_counter at root open
    phases: List[Phase] = field(default_factory=list)
    meta: Dict[str, Any] = field(default_factory=dict)
    duration_sec: float = 0.0             # set when the root closes


class _Frame:
    __slots__ = ("name", "t0", "kind", "fenced")

    def __init__(self, name: str, t0: float, kind: str):
        self.name = name
        self.t0 = t0
        self.kind = kind
        self.fenced = kind in _SYNC_KINDS


class _State(threading.local):
    def __init__(self):
        self.stack: List[_Frame] = []
        self.timeline: Optional[Timeline] = None


_state = _State()
_enabled = True
_root_start_hooks: List[Callable[[Timeline], None]] = []
_root_complete_hooks: List[Callable[[Timeline], None]] = []


def set_enabled(value: bool) -> None:
    """Globally enable/disable recording (the bench's overhead control arm;
    production leaves it on). Disabled spans are no-ops."""
    global _enabled
    _enabled = bool(value)


def enabled() -> bool:
    return _enabled


def on_root_start(cb: Callable[[Timeline], None]) -> None:
    if cb not in _root_start_hooks:
        _root_start_hooks.append(cb)


def on_root_complete(cb: Callable[[Timeline], None]) -> None:
    if cb not in _root_complete_hooks:
        _root_complete_hooks.append(cb)


def _run_hooks(hooks: List[Callable[[Timeline], None]], tl: Timeline) -> None:
    for cb in hooks:
        try:
            cb(tl)
        except Exception:  # noqa: BLE001 - observability must never break ticks
            pass


def _path(upto: Optional[int] = None) -> str:
    frames = _state.stack if upto is None else _state.stack[:upto]
    return "/".join(f.name for f in frames)


def current_path() -> str:
    """Slash-joined path of the innermost open span ("" outside any span)."""
    return _path()


def current_timeline() -> Optional[Timeline]:
    return _state.timeline


@contextlib.contextmanager
def span(name: str, kind: str = "host") -> Iterator[None]:
    """Record ``name`` as a phase of the current timeline. Opening a span
    with an empty stack starts a new timeline (this span is the root; closing
    it emits the timeline to the completion hooks). ``kind="device"`` marks
    an async-dispatching phase — call :func:`fence` on its output before the
    block ends, or the phase is flagged unfenced."""
    if not _enabled:
        yield
        return
    st = _state
    is_root = not st.stack
    now = time.perf_counter()
    if is_root:
        st.timeline = Timeline(name=name, wall_time=time.time(), t0=now)
        _run_hooks(_root_start_hooks, st.timeline)
    frame = _Frame(name, now, kind)
    st.stack.append(frame)
    try:
        yield
    finally:
        end = time.perf_counter()
        tl = st.timeline
        path = _path()
        st.stack.pop()
        if tl is not None:
            tl.phases.append(Phase(
                name=name, path=path, duration_sec=end - frame.t0,
                kind=kind, fenced=frame.fenced,
                offset_sec=frame.t0 - tl.t0,
            ))
            if is_root:
                tl.duration_sec = end - tl.t0
                st.timeline = None
                _run_hooks(_root_complete_hooks, tl)


def fence(value: Any) -> Any:
    """Block until ``value``'s device computation completes (when jax is
    loaded) and mark the innermost open span device-fenced. Returns
    ``value`` so dispatch sites stay one-liners:
    ``out = fence(decide_jit(...))``. Never imports jax: a process that
    never loaded it has nothing to fence.

    Only non-blockable *inputs* (non-array pytrees: TypeError/ValueError)
    are tolerated — a runtime DEVICE failure surfacing at the block must
    propagate exactly as a bare ``block_until_ready`` would, or sites where
    fence is the only blocking call (the plugin server's decide) would
    record a bogus success and resurface the error later with a misleading
    traceback."""
    jax = sys.modules.get("jax")
    if jax is not None:
        try:
            jax.block_until_ready(value)
        except (TypeError, ValueError):
            pass
    if _enabled and _state.stack:
        _state.stack[-1].fenced = True
    return value


def annotate(**kw: Any) -> None:
    """Attach key/value metadata to the current timeline (backend name,
    impl, dirty-group count, refresh-audit outcome, decision digest...).
    No-op outside a span."""
    if _enabled and _state.timeline is not None:
        _state.timeline.meta.update(kw)


def add_phase(name: str, duration_sec: float, kind: str = "host",
              fenced: bool = True) -> None:
    """Append a pre-measured phase under the current path — for callers that
    accumulate sub-step timings across a loop (the golden backend) or know a
    duration from elsewhere. No-op outside a span."""
    tl = _state.timeline
    if not _enabled or tl is None:
        return
    base = _path()
    tl.phases.append(Phase(
        name=name, path=(base + "/" + name) if base else name,
        duration_sec=float(duration_sec), kind=kind, fenced=fenced,
    ))


def graft(phase_dicts: List[Dict[str, Any]], under: Optional[str] = None) -> None:
    """Splice remote phases (a plugin server's shipped timeline, in
    ``Phase.as_dict`` form) into the current timeline, path-prefixed so they
    nest under the caller's span: the cross-process analog of a child span.
    ``under`` defaults to the current path. No-op outside a span."""
    tl = _state.timeline
    if not _enabled or tl is None:
        return
    prefix = _path() if under is None else under
    for p in phase_dicts:
        try:
            path = str(p.get("path") or p.get("name") or "remote")
            off = p.get("offset_ms")
            tl.phases.append(Phase(
                name=str(p.get("name") or path.rsplit("/", 1)[-1]),
                path=(prefix + "/" + path) if prefix else path,
                duration_sec=float(p.get("ms", 0.0)) / 1e3,
                kind=str(p.get("kind", "host")),
                fenced=bool(p.get("fenced", False)),
                # remote-root-relative (see Phase.offset_sec): kept so the
                # trace exporter can lay the server spans out in time
                offset_sec=float(off) / 1e3 if off is not None else None,
                remote=True,
            ))
        except Exception:  # noqa: BLE001 - a malformed remote phase is dropped
            continue
