"""jax.monitoring bridge: compile and transfer telemetry as counters.

JAX announces its internal lifecycle through ``jax.monitoring`` — on this
jax (0.4.x) a compile emits ``/jax/core/compile/jaxpr_trace_duration``,
``.../jaxpr_to_mlir_module_duration`` and ``.../backend_compile_duration``
duration events plus compilation-cache count events. :func:`install`
subscribes once per process and folds them into:

- a process-local snapshot (:func:`snapshot`) the flight recorder diffs
  per tick, so every tick record says how many compiles (and how much
  compile time) happened inside it — a recompilation storm is then visible
  as a per-tick anomaly, not a vibe;
- Prometheus series: ``escalator_tpu_jax_compile_seconds`` (histogram of
  per-program backend-compile durations), ``..._jax_compile_events_total``
  and ``..._jax_transfer_events_total``.

Event classification is by key substring, deliberately version-tolerant:
any duration key containing ``compile`` adds to compile seconds (trace +
MLIR lowering + backend compile are disjoint stages of one compile, so the
sum is "total time spent compiling"); the ``backend_compile`` key counts
the compile event. Keys containing ``transfer`` or ``device_put`` count as
host<->device transfers — this jax version emits none (the counter stays
0 and docs/observability.md says so), but newer runtimes that do are
picked up without a code change.

Listeners cannot be unregistered on this jax; install is process-lifetime
and idempotent. Callbacks are tolerant (``**kwargs``) so jax versions that
add metadata keep working, and they never raise into jax internals.
"""

from __future__ import annotations

import threading
from typing import Dict

_lock = threading.Lock()
_installed = False
_install_failed: str = ""

_counts: Dict[str, float] = {
    "compile_events": 0,
    "compile_seconds": 0.0,
    "transfer_events": 0,
    "monitored_events": 0,
}

#: the per-program compile event (one per XLA backend compile on jax 0.4.x)
_BACKEND_COMPILE = "backend_compile"


def _classify(event: str) -> str:
    e = event.lower()
    if "compil" in e:
        return "compile"
    if "transfer" in e or "device_put" in e:
        return "transfer"
    return "other"


def _on_event(event: str, **kwargs) -> None:  # noqa: ANN003
    try:
        with _lock:
            _counts["monitored_events"] += 1
            if _classify(event) == "transfer":
                _counts["transfer_events"] += 1
                _metrics().jax_transfer_events.inc()
    except Exception:  # noqa: BLE001 - never raise into jax internals
        pass


def _on_duration(event: str, duration: float, **kwargs) -> None:  # noqa: ANN003
    try:
        kind = _classify(event)
        with _lock:
            _counts["monitored_events"] += 1
            if kind == "compile":
                _counts["compile_seconds"] += float(duration)
                if _BACKEND_COMPILE in event:
                    _counts["compile_events"] += 1
                    m = _metrics()
                    m.jax_compile_events.inc()
                    m.jax_compile_seconds.observe(float(duration))
            elif kind == "transfer":
                _counts["transfer_events"] += 1
                _metrics().jax_transfer_events.inc()
    except Exception:  # noqa: BLE001
        pass


def _metrics():
    from escalator_tpu.metrics import metrics

    return metrics


def install() -> bool:
    """Subscribe to jax.monitoring (idempotent; once per process). Returns
    True when listening. Safe without jax installed — the import failure is
    recorded and the counters simply stay at zero."""
    global _installed, _install_failed
    with _lock:
        if _installed:
            return True
        try:
            import jax.monitoring as mon
        except Exception as e:  # noqa: BLE001 - jax-less deployment
            _install_failed = str(e)
            return False
        mon.register_event_listener(_on_event)
        mon.register_event_duration_secs_listener(_on_duration)
        _installed = True
        return True


def installed() -> bool:
    return _installed


def snapshot() -> Dict[str, float]:
    """Copy of the monotonic counters (diff two snapshots for a window)."""
    with _lock:
        return dict(_counts)
