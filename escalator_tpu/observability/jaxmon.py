"""jax.monitoring bridge: compile and transfer telemetry as counters.

JAX announces its internal lifecycle through ``jax.monitoring`` — on this
jax (0.4.x) a compile emits ``/jax/core/compile/jaxpr_trace_duration``,
``.../jaxpr_to_mlir_module_duration`` and ``.../backend_compile_duration``
duration events plus compilation-cache count events. :func:`install`
subscribes once per process and folds them into:

- a process-local snapshot (:func:`snapshot`) the flight recorder diffs
  per tick, so every tick record says how many compiles (and how much
  compile time) happened inside it — a recompilation storm is then visible
  as a per-tick anomaly, not a vibe;
- Prometheus series: ``escalator_tpu_jax_compile_seconds`` (histogram of
  per-program backend-compile durations), ``..._jax_compile_events_total``
  and ``..._jax_transfer_events_total``.

Event classification is by key substring, deliberately version-tolerant:
any duration key containing ``compile`` adds to compile seconds (trace +
MLIR lowering + backend compile are disjoint stages of one compile, so the
sum is "total time spent compiling"); the ``backend_compile`` key counts
the compile event. Keys containing ``transfer`` or ``device_put`` count as
host<->device transfers — this jax version emits none (the counter stays
0 and docs/observability.md says so), but newer runtimes that do are
picked up without a code change.

Listeners cannot be unregistered on this jax; install is process-lifetime
and idempotent. Callbacks are tolerant (``**kwargs``) so jax versions that
add metadata keep working, and they never raise into jax internals.

**Compile observatory (round 15):** beyond counting, every backend compile
lands in a bounded ring (:func:`compile_ring`) with *attribution*: the span
path live at compile time (compiles happen synchronously inside the
dispatching span on the same thread), the timeline's backend annotation,
the jaxlint registry entry the dispatch site maps to, and whatever metadata
kwargs this jax version ships (0.4.x ships none; newer runtimes' fun_name
etc. ride along untouched). The ring embeds in every flight dump
(``compiles`` section) and feeds ``escalator-tpu debug-compiles``, which
diffs observed per-entry compile counts against the jaxlint retrace pins —
a surprise retrace on chip is then NAMED (which entry, under which tick
phase), not just counted.
"""

from __future__ import annotations

import collections
import os
import time
from typing import Any, Dict, List, Optional

from escalator_tpu.analysis import lockwitness

_lock = lockwitness.make_lock("jaxmon.state")
_installed = False
_install_failed: str = ""

#: recent backend compiles, newest last (ESCALATOR_TPU_COMPILE_RING caps
#: it; a junk value falls back to the default rather than crashing every
#: importer at startup — same tolerance as the watchdog knobs)
try:
    _RING_CAPACITY = int(os.environ.get("ESCALATOR_TPU_COMPILE_RING", "64"))
except ValueError:
    _RING_CAPACITY = 64
_ring: "collections.deque[Dict[str, Any]]" = collections.deque(
    maxlen=max(1, _RING_CAPACITY))
_ring_seq = 0

#: dispatch-site span leaf -> jaxlint registry entry (analysis/registry.py
#: names). The attribution contract: a compile whose innermost span is one
#: of these belongs to that entry's program family. Leaves absent here
#: (bench warmups, test jits) attribute to None and still ride the ring.
SPAN_ENTRY_MAP: Dict[str, str] = {
    "delta_decide": "kernel.delta_decide",
    "decide_ordered_incremental": "kernel.ordered_delta_decide",
    "decide_ordered": "kernel.decide",
    "decide_full": "kernel.decide",
    "decide_light": "kernel.decide",
    "decide": "kernel.decide",
    "scatter": "device_state.scatter_update_aggs",
    "fleet_step": "device_state.fleet_step",
    "fleet_ordered_redispatch": "kernel.decide",
    "audit_snapshot": "device_state.audit_snapshot",
    "snapshot_freeze": "snapshot.freeze",
    "restore_upload": "snapshot.restore_adopt",
    "order_repair": "order_tail.order_update",
}

_counts: Dict[str, float] = {
    "compile_events": 0,
    "compile_seconds": 0.0,
    "transfer_events": 0,
    "monitored_events": 0,
}

#: the per-program compile event (one per XLA backend compile on jax 0.4.x)
_BACKEND_COMPILE = "backend_compile"


def _classify(event: str) -> str:
    e = event.lower()
    if "compil" in e:
        return "compile"
    if "transfer" in e or "device_put" in e:
        return "transfer"
    return "other"


def _on_event(event: str, **kwargs) -> None:  # noqa: ANN003
    try:
        with _lock:
            _counts["monitored_events"] += 1
            if _classify(event) == "transfer":
                _counts["transfer_events"] += 1
                _metrics().jax_transfer_events.inc()
    except Exception:  # noqa: BLE001 - never raise into jax internals
        pass


def _record_compile(event: str, duration: float,
                    kwargs: Dict[str, Any]) -> None:
    """One ring entry per backend compile, attributed by the live span path
    (thread-local — the compile runs synchronously inside the dispatching
    span). Runs under the module lock; every lookup is O(1)."""
    global _ring_seq
    from escalator_tpu.observability import spans

    path = spans.current_path()
    tl = spans.current_timeline()
    leaf = path.rsplit("/", 1)[-1] if path else ""
    entry: Dict[str, Any] = {
        "seq": _ring_seq,
        "time_unix": round(time.time(), 3),
        "event": event.rsplit("/", 1)[-1],
        "duration_sec": round(float(duration), 6),
        "path": path,
        "entry": SPAN_ENTRY_MAP.get(leaf),
    }
    _ring_seq += 1
    if tl is not None:
        entry["root"] = tl.name
        backend = tl.meta.get("backend")
        if backend is not None:
            entry["backend"] = backend
    for k, v in kwargs.items():
        # version-tolerant metadata (fun_name, arg shapes on newer jaxes):
        # stringify anything non-scalar so the ring stays JSON-serializable
        entry[k] = v if isinstance(v, (str, int, float, bool)) else str(v)
    _ring.append(entry)


def _on_duration(event: str, duration: float, **kwargs) -> None:  # noqa: ANN003
    try:
        kind = _classify(event)
        with _lock:
            _counts["monitored_events"] += 1
            if kind == "compile":
                _counts["compile_seconds"] += float(duration)
                if _BACKEND_COMPILE in event:
                    _counts["compile_events"] += 1
                    _record_compile(event, duration, kwargs)
                    m = _metrics()
                    m.jax_compile_events.inc()
                    m.jax_compile_seconds.observe(float(duration))
            elif kind == "transfer":
                _counts["transfer_events"] += 1
                _metrics().jax_transfer_events.inc()
    except Exception:  # noqa: BLE001
        pass


def _metrics():
    from escalator_tpu.metrics import metrics

    return metrics


def install() -> bool:
    """Subscribe to jax.monitoring (idempotent; once per process). Returns
    True when listening. Safe without jax installed — the import failure is
    recorded and the counters simply stay at zero."""
    global _installed, _install_failed
    with _lock:
        if _installed:
            return True
        try:
            import jax.monitoring as mon
        except Exception as e:  # noqa: BLE001 - jax-less deployment
            _install_failed = str(e)
            return False
        mon.register_event_listener(_on_event)
        mon.register_event_duration_secs_listener(_on_duration)
        _installed = True
        return True


def installed() -> bool:
    return _installed


def snapshot() -> Dict[str, float]:
    """Copy of the monotonic counters (diff two snapshots for a window)."""
    with _lock:
        return dict(_counts)


def compile_ring() -> List[Dict[str, Any]]:
    """Snapshot of the recent-compile ring, oldest first (embedded in every
    flight dump as ``compiles``; the debug-compiles CLI's source)."""
    with _lock:
        return list(_ring)


def clear_ring() -> None:
    """Drop recorded compiles (test/bench isolation)."""
    with _lock:
        _ring.clear()


def retrace_pins() -> Dict[str, int]:
    """The jaxlint registry's retrace budgets ``{entry: compiles}`` —
    lazily imported (building the registry needs jax + the fixture
    modules) and empty when unavailable, so debug tooling degrades on a
    stripped install instead of crashing."""
    try:
        from escalator_tpu.analysis.registry import default_registry

        return {e.name: e.retrace_budget for e in default_registry()
                if e.retrace_budget is not None}
    except Exception:  # noqa: BLE001 - debug surface: degrade, don't raise
        return {}


def attribute_compiles(
        ring: Optional[List[Dict[str, Any]]] = None,
        pins: Optional[Dict[str, int]] = None) -> List[Dict[str, Any]]:
    """Group a compile ring by attributed registry entry: one row per
    entry/path family with count, total seconds, last event time — and,
    where the jaxlint registry pins a retrace budget, the budget plus a
    ``bust`` flag when the observed count exceeds it (the offending span
    paths NAME the shape family that retraced; a warm steady-state process
    should show zero recent compiles at all)."""
    if ring is None:
        ring = compile_ring()
    if pins is None:
        pins = retrace_pins()
    groups: Dict[str, Dict[str, Any]] = {}
    for rec in ring:
        key = rec.get("entry") or rec.get("path") or "(unattributed)"
        row = groups.setdefault(key, {
            "entry": rec.get("entry"),
            "count": 0,
            "total_sec": 0.0,
            "paths": [],
            "last_time_unix": None,
        })
        row["count"] += 1
        row["total_sec"] = round(
            row["total_sec"] + float(rec.get("duration_sec", 0.0)), 6)
        path = rec.get("path")
        if path and path not in row["paths"]:
            row["paths"].append(path)
        row["last_time_unix"] = rec.get("time_unix")
    out = []
    for key, row in sorted(groups.items()):
        budget = pins.get(row["entry"]) if row["entry"] else None
        if budget is not None:
            row["retrace_budget"] = budget
            row["bust"] = row["count"] > budget
        row["key"] = key
        out.append(row)
    return out
