"""Device resource observatory: HBM/arena accounting, leak watchdog,
on-demand profiler capture.

The span/histogram layers (rounds 9/13) made every tick's *time* accountable;
this module (round 15) does the same for the device's *memory* and the
profiler's view of it, closing the "what was the device holding when that
happened" gap:

- **Buffer-accounting registry** (:data:`RESOURCES`): every owner of
  persistent device state — the resident ClusterArrays, the maintained
  GroupAggregates, the 13 decision columns, the order-state columns, the
  audit double buffer, snapshot freeze copies, the fleet's C-stacked arenas —
  registers a weakref'd provider at construction. Per-owner ``nbytes`` is
  computed purely from array METADATA (``arr.nbytes`` reads the aval — no
  device sync, works even on a donated-away buffer), so a snapshot costs
  microseconds and is safe from any thread. Each owner also declares an
  executable **budget**: the docs' hand-computed HBM envelope formulas
  (docs/performance.md, docs/fleet.md) as code, asserted against the live
  arrays in ``bench.py --smoke`` — the envelope can no longer silently
  drift from the implementation.
- **Growth watchdog** (:data:`MEMORY_WATCHDOG`): samples the total
  registered bytes once per completed root tick (the same root-complete
  hook as the ring/histograms); monotone growth across a full window is the
  leak signature a fixed-buffer design must never show, and flags as a
  rate-limited ``reason="memory"`` flight dump (same discipline as the tail
  watchdog: dump on a worker, never on the tick path).
- **Profiler capture** (:data:`PROFILER`): wrap ``jax.profiler`` around the
  next K root ticks on demand — the ``escalator-tpu debug-profile`` CLI and
  the plugin ``Profile`` RPC drive it, and ``ESCALATOR_TPU_TAIL_PROFILE=1``
  arms the tail watchdog to capture a trace on its first breach, so a slow
  tick on a TPU campaign yields an on-chip profile without a human in the
  loop. The artifact is a TensorBoard/XPlane trace directory (CPU and TPU),
  the profiler-native sibling of the ``debug-trace`` Perfetto export.

Platform capability (``memory_stats()``, ``jax.live_arrays``,
``jax.profiler``) is probed ONCE per process, WARN-logged when missing (the
``unavailable_reason()`` pattern from native/statestore.py), and every
surface degrades to explicit ``"unsupported"`` fields instead of raising —
a CPU-only rig reports ``memory_stats: unsupported`` and keeps the registry
accounting, which needs no runtime support at all.

Zero hard dependencies: this module imports only the stdlib (+ the spans
module) at import time; jax is reached through ``sys.modules`` exactly like
``spans.fence`` — a golden-only controller pays nothing.
"""

from __future__ import annotations

import collections
import dataclasses
import logging
import math
import os
import sys
import threading
import time
import weakref
from typing import Any, Callable, Dict, List, Optional, Tuple

from escalator_tpu.analysis import lockwitness

__all__ = [
    "RESOURCES", "MEMORY_WATCHDOG", "PROFILER",
    "ResourceRegistry", "MemoryWatchdog", "ProfileCapture",
    "capabilities", "unavailable_reason", "device_memory",
    "live_arrays_bytes", "memory_section",
    "expected_cluster_bytes", "expected_aggregates_bytes",
    "expected_decision_columns_bytes", "expected_order_state_bytes",
    "expected_fleet_arena_bytes",
]

log = logging.getLogger("escalator_tpu.observability")

_ENV_WATCH = "ESCALATOR_TPU_MEMORY_WATCH"
_ENV_MIN_GROWTH = "ESCALATOR_TPU_MEMORY_MIN_GROWTH"
_ENV_INTERVAL = "ESCALATOR_TPU_MEMORY_DUMP_INTERVAL_SEC"
_ENV_SAMPLE_EVERY = "ESCALATOR_TPU_MEMORY_SAMPLE_EVERY"

DEFAULT_WINDOW = 64
DEFAULT_MIN_GROWTH = 1 << 20          # 1 MiB across the window
DEFAULT_INTERVAL_SEC = 300.0
#: ticks between registry samples: the metadata walk is ~100 µs with many
#: live owners, so sampling every tick would be the single largest line in
#: the <1% instrumentation budget; a leak ramp is a minutes-scale signal,
#: so a /8 decimation costs nothing but detection latency
DEFAULT_SAMPLE_EVERY = 8


# ---------------------------------------------------------------------------
# Platform capability probe (the unavailable_reason() pattern)
# ---------------------------------------------------------------------------

_caps_lock = lockwitness.make_lock("resources.caps")
_caps: Optional[Dict[str, Optional[str]]] = None


def _probe_capabilities() -> Dict[str, Optional[str]]:
    """One probe per process: for each capability, None = available, else
    the human-readable reason it is not. Never imports jax — a process that
    has not loaded it reports every runtime capability unsupported (the
    registry accounting works regardless)."""
    caps: Dict[str, Optional[str]] = {}
    jax = sys.modules.get("jax")
    if jax is None:
        reason = "jax not loaded in this process"
        return {"memory_stats": reason, "live_arrays": reason,
                "profiler": reason}
    try:
        devs = jax.local_devices()
    except Exception as e:  # noqa: BLE001 - backend init failure
        reason = f"jax device init failed: {e}"
        return {"memory_stats": reason, "live_arrays": reason,
                "profiler": reason}
    try:
        stats = devs[0].memory_stats() if devs else None
        if stats:
            caps["memory_stats"] = None
        else:
            caps["memory_stats"] = (
                f"memory_stats() returns {stats!r} on "
                f"{devs[0].platform if devs else 'no-device'} "
                "(runtime does not report allocator stats)")
    except Exception as e:  # noqa: BLE001
        caps["memory_stats"] = f"memory_stats() raised: {e}"
    caps["live_arrays"] = (None if callable(getattr(jax, "live_arrays", None))
                           else "jax.live_arrays not provided by this jax")
    prof = getattr(jax, "profiler", None)
    if (prof is not None and callable(getattr(prof, "start_trace", None))
            and callable(getattr(prof, "stop_trace", None))):
        caps["profiler"] = None
    else:
        caps["profiler"] = "jax.profiler.start_trace/stop_trace unavailable"
    return caps


def capabilities(refresh: bool = False) -> Dict[str, Optional[str]]:
    """The probed capability map (``{name: None-or-reason}``), cached after
    the first call; missing capabilities WARN-log ONCE with the decision
    taken (explicit ``"unsupported"`` fields, never an exception).
    ``refresh=True`` re-probes — tests and late-jax-loading processes use
    it (the cache deliberately re-probes on its own when jax appears after
    a jax-less first probe)."""
    global _caps
    with _caps_lock:
        stale = (_caps is not None
                 and (_caps.get("memory_stats") or "").startswith(
                     "jax not loaded")
                 and "jax" in sys.modules)
        if _caps is None or refresh or stale:
            _caps = _probe_capabilities()
            for name, reason in _caps.items():
                if reason is not None:
                    log.warning(
                        "resource observatory: %s unavailable (%s); the "
                        "corresponding surfaces report 'unsupported' and "
                        "everything else keeps working", name, reason)
        return dict(_caps)


def unavailable_reason(capability: str) -> Optional[str]:
    """Why ``capability`` (``memory_stats`` | ``live_arrays`` |
    ``profiler``) is unavailable — None when it works (the
    ``statestore.unavailable_reason`` contract)."""
    return capabilities().get(capability)


# ---------------------------------------------------------------------------
# nbytes accounting: pure metadata walks, no jax import, no device sync
# ---------------------------------------------------------------------------


def _walk_nbytes(tree: Any) -> Tuple[int, int]:
    """``(total_nbytes, leaf_count)`` over a pytree-ish value: arrays
    (anything with ``shape`` + ``dtype.itemsize``), dataclasses,
    tuples/lists, dicts, None. Bytes come from ``prod(shape) * itemsize``
    rather than ``.nbytes`` — jax 0.4.x computes ``.nbytes`` through an
    uncached dtype-canonicalization property (~15 µs/array, measured),
    which would put the per-tick watchdog sample outside the <1%
    instrumentation budget; shape and dtype are cached attributes on both
    numpy and jax arrays, and the product is exact for dense arrays (the
    only kind any owner holds). Unknown leaves count zero bytes rather
    than raising — an accounting miss must never break a tick."""
    if tree is None:
        return 0, 0
    shape = getattr(tree, "shape", None)
    if shape is not None:
        itemsize = getattr(getattr(tree, "dtype", None), "itemsize", None)
        if isinstance(itemsize, int):
            return math.prod(shape) * itemsize, 1
    nb = getattr(tree, "nbytes", None)
    if isinstance(nb, int):
        return nb, 1
    if dataclasses.is_dataclass(tree) and not isinstance(tree, type):
        total = count = 0
        for f in dataclasses.fields(tree):
            b, c = _walk_nbytes(getattr(tree, f.name))
            total += b
            count += c
        return total, count
    if isinstance(tree, (tuple, list)):
        total = count = 0
        for item in tree:
            b, c = _walk_nbytes(item)
            total += b
            count += c
        return total, count
    if isinstance(tree, dict):
        total = count = 0
        for item in tree.values():
            b, c = _walk_nbytes(item)
            total += b
            count += c
        return total, count
    return 0, 0


class Registration:
    """Handle for one registered owner instance; ``close()`` deregisters
    (dead weakrefs deregister themselves — close is for explicit teardown
    like a store growth re-registering at new capacities)."""

    def __init__(self, registry: "ResourceRegistry", key: Tuple[str, int]):
        self._registry = registry
        self._key = key

    def close(self) -> None:
        self._registry._remove(self._key)


class ResourceRegistry:
    """Process-global accounting of persistent device-state owners.

    ``register(owner, obj, extract, budget=..., kind=...)`` stores a
    WEAKREF to ``obj`` plus an ``extract(obj)`` callable returning the live
    array tree (or None while absent) and an optional ``budget(obj)``
    callable returning the declared byte envelope (None while
    inapplicable). Owners are NAMES, not instances: several instances of
    one owner (two deciders in a test process) sum under one label, so the
    Prometheus series stays bounded. Dead referents prune lazily."""

    def __init__(self) -> None:
        self._lock = lockwitness.make_lock("resources.registry")
        self._entries: Dict[Tuple[str, int], Tuple[
            "weakref.ref", Callable[[Any], Any],
            Optional[Callable[[Any], Optional[int]]], str]] = {}

    def register(self, owner: str, obj: Any,
                 extract: Callable[[Any], Any],
                 budget: Optional[Callable[[Any], Optional[int]]] = None,
                 kind: str = "device") -> Registration:
        key = (owner, id(obj))
        with self._lock:
            self._entries[key] = (weakref.ref(obj), extract, budget, kind)
        return Registration(self, key)

    def _remove(self, key: Tuple[str, int]) -> None:
        with self._lock:
            self._entries.pop(key, None)

    def clear(self) -> None:
        """Drop every registration (test isolation only — production owners
        live for the process)."""
        with self._lock:
            self._entries.clear()

    def snapshot(self) -> Dict[str, Dict[str, Any]]:
        """Per-owner accounting: ``{owner: {nbytes, arrays, instances,
        budget_bytes, kind}}`` — nbytes from array metadata only. A
        provider that raises reports an ``error`` string for its owner
        instead of propagating (observability must never break a tick)."""
        with self._lock:
            entries = list(self._entries.items())
        out: Dict[str, Dict[str, Any]] = {}
        dead: List[Tuple[str, int]] = []
        for key, (ref, extract, budget, kind) in entries:
            obj = ref()
            if obj is None:
                dead.append(key)
                continue
            owner = key[0]
            row = out.setdefault(owner, {
                "nbytes": 0, "arrays": 0, "instances": 0,
                "budget_bytes": None, "kind": kind,
            })
            row["instances"] += 1
            try:
                nbytes, arrays = _walk_nbytes(extract(obj))
                row["nbytes"] += nbytes
                row["arrays"] += arrays
                if budget is not None:
                    b = budget(obj)
                    if b is not None:
                        row["budget_bytes"] = (b if row["budget_bytes"] is None
                                               else row["budget_bytes"] + b)
            except Exception as e:  # noqa: BLE001
                row["error"] = str(e)
        if dead:
            with self._lock:
                for key in dead:
                    self._entries.pop(key, None)
        return out

    def sampled_bytes(self, kind: Optional[str] = "device") -> int:
        """The watchdog's per-tick fast path: sum registered nbytes WITHOUT
        evaluating budget callables (those may build fixture rows — scrape/
        dump cost, not tick cost). Pure attribute walks, a few µs."""
        with self._lock:
            entries = list(self._entries.values())
        total = 0
        for ref, extract, _budget, entry_kind in entries:
            if kind is not None and entry_kind != kind:
                continue
            obj = ref()
            if obj is None:
                continue
            try:
                total += _walk_nbytes(extract(obj))[0]
            except Exception:  # noqa: BLE001 - accounting must never raise
                continue
        return total

    def total_bytes(self, kind: Optional[str] = "device") -> int:
        """Sum of registered nbytes (``kind=None`` for every kind)."""
        return self.sampled_bytes(kind)


RESOURCES = ResourceRegistry()


def device_memory() -> Dict[str, Any]:
    """Per-device allocator truth where the runtime supports it:
    ``{device: {bytes_in_use, peak_bytes_in_use, ...}}`` — or
    ``{device: {"unsupported": reason}}`` on runtimes (this rig's CPU, the
    axon TPU runtime of every round-4 capture) that report nothing. The
    registry accounting above is the portable signal; this is the
    cross-check that catches what the registry cannot see (XLA temp
    buffers, a leak OUTSIDE the registered owners)."""
    jax = sys.modules.get("jax")
    if jax is None:
        return {"unsupported": "jax not loaded in this process"}
    out: Dict[str, Any] = {}
    try:
        devs = jax.local_devices()
    except Exception as e:  # noqa: BLE001
        return {"unsupported": f"jax device init failed: {e}"}
    for d in devs:
        try:
            stats = d.memory_stats()
        except Exception as e:  # noqa: BLE001
            out[str(d)] = {"unsupported": f"memory_stats() raised: {e}"}
            continue
        if not stats:
            out[str(d)] = {"unsupported": (
                f"memory_stats() returns {stats!r} on {d.platform}")}
            continue
        out[str(d)] = {
            k: stats[k]
            for k in ("bytes_in_use", "peak_bytes_in_use", "bytes_limit",
                      "largest_alloc_size", "num_allocs")
            if k in stats
        }
    return out


def live_arrays_bytes() -> Dict[str, Any]:
    """Total bytes of every live jax array in the process
    (``jax.live_arrays()`` — metadata sum, no sync), the registry's other
    cross-check: ``live - registered`` bounds the unaccounted device state.
    ``{"unsupported": reason}`` where the jax version lacks it."""
    reason = unavailable_reason("live_arrays")
    if reason is not None:
        return {"unsupported": reason}
    jax = sys.modules.get("jax")
    try:
        arrays = jax.live_arrays()
        # shape x itemsize, not .nbytes: a long-lived process holds
        # thousands of live arrays (cached constants of every compiled
        # program) and jax 0.4.x's .nbytes property costs ~15 µs each —
        # this sum runs on every dump and health probe
        total = 0
        for a in arrays:
            total += _walk_nbytes(a)[0]
        return {"count": len(arrays), "nbytes": total}
    except Exception as e:  # noqa: BLE001
        return {"unsupported": f"live_arrays() raised: {e}"}


def memory_section() -> Dict[str, Any]:
    """The ``memory`` section every flight dump and plugin ``health()``
    carries: per-owner registry accounting + allocator/live-array
    cross-checks (explicit ``unsupported`` where the platform reports
    nothing) + watchdog state."""
    owners = RESOURCES.snapshot()
    return {
        "owners": owners,
        "total_registered_bytes": sum(
            r["nbytes"] for r in owners.values() if r.get("kind") == "device"),
        "device": device_memory(),
        "live_arrays": live_arrays_bytes(),
        "capabilities": capabilities(),
        "watchdog": MEMORY_WATCHDOG.state(),
    }


# ---------------------------------------------------------------------------
# Executable budget formulas: the docs' HBM envelopes as code
# ---------------------------------------------------------------------------


def _row_bytes(soa: Any) -> int:
    """Bytes per lane of one SoA section, from its REAL dtypes (the single
    source of truth stays the dataclass constructors, not a hand table)."""
    return sum(getattr(soa, f.name).dtype.itemsize
               for f in dataclasses.fields(soa))


_section_rows_cache: Optional[Tuple[int, int, int]] = None


def _section_rows() -> Tuple[int, int, int]:
    """(pod_row_bytes, node_row_bytes, group_row_bytes) derived ONCE from
    the canonical empty constructors (lazy import: jax-less processes never
    call a budget; cached: budgets evaluate at scrape/dump cadence)."""
    global _section_rows_cache
    if _section_rows_cache is None:
        from escalator_tpu.fleet.service import (
            _empty_groups,
            _empty_nodes,
            _empty_pods,
        )

        _section_rows_cache = (
            _row_bytes(_empty_pods(1)), _row_bytes(_empty_nodes(1)),
            _row_bytes(_empty_groups(1)))
    return _section_rows_cache


def expected_cluster_bytes(pod_capacity: int, node_capacity: int,
                           num_groups: int) -> int:
    """Resident ClusterArrays envelope: ``(P+1)`` pod rows + ``(N+1)`` node
    rows (each carries the scratch lane) + ``G`` group rows, at the real
    column dtypes — the docs/performance.md "25 B/pod + 40 B/node" figures,
    executable."""
    pod_b, node_b, group_b = _section_rows()
    return ((pod_capacity + 1) * pod_b + (node_capacity + 1) * node_b
            + num_groups * group_b)


def expected_aggregates_bytes(num_groups: int, node_lanes: int) -> int:
    """GroupAggregates envelope: nine int64 ``[G]`` sums + bool ``[G]``
    dirty + int64 ``[node_lanes]`` pods-remaining (node_lanes includes the
    scratch lane on the resident path)."""
    return num_groups * (9 * 8 + 1) + node_lanes * 8


_col_bytes_cache: Optional[int] = None


def expected_decision_columns_bytes(num_groups: int) -> int:
    """The 13 persistent decision columns at their wire dtypes (the
    ``fleet.service._COL_DTYPES`` contract — 76 B/group)."""
    global _col_bytes_cache
    if _col_bytes_cache is None:
        import numpy as np

        from escalator_tpu.fleet.service import _COL_DTYPES

        _col_bytes_cache = sum(np.dtype(dt).itemsize
                               for dt in _COL_DTYPES.values())
    return num_groups * _col_bytes_cache


def expected_order_state_bytes(node_lanes: int) -> int:
    """Persistent order state (round 10): three int64 key columns + one
    int32 permutation over the resident node lanes — 28 B/node."""
    return node_lanes * (8 + 8 + 8 + 4)


def expected_fleet_arena_bytes(num_tenants: int, num_groups: int,
                               pod_bucket: int, node_bucket: int) -> int:
    """The fleet's C-stacked arenas (docs/fleet.md capacity envelope):
    ``C+1`` tenant rows (scratch tenant included) of cluster sections +
    aggregates + decision columns at the arena buckets."""
    per_tenant = (
        expected_cluster_bytes(pod_bucket, node_bucket, num_groups)
        + expected_aggregates_bytes(num_groups, node_bucket + 1)
        + expected_decision_columns_bytes(num_groups)
    )
    return (num_tenants + 1) * per_tenant


# ---------------------------------------------------------------------------
# Growth watchdog: monotone registered-buffer growth == leak
# ---------------------------------------------------------------------------


class MemoryWatchdog:
    """Flags monotone live-buffer growth over a window as a leak.

    Every registered owner is a FIXED-size buffer between capacity growths
    (buckets double, rarely), so the total registered bytes should be a
    step function — a ramp is the signature of state retained per tick
    (an audit buffer never released, snapshot freezes accumulating, a
    fleet arena growing every batch). Sampled once per completed root tick
    from the flight-recorder hook (a metadata walk, ~microseconds); a
    breach claims the rate limit and dumps ``reason="memory"`` on a daemon
    worker exactly like the tail watchdog.

    Knobs (env, parsed per tick, memoized on the raw strings):

    - ``ESCALATOR_TPU_MEMORY_WATCH``: window in ticks (default 64;
      ``off``/``0`` disables).
    - ``ESCALATOR_TPU_MEMORY_MIN_GROWTH``: bytes the window must gain
      before a ramp counts (default 1 MiB) — jitter from transient owners
      (the audit double buffer blinking in and out) must not page anyone.
    - ``ESCALATOR_TPU_MEMORY_DUMP_INTERVAL_SEC``: rate limit between
      memory dumps (default 300).
    - ``ESCALATOR_TPU_MEMORY_SAMPLE_EVERY``: ticks between samples
      (default 8 — the steady-tick cost is then a counter increment; the
      window counts SAMPLES, so the default leak horizon is 8×64 ticks).
    """

    def __init__(self) -> None:
        self._lock = lockwitness.make_lock("resources.memwatch")
        self._samples: "collections.deque[int]" = collections.deque(
            maxlen=DEFAULT_WINDOW)
        self._last_dump_mono = -float("inf")
        self._worker: Optional[threading.Thread] = None
        self._ticks = 0
        self._cfg_cache: Tuple[Tuple[Optional[str], ...],
                               Tuple[int, int, float, int]] = (
            ("\0",), (0, 0, 0.0, 1))
        self.breaches = 0
        self.dumps = 0

    def _config(self) -> Tuple[int, int, float, int]:
        raw = (os.environ.get(_ENV_WATCH), os.environ.get(_ENV_MIN_GROWTH),
               os.environ.get(_ENV_INTERVAL),
               os.environ.get(_ENV_SAMPLE_EVERY))
        cached_raw, cached = self._cfg_cache
        if raw == cached_raw:
            return cached
        # strict parses (round-17 satellite, shared with the tail
        # watchdog): 0/negative/non-numeric values WARN once per distinct
        # raw value (the memoization on the raw strings provides the
        # once-ness) and run the default — the old bare int()/float()
        # accepted MEMORY_SAMPLE_EVERY=0 and MIN_GROWTH=-5 without a word
        from escalator_tpu.utils import envparse

        def parse(fn, idx, name, default, **kw):
            try:
                got = fn(raw[idx], name, **kw)
            except ValueError as e:
                log.warning("%s; using default %s", e, default)
                return default
            return default if got is None else got

        if raw[0] is not None and raw[0].strip() == "0":
            window = 0   # documented disable spelling for the window knob
        else:
            window = parse(envparse.parse_env_int, 0, _ENV_WATCH,
                           DEFAULT_WINDOW, allow_off=True, minimum=2)
        min_growth = parse(envparse.parse_env_int, 1, _ENV_MIN_GROWTH,
                           DEFAULT_MIN_GROWTH)
        interval = parse(envparse.parse_env_float, 2, _ENV_INTERVAL,
                         DEFAULT_INTERVAL_SEC, allow_off=True,
                         allow_zero=True)
        every = parse(envparse.parse_env_int, 3, _ENV_SAMPLE_EVERY,
                      DEFAULT_SAMPLE_EVERY)
        cfg = (window, min_growth, interval, every)
        self._cfg_cache = (raw, cfg)
        return cfg

    def on_tick(self, rec: Optional[Dict[str, Any]] = None) -> bool:
        """Sample + evaluate (flight-recorder root-complete hook). Returns
        True when a memory dump was scheduled."""
        window, min_growth, interval, every = self._config()
        if window <= 0:
            if self._samples:
                self._samples.clear()
            return False
        self._ticks += 1
        if self._ticks % every:
            return False
        total = RESOURCES.sampled_bytes()
        with self._lock:
            if self._samples.maxlen != window:
                self._samples = collections.deque(self._samples,
                                                  maxlen=window)
            self._samples.append(total)
            if len(self._samples) < window:
                return False
            seq = list(self._samples)
        steps = [b - a for a, b in zip(seq, seq[1:], strict=False)]
        growth = seq[-1] - seq[0]
        monotone = all(s >= 0 for s in steps)
        rising = sum(1 for s in steps if s > 0)
        if not (monotone and rising >= max(1, (window - 1) // 2)
                and growth >= min_growth):
            return False
        now = time.monotonic()
        with self._lock:
            self.breaches += 1
            if now - self._last_dump_mono < interval:
                return False
            self._last_dump_mono = now   # claimed before the handoff
            self.dumps += 1
            self._samples.clear()        # restart the window post-incident
        info = {
            "window_ticks": window,
            "first_bytes": seq[0],
            "last_bytes": seq[-1],
            "growth_bytes": growth,
            "rising_steps": rising,
            "owners": {name: row["nbytes"]
                       for name, row in RESOURCES.snapshot().items()},
            "tick_seq": (rec or {}).get("seq"),
        }
        try:
            from escalator_tpu.observability import journal

            journal.JOURNAL.event(
                "memory-breach", growth_bytes=growth,
                window_ticks=window, last_bytes=seq[-1],
                tick_seq=(rec or {}).get("seq"))
        except Exception:  # noqa: BLE001 - never break the tick
            pass
        worker = threading.Thread(
            target=self._dump, args=(info,),
            name="escalator-memory-dump", daemon=True)
        with self._lock:
            self._worker = worker
        worker.start()
        return True

    @staticmethod
    def _dump(info: Dict[str, Any]) -> None:
        from escalator_tpu.observability import flightrecorder

        flightrecorder.dump_on_incident("memory",
                                        extra={"memory_watchdog": info})

    def state(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "samples": len(self._samples),
                "last_bytes": self._samples[-1] if self._samples else None,
                "breaches": self.breaches,
                "dumps": self.dumps,
            }

    def drain(self, timeout: float = 10.0) -> None:
        """Join the in-flight dump worker (tests assert on the artifact)."""
        with self._lock:
            worker = self._worker
        if worker is not None:
            worker.join(timeout)

    def reset(self) -> None:
        with self._lock:
            self._samples.clear()
            self._last_dump_mono = -float("inf")
            self.breaches = 0
            self.dumps = 0


MEMORY_WATCHDOG = MemoryWatchdog()


# ---------------------------------------------------------------------------
# On-demand profiler capture: the next K root ticks as an XPlane trace
# ---------------------------------------------------------------------------


class ProfileCapture:
    """Wraps ``jax.profiler.start_trace/stop_trace`` around the next K
    completed root ticks. At most one capture at a time (the jax profiler
    is process-global); arming from any thread is safe, the countdown runs
    in the flight-recorder root-complete hook, and the stop (which
    serializes the trace to ``out_dir``) lands in the inter-tick gap of
    the Kth tick. Degrades to ``{"ok": False, "unsupported": reason}``
    where the platform lacks the profiler — never raises into a tick."""

    #: bound on waiting for a triggered stop's serialization to land —
    #: stop_trace writes the whole XPlane artifact, measured at tens of
    #: seconds late in a long-lived process
    STOP_TIMEOUT_SEC = 180.0

    def __init__(self) -> None:
        self._lock = lockwitness.make_lock("resources.profiler")
        self._active = False
        self._stopping = False
        self._remaining = 0
        self._dir: Optional[str] = None
        self._done: Optional[threading.Event] = None
        self.captures = 0
        self.last_error: Optional[str] = None

    @property
    def active(self) -> bool:
        return self._active

    def start(self, ticks: int, out_dir: str) -> Dict[str, Any]:
        """Arm a capture of the next ``ticks`` root ticks into ``out_dir``
        (created if needed). Non-blocking. Returns ``{"ok": True}``,
        ``{"ok": False, "busy": True}`` when a capture is in flight (or
        its stop is still serializing — starting a new trace under an
        unfinished stop_trace errors inside jax), or
        ``{"ok": False, "unsupported": reason}``."""
        reason = unavailable_reason("profiler")
        if reason is not None:
            return {"ok": False, "unsupported": reason}
        with self._lock:
            if self._active or self._stopping:
                return {"ok": False, "busy": True}
            jax = sys.modules.get("jax")
            try:
                os.makedirs(out_dir, exist_ok=True)
                jax.profiler.start_trace(out_dir)
            except Exception as e:  # noqa: BLE001 - platform-dependent
                self.last_error = str(e)
                return {"ok": False, "unsupported": f"start_trace: {e}"}
            self._active = True
            self._remaining = max(1, int(ticks))
            self._dir = out_dir
            self._done = threading.Event()
            return {"ok": True, "dir": out_dir, "ticks": self._remaining}

    def on_root_complete(self, rec: Optional[Dict[str, Any]] = None) -> None:
        """Countdown hook (flight recorder). The Kth tick TRIGGERS the
        stop; the stop itself — stop_trace serializes the whole XPlane
        artifact, tens of seconds in a long-lived process — runs on a
        daemon worker, never on the tick/RPC thread (the same discipline
        as the tail/memory dump workers)."""
        if not self._active:        # cheap fast path: one attribute read
            return
        with self._lock:
            if not self._active:
                return
            self._remaining -= 1
            if self._remaining > 0:
                return
            self._trigger_stop_locked()

    def _trigger_stop_locked(self) -> None:
        """Hand the stop to a worker (caller holds the lock). ``_done``
        sets only AFTER the serialization lands, so waiters see files."""
        self._active = False
        self._stopping = True
        done = self._done
        threading.Thread(target=self._do_stop, args=(done,),
                         name="escalator-profile-stop", daemon=True).start()

    def _do_stop(self, done: Optional[threading.Event]) -> None:
        jax = sys.modules.get("jax")
        try:
            if jax is not None:
                jax.profiler.stop_trace()
            self.captures += 1
        except Exception as e:  # noqa: BLE001
            self.last_error = str(e)
        with self._lock:
            self._stopping = False
        if done is not None:
            done.set()

    def capture(self, ticks: int, out_dir: str,
                timeout: float = 60.0) -> Dict[str, Any]:
        """Blocking convenience: arm, wait for the K ticks (driven by
        whatever traffic the process serves), return
        ``{"ok": True, "dir": ..., "ticks_captured": K}`` once the trace
        files have landed. On timeout the trace is stopped with whatever
        landed (``timed_out: True`` — a partial profile beats none); the
        wait for that stop's serialization is bounded separately by
        :data:`STOP_TIMEOUT_SEC`."""
        res = self.start(ticks, out_dir)
        if not res.get("ok"):
            return res
        done = self._done
        assert done is not None
        completed = done.wait(timeout)
        with self._lock:
            captured = max(1, int(ticks)) - max(0, self._remaining)
            if not completed and self._active:
                self._trigger_stop_locked()
        if not completed and not done.wait(self.STOP_TIMEOUT_SEC):
            # the serializer is STILL writing past the bound: the caller
            # must not read (or delete) the directory under it — report a
            # named failure instead of shipping torn files
            return {"ok": False, "stop_timeout": True,
                    "error": ("profiler stop did not finish within "
                              f"{self.STOP_TIMEOUT_SEC:.0f}s; trace "
                              "abandoned")}
        out = {"ok": True, "dir": out_dir, "ticks_captured": captured}
        if not completed:
            out["timed_out"] = True
        return out

    def wait_idle(self, timeout: float = STOP_TIMEOUT_SEC) -> bool:
        """Wait for the most recent capture's stop to finish serializing
        (True when idle) — callers that read the trace directory after the
        countdown stopped the capture (tests, the tail-profile operator)
        must not race the worker's write."""
        with self._lock:
            done = self._done
        return True if done is None else done.wait(timeout)

    def abort(self, timeout: float = STOP_TIMEOUT_SEC) -> None:
        """Stop an in-flight capture (test teardown); waits for the stop's
        serialization so the next test's start is not spuriously busy."""
        with self._lock:
            done = self._done
            if self._active:
                self._trigger_stop_locked()
        if done is not None:
            done.wait(timeout)


PROFILER = ProfileCapture()


def trace_files(out_dir: str) -> List[str]:
    """Relative paths of every file a profiler capture wrote under
    ``out_dir`` (the xplane.pb / trace.json.gz set TensorBoard loads)."""
    found: List[str] = []
    for root, _dirs, files in os.walk(out_dir):
        for name in files:
            found.append(os.path.relpath(os.path.join(root, name), out_dir))
    return sorted(found)
