"""Deterministic tick record/replay: re-execute a dumped ring bit-exactly.

The flight recorder (flightrecorder.py) answers *what happened* — phases,
digests, dirty counts. It cannot answer *why tick 417 decided what it did*,
because the inputs are gone. This module closes that gap: when input
recording is on, every incremental tick's **inputs** — the gathered
``(idx, old→new)`` delta batches, the repacked group rows, ``now_sec`` and
the lazy-orders gate — land in a bounded ring next to the flight recorder's
records, and any dump that carries the ring can be re-executed offline
(``escalator-tpu debug-replay``) against a device-state snapshot
(ops/snapshot.py), asserting per-tick crc32 decision-digest equality.

Determinism argument: the incremental decide is a pure function of
``(resident state, delta batch, now_sec, tainted_any)`` — integer/float64
ops with no RNG, no wall clock, no iteration-order dependence — and the
persistent state evolves only through the recorded scatter batches (the
donation protocol makes any other mutation a bug jaxlint's R5 would flag).
So replaying the batches from the snapshot's state reproduces every
decision bit-exactly, on any host, any time later. The one nondeterminism
in the live path — the background refresh audit's *timing* — is
bit-neutral by the PR-5 lockstep proof and is disabled during replay
anyway.

Recording is OFF by default (``ESCALATOR_TPU_RECORD_INPUTS=1`` or
``INPUT_LOG.set_enabled(True)``): a delta batch at production churn is a
few KB per tick, which is cheap but not free, and the ring is most useful
armed around an investigation. The flight recorder's dumps automatically
embed the ring (``tick_inputs``) whenever it is non-empty, so an incident
dump taken while recording is a self-contained replay bundle (modulo the
base snapshot, which the checkpoint cadence provides).
"""

from __future__ import annotations

import base64
import collections
import os
import zlib
from typing import Any, Dict, List, Optional

import numpy as np

from escalator_tpu.analysis import lockwitness

DEFAULT_CAPACITY = int(os.environ.get("ESCALATOR_TPU_INPUT_LOG_SIZE", "256"))


def decision_digest_arrays(status, nodes_delta) -> str:
    """:func:`decision_digest` over already-host column arrays — the form
    the backends' annotate-and-stage helper uses so the digest and the
    provenance feed share ONE device->host copy per column."""
    s = np.ascontiguousarray(np.asarray(status))
    d = np.ascontiguousarray(np.asarray(nodes_delta))
    return format(zlib.crc32(s.tobytes() + d.tobytes()), "08x")


def decision_digest(out) -> str:
    """crc32 over the decision-defining columns (status + nodes_delta) — the
    SAME token ``controller.backend._decision_digest`` stamps into flight
    records (that function delegates here), so a replayed tick's digest is
    directly comparable to the recorded one."""
    return decision_digest_arrays(out.status, out.nodes_delta)


def encode_array(arr) -> Dict[str, Any]:
    """JSON-safe exact encoding: dtype + shape + base64 raw bytes. Integer,
    bool and float64 columns all round-trip bit-exactly."""
    a = np.ascontiguousarray(np.asarray(arr))
    return {
        "dtype": str(a.dtype),
        "shape": list(a.shape),
        "b64": base64.b64encode(a.tobytes()).decode("ascii"),
    }


def decode_array(spec: Dict[str, Any]) -> np.ndarray:
    raw = base64.b64decode(spec["b64"])
    return np.frombuffer(raw, dtype=np.dtype(spec["dtype"])).reshape(
        spec["shape"]).copy()


class TickInputLog:
    """Bounded ring of per-tick input records (thread-safe; the decider's
    tick thread appends, dump/CLI threads snapshot)."""

    def __init__(self, capacity: int = DEFAULT_CAPACITY):
        self.capacity = int(capacity)
        self._ring: "collections.deque[Dict[str, Any]]" = collections.deque(
            maxlen=self.capacity)
        self._lock = lockwitness.make_lock("replay.ring")
        self._enabled = os.environ.get(
            "ESCALATOR_TPU_RECORD_INPUTS", "0").lower() in ("1", "true", "yes")

    def enabled(self) -> bool:
        return self._enabled

    def set_enabled(self, value: bool) -> None:
        self._enabled = bool(value)

    @property
    def depth(self) -> int:
        with self._lock:
            return len(self._ring)

    def record(self, entry: Dict[str, Any]) -> None:
        with self._lock:
            self._ring.append(entry)

    def snapshot(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._ring)

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()


#: the process-wide input log the incremental decider records into
INPUT_LOG = TickInputLog()


def encode_batch(gathered, groups) -> Dict[str, Any]:
    """One ``apply_gathered`` call's inputs: the padded (idx, values) pod and
    node batches plus the (tiny, [G]) group rows when the caller re-uploaded
    them. SoA values encode field by field, iterating dataclass fields — the
    decode side mirrors this exactly."""
    pidx, pvals, nidx, nvals = gathered
    enc: Dict[str, Any] = {
        "pod_idx": encode_array(pidx),
        "pod_vals": {f: encode_array(getattr(pvals, f))
                     for f in pvals.__dataclass_fields__},
        "node_idx": encode_array(nidx),
        "node_vals": {f: encode_array(getattr(nvals, f))
                      for f in nvals.__dataclass_fields__},
    }
    if groups is not None:
        enc["groups"] = {f: encode_array(getattr(groups, f))
                         for f in groups.__dataclass_fields__}
    return enc


def decode_batch(enc: Dict[str, Any]):
    """Inverse of :func:`encode_batch` → ``(gathered, groups)``."""
    from escalator_tpu.core.arrays import GroupArrays, NodeArrays, PodArrays

    gathered = (
        decode_array(enc["pod_idx"]),
        PodArrays(**{f: decode_array(v) for f, v in enc["pod_vals"].items()}),
        decode_array(enc["node_idx"]),
        NodeArrays(**{f: decode_array(v) for f, v in enc["node_vals"].items()}),
    )
    groups = None
    if enc.get("groups") is not None:
        groups = GroupArrays(
            **{f: decode_array(v) for f, v in enc["groups"].items()})
    return gathered, groups


# ---------------------------------------------------------------------------
# Replay executor
# ---------------------------------------------------------------------------


def replay_ring(entries: List[Dict[str, Any]],
                snapshot_path: Optional[str] = None,
                leaves=None, meta=None,
                explain: bool = False,
                explain_groups=None) -> Dict[str, Any]:
    """Re-execute a recorded input ring from a device-state snapshot and
    compare each tick's decision digest (and lazy-orders outcome) against
    the recording. Returns a report dict::

        {"ok": bool, "base_tick": int, "replayed": N,
         "skipped_older": M, "divergent": [per-tick mismatches],
         "ticks": [{"tick", "digest", "recorded_digest", "ok"}, ...]}

    The refresh audit and input recording are disabled inside the replay
    decider — both are bit-neutral, but replay must not re-record itself or
    spend O(cluster) audits re-verifying state it just adopted. Entries at
    or before the snapshot's tick are skipped (the ring may be longer than
    the checkpoint gap); a gap in the remaining tick sequence is a hard
    error — a replay over missing inputs would diverge for boring reasons
    and mask real ones.

    ``explain=True`` (round 19, ``debug-explain --replay``) additionally
    runs the explain kernel over the FINAL replayed state and attaches the
    per-group explanation documents as ``report["explanations"]`` — the
    same named terms, threshold-branch attribution and bit-cross-check
    against the committed columns a live server would serve at that tick,
    reproduced offline from a dump + snapshot alone (the determinism
    argument above extends verbatim: the explain kernel is a pure function
    of the replayed resident state)."""
    from escalator_tpu.ops import device_state as ds
    from escalator_tpu.ops import snapshot as snaplib

    if leaves is None:
        leaves, meta = snaplib.read_snapshot(snapshot_path)
    base_tick = int(meta.get("tick", 0))
    todo = sorted(
        (e for e in entries if int(e["tick"]) > base_tick),
        key=lambda e: int(e["tick"]))
    skipped = len(entries) - len(todo)
    for i, e in enumerate(todo):
        if int(e["tick"]) != base_tick + 1 + i:
            raise ValueError(
                f"input ring has a gap: expected tick {base_tick + 1 + i}, "
                f"found {e['tick']} — the ring no longer covers the span "
                "from this snapshot (take dumps closer to a checkpoint)")

    _cache, inc = ds.restore_decider(
        leaves, meta, refresh_every=0, background=False,
        post_restore_audit=False)
    ticks: List[Dict[str, Any]] = []
    divergent: List[Dict[str, Any]] = []
    for e in todo:
        for enc in e.get("batches", ()):
            gathered, groups = decode_batch(enc)
            inc.apply_gathered(gathered, groups)
        out, ordered = inc.decide(
            int(e["now_sec"]), bool(e["tainted_any"]), _record=False)
        digest = decision_digest(out)
        row = {
            "tick": int(e["tick"]),
            "digest": digest,
            "recorded_digest": e.get("digest"),
            "ordered": bool(ordered),
            "recorded_ordered": bool(e.get("ordered")),
            "ok": (digest == e.get("digest")
                   and bool(ordered) == bool(e.get("ordered"))),
        }
        ticks.append(row)
        if not row["ok"]:
            divergent.append(row)
    report = {
        "ok": not divergent,
        "base_tick": base_tick,
        "replayed": len(ticks),
        "skipped_older": skipped,
        "divergent": divergent,
        "ticks": ticks,
    }
    if explain:
        report["explain_tick"] = base_tick + len(ticks)
        report["explanations"] = inc.explain(groups=explain_groups)
    return report
