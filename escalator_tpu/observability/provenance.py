"""Decision provenance: explainable scale decisions + the flap watchdog.

The observability stack answers *how fast* (histograms, journeys) and *how
healthy* (resources, journal); this module answers **why group G scaled by
Δ** — the question the reference controller's verbose per-nodegroup logging
exists for (``scaleNodeGroup`` → percent usage ``util.go:58-81`` → threshold
switch ``controller.go:332-351``), and the question every tail/SLO-burn
investigation otherwise dead-ends on. Three pieces:

- **Explanations**: the explain kernel (``ops.kernel.explain_decide`` /
  ``ops.device_state.explain_groups``) re-runs the decision calculus over
  the resident state and emits every intermediate BY NAME — masked
  request/capacity sums, cpu/mem percent, ``percentageNeeded``, the active
  threshold-switch arm, the scale-delta derivation, the taint/cordon/drain
  gates, scale-down candidate ranks. This module turns those device terms
  into JSON-safe explanation documents (:func:`build_explanations`) and
  bit-cross-checks the reconstructed columns against the COMMITTED decision
  columns (:func:`cross_check`): the shared math core makes a mismatch
  impossible unless the persistent aggregates drifted (stale cache, missed
  dirty mark) — exactly the bug class the check exists to catch, so any
  mismatch is itself a finding (``explain-mismatch`` journal event + flight
  dump + counter).

- **Decision history + flap watchdog**: a bounded per-(tenant, group) ring
  of recent ``(tick, status, nodes_delta)`` records fed from the flight
  recorder's root-complete hook (decide paths stage the already-host
  columns via :func:`stage`; the hook drains the stash after every timed
  phase closed, so the feed adds nothing to any phase duration). A
  sign-alternation detector over the ring flags oscillating groups —
  up/down/up within the window — with ``fleet_group_flaps_total{klass}``,
  a ``group-flap`` journal event, and a rate-limited ``reason="flap"``
  flight dump naming the offending groups with their explanations attached.

- **Surfacing**: the plugin ``Explain`` RPC and ``escalator-tpu
  debug-explain`` / ``debug-decision-diff`` (cli.py) read the same
  documents; :func:`dump_section` embeds explanations for breaching
  tenants into tail/SLO/flap flight dumps; :func:`health_section` feeds
  the plugin health doc.

Knobs (all env; strict-parsed per utils/envparse, warn-and-default,
memoized on the raw strings):

- ``ESCALATOR_TPU_FLAP_WINDOW``: ring depth the detector scans (default
  8 decisions per group; ``off``/``0`` disables detection — history still
  records).
- ``ESCALATOR_TPU_FLAP_MIN_ALTERNATIONS``: delta-sign flips within the
  window that make a flap (default 3: up/down/up/down).
- ``ESCALATOR_TPU_FLAP_DUMP_INTERVAL_SEC``: rate limit between ``flap``
  dumps per history key (default 300; ``off`` disables the limit; every
  flap journals regardless).

Import cost: stdlib only (numpy lazily inside the feed path) — this module
loads with ``escalator_tpu.observability`` on processes that may never
import jax.
"""

from __future__ import annotations

import collections
import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from escalator_tpu.analysis import lockwitness

__all__ = [
    "COLUMN_FIELDS", "STATUS_BRANCHES", "TERM_GLOSSARY",
    "THRESHOLD_BRANCHES", "DecisionHistory", "FlapWatchdog", "FLAPS",
    "HISTORY", "build_explanations", "cross_check", "diff_explanations",
    "dump_section", "explain_for", "health_section", "on_timeline",
    "register_explainer", "report_mismatches", "reset", "stage",
]

_ENV_WINDOW = "ESCALATOR_TPU_FLAP_WINDOW"
_ENV_MIN_ALT = "ESCALATOR_TPU_FLAP_MIN_ALTERNATIONS"
_ENV_INTERVAL = "ESCALATOR_TPU_FLAP_DUMP_INTERVAL_SEC"

DEFAULT_WINDOW = 8
DEFAULT_MIN_ALTERNATIONS = 3
DEFAULT_INTERVAL_SEC = 300.0
#: history ring depth per key (>= the largest usable flap window)
DEFAULT_HISTORY_DEPTH = int(os.environ.get(
    "ESCALATOR_TPU_PROVENANCE_HISTORY", "32"))
#: distinct history keys kept (LRU): tenants come and go; the observatory
#: must stay bounded no matter how many ids a soak churns through
_MAX_KEYS = 1024

#: timeline-meta stash key for staged decisions (private: deliberately NOT
#: in flightrecorder._META_KEYS, so the stash never bloats tick records)
_STASH = "_provenance_decisions"

#: the 13 persistent decision columns (ops.kernel.GROUP_DECISION_FIELDS —
#: duplicated here so importing the glossary never imports jax; the sync is
#: asserted in tests/test_provenance.py)
COLUMN_FIELDS = (
    "status", "nodes_delta", "cpu_percent", "mem_percent",
    "cpu_request_milli", "mem_request_bytes",
    "cpu_capacity_milli", "mem_capacity_bytes",
    "num_pods", "num_nodes", "num_untainted", "num_tainted", "num_cordoned",
)

#: kernel.EXPLAIN_THRESHOLD_BRANCHES twin (sync asserted in tests)
THRESHOLD_BRANCHES = ("scale_down_fast", "scale_down_slow", "scale_up",
                      "hold")
#: kernel.EXPLAIN_STATUS_BRANCHES twin (sync asserted in tests)
STATUS_BRANCHES = ("invalid_or_empty", "below_min", "above_max",
                   "forced_min", "div_zero", "locked", "neg_delta",
                   "threshold_switch")

#: every explain term, mapped back to the reference controller's source
#: lines — the debug-explain glossary (docs/observability.md renders this)
TERM_GLOSSARY: Dict[str, str] = {
    "status": "committed DecisionStatus code (controller.go:192-397 cascade)",
    "nodes_delta": "committed scaleNodeGroup verdict (controller.go:332-351)",
    "cpu_percent": "reported cpu percent, 0 on pre-percent exits "
                   "(util.go:58-81)",
    "mem_percent": "reported mem percent via MilliValue = bytes*1000 "
                   "(util.go:58-81)",
    "cpu_request_milli": "masked Σ pod cpu requests (k8s/util.go:27-51; "
                         "zeroed on pre-aggregation exits, "
                         "controller.go:233-255)",
    "mem_request_bytes": "masked Σ pod mem requests (k8s/util.go:27-51)",
    "cpu_capacity_milli": "masked Σ node cpu capacity (k8s/util.go:27-51)",
    "mem_capacity_bytes": "masked Σ node mem capacity (k8s/util.go:27-51)",
    "num_pods": "pods counted by the filter pass (controller.go:210-230)",
    "num_nodes": "registered nodes in the group (controller.go:210-230)",
    "num_untainted": "schedulable nodes (controller.go:210-230)",
    "num_tainted": "tainted nodes (controller.go:210-230)",
    "num_cordoned": "cordoned nodes (controller.go:210-230)",
    "cpu_percent_raw": "cpu percent before the reporting mask "
                       "(util.go:58-81)",
    "mem_percent_raw": "mem percent before the reporting mask "
                       "(util.go:58-81)",
    "max_percent": "max(cpu, mem) percent — the threshold switch's input "
                   "(controller.go:332)",
    "from_zero_cpu_needed": "scale-from-zero cpu node estimate from cached "
                            "per-node capacity (util.go:39-46)",
    "from_zero_mem_needed": "scale-from-zero mem node estimate "
                            "(util.go:39-46)",
    "percentage_needed_cpu": "ceil(nodes*(cpu% - thr)/thr) — Go's "
                             "percentageNeeded op order (util.go:33-37)",
    "percentage_needed_mem": "ceil(nodes*(mem% - thr)/thr) (util.go:33-37)",
    "nodes_needed": "max of the cpu/mem estimates pre-truncation "
                    "(util.go:13-46)",
    "up_delta": "int(math.Max(...)) — the scale-up delta before the "
                "threshold switch applies it (util.go:46)",
    "switch_delta": "the threshold switch's verdict before the status "
                    "cascade overrides (controller.go:332-351)",
    "gate_all_zero": "no requests, capacity or untainted nodes: percents "
                     "report 0 (util.go:60-63)",
    "gate_from_zero": "zero capacity, zero untainted: MaxFloat64 percent "
                      "forces scale-from-zero (util.go:64-71)",
    "gate_div_zero": "zero capacity WITH untainted nodes: ERR_DIV_ZERO "
                     "(util.go:72-75)",
    "gate_no_cache": "no cached per-node capacity for scale-from-zero: "
                     "delta falls back to 1 (util.go:41-43)",
    "gate_bad_threshold": "non-positive scale_up_threshold: ERR_NEG_DELTA "
                          "(node_group.go:96 rejects; guarded anyway)",
    "gate_neg_delta": "the scale-up arm computed a negative delta "
                      "(controller.go:345-347)",
    "gate_down_fast": "max_percent < taint_lower (controller.go:334)",
    "gate_down_slow": "taint_lower <= max_percent < taint_upper "
                      "(controller.go:338)",
    "gate_scale_up": "max_percent > scale_up_threshold "
                     "(controller.go:343)",
    "gate_empty": "zero nodes AND zero pods: NOOP_EMPTY "
                  "(controller.go:216-221)",
    "gate_below_min": "num_nodes < min_nodes (controller.go:233)",
    "gate_above_max": "num_nodes > max_nodes (controller.go:244)",
    "gate_forced_min": "untainted < min_nodes: forced scale-up "
                       "(controller.go:258-266)",
    "gate_invalid": "unregistered/invalid group row",
    "gate_locked": "scale lock: delta passes through requested_nodes "
                   "(controller.go:269-279)",
    "gate_pct_computed": "percents were computed (no pre-percent exit "
                         "fired)",
    "gate_pre_agg_exit": "exit before aggregation: the masked sums report "
                         "0 (controller.go:233-255)",
    "threshold_branch": "which controller.go:332-351 arm fired (exactly "
                        "one): " + "/".join(THRESHOLD_BRANCHES),
    "status_branch": "first status-cascade exit arm "
                     "(controller.go:192-397): "
                     + "/".join(STATUS_BRANCHES),
    "cfg_scale_up_threshold": "configured scale-up threshold percent",
    "cfg_taint_lower": "configured taint_lower_percent",
    "cfg_taint_upper": "configured taint_upper_percent",
    "cfg_fast_rate": "configured fast scale-down node rate",
    "cfg_slow_rate": "configured slow scale-down node rate",
    "cfg_min_nodes": "configured min_nodes",
    "cfg_max_nodes": "configured max_nodes",
    "cfg_cached_cpu_milli": "cached per-node cpu for scale-from-zero",
    "cfg_cached_mem_bytes": "cached per-node mem for scale-from-zero",
}

_CONFIG_KEYS = tuple(k for k in TERM_GLOSSARY if k.startswith("cfg_"))
_GATE_KEYS = tuple(k for k in TERM_GLOSSARY if k.startswith("gate_"))


def _status_name(code: int) -> str:
    from escalator_tpu.core.semantics import DecisionStatus

    try:
        return DecisionStatus(int(code)).name
    except ValueError:
        return f"UNKNOWN_{int(code)}"


def _scalar(x) -> Any:
    """One array element as a JSON-exact python scalar (json round-trips
    float64 via repr bit-exactly; ints/bools pass through)."""
    import numpy as np

    v = x.item() if isinstance(x, np.generic) or hasattr(x, "item") else x
    return v


# ---------------------------------------------------------------------------
# Explanations + the bit-cross-check
# ---------------------------------------------------------------------------


def cross_check(terms: Dict[str, Any], committed: Dict[str, Any],
                skip=None) -> List[Dict[str, Any]]:
    """Bit-compare the explain kernel's reconstructed decision columns
    against the COMMITTED columns. ``skip`` is an optional bool [G] mask of
    groups whose committed columns are legitimately behind (dirty groups: a
    pending delta has not been decided yet, so the reconstruction is the
    *next* decision, not a drifted one). Returns one finding per differing
    (group, field): ``{"group", "field", "explained", "committed"}``.

    Float columns compare on raw bits (a NaN or -0.0 drift must not hide
    behind ``==`` semantics); integer columns on value."""
    import numpy as np

    findings: List[Dict[str, Any]] = []
    for field in COLUMN_FIELDS:
        if field not in committed or committed[field] is None:
            continue
        a = np.asarray(terms[field])
        b = np.asarray(committed[field])
        if a.shape != b.shape:
            findings.append({"group": -1, "field": field,
                             "explained": list(a.shape),
                             "committed": list(b.shape)})
            continue
        if a.dtype.kind == "f":
            diff = a.view(np.int64) != b.astype(a.dtype).view(np.int64)
        else:
            diff = a != b
        if skip is not None:
            diff = diff & ~np.asarray(skip)
        for g in np.nonzero(diff)[0]:
            findings.append({
                "group": int(g), "field": field,
                "explained": _scalar(a[g]), "committed": _scalar(b[g]),
            })
    return findings


def build_explanations(terms: Dict[str, Any],
                       committed: Optional[Dict[str, Any]] = None,
                       dirty=None,
                       groups: Optional[Sequence[int]] = None,
                       candidates: Optional[Dict[int, List[int]]] = None,
                       ) -> List[Dict[str, Any]]:
    """Per-group explanation documents from the explain kernel's host term
    dict. ``committed`` (column name -> [G] array) arms the bit-cross-check;
    ``dirty`` marks groups whose committed columns are legitimately pending.
    ``groups`` restricts the output set (default: every group); valid=False
    rows are kept — an invalid group's NOOP_EMPTY is a decision too.
    ``candidates`` optionally attaches scale-down victim node ids per group
    (from order state / a cached ordered answer)."""
    import numpy as np

    G = int(np.asarray(terms["status"]).shape[0])
    wanted = range(G) if groups is None else [g for g in groups
                                             if 0 <= int(g) < G]
    mismatches = (cross_check(terms, committed, skip=dirty)
                  if committed is not None else [])
    by_group: Dict[int, List[Dict[str, Any]]] = {}
    for m in mismatches:
        by_group.setdefault(m["group"], []).append(m)
    dirty_arr = None if dirty is None else np.asarray(dirty)
    docs = []
    for g in wanted:
        g = int(g)
        tb = int(np.asarray(terms["threshold_branch"])[g])
        sb = int(np.asarray(terms["status_branch"])[g])
        doc: Dict[str, Any] = {
            "group": g,
            "status": _scalar(np.asarray(terms["status"])[g]),
            "status_name": _status_name(
                _scalar(np.asarray(terms["status"])[g])),
            "nodes_delta": _scalar(np.asarray(terms["nodes_delta"])[g]),
            "threshold_branch": THRESHOLD_BRANCHES[tb],
            "status_branch": STATUS_BRANCHES[sb],
            "stale": bool(dirty_arr[g]) if dirty_arr is not None else False,
            "terms": {k: _scalar(np.asarray(terms[k])[g])
                      for k in TERM_GLOSSARY
                      if k in terms and not k.startswith(("gate_", "cfg_"))
                      and k not in ("threshold_branch", "status_branch")},
            "gates": {k: bool(np.asarray(terms[k])[g])
                      for k in _GATE_KEYS if k in terms},
            "config": {k: _scalar(np.asarray(terms[k])[g])
                       for k in _CONFIG_KEYS if k in terms},
        }
        if by_group.get(g):
            doc["mismatches"] = by_group[g]
        if candidates and g in candidates:
            doc["scale_down_candidates"] = [int(n) for n in candidates[g]]
        docs.append(doc)
    return docs


def candidate_windows(scale_down_order, untainted_offsets,
                      max_per_group: int = 8) -> Dict[int, List[int]]:
    """Scale-down victim ranks from an ORDERED decision (host arrays):
    group g's candidates are ``scale_down_order[untainted_offsets[g] :
    untainted_offsets[g+1]]`` — the reference's taintOldestN consumption
    order (scale_down.go:171) — truncated to ``max_per_group``."""
    import numpy as np

    order = np.asarray(scale_down_order)
    offs = np.asarray(untainted_offsets)
    out: Dict[int, List[int]] = {}
    for g in range(offs.shape[0] - 1):
        lo, hi = int(offs[g]), int(offs[g + 1])
        if hi > lo:
            out[g] = [int(n) for n in order[lo:min(hi, lo + max_per_group)]]
    return out


_mismatch_lock = lockwitness.make_lock("provenance.mismatch")
_last_mismatch_dump_mono = [-float("inf")]
_mismatch_total = [0]


def report_mismatches(context: str, mismatches: List[Dict[str, Any]],
                      explanations: Optional[List[Dict[str, Any]]] = None
                      ) -> None:
    """An explain/committed divergence IS a finding (the shared math core
    makes it an aggregate-drift symptom): journal it, count it, and flight-
    dump (rate-limited to one per flap interval — a systematically drifted
    arena would otherwise dump per explain call). Never raises."""
    if not mismatches:
        return
    try:
        from escalator_tpu.metrics import metrics

        metrics.provenance_explain_mismatches.inc(len(mismatches))
    except Exception:  # noqa: BLE001 - observability must never break
        pass
    try:
        from escalator_tpu.observability import journal

        journal.JOURNAL.event(
            "explain-mismatch", context=context, count=len(mismatches),
            fields=sorted({m["field"] for m in mismatches}),
            groups=sorted({m["group"] for m in mismatches})[:16])
    except Exception:  # noqa: BLE001
        pass
    now = time.monotonic()
    with _mismatch_lock:
        _mismatch_total[0] += len(mismatches)
        _, _, interval = FLAPS._config()
        limited = (interval and
                   now - _last_mismatch_dump_mono[0] < interval)
        if not limited:
            _last_mismatch_dump_mono[0] = now
    if limited:
        return
    try:
        from escalator_tpu.observability import flightrecorder

        extra: Dict[str, Any] = {"explain_mismatch": {
            "context": context, "mismatches": mismatches[:64]}}
        if explanations:
            extra["explain_mismatch"]["explanations"] = explanations[:16]
        flightrecorder.dump_on_incident("explain-mismatch", extra=extra)
    except Exception:  # noqa: BLE001
        pass


def mismatch_total() -> int:
    with _mismatch_lock:
        return _mismatch_total[0]


# ---------------------------------------------------------------------------
# Decision history + flap watchdog
# ---------------------------------------------------------------------------


class DecisionHistory:
    """Bounded per-key ring of ``(tick, status [G], nodes_delta [G])``
    records — key is a tenant id (fleet) or the backend's root name
    (single cluster). LRU-bounded on keys; a shape change (arena/group
    reconfigure) restarts the key's ring (stacking mixed widths would be
    meaningless)."""

    def __init__(self, depth: int = DEFAULT_HISTORY_DEPTH,
                 max_keys: int = _MAX_KEYS):
        self.depth = max(2, int(depth))
        self.max_keys = int(max_keys)
        self._lock = lockwitness.make_lock("provenance.history")
        self._rings: "collections.OrderedDict[str, collections.deque]" = (
            collections.OrderedDict())
        self._seq: Dict[str, int] = {}

    def push(self, key: str, status, delta,
             tick: Optional[int] = None) -> Tuple[int, list]:
        """Append one decision record; returns ``(tick, window)`` where
        window is the ring contents (newest last) for the detector."""
        import numpy as np

        status = np.asarray(status)
        delta = np.asarray(delta)
        with self._lock:
            ring = self._rings.get(key)
            if ring is None:
                if len(self._rings) >= self.max_keys:
                    old, _ = self._rings.popitem(last=False)
                    self._seq.pop(old, None)
                ring = collections.deque(maxlen=self.depth)
                self._rings[key] = ring
            else:
                self._rings.move_to_end(key)
                if ring and ring[-1][1].shape != status.shape:
                    ring.clear()   # reconfigured: old widths are apples
            if tick is None:
                tick = self._seq.get(key, 0) + 1
            self._seq[key] = int(tick)
            ring.append((int(tick), status, delta))
            return int(tick), list(ring)

    def history(self, key: str, group: Optional[int] = None
                ) -> List[Dict[str, Any]]:
        with self._lock:
            ring = list(self._rings.get(key, ()))
        out = []
        for tick, status, delta in ring:
            if group is None:
                out.append({"tick": tick,
                            "status": [int(s) for s in status],
                            "nodes_delta": [int(d) for d in delta]})
            elif 0 <= group < status.shape[0]:
                out.append({"tick": tick, "status": int(status[group]),
                            "nodes_delta": int(delta[group])})
        return out

    def keys(self) -> List[str]:
        with self._lock:
            return list(self._rings)

    def reset(self) -> None:
        with self._lock:
            self._rings.clear()
            self._seq.clear()


class FlapWatchdog:
    """Sign-alternation/oscillation detector over the decision history
    (singleton :data:`FLAPS`). Two flap classes:

    - ``delta_sign``: a group's nodes_delta sign alternated >= min_alt
      times within the window (holds between moves still count — up, hold,
      down, hold, up is the classic thrash);
    - ``status_churn``: a group's status toggled between exactly two codes
      >= min_alt times (e.g. OK <-> FORCED_MIN bouncing on a taint edge).

    Every flap journals (``group-flap``) and counts
    (``fleet_group_flaps_total{klass}``); the flight dump is rate-limited
    per history key and carries the offending groups' explanations when an
    explainer is registered. A group that keeps flapping re-fires only
    after a full window of new decisions — a sustained oscillation is one
    incident per window, not one per tick."""

    def __init__(self) -> None:
        self._lock = lockwitness.make_lock("provenance.flaps")
        self._cfg_cache: Tuple[Tuple[Optional[str], ...],
                               Tuple[int, int, float]] = (
            ("\0",), (0, 0, 0.0))
        self._last_dump_mono: Dict[str, float] = {}
        #: (key, group) -> tick of the last fired flap (debounce)
        self._last_flap: Dict[Tuple[str, int], int] = {}
        self._worker: Optional[threading.Thread] = None
        self.flaps = 0      # flap incidents observed (dumped or limited)
        self.dumps = 0      # dumps handed to the worker
        #: bounded recent-flap ring for health/metrics/top-K surfacing
        self.recent: "collections.deque" = collections.deque(maxlen=64)
        #: (key, group) -> total flap incidents (bounded with history keys)
        self._totals: Dict[Tuple[str, int], int] = {}

    # -- config ------------------------------------------------------------
    def _config(self) -> Tuple[int, int, float]:
        """(window, min_alternations, dump_interval_sec); window 0 means
        detection off. Same memoize-on-raw-strings discipline as the tail
        watchdog: steady ticks pay one dict lookup, typos warn once."""
        raw = (os.environ.get(_ENV_WINDOW), os.environ.get(_ENV_MIN_ALT),
               os.environ.get(_ENV_INTERVAL))
        cached_raw, cached = self._cfg_cache
        if raw == cached_raw:
            return cached
        import logging

        from escalator_tpu.utils import envparse

        warn = logging.getLogger("escalator_tpu.observability").warning
        try:
            window = envparse.parse_env_int(raw[0], _ENV_WINDOW,
                                            allow_off=True, minimum=2)
        except ValueError as e:
            warn("%s; using default %d", e, DEFAULT_WINDOW)
            window = None
        try:
            min_alt = envparse.parse_env_int(raw[1], _ENV_MIN_ALT)
        except ValueError as e:
            warn("%s; using default %d", e, DEFAULT_MIN_ALTERNATIONS)
            min_alt = None
        try:
            interval = envparse.parse_env_float(raw[2], _ENV_INTERVAL,
                                                allow_off=True,
                                                allow_zero=True)
        except ValueError as e:
            warn("%s; using default %.0f", e, DEFAULT_INTERVAL_SEC)
            interval = None
        cfg = (DEFAULT_WINDOW if window is None else window,
               DEFAULT_MIN_ALTERNATIONS if min_alt is None else min_alt,
               DEFAULT_INTERVAL_SEC if interval is None else interval)
        self._cfg_cache = (raw, cfg)
        return cfg

    # -- detection ---------------------------------------------------------
    @staticmethod
    def _alternations(window: list):
        """Vectorized scan: per group, count delta-sign flips (vs the last
        NONZERO sign — holds don't break an oscillation) and status
        two-value toggles. O(W) numpy ops on [G] rows."""
        import numpy as np

        deltas = np.stack([d for _, _, d in window])      # [W, G]
        statuses = np.stack([s for _, s, _ in window])    # [W, G]
        signs = np.sign(deltas)
        G = deltas.shape[1]
        alt = np.zeros(G, np.int32)
        last = np.zeros(G, np.int32)
        for w in range(signs.shape[0]):
            s = signs[w].astype(np.int32)
            alt += ((s != 0) & (last != 0) & (s != last)).astype(np.int32)
            last = np.where(s != 0, s, last)
        changes = (statuses[1:] != statuses[:-1]).sum(axis=0).astype(
            np.int32) if statuses.shape[0] > 1 else np.zeros(G, np.int32)
        two_valued = np.array([
            len(np.unique(statuses[:, g])) == 2 for g in range(G)
        ]) if G else np.zeros(0, bool)
        return alt, changes, two_valued

    def on_decisions(self, key: str, tick: int, window: list) -> List[dict]:
        """Run detection over one key's updated ring; returns the fired
        flap findings (tests assert on them). Called from the root-complete
        hook — after every timed phase closed — and prefiltered there so
        steady workloads never reach the stack/scan."""
        win, min_alt, interval = self._config()
        if not win or len(window) < 3:
            return []
        window = window[-win:]
        alt, changes, two_valued = self._alternations(window)
        import numpy as np

        sign_flaps = np.nonzero(alt >= min_alt)[0]
        churn_flaps = np.nonzero((changes >= 2 * min_alt) & two_valued)[0]
        findings = []
        for klass, hits in (("delta_sign", sign_flaps),
                            ("status_churn", churn_flaps)):
            for g in hits:
                g = int(g)
                with self._lock:
                    if tick - self._last_flap.get((key, g), -win) < win:
                        continue   # same oscillation, already reported
                    self._last_flap[(key, g)] = tick
                    if len(self._last_flap) > 4 * _MAX_KEYS:
                        self._last_flap.clear()
                findings.append({
                    "key": key, "group": g, "klass": klass, "tick": tick,
                    "alternations": int(alt[g]),
                    "status_changes": int(changes[g]),
                    "history": [
                        {"tick": t, "status": int(s[g]),
                         "nodes_delta": int(d[g])} for t, s, d in window],
                })
        if findings:
            self._fire(key, tick, findings)
        return findings

    def _fire(self, key: str, tick: int, findings: List[dict]) -> None:
        win, min_alt, interval = self._config()
        now = time.monotonic()
        with self._lock:
            self.flaps += len(findings)
            for f in findings:
                self._totals[(key, f["group"])] = self._totals.get(
                    (key, f["group"]), 0) + 1
                self.recent.append({k: f[k] for k in
                                    ("key", "group", "klass", "tick")})
            if len(self._totals) > 4 * _MAX_KEYS:
                self._totals.clear()
            rate_limited = (interval and now - self._last_dump_mono.get(
                key, -float("inf")) < interval)
            if not rate_limited:
                self._last_dump_mono[key] = now   # claimed pre-handoff
                self.dumps += 1
        try:
            from escalator_tpu.metrics import metrics

            for f in findings:
                metrics.fleet_group_flaps.labels(f["klass"]).inc()
        except Exception:  # noqa: BLE001 - never break the tick
            pass
        try:
            # every flap is a journal event — dumped or rate-limited — so
            # "when did the thrash start" survives the dump rate limit
            from escalator_tpu.observability import journal

            journal.JOURNAL.event(
                "group-flap", key=key, tick=tick,
                groups=[f["group"] for f in findings],
                klasses=sorted({f["klass"] for f in findings}),
                window=win, min_alternations=min_alt,
                dumped=not rate_limited)
        except Exception:  # noqa: BLE001
            pass
        if rate_limited:
            return
        # the dump (JSON of a 256-deep ring + an explain gather) runs on a
        # daemon worker — the breaching tick's successor must not pay it
        worker = threading.Thread(
            target=self._dump, args=(key, findings),
            name="escalator-flap-dump", daemon=True)
        with self._lock:
            self._worker = worker
        worker.start()

    @staticmethod
    def _dump(key: str, findings: List[dict]) -> None:
        from escalator_tpu.observability import flightrecorder

        flap_info: Dict[str, Any] = {
            "key": key,
            "groups": [f["group"] for f in findings],
            "findings": findings,
        }
        try:
            docs = explain_for(key, groups=[f["group"] for f in findings])
            if docs is not None:
                flap_info["explanations"] = docs
        except Exception as e:  # noqa: BLE001 - the dump still lands
            flap_info["explanations_error"] = str(e)
        flightrecorder.dump_on_incident("flap", extra={"flap": flap_info})

    # -- surfacing ---------------------------------------------------------
    def top_flapping(self, k: int = 5) -> List[Dict[str, Any]]:
        with self._lock:
            items = sorted(self._totals.items(), key=lambda kv: -kv[1])[:k]
        return [{"key": key, "group": g, "flaps": n}
                for (key, g), n in items]

    def drain(self, timeout: float = 10.0) -> None:
        with self._lock:
            worker = self._worker
        if worker is not None:
            worker.join(timeout)

    def reset(self) -> None:
        with self._lock:
            self._last_dump_mono.clear()
            self._last_flap.clear()
            self._totals.clear()
            self.recent.clear()
            self.flaps = 0
            self.dumps = 0


HISTORY = DecisionHistory()
FLAPS = FlapWatchdog()


# ---------------------------------------------------------------------------
# The decide-path feed (staged on the timeline, drained by the hook)
# ---------------------------------------------------------------------------


def stage(key: str, status, nodes_delta, tick: Optional[int] = None) -> None:
    """Stage one decision's ``(status, nodes_delta)`` host columns for the
    history/flap feed. Decide paths call this where the columns are ALREADY
    host numpy (the digest annotation / fleet unpack) — no extra device
    sync anywhere. The stash rides the current timeline's meta under a
    private key (never recorded) and the flight recorder's root-complete
    hook drains it after all timed phases closed; with no active timeline
    (raw library use) the record feeds through immediately."""
    from escalator_tpu.observability import spans

    entry = (str(key), status, nodes_delta, tick)
    tl = spans.current_timeline()
    if tl is None:
        _ingest([entry])
        return
    tl.meta.setdefault(_STASH, []).append(entry)


def _ingest(entries) -> None:
    import numpy as np

    for key, status, delta, tick in entries:
        status = np.asarray(status)
        delta = np.asarray(delta)
        tick, window = HISTORY.push(key, status, delta, tick=tick)
        # push cleared the ring on a shape change, so a predecessor in the
        # returned window is always shape-compatible
        prev_status = window[-2][1] if len(window) >= 2 else None
        # steady-state prefilter: a group can only START or CONTINUE an
        # oscillation on a tick that moves (nonzero delta) or changes
        # status — everything else skips the window scan entirely
        if not delta.any() and (
                prev_status is None
                or not (prev_status != status).any()):
            continue
        FLAPS.on_decisions(key, tick, window)


def on_timeline(tl) -> None:
    """The flight recorder's provenance feed (called from
    ``flightrecorder._on_root_complete``, isolated like every other
    consumer): drain the timeline's staged decisions into the history +
    flap watchdog. O(1) when nothing was staged."""
    staged = tl.meta.pop(_STASH, None)
    if staged:
        _ingest(staged)


# ---------------------------------------------------------------------------
# Explainer registry (live explanation providers: the fleet engine, a
# backend's decider) + dump/health surfacing
# ---------------------------------------------------------------------------

_explainers_lock = lockwitness.make_lock("provenance.explainers")
_explainers: Dict[str, Any] = {}   # key -> weakref.WeakMethod | callable


def register_explainer(key: str, fn: Callable) -> None:
    """Register a live explanation provider: ``fn(tenant_or_key, groups)``
    -> explanation doc list (or a dict with an "explanations" field). Bound
    methods are held weakly — a dead engine unregisters itself."""
    import weakref

    try:
        ref = weakref.WeakMethod(fn)   # type: ignore[arg-type]
    except TypeError:
        ref = fn                       # plain function: hold directly
    with _explainers_lock:
        _explainers[str(key)] = ref


def unregister_explainer(key: str) -> None:
    with _explainers_lock:
        _explainers.pop(str(key), None)


def _resolve_explainer(key: str):
    import weakref

    with _explainers_lock:
        candidates = [(k, r) for k, r in _explainers.items()
                      if k == key or k == "*"]
        # fleet tenants register under the engine's "*" wildcard
        dead = []
        resolved = None
        for k, ref in candidates:
            fn = ref() if isinstance(ref, weakref.WeakMethod) else ref
            if fn is None:
                dead.append(k)
            elif resolved is None or k == key:
                resolved = fn
        for k in dead:
            _explainers.pop(k, None)
    return resolved


def explain_for(key: str, groups: Optional[Sequence[int]] = None):
    """Live explanation documents for a history key (tenant id / root
    name) via the registered provider; None when no provider covers it."""
    fn = _resolve_explainer(str(key))
    if fn is None:
        return None
    doc = fn(str(key), groups)
    if isinstance(doc, dict):
        return doc.get("explanations", doc)
    return doc


def _breaching_keys(extra: Optional[Dict[str, Any]]) -> List[str]:
    """History keys named by an incident dump's extra sections: the tail
    watchdog's breaching root (``fleet/<tenant>`` roots name the tenant),
    an SLO escalation's tenant list, a flap's key."""
    keys: List[str] = []
    if not extra:
        return keys
    tail = extra.get("tail")
    if isinstance(tail, dict):
        root = str(tail.get("root") or "")
        if root.startswith("fleet/") and not root.startswith("fleet/class/"):
            keys.append(root.split("/", 1)[1])
        elif root:
            keys.append(root)
    slo = extra.get("slo")
    if isinstance(slo, dict):
        for t in slo.get("tenants", ()):
            keys.append(str(t))
    flap = extra.get("flap")
    if isinstance(flap, dict) and flap.get("key"):
        keys.append(str(flap["key"]))
    seen: Dict[str, None] = {}
    return [seen.setdefault(k, k) or k for k in keys if k not in seen]


def dump_section(extra: Optional[Dict[str, Any]] = None
                 ) -> Optional[Dict[str, Any]]:
    """The ``provenance`` section every flight dump carries: flap/mismatch
    state, the top flapping groups, recent decision history for the keys
    the incident names, and — when a live explainer covers a breaching
    tenant — its current explanations. Bounded and never raises (the
    caller isolates it anyway)."""
    keys = _breaching_keys(extra)
    sec: Dict[str, Any] = {
        "flaps_total": FLAPS.flaps,
        "flap_dumps": FLAPS.dumps,
        "explain_mismatches_total": mismatch_total(),
        "recent_flaps": list(FLAPS.recent)[-16:],
        "top_flapping": FLAPS.top_flapping(),
    }
    histories = {}
    explanations = {}
    for key in keys[:8]:
        h = HISTORY.history(key)
        if h:
            histories[key] = h[-DEFAULT_WINDOW:]
        if "flap" in (extra or {}) and extra["flap"].get("key") == key:
            continue   # the flap section already carries its explanations
        try:
            docs = explain_for(key)
        except Exception:  # noqa: BLE001 - a dump must never fail on extras
            docs = None
        if docs:
            explanations[key] = docs[:32]
    if histories:
        sec["history"] = histories
    if explanations:
        sec["explanations"] = explanations
    if not (sec["flaps_total"] or sec["explain_mismatches_total"]
            or histories or explanations):
        return None
    return sec


def health_section() -> Dict[str, Any]:
    """The plugin health doc's provenance row."""
    return {
        "history_keys": len(HISTORY.keys()),
        "history_depth": HISTORY.depth,
        "flaps_total": FLAPS.flaps,
        "flap_dumps": FLAPS.dumps,
        "explain_mismatches_total": mismatch_total(),
        "top_flapping": FLAPS.top_flapping(),
    }


def reset() -> None:
    """Test support: forget all history/flap/mismatch state."""
    HISTORY.reset()
    FLAPS.reset()
    with _mismatch_lock:
        _mismatch_total[0] = 0
        _last_mismatch_dump_mono[0] = -float("inf")


# ---------------------------------------------------------------------------
# Decision-diff forensics (debug-decision-diff)
# ---------------------------------------------------------------------------

#: numeric terms attributed against config thresholds when a decision
#: changed between two explanations: (term, config key, relation)
_CROSSINGS = (
    ("max_percent", "cfg_taint_lower", "<"),
    ("max_percent", "cfg_taint_upper", "<"),
    ("max_percent", "cfg_scale_up_threshold", ">"),
    ("num_nodes", "cfg_min_nodes", "<"),
    ("num_nodes", "cfg_max_nodes", ">"),
    ("num_untainted", "cfg_min_nodes", "<"),
)


def _crossed(a_doc: Dict[str, Any], b_doc: Dict[str, Any]) -> List[str]:
    """Human-readable per-term attributions: which monitored term crossed
    which configured threshold between explanation A and explanation B."""
    notes = []
    for term, cfg, rel in _CROSSINGS:
        av = a_doc["terms"].get(term)
        bv = b_doc["terms"].get(term)
        ac = a_doc["config"].get(cfg)
        bc = b_doc["config"].get(cfg)
        if av is None or bv is None or ac is None or bc is None:
            continue
        if ac != bc:
            # two crossing rules may watch the same config key (min_nodes
            # guards both num_nodes and num_untainted) — note it once
            note = f"{cfg} changed {ac} -> {bc}"
            if note not in notes:
                notes.append(note)
            continue
        was = (av < ac) if rel == "<" else (av > ac)
        now = (bv < bc) if rel == "<" else (bv > bc)
        if was != now:
            notes.append(
                f"{term} crossed {cfg.removeprefix('cfg_')} "
                f"({av} -> {bv}, threshold {ac})")
    if a_doc["threshold_branch"] != b_doc["threshold_branch"]:
        notes.append(
            f"threshold branch {a_doc['threshold_branch']} -> "
            f"{b_doc['threshold_branch']}")
    if a_doc["status_branch"] != b_doc["status_branch"]:
        notes.append(
            f"status branch {a_doc['status_branch']} -> "
            f"{b_doc['status_branch']}")
    for gate in _GATE_KEYS:
        ga, gb = a_doc["gates"].get(gate), b_doc["gates"].get(gate)
        if ga is not None and gb is not None and ga != gb:
            notes.append(f"{gate} {ga} -> {gb}")
    return notes


def diff_explanations(a: List[Dict[str, Any]], b: List[Dict[str, Any]]
                      ) -> Dict[str, Any]:
    """Group-by-group decision diff between two explanation lists (two
    dumps, two replay ticks): for every group whose committed decision
    changed, the per-term attribution — which terms moved, which crossed a
    configured threshold ("Δ changed because mem_percent crossed
    taint_upper"). Groups only in one side are reported as added/removed."""
    a_by = {d["group"]: d for d in a}
    b_by = {d["group"]: d for d in b}
    changed = []
    unchanged = 0
    for g in sorted(set(a_by) & set(b_by)):
        da, db = a_by[g], b_by[g]
        if (da["status"], da["nodes_delta"]) == (
                db["status"], db["nodes_delta"]):
            unchanged += 1
            continue
        term_deltas = {}
        for k in sorted(set(da["terms"]) & set(db["terms"])):
            if da["terms"][k] != db["terms"][k]:
                term_deltas[k] = [da["terms"][k], db["terms"][k]]
        changed.append({
            "group": g,
            "status": [da["status_name"], db["status_name"]],
            "nodes_delta": [da["nodes_delta"], db["nodes_delta"]],
            "threshold_branch": [da["threshold_branch"],
                                 db["threshold_branch"]],
            "attribution": _crossed(da, db),
            "term_deltas": term_deltas,
        })
    return {
        "changed": changed,
        "unchanged_groups": unchanged,
        "only_in_a": sorted(set(a_by) - set(b_by)),
        "only_in_b": sorted(set(b_by) - set(a_by)),
    }
