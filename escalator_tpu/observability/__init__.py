"""Observability: tick span timelines, the flight recorder, jax.monitoring
counters. See docs/observability.md for the operator view.

Importing this package wires the flight recorder into the span layer; the
import itself is cheap (stdlib + prometheus metrics — **no jax**), so every
backend imports it unconditionally. jax.monitoring subscription happens
lazily at the first tick of a process that already loaded jax.
"""

from escalator_tpu.observability import (
    flightrecorder,
    histograms,
    jaxmon,
    journal,
    provenance,
    resources,
    spans,
    tail,
)
from escalator_tpu.observability.flightrecorder import (
    RECORDER,
    dump_on_incident,
)
from escalator_tpu.observability.spans import (
    add_phase,
    annotate,
    current_path,
    current_timeline,
    enabled,
    fence,
    graft,
    set_enabled,
    span,
)

flightrecorder.install()

__all__ = [
    "RECORDER", "add_phase", "annotate", "current_path", "current_timeline",
    "dump_on_incident", "enabled", "fence", "flightrecorder", "graft",
    "histograms", "jaxmon", "journal", "provenance", "resources",
    "set_enabled", "span", "spans", "tail",
]
