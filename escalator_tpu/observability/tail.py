"""Slow-tick deep capture: the tail watchdog.

The flight recorder answers "what were the last N ticks"; the histograms
(histograms.py) answer "what does the tail look like". This module closes
the loop: when a completed root tick lands in its own series' tail — its
duration exceeds ``multiplier x`` the live rolling p99 — the flight
recorder auto-dumps with ``reason="tail"``, so every tail event arrives as
a self-contained "why was this tick slow" bundle: the full span tree of the
breaching tick (and the ticks before it), its compile/transfer deltas and
dirty-group count, and — when input recording is on
(``ESCALATOR_TPU_RECORD_INPUTS=1``) — the replay-ring slice covering it.
The dump document carries a ``tail`` section naming the breaching tick's
seq/root/duration and the p99+threshold it breached.

Knobs (all env; parsed per tick, memoized on the raw strings):

- ``ESCALATOR_TPU_TAIL_CAPTURE``: the breach multiplier (default ``4``;
  ``0``/``off`` disables capture entirely — the histograms keep streaming
  either way).
- ``ESCALATOR_TPU_TAIL_MIN_TICKS``: samples a root series needs before the
  watchdog arms (default 64 — a p99 over fewer ticks is mostly the max).
- ``ESCALATOR_TPU_TAIL_DUMP_INTERVAL_SEC``: rate limit between tail dumps
  (default 60; ``off`` disables the limit). A pathological workload where
  EVERY tick breaches must produce a trickle of bundles, not a
  dump-per-tick write storm. The limit is claimed PER ROOT FAMILY
  (round 17): ``fleet/<tenant>`` roots share one claim, ``fleet/class/…``
  another, and every other root (the tick loop, fleet_batch, bench roots)
  its own — a noisy per-tenant breach storm must not starve the tick
  loop's forensic dumps for the whole interval.

All three are strict-parsed (utils/envparse): 0/negative/non-numeric values
WARN once (per distinct raw value) and run the default instead of being
silently accepted — except the documented ``off``/``0`` disable spellings.
- ``ESCALATOR_TPU_TAIL_PROFILE=1`` (round 15, opt-in): a breach that wins
  the rate limit also arms a jax profiler capture of the next K ticks
  (``ESCALATOR_TPU_TAIL_PROFILE_TICKS``, default 4) into the dump
  directory — see observability/resources.py.

The breach check itself is O(buckets) (~5 µs) and runs in the root-complete
hook, after every timed phase closed. The dump is handed to a daemon worker
thread: serializing a 256-deep ring is milliseconds of JSON, and the
breaching tick's *successor* must not inherit that cost inside its own
timed window (the bench's p99 columns would otherwise report the
instrumentation, not the workload). Rate-limit state is claimed before the
handoff, so concurrent breaches collapse to one worker.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any, Dict, Optional, Tuple

from escalator_tpu.analysis import lockwitness
from escalator_tpu.observability import histograms

__all__ = ["TailWatchdog", "WATCHDOG", "parse_tail_capture"]

_ENV_MULT = "ESCALATOR_TPU_TAIL_CAPTURE"
_ENV_MIN = "ESCALATOR_TPU_TAIL_MIN_TICKS"
_ENV_INTERVAL = "ESCALATOR_TPU_TAIL_DUMP_INTERVAL_SEC"

DEFAULT_MULTIPLIER = 4.0
DEFAULT_MIN_TICKS = 64
DEFAULT_INTERVAL_SEC = 60.0
#: ticks between rolling-p99 recomputes per root series (see _p99_cache)
_P99_REFRESH = 16


def parse_tail_capture(raw: Optional[str]) -> Optional[float]:
    """Multiplier from the ESCALATOR_TPU_TAIL_CAPTURE spelling: unset/empty
    -> the default, "off"/"0" -> disabled (None), else a strict positive
    float multiplier (utils/envparse). A rejected value — junk, negative —
    disables with a one-time warning rather than crashing the tick path
    (fail-soft: this parses on the tick path, not at startup)."""
    from escalator_tpu.utils import envparse

    try:
        mult = envparse.parse_env_float(raw, _ENV_MULT, allow_off=True,
                                        zero_is_off=True)
    except ValueError as e:
        import logging

        logging.getLogger("escalator_tpu.observability").warning(
            "%s; tail capture disabled", e)
        return None
    if mult is None:
        return DEFAULT_MULTIPLIER
    return mult if mult > 0 else None


class TailWatchdog:
    """Per-process tail-breach detector (singleton :data:`WATCHDOG`)."""

    def __init__(self) -> None:
        self._lock = lockwitness.make_lock("tail.watchdog")
        #: rate-limit claims PER ROOT FAMILY (see _root_family): a breach
        #: storm on fleet/<tenant> roots must not starve tick-root dumps
        self._last_dump_mono: Dict[str, float] = {}
        self._worker: Optional[threading.Thread] = None
        #: (raw env tuple) -> parsed config, so steady-state ticks pay one
        #: dict lookup instead of three env parses
        self._cfg_cache: Tuple[Tuple[Optional[str], ...],
                               Tuple[Optional[float], int, float]] = (
            ("\0",), (None, 0, 0.0))
        #: root -> (histogram instance, count at compute time, p99 sec): the
        #: rolling p99 refreshes every _P99_REFRESH ticks per root instead
        #: of per tick — a quantile walk is ~10 µs and a p99 over hundreds
        #: of samples moves negligibly in 16 ticks, so the steady-state
        #: check stays ~1 µs (priced in cfg14_observability_overhead). The
        #: instance doubles as a generation token: histograms.reset()
        #: replaces the object, invalidating the cache even if the new
        #: series' count catches up to the cached one.
        self._p99_cache: Dict[str, Tuple[object, int, float]] = {}
        self.breaches = 0          # breaches observed (dumped or rate-limited)
        self.dumps = 0             # dumps actually handed to the worker

    # -- config ------------------------------------------------------------
    def _config(self) -> Tuple[Optional[float], int, float]:
        raw = (os.environ.get(_ENV_MULT), os.environ.get(_ENV_MIN),
               os.environ.get(_ENV_INTERVAL))
        cached_raw, cached = self._cfg_cache
        if raw == cached_raw:
            return cached
        # strict parses (round-17 satellite): a rejected value WARNS and
        # runs the default — the memoization on the raw strings makes the
        # warning once-per-distinct-value, and the tick path never crashes
        # on an operator typo
        import logging

        from escalator_tpu.utils import envparse

        warn = logging.getLogger("escalator_tpu.observability").warning
        mult = parse_tail_capture(raw[0])
        try:
            min_ticks = envparse.parse_env_int(raw[1], _ENV_MIN)
        except ValueError as e:
            warn("%s; using default %d", e, DEFAULT_MIN_TICKS)
            min_ticks = None
        try:
            interval = envparse.parse_env_float(raw[2], _ENV_INTERVAL,
                                                allow_off=True,
                                                allow_zero=True)
        except ValueError as e:
            warn("%s; using default %.0f", e, DEFAULT_INTERVAL_SEC)
            interval = None
        cfg = (mult,
               DEFAULT_MIN_TICKS if min_ticks is None else min_ticks,
               DEFAULT_INTERVAL_SEC if interval is None else interval)
        self._cfg_cache = (raw, cfg)
        return cfg

    @staticmethod
    def _root_family(root: str) -> str:
        """The rate-limit key: per-tenant and per-class fleet roots collapse
        to one family each (their cardinality scales with tenants — a
        per-root claim would defeat the limit), every other root name is its
        own family (the tick loop must never be starved by a fleet storm)."""
        if root.startswith("fleet/class/"):
            return "fleet/class"
        if root.startswith("fleet/"):
            return "fleet"
        return root

    # -- the hook ----------------------------------------------------------
    def on_record(self, rec: Dict[str, Any]) -> bool:
        """Called by the flight recorder for every completed root timeline,
        BEFORE the tick lands in its root histogram: the comparison
        population is the *prior* ticks — at realistic sample counts
        p99 ~= max, so a breach folded in first could never exceed its own
        p99. Returns True when a tail dump was scheduled (tests poll
        :meth:`drain`)."""
        mult, min_ticks, interval = self._config()
        if mult is None:
            return False
        root = str(rec.get("root") or "unknown")
        hist = histograms.TICKS.peek(root)
        if hist is None or hist.count < min_ticks:
            return False
        count = hist.count
        cached = self._p99_cache.get(root)
        if (cached is not None and cached[0] is hist
                and count - cached[1] < _P99_REFRESH):
            p99 = cached[2]
        else:
            p99 = hist.quantile(0.99)
            if p99 is None:
                return False
            self._p99_cache[root] = (hist, count, p99)
        duration_sec = float(rec.get("duration_ms", 0.0)) / 1e3
        threshold = mult * p99
        if duration_sec <= threshold:
            return False
        now = time.monotonic()
        family = self._root_family(root)
        with self._lock:
            self.breaches += 1
            rate_limited = (now - self._last_dump_mono.get(
                family, -float("inf")) < interval)
            if not rate_limited:
                self._last_dump_mono[family] = now  # claimed pre-handoff
                self.dumps += 1
        try:
            # every breach is a journal event — dumped or rate-limited —
            # so "when did the tail go bad" survives even when the dump
            # rate limit swallowed the artifact
            from escalator_tpu.observability import journal

            journal.JOURNAL.event(
                "tail-breach", root=root, seq=rec.get("seq"),
                duration_ms=rec.get("duration_ms"),
                p99_ms=round(p99 * 1e3, 4), multiplier=mult,
                dumped=not rate_limited)
        except Exception:  # noqa: BLE001 - never break the tick
            pass
        if rate_limited:
            return False
        tail_info = {
            "seq": rec.get("seq"),
            "root": root,
            "backend": rec.get("backend"),
            "duration_ms": rec.get("duration_ms"),
            "p99_ms": round(p99 * 1e3, 4),
            "threshold_ms": round(threshold * 1e3, 4),
            "multiplier": mult,
            "tick_count": hist.count,
        }
        if os.environ.get("ESCALATOR_TPU_TAIL_PROFILE", "").lower() in (
                "1", "true", "yes"):
            # opt-in escalation (round 15): the first tail breach after
            # arming ALSO captures a jax profiler trace of the next K ticks
            # (the ticks most likely to share the breach's cause), so a
            # slow tick on a TPU campaign yields an on-chip profile with no
            # human in the loop. Rides the SAME rate-limit claim as the
            # dump — a breach storm produces a trickle of profiles, not a
            # profiler pile-up. Degrades to an "unsupported" note where the
            # platform lacks the profiler.
            try:
                from escalator_tpu.observability import flightrecorder, resources

                ticks = int(os.environ.get(
                    "ESCALATOR_TPU_TAIL_PROFILE_TICKS", "4"))
                out_dir = os.path.join(
                    flightrecorder.dump_dir(),
                    f"escalator-tpu-profile-tail-{os.getpid()}-"
                    f"{int(time.time())}")
                tail_info["profile"] = dict(
                    resources.PROFILER.start(ticks, out_dir))
            except Exception as e:  # noqa: BLE001 - never break the tick
                tail_info["profile"] = {"ok": False, "error": str(e)}
        worker = threading.Thread(
            target=self._dump, args=(tail_info,),
            name="escalator-tail-dump", daemon=True)
        with self._lock:
            self._worker = worker
        worker.start()
        return True

    @staticmethod
    def _dump(tail_info: Dict[str, Any]) -> None:
        # the worker serializes/writes; dump_on_incident never raises
        from escalator_tpu.observability import flightrecorder

        flightrecorder.dump_on_incident("tail", extra={"tail": tail_info})

    # -- test/bench support -------------------------------------------------
    def drain(self, timeout: float = 10.0) -> None:
        """Join the in-flight dump worker (tests assert on the artifact; the
        production path never waits)."""
        with self._lock:
            worker = self._worker
        if worker is not None:
            worker.join(timeout)

    def reset(self) -> None:
        with self._lock:
            self._last_dump_mono.clear()
            self._p99_cache.clear()
            self.breaches = 0
            self.dumps = 0


WATCHDOG = TailWatchdog()
