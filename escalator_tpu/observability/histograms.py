"""Streaming fixed-log-bucket latency histograms: the tail-latency substrate.

Every number this repo published before round 13 was a median — bench rows,
the coarse `tick_phase_seconds` Prometheus buckets, the Grafana panels — but
PAPER.md's target is a latency *SLO* (<50 ms scale decisions), and an SLO is
a tail statement. This module is the HdrHistogram-style (Gray/Tene) engine
that turns the span layer's per-phase durations into always-on quantiles:

- **Fixed log buckets.** Base-1.25 geometric buckets spanning 1 µs .. 10 s
  (73 buckets + underflow + overflow), so any quantile is exact to within
  one bucket width — a guaranteed <= 25% relative error at any magnitude,
  from a 10 µs pack phase to a 5 s compile-contaminated tick, with no
  a-priori knowledge of the distribution. `bench.py --smoke` proves the
  bound against ``np.percentile`` ground truth on adversarial distributions.
- **O(1) record.** One log, one clamp, one int64 increment under a lock
  (~1 µs; inside the instrumentation-overhead budget the PR-4 interleaved
  arms gate at < 1%). No allocation after construction.
- **Mergeable.** Bucket layout is a module constant, so histograms add
  counter-wise — per-backend series merge into the process root view the
  plugin ``health()`` tail fields report.

Zero dependencies (stdlib only), same deployment contract as spans.py: a
golden-only controller records its tail without importing jax or numpy.

Feeding happens in the flight recorder's root-complete hook
(flightrecorder.py): every completed timeline lands its leaf phases in
:data:`PHASES` keyed ``(backend, phase)`` and its root duration in
:data:`TICKS` keyed by root name — the same single channel that feeds the
ring and the Prometheus series, so quantiles, records and metrics can never
disagree about what a tick cost. Prometheus export (the fine-bucket
``escalator_tpu_tick_phase_hist_seconds`` / ``escalator_tpu_tick_e2e_seconds``
native histograms) is a pull-time collector in metrics/metrics.py.
"""

from __future__ import annotations

import math
from array import array
from typing import Dict, Iterator, List, Optional, Tuple

from escalator_tpu.analysis import lockwitness

__all__ = [
    "BASE", "LO", "HI", "NUM_BUCKETS", "EDGES",
    "LogHistogram", "HistogramSet",
    "PHASES", "TICKS", "STAGES", "JOURNEY_STAGES", "tick_quantiles_ms",
    "reset",
]

#: bucket growth factor: consecutive bucket bounds differ by 25%, which is
#: the worst-case relative quantile error (one bucket width)
BASE = 1.25
#: smallest resolvable duration (1 µs): everything below lands in the
#: underflow bucket, reported as LO
LO = 1e-6
#: top of the resolvable range (10 s): a wedged tick beyond it lands in the
#: overflow bucket, reported as HI (the wedge watchdog owns anything slower)
HI = 10.0

_LOG_BASE = math.log(BASE)
#: bucket i (0-based, after the underflow slot) covers [EDGES[i], EDGES[i+1])
NUM_BUCKETS = int(math.ceil(math.log(HI / LO) / _LOG_BASE))          # 73
EDGES: Tuple[float, ...] = tuple(
    LO * BASE ** i for i in range(NUM_BUCKETS)) + (HI,)

#: upper-bound labels, precomputed once (cumulative_buckets emits the full
#: fixed layout on every scrape — formatting 73 floats per series per scrape
#: would dominate the collector otherwise)
_EDGE_LABELS: Tuple[str, ...] = tuple(
    f"{e:.9g}" for e in EDGES[1:])

#: counts layout: [underflow] + NUM_BUCKETS regular + [overflow]
_UNDER = 0
_FIRST = 1
_OVER = NUM_BUCKETS + 1
_SLOTS = NUM_BUCKETS + 2


def bucket_index(seconds: float) -> int:
    """Slot index for a duration (O(1)): log-estimate plus a one-step
    correction for float rounding at bucket boundaries (the estimate can be
    off by one when ``seconds`` sits exactly on an edge; the correction makes
    boundary placement exact — locked by tests/test_tail_latency.py)."""
    if seconds < LO:
        return _UNDER
    if seconds >= HI:
        return _OVER
    i = int(math.log(seconds / LO) / _LOG_BASE)
    if i >= NUM_BUCKETS:
        i = NUM_BUCKETS - 1
    # correct the float estimate (at most one step either way)
    if seconds < EDGES[i]:
        i -= 1
    elif i + 1 < NUM_BUCKETS and seconds >= EDGES[i + 1]:
        i += 1
    return _FIRST + i


def bucket_bounds(seconds: float) -> Tuple[float, float]:
    """(lower, upper) edge of the bucket a duration lands in — the "one
    bucket width" the accuracy contract is stated against. Underflow reports
    (0, LO); overflow (HI, HI)."""
    slot = bucket_index(seconds)
    if slot == _UNDER:
        return 0.0, LO
    if slot == _OVER:
        return HI, HI
    i = slot - _FIRST
    return EDGES[i], EDGES[i + 1]


class LogHistogram:
    """One streaming latency series: int64 bucket counts + running sum.

    Thread-safe (`record` from tick threads, `snapshot`/`quantile` from
    scrape/health threads); the lock guards a handful of int ops, so a
    record is ~1 µs.
    """

    __slots__ = ("_counts", "_count", "_sum", "_max", "_min", "_lock")

    def __init__(self) -> None:
        self._counts = array("q", [0]) * _SLOTS
        self._count = 0
        self._sum = 0.0
        self._max = 0.0
        self._min = math.inf
        self._lock = lockwitness.make_lock("histograms.series")

    # -- writing -----------------------------------------------------------
    def record(self, seconds: float) -> None:
        slot = bucket_index(seconds)
        with self._lock:
            self._counts[slot] += 1
            self._count += 1
            self._sum += seconds
            if seconds > self._max:
                self._max = seconds
            if seconds < self._min:
                self._min = seconds

    def merge(self, other: "LogHistogram") -> None:
        """Counter-wise add (bucket layout is a module constant, so merges
        are exact — the per-backend tick series sum into the process root
        view without re-sampling)."""
        with other._lock:
            counts = array("q", other._counts)
            count, total = other._count, other._sum
            mx, mn = other._max, other._min
        with self._lock:
            for i, c in enumerate(counts):
                self._counts[i] += c
            self._count += count
            self._sum += total
            if mx > self._max:
                self._max = mx
            if mn < self._min:
                self._min = mn

    # -- reading -----------------------------------------------------------
    @property
    def count(self) -> int:
        return self._count

    @property
    def sum_seconds(self) -> float:
        return self._sum

    @property
    def max_seconds(self) -> float:
        return self._max

    @property
    def min_seconds(self) -> float:
        return self._min if self._count else 0.0

    def quantile(self, q: float) -> Optional[float]:
        """The q-quantile (q in [0, 1]) with linear interpolation inside the
        landing bucket — always within one bucket width of the exact order
        statistic. None on an empty histogram. Underflow reports LO's lower
        neighborhood as LO/2; overflow clamps to HI (anything out there is
        the wedge watchdog's jurisdiction, not a quantile's)."""
        with self._lock:
            counts = array("q", self._counts)
            total = self._count
        if total == 0:
            return None
        q = min(max(q, 0.0), 1.0)
        target = q * total
        cum = 0
        for slot, c in enumerate(counts):
            if c == 0:
                continue
            if cum + c >= target:
                if slot == _UNDER:
                    return LO / 2
                if slot == _OVER:
                    return HI
                lo, hi = EDGES[slot - _FIRST], EDGES[slot - _FIRST + 1]
                frac = (target - cum) / c if c else 0.0
                return lo + (hi - lo) * min(max(frac, 0.0), 1.0)
            cum += c
        return HI  # unreachable with consistent counts; defensive

    def quantiles(self) -> Dict[str, Optional[float]]:
        """The published accessor set: exact-to-one-bucket p50/p90/p99/p999
        plus count/min/max (None quantiles on an empty series)."""
        return {
            "count": self._count,
            "p50": self.quantile(0.50),
            "p90": self.quantile(0.90),
            "p99": self.quantile(0.99),
            "p999": self.quantile(0.999),
            "min": self.min_seconds if self._count else None,
            "max": self._max if self._count else None,
        }

    def cumulative_buckets(self) -> List[Tuple[str, int]]:
        """Prometheus-histogram form: (upper-bound-label, cumulative count)
        for EVERY bucket edge plus +Inf. The full fixed layout is emitted
        even where empty: `sum by (le)` quantile queries (the shipped
        Grafana panels) require every series to expose the same `le` set —
        a truncated-series sum is non-monotonic in `le` and
        histogram_quantile returns garbage — and `rate()` needs each `le`
        series to exist continuously over time."""
        with self._lock:
            counts = array("q", self._counts)
            total = self._count
        out: List[Tuple[str, int]] = []
        cum = counts[_UNDER]
        for i in range(NUM_BUCKETS):
            cum += counts[_FIRST + i]
            out.append((_EDGE_LABELS[i], cum))
        out.append(("+Inf", total))
        return out


class HistogramSet:
    """Label-keyed LogHistogram registry (process-global instances below).

    ``get`` allocates on first touch; the dict is tiny (backends x phase
    names), so a snapshot is a cheap copy under the lock.
    """

    def __init__(self) -> None:
        self._lock = lockwitness.make_lock("histograms.set")
        self._hists: Dict[Tuple[str, ...], LogHistogram] = {}

    def get(self, *key: str) -> LogHistogram:
        with self._lock:
            h = self._hists.get(key)
            if h is None:
                h = self._hists[key] = LogHistogram()
            return h

    def observe(self, key: Tuple[str, ...], seconds: float) -> None:
        self.get(*key).record(seconds)

    def peek(self, *key: str) -> Optional[LogHistogram]:
        with self._lock:
            return self._hists.get(key)

    def items(self) -> Iterator[Tuple[Tuple[str, ...], LogHistogram]]:
        with self._lock:
            snap = list(self._hists.items())
        return iter(snap)

    def merged(self) -> LogHistogram:
        out = LogHistogram()
        for _, h in self.items():
            out.merge(h)
        return out

    def discard(self, *key: str) -> None:
        """Drop one series (no-op when absent) — the fleet scheduler
        retires a tenant's ``fleet/<tenant>`` root on evict so per-tenant
        series cardinality tracks RESIDENT tenants, not every id ever
        seen."""
        with self._lock:
            self._hists.pop(key, None)

    def clear(self) -> None:
        with self._lock:
            self._hists.clear()


#: leaf-phase series keyed (backend, phase) — same leaf-only/remote-skip
#: selection as the Prometheus feed (see flightrecorder._on_root_complete)
PHASES = HistogramSet()
#: root end-to-end series keyed by root timeline name ("tick" for the
#: controller loop; standalone backend/bench roots keep their own series so
#: the tail watchdog always compares a tick against its own population)
TICKS = HistogramSet()
#: THE canonical journey stage set, in pipeline order — the scheduler
#: records them, the trace exporter lays them out, the plugin ships them,
#: bench asserts on them; everyone imports THIS tuple (hand-copies drift:
#: a sixth stage added in one place would silently never render elsewhere)
JOURNEY_STAGES = ("admission", "batch_assembly", "dispatch", "ordered_tail",
                  "unpack", "cached")

#: fleet request-journey stage series keyed (class, stage) — fed from the
#: scheduler's respond-side journey bookkeeping (round 17), NOT from the
#: span layer: stages are per-REQUEST slices of the pipeline (admission /
#: batch_assembly / dispatch / ordered_tail / unpack, plus the derived
#: "service" = everything after queue wait that the health probe's
#: queue-wait-vs-service split reads). Exported as
#: ``escalator_tpu_fleet_stage_seconds{klass,stage}`` by the same pull-time
#: collector as PHASES/TICKS.
STAGES = HistogramSet()


def tick_quantiles_ms(root: Optional[str] = None) -> Dict[str, Optional[float]]:
    """Quantiles of the root tick series in milliseconds — ``root=None``
    merges every root series (the process-wide view the plugin ``health()``
    tail fields ship). Quantile values are None when nothing recorded."""
    if root is None:
        h = TICKS.merged()
    else:
        h = TICKS.peek(root) or LogHistogram()
    out = h.quantiles()
    return {
        k: (round(v * 1e3, 4) if isinstance(v, float) else v)
        for k, v in out.items()
    }


def reset() -> None:
    """Drop every recorded series (test/bench isolation; production never
    calls this — the histograms are the process's lifetime tail memory)."""
    PHASES.clear()
    TICKS.clear()
    STAGES.clear()
