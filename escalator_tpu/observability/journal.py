"""Ops event journal: a bounded ring of discrete, structured operator events.

The flight recorder answers "what did the last N ticks COST"; the histograms
answer "what does the tail look like". Neither answers "what HAPPENED around
tick N" — a tenant eviction, an arena grow, a StaleBatchError, an admission
reject storm, a chaos firing, an SLO burn — without grepping logs across
threads and processes. This module is the discrete-event sibling of the tick
ring: every noteworthy state change appends ONE structured event with a
monotonic sequence number, and the ring rides along in every flight dump, so
"what happened around that breach" is one artifact, not log archaeology.

Event sources wired in round 17 (grep ``JOURNAL.event`` to enumerate):

- fleet tenant lifecycle: register / evict / arena grow / arena compact /
  dispatch-failure rebuild / stale prepared batches
  (escalator_tpu/fleet/service.py),
- admission rejects with reason + class + tenant and per-class SLO
  breach / error-budget burn escalations (escalator_tpu/fleet/scheduler.py),
- incremental refresh-audit outcomes — mismatches and audit-worker deaths
  (ops/device_state.py),
- chaos-site firings (escalator_tpu/chaos.py),
- tail-latency and memory-growth watchdog breaches
  (observability/tail.py, observability/resources.py).

Design contract (same family as spans.py / histograms.py):

- **Zero dependencies**, stdlib only; importable from a golden-only process.
- **Never raises into the caller**: an observability failure must not become
  a second incident. Field values are sanitized to JSON/msgpack-safe
  scalars at append time (anything else is ``str()``-ed).
- **Cheap**: one dict build + deque append under a lock (~1 µs); emitters
  sit on state-CHANGE paths (registers, rejects, breaches), never on the
  per-tick or per-request steady path.
- **Bounded**: ``ESCALATOR_TPU_JOURNAL_SIZE`` (default 512) events; the
  sequence number keeps counting, so a reader can tell "ring wrapped"
  (first event's seq > 1) from "nothing happened".

Readers: ``FlightRecorder.as_dump`` embeds the ring under ``"journal"``;
``escalator-tpu debug-journal`` prints it from a dump file or a live plugin
(the ``Journal`` RPC); the plugin serves it raw over msgpack.
"""

from __future__ import annotations

import collections
import os
import time
from typing import Any, Dict, List, Optional

from escalator_tpu.analysis import lockwitness

__all__ = ["OpsJournal", "JOURNAL"]

DEFAULT_CAPACITY = 512


def _capacity_from_env() -> int:
    from escalator_tpu.utils import envparse

    raw = os.environ.get("ESCALATOR_TPU_JOURNAL_SIZE")
    try:
        parsed = envparse.parse_env_int(raw, "ESCALATOR_TPU_JOURNAL_SIZE",
                                        minimum=16)
    except ValueError as e:
        import logging

        logging.getLogger("escalator_tpu.observability").warning(
            "%s; using default %d", e, DEFAULT_CAPACITY)
        parsed = None
    return DEFAULT_CAPACITY if parsed is None else parsed


def _sanitize(value: Any) -> Any:
    """JSON/msgpack-safe scalars only: events end up in flight dumps
    (json.dump, no default=) and Journal RPC responses (msgpack.packb) —
    one exotic field value must not fail a whole dump."""
    if value is None or isinstance(value, (bool, int, float, str)):
        return value
    if isinstance(value, (list, tuple)):
        return [_sanitize(v) for v in value]
    if isinstance(value, dict):
        return {str(k): _sanitize(v) for k, v in value.items()}
    return str(value)


class OpsJournal:
    """Bounded, thread-safe ring of structured ops events (singleton
    :data:`JOURNAL`)."""

    def __init__(self, capacity: Optional[int] = None):
        self.capacity = int(capacity) if capacity else _capacity_from_env()
        self._ring: "collections.deque[Dict[str, Any]]" = collections.deque(
            maxlen=self.capacity)
        self._seq = 0
        self._lock = lockwitness.make_lock("journal.ring")

    # -- writing -----------------------------------------------------------
    def event(self, kind: str, **fields: Any) -> Optional[Dict[str, Any]]:
        """Append one event. Returns the stored dict, or None when the
        append failed (this method NEVER raises — emitters sit on incident
        and lifecycle paths where a secondary failure is unaffordable)."""
        try:
            ev: Dict[str, Any] = {
                "kind": str(kind),
                "time_unix": round(time.time(), 3),
            }
            for k, v in fields.items():
                if v is not None:
                    ev[k] = _sanitize(v)
            with self._lock:
                self._seq += 1
                ev["seq"] = self._seq
                self._ring.append(ev)
            return ev
        except Exception:  # noqa: BLE001 - observability must never break callers
            return None

    # -- reading -----------------------------------------------------------
    @property
    def depth(self) -> int:
        with self._lock:
            return len(self._ring)

    @property
    def total_recorded(self) -> int:
        with self._lock:
            return self._seq

    def snapshot(self, since_seq: int = 0,
                 kinds: Optional[List[str]] = None) -> List[Dict[str, Any]]:
        """Events with ``seq > since_seq`` (all by default), optionally
        filtered to a kind set, oldest first."""
        with self._lock:
            events = list(self._ring)
        if since_seq:
            events = [e for e in events if e["seq"] > since_seq]
        if kinds:
            wanted = set(kinds)
            events = [e for e in events if e["kind"] in wanted]
        return events

    def as_doc(self, since_seq: int = 0) -> Dict[str, Any]:
        """The wire/dump form: events + ring metadata (a reader can tell a
        wrapped ring — ``events[0].seq > 1`` — from a quiet one)."""
        events = self.snapshot(since_seq=since_seq)
        return {
            "capacity": self.capacity,
            "total_recorded": self.total_recorded,
            "events": events,
        }

    def clear(self) -> None:
        """Test isolation only (the seq counter keeps counting — sequence
        numbers stay monotonic across clears, like the recorder's)."""
        with self._lock:
            self._ring.clear()


#: the process-wide journal every emitter appends to
JOURNAL = OpsJournal()
