"""Flight-recorder ring -> Chrome trace-event / Perfetto JSON.

A flight dump is exact but hard to *read*: a tail bundle's span tree is a
flat phase list with slash paths. This module renders any dump document (or
the live ring) to the trace-event format Perfetto (https://ui.perfetto.dev)
and ``chrome://tracing`` open natively — the same format the archived TPU
device traces in ``tpu_traces/`` use — so a human can scrub a slow tick.

Layout decisions:

- every phase is a complete ("X") duration event; nesting is by time
  containment, which the span layer's offsets guarantee for fenced phases;
- **unfenced device phases get their own track** ("overlap"): an overlapped
  dispatch's span measured enqueue time while the device program ran past
  the span's close — drawing it nested would misrepresent containment, so
  it sits on a parallel track flagged ``fenced=false`` (read it with the
  record's ``overlap_*`` keys, per docs/observability.md);
- **grafted plugin-server spans get their own track** ("plugin server") and
  are re-anchored in time under the local ``rpc`` span that carried them
  (their offsets are remote-root-relative — see ``spans.graft``), so one
  trace shows client and server of a plugin-routed decide together;
- phases recorded without an offset (``spans.add_phase`` accumulations)
  are laid out cursor-sequentially from their parent's start — positions
  are then best-effort, durations exact;
- **request journeys get a per-request track family** (round 17): a
  ``fleet_batch`` record carrying ``journeys`` (the scheduler's respond-side
  per-request stage decomposition) renders one track per tenant — a parent
  ``req <tenant>`` slice spanning enqueue→respond with the five stage
  slices (admission / batch_assembly / dispatch / ordered_tail / unpack)
  laid contiguously inside it, positioned in record time via the record's
  ``journey_mono_t0`` clock anchor. The dispatch stage therefore lines up
  under the fleet_batch slice's ``fleet_step`` span it rode, and a tenant's
  queue wait is visibly the gap BEFORE the batch opened.

``escalator-tpu debug-trace`` (cli.py) is the operator entry: a dump file
or a live plugin's ``Dump`` RPC in, a ``.trace.json`` out.
"""

from __future__ import annotations

from typing import Any, Dict, List

__all__ = ["trace_from_dump", "trace_from_records", "TID_TICK",
           "TID_OVERLAP", "TID_REMOTE", "TID_JOURNEY_BASE",
           "JOURNEY_STAGE_ORDER"]

TID_TICK = 1      # fenced / host / rpc phases: the tick's main track
TID_OVERLAP = 2   # unfenced device dispatches (overlap windows)
TID_REMOTE = 3    # grafted plugin-server phases
#: per-request journey tracks allocate upward from here, one per tenant
#: (stable across the records of one trace)
TID_JOURNEY_BASE = 32

#: the canonical journey stage order (histograms.py is stdlib-only, so
#: this module stays dependency-free); contiguous by construction, so
#: cumulative layout from the enqueue anchor is exact
from escalator_tpu.observability.histograms import (  # noqa: E402
    JOURNEY_STAGES as JOURNEY_STAGE_ORDER,
)

_THREAD_NAMES = {
    TID_TICK: "tick",
    TID_OVERLAP: "overlap (unfenced dispatch)",
    TID_REMOTE: "plugin server (grafted)",
}

#: record keys lifted into the root event's args (the "why" annotations a
#: human wants on the tick slice itself)
_ROOT_ARG_KEYS = (
    "backend", "impl", "ordered", "digest", "dirty_groups", "refresh_audit",
    "store", "order_path", "order_dirty_lanes", "compile_events",
    "compile_seconds", "transfer_events", "overlap_host_ms",
    "overlap_sync_wait_ms", "overlap_saved_ms", "fallback", "fallback_code",
    "chaos", "restored", "seq",
)


def _tid_for(phase: Dict[str, Any]) -> int:
    if phase.get("remote"):
        return TID_REMOTE
    if not phase.get("fenced", True) and phase.get("kind") == "device":
        return TID_OVERLAP
    return TID_TICK


def _journey_events(rec: Dict[str, Any], pid: int,
                    journey_tids: Dict[str, int]) -> List[Dict[str, Any]]:
    """Per-request journey slices for one record (empty when the record
    carries no journeys or no clock anchor). ``journey_tids`` is shared
    across the trace so a tenant keeps ONE track; newly-allocated tracks
    emit their thread_name metadata inline."""
    journeys = rec.get("journeys") or ()
    mono0 = rec.get("journey_mono_t0")
    if not journeys or mono0 is None:
        return []
    base_us = float(rec.get("time_unix", 0.0)) * 1e6
    events: List[Dict[str, Any]] = []
    for j in journeys:
        try:
            tenant = str(j.get("tenant", "?"))
            tid = journey_tids.get(tenant)
            if tid is None:
                tid = TID_JOURNEY_BASE + len(journey_tids)
                journey_tids[tenant] = tid
                events.append({
                    "name": "thread_name", "ph": "M", "pid": pid,
                    "tid": tid, "args": {"name": f"journey {tenant}"},
                })
            t_enq = base_us + (float(j["enqueued_mono"])
                               - float(mono0)) * 1e6
            e2e_us = float(j.get("e2e_ms", 0.0)) * 1e3
            events.append({
                "name": f"req {tenant} [{j.get('klass', '?')}]",
                "cat": "journey", "ph": "X",
                "ts": round(t_enq, 3), "dur": round(e2e_us, 3),
                "pid": pid, "tid": tid,
                "args": {
                    "path": f"journey/{tenant}",
                    "fenced": True,
                    "klass": j.get("klass"),
                    "deferrals": j.get("deferrals"),
                    "e2e_ms": j.get("e2e_ms"),
                    "fleet_batch_seq": rec.get("seq"),
                },
            })
            stages = j.get("stages_ms") or {}
            cursor = t_enq
            for stage in JOURNEY_STAGE_ORDER:
                if stage not in stages:
                    continue   # unrecorded stage ("cached" on miss paths)
                dur_us = float(stages.get(stage, 0.0)) * 1e3
                if stage == "ordered_tail" and dur_us <= 0:
                    continue   # most tenants never sort: keep tracks clean
                events.append({
                    "name": stage,
                    "cat": "device" if stage == "dispatch" else "journey",
                    "ph": "X",
                    "ts": round(cursor, 3), "dur": round(max(dur_us, 0), 3),
                    "pid": pid, "tid": tid,
                    "args": {"path": f"journey/{tenant}/{stage}",
                             "fenced": True},
                })
                cursor += max(dur_us, 0)
        except Exception:  # noqa: BLE001 - a malformed journey is dropped
            continue
    return events


def _record_events(rec: Dict[str, Any], pid: int) -> List[Dict[str, Any]]:
    base_us = float(rec.get("time_unix", 0.0)) * 1e6
    phases: List[Dict[str, Any]] = list(rec.get("phases") or ())
    root = str(rec.get("root", ""))

    # pass 1: absolute start (µs) of every offset-carrying LOCAL phase,
    # keyed by path (first occurrence wins — the anchor for children)
    starts: Dict[str, float] = {}
    for p in phases:
        off = p.get("offset_ms")
        if off is None or p.get("remote"):
            continue
        starts.setdefault(str(p["path"]), base_us + float(off) * 1e3)

    def _anchor(path: str) -> float:
        """Start of the longest local strict path prefix (the enclosing
        span), falling back to the record base."""
        probe = path
        while "/" in probe:
            probe = probe.rsplit("/", 1)[0]
            if probe in starts:
                return starts[probe]
        return base_us

    # pass 2: events; offsetless phases advance a per-parent cursor
    cursors: Dict[str, float] = {}
    events: List[Dict[str, Any]] = []
    for p in phases:
        path = str(p.get("path") or p.get("name") or "phase")
        dur_us = float(p.get("ms", 0.0)) * 1e3
        off = p.get("offset_ms")
        exact = off is not None
        if p.get("remote"):
            anchor = _anchor(path)
            if exact:
                ts = anchor + float(off) * 1e3
            else:
                ts = cursors.get(path.rsplit("/", 1)[0], anchor)
                cursors[path.rsplit("/", 1)[0]] = ts + dur_us
        elif exact:
            ts = base_us + float(off) * 1e3
        else:
            parent = path.rsplit("/", 1)[0] if "/" in path else ""
            ts = cursors.get(parent, starts.get(parent, base_us))
            cursors[parent] = ts + dur_us
        args: Dict[str, Any] = {
            "path": path,
            "fenced": bool(p.get("fenced", True)),
        }
        if p.get("remote"):
            args["remote"] = True
        if not exact:
            args["layout"] = "cursor (no recorded offset)"
        if path == root:
            for k in _ROOT_ARG_KEYS:
                if rec.get(k) is not None:
                    args[k] = rec[k]
        events.append({
            "name": str(p.get("name") or path.rsplit("/", 1)[-1]),
            "cat": str(p.get("kind", "host")),
            "ph": "X",
            "ts": round(ts, 3),
            "dur": round(dur_us, 3),
            "pid": pid,
            "tid": _tid_for(p),
            "args": args,
        })
    return events


def trace_from_records(records: List[Dict[str, Any]], pid: int = 1,
                       process_name: str = "escalator-tpu") -> Dict[str, Any]:
    """Trace document from raw tick records (the live ring's snapshot)."""
    events: List[Dict[str, Any]] = [{
        "name": "process_name", "ph": "M", "pid": pid, "tid": 0,
        "args": {"name": process_name},
    }]
    for tid, tname in _THREAD_NAMES.items():
        events.append({
            "name": "thread_name", "ph": "M", "pid": pid, "tid": tid,
            "args": {"name": tname},
        })
    journey_tids: Dict[str, int] = {}
    for rec in records:
        events.extend(_record_events(rec, pid))
        events.extend(_journey_events(rec, pid, journey_tids))
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def trace_from_dump(doc: Dict[str, Any]) -> Dict[str, Any]:
    """Trace document from a flight dump (``FlightRecorder.as_dump`` /
    ``debug-dump`` output). The dump's reason/pid/tail annotations ride
    along under ``otherData`` so the provenance stays inside the trace."""
    pid = int(doc.get("pid") or 1)
    out = trace_from_records(
        list(doc.get("ticks") or ()), pid=pid,
        process_name=f"escalator-tpu (dump: {doc.get('reason', '?')})")
    other: Dict[str, Any] = {
        "reason": doc.get("reason"),
        "dumped_at_unix": doc.get("dumped_at_unix"),
        "total_recorded": doc.get("total_recorded"),
    }
    if doc.get("tail") is not None:
        other["tail"] = doc["tail"]
    if doc.get("tick_quantiles_ms") is not None:
        other["tick_quantiles_ms"] = doc["tick_quantiles_ms"]
    out["otherData"] = other
    return out


def live_trace() -> Dict[str, Any]:
    """Trace of THIS process's live ring (no dump file round-trip)."""
    from escalator_tpu.observability.flightrecorder import RECORDER

    return trace_from_dump(RECORDER.as_dump("live-trace"))
