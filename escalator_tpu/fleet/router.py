"""Partition router: horizontal scale-out for the fleet decision service.

One fleet process is host-bound on the rig (PR 11/13 recorder columns:
``host_diff`` + ``batch_assembly`` ≈ ``fleet_step``) — every host-side win
so far still funnels through a single Python process and one GIL. This
module is the scale-out answer (round 20, ROADMAP item 4): N live plugin
partitions each own a tenant shard, fronted by a thin client-side router.

- **Routing**: tenants map to partitions by consistent hash (blake2b points
  on a ring, ``replicas`` virtual nodes per partition) with an explicit
  override map layered on top. Adding/removing a partition moves only the
  keys whose arc changed (test-locked); overrides pin migrated/re-homed
  tenants wherever the ring says otherwise.
- **Forwarding**: decide frames pass through UNCHANGED — the
  ``__tenant__``/``__delta__`` sidecar wire format is partition-agnostic,
  so the router is a connection picker, not a proxy: it hands the tenant's
  home :class:`~escalator_tpu.plugin.client.ComputeClient` to the caller's
  :class:`~escalator_tpu.plugin.client.FleetStreamSession` and rebinds the
  session when the tenant moves.
- **Migration** (warm): ``migrate_tenant`` drives the row-snapshot protocol
  end to end — quiesce+freeze on the source (``TenantSnapshot``), evict,
  adopt on the target (``TenantAdopt``) — emitting the journal sequence
  ``migration-start → migration-row-snapshot → migration-evict →
  migration-adopt → migration-complete``. Routed decides for the moving
  tenant HOLD (bounded) during the window; every other tenant keeps
  flowing. The first post-migration decide folds everything since into one
  delta batch (the PR-6 killed-leader warm start — see
  ``FleetStreamSession.rebind``).
- **Failover**: per-partition circuit breaking on the existing
  consecutive-failure model (``GrpcBackend``'s breaker, applied per
  partition). When a partition's breaker opens, ``fail_over`` re-homes
  every tenant it owned onto the survivors from the ROLLING CHECKPOINT
  (``checkpoint_tenants`` parks each tenant's row blob in
  ``checkpoint_dir``), with per-tenant digest continuity wherever a
  checkpoint exists and a full-frame cold resync where none does.
- **Aggregation**: ``health()`` / ``journal()`` / ``explain()`` fan out and
  merge across partitions, tagging rows with the partition name.
- **Rebalancing**: :class:`Rebalancer` watches per-partition SLO budget
  burn (the PR-12 ``stats()`` surface riding ``health()``) and migrates the
  hottest tenants off a burning partition before its error budget empties.

Concurrency contract (threadlint-covered, ``router.state`` rank 12): one
lock guards the ring, override map, session registry, traffic counters and
breaker states. NO gRPC round-trip ever runs under it — every RPC helper
snapshots what it needs, releases, calls, then reacquires to commit (rule
T2 enforces this statically; the lock witness at runtime). The migration
hold is an Event waited on OUTSIDE the lock, bounded by
``migration_hold_sec``.

See docs/scale-out.md for the operator view and the measured SLOs.
"""

from __future__ import annotations

import bisect
import hashlib
import logging
import os
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from escalator_tpu import observability as obs
from escalator_tpu.analysis import lockwitness
from escalator_tpu.metrics import metrics

log = logging.getLogger("escalator_tpu.fleet.router")

__all__ = [
    "Partition",
    "PartitionRouter",
    "Rebalancer",
    "RouterError",
    "hash_ring_points",
]

#: virtual nodes per partition on the hash ring; 64 keeps the per-partition
#: share within a few percent of uniform at single-digit partition counts
DEFAULT_REPLICAS = 64


class RouterError(RuntimeError):
    """A routing/migration operation that cannot proceed (no partitions,
    unknown partition name, migration to the current home)."""


def _point(key: bytes) -> int:
    """One 64-bit ring coordinate. blake2b, like every other digest in the
    repo — md5/sha1 would be the only other users of hashlib here."""
    return int.from_bytes(
        hashlib.blake2b(key, digest_size=8).digest(), "big")


def hash_ring_points(name: str, replicas: int = DEFAULT_REPLICAS
                     ) -> List[int]:
    """The ring coordinates one partition occupies (pure; test surface)."""
    return [_point(f"{name}#{i}".encode()) for i in range(replicas)]


@dataclass
class Partition:
    """One fleet plugin process behind the router.

    ``client`` is the partition's :class:`ComputeClient`; breaker fields
    mirror ``GrpcBackend``'s consecutive-failure model, held per partition
    and mutated only under the router lock.
    """

    name: str
    address: str
    client: object = None
    #: consecutive forwarding failures (post-retry); reset on any success
    failures: int = 0
    #: breaker open = the partition is considered DOWN until fail-over or
    #: an operator re-add; unlike the backend breaker there is no probe
    #: loop — a partition's tenants are re-homed, not served degraded
    down: bool = False

    def as_doc(self) -> dict:
        return {"name": self.name, "address": self.address,
                "failures": self.failures, "down": self.down}


@dataclass
class _MigrationHold:
    """Gate for routed decides of ONE tenant while it moves."""

    done: threading.Event = field(default_factory=threading.Event)
    dest: str = ""


class PartitionRouter:
    """Consistent-hash router over N fleet partitions (see module doc).

    Thread-safe: decide forwarding, migration, failover and the aggregation
    probes may run concurrently from different threads (the rebalancer and
    the checkpointer are exactly such threads).
    """

    def __init__(self, partitions: "Dict[str, str] | None" = None, *,
                 replicas: int = DEFAULT_REPLICAS,
                 overrides: "Dict[str, str] | None" = None,
                 breaker_threshold: int = 3,
                 checkpoint_dir: "str | None" = None,
                 timeout_sec: float = 30.0,
                 retry=None,
                 migration_hold_sec: float = 60.0,
                 client_factory=None):
        from escalator_tpu.plugin.client import ComputeClient

        self.replicas = int(replicas)
        self.breaker_threshold = int(breaker_threshold)
        self.checkpoint_dir = checkpoint_dir
        self.timeout_sec = float(timeout_sec)
        self.retry = retry
        self.migration_hold_sec = float(migration_hold_sec)
        self._client_factory = client_factory or (
            lambda addr: ComputeClient(addr, timeout_sec=self.timeout_sec,
                                       retry=self.retry))
        self._lock = lockwitness.make_lock("router.state")
        #: sorted ring of (point, partition name); rebuilt on membership
        #: change — reads copy the list reference under the lock
        self._ring: List[Tuple[int, str]] = []
        self._partitions: Dict[str, Partition] = {}
        self._overrides: Dict[str, str] = dict(overrides or {})
        #: live FleetStreamSessions by tenant (rebound on move)
        self._sessions: Dict[str, object] = {}
        #: tenant -> last routed home. The failover/checkpoint set: ring
        #: state is already pruned by the time a breaker-tripped fail_over
        #: runs, so "who lived on the dead partition" must be remembered
        #: at routing time, not re-derived
        self._known: Dict[str, str] = {}
        #: decides forwarded per tenant (the rebalancer's heat signal)
        self._traffic: Dict[str, int] = {}
        #: per-partition journal cursors for incremental aggregation
        self._cursors: Dict[str, int] = {}
        self._migrating: Dict[str, _MigrationHold] = {}
        for name, address in (partitions or {}).items():
            self.add_partition(name, address)

    # -- membership / ring ----------------------------------------------------

    def add_partition(self, name: str, address: str, client=None) -> None:
        """Add (or revive) a partition and splice its arcs into the ring.
        Only keys landing on the new arcs move — the consistent-hash
        guarantee the hash-stability tests lock."""
        client = client if client is not None else self._client_factory(
            address)
        points = hash_ring_points(name, self.replicas)
        with self._lock:
            self._partitions[name] = Partition(
                name=name, address=address, client=client)
            ring = [(p, n) for p, n in self._ring if n != name]
            ring.extend((p, name) for p in points)
            ring.sort()
            self._ring = ring
        log.info("router: partition %r at %s joined (%d ring points)",
                 name, address, len(points))

    def remove_partition(self, name: str) -> None:
        """Drop a partition from the ring (operator action or failover).
        Its keys re-hash onto the survivors; overrides are untouched."""
        with self._lock:
            self._ring = [(p, n) for p, n in self._ring if n != name]
            part = self._partitions.get(name)
            if part is not None:
                part.down = True

    def partitions(self) -> List[dict]:
        with self._lock:
            return [p.as_doc() for p in self._partitions.values()]

    def home(self, tenant_id: str) -> str:
        """The tenant's partition: override first, else the first ring arc
        clockwise of the tenant's hash point."""
        with self._lock:
            return self._home_locked(tenant_id)

    def _home_locked(self, tenant_id: str) -> str:
        override = self._overrides.get(tenant_id)
        if override is not None:
            part = self._partitions.get(override)
            if part is not None and not part.down:
                return override
        if not self._ring:
            raise RouterError("no live partitions on the ring")
        h = _point(str(tenant_id).encode())
        i = bisect.bisect_right(self._ring, (h, ""))
        if i >= len(self._ring):
            i = 0
        return self._ring[i][1]

    def client_for(self, tenant_id: str):
        """The tenant's home ComputeClient (waits out a migration hold)."""
        self._await_migration(tenant_id)
        with self._lock:
            name = self._home_locked(tenant_id)
            return self._partitions[name].client

    # -- forwarding -----------------------------------------------------------

    def stream_session(self, tenant_id: str, **session_kw):
        """A :class:`FleetStreamSession` homed by the ring, registered for
        automatic rebinding when the tenant migrates or fails over."""
        from escalator_tpu.plugin.client import FleetStreamSession

        self._await_migration(tenant_id)
        with self._lock:
            name = self._home_locked(tenant_id)
            client = self._partitions[name].client
            self._known[tenant_id] = name
        session = FleetStreamSession(client, tenant_id, **session_kw)
        with self._lock:
            self._sessions[tenant_id] = session
        return session

    def decide_stream(self, session, now_sec: int, **kw):
        """One routed streamed decide with breaker + failover semantics:
        forwards via the session (frames unchanged), counts traffic, and —
        when the home partition's breaker trips — fails its tenants over to
        the survivors and replays THIS decide on the new home. The caller
        sees one slow decide instead of an error: the measured failover
        gap. Raises when no checkpointed survivor can take the tenant."""
        import grpc

        tenant_id = session.tenant_id
        self._await_migration(tenant_id)
        with self._lock:
            name = self._home_locked(tenant_id)
            self._known[tenant_id] = name
            self._traffic[tenant_id] = self._traffic.get(tenant_id, 0) + 1
            if self._sessions.get(tenant_id) is not session:
                self._sessions[tenant_id] = session
        try:
            self._chaos_partition(name)
            result = session.decide(now_sec, **kw)
        except grpc.RpcError:
            if not self._record_failure(name):
                raise
            self.fail_over(name)
            # fail_over rebound the session (resync where needed): replay
            return session.decide(now_sec, **kw)
        self._record_success(name)
        return result

    def evict_tenant(self, tenant_id: str) -> dict:
        client = self.client_for(tenant_id)
        ack = client.evict_tenant(tenant_id)
        with self._lock:
            self._sessions.pop(tenant_id, None)
            self._known.pop(tenant_id, None)
            self._traffic.pop(tenant_id, None)
            self._overrides.pop(tenant_id, None)
        return ack

    @staticmethod
    def _chaos_partition(name: str) -> None:
        """The ``router_partition`` chaos site: pretend the home partition
        died mid-campaign. Raises the SAME synthetic retryable RpcError the
        ``plugin_rpc`` site uses, so the injected fault walks the real
        breaker → fail_over → replay ladder — a partition kill without a
        process kill (the chaos-soak job arms it; ``partition=`` scopes the
        blast to one partition, ``code=`` picks the status)."""
        from escalator_tpu.chaos import CHAOS

        params = CHAOS.params("router_partition")
        only = params.get("partition")
        if only and only != name:
            return   # scoped to another partition: not even an eligible call
        if CHAOS.should_fire("router_partition"):
            import grpc

            from escalator_tpu.plugin.client import _InjectedRpcError

            code = params.get("code", "unavailable").upper()
            raise _InjectedRpcError(getattr(grpc.StatusCode, code,
                                            grpc.StatusCode.UNAVAILABLE))

    def _record_failure(self, name: str) -> bool:
        """Count one post-retry forwarding failure; True when the breaker
        just opened (the caller owns running fail_over OUTSIDE the lock)."""
        with self._lock:
            part = self._partitions.get(name)
            if part is None or part.down:
                return False
            part.failures += 1
            if part.failures >= self.breaker_threshold:
                part.down = True
                self._ring = [(p, n) for p, n in self._ring if n != name]
                tripped = True
            else:
                tripped = False
        if tripped:
            metrics.router_breaker_trips.labels(name).inc()
            obs.journal.JOURNAL.event(
                "partition-breaker-open", partition=name,
                failures=self.breaker_threshold)
        return tripped

    def _record_success(self, name: str) -> None:
        with self._lock:
            part = self._partitions.get(name)
            if part is not None:
                part.failures = 0

    # -- migration ------------------------------------------------------------

    def _await_migration(self, tenant_id: str) -> None:
        with self._lock:
            hold = self._migrating.get(tenant_id)
        if hold is not None:
            hold.done.wait(timeout=self.migration_hold_sec)

    def migrate_tenant(self, tenant_id: str, dest: str,
                       timeout_sec: "float | None" = None) -> dict:
        """Move one tenant WARM from its current home to partition
        ``dest``: quiesce+freeze the row on the source, evict, adopt on the
        target, pin the override, rebind the live session. Journal sequence
        (test- and doc-locked): ``migration-start → migration-row-snapshot
        → migration-evict → migration-adopt → migration-complete``. Routed
        decides for this tenant hold for the duration (bounded by
        ``migration_hold_sec``); returns a report with the measured gap."""
        timeout = float(timeout_sec if timeout_sec is not None
                        else self.timeout_sec)
        with self._lock:
            src = self._home_locked(tenant_id)
            dpart = self._partitions.get(dest)
            if dpart is None or dpart.down:
                raise RouterError(f"unknown or down partition {dest!r}")
            if src == dest:
                raise RouterError(
                    f"tenant {tenant_id!r} already lives on {dest!r}")
            if tenant_id in self._migrating:
                raise RouterError(
                    f"tenant {tenant_id!r} is already migrating")
            hold = _MigrationHold(dest=dest)
            self._migrating[tenant_id] = hold
            src_client = self._partitions[src].client
            dest_client = dpart.client
            session = self._sessions.get(tenant_id)
        obs.journal.JOURNAL.event(
            "migration-start", tenant=tenant_id, source=src, dest=dest)
        t0 = time.perf_counter()
        try:
            blob = src_client.snapshot_tenant(tenant_id, timeout_sec=timeout)
            obs.journal.JOURNAL.event(
                "migration-row-snapshot", tenant=tenant_id, source=src,
                nbytes=len(blob))
            src_client.evict_tenant(tenant_id)
            obs.journal.JOURNAL.event(
                "migration-evict", tenant=tenant_id, source=src)
            ack = dest_client.adopt_tenant(blob)
            obs.journal.JOURNAL.event(
                "migration-adopt", tenant=tenant_id, dest=dest,
                shard=int(ack.get("shard", -1)), row=int(ack.get("row", -1)))
            with self._lock:
                self._overrides[tenant_id] = dest
                self._known[tenant_id] = dest
            if session is not None:
                # warm: the target twin IS the frozen row — delta path
                # continues, no resync (FleetStreamSession.rebind doc)
                session.rebind(dest_client)
            if self.checkpoint_dir:
                self._write_checkpoint(tenant_id, blob)
            gap_ms = (time.perf_counter() - t0) * 1e3
            metrics.router_migrations.labels("ok").inc()
            obs.journal.JOURNAL.event(
                "migration-complete", tenant=tenant_id, source=src,
                dest=dest, gap_ms=round(gap_ms, 3))
            log.info("router: migrated %r %s -> %s in %.1f ms",
                     tenant_id, src, dest, gap_ms)
            return {"tenant": tenant_id, "source": src, "dest": dest,
                    "gap_ms": round(gap_ms, 3),
                    "shard": int(ack.get("shard", -1)),
                    "row": int(ack.get("row", -1))}
        except Exception:
            metrics.router_migrations.labels("error").inc()
            raise
        finally:
            with self._lock:
                self._migrating.pop(tenant_id, None)
            hold.done.set()

    # -- rolling checkpoint / failover ---------------------------------------

    def _checkpoint_path(self, tenant_id: str) -> str:
        # tenant ids passed validate_tenant_id ([a-z0-9._-]): safe as a
        # filename component without escaping
        return os.path.join(self.checkpoint_dir,
                            f"tenant-{tenant_id}.escsnap")

    def _write_checkpoint(self, tenant_id: str, blob: bytes) -> None:
        os.makedirs(self.checkpoint_dir, exist_ok=True)
        path = self._checkpoint_path(tenant_id)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "wb") as f:
            f.write(blob)
        os.replace(tmp, path)

    def checkpoint_tenants(self, tenants: "List[str] | None" = None) -> dict:
        """Roll the checkpoint: snapshot each known tenant's row off its
        live home and park the blob in ``checkpoint_dir`` (atomic rename).
        The failover source of truth — a tenant's decision continuity after
        a partition kill is bounded by this cadence. Returns per-tenant
        outcomes; a partition error marks its tenants ``"error"`` without
        failing the sweep (the next roll retries)."""
        import grpc

        if not self.checkpoint_dir:
            raise RouterError("router has no checkpoint_dir configured")
        with self._lock:
            todo = list(tenants if tenants is not None else self._known)
        report: Dict[str, str] = {}
        for tenant_id in todo:
            with self._lock:
                hold = self._migrating.get(tenant_id)
            if hold is not None:
                report[tenant_id] = "migrating"
                continue
            try:
                client = self.client_for(tenant_id)
                blob = client.snapshot_tenant(tenant_id)
                self._write_checkpoint(tenant_id, blob)
                report[tenant_id] = "ok"
            except (grpc.RpcError, RouterError, OSError) as e:
                report[tenant_id] = "error"
                log.warning("router: checkpoint of %r failed: %s",
                            tenant_id, e)
        ok = sum(1 for v in report.values() if v == "ok")
        obs.journal.JOURNAL.event(
            "router-checkpoint", tenants=len(report), ok=ok)
        return report

    def fail_over(self, name: str, dest: "str | None" = None) -> dict:
        """Re-home every tenant of a dead partition onto the survivors.

        For each tenant whose home was ``name``: adopt its latest rolling
        checkpoint on the ring-chosen survivor (or ``dest``), pin the
        override, and rebind any live session with ``resync=True`` — the
        checkpoint may predate the last served tick, so the next decide
        ships a FULL frame that rebases the twin (digest continuity then
        holds from the checkpointed columns; the decision gap is bounded by
        the checkpoint cadence plus this re-home). Tenants with no
        checkpoint re-home COLD (full frame onto an empty row). Journal:
        ``partition-failover-start``, per-tenant ``failover-rehome``,
        ``partition-failover-complete`` with the measured wall time."""
        import grpc

        t0 = time.perf_counter()
        with self._lock:
            part = self._partitions.get(name)
            if part is None:
                raise RouterError(f"unknown partition {name!r}")
            part.down = True
            part.failures = max(part.failures, self.breaker_threshold)
            self._ring = [(p, n) for p, n in self._ring if n != name]
            if not self._ring:
                raise RouterError(
                    f"partition {name!r} died and no survivors remain")
            # tenants homed on the dead partition at their last routing —
            # the ring is already pruned, so the remembered homes are the
            # only authority on who lived there
            victims = [t for t, h in self._known.items() if h == name]
        obs.journal.JOURNAL.event(
            "partition-failover-start", partition=name,
            tenants=len(victims))
        moved: Dict[str, str] = {}
        for tenant_id in victims:
            with self._lock:
                new_home = dest or self._home_locked(tenant_id)
                client = self._partitions[new_home].client
                session = self._sessions.get(tenant_id)
            outcome = "cold"
            blob = self._read_checkpoint(tenant_id)
            if blob is not None:
                try:
                    client.adopt_tenant(blob)
                    outcome = "warm"
                except grpc.RpcError as e:
                    log.warning(
                        "router: checkpoint adopt of %r on %r failed (%s); "
                        "re-homing cold", tenant_id, new_home, e)
            with self._lock:
                self._overrides[tenant_id] = new_home
                self._known[tenant_id] = new_home
            if session is not None:
                session.rebind(client, resync=True)
            moved[tenant_id] = new_home
            metrics.router_failover_rehomes.labels(outcome).inc()
            obs.journal.JOURNAL.event(
                "failover-rehome", tenant=tenant_id, partition=new_home,
                outcome=outcome)
        wall_ms = (time.perf_counter() - t0) * 1e3
        obs.journal.JOURNAL.event(
            "partition-failover-complete", partition=name,
            tenants=len(moved), wall_ms=round(wall_ms, 3))
        log.warning("router: partition %r failed over (%d tenants, %.1f ms)",
                    name, len(moved), wall_ms)
        return {"partition": name, "moved": moved,
                "wall_ms": round(wall_ms, 3)}

    def _read_checkpoint(self, tenant_id: str) -> "bytes | None":
        if not self.checkpoint_dir:
            return None
        try:
            with open(self._checkpoint_path(tenant_id), "rb") as f:
                return f.read()
        except OSError:
            return None

    # -- aggregation ----------------------------------------------------------

    def _live_clients(self) -> List[Tuple[str, object]]:
        with self._lock:
            return [(p.name, p.client) for p in self._partitions.values()
                    if not p.down]

    def health(self) -> dict:
        """Per-partition health docs plus an aggregate row: partition
        count, summed tenants/queue depth, and the down list — the
        single probe ``escalator-tpu debug-partitions`` renders."""
        import grpc

        docs: Dict[str, dict] = {}
        for name, client in self._live_clients():
            try:
                docs[name] = client.health()
            except grpc.RpcError as e:
                docs[name] = {"ok": False, "error": str(e)}
        with self._lock:
            down = [p.name for p in self._partitions.values() if p.down]
            overrides = dict(self._overrides)
        tenants = sum(d.get("fleet", {}).get("tenants", 0)
                      for d in docs.values() if d.get("ok"))
        queue = sum(d.get("fleet", {}).get("queue_depth", 0)
                    for d in docs.values() if d.get("ok"))
        return {
            "ok": all(d.get("ok") for d in docs.values()) and not down,
            "partitions": docs,
            "down": down,
            "overrides": overrides,
            "aggregate": {"partitions": len(docs), "tenants": tenants,
                          "queue_depth": queue},
        }

    def journal(self) -> dict:
        """The merged ops journal across partitions: each partition's
        events (incremental via per-partition ``since`` cursors) tagged
        with ``partition`` and merged in wall-clock order. The router's own
        events (migration/failover) live in THIS process's journal — read
        them locally; this method aggregates the serving side."""
        import grpc

        merged: List[dict] = []
        for name, client in self._live_clients():
            with self._lock:
                since = self._cursors.get(name, 0)
            try:
                doc = client.journal(since_seq=since)
            except grpc.RpcError:
                continue
            events = doc.get("events", [])
            if events:
                with self._lock:
                    self._cursors[name] = max(
                        self._cursors.get(name, 0),
                        max(int(e.get("seq", 0)) for e in events))
            for e in events:
                e = dict(e)
                e["partition"] = name
                merged.append(e)
        merged.sort(key=lambda e: (e.get("ts", 0), e.get("seq", 0)))
        return {"events": merged}

    def explain(self, tenant: "str | None" = None,
                groups: "list | None" = None) -> dict:
        """Explain routed to the tenant's home; discovery (no tenant)
        merges every partition's known keys, tagged by partition."""
        import grpc

        if tenant is not None:
            client = self.client_for(tenant)
            doc = client.explain(tenant=tenant, groups=groups)
            doc["partition"] = self.home(tenant)
            return doc
        keys: Dict[str, List[str]] = {}
        for name, client in self._live_clients():
            try:
                keys[name] = client.explain().get("keys", [])
            except grpc.RpcError:
                keys[name] = []
        return {"keys": keys}

    # -- introspection --------------------------------------------------------

    def tenants_on(self, name: str) -> List[str]:
        with self._lock:
            return [t for t in self._known if self._home_locked(t) == name]

    def traffic(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._traffic)

    def close(self) -> None:
        with self._lock:
            clients = [p.client for p in self._partitions.values()]
        for c in clients:
            try:
                c.close()
            except Exception:  # noqa: BLE001 - shutdown best-effort
                pass


class Rebalancer:
    """SLO-burn-driven tenant rebalancing across partitions (round 20).

    Watches the per-partition per-class ``slo_burn`` surface (PR 12: burn
    rate of the p99 error budget, riding ``health()``'s fleet section) and,
    when one partition burns past ``burn_threshold`` while another sits
    below ``cool_threshold``, migrates the burning partition's hottest
    tenants (by routed decide count) onto the coolest survivor — before the
    budget empties, instead of after the pager fires. ``step()`` is the
    synchronous, testable unit; ``start()`` runs it on a daemon thread
    every ``interval_sec``.
    """

    def __init__(self, router: PartitionRouter, *,
                 burn_threshold: float = 1.0,
                 cool_threshold: float = 0.5,
                 interval_sec: float = 5.0,
                 max_moves_per_step: int = 1):
        self.router = router
        self.burn_threshold = float(burn_threshold)
        self.cool_threshold = float(cool_threshold)
        self.interval_sec = float(interval_sec)
        self.max_moves_per_step = int(max_moves_per_step)
        self._stop = threading.Event()
        self._thread: "threading.Thread | None" = None

    @staticmethod
    def _burn_of(doc: dict) -> float:
        """A partition's worst per-class SLO budget burn (0 when the fleet
        section is missing — a non-fleet or unreachable partition never
        looks hot)."""
        classes = doc.get("fleet", {}).get("classes", {}) or {}
        burns = [row.get("slo_burn") or 0.0 for row in classes.values()]
        return max(burns, default=0.0)

    def step(self) -> List[dict]:
        """One rebalance pass: returns the migration reports it made
        (empty when no partition is burning, no survivor is cool, or the
        burning partition has no tenants to shed)."""
        health = self.router.health()
        burns = {name: self._burn_of(doc)
                 for name, doc in health["partitions"].items()
                 if doc.get("ok")}
        if len(burns) < 2:
            return []
        hot = max(burns, key=lambda n: burns[n])
        cool = min(burns, key=lambda n: burns[n])
        if burns[hot] < self.burn_threshold or \
                burns[cool] > self.cool_threshold:
            return []
        traffic = self.router.traffic()
        victims = sorted(self.router.tenants_on(hot),
                         key=lambda t: traffic.get(t, 0), reverse=True)
        moves: List[dict] = []
        for tenant_id in victims[:self.max_moves_per_step]:
            try:
                report = self.router.migrate_tenant(tenant_id, cool)
            except Exception as e:  # noqa: BLE001 - a failed move must not
                # kill the loop; the tenant stays where it is
                log.warning("rebalancer: migrating %r off %r failed: %s",
                            tenant_id, hot, e)
                continue
            report["reason"] = {"burn": round(burns[hot], 2),
                                "cool_burn": round(burns[cool], 2)}
            obs.journal.JOURNAL.event(
                "rebalance-migrate", tenant=tenant_id, source=hot,
                dest=cool, burn=round(burns[hot], 2))
            moves.append(report)
        return moves

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name="escalator-router-rebalance",
            daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=10.0)
            self._thread = None

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_sec):
            try:
                self.step()
            except Exception:  # noqa: BLE001 - the loop must survive probes
                log.exception("rebalancer step failed")
