"""FleetEngine: the device-side owner of the multi-tenant decision arenas.

The round-8 incremental decide keeps ONE cluster's state device-resident and
pays O(dirty) per tick; the fleet engine stacks C independent tenants along a
leading cluster axis — since round 16 PARTITIONED across a device mesh — and
pays one dispatch per MICRO-BATCH of tenants:

- resident arrays ``pods [S, Cs+1, P+1]`` / ``nodes [S, Cs+1, N+1]`` /
  ``groups [S, Cs+1, G]`` — ``S`` mesh shards of ``Cs`` tenant rows each,
  sharded one row per device (row ``Cs`` of every shard is that shard's
  scratch tenant; each row keeps its own scratch lane),
- per-tenant :class:`~escalator_tpu.ops.kernel.GroupAggregates` arenas
  ``[S, Cs+1, G]`` (+ ``node_pods_remaining [S, Cs+1, N+1]``) maintained by
  the same exact integer deltas as the single-tenant path,
- the 13 persistent decision columns ``[S, Cs+1, G]``.

Tenants are embarrassingly parallel — ``fleet_decide`` has zero collectives
— so the sharded step (``ops.device_state.make_fleet_step_sharded``) runs
each shard's micro-batch slice independently and per-shard device time
shrinks with the mesh. Every tenant's 13 decision columns stay BIT-IDENTICAL
to the unsharded single-device path (and to its standalone ``decide_jit``),
locked by the randomized add/evict/grow soak in tests/test_fleet.py.

Ragged tenants pack into shared power-of-two ``(G, N, P)`` buckets (the
``statestore.delta_bucket`` policy generalized to arena shapes) with their
per-lane ``valid`` masks; a tenant outgrowing a bucket grows the arena
(rare: buckets double), and :meth:`FleetEngine.compact` repacks live tenants
into the smallest bucket after mass evictions.

**Two-stage pipeline API (round 16).** The old blocking ``step`` split into
:meth:`FleetEngine.prepare_batch` (all host work: validation, per-tenant
positional diff against the host twins, dirty bookkeeping, operand assembly
— CPU-bound, no device access) and :meth:`FleetEngine.execute_batch` (the
one fused device dispatch + per-tenant unpack/ordered tails), so a
pipelining scheduler can assemble batch k+1's host diff while batch k's
device program is in flight. ``step()`` is still both stages back-to-back.

Concurrency contract (the scheduler runs ONE prep thread + ONE dispatch
thread; lock order is ``_exec_lock`` → ``_host`` (condition) →
``_device_lock``, and prepared batches execute IN ORDER):

- ``prepare_batch`` owns the host twins/slot maps under ``_host`` and
  registers itself as ``_staged`` before returning; ``execute_batch``
  clears that registration at its very END (after ordered tails), under
  ``_host``'s condition, which is also the channel arena reshapes wait on.
- An arena reshape (grow/compact/rebuild) bumps ``_epoch`` and must first
  ``_await_staged_drain`` — a staged batch's operands are shaped at the old
  buckets. The wait releases ``_host`` (condition variable), so the
  dispatch thread can finish the staged batch meanwhile.
- ``execute_batch``'s epoch check is an UNLOCKED read on purpose: taking
  ``_host`` there would deadlock against a grow waiting (under ``_host``)
  for the staged batch this very call is trying to drain. A stale batch
  (epoch behind — only the dispatch-failure rebuild produces one) FAILS
  with :class:`StaleBatchError`; re-preparing from the dispatch thread
  would race the prep thread and break in-order twin adoption.
- The dispatch-failure path bumps the epoch UNLOCKED first (so drain
  waiters can classify the staged batch stale) and again under ``_host``
  atomically with the twin reset.
- ``release_prepared`` (scheduler shutdown with a staged-but-never-
  dispatched batch) takes ``_exec_lock`` bounded, then rolls the twins
  back from the per-entry rollback records — twins advance at PREP time,
  so an abandoned prep must unwind or the next diff would skip lanes the
  device never saw.

Because twins adopt at prepare time, callers must NOT mutate a request's
arrays between ``submit`` and completion — the engine copies each section
into the arena-bucket twin during prep (``_repad_copy``), so the window is
the prep call itself.

Orders run the lazy protocol per MICRO-BATCH (round 18): the batch dispatch
is the light program; every tenant whose decision consumes an order
(tainted nodes exist, or some group scales down) rides ONE batched
order-repair dispatch (``device_state.make_fleet_order_tail_sharded`` —
the kernel's exact ordered branch vmapped over the order-needing rows,
fed the resident post-step state) whose ``untaint_order``/
``scale_down_order`` graft into the already-unpacked decisions. Steady
fleets sort never; a drain-heavy batch pays one fused sort dispatch, not
one 55 ms O(arena) re-dispatch per draining tenant.

Round 18 also adds the host-side fast paths: a per-tenant input DIGEST
answers unchanged requests straight from the cached decision columns
(never entering the micro-batch), and tenant DELTA FRAMES
(:class:`DeltaFrame` — state-store-twin dirty drains shipped over the
wire) replace the per-tenant positional diff with a direct scatter, so
steady prep cost is O(churn) rather than O(cluster).
"""

from __future__ import annotations

import hashlib
import logging
import time
from dataclasses import dataclass, field, fields
from typing import Dict, List, Optional, Sequence, Tuple, Union

import numpy as np

from escalator_tpu import observability as obs
from escalator_tpu.analysis import lockwitness
from escalator_tpu.core.arrays import (
    NO_TAINT_TIME,
    ClusterArrays,
    GroupArrays,
    NodeArrays,
    PodArrays,
)
from escalator_tpu.metrics import metrics
from escalator_tpu.native.statestore import delta_bucket

log = logging.getLogger("escalator_tpu.fleet")

#: Tenant-id wire contract: a non-empty printable string, bounded so a
#: hostile frame cannot balloon the slot map key space per request.
MAX_TENANT_ID_LEN = 128


class TenantError(ValueError):
    """A per-tenant request the fleet cannot serve (malformed/unknown tenant
    id, bucket caps exceeded). Maps to INVALID_ARGUMENT at the gRPC edge —
    and never poisons the batch it would have ridden in."""


class StaleBatchError(RuntimeError):
    """A prepared batch went stale before executing: the arenas were
    rebuilt (dispatch-failure recovery) after it was prepared, so its
    operands describe state that no longer exists. The batch fails and
    its requests must be resubmitted."""


def validate_tenant_id(tenant_id) -> str:
    """The ONE tenant-id validation both the gRPC edge and the engine run:
    a non-empty printable str of at most MAX_TENANT_ID_LEN chars."""
    if not isinstance(tenant_id, str):
        raise TenantError(f"tenant id must be a string, got "
                          f"{type(tenant_id).__name__}")
    if not tenant_id or len(tenant_id) > MAX_TENANT_ID_LEN:
        raise TenantError(
            f"tenant id must be 1..{MAX_TENANT_ID_LEN} chars, got "
            f"{len(tenant_id)}")
    if not tenant_id.isprintable():
        raise TenantError("tenant id must be printable")
    return tenant_id


@dataclass
class DeltaFrame:
    """A tenant's packed dirty drain (round 18 streaming ingestion): the
    ``(idx, values)`` batches a state-store twin's ``drain_dirty_packed``
    emits, trimmed of padding, plus the request's padded shapes. The engine
    scatters these straight into the tenant's host twin and feeds them to
    the fused step as the delta batch — no per-tenant positional diff runs
    at all (``prepare_batch``'s ``_changed_rows`` is the O(cluster) host
    cost this replaces). ``groups`` ships the full section only when the
    group options changed (``set_groups``/reload); ``None`` means
    unchanged. Slot indices address the tenant's resident lanes — the
    client and engine agree on slot identity because BOTH sides run the
    same state-store slot allocator (the store twin is the contract)."""

    shapes: Tuple[int, int, int]          # the request's (G, P, N) paddings
    pod_idx: np.ndarray                   # int [dp] changed pod slots
    pod_vals: PodArrays                   # [dp] packed rows at those slots
    node_idx: np.ndarray                  # int [dn]
    node_vals: NodeArrays                 # [dn]
    groups: Optional[GroupArrays] = None  # full section iff options changed


@dataclass
class DecideRequest:
    """One tenant's decide: a packed cluster (any padding at or under the
    arena caps) + the timestamp the decision evaluates at. ``delta``
    (round 18) replaces the full cluster with a packed dirty drain against
    the tenant's resident twin — ``cluster`` is then None and the tenant
    must already be resident (a delta before any full frame is a
    TenantError; growth past the arena buckets requires a full frame)."""

    tenant_id: str
    cluster: Optional[ClusterArrays]
    now_sec: int
    delta: Optional[DeltaFrame] = None


@dataclass
class EvictRequest:
    """Deregister a tenant: its lanes clear, its slot frees for reuse."""

    tenant_id: str


@dataclass
class EvictAck:
    tenant_id: str


@dataclass
class FleetDecision:
    """One tenant's result, sliced back to ITS request's padded shapes — the
    13 decision columns are bit-identical to a standalone
    ``decide_jit``/``delta_decide_jit`` on the same cluster. ``ordered``
    carries the lazy-orders flag: False means the order fields are
    input-order placeholders and no window may be read (exactly the
    single-cluster protocol's contract). ``shard`` is the mesh row the
    tenant's arena lives on."""

    tenant_id: str
    arrays: object          # kernel.DecisionArrays with numpy leaves
    ordered: bool
    batch_size: int
    shard: int = 0
    #: engine-side journey raw material (round 17): the batch's device
    #: dispatch window (monotonic stamps), this tenant's ordered-tail cost,
    #: and the shared per-batch journey sink the scheduler appends the
    #: finished journey into (so the fleet_batch flight record carries it).
    #: None from engines that predate journeys.
    stages: Optional[dict] = None
    #: the finished per-request journey, attached by the scheduler on the
    #: respond side (stage durations summing to the endpoint e2e) — the
    #: gRPC edge ships it back to the caller as span phases + fleet sidecar
    journey: Optional[dict] = None
    #: round 18: True when the digest fast path answered this request from
    #: the tenant's cached decision columns without entering the
    #: micro-batch (``batch_size`` is then 0 — the request rode no batch).
    #: The arrays are bit-equal to what a dispatch would have produced
    #: (locked by the churn soak); callers must not mutate them.
    cached: bool = False


def _pow2(n: int, lo: int = 1) -> int:
    return max(lo, 1 << max(int(n) - 1, 0).bit_length())


def _empty_pods(P: int) -> PodArrays:
    return PodArrays(
        group=np.zeros(P, np.int32), cpu_milli=np.zeros(P, np.int64),
        mem_bytes=np.zeros(P, np.int64), node=np.full(P, -1, np.int32),
        valid=np.zeros(P, bool),
    )


def _empty_nodes(N: int) -> NodeArrays:
    return NodeArrays(
        group=np.zeros(N, np.int32), cpu_milli=np.zeros(N, np.int64),
        mem_bytes=np.zeros(N, np.int64), creation_ns=np.zeros(N, np.int64),
        tainted=np.zeros(N, bool), cordoned=np.zeros(N, bool),
        no_delete=np.zeros(N, bool),
        taint_time_sec=np.full(N, NO_TAINT_TIME, np.int64),
        valid=np.zeros(N, bool),
    )


def _empty_groups(G: int) -> GroupArrays:
    # pack_groups' padding conventions exactly (scale_up_thr=1 guards /0)
    return GroupArrays(
        min_nodes=np.zeros(G, np.int32), max_nodes=np.zeros(G, np.int32),
        taint_lower=np.zeros(G, np.int32), taint_upper=np.zeros(G, np.int32),
        scale_up_thr=np.ones(G, np.int32), slow_rate=np.zeros(G, np.int32),
        fast_rate=np.zeros(G, np.int32), locked=np.zeros(G, bool),
        requested_nodes=np.zeros(G, np.int32),
        cached_cpu_milli=np.zeros(G, np.int64),
        cached_mem_bytes=np.zeros(G, np.int64),
        soft_grace_sec=np.zeros(G, np.int64),
        hard_grace_sec=np.zeros(G, np.int64),
        emptiest=np.zeros(G, bool), valid=np.zeros(G, bool),
    )


def _repad(src, bucket: int, empty_fn):
    """A section re-padded into the arena bucket: the client's lanes lead,
    the tail carries the SAME pad values a fresh twin starts with — so
    padding lanes never read as changed in the positional diff."""
    n = int(getattr(src, "valid").shape[0])
    if n == bucket:
        return src
    out = empty_fn(bucket)
    for f in fields(src):
        getattr(out, f.name)[:n] = getattr(src, f.name)
    return out


def _repad_copy(src, bucket: int, empty_fn):
    """:func:`_repad` that ALWAYS copies — prepared twins must not alias a
    caller's request arrays (the pipeline holds them across the dispatch,
    after the RPC that carried them has already returned)."""
    out = _repad(src, bucket, empty_fn)
    if out is src:
        out = type(src)(**{f.name: np.array(getattr(src, f.name))
                           for f in fields(src)})
    return out


def _changed_rows(old, new) -> np.ndarray:
    """Row indices where ANY column differs (positional diff, all fields)."""
    changed = None
    for f in fields(old):
        d = np.asarray(getattr(old, f.name)) != np.asarray(getattr(new, f.name))
        changed = d if changed is None else (changed | d)
    return np.nonzero(changed)[0].astype(np.int64)


def _request_digest(cluster: ClusterArrays, now_sec: int) -> bytes:
    """Content digest of one full-frame request (round 18 fast path): every
    section's raw column bytes plus shapes/dtypes plus ``now_sec``. Two
    requests with equal digests produce bit-identical decisions (decide is
    deterministic in content + now, and the answer's slicing depends only
    on the request shapes, which the digest covers)."""
    h = hashlib.blake2b(digest_size=16)
    h.update(np.int64(now_sec).tobytes())
    for section in (cluster.groups, cluster.pods, cluster.nodes):
        for f in fields(section):
            a = np.ascontiguousarray(getattr(section, f.name))
            h.update(f.name.encode())
            h.update(repr((a.shape, a.dtype.str)).encode())
            h.update(a.tobytes())
    return h.digest()


#: The persistent-decision-column dtypes, in kernel.GROUP_DECISION_FIELDS
#: order — the [S, Cs+1, G] arena columns must match DecisionArrays
#: bit-for-bit.
_COL_DTYPES = {
    "status": np.int32, "nodes_delta": np.int32,
    "cpu_percent": np.float64, "mem_percent": np.float64,
    "cpu_request_milli": np.int64, "mem_request_bytes": np.int64,
    "cpu_capacity_milli": np.int64, "mem_capacity_bytes": np.int64,
    "num_pods": np.int32, "num_nodes": np.int32,
    "num_untainted": np.int32, "num_tainted": np.int32,
    "num_cordoned": np.int32,
}


def zero_state(C: int, G: int, P: int, N: int):
    """Freshly-zeroed host arenas at the given buckets: C+1 tenant rows
    (row C is the scratch tenant), per-row scratch lane on the pod/node
    axes. The (pods, nodes, groups, aggs, prev_cols) tuple feeds
    ``ops.device_state._fleet_step`` directly — the jaxlint registry builds
    its fleet fixture from this too, so the analyzed program is constructed
    exactly like production's."""
    from escalator_tpu.ops import kernel as _kernel

    stack = lambda soa: type(soa)(  # noqa: E731
        **{f.name: np.broadcast_to(
            getattr(soa, f.name), (C + 1,) + getattr(soa, f.name).shape
        ).copy() for f in fields(soa)})
    pods = stack(_empty_pods(P + 1))
    nodes = stack(_empty_nodes(N + 1))
    groups = stack(_empty_groups(G))
    aggs = _kernel.GroupAggregates(
        cpu_req=np.zeros((C + 1, G), np.int64),
        mem_req=np.zeros((C + 1, G), np.int64),
        num_pods=np.zeros((C + 1, G), np.int64),
        cpu_cap=np.zeros((C + 1, G), np.int64),
        mem_cap=np.zeros((C + 1, G), np.int64),
        num_nodes=np.zeros((C + 1, G), np.int64),
        num_untainted=np.zeros((C + 1, G), np.int64),
        num_tainted=np.zeros((C + 1, G), np.int64),
        num_cordoned=np.zeros((C + 1, G), np.int64),
        node_pods_remaining=np.zeros((C + 1, N + 1), np.int64),
        dirty=np.zeros((C + 1, G), bool),
    )
    prev_cols = tuple(np.zeros((C + 1, G), _COL_DTYPES[n])
                      for n in _kernel.GROUP_DECISION_FIELDS)
    return pods, nodes, groups, aggs, prev_cols


def zero_state_sharded(S: int, C: int, G: int, P: int, N: int):
    """:func:`zero_state` with a leading shard axis: ``S`` independent
    ``[C+1, …]`` arena stacks (each shard carries its OWN scratch tenant
    row). Feeds ``device_state.make_fleet_step_sharded`` directly."""
    base = zero_state(C, G, P, N)

    def stack(x):
        if isinstance(x, tuple):
            return tuple(stack(v) for v in x)
        if isinstance(x, np.ndarray):
            return np.broadcast_to(x, (S,) + x.shape).copy()
        return type(x)(**{f.name: stack(getattr(x, f.name))
                          for f in fields(x)})

    return tuple(stack(part) for part in base)


@dataclass
class _Tenant:
    shard: int               # mesh row the tenant's arena slot lives on
    row: int                 # tenant row within the shard (< Cs)
    pods: PodArrays          # host twin at bucket shapes (no scratch lane)
    nodes: NodeArrays
    groups: GroupArrays
    dirty: np.ndarray        # bool [G] — pending dirty groups (host mirror)
    shapes: Tuple[int, int, int]   # the LAST request's (G, P, N) paddings
    ticks: int = 0
    #: round-18 digest fast path: the answer the last dispatch produced for
    #: this tenant (COPIED slices — never views pinning the [S,T,…] batch
    #: output), the full-frame digest that produced it (None when it came
    #: off the delta path), the now it evaluated at, and the arena epoch it
    #: is valid under. Any reshape/rebuild bumps the epoch and the whole
    #: entry goes stale; evict→re-register makes a fresh _Tenant, so a
    #: recycled id can never see the old tenant's columns.
    cache_digest: Optional[bytes] = None
    cache_now: int = 0
    cache_arrays: Optional[object] = None   # kernel.DecisionArrays, numpy
    cache_ordered: bool = False
    cache_epoch: int = -1


@dataclass
class _Entry:
    """One prepared request: everything execute/rollback needs, snapshotted
    at prep time (execute must not read mutable tenant fields — a later
    prep may be rewriting them concurrently)."""

    pos: int
    request: Union[DecideRequest, EvictRequest]
    tenant: _Tenant
    shard: int
    row: int
    shapes: Tuple[int, int, int]
    new_secs: tuple          # (pods, nodes, groups) at arena buckets
    now: int
    pod_slots: np.ndarray
    node_slots: np.ndarray
    dirty_mask: np.ndarray
    tainted_any: bool
    evict: bool
    registered: bool         # this prep created the tenant (rollback: drop)
    # rollback: the twin references this prep replaced (None for evicts —
    # the tenant object itself, still holding its twins, is the rollback)
    old_twins: Optional[tuple]
    old_dirty: Optional[np.ndarray]
    old_shapes: Optional[tuple]
    t_index: int = -1        # position within the shard's batch slice
    #: full-frame request digest (None for delta/evict entries) — written
    #: into the tenant's cache entry after the dispatch answers
    digest: Optional[bytes] = None
    #: delta-path rollback record: (pod_idx, old_pod_rows, node_idx,
    #: old_node_rows, old_groups_or_None) — delta prep scatters into the
    #: live twin IN PLACE, so the undo is the gathered old rows, not a
    #: twin reference swap (old_twins is None for delta entries)
    delta_undo: Optional[tuple] = None


@dataclass
class _PreparedBatch:
    """The output of :meth:`FleetEngine.prepare_batch`: host-assembled
    operands for one micro-batch, valid at ``epoch``. ``results`` already
    carries the per-request TenantErrors; execute fills the rest."""

    epoch: int
    requests: list
    results: list
    entries: List[_Entry]
    operands: Optional[tuple]
    prep_ms: float = 0.0
    #: set by a pipelining scheduler: how much of this prep ran while a
    #: device program was in flight (annotated onto the fleet_batch record)
    overlap_saved_ms: Optional[float] = None
    executed: bool = False
    released: bool = False
    #: request-journey raw material (round 17): the fused dispatch's
    #: monotonic window (device-fenced — dispatch_t1 is read after the
    #: program's outputs landed on host) and the shared journey sink this
    #: batch's fleet_batch record carries. The scheduler appends each
    #: request's finished journey to the sink on the respond side, AFTER
    #: the record is in the ring — list identity is the channel.
    dispatch_t0: float = 0.0
    dispatch_t1: float = 0.0
    journeys: list = field(default_factory=list)


class FleetEngine:
    """Owns the shard-stacked device arenas + host twins for a fleet of
    tenants across a device mesh.

    Mutation concurrency: at most ONE thread may run :meth:`prepare_batch`
    at a time and ONE thread :meth:`execute_batch` (the scheduler's prep +
    dispatch workers), with prepared batches executed in prepare order;
    :meth:`step` is both stages back-to-back for sequential callers. Reads
    like :attr:`tenant_count` are safe from any thread."""

    def __init__(self, num_groups: int = 8, pod_capacity: int = 128,
                 node_capacity: int = 64, max_tenants: int = 8,
                 device=None, num_shards: int = 1,
                 max_group_bucket: int = 1 << 12,
                 max_pod_bucket: int = 1 << 20,
                 max_node_bucket: int = 1 << 18,
                 max_tenant_bucket: int = 1 << 16):
        from escalator_tpu.jaxconfig import guarded_devices
        from escalator_tpu.ops import device_state as ds

        if device is not None:
            devices = [device]
        else:
            devices = list(guarded_devices())
        S = len(devices) if num_shards in (0, None) else int(num_shards)
        if S < 1 or S > len(devices):
            raise ValueError(
                f"num_shards={num_shards} needs 1..{len(devices)} of the "
                f"available devices")
        self._devices = devices[:S]
        self._S = S
        self._mesh = self._make_mesh(self._devices)
        self._step_fn = ds.make_fleet_step_sharded(self._mesh)
        self._order_tail_fn = ds.make_fleet_order_tail_sharded(self._mesh)
        self._G = _pow2(num_groups, 4)
        self._P = _pow2(pod_capacity, 16)
        self._N = _pow2(node_capacity, 8)
        # per-SHARD tenant rows: the pow2 bucket over an even split
        self._C = _pow2(-(-int(max_tenants) // S), 2)
        self._caps = (max_group_bucket, max_pod_bucket, max_node_bucket,
                      max_tenant_bucket)
        self._tenants: Dict[str, _Tenant] = {}
        self._free: List[List[int]] = [list(range(self._C))
                                       for _ in range(S)]
        # lock order: _exec_lock -> _host (condition) -> _device_lock —
        # declared (ranks 20/30/40) in analysis/concurrency.py and enforced
        # by threadlint (static) + the lock witness (ESCALATOR_TPU_LOCK_WITNESS=1)
        self._exec_lock = lockwitness.make_lock("engine.exec")
        self._host = lockwitness.make_condition("engine.host")
        self._device_lock = lockwitness.make_lock("engine.device")
        self._epoch = 0
        self._staged: Optional[_PreparedBatch] = None
        self.batches = 0
        self.decisions = 0
        #: order-consuming tenants served (kept name: it now counts tenants
        #: REPAIRED by the batched tail, not separate device dispatches)
        self.ordered_redispatches = 0
        #: batched order-tail device dispatches (round 18): at most ONE per
        #: micro-batch regardless of how many tenants consume orders
        self.tail_dispatches = 0
        #: requests answered by the digest fast path without entering a
        #: micro-batch
        self.cache_hits = 0
        self._init_state()
        # decision-provenance hook: tenant ids are history keys; the flap
        # dump worker resolves them through this wildcard registration
        # (WeakMethod inside — the engine's lifetime is not extended)
        obs.provenance.register_explainer("*", self._explain_for_provenance)

    # -- arena construction / reshaping --------------------------------------

    @staticmethod
    def _make_mesh(devices):
        from jax.sharding import Mesh

        from escalator_tpu.ops import device_state as ds

        return Mesh(np.array(devices), (ds.FLEET_SHARD_AXIS,))

    @property
    def _sharding(self):
        from jax.sharding import NamedSharding, PartitionSpec

        from escalator_tpu.ops import device_state as ds

        return NamedSharding(self._mesh, PartitionSpec(ds.FLEET_SHARD_AXIS))

    def _host_zero_state(self, C: int, G: int, P: int, N: int):
        return zero_state_sharded(self._S, C, G, P, N)

    def _init_state(self) -> None:
        import jax

        from escalator_tpu.observability import resources
        from escalator_tpu.ops import device_state as _ds  # noqa: F401
        # (importing device_state registers the SoA dataclasses as pytrees
        # — device_put on PodArrays/NodeArrays/GroupArrays needs them)
        self._state = jax.device_put(
            self._host_zero_state(self._C, self._G, self._P, self._N),
            self._sharding)
        # HBM accounting: the shard-stacked arenas are ONE owner whose
        # budget is the docs/fleet.md capacity-envelope formula at the
        # CURRENT buckets, times the shard count (each shard adds its own
        # scratch row); a grow/compact moves the envelope with the arrays
        resources.RESOURCES.register(
            "fleet_arenas", self, lambda e: e._state,
            budget=lambda e: e._S * resources.expected_fleet_arena_bytes(
                e._C, e._G, e._P, e._N))

    def _pull_state(self):
        """D2H copy of the arenas (the reshape paths' staging buffers)."""
        from jax import tree_util

        return tree_util.tree_map(np.asarray, self._state)

    def _await_staged_drain(self) -> None:
        """Wait (releasing ``_host``) until no prepared batch is
        outstanding at the CURRENT epoch — arena reshapes must not pull the
        rug from under operands staged at the old buckets. A stale staged
        batch (epoch behind, arenas already rebuilt) is skipped: execute
        discards it with StaleBatchError rather than running it."""
        while True:
            st = self._staged
            if st is None or st.released or st.executed:
                return
            if st.epoch != self._epoch and self._state is not None:
                return
            self._host.wait(timeout=0.1)

    def _grow(self, G2: int, P2: int, N2: int, C2: int) -> None:
        """Grow the arenas to new buckets: copy the leading real lanes/rows
        of every shard into freshly-zeroed arrays (pad values are
        position-invariant, so the old scratch lane/rows are reproduced by
        construction) and re-upload. O(arena) host work — rare by design:
        buckets double. Caller holds ``_host``; waits out any staged batch
        and bumps the epoch."""
        import jax

        cap_g, cap_p, cap_n, cap_c = self._caps
        if G2 > cap_g or P2 > cap_p or N2 > cap_n or C2 * self._S > cap_c:
            raise TenantError(
                f"fleet arena bucket cap exceeded: need (G={G2}, P={P2}, "
                f"N={N2}, C={C2 * self._S}) caps (G={cap_g}, P={cap_p}, "
                f"N={cap_n}, C={cap_c})")
        self._await_staged_drain()
        C, G, P, N = self._C, self._G, self._P, self._N
        with self._device_lock:
            old = self._pull_state()
            new = self._host_zero_state(C2, G2, P2, N2)

            def copy_soa(dst, src, lanes):
                for f in fields(dst):
                    getattr(dst, f.name)[:, : C + 1, :lanes] = \
                        getattr(src, f.name)[:, :, :lanes]

            pods_o, nodes_o, groups_o, aggs_o, cols_o = old
            pods_n, nodes_n, groups_n, aggs_n, cols_n = new
            copy_soa(pods_n, pods_o, P)     # real lanes; scratch lane = pad
            copy_soa(nodes_n, nodes_o, N)
            copy_soa(groups_n, groups_o, G)
            for f in fields(type(aggs_n)):
                dst, src = getattr(aggs_n, f.name), getattr(aggs_o, f.name)
                # node_pods_remaining copies its real lanes only (the old
                # scratch lane holds 0, the new arrays' default); [G]
                # columns copy whole (G2 >= G)
                lanes = N if f.name == "node_pods_remaining" else src.shape[2]
                dst[:, : C + 1, :lanes] = src[:, :, :lanes]
            for dst, src in zip(cols_n, cols_o, strict=True):
                dst[:, : C + 1, :G] = src
            # each shard's old scratch row (index C) carried pad values
            # only, so landing it at row C of the new stack is harmless;
            # rows C..C2 start as fresh scratch/empty rows either way.
            self._state = jax.device_put(new, self._sharding)
        if G2 != G:
            # new group rows exist for every tenant now; their persistent
            # columns are zeros, not a computed decision — recompute
            # everything at the next touch (superset-dirty is parity-safe)
            for t in self._tenants.values():
                t.dirty = np.ones(G2, bool)
        for t in self._tenants.values():
            t.pods = _repad(t.pods, P2, _empty_pods)
            t.nodes = _repad(t.nodes, N2, _empty_nodes)
            t.groups = _repad(t.groups, G2, _empty_groups)
            if len(t.dirty) != G2:
                d = np.zeros(G2, bool)
                d[: len(t.dirty)] = t.dirty
                t.dirty = d
        if C2 != C:
            for s in range(self._S):
                self._free[s].extend(range(C, C2))
        self._G, self._P, self._N, self._C = G2, P2, N2, C2
        self._epoch += 1
        # arena lifecycle visibility (round 15): a grow silently doubled
        # resident HBM before this — now it counts, annotates the
        # fleet_batch/fleet_prep flight record it happened under, and moves
        # the registered fleet_arenas owner bytes + budget in the same tick
        metrics.fleet_arena_grows.inc()
        obs.annotate(fleet_arena_grow=(
            f"G={G2} P={P2} N={N2} C={C2 * self._S}"))
        obs.journal.JOURNAL.event(
            "fleet-arena-grow", groups=G2, pods=P2, nodes=N2,
            tenants=C2 * self._S, epoch=self._epoch)
        log.info("fleet arena grown to G=%d P=%d N=%d C=%d (x%d shards)",
                 G2, P2, N2, C2, self._S)

    def compact(self) -> dict:
        """Repack live tenants round-robin across the shards' leading rows
        and shrink the tenant axis to the smallest power-of-two bucket that
        holds them — the post-mass-eviction memory reclaim. Lane buckets
        are left alone (shrinking them would force every tenant's twin
        through a repad for marginal HBM). Returns {tenants, old_c,
        new_c} (tenant-row counts summed over shards)."""
        # own span root: compact runs OUTSIDE any batch (an operator or
        # maintenance call), and annotate() is a no-op without a timeline
        # — without this the advertised fleet_arena_compact annotation
        # could never reach a flight record.
        # Drain-then-lock loop: waiting for the staged batch WHILE holding
        # _exec_lock would deadlock — the execute that drains it needs
        # that very lock. So wait under _host alone, then take the locks
        # and re-check nothing re-staged in the window.
        with obs.span("fleet_compact"):
            # bounded: under continuous pipelined traffic the prep thread
            # can re-stage a batch in the drain->lock window every round,
            # so an unbounded loop could spin forever — fail the admin
            # call instead of wedging it (the caller retries off-peak or
            # pauses the scheduler first)
            deadline = time.monotonic() + 30.0
            while True:
                with self._host:
                    self._await_staged_drain()
                with self._exec_lock, self._host:
                    st = self._staged
                    if (st is None or st.executed or st.released
                            or st.epoch != self._epoch):
                        return self._compact_locked()
                if time.monotonic() > deadline:
                    raise RuntimeError(
                        "fleet compact timed out: a staged batch kept "
                        "re-appearing for 30 s (continuous pipelined "
                        "traffic) — pause the scheduler and retry")

    def _compact_locked(self) -> dict:
        """Caller holds ``_exec_lock`` + ``_host`` with no live staged
        batch."""
        import jax
        from jax import tree_util

        live = sorted(self._tenants.values(),
                      key=lambda t: (t.shard, t.row))
        C2 = _pow2(-(-len(live) // self._S), 2)
        old_c = self._C * self._S
        with self._device_lock:
            old = self._pull_state()
            new = self._host_zero_state(C2, self._G, self._P, self._N)
            placement = [(t, i % self._S, i // self._S)
                         for i, t in enumerate(live)]

            def place(dst_tree, src_tree):
                for f_dst, f_src in zip(
                        tree_util.tree_leaves(dst_tree),
                        tree_util.tree_leaves(src_tree), strict=True):
                    for t, s2, r2 in placement:
                        f_dst[s2, r2] = f_src[t.shard, t.row]

            for dst, src in zip(new, old, strict=True):
                place(dst, src)
            self._state = jax.device_put(new, self._sharding)
        for t, s2, r2 in placement:
            t.shard, t.row = s2, r2
        used = [0] * self._S
        for t in live:
            used[t.shard] += 1
        self._free = [list(range(used[s], C2)) for s in range(self._S)]
        self._C = C2
        self._epoch += 1
        metrics.fleet_arena_compacts.inc()
        obs.annotate(fleet_arena_compact=f"C={old_c}->{C2 * self._S}")
        obs.journal.JOURNAL.event(
            "fleet-arena-compact", tenants=len(live), old_c=old_c,
            new_c=C2 * self._S, epoch=self._epoch)
        log.info("fleet arena compacted: %d tenants, C %d -> %d",
                 len(live), old_c, C2 * self._S)
        return {"tenants": len(live), "old_c": old_c,
                "new_c": C2 * self._S}

    # -- tenant lifecycle ----------------------------------------------------

    @property
    def tenant_count(self) -> int:
        return len(self._tenants)

    @property
    def shards(self) -> int:
        return self._S

    @property
    def buckets(self) -> dict:
        return {"groups": self._G, "pods": self._P, "nodes": self._N,
                "tenants": self._C * self._S,
                "tenant_rows_per_shard": self._C, "shards": self._S}

    def has_tenant(self, tenant_id: str) -> bool:
        return tenant_id in self._tenants

    def shard_of(self, tenant_id: str) -> Optional[int]:
        t = self._tenants.get(tenant_id)
        return None if t is None else t.shard

    def _register(self, tenant_id: str) -> _Tenant:
        if not any(self._free):
            self._grow(self._G, self._P, self._N, self._C * 2)
        # balance: the shard with the most free rows (ties -> lowest id)
        shard = max(range(self._S), key=lambda s: (len(self._free[s]), -s))
        t = _Tenant(
            shard=shard, row=self._free[shard].pop(0),
            pods=_empty_pods(self._P), nodes=_empty_nodes(self._N),
            groups=_empty_groups(self._G),
            # bootstrap: EVERY group row computes on the first decide, so
            # invalid/padding rows carry real NOOP_EMPTY decisions rather
            # than the arena's zero-initialized columns
            dirty=np.ones(self._G, bool),
            shapes=(self._G, self._P, self._N),
        )
        self._tenants[tenant_id] = t
        metrics.fleet_tenant_count.set(len(self._tenants))
        obs.journal.JOURNAL.event("fleet-tenant-register", tenant=tenant_id,
                                  shard=t.shard, row=t.row)
        return t

    def _ensure_buckets(self, cluster: ClusterArrays) -> None:
        G_c = int(cluster.groups.valid.shape[0])
        P_c = int(cluster.pods.valid.shape[0])
        N_c = int(cluster.nodes.valid.shape[0])
        if G_c > self._G or P_c > self._P or N_c > self._N:
            self._grow(max(self._G, _pow2(G_c, 4)),
                       max(self._P, _pow2(P_c, 16)),
                       max(self._N, _pow2(N_c, 8)), self._C)

    # -- stage 1: host prep ---------------------------------------------------

    def prepare_batch(self, requests: Sequence[Union[DecideRequest,
                                                     EvictRequest]]
                      ) -> _PreparedBatch:
        """All host work for one micro-batch: validation, tenant lifecycle
        (register/evict slot moves), per-tenant positional diff, dirty
        bookkeeping, twin adoption, and operand assembly. No device access
        (an arena grow is the one exception — it drains any staged batch
        first). At most one request per tenant (the scheduler's coalescing
        guarantees it; direct callers must too). The returned batch is
        registered as the engine's staged batch until executed or
        released."""
        seen = set()
        for r in requests:
            if r.tenant_id in seen:
                raise ValueError(
                    f"duplicate tenant {r.tenant_id!r} in one micro-batch")
            seen.add(r.tenant_id)
        t0 = time.perf_counter()
        results: List[object] = [None] * len(requests)
        entries: List[_Entry] = []
        journeys: list = []
        with obs.span("fleet_prep"), self._host:
            with obs.span("fleet_diff"):
                # pass 1: grow the lane buckets for EVERY request up front —
                # a grow mid-batch would invalidate sections staged at the
                # old shapes (a cap breach rejects that request alone).
                # Delta frames never grow (growth requires a full frame —
                # _prepare_entry rejects an oversized one per request).
                for pos, r in enumerate(requests):
                    if (isinstance(r, EvictRequest)
                            or getattr(r, "delta", None) is not None):
                        continue
                    try:
                        self._ensure_buckets(r.cluster)
                    except TenantError as e:
                        results[pos] = e
                pending_free: List[Tuple[int, int]] = []
                try:
                    for pos, r in enumerate(requests):
                        if results[pos] is not None:
                            continue
                        try:
                            digest = None
                            if not isinstance(r, EvictRequest):
                                digest, hit = self._cache_probe(r)
                                if hit:
                                    results[pos] = self._cache_answer(
                                        r, journeys)
                                    continue
                            entries.append(self._prepare_entry(
                                pos, r, pending_free, digest))
                        except TenantError as e:
                            results[pos] = e
                    operands = (self._assemble(entries) if entries
                                else None)
                except BaseException:
                    # a non-TenantError escape (a device error inside a
                    # register-grow, an assembly failure) must not leave
                    # the engine half-prepared: earlier entries' twins
                    # were already adopted and evicted tenants already
                    # popped — unwind them through the same per-entry
                    # rollback records release_prepared uses (evict rows
                    # were never flushed into _free, so the resurrect
                    # path's membership guard holds), then re-raise so
                    # the caller fails the whole batch
                    for e in reversed(entries):
                        self._rollback_entry(e)
                    metrics.fleet_tenant_count.set(len(self._tenants))
                    raise
                # evicted rows become reusable for the NEXT prepare only —
                # same-batch reuse would put two batch entries on one
                # arena row (scatter order between them is undefined)
                for shard, row in pending_free:
                    self._free[shard].append(row)
                    self._free[shard].sort()
            pb = _PreparedBatch(
                epoch=self._epoch, requests=list(requests), results=results,
                entries=entries, operands=operands,
                prep_ms=(time.perf_counter() - t0) * 1e3,
                journeys=journeys)
            self._staged = pb
        return pb

    # -- the digest fast path (round 18) --------------------------------------

    def _cache_probe(self, r: DecideRequest
                     ) -> Tuple[Optional[bytes], bool]:
        """(digest, hit) for one decide request; caller holds ``_host``.
        A hit means the tenant's cached decision columns are bit-equal to
        what a dispatch would produce: same input content at the same
        ``now_sec`` under the same arena epoch (decide is deterministic in
        content + now; an unchanged tenant's persistent columns survive a
        dispatch untouched, and the ordered tail recomputes
        deterministically). A full frame matches by content digest; a delta
        frame matches only when EMPTY (no changed slots, no group reload)
        at the cached now. The ``fleet_digest`` chaos site forces a miss —
        the request then rides the batch and the soak's bit-parity check
        proves the cache would have answered identically."""
        tenant = self._tenants.get(r.tenant_id)
        delta = getattr(r, "delta", None)
        if delta is not None:
            digest = None
            hit = (tenant is not None
                   and tenant.cache_arrays is not None
                   and tenant.cache_epoch == self._epoch
                   and int(r.now_sec) == tenant.cache_now
                   and not tenant.dirty.any()
                   and len(np.asarray(delta.pod_idx)) == 0
                   and len(np.asarray(delta.node_idx)) == 0
                   and delta.groups is None
                   and tuple(delta.shapes) == tuple(tenant.shapes))
        else:
            digest = _request_digest(r.cluster, r.now_sec)
            hit = (tenant is not None
                   and tenant.cache_arrays is not None
                   and tenant.cache_epoch == self._epoch
                   and tenant.cache_digest == digest
                   and not tenant.dirty.any())
        if hit:
            from escalator_tpu.chaos import CHAOS

            if CHAOS.should_fire("fleet_digest"):
                hit = False
        return digest, hit

    def _cache_answer(self, r: DecideRequest, journeys: list
                      ) -> FleetDecision:
        """Serve one digest hit from the tenant's cached columns — no
        entry, no batch slot, no device work. ``batch_size`` is 0: the
        request rode no micro-batch."""
        t = self._tenants[r.tenant_id]
        self.cache_hits += 1
        obs.journal.JOURNAL.event(
            "fleet-cache-hit", tenant=r.tenant_id, now=int(r.now_sec))
        # the cached answer IS this tick's decision — feed the history/flap
        # watchdog the same columns a dispatch would have staged, so the
        # digest fast path cannot blind the oscillation detector
        obs.provenance.stage(
            r.tenant_id, np.array(t.cache_arrays.status),
            np.array(t.cache_arrays.nodes_delta), tick=t.ticks)
        return FleetDecision(
            tenant_id=r.tenant_id, arrays=t.cache_arrays,
            ordered=t.cache_ordered, batch_size=0, shard=t.shard,
            cached=True, stages={"sink": journeys})

    def _prepare_entry(self, pos: int, r, pending_free,
                       digest: Optional[bytes] = None) -> _Entry:
        """Validate + stage one request: resolve its tenant (registering a
        new one / unregistering an evict), diff against the host twin, fold
        the dirty mask, ADOPT the new twins (rollback records kept), and
        return the entry execute will slice. A delta-frame request skips
        the positional diff entirely (:meth:`_prepare_delta_entry`)."""
        validate_tenant_id(r.tenant_id)
        if getattr(r, "delta", None) is not None:
            return self._prepare_delta_entry(pos, r)
        evict = isinstance(r, EvictRequest)
        registered = False
        if evict:
            tenant = self._tenants.pop(r.tenant_id, None)
            if tenant is None:
                raise TenantError(f"unknown tenant {r.tenant_id!r}")
            metrics.fleet_tenant_count.set(len(self._tenants))
            obs.journal.JOURNAL.event(
                "fleet-tenant-evict", tenant=r.tenant_id,
                shard=tenant.shard, row=tenant.row)
            # eviction is a decide against the EMPTY cluster: every valid
            # lane clears, aggregates fall to zero, the slot frees after
            new_p, new_n, new_g = (_empty_pods(self._P),
                                   _empty_nodes(self._N),
                                   _empty_groups(self._G))
            now = 0
            pending_free.append((tenant.shard, tenant.row))
        else:
            tenant = self._tenants.get(r.tenant_id)
            if tenant is None:
                tenant = self._register(r.tenant_id)
                registered = True
            new_p = _repad_copy(r.cluster.pods, self._P, _empty_pods)
            new_n = _repad_copy(r.cluster.nodes, self._N, _empty_nodes)
            new_g = _repad_copy(r.cluster.groups, self._G, _empty_groups)
            now = int(r.now_sec)
        old_twins = (tenant.pods, tenant.nodes, tenant.groups)
        old_dirty = tenant.dirty
        old_shapes = tenant.shapes
        pod_slots = _changed_rows(tenant.pods, new_p)
        node_slots = _changed_rows(tenant.nodes, new_n)
        # dirty-group bookkeeping (host mirror, superset-safe): groups any
        # changed lane pointed at — before OR after — plus every group row
        # that changed
        G = self._G
        touched = old_dirty.copy()
        for soa, slots in ((tenant.pods, pod_slots), (new_p, pod_slots),
                           (tenant.nodes, node_slots), (new_n, node_slots)):
            gids = np.asarray(soa.group)[slots]
            touched[np.clip(gids, 0, G - 1)] = True
        touched[_changed_rows(tenant.groups, new_g)] = True
        # adopt the twins NOW (prep time): the diff for the NEXT batch must
        # run against this request's content even while this batch is still
        # in flight — in-order execution makes the device catch up first
        tenant.pods, tenant.nodes, tenant.groups = new_p, new_n, new_g
        tenant.dirty = np.zeros(G, bool)
        tenant.ticks += 1
        if not evict:
            tenant.shapes = (
                int(r.cluster.groups.valid.shape[0]),
                int(r.cluster.pods.valid.shape[0]),
                int(r.cluster.nodes.valid.shape[0]),
            )
        tainted_any = bool((np.asarray(new_n.valid)
                            & np.asarray(new_n.tainted)).any())
        return _Entry(
            pos=pos, request=r, tenant=tenant, shard=tenant.shard,
            row=tenant.row, shapes=tenant.shapes,
            new_secs=(new_p, new_n, new_g), now=now,
            pod_slots=pod_slots, node_slots=node_slots, dirty_mask=touched,
            tainted_any=tainted_any, evict=evict, registered=registered,
            old_twins=old_twins, old_dirty=old_dirty, old_shapes=old_shapes,
            digest=digest)

    def _prepare_delta_entry(self, pos: int, r: DecideRequest) -> _Entry:
        """Stage one STREAMED request (round 18): scatter the client's
        packed dirty drain straight into the tenant's live twin — the
        changed-slot lists ARE the delta batch, so no O(cluster) positional
        diff runs. The undo record is the gathered old rows (the twin
        mutates in place; a later prep may swap the twin REFERENCES, but
        in-order execution plus the depth-1 pipeline mean at most this one
        staged batch can need unwinding, and its undo targets the arrays it
        scattered into)."""
        delta = r.delta
        tenant = self._tenants.get(r.tenant_id)
        if tenant is None:
            raise TenantError(
                f"tenant {r.tenant_id!r} sent a delta frame before any "
                "full frame; send a full frame first")
        G_c, P_c, N_c = (int(x) for x in delta.shapes)
        if G_c > self._G or P_c > self._P or N_c > self._N:
            raise TenantError(
                f"delta frame shapes (G={G_c}, P={P_c}, N={N_c}) exceed "
                f"the arena buckets (G={self._G}, P={self._P}, "
                f"N={self._N}); arena growth requires a full frame")
        pod_idx = np.asarray(delta.pod_idx, np.int64).ravel()
        node_idx = np.asarray(delta.node_idx, np.int64).ravel()
        for name, idx, cap in (("pod", pod_idx, self._P),
                               ("node", node_idx, self._N)):
            if idx.size and (int(idx.min()) < 0 or int(idx.max()) >= cap):
                raise TenantError(
                    f"delta frame {name} slot out of range (bucket {cap})")
        old_dirty = tenant.dirty
        old_shapes = tenant.shapes
        G = self._G
        # undo = the old rows at the scattered slots, gathered BEFORE the
        # scatter; plus the old groups reference when the section reloads
        gather = lambda soa, idx: type(soa)(  # noqa: E731
            **{f.name: np.array(np.asarray(getattr(soa, f.name))[idx])
               for f in fields(soa)})
        undo_p = gather(tenant.pods, pod_idx)
        undo_n = gather(tenant.nodes, node_idx)
        # dirty-group bookkeeping, identical superset rule to the diff
        # path: groups the changed slots pointed at before OR after, plus
        # every changed group row when the section reloads
        touched = old_dirty.copy()
        for soa, idx in ((tenant.pods, pod_idx), (tenant.nodes, node_idx)):
            gids = np.asarray(soa.group)[idx]
            touched[np.clip(gids, 0, G - 1)] = True
        for vals, idx in ((delta.pod_vals, pod_idx),
                          (delta.node_vals, node_idx)):
            gids = np.asarray(vals.group)[: len(idx)]
            touched[np.clip(gids, 0, G - 1)] = True
        old_groups = None
        if delta.groups is not None:
            new_g = _repad_copy(delta.groups, G, _empty_groups)
            touched[_changed_rows(tenant.groups, new_g)] = True
            old_groups = tenant.groups
            tenant.groups = new_g
        # scatter the drain into the live twin (in place — the adopt)
        for f in fields(tenant.pods):
            np.asarray(getattr(tenant.pods, f.name))[pod_idx] = \
                np.asarray(getattr(delta.pod_vals, f.name))[: len(pod_idx)]
        for f in fields(tenant.nodes):
            np.asarray(getattr(tenant.nodes, f.name))[node_idx] = \
                np.asarray(getattr(delta.node_vals, f.name))[: len(node_idx)]
        tenant.dirty = np.zeros(G, bool)
        tenant.ticks += 1
        tenant.shapes = (G_c, P_c, N_c)
        tainted_any = bool((np.asarray(tenant.nodes.valid)
                            & np.asarray(tenant.nodes.tainted)).any())
        return _Entry(
            pos=pos, request=r, tenant=tenant, shard=tenant.shard,
            row=tenant.row, shapes=tenant.shapes,
            new_secs=(tenant.pods, tenant.nodes, tenant.groups),
            now=int(r.now_sec), pod_slots=pod_idx, node_slots=node_idx,
            dirty_mask=touched, tainted_any=tainted_any, evict=False,
            registered=False, old_twins=None, old_dirty=old_dirty,
            old_shapes=old_shapes,
            delta_undo=(pod_idx, undo_p, node_idx, undo_n, old_groups))

    def _assemble(self, entries: List[_Entry]) -> tuple:
        """Build the ``[S, T, …]`` batched operands: each entry lands in
        ITS shard's batch slice; shards with fewer (or no) entries pad with
        scratch-row no-ops. Buckets: lane batches pad to the shared
        ``statestore.delta_bucket`` widths, dirty rows to the shared
        ``kernel.fleet_dirty_bucket`` width, the per-shard batch width to a
        power of two — so the jit cache keys on a handful of bucket shapes,
        never on batch content."""
        from escalator_tpu.ops import device_state as ds
        from escalator_tpu.ops import kernel as _kernel

        G, P, N, C, S = self._G, self._P, self._N, self._C, self._S
        per_shard: List[List[_Entry]] = [[] for _ in range(S)]
        for e in entries:
            e.t_index = len(per_shard[e.shard])
            per_shard[e.shard].append(e)
        T = _pow2(max(len(lst) for lst in per_shard))
        B_pod = delta_bucket(max(len(e.pod_slots) for e in entries))
        B_node = delta_bucket(max(len(e.node_slots) for e in entries))
        rows = np.full((S, T), C, np.int32)
        nows = np.zeros((S, T), np.int64)
        pod_idx = np.full((S, T, B_pod), P, np.int32)
        node_idx = np.full((S, T, B_node), N, np.int32)
        dirty_stack = np.zeros((S, T, G), bool)
        # preallocate the value stacks from the pad gather (no-op entries
        # carry exactly these values)
        _, pv0 = ds._gather_padded(
            _empty_pods(0), np.zeros(0, np.int64), B_pod, P, ds._POD_PAD)
        _, nv0 = ds._gather_padded(
            _empty_nodes(0), np.zeros(0, np.int64), B_node, N, ds._NODE_PAD)
        bstack = lambda soa, lead: type(soa)(  # noqa: E731
            **{f.name: np.broadcast_to(
                getattr(soa, f.name), lead + getattr(soa, f.name).shape
            ).copy() for f in fields(soa)})
        pod_vals = bstack(pv0, (S, T))
        node_vals = bstack(nv0, (S, T))
        groups_new = bstack(_empty_groups(G), (S, T))
        for s, lst in enumerate(per_shard):
            for t, e in enumerate(lst):
                rows[s, t] = e.row
                nows[s, t] = e.now
                new_p, new_n, new_g = e.new_secs
                pi, pv = ds._gather_padded(new_p, e.pod_slots, B_pod, P,
                                           ds._POD_PAD)
                ni, nv = ds._gather_padded(new_n, e.node_slots, B_node, N,
                                           ds._NODE_PAD)
                pod_idx[s, t], node_idx[s, t] = pi, ni
                for f in fields(pv):
                    getattr(pod_vals, f.name)[s, t] = getattr(pv, f.name)
                for f in fields(nv):
                    getattr(node_vals, f.name)[s, t] = getattr(nv, f.name)
                for f in fields(new_g):
                    getattr(groups_new, f.name)[s, t] = getattr(new_g, f.name)
                dirty_stack[s, t] = e.dirty_mask
        dirty_idx = _kernel.fleet_dirty_indices_stacked(dirty_stack, G)
        return (rows, groups_new, pod_idx, pod_vals, node_idx, node_vals,
                dirty_idx, nows)

    # -- stage 2: the device dispatch -----------------------------------------

    def execute_batch(self, pb: _PreparedBatch
                      ) -> List[Union[FleetDecision, EvictAck, Exception]]:
        """Run one prepared batch: the ONE fused sharded device program,
        per-tenant unpack, and ordered tails. A batch gone stale (epoch
        behind — only the dispatch-failure rebuild can do this, since
        grows/compacts DRAIN the staged batch before reshaping) fails with
        :class:`StaleBatchError` instead of re-preparing: a re-prepare
        from this (dispatch) thread would race the scheduler's prep
        thread and break the in-order prepare→execute invariant the twins
        depend on. The twins were already reset wholesale by the rebuild,
        so there is nothing to roll back — the scheduler surfaces the
        error per request and clients resubmit."""
        # UNLOCKED epoch read by design: taking _host here deadlocks
        # against a grow waiting (under _host) for THIS batch to drain
        if pb.epoch != self._epoch:
            self._discard_stale(pb)
        with self._exec_lock:
            if pb.epoch != self._epoch:
                self._discard_stale(pb)
            return self._execute_locked(pb)

    def _discard_stale(self, pb: _PreparedBatch) -> None:
        with self._host:
            pb.released = True
            if self._staged is pb:
                self._staged = None
            self._host.notify_all()
        obs.journal.JOURNAL.event(
            "fleet-stale-batch", batch_epoch=pb.epoch, epoch=self._epoch,
            requests=len(pb.requests))
        raise StaleBatchError(
            "prepared fleet batch went stale (arenas rebuilt after a "
            "dispatch failure); resubmit the requests")

    def _execute_locked(self, pb: _PreparedBatch) -> list:
        from escalator_tpu.ops import device_state as ds
        from escalator_tpu.ops import kernel as _kernel

        results = pb.results
        try:
            with obs.span("fleet_batch"):
                obs.annotate(backend="fleet", batch_size=len(pb.entries),
                             fleet_shards=self._S,
                             overlap_host_ms=round(pb.prep_ms, 3))
                if pb.overlap_saved_ms is not None:
                    obs.annotate(
                        overlap_saved_ms=round(pb.overlap_saved_ms, 3))
                    metrics.fleet_overlap_saved_ms.inc(
                        max(pb.overlap_saved_ms, 0.0))
                # journey anchoring (round 17): the record carries the
                # shared journey sink (the scheduler appends finished
                # journeys after completion) plus the monotonic time of
                # this record's root open, so the trace exporter can lay
                # journey slices out in record time. One clock-pair read
                # per batch, not per request.
                tl = obs.current_timeline()
                if tl is not None:
                    obs.annotate(
                        journeys=pb.journeys,
                        journey_mono_t0=round(
                            time.monotonic()
                            - (time.perf_counter() - tl.t0), 6))
                if pb.entries:
                    pb.dispatch_t0 = time.monotonic()
                    out_host = self._dispatch(pb, ds)
                    # read AFTER _dispatch's host conversion blocked on the
                    # program: the window is device time, not dispatch time
                    pb.dispatch_t1 = time.monotonic()
                    order_pending: list = []
                    with obs.span("fleet_unpack"):
                        for e in pb.entries:
                            results[e.pos] = self._finish(
                                e, pb, out_host, len(pb.entries),
                                _kernel, order_pending)
                    if order_pending:
                        self._batched_order_tail(order_pending, _kernel)
                    self._write_cache(pb)
                self.batches += 1
                obs.annotate(
                    tenants=[r.tenant_id for r in pb.requests],
                    fleet_tenants_resident=len(self._tenants))
        finally:
            pb.executed = True
            with self._host:
                if self._staged is pb:
                    self._staged = None
                self._host.notify_all()
        return results

    def _dispatch(self, pb: _PreparedBatch, ds) -> dict:
        """The one fused sharded device program; adopts the returned arenas
        and returns the batch outputs as host arrays ``[S, T, …]``."""
        try:
            with obs.span("fleet_step", kind="device"), self._device_lock:
                state = self._state
                self._state = None   # donated — the refs die here
                state2, out = self._step_fn(*state, *pb.operands)
                self._state = state2
                # fence before the host conversion: marks the fleet_step
                # span device-fenced (the journey's dispatch stage quotes
                # this window as device time) — the np.asarray reads below
                # would block anyway, the fence makes the flag honest
                obs.fence(out)
                return {
                    f.name: np.asarray(getattr(out, f.name))
                    for f in fields(out)
                }
        except BaseException:
            # the donation may already have consumed the old buffers, so
            # the pre-dispatch state is unrecoverable — rebuild the arenas
            # from scratch and force every tenant through a full
            # re-bootstrap (the host twins reset to empty, so each tenant's
            # next diff re-uploads all its lanes). The batch still fails
            # (the scheduler surfaces it per request), but the NEXT batch
            # serves instead of unpacking None forever.
            log.exception(
                "fleet_step dispatch failed; rebuilding the arenas — "
                "every tenant re-bootstraps on its next decide")
            obs.journal.JOURNAL.event(
                "fleet-rebuild", tenants=len(self._tenants),
                epoch=self._epoch, requests=len(pb.requests))
            # epoch bump UNLOCKED first: a drain-waiter inside a grow can
            # classify any staged batch stale without waiting on the
            # rebuild below
            # threadlint: waive[T3] deliberate unlocked bump (see above)
            self._epoch += 1
            with self._host:
                with self._device_lock:
                    self._init_state()
                for t in self._tenants.values():
                    t.pods = _empty_pods(self._P)
                    t.nodes = _empty_nodes(self._N)
                    t.groups = _empty_groups(self._G)
                    t.dirty = np.ones(self._G, bool)
                    t.cache_digest, t.cache_arrays = None, None
                self._epoch += 1
                if self._staged is pb:
                    self._staged = None
                self._host.notify_all()
            raise

    def _finish(self, e: _Entry, pb: _PreparedBatch, out_host, batch_size,
                _kernel, order_pending: list):
        """Slice the entry's ``[shard, t]`` batch row back to its request's
        shapes. An order-consuming tenant (tainted nodes exist / some group
        scales down) is queued on ``order_pending`` — the batched tail
        (:meth:`_batched_order_tail`) grafts its real orders in ONE extra
        dispatch per micro-batch after the unpack loop."""
        if e.evict:
            # slot freeing happened at prep (visible to the next prepare);
            # the ack just confirms the zeroing dispatch went out
            return EvictAck(tenant_id=e.request.tenant_id)
        G_c, _P_c, N_c = e.shapes
        sliced = {}
        for f in fields(_kernel.DecisionArrays):
            col = out_host[f.name][e.shard, e.t_index]
            if f.name in ("untainted_offsets", "tainted_offsets"):
                sliced[f.name] = col[: G_c + 1]
            elif f.name in _kernel.GROUP_DECISION_FIELDS:
                sliced[f.name] = col[:G_c]
            else:
                sliced[f.name] = col[:N_c]
        out = _kernel.DecisionArrays(**sliced)
        self.decisions += 1
        # provenance feed: the sliced columns are ALREADY host numpy (no
        # extra device sync); copied so the history ring never pins the
        # whole [S, T, …] batch output through a view
        obs.provenance.stage(
            e.request.tenant_id, np.array(sliced["status"]),
            np.array(sliced["nodes_delta"]), tick=e.tenant.ticks)
        dec = FleetDecision(
            tenant_id=e.request.tenant_id, arrays=out, ordered=False,
            batch_size=batch_size, shard=e.shard,
            # journey raw material: the batch's fenced dispatch window,
            # the batched ordered-tail cost when this tenant consumed it
            # (grafted below; other tenants' tail lands in the request's
            # unpack stage — real wait time on this thread), and the
            # record's journey sink
            stages={"dispatch_t0": pb.dispatch_t0,
                    "dispatch_t1": pb.dispatch_t1,
                    "ordered_tail_ms": 0.0,
                    "sink": pb.journeys})
        if e.tainted_any or bool((sliced["nodes_delta"] < 0).any()):
            order_pending.append((e, dec))
        return dec

    def _batched_order_tail(self, order_pending: list, _kernel) -> None:
        """The lazy protocol's ordered tail for EVERY order-consuming
        tenant of the micro-batch, as ONE fused dispatch (round 18 —
        replaces the per-tenant ``fleet_shard_local`` + ordered
        ``decide_jit`` re-dispatch, which paid an O(arena)-gather cost per
        draining tenant): each shard vmaps the kernel's exact ordered
        branch (``ops.order_tail`` keys + the single 4-key sort) over its
        order-needing rows, fed the RESIDENT post-step nodes/groups/
        aggregates — the same inputs the ordered re-dispatch read — so the
        grafted ``untaint_order``/``scale_down_order`` are bit-identical
        to a standalone ordered decide (every other field already is, per
        ``decide``'s with_orders contract). Rows pad to the shared
        ``kernel.fleet_order_bucket`` width with scratch-row no-ops, so
        the jit cache keys on bucket shapes alone."""
        t_tail = time.monotonic()
        S, C = self._S, self._C
        counts = [0] * S
        for e, _dec in order_pending:
            counts[e.shard] += 1
        T2 = _kernel.fleet_order_bucket(max(counts), C + 1)
        rows = np.full((S, T2), C, np.int32)
        slot = [0] * S
        placed = []
        for e, dec in order_pending:
            k = slot[e.shard]
            slot[e.shard] += 1
            rows[e.shard, k] = e.row
            placed.append((e, dec, k))
        with obs.span("fleet_order_tail", kind="device"), \
                self._device_lock:
            pods, nodes, groups, aggs, _cols = self._state
            unt, sdn = self._order_tail_fn(nodes, groups, aggs, rows)
            obs.fence((unt, sdn))
            unt, sdn = np.asarray(unt), np.asarray(sdn)
        tail_ms = (time.monotonic() - t_tail) * 1e3
        self.tail_dispatches += 1
        metrics.fleet_tail_batch_size.observe(len(order_pending))
        from dataclasses import replace as _dc_replace

        for e, dec, k in placed:
            _G_c, _P_c, N_c = e.shapes
            dec.arrays = _dc_replace(
                dec.arrays,
                untaint_order=unt[e.shard, k, :N_c],
                scale_down_order=sdn[e.shard, k, :N_c])
            dec.ordered = True
            dec.stages["ordered_tail_ms"] = tail_ms
            self.ordered_redispatches += 1

    def _write_cache(self, pb: _PreparedBatch) -> None:
        """Stash each served entry's answer on its tenant for the digest
        fast path — AFTER the ordered tails grafted, so a cached answer
        carries real orders. Copies the sliced columns (views would pin
        the whole [S, T, …] batch output). Runs under ``_exec_lock``;
        takes ``_host`` briefly (legal: _exec_lock → _host). Writing is
        correct even when a pipelined prep already adopted newer twins for
        the tenant: the cache maps (input digest / empty delta at now) →
        answer, and decide's determinism makes that mapping globally
        valid regardless of interleaving."""
        updates = []
        for e in pb.entries:
            if e.evict:
                continue
            dec = pb.results[e.pos]
            if not isinstance(dec, FleetDecision):
                continue
            arr = dec.arrays
            copied = type(arr)(**{
                f.name: np.array(getattr(arr, f.name))
                for f in fields(arr)})
            updates.append((e, dec, copied))
        if not updates:
            return
        with self._host:
            for e, dec, copied in updates:
                t = e.tenant
                t.cache_digest = e.digest
                t.cache_now = e.now
                t.cache_arrays = copied
                t.cache_ordered = dec.ordered
                t.cache_epoch = pb.epoch

    # -- decision provenance (round 19) --------------------------------------

    def explain_tenant(self, tenant_id: str,
                       groups: Optional[Sequence[int]] = None
                       ) -> List[dict]:
        """Re-derive one tenant's full decision calculus from the RESIDENT
        arenas and bit-cross-check the reconstructed 13 columns against the
        committed ones. The gather is ``device_state.explain_tenant_local``
        over the tenant's shard-LOCAL block (``fleet_shard_local`` — the
        ordered tail's zero-copy idiom), so explaining one tenant is O(row)
        on its own device, never an O(arena) cross-device program.

        The fused step writes a tenant's aggregates and its decision
        columns in ONE device program, so under ``_device_lock`` the two
        are always from the same committed tick — any mismatch is real
        arena drift, journaled + counted + rate-limit-dumped by
        ``provenance.report_mismatches``. READ-ONLY: the arenas stay
        resident; nothing is donated.

        Returns per-group explanation documents
        (:func:`~escalator_tpu.observability.provenance.build_explanations`)
        at the tenant's REQUEST group count, with scale-down victim windows
        attached when the tenant's cached answer carries real orders.
        Callable from any thread (the flap dump worker uses it via the
        wildcard explainer registration)."""
        from escalator_tpu.observability import provenance
        from escalator_tpu.ops import device_state as ds
        from escalator_tpu.ops import kernel as _kernel

        with self._host:
            t = self._tenants.get(tenant_id)
            if t is None:
                raise TenantError(f"unknown tenant {tenant_id!r}")
            shard, row = t.shard, t.row
            G_c = t.shapes[0]
            cached = (t.cache_arrays
                      if t.cache_ordered and t.cache_epoch == self._epoch
                      else None)
        candidates = None
        if cached is not None:
            candidates = provenance.candidate_windows(
                cached.scale_down_order, cached.untainted_offsets)
        with obs.span("fleet_explain", kind="device"), self._device_lock:
            _pods, _nodes, groups_a, aggs, prev_cols = self._state
            g_blk, a_blk, c_blk = ds.fleet_shard_local(
                (groups_a, aggs, prev_cols), shard)
            terms, committed = ds.explain_tenant_local(
                g_blk, a_blk, c_blk, np.int32(row))
            obs.fence((terms, committed))
            host_terms = {k: np.asarray(v)[:G_c]
                          for k, v in terms.items()}
            committed_cols = {
                name: np.asarray(col)[:G_c]
                for name, col in zip(_kernel.GROUP_DECISION_FIELDS,
                                     committed, strict=True)}
        mismatches = provenance.cross_check(host_terms, committed_cols)
        if mismatches:
            provenance.report_mismatches(f"fleet/{tenant_id}", mismatches)
        return provenance.build_explanations(
            host_terms, committed_cols, groups=groups,
            candidates=candidates)

    def _explain_for_provenance(self, key: str, groups=None):
        """The provenance registry's wildcard explainer (held weakly —
        a dead engine unregisters itself): explanation docs for a live
        tenant, None for keys this engine does not own. Never raises —
        the flap dump worker calls through here."""
        try:
            return self.explain_tenant(key, groups=groups)
        except TenantError:
            return None
        except Exception:  # noqa: BLE001 - dump-path helper must not break
            log.debug("explain_tenant(%r) failed", key, exc_info=True)
            return None

    # -- tenant-row snapshot / adopt (round 20: warm migration) ---------------

    def snapshot_tenant_row(self, tenant_id: str, timeout_sec: float = 30.0):
        """Freeze ONE tenant's persistent state into snapshot leaves:
        ``(leaves, meta)`` in the ``ops.snapshot`` tenant-row format (host
        cluster twins at the tenant's request shapes, the aggregates row,
        the 13 decision columns, the dirty mask, and the digest-fast-path
        cache when it is live). The freeze point is a batch boundary — the
        same drain-then-lock loop as :meth:`compact`, so the host twins and
        the device row are from the SAME committed tick — and the device
        gather is the explain path's ``fleet_shard_local`` + row-gather
        idiom (``snapshot.tenant_row_freeze``): O(row), shard-local, no
        donation, arenas stay live. Migration = this, then
        ``evict_tenant`` on the source, then :meth:`adopt_tenant_row` on
        the target; the first post-migration request folds everything that
        changed in between into one delta batch, exactly like the PR-6
        killed-leader warm start."""
        deadline = time.monotonic() + timeout_sec
        while True:
            with self._host:
                self._await_staged_drain()
            with self._exec_lock, self._host:
                st = self._staged
                if (st is None or st.executed or st.released
                        or st.epoch != self._epoch):
                    return self._snapshot_row_locked(tenant_id)
            if time.monotonic() > deadline:
                raise RuntimeError(
                    "tenant-row snapshot timed out: a staged batch kept "
                    "re-appearing — quiesce the tenant (the scheduler's "
                    "snapshot path does) and retry")

    def _snapshot_row_locked(self, tenant_id: str):
        """Caller holds ``_exec_lock`` + ``_host`` with no live staged
        batch."""
        from jax import tree_util

        from escalator_tpu.ops import device_state as ds
        from escalator_tpu.ops import snapshot as snaplib

        t = self._tenants.get(tenant_id)
        if t is None:
            raise TenantError(f"unknown tenant {tenant_id!r}")
        G_c, P_c, N_c = t.shapes
        with obs.span("fleet_row_freeze", kind="device"), self._device_lock:
            _pods, _nodes, _groups, aggs, prev_cols = self._state
            a_blk, c_blk = ds.fleet_shard_local((aggs, prev_cols), t.shard)
            frozen = snaplib.tenant_row_freeze((a_blk, c_blk), t.row)
            aggs_row, col_rows = tree_util.tree_map(np.asarray, frozen)
        trim = lambda soa, k: type(soa)(  # noqa: E731
            **{f.name: np.array(getattr(soa, f.name)[:k])
               for f in fields(type(soa))})
        cluster = ClusterArrays(
            groups=trim(t.groups, G_c), pods=trim(t.pods, P_c),
            nodes=trim(t.nodes, N_c))
        aggs_trim = type(aggs_row)(**{
            f.name: np.array(getattr(aggs_row, f.name)[
                :N_c if f.name == "node_pods_remaining" else G_c])
            for f in fields(type(aggs_row))})
        cols_trim = tuple(np.array(c[:G_c]) for c in col_rows)
        cache_live = (t.cache_arrays is not None
                      and t.cache_epoch == self._epoch)
        leaves = snaplib.tenant_row_to_leaves(
            cluster, aggs_trim, cols_trim, np.array(t.dirty[:G_c]),
            cache_arrays=t.cache_arrays if cache_live else None)
        meta = {
            "kind": snaplib.TENANT_ROW_KIND,
            "tenant": tenant_id,
            "shapes": [G_c, P_c, N_c],
            "ticks": int(t.ticks),
            "cache": {
                "live": bool(cache_live),
                "digest": (t.cache_digest.hex()
                           if cache_live and t.cache_digest else None),
                "now": int(t.cache_now) if cache_live else 0,
                "ordered": bool(t.cache_ordered) if cache_live else False,
            },
        }
        obs.journal.JOURNAL.event(
            "fleet-tenant-row-snapshot", tenant=tenant_id, shard=t.shard,
            row=t.row, ticks=int(t.ticks))
        return leaves, meta

    def adopt_tenant_row(self, leaves, meta,
                         timeout_sec: float = 30.0) -> Tuple[int, int]:
        """Adopt a tenant-row snapshot as a RESIDENT tenant: register a
        fresh slot, seed the host twins/dirty mask/digest cache from the
        leaves, and scatter the row into the arenas with the donated
        ``snapshot.tenant_row_adopt`` program (in-place dynamic-update-
        slice — one H2D upload, zero arena copies). Returns ``(shard,
        row)``. Rejections keep the existing restore-outcome taxonomy:
        structurally invalid rows raise :class:`SnapshotCorruptError`
        (``snapshot_restores_total{outcome="corrupt"}``), rows the arena
        cannot hold (bucket caps) or a resident same-id tenant raise
        :class:`TenantError` (``outcome="stale"``) — the caller falls back
        to the cold path (a full first frame), never to a wrong adopt."""
        from escalator_tpu.ops import snapshot as snaplib

        try:
            if meta.get("kind") != snaplib.TENANT_ROW_KIND:
                raise snaplib.SnapshotCorruptError(
                    f"not a tenant-row snapshot (kind="
                    f"{meta.get('kind')!r})")
            try:
                tenant_id = validate_tenant_id(meta.get("tenant"))
            except TenantError as e:
                raise snaplib.SnapshotCorruptError(
                    f"tenant-row meta carries an invalid tenant id: {e}"
                ) from None
            cluster, aggs_row, col_rows, dirty, cache = \
                snaplib.leaves_to_tenant_row(leaves)
            shapes = tuple(int(v) for v in meta.get("shapes", ()))
            got = (int(cluster.groups.valid.shape[0]),
                   int(cluster.pods.valid.shape[0]),
                   int(cluster.nodes.valid.shape[0]))
            if len(shapes) != 3 or shapes != got:
                raise snaplib.SnapshotCorruptError(
                    f"tenant-row meta shapes {shapes} disagree with leaf "
                    f"shapes {got}")
            if (dirty.shape[0] != shapes[0]
                    or aggs_row.cpu_req.shape[0] != shapes[0]
                    or aggs_row.node_pods_remaining.shape[0] != shapes[2]):
                raise snaplib.SnapshotCorruptError(
                    "tenant-row aggregate/dirty rows disagree with the "
                    "declared shapes")
        except snaplib.SnapshotCorruptError:
            metrics.snapshot_restores.labels("corrupt").inc()
            raise
        deadline = time.monotonic() + timeout_sec
        while True:
            with self._host:
                self._await_staged_drain()
            with self._exec_lock, self._host:
                st = self._staged
                if (st is None or st.executed or st.released
                        or st.epoch != self._epoch):
                    try:
                        return self._adopt_row_locked(
                            tenant_id, cluster, aggs_row, col_rows, dirty,
                            cache, meta)
                    except TenantError:
                        metrics.snapshot_restores.labels("stale").inc()
                        raise
            if time.monotonic() > deadline:
                raise RuntimeError(
                    "tenant-row adopt timed out: a staged batch kept "
                    "re-appearing — pause the scheduler and retry")

    def _adopt_row_locked(self, tenant_id, cluster, aggs_row, col_rows,
                          dirty, cache, meta) -> Tuple[int, int]:
        """Caller holds ``_exec_lock`` + ``_host`` with no live staged
        batch."""
        from escalator_tpu.ops import kernel as _kernel
        from escalator_tpu.ops import snapshot as snaplib

        if tenant_id in self._tenants:
            raise TenantError(
                f"tenant {tenant_id!r} is already resident — evict before "
                f"adopting a migrated row")
        G_c, P_c, N_c = (int(cluster.groups.valid.shape[0]),
                         int(cluster.pods.valid.shape[0]),
                         int(cluster.nodes.valid.shape[0]))
        self._ensure_buckets(cluster)   # may grow; raises TenantError at caps
        t = self._register(tenant_id)
        t.pods = _repad_copy(cluster.pods, self._P, _empty_pods)
        t.nodes = _repad_copy(cluster.nodes, self._N, _empty_nodes)
        t.groups = _repad_copy(cluster.groups, self._G, _empty_groups)
        t.shapes = (G_c, P_c, N_c)
        t.ticks = int(meta.get("ticks", 0))
        # pad lanes past the snapshot's group count: a dispatched row holds
        # the kernel's invalid-lane fixpoint there (status=NOOP_EMPTY,
        # every other column 0, dirty clear) — reproduce it, or the
        # full-width ``dirty.any()`` proxy in the digest fast path would
        # miss forever on a migrated tenant whose arena is wider than its
        # request. A never-dispatched row (ticks=0) keeps register()'s
        # all-dirty bootstrap instead: its arena really is all zeros.
        dispatched = t.ticks > 0
        if dispatched:
            t.dirty[:] = False
        t.dirty[:G_c] = dirty
        # row values at ARENA shapes: twins lead, the scratch lane / pad
        # tail carries the positions' pad values (the same invariant
        # _assemble maintains), aggregates and columns zero-fill past the
        # snapshot's request shapes
        pods_row = _repad(t.pods, self._P + 1, _empty_pods)
        nodes_row = _repad(t.nodes, self._N + 1, _empty_nodes)
        groups_row = t.groups
        aggs_full = _kernel.GroupAggregates(**{
            f.name: self._zero_row_like(getattr(aggs_row, f.name),
                                        self._N + 1
                                        if f.name == "node_pods_remaining"
                                        else self._G)
            for f in fields(_kernel.GroupAggregates)})
        cols_full = []
        for name, col in zip(_kernel.GROUP_DECISION_FIELDS, col_rows,
                             strict=True):
            full = np.zeros(self._G, _COL_DTYPES[name])
            if dispatched and name == "status":
                from escalator_tpu.core.semantics import DecisionStatus

                full[G_c:] = int(DecisionStatus.NOOP_EMPTY)
            full[:G_c] = col
            cols_full.append(full)
        row_values = (pods_row, nodes_row, groups_row, aggs_full,
                      tuple(cols_full))
        with obs.span("fleet_row_adopt", kind="device"), self._device_lock:
            self._state = snaplib.tenant_row_adopt(
                self._state, t.shard, t.row, row_values)
        if cache is not None and meta.get("cache", {}).get("live"):
            cmeta = meta["cache"]
            t.cache_arrays = cache
            t.cache_digest = (bytes.fromhex(cmeta["digest"])
                              if cmeta.get("digest") else None)
            t.cache_now = int(cmeta.get("now", 0))
            t.cache_ordered = bool(cmeta.get("ordered", False))
            t.cache_epoch = self._epoch
        metrics.snapshot_restores.labels("warm").inc()
        obs.journal.JOURNAL.event(
            "fleet-tenant-row-adopt", tenant=tenant_id, shard=t.shard,
            row=t.row, ticks=int(t.ticks))
        log.info("adopted tenant-row snapshot for %r at shard=%d row=%d",
                 tenant_id, t.shard, t.row)
        return t.shard, t.row

    @staticmethod
    def _zero_row_like(src: np.ndarray, width: int) -> np.ndarray:
        full = np.zeros(width, src.dtype)
        full[:src.shape[0]] = src
        return full

    # -- the sequential convenience + release --------------------------------

    def step(self, requests: Sequence[Union[DecideRequest, EvictRequest]]
             ) -> List[Union[FleetDecision, EvictAck, Exception]]:
        """Serve one micro-batch end to end (prepare + execute) — the
        sequential caller's API, and the non-pipelined scheduler path."""
        return self.execute_batch(self.prepare_batch(requests))

    def release_prepared(self, pb: _PreparedBatch,
                         wait_sec: float = 5.0) -> bool:
        """Abandon a prepared-but-never-executed batch (scheduler
        shutdown): roll the host twins back from the per-entry rollback
        records so the engine's next diff still matches the device state.
        Waits (bounded) for any in-flight execute first; when the engine is
        wedged past ``wait_sec`` the rollback is skipped (the staged
        registration still clears so reshapes don't wait forever). Returns
        True when the rollback ran."""
        got = self._exec_lock.acquire(timeout=wait_sec)
        try:
            with self._host:
                if pb.executed or pb.released:
                    return False
                pb.released = True
                rolled = False
                if got and pb.epoch == self._epoch:
                    for e in reversed(pb.entries):
                        self._rollback_entry(e)
                    metrics.fleet_tenant_count.set(len(self._tenants))
                    rolled = True
                elif not got:
                    log.warning(
                        "release_prepared: execute still holds the engine "
                        "after %.1fs — skipping twin rollback", wait_sec)
                if self._staged is pb:
                    self._staged = None
                self._host.notify_all()
                return rolled
        finally:
            if got:
                self._exec_lock.release()

    def _rollback_entry(self, e: _Entry) -> None:
        tid = e.request.tenant_id
        if e.evict:
            # the evict never dispatched: resurrect the tenant (its twins
            # were replaced with empties — restore) and re-claim its row
            t = e.tenant
            t.pods, t.nodes, t.groups = e.old_twins
            t.dirty = e.old_dirty
            t.shapes = e.old_shapes
            t.ticks -= 1
            t.cache_digest, t.cache_arrays = None, None
            self._tenants[tid] = t
            if t.row in self._free[t.shard]:
                self._free[t.shard].remove(t.row)
            return
        if e.registered:
            # the registration never reached the device: drop the tenant
            self._tenants.pop(tid, None)
            self._free[e.shard].append(e.row)
            self._free[e.shard].sort()
            return
        t = e.tenant
        if e.delta_undo is not None:
            # delta prep scattered in place: scatter the gathered old rows
            # back (the reverse order of a reversed-entries unwind keeps
            # later scatters from clobbering this one's restore)
            pidx, undo_p, nidx, undo_n, old_groups = e.delta_undo
            for f in fields(t.pods):
                np.asarray(getattr(t.pods, f.name))[pidx] = \
                    np.asarray(getattr(undo_p, f.name))
            for f in fields(t.nodes):
                np.asarray(getattr(t.nodes, f.name))[nidx] = \
                    np.asarray(getattr(undo_n, f.name))
            if old_groups is not None:
                t.groups = old_groups
            t.dirty = e.old_dirty
            t.shapes = e.old_shapes
            t.ticks -= 1
            t.cache_digest, t.cache_arrays = None, None
            return
        t.pods, t.nodes, t.groups = e.old_twins
        t.dirty = e.old_dirty
        t.shapes = e.old_shapes
        t.ticks -= 1
        t.cache_digest, t.cache_arrays = None, None

    # -- self-audit ----------------------------------------------------------

    def audit(self) -> list:
        """Recompute every tenant row's aggregates from the resident arrays
        (``kernel.fleet_compute_aggregates_jit``) and bit-compare against
        the maintained arenas — the fleet form of the round-8 refresh
        audit, over every shard. Returns the mismatched column names
        ([] = clean)."""
        from dataclasses import fields as dfields

        from escalator_tpu.ops import kernel as _kernel

        with self._exec_lock, self._host, self._device_lock:
            host = self._pull_state()
        pods, nodes, groups, aggs, _cols = host
        merge = lambda soa: type(soa)(  # noqa: E731
            **{f.name: np.asarray(getattr(soa, f.name)).reshape(
                (-1,) + np.asarray(getattr(soa, f.name)).shape[2:])
               for f in dfields(soa)})
        fresh = _kernel.fleet_compute_aggregates_jit(
            ClusterArrays(groups=merge(groups), pods=merge(pods),
                          nodes=merge(nodes)))

        def flat(col):
            a = np.asarray(col)
            return a.reshape((-1,) + a.shape[2:])

        return [
            f.name for f in dfields(_kernel.GroupAggregates)
            if f.name != "dirty"
            and not np.array_equal(flat(getattr(aggs, f.name)),
                                   np.asarray(getattr(fresh, f.name)))
        ]
