"""FleetEngine: the device-side owner of the multi-tenant decision arenas.

The round-8 incremental decide keeps ONE cluster's state device-resident and
pays O(dirty) per tick; the fleet engine stacks C independent tenants along a
leading cluster axis and pays one dispatch per MICRO-BATCH of tenants:

- resident arrays ``pods [C+1, P+1]`` / ``nodes [C+1, N+1]`` /
  ``groups [C+1, G]`` (row C is a scratch tenant — the row-level analog of
  the scratch lane; each row keeps its own scratch lane),
- per-tenant :class:`~escalator_tpu.ops.kernel.GroupAggregates` arenas
  ``[C+1, G]`` (+ ``node_pods_remaining [C+1, N+1]``) maintained by the same
  exact integer deltas as the single-tenant path,
- the 13 persistent decision columns ``[C+1, G]``.

Ragged tenants pack into shared power-of-two ``(G, N, P)`` buckets (the
``statestore.delta_bucket`` policy generalized to arena shapes) with their
per-lane ``valid`` masks; a tenant outgrowing a bucket grows the arena
(rare: buckets double), and :meth:`FleetEngine.compact` repacks live tenants
into the smallest bucket after mass evictions.

Per micro-batch, ``ops.device_state._fleet_step`` runs scatter + aggregate
maintenance + per-tenant delta decide as ONE fused program. Host work per
request is the positional column diff against the tenant's host twin
(``_changed_slots`` — the IncrementalJaxBackend host-diff, per tenant) plus
O(G) dirty bookkeeping; the dirty-group set is tracked host-side as a
SUPERSET of the device semantics (recomputing a clean row reproduces its
value bit-exactly, so a superset can never break parity — locked by the
multi-tenant soak in tests/test_fleet.py).

Orders run the lazy protocol PER TENANT: the batch dispatch is the light
program; a tenant whose decision consumes an order (tainted nodes exist, or
some group scales down) gets a single-tenant ordered re-dispatch fed its
maintained aggregates (``device_state._fleet_tenant_state`` +
``kernel.decide_jit(aggregates=…)``) — steady fleets sort never, drains sort
per draining tenant.
"""

from __future__ import annotations

import logging
import threading
from dataclasses import dataclass, fields
from typing import Dict, List, Sequence, Tuple, Union

import numpy as np

from escalator_tpu import observability as obs
from escalator_tpu.core.arrays import (
    NO_TAINT_TIME,
    ClusterArrays,
    GroupArrays,
    NodeArrays,
    PodArrays,
)
from escalator_tpu.metrics import metrics
from escalator_tpu.native.statestore import delta_bucket

log = logging.getLogger("escalator_tpu.fleet")

#: Tenant-id wire contract: a non-empty printable string, bounded so a
#: hostile frame cannot balloon the slot map key space per request.
MAX_TENANT_ID_LEN = 128


class TenantError(ValueError):
    """A per-tenant request the fleet cannot serve (malformed/unknown tenant
    id, bucket caps exceeded). Maps to INVALID_ARGUMENT at the gRPC edge —
    and never poisons the batch it would have ridden in."""


def validate_tenant_id(tenant_id) -> str:
    """The ONE tenant-id validation both the gRPC edge and the engine run:
    a non-empty printable str of at most MAX_TENANT_ID_LEN chars."""
    if not isinstance(tenant_id, str):
        raise TenantError(f"tenant id must be a string, got "
                          f"{type(tenant_id).__name__}")
    if not tenant_id or len(tenant_id) > MAX_TENANT_ID_LEN:
        raise TenantError(
            f"tenant id must be 1..{MAX_TENANT_ID_LEN} chars, got "
            f"{len(tenant_id)}")
    if not tenant_id.isprintable():
        raise TenantError("tenant id must be printable")
    return tenant_id


@dataclass
class DecideRequest:
    """One tenant's decide: a packed cluster (any padding at or under the
    arena caps) + the timestamp the decision evaluates at."""

    tenant_id: str
    cluster: ClusterArrays
    now_sec: int


@dataclass
class EvictRequest:
    """Deregister a tenant: its lanes clear, its slot frees for reuse."""

    tenant_id: str


@dataclass
class EvictAck:
    tenant_id: str


@dataclass
class FleetDecision:
    """One tenant's result, sliced back to ITS request's padded shapes — the
    13 decision columns are bit-identical to a standalone
    ``decide_jit``/``delta_decide_jit`` on the same cluster. ``ordered``
    carries the lazy-orders flag: False means the order fields are
    input-order placeholders and no window may be read (exactly the
    single-cluster protocol's contract)."""

    tenant_id: str
    arrays: object          # kernel.DecisionArrays with numpy leaves
    ordered: bool
    batch_size: int


def _pow2(n: int, lo: int = 1) -> int:
    return max(lo, 1 << max(int(n) - 1, 0).bit_length())


def _empty_pods(P: int) -> PodArrays:
    return PodArrays(
        group=np.zeros(P, np.int32), cpu_milli=np.zeros(P, np.int64),
        mem_bytes=np.zeros(P, np.int64), node=np.full(P, -1, np.int32),
        valid=np.zeros(P, bool),
    )


def _empty_nodes(N: int) -> NodeArrays:
    return NodeArrays(
        group=np.zeros(N, np.int32), cpu_milli=np.zeros(N, np.int64),
        mem_bytes=np.zeros(N, np.int64), creation_ns=np.zeros(N, np.int64),
        tainted=np.zeros(N, bool), cordoned=np.zeros(N, bool),
        no_delete=np.zeros(N, bool),
        taint_time_sec=np.full(N, NO_TAINT_TIME, np.int64),
        valid=np.zeros(N, bool),
    )


def _empty_groups(G: int) -> GroupArrays:
    # pack_groups' padding conventions exactly (scale_up_thr=1 guards /0)
    return GroupArrays(
        min_nodes=np.zeros(G, np.int32), max_nodes=np.zeros(G, np.int32),
        taint_lower=np.zeros(G, np.int32), taint_upper=np.zeros(G, np.int32),
        scale_up_thr=np.ones(G, np.int32), slow_rate=np.zeros(G, np.int32),
        fast_rate=np.zeros(G, np.int32), locked=np.zeros(G, bool),
        requested_nodes=np.zeros(G, np.int32),
        cached_cpu_milli=np.zeros(G, np.int64),
        cached_mem_bytes=np.zeros(G, np.int64),
        soft_grace_sec=np.zeros(G, np.int64),
        hard_grace_sec=np.zeros(G, np.int64),
        emptiest=np.zeros(G, bool), valid=np.zeros(G, bool),
    )


def _repad(src, bucket: int, empty_fn):
    """A section re-padded into the arena bucket: the client's lanes lead,
    the tail carries the SAME pad values a fresh twin starts with — so
    padding lanes never read as changed in the positional diff."""
    n = int(getattr(src, "valid").shape[0])
    if n == bucket:
        return src
    out = empty_fn(bucket)
    for f in fields(src):
        getattr(out, f.name)[:n] = getattr(src, f.name)
    return out


def _changed_rows(old, new) -> np.ndarray:
    """Row indices where ANY column differs (positional diff, all fields)."""
    changed = None
    for f in fields(old):
        d = np.asarray(getattr(old, f.name)) != np.asarray(getattr(new, f.name))
        changed = d if changed is None else (changed | d)
    return np.nonzero(changed)[0].astype(np.int64)


#: The persistent-decision-column dtypes, in kernel.GROUP_DECISION_FIELDS
#: order — the [C+1, G] arena columns must match DecisionArrays bit-for-bit.
_COL_DTYPES = {
    "status": np.int32, "nodes_delta": np.int32,
    "cpu_percent": np.float64, "mem_percent": np.float64,
    "cpu_request_milli": np.int64, "mem_request_bytes": np.int64,
    "cpu_capacity_milli": np.int64, "mem_capacity_bytes": np.int64,
    "num_pods": np.int32, "num_nodes": np.int32,
    "num_untainted": np.int32, "num_tainted": np.int32,
    "num_cordoned": np.int32,
}


def zero_state(C: int, G: int, P: int, N: int):
    """Freshly-zeroed host arenas at the given buckets: C+1 tenant rows
    (row C is the scratch tenant), per-row scratch lane on the pod/node
    axes. The (pods, nodes, groups, aggs, prev_cols) tuple feeds
    ``ops.device_state._fleet_step`` directly — the jaxlint registry builds
    its fleet fixture from this too, so the analyzed program is constructed
    exactly like production's."""
    from escalator_tpu.ops import kernel as _kernel

    stack = lambda soa: type(soa)(  # noqa: E731
        **{f.name: np.broadcast_to(
            getattr(soa, f.name), (C + 1,) + getattr(soa, f.name).shape
        ).copy() for f in fields(soa)})
    pods = stack(_empty_pods(P + 1))
    nodes = stack(_empty_nodes(N + 1))
    groups = stack(_empty_groups(G))
    aggs = _kernel.GroupAggregates(
        cpu_req=np.zeros((C + 1, G), np.int64),
        mem_req=np.zeros((C + 1, G), np.int64),
        num_pods=np.zeros((C + 1, G), np.int64),
        cpu_cap=np.zeros((C + 1, G), np.int64),
        mem_cap=np.zeros((C + 1, G), np.int64),
        num_nodes=np.zeros((C + 1, G), np.int64),
        num_untainted=np.zeros((C + 1, G), np.int64),
        num_tainted=np.zeros((C + 1, G), np.int64),
        num_cordoned=np.zeros((C + 1, G), np.int64),
        node_pods_remaining=np.zeros((C + 1, N + 1), np.int64),
        dirty=np.zeros((C + 1, G), bool),
    )
    prev_cols = tuple(np.zeros((C + 1, G), _COL_DTYPES[n])
                      for n in _kernel.GROUP_DECISION_FIELDS)
    return pods, nodes, groups, aggs, prev_cols


@dataclass
class _Tenant:
    slot: int
    pods: PodArrays          # host twin at bucket shapes (no scratch lane)
    nodes: NodeArrays
    groups: GroupArrays
    dirty: np.ndarray        # bool [G] — pending dirty groups (host mirror)
    shapes: Tuple[int, int, int]   # the LAST request's (G, P, N) paddings
    ticks: int = 0


class FleetEngine:
    """Owns the C-stacked device arenas + host twins for a fleet of tenants.

    NOT internally synchronized for mutation: exactly one caller —
    normally the :class:`~escalator_tpu.fleet.scheduler.FleetScheduler`
    worker — may run :meth:`step` / :meth:`compact` at a time (reads like
    :attr:`tenant_count` are safe from any thread)."""

    def __init__(self, num_groups: int = 8, pod_capacity: int = 128,
                 node_capacity: int = 64, max_tenants: int = 8,
                 device=None,
                 max_group_bucket: int = 1 << 12,
                 max_pod_bucket: int = 1 << 20,
                 max_node_bucket: int = 1 << 18,
                 max_tenant_bucket: int = 1 << 16):
        from escalator_tpu.jaxconfig import guarded_devices

        self._device = device if device is not None else guarded_devices()[0]
        self._G = _pow2(num_groups, 4)
        self._P = _pow2(pod_capacity, 16)
        self._N = _pow2(node_capacity, 8)
        self._C = _pow2(max_tenants, 2)
        self._caps = (max_group_bucket, max_pod_bucket, max_node_bucket,
                      max_tenant_bucket)
        self._tenants: Dict[str, _Tenant] = {}
        self._free: List[int] = list(range(self._C))
        self._lock = threading.Lock()   # slot map reads vs step mutation
        self.batches = 0
        self.decisions = 0
        self.ordered_redispatches = 0
        self._init_state()

    # -- arena construction / reshaping --------------------------------------

    def _host_zero_state(self, C: int, G: int, P: int, N: int):
        return zero_state(C, G, P, N)

    def _init_state(self) -> None:
        import jax

        from escalator_tpu.observability import resources
        from escalator_tpu.ops import device_state as _ds  # noqa: F401
        # (importing device_state registers the SoA dataclasses as pytrees
        # — device_put on PodArrays/NodeArrays/GroupArrays needs them)
        self._state = jax.device_put(
            self._host_zero_state(self._C, self._G, self._P, self._N),
            self._device)
        # HBM accounting: the C-stacked arenas are ONE owner whose budget
        # is the docs/fleet.md capacity-envelope formula at the CURRENT
        # buckets (the budget callable re-reads them, so a grow/compact
        # moves the envelope with the arrays)
        resources.RESOURCES.register(
            "fleet_arenas", self, lambda e: e._state,
            budget=lambda e: resources.expected_fleet_arena_bytes(
                e._C, e._G, e._P, e._N))

    def _pull_state(self):
        """D2H copy of the arenas (the reshape paths' staging buffers)."""
        from jax import tree_util

        return tree_util.tree_map(np.asarray, self._state)

    def _grow(self, G2: int, P2: int, N2: int, C2: int) -> None:
        """Grow the arenas to new buckets: copy the leading real lanes/rows
        into freshly-zeroed arrays (pad values are position-invariant, so
        the old scratch lane/rows are reproduced by construction) and
        re-upload. O(arena) host work — rare by design: buckets double."""
        import jax

        cap_g, cap_p, cap_n, cap_c = self._caps
        if G2 > cap_g or P2 > cap_p or N2 > cap_n or C2 > cap_c:
            raise TenantError(
                f"fleet arena bucket cap exceeded: need (G={G2}, P={P2}, "
                f"N={N2}, C={C2}) caps (G={cap_g}, P={cap_p}, N={cap_n}, "
                f"C={cap_c})")
        old = self._pull_state()
        new = self._host_zero_state(C2, G2, P2, N2)
        C, G, P, N = self._C, self._G, self._P, self._N

        def copy_soa(dst, src, lanes):
            for f in fields(dst):
                getattr(dst, f.name)[: C + 1, :lanes] = \
                    getattr(src, f.name)[:, :lanes]

        pods_o, nodes_o, groups_o, aggs_o, cols_o = old
        pods_n, nodes_n, groups_n, aggs_n, cols_n = new
        copy_soa(pods_n, pods_o, P)     # real lanes; scratch lane = pad
        copy_soa(nodes_n, nodes_o, N)
        copy_soa(groups_n, groups_o, G)
        for f in fields(type(aggs_n)):
            dst, src = getattr(aggs_n, f.name), getattr(aggs_o, f.name)
            # node_pods_remaining copies its real lanes only (the old
            # scratch lane holds 0, the new arrays' default); [G] columns
            # copy whole (G2 >= G)
            lanes = N if f.name == "node_pods_remaining" else src.shape[1]
            dst[: C + 1, :lanes] = src[:, :lanes]
        for dst, src in zip(cols_n, cols_o, strict=True):
            dst[: C + 1, :G] = src
        # the scratch tenant row (index C of the OLD stack) carried pad
        # values only, so landing it at row C of the new stack is harmless;
        # rows C..C2 start as fresh scratch/empty rows either way.
        self._state = jax.device_put(new, self._device)
        if G2 != G:
            # new group rows exist for every tenant now; their persistent
            # columns are zeros, not a computed decision — recompute
            # everything at the next touch (superset-dirty is parity-safe)
            for t in self._tenants.values():
                t.dirty = np.ones(G2, bool)
        for t in self._tenants.values():
            t.pods = _repad(t.pods, P2, _empty_pods)
            t.nodes = _repad(t.nodes, N2, _empty_nodes)
            t.groups = _repad(t.groups, G2, _empty_groups)
            if len(t.dirty) != G2:
                d = np.zeros(G2, bool)
                d[: len(t.dirty)] = t.dirty
                t.dirty = d
        if C2 != C:
            self._free.extend(range(C, C2))
        self._G, self._P, self._N, self._C = G2, P2, N2, C2
        # arena lifecycle visibility (round 15): a grow silently doubled
        # resident HBM before this — now it counts, annotates the
        # fleet_batch flight record it happened under, and moves the
        # registered fleet_arenas owner bytes + budget in the same tick
        metrics.fleet_arena_grows.inc()
        obs.annotate(fleet_arena_grow=f"G={G2} P={P2} N={N2} C={C2}")
        log.info("fleet arena grown to G=%d P=%d N=%d C=%d", G2, P2, N2, C2)

    def compact(self) -> dict:
        """Repack live tenants into the leading slots and shrink the tenant
        axis to the smallest power-of-two bucket that holds them — the
        post-mass-eviction memory reclaim. Lane buckets are left alone
        (shrinking them would force every tenant's twin through a repad for
        marginal HBM). Returns {tenants, old_c, new_c}."""
        from jax import tree_util

        import jax

        # own span root: compact runs OUTSIDE any batch (an operator or
        # maintenance call), and annotate() is a no-op without a timeline
        # — without this the advertised fleet_arena_compact annotation
        # could never reach a flight record
        with obs.span("fleet_compact"), self._lock:
            live = sorted(self._tenants.values(), key=lambda t: t.slot)
            C2 = _pow2(len(live), 2)
            old_c = self._C
            rows = [t.slot for t in live]
            old = self._pull_state()
            new = self._host_zero_state(C2, self._G, self._P, self._N)

            def place(dst_tree, src_tree):
                for f_dst, f_src in zip(
                        tree_util.tree_leaves(dst_tree),
                        tree_util.tree_leaves(src_tree), strict=True):
                    for i, r in enumerate(rows):
                        f_dst[i] = f_src[r]

            for dst, src in zip(new, old, strict=True):
                place(dst, src)
            self._state = jax.device_put(new, self._device)
            for i, t in enumerate(live):
                t.slot = i
            self._free = list(range(len(live), C2))
            self._C = C2
            metrics.fleet_arena_compacts.inc()
            obs.annotate(fleet_arena_compact=f"C={old_c}->{C2}")
        log.info("fleet arena compacted: %d tenants, C %d -> %d",
                 len(live), old_c, C2)
        return {"tenants": len(live), "old_c": old_c, "new_c": C2}

    # -- tenant lifecycle ----------------------------------------------------

    @property
    def tenant_count(self) -> int:
        return len(self._tenants)

    @property
    def buckets(self) -> dict:
        return {"groups": self._G, "pods": self._P, "nodes": self._N,
                "tenants": self._C}

    def has_tenant(self, tenant_id: str) -> bool:
        return tenant_id in self._tenants

    def _register(self, tenant_id: str) -> _Tenant:
        if not self._free:
            self._grow(self._G, self._P, self._N, self._C * 2)
        t = _Tenant(
            slot=self._free.pop(0),
            pods=_empty_pods(self._P), nodes=_empty_nodes(self._N),
            groups=_empty_groups(self._G),
            # bootstrap: EVERY group row computes on the first decide, so
            # invalid/padding rows carry real NOOP_EMPTY decisions rather
            # than the arena's zero-initialized columns
            dirty=np.ones(self._G, bool),
            shapes=(self._G, self._P, self._N),
        )
        self._tenants[tenant_id] = t
        metrics.fleet_tenant_count.set(len(self._tenants))
        return t

    def _ensure_buckets(self, cluster: ClusterArrays) -> None:
        G_c = int(cluster.groups.valid.shape[0])
        P_c = int(cluster.pods.valid.shape[0])
        N_c = int(cluster.nodes.valid.shape[0])
        if G_c > self._G or P_c > self._P or N_c > self._N:
            self._grow(max(self._G, _pow2(G_c, 4)),
                       max(self._P, _pow2(P_c, 16)),
                       max(self._N, _pow2(N_c, 8)), self._C)

    # -- the micro-batch step ------------------------------------------------

    def step(self, requests: Sequence[Union[DecideRequest, EvictRequest]]
             ) -> List[Union[FleetDecision, EvictAck, Exception]]:
        """Serve one micro-batch: at most one request per tenant (the
        scheduler's coalescing guarantees it; direct callers must too).
        Returns one result per request, position-aligned; a request that
        fails validation comes back as its exception WITHOUT poisoning the
        rest of the batch. One ``_fleet_step`` dispatch total, plus one
        ordered re-dispatch per tenant whose decision consumes an order."""
        from escalator_tpu.ops import device_state as ds
        from escalator_tpu.ops import kernel as _kernel

        seen = set()
        for r in requests:
            if r.tenant_id in seen:
                raise ValueError(
                    f"duplicate tenant {r.tenant_id!r} in one micro-batch")
            seen.add(r.tenant_id)
        results: List[Union[FleetDecision, EvictAck, Exception, None]] = (
            [None] * len(requests))
        with obs.span("fleet_batch"), self._lock:
            obs.annotate(backend="fleet", batch_size=len(requests))
            prepared = []   # (pos, tenant, new sections, now, request)
            with obs.span("fleet_diff"):
                # pass 1: grow the lane buckets for EVERY request up front —
                # a grow mid-batch would invalidate sections staged at the
                # old shapes (a cap breach rejects that request alone)
                for pos, r in enumerate(requests):
                    if isinstance(r, EvictRequest):
                        continue
                    try:
                        self._ensure_buckets(r.cluster)
                    except TenantError as e:
                        results[pos] = e
                for pos, r in enumerate(requests):
                    if results[pos] is not None:
                        continue
                    try:
                        prepared.append((pos, *self._prepare(r)))
                    except TenantError as e:
                        results[pos] = e
            if prepared:
                out_host = self._dispatch(prepared, ds, _kernel)
                with obs.span("fleet_unpack"):
                    for i, (pos, tenant, new_secs, now, r) in enumerate(
                            prepared):
                        results[pos] = self._finish(
                            i, out_host, tenant, new_secs, now, r,
                            len(prepared), ds, _kernel)
            self.batches += 1
            obs.annotate(
                tenants=[r.tenant_id for r in requests],
                fleet_tenants_resident=len(self._tenants))
        return results   # type: ignore[return-value]

    def _prepare(self, r):
        """Validate + stage one request: resolve its tenant (registering a
        new one), re-pad its sections into the arena buckets, and leave the
        twin/dirty update to the post-dispatch finish."""
        validate_tenant_id(r.tenant_id)
        if isinstance(r, EvictRequest):
            tenant = self._tenants.get(r.tenant_id)
            if tenant is None:
                raise TenantError(f"unknown tenant {r.tenant_id!r}")
            # eviction is a decide against the EMPTY cluster: every valid
            # lane clears, aggregates fall to zero, the slot frees after
            new_secs = (_empty_pods(self._P), _empty_nodes(self._N),
                        _empty_groups(self._G))
            return tenant, new_secs, 0, r
        tenant = self._tenants.get(r.tenant_id)
        if tenant is None:
            tenant = self._register(r.tenant_id)
        tenant.shapes = (
            int(r.cluster.groups.valid.shape[0]),
            int(r.cluster.pods.valid.shape[0]),
            int(r.cluster.nodes.valid.shape[0]),
        )
        new_secs = (
            _repad(r.cluster.pods, self._P, _empty_pods),
            _repad(r.cluster.nodes, self._N, _empty_nodes),
            _repad(r.cluster.groups, self._G, _empty_groups),
        )
        return tenant, new_secs, int(r.now_sec), r

    def _dispatch(self, prepared, ds, _kernel):
        """Build the batched operands, run the ONE fused device program,
        adopt the returned arenas, and return the batch outputs as host
        arrays. Buckets: lane batches pad to the shared
        ``statestore.delta_bucket`` widths, dirty rows to the shared
        ``kernel.fleet_dirty_indices`` width, the tenant batch itself to a
        power of two (pad entries ride the scratch tenant row) — so the jit
        cache keys on a handful of bucket shapes, never on batch content."""
        G, P, N, C = self._G, self._P, self._N, self._C
        diffs = []
        for _pos, tenant, (new_p, new_n, new_g), now, _r in prepared:
            pod_slots = _changed_rows(tenant.pods, new_p)
            node_slots = _changed_rows(tenant.nodes, new_n)
            # dirty-group bookkeeping (host mirror, superset-safe): groups
            # any changed lane pointed at — before OR after — plus every
            # group row that changed
            touched = tenant.dirty
            for soa, slots in ((tenant.pods, pod_slots), (new_p, pod_slots),
                               (tenant.nodes, node_slots),
                               (new_n, node_slots)):
                gids = np.asarray(soa.group)[slots]
                touched[np.clip(gids, 0, G - 1)] = True
            changed_g = np.zeros(G, bool)
            changed_g[_changed_rows(tenant.groups, new_g)] = True
            tenant.dirty = touched | changed_g
            diffs.append((tenant, pod_slots, node_slots, new_p, new_n, new_g,
                          now))
        B_pod = delta_bucket(max(len(d[1]) for d in diffs))
        B_node = delta_bucket(max(len(d[2]) for d in diffs))
        T = _pow2(len(diffs))
        rows = np.full(T, C, np.int32)
        nows = np.zeros(T, np.int64)
        pod_idx = np.full((T, B_pod), P, np.int32)
        node_idx = np.full((T, B_node), N, np.int32)
        pod_vals = [None] * T
        node_vals = [None] * T
        groups_new = [None] * T
        dirty_masks = []
        for t, (tenant, ps, ns, new_p, new_n, new_g, now) in enumerate(diffs):
            rows[t] = tenant.slot
            nows[t] = now
            pi, pv = ds._gather_padded(new_p, ps, B_pod, P, ds._POD_PAD)
            ni, nv = ds._gather_padded(new_n, ns, B_node, N, ds._NODE_PAD)
            pod_idx[t], node_idx[t] = pi, ni
            pod_vals[t], node_vals[t] = pv, nv
            groups_new[t] = new_g
            dirty_masks.append(tenant.dirty)
        # pad batch entries: scratch tenant row + no-op batches
        if len(diffs) < T:
            _, pv0 = ds._gather_padded(
                _empty_pods(0), np.zeros(0, np.int64), B_pod, P, ds._POD_PAD)
            _, nv0 = ds._gather_padded(
                _empty_nodes(0), np.zeros(0, np.int64), B_node, N,
                ds._NODE_PAD)
            for t in range(len(diffs), T):
                pod_vals[t], node_vals[t] = pv0, nv0
                groups_new[t] = _empty_groups(G)
        dirty_masks.extend(
            [np.zeros(G, bool)] * (T - len(diffs)))
        dirty_idx = _kernel.fleet_dirty_indices(dirty_masks, G)
        stack = lambda soas: type(soas[0])(  # noqa: E731
            **{f.name: np.stack([getattr(s, f.name) for s in soas])
               for f in fields(soas[0])})
        with obs.span("fleet_step", kind="device"):
            pods, nodes, groups, aggs, prev_cols = self._state
            self._state = None   # donated — the refs die here
            try:
                state, out = ds._fleet_step(
                    pods, nodes, groups, aggs, prev_cols, rows,
                    stack(groups_new), pod_idx, stack(pod_vals),
                    node_idx, stack(node_vals), dirty_idx, nows)
                self._state = state
                out_host = {
                    f.name: np.asarray(getattr(out, f.name))
                    for f in fields(out)
                }
            except BaseException:
                # the donation may already have consumed the old buffers, so
                # the pre-dispatch state is unrecoverable — rebuild the
                # arenas from scratch and force every tenant through a full
                # re-bootstrap (the host twins reset to empty, so each
                # tenant's next diff re-uploads all its lanes). The batch
                # still fails (the scheduler surfaces it per request), but
                # the NEXT batch serves instead of unpacking None forever.
                log.exception(
                    "fleet_step dispatch failed; rebuilding the arenas — "
                    "every tenant re-bootstraps on its next decide")
                self._init_state()
                for t in self._tenants.values():
                    t.pods = _empty_pods(self._P)
                    t.nodes = _empty_nodes(self._N)
                    t.groups = _empty_groups(self._G)
                    t.dirty = np.ones(self._G, bool)
                raise
        # adopt the twins + clear consumed dirty AFTER the dispatch went out
        for tenant, _ps, _ns, new_p, new_n, new_g, _now in diffs:
            tenant.pods, tenant.nodes, tenant.groups = new_p, new_n, new_g
            tenant.dirty = np.zeros(G, bool)
            tenant.ticks += 1
        return out_host

    def _finish(self, i, out_host, tenant, new_secs, now, r, batch_size,
                ds, _kernel):
        """Slice batch row ``i`` back to the request's shapes and run the
        per-tenant lazy-orders tail (ordered re-dispatch when consumed)."""
        if isinstance(r, EvictRequest):
            self._tenants.pop(r.tenant_id, None)
            self._free.append(tenant.slot)
            self._free.sort()
            metrics.fleet_tenant_count.set(len(self._tenants))
            return EvictAck(tenant_id=r.tenant_id)
        G_c, _P_c, N_c = tenant.shapes
        new_p, new_n, _new_g = new_secs
        sliced = {}
        for f in fields(_kernel.DecisionArrays):
            col = out_host[f.name][i]
            if f.name in ("untainted_offsets", "tainted_offsets"):
                sliced[f.name] = col[: G_c + 1]
            elif f.name in _kernel.GROUP_DECISION_FIELDS:
                sliced[f.name] = col[:G_c]
            else:
                sliced[f.name] = col[:N_c]
        tainted_any = bool((np.asarray(new_n.valid)
                            & np.asarray(new_n.tainted)).any())
        needs_orders = tainted_any or bool(
            (sliced["nodes_delta"] < 0).any())
        ordered = False
        if needs_orders:
            sliced = self._ordered_redispatch(
                tenant, now, G_c, N_c, ds, _kernel)
            ordered = True
        out = _kernel.DecisionArrays(**sliced)
        self.decisions += 1
        return FleetDecision(tenant_id=r.tenant_id, arrays=out,
                             ordered=ordered, batch_size=batch_size)

    def _ordered_redispatch(self, tenant, now, G_c, N_c, ds, _kernel):
        """The lazy protocol's ordered tail for ONE tenant: gather its
        resident row and run the full ordered decide fed its maintained
        aggregates — windows bit-exact vs the tenant's standalone ordered
        decide (invalid bucket lanes sort behind every selected lane, so
        the leading windows are unchanged by the arena padding)."""
        with obs.span("fleet_ordered_redispatch", kind="device"):
            pods, nodes, groups, aggs, _cols = self._state
            cluster, aggs_row = ds._fleet_tenant_state(
                pods, nodes, groups, aggs, np.int32(tenant.slot))
            out = obs.fence(_kernel.decide_jit(
                cluster, np.int64(now),
                aggregates=_kernel.aggregates_tuple(aggs_row),
                with_orders=True))
        self.ordered_redispatches += 1
        sliced = {}
        for f in fields(_kernel.DecisionArrays):
            col = np.asarray(getattr(out, f.name))
            if f.name in ("untainted_offsets", "tainted_offsets"):
                sliced[f.name] = col[: G_c + 1]
            elif f.name in _kernel.GROUP_DECISION_FIELDS:
                sliced[f.name] = col[:G_c]
            else:
                sliced[f.name] = col[:N_c]
        return sliced

    # -- self-audit ----------------------------------------------------------

    def audit(self) -> list:
        """Recompute every tenant row's aggregates from the resident arrays
        (``kernel.fleet_compute_aggregates_jit``) and bit-compare against
        the maintained arenas — the fleet form of the round-8 refresh
        audit. Returns the mismatched column names ([] = clean)."""
        from dataclasses import fields as dfields

        from escalator_tpu.ops import kernel as _kernel

        with self._lock:
            pods, nodes, groups, aggs, _cols = self._state
            fresh = _kernel.fleet_compute_aggregates_jit(
                ClusterArrays(groups=groups, pods=pods, nodes=nodes))
            return [
                f.name for f in dfields(_kernel.GroupAggregates)
                if f.name != "dirty"
                and not np.array_equal(np.asarray(getattr(aggs, f.name)),
                                       np.asarray(getattr(fresh, f.name)))
            ]
