"""Fleet-scale decision service (round 14): multi-tenant continuous batching.

One device program decides for an entire fleet of tenants per dispatch:

- :mod:`escalator_tpu.fleet.service` — :class:`FleetEngine`, the device-side
  arena owner: C-stacked resident cluster rows + per-tenant
  ``GroupAggregates`` arenas, host twins for the per-tenant diff, tenant
  lifecycle (register / evict / arena grow / compact), and the fused
  per-micro-batch scatter + delta-decide dispatch
  (``ops.device_state._fleet_step``).
- :mod:`escalator_tpu.fleet.scheduler` — :class:`FleetScheduler`, the
  continuous-batching front: request coalescing into tick-aligned
  micro-batches (size- or deadline-triggered flush), a bounded admission
  queue with backpressure, per-tenant in-flight caps, oldest-first
  fairness, and per-tenant latency series feeding the tail layer.

The gRPC integration lives in ``plugin/server.py`` (``make_server(fleet=…)``)
and ``plugin/codec.py`` (the ``__tenant__`` frame sidecar). See
docs/fleet.md for the operator view.
"""

from escalator_tpu.fleet.scheduler import (
    DEFAULT_CLASSES,
    AdmissionError,
    FleetScheduler,
    PriorityClass,
)
from escalator_tpu.fleet.service import (
    DecideRequest,
    DeltaFrame,
    EvictAck,
    EvictRequest,
    FleetDecision,
    FleetEngine,
    StaleBatchError,
    TenantError,
    validate_tenant_id,
)

__all__ = [
    "AdmissionError", "DEFAULT_CLASSES", "DecideRequest", "DeltaFrame",
    "EvictAck", "EvictRequest", "FleetDecision", "FleetEngine",
    "FleetScheduler", "PartitionRouter", "PriorityClass", "Rebalancer",
    "RouterError", "StaleBatchError", "TenantError", "validate_tenant_id",
]


def __getattr__(name):
    # the router pulls in the gRPC client stack; lazy so embedders of the
    # bare engine/scheduler (and the analysis CLI's pin-before-import
    # dance) never pay for grpc at fleet import time
    if name in ("PartitionRouter", "Rebalancer", "RouterError"):
        from escalator_tpu.fleet import router as _router

        return getattr(_router, name)
    raise AttributeError(name)
