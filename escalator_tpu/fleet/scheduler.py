"""FleetScheduler: continuous batching in front of the FleetEngine.

The plugin's fleet mode serves many tenants from one device mesh; the
scheduler is the admission-and-coalescing layer between their concurrent
RPCs and the engine's one-dispatch-per-micro-batch step:

- **Coalescing**: requests queue and flush as a micro-batch when either the
  batch-size trigger (``max_batch`` waiting) or the deadline trigger (the
  oldest request has waited ``flush_ms``) fires — tick-aligned batching
  without penalizing a lone tenant more than one flush interval.
- **Pipelining (round 16)**: with an engine that exposes the two-stage
  ``prepare_batch``/``execute_batch`` API, a PREP worker assembles batch
  k+1's host diff while the DISPATCH worker's batch k device program is in
  flight (depth-1 staged slot — prep runs at most one batch ahead, and the
  engine executes batches in prepare order). The ``fleet_batch`` flight
  record carries ``overlap_host_ms`` (this batch's prep wall time) and
  ``overlap_saved_ms`` (how much of that prep overlapped recent dispatch
  windows) so the overlap is recorder-proven, not assumed.
- **Priority classes (round 16)**: every request carries a class
  (:class:`PriorityClass`: ``critical``/``standard``/``batch`` by default,
  weights 4/2/1). Batch assembly is weighted-fair across the non-empty
  class queues (oldest-first within a class, still at most one request per
  tenant per batch); a class can be capped to a ``queue_share`` of the
  admission queue (the default ``batch`` class may hold at most half) and
  declares an optional ``p99_target_ms`` — measured per-class p99 (from the
  ``fleet/class/<name>`` histogram series) is checked on a served-request
  cadence and breaches count ``fleet_class_p99_breach_total{klass}``.
- **Admission / backpressure**: the queue is bounded (``queue_limit``); an
  overflowing submit raises :class:`AdmissionError` with a retry-after
  estimate, which the gRPC edge maps to RESOURCE_EXHAUSTED + a
  ``escalator-retry-after-ms`` trailer the client's RetryPolicy honors.
  A ``tenant-inflight`` rejection's retry-after scales with the tenant's
  own in-flight depth plus the queue backlog (a rejected client must not
  thundering-herd back after one flush interval).
- **Per-tenant attribution**: every served request records its
  enqueue-to-completion latency into the streaming histogram layer under a
  tenant-labeled root (``fleet/<tenant>``) AND its class root
  (``fleet/class/<name>``), so per-tenant and per-class p99s ride the same
  PR-8 tail machinery as tick latencies. Errored results are NOT recorded
  (a failed batch's wait time is not service latency).
- **Request journeys (round 17)**: every served decide carries a journey —
  five contiguous, summing-to-e2e stage durations (``admission`` queue
  wait incl. the class-deferral count, ``batch_assembly`` the prep window
  it rode, ``dispatch`` the fused device program's fenced window,
  ``ordered_tail`` its own lazy-orders re-dispatch, ``unpack`` the rest of
  the respond path) — assembled on the RESPOND side (``_record_journey``,
  off the device hot path; the only stamps on the take path are one
  hoisted clock read per flush and per-skip counter increments inside
  ``_take_batch``'s existing single pass). Journeys feed the
  ``(class, stage)`` histograms behind
  ``escalator_tpu_fleet_stage_seconds{klass,stage}``, ride the batch's
  ``fleet_batch`` flight record (Perfetto per-request tracks via
  ``debug-trace``), and ship back to the gRPC caller. Per-class
  **error-budget burn** rides the same rolling window as the p99 check:
  ``fleet_slo_budget_burn{klass}`` publishes the rate, fast burns journal
  an escalation and (``ESCALATOR_TPU_TAIL_PROFILE=1``) arm a profiler
  capture — the PR-10 tail-breach escalation path, now SLO-driven.
"""

from __future__ import annotations

import heapq
import os
import threading
import time
from collections import deque
from concurrent.futures import Future
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple, Union

from escalator_tpu import observability as obs
from escalator_tpu.analysis import lockwitness
from escalator_tpu.fleet.service import (
    DecideRequest,
    EvictRequest,
    FleetEngine,
    TenantError,
    validate_tenant_id,
)
from escalator_tpu.metrics import metrics


@dataclass(frozen=True)
class PriorityClass:
    """One admission class. ``weight`` sets the class's share of each
    micro-batch under saturation (weighted-fair assembly); ``queue_share``
    caps how much of the bounded queue the class may occupy (1.0 = no cap;
    overflow rejects with reason ``queue-full-<name>``); ``p99_target_ms``
    declares the class SLO checked against the measured ``fleet/class/…``
    p99 (None = best effort, never breaches)."""

    name: str
    weight: int = 1
    queue_share: float = 1.0
    p99_target_ms: Optional[float] = None


#: The default class set: latency-sensitive control loops, the steady
#: majority, and best-effort bulk (capped to half the queue so a bulk flood
#: cannot starve admission for the other classes).
DEFAULT_CLASSES = (
    PriorityClass("critical", weight=4, queue_share=1.0, p99_target_ms=100.0),
    PriorityClass("standard", weight=2, queue_share=1.0,
                  p99_target_ms=1000.0),
    PriorityClass("batch", weight=1, queue_share=0.5, p99_target_ms=None),
)

#: served-request cadence for the per-class p99-vs-target check — cheap
#: (one histogram quantile) but not per-request
_SLO_CHECK_EVERY = 16

#: the five REAL journey stages, in pipeline order — their durations sum to
#: the request's endpoint e2e by construction (contiguous wall-clock
#: segments on one monotonic clock); "service" is the derived sixth series
#: (everything after queue wait) the health probe's split reads. The tuple
#: itself is canonical in observability.histograms (the exporter/bench
#: import it there — one definition, no drift).
JOURNEY_STAGES = obs.histograms.JOURNEY_STAGES

#: error-budget burn thresholds for classes with a p99 target: the budget
#: is the 1% of requests a p99 SLO permits over target, and burn is the
#: observed violation fraction over the rolling check window divided by
#: that allowance. 1.0 = burning exactly the allotment; the fast threshold
#: is the classic multi-window page-now rate (Google SRE workbook: budget
#: gone in ~2 days), the slow one the sustained-ticket rate.
SLO_FAST_BURN = 14.4
SLO_SLOW_BURN = 3.0

#: seconds between fast-burn escalations per class (journal + optional
#: profiler arm) — a sustained breach must trickle, not storm
_SLO_ESCALATE_INTERVAL_SEC = 60.0


class AdmissionError(Exception):
    """A request the scheduler refused at the door. ``reason`` is the
    metrics label (queue-full / queue-full-<class> / tenant-inflight);
    ``retry_after_ms`` is the backoff hint shipped to the client as a gRPC
    trailer."""

    def __init__(self, reason: str, retry_after_ms: float):
        super().__init__(
            f"fleet admission rejected ({reason}); retry after "
            f"{retry_after_ms:.0f} ms")
        self.reason = reason
        self.retry_after_ms = float(retry_after_ms)


def _noop_shaped(req) -> bool:
    """A decide carrying an EMPTY delta frame — the streaming twin's
    "nothing changed" shape, which the digest fast path answers from the
    per-tenant decision cache without a device lane. Static on the
    request (no engine state read — the take loop runs concurrently with
    the PREP thread's cache probes), so it is a shape test, not a hit
    prediction: a miss still decides correctly, it just occupies a lane."""
    delta = getattr(req, "delta", None)
    return (delta is not None and len(delta.pod_idx) == 0
            and len(delta.node_idx) == 0 and delta.groups is None)


@dataclass
class _Pending:
    request: Union[DecideRequest, EvictRequest]
    future: Future
    klass: str = "standard"
    enqueued: float = field(default_factory=time.monotonic)
    #: journey bookkeeping (round 17): when the flush took this request
    #: (admission stage closes here) and how many one-per-tenant skips it
    #: ate while queued — both written inside _take_batch's existing single
    #: pass (one hoisted clock read per flush, one attribute store per
    #: request; no locks added)
    taken: float = 0.0
    deferrals: int = 0


class FleetScheduler:
    """Admission queue + micro-batch workers over one :class:`FleetEngine`.

    ``submit``/``evict`` are thread-safe (the gRPC pool calls them
    concurrently); the engine is owned by the worker pair — a PREP thread
    and a DISPATCH thread in pipelined mode (the default when the engine
    has the two-stage API), or one worker running ``engine.step`` when
    ``pipeline=False``."""

    def __init__(self, engine: FleetEngine, max_batch: int = 32,
                 flush_ms: float = 2.0, queue_limit: int = 256,
                 per_tenant_inflight: int = 2,
                 classes: Tuple[PriorityClass, ...] = DEFAULT_CLASSES,
                 default_class: Optional[str] = None,
                 pipeline: bool = True):
        self.engine = engine
        self.max_batch = int(max_batch)
        self.flush_sec = float(flush_ms) / 1e3
        self.queue_limit = int(queue_limit)
        self.per_tenant_inflight = int(per_tenant_inflight)
        self.classes: Dict[str, PriorityClass] = {}
        for c in classes:
            if c.name in self.classes:
                raise ValueError(f"duplicate priority class {c.name!r}")
            if c.weight < 1:
                raise ValueError(f"class {c.name!r} weight must be >= 1")
            self.classes[c.name] = c
        if default_class is None:
            default_class = ("standard" if "standard" in self.classes
                             else next(iter(self.classes)))
        if default_class not in self.classes:
            raise ValueError(f"unknown default class {default_class!r}")
        self.default_class = default_class
        self._queues: Dict[str, deque] = {
            name: deque() for name in self.classes}
        self._cv = lockwitness.make_condition("scheduler.cv")
        self._inflight: Dict[str, int] = {}
        self._paused = False
        self._closed = False
        self.admitted_total = 0
        self.rejected_total = 0
        self.deferred_total = 0
        self.class_breaches: Dict[str, int] = {n: 0 for n in self.classes}
        self._class_served: Dict[str, int] = {n: 0 for n in self.classes}
        # per-class ROLLING window for the SLO check: the lifetime
        # fleet/class/<name> series keeps a breach pinned long after the
        # class recovers (a startup spike dominates the cumulative p99
        # until ~100x as many good samples dilute it) — the breach check
        # reads the samples since the LAST check and resets
        self._slo_windows: Dict[str, obs.histograms.LogHistogram] = {
            n: obs.histograms.LogHistogram() for n in self.classes}
        # error-budget accounting (round 17): [requests, over-target] per
        # class over the SAME rolling window as _slo_windows; the check
        # turns them into a burn rate (violation fraction / the 1% a p99
        # SLO allows), publishes fleet_slo_budget_burn{klass}, and
        # fast-burn escalates (journal event + optional profiler arm)
        self._slo_burn_counts: Dict[str, List[int]] = {
            n: [0, 0] for n in self.classes}
        self.last_burn: Dict[str, float] = {n: 0.0 for n in self.classes}
        self._slo_escalated: Dict[str, float] = {}
        # escalation needs TWO consecutive fast windows: within one ~16-
        # request window every same-batch violation is perfectly
        # correlated, so a single slow batch (GC pause, recompile) reads
        # as burn >= 14.4 — sustained-across-windows is the page signal,
        # one window is a hiccup (the gauge and slo-burn/slo-breach
        # journal events still report immediately)
        self._slo_fast_streak: Dict[str, int] = {n: 0 for n in self.classes}
        # tenant -> {class: queued count}: the evict-class inheritance
        # index (scanning every queued request under the cv put an
        # O(queue_limit) walk on the lock that serializes submit)
        self._queued_classes: Dict[str, Dict[str, int]] = {}
        # rolling fraction of decides the digest fast path answered
        # (round 18): an EMA updated on the respond side — the retry-after
        # estimate discounts the backlog by it, because cached requests
        # never consume a batch slot. Starts at 0.0 so a fleet with no
        # cache hits computes EXACTLY the old estimate.
        self._cache_hit_ema = 0.0
        self.pipelined = bool(pipeline) and hasattr(engine, "prepare_batch")
        # pipelined-mode plumbing: the depth-1 staged slot between the two
        # workers, and the recent dispatch windows the overlap accounting
        # sums a prep window against (prep runs AHEAD of its own dispatch,
        # so its overlap partner is whatever dispatches ran meanwhile)
        self._staged_slot: Optional[tuple] = None
        self._dispatch_windows: deque = deque(maxlen=8)
        self._dispatch_busy_since: Optional[float] = None
        if self.pipelined:
            self._worker = threading.Thread(
                target=self._run_prep, name="escalator-tpu-fleet-prep",
                daemon=True)
            self._dispatcher = threading.Thread(
                target=self._run_dispatch,
                name="escalator-tpu-fleet-dispatch", daemon=True)
            self._dispatcher.start()
        else:
            self._worker = threading.Thread(
                target=self._run, name="escalator-tpu-fleet", daemon=True)
            self._dispatcher = None
        self._worker.start()

    # -- admission ------------------------------------------------------------

    @property
    def queue_depth(self) -> int:
        return sum(len(q) for q in self._queues.values())

    def oldest_waiting_sec(self) -> float:
        """Age of the oldest queued request (0.0 when the queue is empty) —
        the health probe's stale-but-alive signal for the batcher: a live
        scheduler keeps this under ~one flush interval; a wedged worker
        shows it growing tick over tick."""
        with self._cv:
            oldest = self._oldest_enqueued()
            return 0.0 if oldest is None else time.monotonic() - oldest

    def _oldest_enqueued(self) -> Optional[float]:
        heads = [q[0].enqueued for q in self._queues.values() if q]
        return min(heads) if heads else None

    def _reject(self, reason: str, retry_after_ms: float,
                klass: Optional[str] = None,
                tenant: Optional[str] = None):
        self.rejected_total += 1
        metrics.fleet_admission_rejects.labels(reason).inc()
        # the estimate INPUTS ride the journal event (round 18): a flat
        # overestimate under a mostly-idle fleet was only diagnosable by
        # reconstructing the formula — now the reject record carries the
        # terms the backoff was computed from
        obs.journal.JOURNAL.event("admission-reject", reason=reason,
                                  klass=klass, tenant=tenant,
                                  retry_after_ms=round(retry_after_ms, 1),
                                  queue_depth=self.queue_depth,
                                  max_batch=self.max_batch,
                                  flush_ms=round(self.flush_sec * 1e3, 3),
                                  cache_hit_frac=round(
                                      self._cache_hit_ema, 4))
        raise AdmissionError(reason, retry_after_ms)

    def _retry_after_ms(self, extra_batches: float) -> float:
        """Backoff hint: the backlog drains at one ``max_batch`` per flush
        interval; ``extra_batches`` rides on top (a tenant-inflight
        rejection adds the tenant's own depth — each of its requests must
        ride a SEPARATE batch, so its backlog clears serially even when
        the queue is empty). Both terms discount by the rolling digest
        cache-hit fraction (round 18): a cached-capable request never
        consumes a batch slot — it answers at prep time — so under a
        mostly-idle fleet the undiscounted estimate inflated client
        backoff by up to the idle fraction. At a 0.0 hit fraction this is
        bit-for-bit the old formula."""
        live = 1.0 - min(max(self._cache_hit_ema, 0.0), 1.0)
        backlog = self.queue_depth / max(self.max_batch, 1)
        return (extra_batches * live + backlog * live + 1.0) \
            * self.flush_sec * 1e3

    def resolve_class(self, klass: Optional[str]) -> str:
        """Map a request's (optional) class name to a configured class —
        the ONE validation both the gRPC edge and direct callers run."""
        if klass is None:
            return self.default_class
        if klass not in self.classes:
            raise TenantError(
                f"unknown priority class {klass!r} (configured: "
                f"{sorted(self.classes)})")
        return klass

    def submit(self, tenant_id: str, cluster, now_sec: int,
               klass: Optional[str] = None, delta=None) -> Future:
        """Admit one decide. ``delta`` (round 18) is a
        :class:`~escalator_tpu.fleet.service.DeltaFrame` replacing the
        full cluster — ``cluster`` is then None and the engine scatters
        the drain instead of diffing. Raises :class:`TenantError` on a
        malformed tenant id or unknown priority class (before anything
        queues — a bad request never poisons a batch) and
        :class:`AdmissionError` on backpressure."""
        validate_tenant_id(tenant_id)
        klass = self.resolve_class(klass)
        return self._admit(
            DecideRequest(tenant_id, cluster, int(now_sec), delta=delta),
            klass)

    def evict(self, tenant_id: str) -> Future:
        """Admit an eviction (serialized with the decide stream, so a
        decide admitted before the evict still serves). The evict inherits
        the LIGHTEST class among the tenant's queued requests — riding a
        heavier class could dispatch the evict in an EARLIER batch than a
        decide admitted before it, resurrecting the tenant the caller just
        tore down. The unknown-tenant TenantError is NOT counted here —
        the gRPC edge owns the invalid-tenant metric (counting in both
        places double-counted one rejected RPC)."""
        validate_tenant_id(tenant_id)
        if not self.engine.has_tenant(tenant_id):
            raise TenantError(f"unknown tenant {tenant_id!r}")
        with self._cv:
            queued = self._queued_classes.get(tenant_id)
            if queued:
                klass = min(queued, key=lambda n: self.classes[n].weight)
            else:
                klass = self.default_class
        return self._admit(EvictRequest(tenant_id), klass)

    def snapshot_tenant(self, tenant_id: str, timeout_sec: float = 30.0):
        """Quiesce ONE tenant, then freeze its arena row (round 20 — the
        migration source path): wait until the tenant has zero queued and
        zero in-flight requests, then take
        :meth:`FleetEngine.snapshot_tenant_row` at a batch boundary.
        Returns ``(leaves, meta)`` in the tenant-row snapshot format.

        The quiesce covers requests ALREADY admitted — the caller (the
        partition router) owns keeping new ones out by holding the
        tenant's stream for the duration of the migration; this method is
        not a barrier against a second independent client. Other tenants'
        traffic keeps flowing throughout — nothing here pauses the
        scheduler."""
        validate_tenant_id(tenant_id)
        if not self.engine.has_tenant(tenant_id):
            raise TenantError(f"unknown tenant {tenant_id!r}")
        deadline = time.monotonic() + timeout_sec
        while True:
            with self._cv:
                if (tenant_id not in self._inflight
                        and tenant_id not in self._queued_classes):
                    break
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise TimeoutError(
                        f"tenant {tenant_id!r} did not quiesce within "
                        f"{timeout_sec}s "
                        f"(inflight={self._inflight.get(tenant_id, 0)})")
                self._cv.wait(timeout=min(0.05, remaining))
        return self.engine.snapshot_tenant_row(
            tenant_id, timeout_sec=max(deadline - time.monotonic(), 1.0))

    def adopt_tenant(self, leaves, meta) -> tuple:
        """Adopt a tenant-row snapshot on THIS partition (round 20 — the
        migration target path): delegates to
        :meth:`FleetEngine.adopt_tenant_row`, which serializes itself
        against staged batches. Returns ``(shard, row)``."""
        return self.engine.adopt_tenant_row(leaves, meta)

    def _admit(self, request, klass: str) -> Future:
        fut: Future = Future()
        cls = self.classes[klass]
        with self._cv:
            if self._closed:
                raise RuntimeError("fleet scheduler is shut down")
            tid = request.tenant_id
            # tenant cap BEFORE the queue bound: when both apply, the
            # precise reason is the tenant's own chattiness, not the queue
            depth = self._inflight.get(tid, 0)
            if depth >= self.per_tenant_inflight:
                self._reject("tenant-inflight", self._retry_after_ms(depth),
                             klass=klass, tenant=tid)
            if cls.queue_share < 1.0 and len(self._queues[klass]) >= max(
                    1, int(self.queue_limit * cls.queue_share)):
                self._reject(f"queue-full-{klass}", self._retry_after_ms(0),
                             klass=klass, tenant=tid)
            if self.queue_depth >= self.queue_limit:
                self._reject("queue-full", self._retry_after_ms(0),
                             klass=klass, tenant=tid)
            self._inflight[tid] = depth + 1
            self.admitted_total += 1
            self._queues[klass].append(_Pending(request, fut, klass))
            per_tenant = self._queued_classes.setdefault(tid, {})
            per_tenant[klass] = per_tenant.get(klass, 0) + 1
            self._cv.notify_all()
        return fut

    def stats(self) -> dict:
        """One CONSISTENT snapshot of the health counters, taken under the
        scheduler lock — the plugin ``health()`` fleet section reads this
        instead of racing the workers field by field. Includes per-class
        queue depth, served count, measured p99 vs target, and breaches."""
        with self._cv:
            oldest = self._oldest_enqueued()
            per_class = {
                name: {
                    "weight": cls.weight,
                    "queue_depth": len(self._queues[name]),
                    "served": self._class_served[name],
                    "p99_target_ms": cls.p99_target_ms,
                    "breaches": self.class_breaches[name],
                }
                for name, cls in self.classes.items()
            }
            snap = {
                "queue_depth": self.queue_depth,
                "admitted_total": self.admitted_total,
                "rejected_total": self.rejected_total,
                "deferred_total": self.deferred_total,
                "oldest_waiting_sec": round(
                    0.0 if oldest is None
                    else time.monotonic() - oldest, 4),
                "pipelined": self.pipelined,
                "classes": per_class,
            }
        # quantiles OUTSIDE the lock: the histogram layer has its own
        # synchronization, and a health probe must not serialize the hot
        # submit path behind per-class p99 scans
        def _q(h, q):
            v = h.quantile(q) if h is not None else None
            return None if v is None else round(v * 1e3, 3)

        for name, row in per_class.items():
            h = obs.histograms.TICKS.peek(f"fleet/class/{name}")
            row["p99_ms"] = _q(h, 0.99)
            # queue-wait vs service-time split from the journey stage
            # histograms (round 17): stale-but-alive triage can now tell
            # BACKPRESSURE (queue-wait p99 grows, service flat) from SLOW
            # DISPATCH (service p99 grows) without a Prometheus scrape
            qw = obs.histograms.STAGES.peek(name, "admission")
            sv = obs.histograms.STAGES.peek(name, "service")
            row["queue_wait_p50_ms"] = _q(qw, 0.50)
            row["queue_wait_p99_ms"] = _q(qw, 0.99)
            row["service_p50_ms"] = _q(sv, 0.50)
            row["service_p99_ms"] = _q(sv, 0.99)
            row["slo_burn"] = round(self.last_burn.get(name, 0.0), 2)
        return snap

    # -- batch assembly -------------------------------------------------------

    def pause(self) -> None:
        """Hold the workers (tests/smoke drive deterministic backpressure
        by filling the queue against a paused scheduler)."""
        with self._cv:
            self._paused = True

    def resume(self) -> None:
        with self._cv:
            self._paused = False
            self._cv.notify_all()

    def _take_batch(self) -> List[_Pending]:
        """Weighted-fair batch assembly (caller holds the lock), ONE pass
        per queue: each non-empty class gets a slot quota proportional to
        its weight (at least one — head-of-line age stays bounded for
        every class), then leftover capacity fills oldest-first across
        classes via a heap merge over per-class scan cursors. Every queued
        request is visited AT MOST ONCE per flush — the round-14 assembly
        re-scanned every queue from the head for each leftover slot,
        O(queue × batch) under a deep backlog. ``taken`` is the per-flush
        tenant index enforcing at most one request per tenant per batch;
        a skipped request keeps its queue position (a taken tenant stays
        taken for the whole flush, so passing it once is final) and counts
        ``fleet_batch_deferred_total``. Within a class requests leave
        oldest-first. No-op-shaped requests (empty delta frames) are
        taken WITHOUT consuming a batch slot — see ``_noop_shaped``."""
        batch: List[_Pending] = []
        taken: set = set()
        deferred = 0
        # micro-batch (device-lane) slots consumed: no-op-shaped requests
        # (empty delta frames — the streaming twin's idle shape, the
        # digest fast path's target) ride the flush WITHOUT a slot.
        # Counting them against max_batch would cap a mostly-idle fleet
        # at max_batch cached answers per device dispatch; slot-free they
        # all drain in one flush and only real churn pays dispatches.
        # (An idle-shaped request that then MISSES the digest probe — a
        # clock edge, an eviction — still decides correctly; the batch
        # just runs a few lanes over max_batch that flush.)
        slots = 0
        # one clock read per flush: every request this batch takes closes
        # its admission (queue-wait) stage at the same flush instant
        now_take = time.monotonic()
        names = [n for n, q in self._queues.items() if q]
        items = {n: list(self._queues[n]) for n in names}
        consumed = {n: [False] * len(items[n]) for n in names}
        cursor = {n: 0 for n in names}

        def next_free(name: str) -> Optional[int]:
            """Advance the class cursor to its next takeable request,
            counting one-per-tenant skips as it passes them."""
            nonlocal deferred
            lst = items[name]
            i = cursor[name]
            while i < len(lst):
                if lst[i].request.tenant_id in taken:
                    deferred += 1
                    lst[i].deferrals += 1   # journey: class-deferral count
                    i += 1
                    continue
                cursor[name] = i
                return i
            cursor[name] = i
            return None

        def take_at(name: str, i: int) -> bool:
            """Take the request; returns True when it consumed a slot."""
            nonlocal slots
            p = items[name][i]
            consumed[name][i] = True
            cursor[name] = i + 1
            taken.add(p.request.tenant_id)
            p.taken = now_take          # journey: admission stage closes
            batch.append(p)
            self._drop_queued_class(p.request.tenant_id, name)
            if _noop_shaped(p.request):
                return False
            slots += 1
            return True

        total_w = sum(self.classes[n].weight for n in names)
        # phase 1: weighted quotas, heaviest classes first (every active
        # class gets at least one slot — head-of-line age stays bounded
        # for the lightest class too). That guarantee needs a slot per
        # active class: with max_batch SMALLER than the active-class
        # count, heaviest-first quotas would starve the lightest class
        # for as long as heavier queues stay non-empty — skip straight
        # to the oldest-first fill, which is starvation-free.
        if self.max_batch >= len(names):
            for name in sorted(names,
                               key=lambda n: -self.classes[n].weight):
                quota = max(1, (self.max_batch * self.classes[name].weight)
                            // max(total_w, 1))
                while quota > 0 and slots < self.max_batch:
                    i = next_free(name)
                    if i is None:
                        break
                    if take_at(name, i):
                        quota -= 1
        # phase 2: leftover capacity fills oldest-first across classes — a
        # heap merge over the class cursors. A tenant can queue in more
        # than one class, so a popped head re-ranks (re-push) when the
        # cursor had to advance past newly-taken tenants.
        heap: List[Tuple[float, str]] = []
        for name in names:
            i = next_free(name)
            if i is not None:
                heapq.heappush(heap, (items[name][i].enqueued, name))
        while heap and slots < self.max_batch:
            key, name = heapq.heappop(heap)
            i = next_free(name)
            if i is None:
                continue
            if items[name][i].enqueued > key:
                heapq.heappush(heap, (items[name][i].enqueued, name))
                continue
            take_at(name, i)
            j = next_free(name)
            if j is not None:
                heapq.heappush(heap, (items[name][j].enqueued, name))
        # phase 3: the slot cap above stops REAL takes only — vacuum any
        # remaining no-op-shaped requests (slot-free by definition) so an
        # idle backlog drains this flush instead of trickling out
        # max_batch per dispatch behind real churn; real requests keep
        # their queue positions for the next flush.
        if slots >= self.max_batch:
            for name in names:
                for i, p in enumerate(items[name]):
                    if consumed[name][i] or not _noop_shaped(p.request):
                        continue
                    if p.request.tenant_id in taken:
                        deferred += 1
                        p.deferrals += 1
                        continue
                    take_at(name, i)
        # rebuild the queues without the consumed entries, order preserved
        for name in names:
            q = self._queues[name]
            q.clear()
            q.extend(p for p, c in zip(items[name], consumed[name],
                                       strict=True) if not c)
        if deferred:
            self.deferred_total += deferred
            metrics.fleet_batch_deferred.inc(deferred)
        return batch

    def _drop_queued_class(self, tid: str, klass: str) -> None:
        """Decrement the tenant's queued-class index (caller holds the
        lock) — requests leave the queues only here (batch take) and in
        ``shutdown`` (which clears the index wholesale)."""
        per_tenant = self._queued_classes.get(tid)
        if not per_tenant:
            return
        left = per_tenant.get(klass, 1) - 1
        if left > 0:
            per_tenant[klass] = left
        else:
            per_tenant.pop(klass, None)
            if not per_tenant:
                self._queued_classes.pop(tid, None)

    def _flush_wait(self) -> Optional[float]:
        """None when a batch should flush NOW; else how long to wait
        (caller holds the lock)."""
        oldest = self._oldest_enqueued()
        if oldest is None or self._paused:
            return 0.1
        if self.queue_depth >= self.max_batch:
            return None
        age = time.monotonic() - oldest
        if age >= self.flush_sec:
            return None
        return self.flush_sec - age

    # -- the non-pipelined worker --------------------------------------------

    def _run(self) -> None:
        while True:
            with self._cv:
                while True:
                    if self._closed:
                        return
                    wait = self._flush_wait()
                    if wait is None:
                        break
                    self._cv.wait(timeout=wait)
                batch = self._take_batch()
            if batch:
                self._serve(batch)

    def _serve(self, batch: List[_Pending]) -> None:
        try:
            results = self.engine.step([p.request for p in batch])
        except BaseException as e:  # noqa: BLE001 - engine failure fails the batch
            results = [e] * len(batch)
        self._complete(batch, results)

    # -- the pipelined worker pair -------------------------------------------

    def _run_prep(self) -> None:
        """PREP worker: takes a flushed batch, runs the engine's host-side
        prepare, and hands the prepared batch to the dispatch worker via
        the depth-1 staged slot (waiting while the slot is occupied — prep
        runs at most one batch ahead, which the engine's staged-batch
        protocol requires)."""
        while True:
            with self._cv:
                while True:
                    if self._closed:
                        return
                    if self._staged_slot is None:
                        wait = self._flush_wait()
                        if wait is None:
                            break
                        self._cv.wait(timeout=wait)
                    else:
                        self._cv.wait(timeout=0.1)
                batch = self._take_batch()
            if not batch:
                continue
            p0 = time.monotonic()
            try:
                pb = self.engine.prepare_batch([p.request for p in batch])
            except BaseException as e:  # noqa: BLE001 - prep failure fails the batch
                self._complete(batch, [e] * len(batch))
                continue
            p1 = time.monotonic()
            pb.overlap_saved_ms = self._overlap_saved_ms(p0, p1)
            with self._cv:
                self._staged_slot = (batch, pb)
                self._cv.notify_all()

    def _overlap_saved_ms(self, p0: float, p1: float) -> float:
        """How much of the prep window [p0, p1] ran while a device dispatch
        was in flight — summed against the recent dispatch windows (prep
        runs ahead of its OWN dispatch, so its overlap partners are the
        batches dispatched meanwhile). This is the recorder-proven 'host
        work hidden under the device program' number."""
        with self._cv:
            windows = list(self._dispatch_windows)
            if self._dispatch_busy_since is not None:
                windows.append((self._dispatch_busy_since, time.monotonic()))
        saved = 0.0
        for d0, d1 in windows:
            saved += max(0.0, min(p1, d1) - max(p0, d0))
        return saved * 1e3

    def _run_dispatch(self) -> None:
        """DISPATCH worker: executes staged batches in order. On shutdown
        it drains a staged batch first (the in-flight contract: a batch
        that reached prepare either executes or is released — its futures
        never dangle)."""
        while True:
            with self._cv:
                while self._staged_slot is None:
                    if self._closed:
                        return
                    self._cv.wait(timeout=0.1)
                batch, pb = self._staged_slot
                self._staged_slot = None
                self._dispatch_busy_since = time.monotonic()
                self._cv.notify_all()
            try:
                results = self.engine.execute_batch(pb)
            except BaseException as e:  # noqa: BLE001 - engine failure fails the batch
                results = [e] * len(batch)
            with self._cv:
                self._dispatch_windows.append(
                    (self._dispatch_busy_since, time.monotonic()))
                self._dispatch_busy_since = None
            self._complete(batch, results)

    # -- completion -----------------------------------------------------------

    def _complete(self, batch: List[_Pending], results: list) -> None:
        from escalator_tpu.fleet.service import EvictAck

        # the micro-batch size is the DISPATCHED lane count: cached
        # answers never entered the device program (slot-free take), so
        # counting them would both pollute the coalescing signal and
        # break the dashboard's hit-fraction denominator
        n_dispatched = sum(
            1 for r in results if not getattr(r, "cached", False))
        if n_dispatched:
            metrics.fleet_batch_size.observe(n_dispatched)
        done = time.monotonic()
        slo_checks = []
        with self._cv:
            for p in batch:
                tid = p.request.tenant_id
                left = self._inflight.get(tid, 1) - 1
                if left > 0:
                    self._inflight[tid] = left
                else:
                    self._inflight.pop(tid, None)
            for p, res in zip(batch, results, strict=True):
                if isinstance(res, BaseException):
                    # errored results are NOT service latency — recording
                    # them would fold queue wait on a failed batch into the
                    # tenant/class SLO series
                    continue
                if not isinstance(res, EvictAck):
                    # cache-hit EMA for the retry-after discount: decides
                    # only (evicts can never hit), alpha 0.05 ≈ the last
                    # ~20 decides dominate
                    hit = 1.0 if getattr(res, "cached", False) else 0.0
                    self._cache_hit_ema += 0.05 * (hit - self._cache_hit_ema)
                    if hit:
                        metrics.fleet_cache_hits.labels(p.klass).inc()
                self._class_served[p.klass] += 1
                if self._class_served[p.klass] % _SLO_CHECK_EVERY == 0:
                    slo_checks.append(p.klass)
                # error-budget accounting over the same rolling window as
                # the p99 check: a decide counted against its class's
                # target (evicts have no latency contract)
                target = self.classes[p.klass].p99_target_ms
                if target is not None and not isinstance(res, EvictAck):
                    cnt = self._slo_burn_counts[p.klass]
                    cnt[0] += 1
                    if (done - p.enqueued) * 1e3 > target:
                        cnt[1] += 1
            self._cv.notify_all()
        for p, res in zip(batch, results, strict=True):
            if isinstance(res, EvictAck):
                # retire the tenant's series with its arena slot: per-tenant
                # cardinality tracks resident tenants, not every id ever seen
                obs.histograms.TICKS.discard(f"fleet/{p.request.tenant_id}")
            elif not isinstance(res, BaseException):
                # tenant-labeled AND class-labeled root series feeding the
                # PR-8 tail layer: the request's e2e latency (queue wait +
                # batch service) — exported as
                # escalator_tpu_tick_e2e_seconds{root="fleet/..."}
                dur = done - p.enqueued
                obs.histograms.TICKS.observe(
                    (f"fleet/{p.request.tenant_id}",), dur)
                obs.histograms.TICKS.observe(
                    (f"fleet/class/{p.klass}",), dur)
                self._slo_windows[p.klass].record(dur)
                # journey bookkeeping lives HERE, on the respond side —
                # off the device hot path, after every stage boundary is
                # known, before the future resolves (the gRPC edge ships
                # the journey back with the response)
                self._record_journey(p, res, done)
            if isinstance(res, BaseException):
                p.future.set_exception(res)
            else:
                p.future.set_result(res)
        for klass in slo_checks:
            self._check_class_slo(klass)

    def _record_journey(self, p: _Pending, res, done: float) -> None:
        """Assemble one request's journey from the stage boundaries the
        pipeline stamped (enqueue → taken → dispatch window → done, all
        time.monotonic), feed the per-(class, stage) histograms, append to
        the batch's fleet_batch record sink, and attach to the result.

        The five stage durations are CONTIGUOUS wall-clock segments, so
        they sum to the endpoint e2e (``done - enqueued`` — the same value
        the fleet/<tenant> series just recorded) by construction; the
        smoke's 5% tolerance covers only clamp/rounding slack. Engines
        that predate the two-stage stamps (or stub engines in tests)
        contribute a zero-width dispatch window and the time folds into
        batch_assembly/unpack — the sum identity still holds."""
        st = getattr(res, "stages", None) or {}
        t0 = p.enqueued
        t1 = p.taken or t0
        if getattr(res, "cached", False):
            # digest fast path (round 18): the request never entered the
            # micro-batch — everything after the flush took it is the ONE
            # ``cached`` stage (prep-side digest check + answer), and the
            # batch/device stages are honestly zero. The contiguous-
            # segments sum identity still holds: admission + cached ==
            # e2e exactly.
            stages_ms = {
                "admission": (t1 - t0) * 1e3,
                "batch_assembly": 0.0,
                "dispatch": 0.0,
                "ordered_tail": 0.0,
                "unpack": 0.0,
                "cached": (done - t1) * 1e3,
            }
        else:
            t2 = st.get("dispatch_t0") or t1
            t3 = st.get("dispatch_t1") or t2
            # a stale dispatch window (engine stamped an earlier batch)
            # must not produce negative stages: clamp into [t1, done]
            t2 = min(max(t2, t1), done)
            t3 = min(max(t3, t2), done)
            tail_ms = float(st.get("ordered_tail_ms") or 0.0)
            tail_ms = min(tail_ms, max(0.0, (done - t3) * 1e3))
            stages_ms = {
                "admission": (t1 - t0) * 1e3,
                "batch_assembly": (t2 - t1) * 1e3,
                "dispatch": (t3 - t2) * 1e3,
                "ordered_tail": tail_ms,
                "unpack": (done - t3) * 1e3 - tail_ms,
                "cached": 0.0,
            }
        journey = {
            "tenant": p.request.tenant_id,
            "klass": p.klass,
            "deferrals": p.deferrals,
            "enqueued_mono": round(t0, 6),
            "done_mono": round(done, 6),
            "stages_ms": {k: round(v, 4) for k, v in stages_ms.items()},
            "e2e_ms": round((done - t0) * 1e3, 4),
        }
        for stage, ms in stages_ms.items():
            obs.histograms.STAGES.observe((p.klass, stage), ms / 1e3)
        # the derived split the health probe reads: queue wait IS the
        # admission stage; service = everything after the flush took it
        obs.histograms.STAGES.observe((p.klass, "service"),
                                      max(0.0, done - t1))
        sink = st.get("sink")
        if sink is not None:
            sink.append(journey)
        if hasattr(res, "journey"):
            res.journey = journey

    def _check_class_slo(self, klass: str) -> None:
        """Breach check over the ROLLING window (the samples recorded
        since the last check for this class, >= the check cadence): a
        lifetime series would pin one startup spike as a breach for hours
        after the class recovered. The window resets after evaluation, so
        `fleet_class_p99_breach_total` keeps counting exactly while the
        RECENT p99 sits above target and stops one window after recovery
        (the lifetime `fleet/class/<name>` series still feeds the
        Prometheus export and `stats()`)."""
        target = self.classes[klass].p99_target_ms
        if target is None:
            return
        with self._cv:
            window = self._slo_windows[klass]
            self._slo_windows[klass] = obs.histograms.LogHistogram()
            requests, violations = self._slo_burn_counts[klass]
            self._slo_burn_counts[klass] = [0, 0]
        p99 = window.quantile(0.99)
        breached = p99 is not None and p99 * 1e3 > target
        if breached:
            with self._cv:
                self.class_breaches[klass] += 1
            metrics.fleet_class_p99_breach.labels(klass).inc()
        # error-budget burn over the same window: a p99 target allows 1%
        # of requests over it; burn = observed violation fraction / 1%.
        burn = ((violations / requests) / 0.01) if requests else 0.0
        self.last_burn[klass] = burn
        metrics.fleet_slo_budget_burn.labels(klass).set(burn)
        level = ("fast" if burn >= SLO_FAST_BURN
                 else "slow" if burn >= SLO_SLOW_BURN else None)
        if breached or level is not None:
            obs.journal.JOURNAL.event(
                "slo-breach" if breached else "slo-burn", klass=klass,
                p99_ms=None if p99 is None else round(p99 * 1e3, 3),
                target_ms=target, burn=round(burn, 2),
                level=level or "none", window_requests=requests)
        with self._cv:
            streak = (self._slo_fast_streak[klass] + 1 if level == "fast"
                      else 0)
            self._slo_fast_streak[klass] = streak
        if streak >= 2:
            self._escalate_slo(klass, burn, p99, target)

    def _escalate_slo(self, klass: str, burn: float,
                      p99: Optional[float], target: float) -> None:
        """Fast-burn escalation — fired only on the SECOND consecutive
        fast window (see ``_slo_fast_streak``) and rate-limited per class:
        a journal event plus — when ``ESCALATOR_TPU_TAIL_PROFILE=1``, the
        same opt-in that arms the tail watchdog's capture — a jax profiler
        capture of the next K ticks into the dump directory, so a burning
        SLO on a TPU campaign yields an on-chip profile with no human in
        the loop.
        The arm runs on a daemon worker (the watchdog-dump discipline):
        ``jax.profiler.start_trace`` was measured taking ~16 s on its
        FIRST call in a process, and the completion path must never pay
        that. The worker journals the arm outcome as a follow-up
        ``slo-profile-armed`` event."""
        now = time.monotonic()
        with self._cv:
            if (now - self._slo_escalated.get(klass, -float("inf"))
                    < _SLO_ESCALATE_INTERVAL_SEC):
                return
            self._slo_escalated[klass] = now
        profile_on = os.environ.get(
            "ESCALATOR_TPU_TAIL_PROFILE", "").lower() in ("1", "true", "yes")
        obs.journal.JOURNAL.event(
            "slo-escalation", klass=klass, burn=round(burn, 2),
            p99_ms=None if p99 is None else round(p99 * 1e3, 3),
            target_ms=target, profile_requested=profile_on)
        if not profile_on:
            return

        def _arm():
            try:
                from escalator_tpu.observability import (
                    flightrecorder,
                    resources,
                )

                ticks = int(os.environ.get(
                    "ESCALATOR_TPU_TAIL_PROFILE_TICKS", "4"))
                out_dir = os.path.join(
                    flightrecorder.dump_dir(),
                    f"escalator-tpu-profile-slo-{klass}-{os.getpid()}-"
                    f"{int(time.time())}")
                profile = dict(resources.PROFILER.start(ticks, out_dir))
            except Exception as e:  # noqa: BLE001 - never break anything
                profile = {"ok": False, "error": str(e)}
            obs.journal.JOURNAL.event("slo-profile-armed", klass=klass,
                                      profile=profile)

        threading.Thread(target=_arm, name="escalator-slo-profile",
                         daemon=True).start()

    # -- shutdown -------------------------------------------------------------

    def shutdown(self) -> None:
        """Stop the workers. The in-flight/staged batch DRAINS (its futures
        resolve with real results); queued-but-never-prepped requests fail
        with RuntimeError. A staged batch the dispatch worker could not
        drain (wedged engine) is released back to the engine so its twin
        adoption unwinds, and its futures fail."""
        with self._cv:
            self._closed = True
            pending = [p for q in self._queues.values() for p in q]
            for q in self._queues.values():
                q.clear()
            self._queued_classes.clear()
            self._cv.notify_all()
        for p in pending:
            p.future.set_exception(RuntimeError("fleet scheduler shut down"))
        self._worker.join(timeout=5.0)
        if self._dispatcher is not None:
            self._dispatcher.join(timeout=10.0)
            leftover = None
            with self._cv:
                leftover = self._staged_slot
                self._staged_slot = None
            if leftover is not None:
                batch, pb = leftover
                try:
                    self.engine.release_prepared(pb)
                except Exception:  # noqa: BLE001 - release is best-effort here
                    pass
                err = RuntimeError("fleet scheduler shut down")
                for p in batch:
                    if not p.future.done():
                        p.future.set_exception(err)
