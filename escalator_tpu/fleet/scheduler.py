"""FleetScheduler: continuous batching in front of the FleetEngine.

The plugin's fleet mode serves many tenants from one device; the scheduler
is the admission-and-coalescing layer between their concurrent RPCs and the
engine's one-dispatch-per-micro-batch step:

- **Coalescing**: requests queue and flush as a micro-batch when either the
  batch-size trigger (``max_batch`` waiting) or the deadline trigger (the
  oldest request has waited ``flush_ms``) fires — tick-aligned batching
  without penalizing a lone tenant more than one flush interval.
- **Admission / backpressure**: the queue is bounded (``queue_limit``); an
  overflowing submit raises :class:`AdmissionError` with a retry-after
  estimate, which the gRPC edge maps to RESOURCE_EXHAUSTED + a
  ``escalator-retry-after-ms`` trailer the client's RetryPolicy honors.
- **Fairness under overload**: per-tenant in-flight caps
  (``per_tenant_inflight``) stop one chatty tenant from occupying the whole
  queue, and batch assembly walks the queue oldest-first, taking at most
  one request per tenant per batch (a tenant's second request rides the
  NEXT batch — the engine's arenas require it, and it keeps head-of-line
  age bounded for everyone else).
- **Per-tenant attribution**: every served request records its
  enqueue-to-completion latency into the streaming histogram layer under a
  tenant-labeled root (``fleet/<tenant>`` in
  ``escalator_tpu_tick_e2e_seconds``), so per-tenant p99s ride the same
  PR-8 tail machinery as tick latencies.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from concurrent.futures import Future
from dataclasses import dataclass, field
from typing import Dict, Union

from escalator_tpu import observability as obs
from escalator_tpu.fleet.service import (
    DecideRequest,
    EvictRequest,
    FleetEngine,
    TenantError,
    validate_tenant_id,
)
from escalator_tpu.metrics import metrics


class AdmissionError(Exception):
    """A request the scheduler refused at the door. ``reason`` is the
    metrics label (queue-full / tenant-inflight); ``retry_after_ms`` is the
    backoff hint shipped to the client as a gRPC trailer."""

    def __init__(self, reason: str, retry_after_ms: float):
        super().__init__(
            f"fleet admission rejected ({reason}); retry after "
            f"{retry_after_ms:.0f} ms")
        self.reason = reason
        self.retry_after_ms = float(retry_after_ms)


@dataclass
class _Pending:
    request: Union[DecideRequest, EvictRequest]
    future: Future
    enqueued: float = field(default_factory=time.monotonic)


class FleetScheduler:
    """Admission queue + micro-batch worker over one :class:`FleetEngine`.

    ``submit``/``evict`` are thread-safe (the gRPC pool calls them
    concurrently); one daemon worker owns the engine."""

    def __init__(self, engine: FleetEngine, max_batch: int = 32,
                 flush_ms: float = 2.0, queue_limit: int = 256,
                 per_tenant_inflight: int = 2):
        self.engine = engine
        self.max_batch = int(max_batch)
        self.flush_sec = float(flush_ms) / 1e3
        self.queue_limit = int(queue_limit)
        self.per_tenant_inflight = int(per_tenant_inflight)
        self._q: deque = deque()
        self._cv = threading.Condition()
        self._inflight: Dict[str, int] = {}
        self._paused = False
        self._closed = False
        self.admitted_total = 0
        self.rejected_total = 0
        self._worker = threading.Thread(
            target=self._run, name="escalator-tpu-fleet", daemon=True)
        self._worker.start()

    # -- admission ------------------------------------------------------------

    @property
    def queue_depth(self) -> int:
        return len(self._q)

    def oldest_waiting_sec(self) -> float:
        """Age of the oldest queued request (0.0 when the queue is empty) —
        the health probe's stale-but-alive signal for the batcher: a live
        scheduler keeps this under ~one flush interval; a wedged worker
        shows it growing tick over tick."""
        with self._cv:
            if not self._q:
                return 0.0
            return time.monotonic() - self._q[0].enqueued

    def _reject(self, reason: str, retry_after_ms: float):
        self.rejected_total += 1
        metrics.fleet_admission_rejects.labels(reason).inc()
        raise AdmissionError(reason, retry_after_ms)

    def submit(self, tenant_id: str, cluster, now_sec: int) -> Future:
        """Admit one decide. Raises :class:`TenantError` on a malformed
        tenant id (before anything queues — a bad request never poisons a
        batch) and :class:`AdmissionError` on backpressure."""
        validate_tenant_id(tenant_id)
        return self._admit(DecideRequest(tenant_id, cluster, int(now_sec)))

    def evict(self, tenant_id: str) -> Future:
        """Admit an eviction (serialized with the decide stream, so a
        decide admitted before the evict still serves). The unknown-tenant
        TenantError is NOT counted here — the gRPC edge owns the
        invalid-tenant metric (counting in both places double-counted one
        rejected RPC)."""
        validate_tenant_id(tenant_id)
        if not self.engine.has_tenant(tenant_id):
            raise TenantError(f"unknown tenant {tenant_id!r}")
        return self._admit(EvictRequest(tenant_id))

    def _admit(self, request) -> Future:
        fut: Future = Future()
        with self._cv:
            if self._closed:
                raise RuntimeError("fleet scheduler is shut down")
            tid = request.tenant_id
            # tenant cap BEFORE the queue bound: when both apply, the
            # precise reason is the tenant's own chattiness, not the queue
            if self._inflight.get(tid, 0) >= self.per_tenant_inflight:
                self._reject("tenant-inflight", self.flush_sec * 1e3)
            if len(self._q) >= self.queue_limit:
                # retry-after: how long the backlog takes to drain at one
                # max_batch per flush interval (floor one interval)
                est = (len(self._q) / max(self.max_batch, 1) + 1.0) * (
                    self.flush_sec * 1e3)
                self._reject("queue-full", est)
            self._inflight[tid] = self._inflight.get(tid, 0) + 1
            self.admitted_total += 1
            self._q.append(_Pending(request, fut))
            self._cv.notify()
        return fut

    # -- the worker -----------------------------------------------------------

    def pause(self) -> None:
        """Hold the worker (tests/smoke drive deterministic backpressure by
        filling the queue against a paused worker)."""
        with self._cv:
            self._paused = True

    def resume(self) -> None:
        with self._cv:
            self._paused = False
            self._cv.notify()

    def _take_batch(self):
        """Oldest-first batch assembly, at most one request per tenant —
        skipped requests keep their queue position for the next batch."""
        batch = []
        taken_tenants = set()
        kept = deque()
        while self._q and len(batch) < self.max_batch:
            p = self._q.popleft()
            if p.request.tenant_id in taken_tenants:
                kept.append(p)
                continue
            taken_tenants.add(p.request.tenant_id)
            batch.append(p)
        kept.extend(self._q)
        self._q = kept
        return batch

    def _run(self) -> None:
        while True:
            with self._cv:
                while True:
                    if self._closed:
                        return
                    if self._q and not self._paused:
                        age = time.monotonic() - self._q[0].enqueued
                        if (len(self._q) >= self.max_batch
                                or age >= self.flush_sec):
                            break
                        self._cv.wait(timeout=self.flush_sec - age)
                    else:
                        self._cv.wait(timeout=0.1)
                batch = self._take_batch()
            if batch:
                self._serve(batch)

    def _serve(self, batch) -> None:
        metrics.fleet_batch_size.observe(len(batch))
        try:
            results = self.engine.step([p.request for p in batch])
        except BaseException as e:  # noqa: BLE001 - engine failure fails the batch
            results = [e] * len(batch)
        done = time.monotonic()
        with self._cv:
            for p in batch:
                tid = p.request.tenant_id
                left = self._inflight.get(tid, 1) - 1
                if left > 0:
                    self._inflight[tid] = left
                else:
                    self._inflight.pop(tid, None)
            self._cv.notify()
        from escalator_tpu.fleet.service import EvictAck

        for p, res in zip(batch, results, strict=True):
            if isinstance(res, EvictAck):
                # retire the tenant's series with its arena slot: per-tenant
                # cardinality tracks resident tenants, not every id ever seen
                obs.histograms.TICKS.discard(f"fleet/{p.request.tenant_id}")
            else:
                # tenant-labeled root series feeding the PR-8 tail layer:
                # the request's e2e latency (queue wait + batch service),
                # one histogram per tenant — exported as
                # escalator_tpu_tick_e2e_seconds{root="fleet/<tenant>"}
                obs.histograms.TICKS.observe(
                    (f"fleet/{p.request.tenant_id}",), done - p.enqueued)
            if isinstance(res, BaseException):
                p.future.set_exception(res)
            else:
                p.future.set_result(res)

    def shutdown(self) -> None:
        with self._cv:
            self._closed = True
            pending = list(self._q)
            self._q.clear()
            self._cv.notify_all()
        for p in pending:
            p.future.set_exception(RuntimeError("fleet scheduler shut down"))
        self._worker.join(timeout=5.0)
