"""escalator-tpu: TPU-native rebuild of the Atlassian Escalator batch autoscaler.

Layer map (mirrors SURVEY.md §1 of the reference, re-architected TPU-first):

- ``escalator_tpu.core``       — typed cluster state, dense arrays, golden semantics
- ``escalator_tpu.ops``        — batched JAX/XLA decision kernels
- ``escalator_tpu.parallel``   — mesh sharding: group axis, pod axis, 2-D grid
  (shard_map/pjit over flat or hybrid dcn/ici meshes)
- ``escalator_tpu.analysis``   — jaxlint: jaxpr/HLO-level invariant analyzer
  over every kernel entry point (CI gate, ``python -m escalator_tpu.analysis``)
- ``escalator_tpu.controller`` — the imperative controller shell (tick loop, executors)
- ``escalator_tpu.k8s``        — k8s object model, listers, taint mechanics, election
- ``escalator_tpu.cloudprovider`` — provider SPI + implementations
- ``escalator_tpu.metrics``    — Prometheus metrics (same `escalator_*` names)
- ``escalator_tpu.plugin``     — gRPC compute-plugin service wrapping the solver
- ``escalator_tpu.testsupport``— fake cluster builders, mock providers
"""

__version__ = "0.3.0"
