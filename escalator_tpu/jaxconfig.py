"""JAX global configuration for exact-parity arithmetic.

The decision math must match the reference's Go float64/int64 semantics bit-for-bit
(SURVEY.md §7 "bit-exact parity"). JAX defaults to 32-bit; we enable x64 once, before
any kernel is traced. The f64 work is tiny ([num_groups]-shaped scalars) — the heavy
[num_pods] segment sums stay integer — so TPU f64 emulation cost is negligible here.
"""

from __future__ import annotations

_configured = False


def ensure_x64() -> None:
    global _configured
    if _configured:
        return
    import jax

    jax.config.update("jax_enable_x64", True)
    _configured = True


def shard_map(f=None, *, mesh, in_specs, out_specs, check_vma: bool = True):
    """``jax.shard_map`` compatibility seam: newer jax exposes it top-level
    with a ``check_vma`` kwarg; the 0.4.x line ships
    ``jax.experimental.shard_map.shard_map`` with the same knob named
    ``check_rep``. Every shard_map in this codebase goes through here so a
    jax upgrade/downgrade is one function's concern. Usable directly or as
    ``@partial(shard_map, mesh=..., in_specs=..., out_specs=...)``."""
    import functools

    import jax

    if f is None:
        return functools.partial(
            shard_map, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=check_vma,
        )
    try:
        sm = jax.shard_map
        kw = {"check_vma": check_vma}
    except AttributeError:
        from jax.experimental.shard_map import shard_map as sm

        kw = {"check_rep": check_vma}
    return sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kw)


_probe_result = None


def _backends_already_initialized() -> bool:
    """True when this process has live jax backends. Pinning jax_platforms
    after initialization is a no-op, so probing can neither help nor be
    trusted — a parent that holds the accelerator exclusively would make the
    probe SUBPROCESS fail and falsely degrade a healthy device."""
    try:
        from jax._src import xla_bridge

        return bool(xla_bridge._backends)
    except Exception:  # pragma: no cover - private API moved; fall through
        return False


def _pinned_to_cpu() -> bool:
    """True when jax_platforms is already pinned to cpu (tests, a previous
    degrade): the CPU backend cannot wedge, and the probe subprocess would
    probe the DEFAULT platform (a machine sitecustomize may pin the tunnel
    there), hanging for no reason."""
    try:
        import jax

        return jax.config.jax_platforms == "cpu"
    except Exception:  # pragma: no cover
        return False


def guarded_devices() -> list:
    """``jax.devices()`` behind the wedged-transport probe. Backend init is
    exactly the call that hangs forever on a wedged tunnel, and raw library
    use (mesh constructors, device caches — no CLI/backend guard upstream)
    reaches it first. The probe is cached process-wide and fast-paths once
    backends are live or the platform is cpu-pinned."""
    ensure_responsive_accelerator()
    import jax

    return jax.devices()


def ensure_responsive_accelerator(
    timeout_sec: float = 90.0,
    attempts: int = 1,
    retry_wait_sec: float = 20.0,
    attempt_log: "str | None" = None,
) -> bool:
    """Probe the default JAX platform in a SUBPROCESS and pin the CPU backend
    if it does not answer. Some accelerator transports (the TPU tunnel this
    repo targets) can wedge indefinitely at the first dispatch; a long-lived
    controller must degrade to XLA-CPU (the same traced program — decisions
    stay bit-identical) rather than hang its control loop forever. In-process
    timeouts cannot interrupt a wedged dispatch, hence the subprocess; the
    platform pin must go through jax.config because environments may pin
    platforms in sitecustomize, ignoring JAX_PLATFORMS.

    Returns True when NO DEGRADE IS NEEDED — which means "a probe answered"
    only when a probe actually ran. The fast paths below return True for a
    process that is merely cpu-pinned or already initialized (nothing a probe
    could change); callers must not surface the return value as "live
    accelerator verified". Result is cached (one probe campaign per process)
    except on those fast paths.

    ``attempts > 1`` retries a failed probe after ``retry_wait_sec`` — the
    tunnel this repo targets wedges for long stretches and sometimes recovers,
    so callers that can afford the wait (the benchmark harness) should probe
    more than once before settling for the CPU. Every attempt is appended to
    ``attempt_log`` (timestamped, auditable) when given."""
    global _probe_result
    if _probe_result is not None:
        return _probe_result
    if _backends_already_initialized() or _pinned_to_cpu():
        # library-embedding fast paths (see the helpers): nothing a probe
        # could change, so report healthy and leave the process alone. NOT
        # cached: a caller that later unpins/reinitializes deserves a real
        # probe campaign.
        return True
    import subprocess
    import sys
    import time as _time

    def _note(msg: str) -> None:
        if attempt_log:
            try:
                from datetime import datetime, timezone

                stamp = datetime.now(timezone.utc).strftime("%Y-%m-%dT%H:%M:%SZ")
                with open(attempt_log, "a") as f:
                    f.write(f"{stamp} {msg}\n")
            except OSError:
                pass

    code = "import jax; jax.block_until_ready(jax.numpy.ones(8))"
    alive = False
    for attempt in range(max(1, attempts)):
        if attempt:
            _time.sleep(retry_wait_sec)
        try:
            alive = (
                subprocess.run(
                    [sys.executable, "-c", code],
                    timeout=timeout_sec,
                    capture_output=True,
                ).returncode
                == 0
            )
        except Exception:
            alive = False
        _note(
            f"bench probe attempt {attempt + 1}/{attempts}: "
            + ("OK" if alive else f"no answer within {timeout_sec:.0f}s")
        )
        if alive:
            break
    if not alive:
        import logging

        try:
            import jax
        except ImportError:
            # jax-less install: nothing to pin; callers fall back to the
            # dependency-free golden backend (make_backend("auto"))
            _probe_result = False
            return False
        logging.getLogger("escalator_tpu").warning(
            "accelerator did not answer a probe within %.0fs; pinning the CPU"
            " backend (same traced kernels, bit-identical decisions)",
            timeout_sec,
        )
        jax.config.update("jax_platforms", "cpu")
    _probe_result = alive
    return alive


#: Platforms where COMPILED Pallas kernels exist ("axon" is the TPU tunnel's
#: platform name). Single source for pallas_kernel._use_interpret (interpret
#: off these platforms) and ops.kernel.native_tick_impl (never default the
#: production hot path onto interpreter-mode Pallas).
PALLAS_COMPILED_PLATFORMS = ("tpu", "axon")
