"""JAX global configuration for exact-parity arithmetic.

The decision math must match the reference's Go float64/int64 semantics bit-for-bit
(SURVEY.md §7 "bit-exact parity"). JAX defaults to 32-bit; we enable x64 once, before
any kernel is traced. The f64 work is tiny ([num_groups]-shaped scalars) — the heavy
[num_pods] segment sums stay integer — so TPU f64 emulation cost is negligible here.
"""

from __future__ import annotations

_configured = False


def ensure_x64() -> None:
    global _configured
    if _configured:
        return
    import jax

    jax.config.update("jax_enable_x64", True)
    _configured = True
