# Developer entrypoints (the reference ships the same one-command workflow:
# /root/reference/Makefile:13-17 — test = unit+race+cover, vet, lint).
# The race detector's role here is played by the threaded concurrency soak,
# which runs as part of the suite (tests/test_concurrency_soak.py).

.PHONY: test lint typecheck analyze build-native bench dryrun clean

test:
	python -m pytest tests/ -x -q

# CI installs ruff (see .github/workflows/ci.yml); on a rig without it,
# degrade to a syntax sweep so `make lint` still catches E9-class breakage
lint:
	@if command -v ruff >/dev/null 2>&1; then \
	  ruff check escalator_tpu tests bench.py; \
	else \
	  echo "ruff not installed (CI runs the full check); syntax sweep only"; \
	  python -m compileall -q escalator_tpu tests bench.py; \
	fi

# scoped to the annotated core subset (pyproject [tool.mypy] files=...);
# the full-tree sweep is the CI typecheck-full job, staged non-blocking
typecheck:
	mypy

# jaxlint: jaxpr/HLO-level invariant analysis over every kernel entry point
# both static-analysis passes (docs/static-analysis.md): threadlint first
# (rules T1-T4 — pure AST, no jax import, fails in milliseconds), then
# jaxlint (rules R1-R8 — pins cpu + 8 virtual devices itself). Nonzero
# exit on any unwaived finding — a blocking CI step.
analyze:
	python -m escalator_tpu.analysis --threadlint
	python -m escalator_tpu.analysis

# the C++ state store builds lazily on first use; this forces a fresh build
build-native:
	g++ -O2 -shared -fPIC -std=c++17 \
	  -o escalator_tpu/native/libessstate.so escalator_tpu/native/statestore.cpp

bench:
	python bench.py

# multi-chip sharding validation on 8 virtual devices (no TPU needed)
dryrun:
	JAX_PLATFORMS=cpu python -c "import __graft_entry__ as g; g.dryrun_multichip(8)"

clean:
	rm -f escalator_tpu/native/libessstate.so
	find . -name __pycache__ -type d -prune -exec rm -rf {} +
