"""R6 regression: the kernel/grid/podaxis entries compile EXACTLY once per
program variant across a two-tick smoke sweep.

The analyzer's R6 rule pins an upper bound; this test pins the exact count,
on shapes no other test uses (primes — a shared jit cache entry from another
test file would make "0 compiles" pass a broken cache-key silently). What it
catches: accidental static-argnum churn (a python scalar that should be a
traced array, a dict arg that rebuilds each tick, a numpy scalar flipping
weak-type), which melts the jit cache and turns every tick into a
multi-second retrace — invisible to correctness tests, fatal to the 50 ms
budget.
"""

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from escalator_tpu.analysis.registry import representative_cluster  # noqa: E402
from escalator_tpu.ops import kernel, order_tail  # noqa: E402
from escalator_tpu.parallel import grid, mesh as pmesh, podaxis  # noqa: E402

# Shapes unique to this file (primes; no other test traces these sizes).
G, P, N = 7, 184, 61          # P % 8 == 0 for the podaxis mesh split
SG, SP, SN = 5, 24, 11        # per-shard sizes for the stacked grid layout
NOW = np.int64(1_700_000_123)


def _cluster(seed):
    return representative_cluster(G=G, P=P, N=N, seed=seed)


def test_kernel_decide_compiles_once_per_variant():
    before = kernel._decide_jit_raw._cache_size()
    for seed in (101, 102):                      # two ticks, fresh data
        for with_orders in (True, False):        # ordered + lazy-light
            jax.block_until_ready(
                kernel._decide_jit_raw(_cluster(seed), NOW,
                                       with_orders=with_orders)
            )
    compiles = kernel._decide_jit_raw._cache_size() - before
    assert compiles == 2, (
        f"expected exactly 2 compiles (ordered + light), got {compiles}: "
        "the second tick retraced — look for static-argnum/weak-type churn"
    )


def test_podaxis_decider_compiles_once_across_block_rebalance():
    m = pmesh.make_mesh()
    decider = podaxis.make_podaxis_decider(m)
    before = decider._cache_size()
    for seed in (111, 112):
        cluster = podaxis.pad_pods_for_mesh(_cluster(seed), m)
        blocks = order_tail.assign_order_blocks(
            np.asarray(cluster.nodes.group), np.asarray(cluster.nodes.valid),
            int(m.devices.size), num_groups=G,
        )
        # a backend holds a high-water-mark width exactly so the per-tick
        # block rebalance cannot retrace; replicate that here
        blocks = order_tail.pad_order_blocks(blocks, N)
        jax.block_until_ready(decider(cluster, NOW, blocks))
    compiles = decider._cache_size() - before
    assert compiles == 1, (
        f"expected exactly 1 compile for two block-sharded ticks, got "
        f"{compiles}"
    )


def test_delta_decide_compiles_once_per_dirty_bucket():
    """The incremental decide's jit cache keys on the dirty BUCKET width
    (kernel.dirty_indices: power-of-two, min 8, capped at G), not the dirty
    set itself: two ticks with different dirty rows in the same bucket hit
    the cache; crossing a bucket boundary compiles exactly once more. Uses
    file-unique prime shapes (G=23 so buckets 8 and 16 are both reachable).
    """
    DG, DP, DN = 23, 206, 59
    cluster = representative_cluster(G=DG, P=DP, N=DN, seed=131)
    aggs = kernel.compute_aggregates_jit(cluster)
    light = kernel._decide_jit_raw(cluster, NOW, with_orders=False)
    prev = tuple(getattr(light, f) for f in kernel.GROUP_DECISION_FIELDS)

    def tick(dirty_rows):
        nonlocal aggs, prev
        mask = np.zeros(DG, bool)
        mask[dirty_rows] = True
        idx = kernel.dirty_indices(mask)
        out, aggs = kernel._delta_decide_raw(cluster, aggs, prev, idx, NOW)
        jax.block_until_ready(out)
        prev = tuple(getattr(out, f) for f in kernel.GROUP_DECISION_FIELDS)
        return idx.shape[0]

    before = kernel._delta_decide_raw._cache_size()
    assert tick([1, 2, 3]) == 8          # bucket 8
    assert tick([5, 9]) == 8             # same bucket, different rows
    compiles = kernel._delta_decide_raw._cache_size() - before
    assert compiles == 1, (
        f"expected exactly 1 compile for two same-bucket delta ticks, got "
        f"{compiles}: the dirty-row CONTENTS must not be a cache key"
    )
    assert tick(list(range(11))) == 16   # bucket 16: one more compile
    assert tick(list(range(9))) == 16    # back in bucket 16: cached
    compiles = kernel._delta_decide_raw._cache_size() - before
    assert compiles == 2, (
        f"expected exactly 2 compiles across buckets 8 and 16, got {compiles}"
    )


def test_grid_decider_compiles_once():
    m = grid.make_grid_mesh(num_group_shards=4)

    def stacked(seed):
        shards = [
            representative_cluster(G=SG, P=SP, N=SN, seed=seed + s)
            for s in range(4)
        ]
        leaves = [c.tree_flatten()[0] for c in shards]
        from escalator_tpu.core.arrays import ClusterArrays

        return grid.pad_stacked_pods_for_grid(
            ClusterArrays.tree_unflatten(
                None, [np.stack(parts) for parts in zip(*leaves, strict=True)]
            ),
            m,
        )

    decider = grid.make_grid_decider(m)
    before = decider._cache_size()
    for seed in (121, 122):
        jax.block_until_ready(decider(stacked(seed), NOW))
    compiles = decider._cache_size() - before
    assert compiles == 1, (
        f"expected exactly 1 compile for two grid ticks, got {compiles}"
    )
