"""Round 17: request journeys, SLO error budgets, the ops event journal,
and the strict env parsers.

Everything here drives the REAL FleetScheduler with device-free stub
engines (the admission/journey/SLO logic needs no jax compile), so the
whole file costs well under the tier-1 time-neutrality bar; the real-gRPC
journey decomposition, Journal RPC round-trip and overhead gate live in
``bench.py --smoke`` (tests/test_bench_smoke.py runs it)."""

from __future__ import annotations

import json
import time

import pytest

from escalator_tpu import observability as obs
from escalator_tpu.fleet.scheduler import (
    JOURNEY_STAGES,
    SLO_FAST_BURN,
    FleetScheduler,
    PriorityClass,
)
from escalator_tpu.fleet.service import (
    EvictAck,
    EvictRequest,
    FleetDecision,
)
from escalator_tpu.observability import histograms as hg
from escalator_tpu.observability import journal as journal_mod
from escalator_tpu.observability import spans, tail
from escalator_tpu.utils import envparse


# --------------------------------------------------------------- stub engine
class _JourneyEngine:
    """Device-free engine returning REAL FleetDecision objects with the
    round-17 stage stamps (a fenced-window stand-in via sleep) and the
    shared journey sink — the scheduler path under test is identical to
    production's."""

    def __init__(self, exec_sec: float = 0.002, tail_ms: float = 0.0):
        self.exec_sec = exec_sec
        self.tail_ms = tail_ms
        self.sink: list = []
        self.tenants: set = set()

    @property
    def tenant_count(self):
        return len(self.tenants)

    def has_tenant(self, tid):
        return tid in self.tenants

    def step(self, requests):
        t0 = time.monotonic()
        if self.exec_sec:
            time.sleep(self.exec_sec)
        t1 = time.monotonic()
        out = []
        for r in requests:
            if isinstance(r, EvictRequest):
                self.tenants.discard(r.tenant_id)
                out.append(EvictAck(r.tenant_id))
                continue
            self.tenants.add(r.tenant_id)
            out.append(FleetDecision(
                tenant_id=r.tenant_id, arrays=None, ordered=False,
                batch_size=len(requests),
                stages={"dispatch_t0": t0, "dispatch_t1": t1,
                        "ordered_tail_ms": self.tail_ms,
                        "sink": self.sink}))
        return out


# ------------------------------------------------------------ strict envparse
def test_envparse_int_strict_rejections():
    for bad in ("0", "-3", "abc", "1.5", "--", "off"):
        with pytest.raises(ValueError):
            envparse.parse_env_int(bad, "KNOB")
    # "off" allowed only when the knob documents it
    assert envparse.parse_env_int("off", "KNOB", allow_off=True) == 0
    assert envparse.parse_env_int("7", "KNOB") == 7
    assert envparse.parse_env_int(None, "KNOB") is None
    assert envparse.parse_env_int("  ", "KNOB") is None
    assert envparse.parse_env_int("2", "KNOB", minimum=2) == 2
    with pytest.raises(ValueError):
        envparse.parse_env_int("1", "KNOB", minimum=2)
    # the knob name must reach the operator's eyes
    with pytest.raises(ValueError, match="MY_KNOB"):
        envparse.parse_env_int("junk", "MY_KNOB")


def test_envparse_float_strict_rejections():
    for bad in ("0", "-1", "nonsense"):
        with pytest.raises(ValueError):
            envparse.parse_env_float(bad, "KNOB")
    assert envparse.parse_env_float("2.5", "KNOB") == 2.5
    assert envparse.parse_env_float(None, "KNOB") is None
    assert envparse.parse_env_float("off", "KNOB", allow_off=True) == 0.0
    # TAIL_CAPTURE contract: "0" is a documented off spelling
    assert envparse.parse_env_float("0", "KNOB", allow_off=True,
                                    zero_is_off=True) == 0.0
    # intervals: zero allowed explicitly, negatives never
    assert envparse.parse_env_float("0", "KNOB", allow_zero=True) == 0.0
    with pytest.raises(ValueError):
        envparse.parse_env_float("-0.1", "KNOB", allow_zero=True)


def test_watchdog_env_junk_warns_and_runs_default(monkeypatch, caplog):
    """The tick-path watchdog configs reject junk LOUDLY (one warning per
    distinct raw value) and run the default — the old bare int()/float()
    accepted TAIL_MIN_TICKS=-5 and MEMORY_SAMPLE_EVERY=0 silently."""
    import logging

    from escalator_tpu.observability import resources

    monkeypatch.setenv("ESCALATOR_TPU_TAIL_MIN_TICKS", "-5")
    monkeypatch.setenv("ESCALATOR_TPU_TAIL_DUMP_INTERVAL_SEC", "junk")
    with caplog.at_level(logging.WARNING, "escalator_tpu.observability"):
        mult, min_ticks, interval = tail.WATCHDOG._config()
    assert min_ticks == tail.DEFAULT_MIN_TICKS
    assert interval == tail.DEFAULT_INTERVAL_SEC
    assert sum("TAIL_MIN_TICKS" in r.message for r in caplog.records) == 1
    caplog.clear()
    monkeypatch.setenv("ESCALATOR_TPU_MEMORY_SAMPLE_EVERY", "0")
    monkeypatch.setenv("ESCALATOR_TPU_MEMORY_MIN_GROWTH", "-1")
    with caplog.at_level(logging.WARNING, "escalator_tpu.observability"):
        window, min_growth, _interval, every = (
            resources.MEMORY_WATCHDOG._config())
    assert every == resources.DEFAULT_SAMPLE_EVERY
    assert min_growth == resources.DEFAULT_MIN_GROWTH
    # the documented disable spellings still work
    monkeypatch.setenv("ESCALATOR_TPU_MEMORY_WATCH", "0")
    assert resources.MEMORY_WATCHDOG._config()[0] == 0
    monkeypatch.setenv("ESCALATOR_TPU_MEMORY_WATCH", "off")
    assert resources.MEMORY_WATCHDOG._config()[0] == 0


# ------------------------------------------------------------------- journal
def test_journal_ring_bounds_seq_and_filters():
    j = journal_mod.OpsJournal(capacity=16)
    for i in range(40):
        j.event("tick" if i % 2 else "tock", n=i)
    assert j.depth == 16 and j.total_recorded == 40
    events = j.snapshot()
    seqs = [e["seq"] for e in events]
    assert seqs == list(range(25, 41))      # monotonic, ring wrapped
    assert j.snapshot(since_seq=38) == events[-2:]
    assert all(e["kind"] == "tick" for e in j.snapshot(kinds=["tick"]))
    doc = j.as_doc()
    assert doc["total_recorded"] == 40 and doc["capacity"] == 16
    json.dumps(doc)   # wire-safe by construction


def test_journal_sanitizes_exotic_fields():
    j = journal_mod.OpsJournal(capacity=16)
    ev = j.event("weird", obj=object(), arr=(1, object()), none=None,
                 nested={"k": object()})
    assert "none" not in ev                      # None fields dropped
    json.dumps(ev)                               # everything else str()-ed
    assert isinstance(ev["obj"], str)
    assert ev["arr"][0] == 1 and isinstance(ev["arr"][1], str)


def test_journal_rides_flight_dump(tmp_path):
    journal_mod.JOURNAL.event("test-dump-marker", detail="ride-along")
    doc = obs.RECORDER.as_dump("journey-test")
    assert "journal" in doc
    kinds = [e["kind"] for e in doc["journal"]["events"]]
    assert "test-dump-marker" in kinds
    json.dumps(doc["journal"])


def test_debug_journal_cli_reads_dump_and_filters(tmp_path, capsys):
    from escalator_tpu.cli import main as cli_main

    journal_mod.JOURNAL.event("cli-marker", tenant="cli-t", klass="batch")
    dump = tmp_path / "ring.json"
    obs.RECORDER.dump(str(dump), reason="journey-cli-test")
    assert cli_main(["debug-journal", "--dump", str(dump),
                     "--kind", "cli-marker"]) == 0
    out = capsys.readouterr().out
    assert "cli-marker" in out and "tenant=cli-t" in out
    # --json emits machine-readable filtered events
    assert cli_main(["debug-journal", "--dump", str(dump), "--json",
                     "--kind", "cli-marker"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert all(e["kind"] == "cli-marker" for e in doc["events"])
    assert doc["events"]
    # unreadable source is exit 2, reserved from "empty journal" (exit 0)
    assert cli_main(["debug-journal", "--dump",
                     str(tmp_path / "missing.json")]) == 2


# ------------------------------------------ tail rate limit per root family
def _run_root_ticks(root, n, sleep_sec, leaf="steady_work"):
    for _ in range(n):
        with spans.span(root):
            spans.annotate(backend="journeytest")
            with spans.span(leaf):
                time.sleep(sleep_sec)


def test_tail_dump_rate_limit_is_per_root_family(tmp_path, monkeypatch):
    """A fleet/<tenant> breach claiming the rate limit must NOT starve a
    tick-family breach arriving inside the interval — the round-17
    regression: the old single global claim let a noisy tenant storm eat
    every tick-root forensic dump for the whole interval."""
    monkeypatch.setenv("ESCALATOR_TPU_DUMP_DIR", str(tmp_path))
    monkeypatch.setenv("ESCALATOR_TPU_TAIL_CAPTURE", "3.0")
    monkeypatch.setenv("ESCALATOR_TPU_TAIL_MIN_TICKS", "40")
    monkeypatch.setenv("ESCALATOR_TPU_TAIL_DUMP_INTERVAL_SEC", "600")
    tail.WATCHDOG.reset()
    # families collapse per-tenant/per-class roots; plain roots stand alone
    assert tail.WATCHDOG._root_family("fleet/tenant-a") == "fleet"
    assert tail.WATCHDOG._root_family("fleet/class/batch") == "fleet/class"
    assert tail.WATCHDOG._root_family("tick") == "tick"
    fleet_root = "fleet/journeytest-tenant"
    tick_root = "journeytest_tick"
    _run_root_ticks(fleet_root, 40, 0.0005)
    _run_root_ticks(tick_root, 40, 0.0005)
    # fleet family breaches and claims its rate limit
    _run_root_ticks(fleet_root, 1, 0.05, leaf="slow_fleet")
    tail.WATCHDOG.drain()
    assert tail.WATCHDOG.dumps == 1
    # a second fleet breach inside the interval: rate-limited (unchanged)
    _run_root_ticks(fleet_root, 1, 0.05, leaf="slow_fleet")
    tail.WATCHDOG.drain()
    assert tail.WATCHDOG.dumps == 1
    # but a TICK-family breach still dumps — its family claim is its own
    _run_root_ticks(tick_root, 1, 0.05, leaf="slow_tick")
    tail.WATCHDOG.drain()
    assert tail.WATCHDOG.dumps == 2, (
        "tick-family dump starved by the fleet family's rate-limit claim")
    dumps = sorted(tmp_path.glob("escalator-tpu-flight-tail-*.json"))
    assert len(dumps) == 2
    roots = {json.loads(p.read_text())["tail"]["root"] for p in dumps}
    assert roots == {fleet_root, tick_root}
    # every breach — dumped or rate-limited — journaled with the verdict
    evs = [e for e in journal_mod.JOURNAL.snapshot(kinds=["tail-breach"])
           if e.get("root") in (fleet_root, tick_root)]
    assert len(evs) == 3
    assert [e["dumped"] for e in evs] == [True, False, True]
    tail.WATCHDOG.reset()


# ------------------------------------------------------------ journeys
def test_scheduler_journey_stages_sum_to_e2e_and_feed_histograms():
    eng = _JourneyEngine(exec_sec=0.003)
    sched = FleetScheduler(eng, max_batch=4, flush_ms=2.0, pipeline=False)
    try:
        sched.pause()
        futs = {k: sched.submit(f"jt-{k}", None, 0, klass=k)
                for k in ("critical", "standard", "batch")}
        sched.resume()
        for klass, fut in futs.items():
            res = fut.result(timeout=10)
            j = res.journey
            assert j is not None and j["klass"] == klass
            assert set(j["stages_ms"]) == set(JOURNEY_STAGES)
            ssum = sum(j["stages_ms"].values())
            assert ssum == pytest.approx(j["e2e_ms"], abs=0.01)
            # the batch slept 3 ms inside the dispatch window
            assert j["stages_ms"]["dispatch"] >= 2.0
            assert j["stages_ms"]["admission"] >= 0.0
        # journeys landed in the engine's sink (= the fleet_batch record's
        # shared list in production)
        assert {j["tenant"] for j in eng.sink} == {
            f"jt-{k}" for k in futs}
        # per-(class, stage) histograms + the derived service split
        for klass in futs:
            for stage in JOURNEY_STAGES + ("service",):
                h = hg.STAGES.peek(klass, stage)
                assert h is not None and h.count >= 1, (klass, stage)
        # health split: queue-wait vs service per class, read from stats().
        # presence + positivity only: STAGES is process-global, so a full
        # suite run has already folded other tests' fast journeys into the
        # "critical" series — magnitude asserts live on the per-request
        # journey above, which is this test's own
        row = sched.stats()["classes"]["critical"]
        assert row["queue_wait_p99_ms"] is not None
        assert row["service_p99_ms"] is not None
        assert row["service_p50_ms"] > 0
        assert "slo_burn" in row
    finally:
        sched.shutdown()


def test_scheduler_journey_counts_deferrals():
    eng = _JourneyEngine(exec_sec=0.0)
    sched = FleetScheduler(eng, max_batch=8, flush_ms=20.0, queue_limit=64,
                           per_tenant_inflight=4, pipeline=False)
    try:
        sched.pause()
        f1 = sched.submit("dup", None, 0)
        f2 = sched.submit("dup", None, 1)   # same tenant: deferred once
        sched.resume()
        j1 = f1.result(timeout=10).journey
        j2 = f2.result(timeout=10).journey
        assert j1["deferrals"] == 0
        assert j2["deferrals"] >= 1
    finally:
        sched.shutdown()


def test_journey_tolerates_stub_engine_results():
    """Engines returning plain tuples (the legacy test stubs) still serve:
    the journey derives with a zero-width dispatch window and no result
    attachment — the scheduler must not require FleetDecision."""
    class _Tuples:
        tenants: set = set()

        @property
        def tenant_count(self):
            return 0

        def has_tenant(self, t):
            return False

        def step(self, requests):
            return [("decided", r.tenant_id) for r in requests]

    sched = FleetScheduler(_Tuples(), flush_ms=1.0, pipeline=False)
    try:
        assert sched.submit("t", None, 0).result(timeout=10)[0] == "decided"
        h = hg.STAGES.peek("standard", "admission")
        assert h is not None and h.count >= 1
    finally:
        sched.shutdown()


# --------------------------------------------------------- SLO error budget
def test_slo_burn_breach_journals_and_escalates(monkeypatch, tmp_path):
    """Acceptance lock: a forced per-class p99 breach through the REAL
    scheduler raises fleet_slo_budget_burn{klass} above the fast-burn
    threshold, emits journal events, and (ESCALATOR_TPU_TAIL_PROFILE=1)
    arms a profiler capture."""
    from escalator_tpu.observability import resources

    monkeypatch.setenv("ESCALATOR_TPU_DUMP_DIR", str(tmp_path))
    monkeypatch.setenv("ESCALATOR_TPU_TAIL_PROFILE", "1")
    # stub the profiler START only (the arm rides a daemon worker because
    # the real jax start_trace costs ~16 s on first use — priced by
    # test_resources and the smoke's profiler leg, not re-paid here);
    # this test locks that the REAL scheduler drives the arm with the
    # right target
    armed: list = []

    def fake_start(ticks, out_dir):
        armed.append((ticks, out_dir))
        return {"ok": True, "dir": out_dir, "ticks": ticks}

    monkeypatch.setattr(resources.PROFILER, "start", fake_start)
    seq0 = journal_mod.JOURNAL.total_recorded
    eng = _JourneyEngine(exec_sec=0.001)
    # every request violates the microscopic target -> burn = 100x; TWO
    # check windows (2 x _SLO_CHECK_EVERY requests) because escalation
    # deliberately needs two consecutive fast windows — one window's
    # violations are same-batch-correlated, and a single slow batch must
    # not page
    sched = FleetScheduler(
        eng, max_batch=4, flush_ms=1.0, pipeline=False,
        classes=(PriorityClass("critical", weight=4, p99_target_ms=0.001),),
        default_class="critical")
    try:
        futs = [sched.submit(f"slo-{i}", None, 0) for i in range(32)]
        for f in futs:
            f.result(timeout=10)
        deadline = time.monotonic() + 5
        while (sched.last_burn["critical"] < SLO_FAST_BURN
               and time.monotonic() < deadline):
            time.sleep(0.01)
        assert sched.last_burn["critical"] >= SLO_FAST_BURN
        assert sched.class_breaches["critical"] >= 1
        # the gauge carries the same burn the scheduler computed
        from escalator_tpu.metrics import metrics

        sample = metrics.registry.get_sample_value(
            "escalator_tpu_fleet_slo_budget_burn", {"klass": "critical"})
        assert sample is not None and sample >= SLO_FAST_BURN
        evs = journal_mod.JOURNAL.snapshot(since_seq=seq0)
        kinds = [e["kind"] for e in evs]
        assert "slo-breach" in kinds
        esc = [e for e in evs if e["kind"] == "slo-escalation"]
        assert esc and esc[0]["klass"] == "critical"
        assert esc[0]["burn"] >= SLO_FAST_BURN
        assert esc[0]["profile_requested"] is True
        # the arm worker drove PROFILER.start at the dump dir and
        # journaled the outcome
        deadline = time.monotonic() + 5
        while not armed and time.monotonic() < deadline:
            time.sleep(0.01)
        assert armed and armed[0][0] == 4
        assert armed[0][1].startswith(str(tmp_path))
        deadline = time.monotonic() + 5
        while (not journal_mod.JOURNAL.snapshot(
                since_seq=seq0, kinds=["slo-profile-armed"])
               and time.monotonic() < deadline):
            time.sleep(0.01)
        prof_evs = journal_mod.JOURNAL.snapshot(
            since_seq=seq0, kinds=["slo-profile-armed"])
        assert prof_evs and prof_evs[0]["profile"]["ok"] is True
    finally:
        sched.shutdown()


# -------------------------------------------------------------- trace export
def test_trace_export_renders_journey_track_family():
    from escalator_tpu.observability import traceexport

    mono0 = 1000.0
    journeys = []
    for i, klass in enumerate(("critical", "batch")):
        journeys.append({
            "tenant": f"trace-t{i}", "klass": klass, "deferrals": i,
            "enqueued_mono": mono0 + 0.001 + i * 0.0001,
            "done_mono": mono0 + 0.010,
            "stages_ms": {"admission": 2.0, "batch_assembly": 1.0,
                          "dispatch": 4.0, "ordered_tail": 0.0,
                          "unpack": 1.5},
            "e2e_ms": 8.5,
        })
    rec = {"root": "fleet_batch", "time_unix": 1_700_000_000.0,
           "duration_ms": 6.0, "seq": 3, "phases": [
               {"name": "fleet_batch", "path": "fleet_batch", "ms": 6.0,
                "kind": "host", "fenced": True, "offset_ms": 0.0}],
           "journeys": journeys, "journey_mono_t0": mono0}
    doc = traceexport.trace_from_records([rec])
    ev = doc["traceEvents"]
    jslices = [e for e in ev if e.get("ph") == "X"
               and e.get("tid", 0) >= traceexport.TID_JOURNEY_BASE]
    # one parent req slice per tenant, stages contiguous inside it, on a
    # per-tenant track named in the thread metadata
    req = {e["name"]: e for e in jslices if e["name"].startswith("req ")}
    assert set(req) == {"req trace-t0 [critical]", "req trace-t1 [batch]"}
    tids = {e["tid"] for e in jslices}
    assert len(tids) == 2
    names = {e["args"]["name"] for e in ev
             if e["ph"] == "M" and e["name"] == "thread_name"
             and e.get("tid", 0) >= traceexport.TID_JOURNEY_BASE}
    assert names == {"journey trace-t0", "journey trace-t1"}
    t0_stages = sorted(
        (e for e in jslices if e["tid"] == req[
            "req trace-t0 [critical]"]["tid"]
         and not e["name"].startswith("req ")),
        key=lambda e: e["ts"])
    assert [e["name"] for e in t0_stages] == [
        "admission", "batch_assembly", "dispatch", "unpack"]  # tail=0 skipped
    for a, b in zip(t0_stages, t0_stages[1:], strict=False):
        assert b["ts"] == pytest.approx(a["ts"] + a["dur"], abs=0.01)
    parent = req["req trace-t0 [critical]"]
    assert t0_stages[0]["ts"] == pytest.approx(parent["ts"], abs=0.01)
    assert parent["args"]["fleet_batch_seq"] == 3
    # zero-duration ordered_tail slices are suppressed, dispatch is cat=device
    assert all(e["name"] != "ordered_tail" for e in jslices)
    disp = next(e for e in t0_stages if e["name"] == "dispatch")
    assert disp["cat"] == "device"


def test_journey_span_phases_ship_shape():
    """The server-side journey→span-phase conversion the gRPC edge ships:
    parent spans the e2e, stage offsets cumulative, dispatch kind=device —
    graftable by spans.graft without translation."""
    from escalator_tpu.plugin.server import _journey_span_phases

    journey = {"stages_ms": {"admission": 2.0, "batch_assembly": 1.0,
                             "dispatch": 4.0, "ordered_tail": 0.5,
                             "unpack": 1.0},
               "e2e_ms": 8.5}
    phases = _journey_span_phases(journey)
    assert phases[0]["path"] == "journey" and phases[0]["ms"] == 8.5
    offs = {p["name"]: p["offset_ms"] for p in phases[1:]}
    assert offs == {"admission": 0.0, "batch_assembly": 2.0,
                    "dispatch": 3.0, "ordered_tail": 7.0, "unpack": 7.5}
    kinds = {p["name"]: p["kind"] for p in phases[1:]}
    assert kinds["dispatch"] == "device"
    # grafts cleanly under a live timeline
    with spans.span("client_tick"):
        with spans.span("rpc", kind="rpc"):
            pass
        spans.graft(phases, under="client_tick/rpc")
        tl = spans.current_timeline()
        grafted = [p for p in tl.phases if p.remote]
    assert any(p.path == "client_tick/rpc/journey/dispatch"
               for p in grafted)


# ------------------------------------------------------------------ inertness
def test_journey_and_journal_layers_are_jaxpr_inert():
    """The round-17 layers are hook-side only: tracing a registry entry
    while journeys/journal events are being recorded yields a jaxpr
    byte-identical to a quiet trace (jaxlint's 30 entries stay untouched)."""
    import jax

    from escalator_tpu.analysis.registry import default_registry

    entries = {e.name: e for e in default_registry()}
    traced = entries["kernel.decide"].build()

    def jaxpr_text():
        return str(jax.make_jaxpr(traced.fn)(*traced.args))

    plain = jaxpr_text()
    eng = _JourneyEngine(exec_sec=0.0)
    sched = FleetScheduler(eng, flush_ms=1.0, pipeline=False)
    try:
        sched.submit("inert-t", None, 0).result(timeout=10)
        journal_mod.JOURNAL.event("inertness-probe", armed=True)
        with spans.span("inert_trace"):
            armed = jaxpr_text()
    finally:
        sched.shutdown()
    assert armed == plain
