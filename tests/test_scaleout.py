"""Scale-out layer tests (round 20): tenant-row snapshot round-trip,
consistent-hash router stability, warm migration through the router under
the runtime lock witness, and the repo-hygiene guard for flight dumps.

The gRPC wire path for the same machinery (TenantSnapshot/TenantAdopt RPCs,
subprocess partitions, kill-based failover) is exercised by the scale-out
smoke leg in bench.py — these tests stay in-process so tier-1 keeps its
budget; the router here talks to FleetEngines through the SAME client
surface the real ComputeClient exposes.
"""

import os
import subprocess

import numpy as np
import pytest

from escalator_tpu import observability as obs
from escalator_tpu.analysis.registry import representative_cluster
from escalator_tpu.fleet import (
    DecideRequest,
    EvictAck,
    EvictRequest,
    FleetEngine,
    TenantError,
)
from escalator_tpu.fleet.router import (
    PartitionRouter,
    RouterError,
    hash_ring_points,
)
from escalator_tpu.metrics import metrics
from escalator_tpu.ops import kernel
from escalator_tpu.ops import snapshot as snaplib

NOW = 1_700_000_000
G, P, N = 6, 24, 12

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def tiny_cluster(seed: int):
    return representative_cluster(G, P, N, seed=seed)


def make_engine(**kw):
    kw.setdefault("num_groups", G)
    kw.setdefault("pod_capacity", P)
    kw.setdefault("node_capacity", N)
    kw.setdefault("max_tenants", 4)
    return FleetEngine(**kw)


def assert_column_parity(arrays, cluster, now, msg=""):
    import jax

    ref = kernel.decide_jit(jax.device_put(cluster), np.int64(now))
    for f in kernel.GROUP_DECISION_FIELDS:
        np.testing.assert_array_equal(
            np.asarray(getattr(arrays, f)), np.asarray(getattr(ref, f)),
            err_msg=f"{msg}:{f}")


# ---------------------------------------------------------------------------
# repo hygiene: flight dumps never land in the tree
# ---------------------------------------------------------------------------


def test_no_flight_dumps_tracked_outside_traces():
    """Incident flight dumps are working artifacts: .gitignore keeps them
    out, and this guard fails LOUDLY if one is ever force-added anywhere
    but the curated tpu_traces/ corpus."""
    try:
        out = subprocess.run(
            ["git", "ls-files"], cwd=REPO_ROOT, capture_output=True,
            text=True, timeout=30, check=True).stdout
    except (OSError, subprocess.SubprocessError):
        pytest.skip("git unavailable (sdist / exported tree)")
    strays = [
        path for path in out.splitlines()
        if os.path.basename(path).startswith("escalator-tpu-flight-")
        and path.endswith(".json")
        and not path.startswith("tpu_traces/")
    ]
    assert not strays, (
        f"flight dumps tracked outside tpu_traces/: {strays} — "
        f"git rm them (incident dumps are diagnostics, not sources)")


# ---------------------------------------------------------------------------
# tenant-row snapshot: freeze -> serialize -> adopt round trip
# ---------------------------------------------------------------------------


def _dispatched_engine(tenant="t0", seed=7, ticks=2):
    """An engine with one dispatched tenant (ticks>0, digest cache live:
    the last tick repeats the previous cluster at a later now)."""
    eng = make_engine()
    cluster = tiny_cluster(seed)
    for k in range(ticks):
        eng.step([DecideRequest(tenant, tiny_cluster(seed), NOW + 60 * k)])
    return eng, cluster


def test_tenant_row_roundtrip_bit_parity():
    eng_a, cluster = _dispatched_engine()
    leaves, meta = eng_a.snapshot_tenant_row("t0")
    assert meta["kind"] == snaplib.TENANT_ROW_KIND
    assert meta["tenant"] == "t0" and meta["ticks"] == 2

    # serialize -> parse: every leaf bit-identical (order state and the
    # digest/decision cache ride as cache.* leaves when live)
    blob = snaplib.snapshot_to_bytes(leaves, meta)
    leaves2, meta2 = snaplib.snapshot_from_bytes(blob, label="<test>")
    assert set(leaves2) == set(leaves)
    for key in leaves:
        np.testing.assert_array_equal(
            np.asarray(leaves2[key]), np.asarray(leaves[key]),
            err_msg=f"leaf {key}")
    assert meta2["cache"] == meta["cache"]

    # adopt on a second engine; re-freezing must reproduce the same row
    # (freeze -> adopt -> freeze is a fixpoint, digest cache included)
    eng_b = make_engine()
    shard, row = eng_b.adopt_tenant_row(leaves2, meta2)
    assert shard >= 0 and row >= 0
    leaves3, meta3 = eng_b.snapshot_tenant_row("t0")
    assert set(leaves3) == set(leaves)
    for key in leaves:
        np.testing.assert_array_equal(
            np.asarray(leaves3[key]), np.asarray(leaves[key]),
            err_msg=f"post-adopt leaf {key}")
    assert meta3["cache"] == meta["cache"]
    assert meta3["ticks"] == meta["ticks"]

    # post-adopt decides stay bit-identical to the standalone decide
    later = NOW + 600
    [fd] = eng_b.step([DecideRequest("t0", tiny_cluster(7), later)])
    assert_column_parity(fd.arrays, cluster, later, msg="post-adopt")


def test_tenant_row_corrupt_rejected():
    eng_a, _ = _dispatched_engine(seed=9)
    leaves, meta = eng_a.snapshot_tenant_row("t0")
    blob = bytearray(snaplib.snapshot_to_bytes(leaves, meta))

    # torn payload: the container checksum rejects before any adopt
    blob[-3] ^= 0xFF
    with pytest.raises(snaplib.SnapshotCorruptError):
        snaplib.snapshot_from_bytes(bytes(blob), label="<torn>")

    # wrong kind: a whole-decider snapshot fed to the row-adopt path is a
    # NAMED rejection with the corrupt outcome metric, not a shape error
    eng_b = make_engine()
    bad_meta = dict(meta, kind="escalator-decider-state")
    before = metrics.snapshot_restores.labels("corrupt")._value.get()
    with pytest.raises(snaplib.SnapshotCorruptError):
        eng_b.adopt_tenant_row(leaves, bad_meta)
    assert metrics.snapshot_restores.labels(
        "corrupt")._value.get() == before + 1


def test_tenant_row_stale_resident_rejected():
    eng_a, _ = _dispatched_engine(seed=11)
    leaves, meta = eng_a.snapshot_tenant_row("t0")
    # the SOURCE engine still holds t0: adopting the row back without an
    # evict is the split-brain shape -> stale rejection, cold path
    before = metrics.snapshot_restores.labels("stale")._value.get()
    with pytest.raises(TenantError):
        eng_a.adopt_tenant_row(leaves, meta)
    assert metrics.snapshot_restores.labels(
        "stale")._value.get() == before + 1


# ---------------------------------------------------------------------------
# router: consistent-hash stability
# ---------------------------------------------------------------------------


class _NullClient:
    def __init__(self, address=""):
        self.address = address

    def close(self):
        pass


def test_ring_points_deterministic():
    assert hash_ring_points("p0") == hash_ring_points("p0")
    assert hash_ring_points("p0") != hash_ring_points("p1")
    assert len(set(hash_ring_points("p0", 64))) == 64


def test_router_hash_stability_under_membership_change():
    router = PartitionRouter({"p0": "a:1", "p1": "a:2", "p2": "a:3"},
                             client_factory=_NullClient)
    tenants = [f"tenant-{i}" for i in range(256)]
    before = {t: router.home(t) for t in tenants}
    assert len(set(before.values())) == 3   # 256 keys spread over 3 parts

    # add: ONLY keys landing on the new arcs move, and they move to p3
    router.add_partition("p3", "a:4", client=_NullClient())
    after = {t: router.home(t) for t in tenants}
    moved = {t for t in tenants if after[t] != before[t]}
    assert moved, "a joining partition must take some arcs"
    assert len(moved) < len(tenants), "a join must not reshuffle the world"
    assert all(after[t] == "p3" for t in moved)

    # remove: the mapping returns to exactly the pre-join assignment
    router.remove_partition("p3")
    assert {t: router.home(t) for t in tenants} == before
    router.close()


def test_router_override_pins_home():
    router = PartitionRouter({"p0": "a:1", "p1": "a:2"},
                             overrides={"pinned": "p1"},
                             client_factory=_NullClient)
    assert router.home("pinned") == "p1"
    # a dead override target falls back to the ring, never errors
    router.remove_partition("p1")
    assert router.home("pinned") == "p0"
    router.close()


def test_router_no_live_partitions_is_an_error():
    router = PartitionRouter(client_factory=_NullClient)
    with pytest.raises(RouterError):
        router.home("anyone")
    router.close()


# ---------------------------------------------------------------------------
# warm migration through the router, under the runtime lock witness
# ---------------------------------------------------------------------------


class _EngineClient:
    """In-process partition: a FleetEngine behind the exact client surface
    migrate_tenant/fail_over drive (snapshot_tenant/evict_tenant/
    adopt_tenant returning the wire-shaped docs)."""

    def __init__(self, engine):
        self.engine = engine

    def snapshot_tenant(self, tenant_id, timeout_sec=None):
        leaves, meta = self.engine.snapshot_tenant_row(tenant_id)
        return snaplib.snapshot_to_bytes(leaves, meta)

    def adopt_tenant(self, blob):
        leaves, meta = snaplib.snapshot_from_bytes(blob, label="<adopt>")
        shard, row = self.engine.adopt_tenant_row(leaves, meta)
        return {"ok": True, "tenant": meta.get("tenant"),
                "shard": shard, "row": row}

    def evict_tenant(self, tenant_id):
        [ack] = self.engine.step([EvictRequest(tenant_id)])
        assert isinstance(ack, EvictAck)
        return {"ok": True}

    def close(self):
        pass


MIGRATION_SEQUENCE = ["migration-start", "migration-row-snapshot",
                      "migration-evict", "migration-adopt",
                      "migration-complete"]


def test_warm_migration_journal_sequence_and_parity(monkeypatch):
    # the runtime witness turns every contract-lock acquisition into a
    # rank check: a regression in the router/engine lock order fails HERE,
    # not in a production deadlock
    monkeypatch.setenv("ESCALATOR_TPU_LOCK_WITNESS", "1")
    engines = {"p0": make_engine(), "p1": make_engine()}
    router = PartitionRouter(client_factory=_NullClient)
    for name, eng in engines.items():
        router.add_partition(name, f"inproc:{name}",
                             client=_EngineClient(eng))
    tenant = "mig-tenant"
    src = router.home(tenant)
    dest = "p1" if src == "p0" else "p0"
    cluster = tiny_cluster(13)
    for k in range(2):
        engines[src].step([DecideRequest(tenant, tiny_cluster(13),
                                         NOW + 60 * k)])

    seq0 = obs.journal.JOURNAL.total_recorded
    report = router.migrate_tenant(tenant, dest)
    assert report["source"] == src and report["dest"] == dest
    assert report["gap_ms"] > 0

    # journal sequence is doc-locked (docs/scale-out.md)
    events = [e for e in obs.journal.JOURNAL.snapshot(since_seq=seq0)
              if e.get("tenant") == tenant]
    kinds = [e["kind"] for e in events]
    mig = [k for k in kinds if k in MIGRATION_SEQUENCE]
    assert mig == MIGRATION_SEQUENCE, kinds

    # the tenant now routes to dest (override pin) and decides WARM with
    # bit-parity — zero digest divergence vs the standalone control
    assert router.home(tenant) == dest
    later = NOW + 600
    [fd] = engines[dest].step([DecideRequest(tenant, tiny_cluster(13),
                                             later)])
    assert_column_parity(fd.arrays, cluster, later, msg="post-migration")
    # and the source really evicted: adopting back would not be "stale"
    with pytest.raises(TenantError):
        engines[src].snapshot_tenant_row(tenant)
    router.close()


def test_migration_rejects_bad_targets():
    engines = {"p0": make_engine(), "p1": make_engine()}
    router = PartitionRouter(client_factory=_NullClient)
    for name, eng in engines.items():
        router.add_partition(name, f"inproc:{name}",
                             client=_EngineClient(eng))
    tenant = "t-reject"
    home = router.home(tenant)
    with pytest.raises(RouterError):
        router.migrate_tenant(tenant, home)        # src == dest
    with pytest.raises(RouterError):
        router.migrate_tenant(tenant, "ghost")     # unknown partition
    router.close()
