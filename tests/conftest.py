# Test environment: force the CPU backend with 8 virtual devices so the multi-chip
# sharding path is exercised without TPU hardware, and so float64 parity tests are
# bit-exact (TPU f64 emulation is not). A sitecustomize on this machine pins
# jax_platforms to the TPU tunnel, so the env var alone is not enough — we override
# the config after import, before any computation runs.
import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _flight_dump_dir_hygiene(tmp_path, monkeypatch):
    """Flight-recorder incident dumps land in ESCALATOR_TPU_DUMP_DIR
    (default CWD) — point every test at its tmpdir so suite runs stop
    littering the repo root with escalator-tpu-flight-*.json debris. Tests
    that probe the env contract monkeypatch over this (later patch wins)."""
    monkeypatch.setenv("ESCALATOR_TPU_DUMP_DIR", str(tmp_path))
