"""Structural checks on the committed TPU device trace (tpu_traces/).

docs/performance.md instructs readers to trust the trace's STRUCTURE (which
programs/ops executed) and not its absolute durations (profiler-mode
distortion, documented there). This locks the structural claims the docs and
kernel docstrings make against the actual archived artifact:

- the traced program is the batched decide;
- the grouped orderings lower to multi-key sorts, not chains of argsorts;
- the empty-selection skips are real runtime conditionals (lax.cond).

The expected op counts are VINTAGE-AWARE: traces captured before the round-5
combined-sort change (ops/kernel.py decide's _combined_order — both
orderings from ONE 4-key sort behind ONE cond) show two sorts and two
conditionals; traces of the current kernel must show one of each. The trace
dir names are capture timestamps, which is how vintage is decided.
"""

from __future__ import annotations

import collections
import functools
import gzip
import json
import pathlib

import pytest

REPO = pathlib.Path(__file__).resolve().parent.parent

#: first capture timestamp at which the combined-sort kernel could appear
#: (commit time of the one-sort decide, 2026-07-30 ~18:30Z)
COMBINED_SORT_SINCE = "trace_20260730T183000Z"


@functools.lru_cache(maxsize=2)
def _device_trace(variant="xla"):
    """(op-name counts, trace dir name) from the newest archived trace of the
    given variant ("xla" = default decide; "pallas" = dirs suffixed
    -pallas)."""
    traces = [
        p for p in sorted(
            REPO.glob("tpu_traces/*/plugins/profile/*/*.trace.json.gz"))
        # classify by the trace DIR name, not the whole path (a checkout
        # path containing "-pallas" must not reclassify every trace)
        if p.relative_to(REPO / "tpu_traces").parts[0].endswith("-pallas")
        == (variant == "pallas")
    ]
    if not traces:
        pytest.skip(f"no archived {variant} device trace in this checkout")
    newest = traces[-1]
    data = json.loads(gzip.open(newest).read())
    tracks = {
        e["pid"]: e["args"].get("name", "")
        for e in data["traceEvents"]
        if e.get("ph") == "M" and e.get("name") == "process_name"
    }
    names = collections.Counter(
        e["name"]
        for e in data["traceEvents"]
        if e.get("ph") == "X"
        and tracks.get(e.get("pid", -1), "").startswith("/device:")
    )
    return names, newest.relative_to(REPO / "tpu_traces").parts[0]


def test_trace_is_the_decide_program():
    names, _ = _device_trace()
    assert any(n.startswith("jit_decide") for n in names), sorted(names)[:5]


def test_ordering_sorts_and_conditionals_match_kernel_vintage():
    names, trace_dir = _device_trace()
    sorts = [n for n in names if n.startswith("sort")]
    conds = [n for n in names if n.startswith("conditional")]
    # pre-round-5 kernels: one multi-key sort + one cond per ordering (two
    # orderings); current kernel: ONE combined 4-key sort behind ONE cond.
    # Either way, chains of argsorts would show up as more sorts.
    want = 2 if trace_dir.split("-")[0] < COMBINED_SORT_SINCE else 1
    assert len(sorts) == want, (trace_dir, sorts)
    assert len(conds) == want, (trace_dir, conds)
    # every sort/cond executed exactly once per traced decide — anchored to
    # the decide op's own count, so a second program mixed into the trace
    # (even with uniform counts) cannot satisfy this
    decide = [n for n in names if n.startswith("jit_decide")]
    assert len({names[n] for n in sorts + conds + decide}) == 1


def test_pallas_trace_is_the_decide_program():
    """When a -pallas trace is archived (tools/capture_tpu_profile.sh with
    ESCALATOR_TRACE_IMPL=pallas), it must at minimum be the decide program.
    Tighten this to assert the Mosaic kernel op once the first artifact
    shows its exact trace name (custom-call naming varies by toolchain)."""
    names, _ = _device_trace("pallas")
    assert any(n.startswith("jit_decide") for n in names), sorted(names)[:10]
