"""Structural checks on the committed TPU device trace (tpu_traces/).

docs/performance.md instructs readers to trust the trace's STRUCTURE (which
programs/ops executed) and not its absolute durations (profiler-mode
distortion, documented there). This locks the structural claims the docs and
kernel docstrings make against the actual archived artifact:

- the traced program is the batched decide;
- the two grouped orderings lower to exactly TWO multi-key sorts
  (ops/kernel.py _grouped_order — one sort per ordering, not chains);
- the two empty-selection skips are real runtime conditionals
  (the lax.cond pair in ops/kernel.py decide).
"""

from __future__ import annotations

import collections
import functools
import gzip
import json
import pathlib

import pytest

REPO = pathlib.Path(__file__).resolve().parent.parent


@functools.lru_cache(maxsize=2)
def _device_op_names(variant="xla"):
    """Op-name counts from the newest archived trace of the given variant
    ("xla" = default decide; "pallas" = trace dirs suffixed -pallas)."""
    traces = [
        p for p in sorted(
            REPO.glob("tpu_traces/*/plugins/profile/*/*.trace.json.gz"))
        # classify by the trace DIR name, not the whole path (a checkout
        # path containing "-pallas" must not reclassify every trace)
        if p.relative_to(REPO / "tpu_traces").parts[0].endswith("-pallas")
        == (variant == "pallas")
    ]
    if not traces:
        pytest.skip(f"no archived {variant} device trace in this checkout")
    data = json.loads(gzip.open(traces[-1]).read())
    tracks = {
        e["pid"]: e["args"].get("name", "")
        for e in data["traceEvents"]
        if e.get("ph") == "M" and e.get("name") == "process_name"
    }
    return collections.Counter(
        e["name"]
        for e in data["traceEvents"]
        if e.get("ph") == "X"
        and tracks.get(e.get("pid", -1), "").startswith("/device:")
    )


def test_trace_is_the_decide_program():
    names = _device_op_names()
    assert any(n.startswith("jit_decide") for n in names), sorted(names)[:5]


def test_orderings_are_two_sorts_and_two_conditionals():
    names = _device_op_names()
    sorts = [n for n in names if n.startswith("sort")]
    conds = [n for n in names if n.startswith("conditional")]
    # one multi-key sort per ordering (scale-down victims, untaint
    # candidates) — chains of argsorts would show up as more
    assert len(sorts) == 2, sorts
    # one lax.cond per ordering's empty-selection skip
    assert len(conds) == 2, conds
    # every sort/cond executed exactly once per traced decide — anchored to
    # the decide op's own count, so a second program mixed into the trace
    # (even with uniform counts) cannot satisfy this
    decide = [n for n in names if n.startswith("jit_decide")]
    assert len({names[n] for n in sorts + conds + decide}) == 1


def test_pallas_trace_is_the_decide_program():
    """When a -pallas trace is archived (tools/capture_tpu_profile.sh with
    ESCALATOR_TRACE_IMPL=pallas), it must at minimum be the decide program.
    Tighten this to assert the Mosaic kernel op once the first artifact
    shows its exact trace name (custom-call naming varies by toolchain)."""
    names = _device_op_names("pallas")
    assert any(n.startswith("jit_decide") for n in names), sorted(names)[:10]
