"""Controller integration tests — the distinctive layer of the reference's test
strategy (/root/reference/pkg/controller/controller_scale_node_group_test.go): full
ticks against the fake client + mock provider + mock clock, including multi-run
convergence. Parametrized over backends so the object shell and the device kernel are
exercised through the same scenarios."""

import logging

import pytest

from escalator_tpu.controller import controller as ctl
from escalator_tpu.controller import node_group as ngmod
from escalator_tpu.controller.backend import (
    GoldenBackend,
    IncrementalJaxBackend,
    JaxBackend,
    GridJaxBackend,
    PodAxisJaxBackend,
)
from escalator_tpu.controller.native_backend import make_native_backend
from escalator_tpu.k8s import types as k8s
from escalator_tpu.k8s.cache import EventfulClient
from escalator_tpu.k8s.client import InMemoryKubernetesClient
from escalator_tpu.testsupport.builders import (
    NodeOpts,
    PodOpts,
    build_test_nodes,
    build_test_pods,
)
from escalator_tpu.testsupport.cloud_provider import (
    MockBuilder,
    MockCloudProvider,
    MockNodeGroup,
)
from escalator_tpu.utils.clock import MockClock

LABEL_KEY = "customer"
LABEL_VALUE = "buildeng"


def make_opts(**kw):
    base = dict(
        name="buildeng",
        label_key=LABEL_KEY,
        label_value=LABEL_VALUE,
        cloud_provider_group_name="buildeng-asg",
        min_nodes=1,
        max_nodes=100,
        taint_upper_capacity_threshold_percent=45,
        taint_lower_capacity_threshold_percent=30,
        scale_up_threshold_percent=70,
        slow_node_removal_rate=1,
        fast_node_removal_rate=2,
        soft_delete_grace_period="5m",
        hard_delete_grace_period="15m",
        scale_up_cool_down_period="10m",
    )
    base.update(kw)
    return ngmod.NodeGroupOptions(**base)


class World:
    """One controller + fake cluster + mock provider, wired together."""

    def __init__(self, ng_opts, nodes=None, pods=None, backend=None,
                 target_size=None, max_size=None, dry_mode=False):
        self.clock = MockClock()
        for n in nodes or []:
            n.labels = {LABEL_KEY: LABEL_VALUE}
        self.client = EventfulClient(nodes=nodes or [], pods=pods or [])
        if callable(backend) and not hasattr(backend, "decide"):
            backend = backend(self.client, [ng_opts])
        self.provider = MockCloudProvider()
        self.group = MockNodeGroup(
            "buildeng-asg", "buildeng",
            min_size=ng_opts.min_nodes,
            max_size=max_size if max_size is not None else ng_opts.max_nodes,
            target_size=target_size if target_size is not None else len(nodes or []),
        )
        self.provider.register_node_group(self.group)
        self.controller = ctl.Controller(
            ctl.Opts(
                client=self.client,
                node_groups=[ng_opts],
                cloud_provider_builder=MockBuilder(self.provider),
                dry_mode=dry_mode,
                backend=backend,
                clock=self.clock,
            )
        )
        self.state = self.controller.node_groups[ng_opts.name]

    def tick(self):
        self.controller.run_once()

    def tainted_nodes(self):
        return [
            n for n in self.client.list_nodes()
            if k8s.get_to_be_removed_taint(n) is not None
        ]

    def simulate_cloud_fills_nodes(self, cpu, mem):
        """Bring provider target to life as registered kube nodes."""
        missing = self.group.target_size() - len(self.client.list_nodes())
        for n in build_test_nodes(max(0, missing), NodeOpts(
                cpu=cpu, mem=mem, label_key=LABEL_KEY, label_value=LABEL_VALUE,
                creation_time_ns=int(self.clock.now() * 1e9))):
            self.client.add_node(n)


BACKENDS = {
    "golden": lambda: GoldenBackend(),
    "jax": lambda: JaxBackend(),
    "podaxis": lambda: PodAxisJaxBackend(),
    "grid": lambda: GridJaxBackend(),
    # factory taking (client, ng_opts_list); World detects and applies it
    "native": lambda: make_native_backend,
    # round-8 incremental paths: delta-maintained aggregates + dirty-group
    # compacted decide, through the full controller lifecycle (refresh
    # cadence of 3 so the bit-equality audit fires mid-lifecycle too)
    "incremental": lambda: IncrementalJaxBackend(refresh_every=3),
    "native-inc": lambda: (lambda client, opts: make_native_backend(
        client, opts, incremental=True, refresh_every=3)),
}


@pytest.fixture(params=list(BACKENDS), ids=list(BACKENDS))
def backend(request):
    return BACKENDS[request.param]()


def test_scale_up_increases_provider(backend):
    pods = build_test_pods(10, PodOpts(
        cpu=[500], mem=[10**9],
        node_selector_key=LABEL_KEY, node_selector_value=LABEL_VALUE))
    nodes = build_test_nodes(2, NodeOpts(cpu=1000, mem=4 * 10**9))
    w = World(make_opts(), nodes=nodes, pods=pods, backend=backend)
    w.tick()
    # cpu 5000/2000 = 250% -> delta ceil(2*(250-70)/70) = 6
    assert w.state.scale_delta == 6
    assert w.group.increase_calls == [6]
    assert w.group.target_size() == 8
    # provider scale-out locks the scale lock
    assert w.state.scale_lock.locked()


def test_locked_group_returns_requested(backend):
    pods = build_test_pods(10, PodOpts(
        cpu=[500], mem=[10**9],
        node_selector_key=LABEL_KEY, node_selector_value=LABEL_VALUE))
    nodes = build_test_nodes(2, NodeOpts(cpu=1000, mem=4 * 10**9))
    w = World(make_opts(), nodes=nodes, pods=pods, backend=backend)
    w.tick()
    assert w.group.increase_calls == [6]
    # Locked: second tick must not scale again, returns requested nodes
    w.tick()
    assert w.group.increase_calls == [6]
    assert w.state.scale_delta == 6  # requestedNodes
    # after the cooldown the lock opens
    w.clock.advance(601)
    w.simulate_cloud_fills_nodes(1000, 4 * 10**9)
    w.tick()
    assert not w.state.scale_lock.is_locked


def test_convergence_after_cloud_fulfills(backend):
    """Two-phase convergence (reference test at
    controller_scale_node_group_test.go:531-546): scale up, let the cloud bring the
    nodes, re-run -> delta 0."""
    pods = build_test_pods(40, PodOpts(
        cpu=[500], mem=[10**9],
        node_selector_key=LABEL_KEY, node_selector_value=LABEL_VALUE))
    nodes = build_test_nodes(10, NodeOpts(cpu=2000, mem=8 * 10**9))
    w = World(make_opts(), nodes=nodes, pods=pods, backend=backend)
    w.tick()
    assert w.state.scale_delta > 0
    w.clock.advance(601)  # past cooldown
    w.simulate_cloud_fills_nodes(2000, 8 * 10**9)
    w.tick()
    assert w.state.scale_delta == 0
    # converged: util at/below threshold, nothing else to do
    cpu_pct = 40 * 500 / (w.group.target_size() * 2000) * 100
    assert cpu_pct <= 70


def test_scale_up_untaints_first(backend):
    """Tainted nodes are untainted (newest first) before provider scale
    (reference: scale_up.go:14-45, untaintNewestN)."""
    young = build_test_nodes(2, NodeOpts(
        cpu=1000, mem=4 * 10**9, tainted=True, taint_time_sec=100,
        creation_time_ns=2_000_000_000))
    old = build_test_nodes(2, NodeOpts(
        cpu=1000, mem=4 * 10**9, tainted=True, taint_time_sec=100,
        creation_time_ns=1_000_000_000))
    active = build_test_nodes(2, NodeOpts(cpu=1000, mem=4 * 10**9))
    pods = build_test_pods(4, PodOpts(
        cpu=[500], mem=[10**9],
        node_selector_key=LABEL_KEY, node_selector_value=LABEL_VALUE))
    w = World(make_opts(), nodes=young + old + active, pods=pods, backend=backend)
    # cpu: 2000/2000 = 100% > 70 -> delta = ceil(2*30/70) = 1 -> untaint 1 newest
    w.tick()
    assert w.state.scale_delta == 1
    assert w.group.increase_calls == []  # satisfied by untainting alone
    assert len(w.tainted_nodes()) == 3
    untainted_names = {
        n.name for n in w.client.list_nodes()
        if k8s.get_to_be_removed_taint(n) is None
    }
    assert young[0].name in untainted_names or young[1].name in untainted_names


def test_scale_down_taints_oldest(backend):
    nodes_old = build_test_nodes(1, NodeOpts(
        cpu=1000, mem=4 * 10**9, creation_time_ns=1_000))
    nodes_new = build_test_nodes(9, NodeOpts(
        cpu=1000, mem=4 * 10**9, creation_time_ns=2_000_000))
    pods = build_test_pods(1, PodOpts(
        cpu=[100], mem=[10**8],
        node_selector_key=LABEL_KEY, node_selector_value=LABEL_VALUE))
    w = World(make_opts(), nodes=nodes_old + nodes_new, pods=pods, backend=backend)
    w.tick()
    # ~1% -> fast removal rate 2
    assert w.state.scale_delta == -2
    tainted = w.tainted_nodes()
    assert len(tainted) == 2
    assert nodes_old[0].name in {n.name for n in tainted}


def test_scale_down_respects_min(backend):
    nodes = build_test_nodes(3, NodeOpts(cpu=1000, mem=4 * 10**9))
    pods = build_test_pods(1, PodOpts(
        cpu=[10], mem=[10**7],
        node_selector_key=LABEL_KEY, node_selector_value=LABEL_VALUE))
    w = World(make_opts(min_nodes=2, fast_node_removal_rate=5),
              nodes=nodes, pods=pods, backend=backend)
    w.tick()
    # clamp: untainted(3) - min(2) = 1 tainted despite rate 5
    assert len(w.tainted_nodes()) == 1


def test_reaper_deletes_after_grace(backend):
    now = int(MockClock().now())
    tainted = build_test_nodes(2, NodeOpts(
        cpu=1000, mem=4 * 10**9, tainted=True, taint_time_sec=now - 1000))
    active = build_test_nodes(2, NodeOpts(cpu=1000, mem=4 * 10**9))
    pods = build_test_pods(2, PodOpts(
        cpu=[500], mem=[10**9],
        node_selector_key=LABEL_KEY, node_selector_value=LABEL_VALUE))
    w = World(make_opts(), nodes=tainted + active, pods=pods, backend=backend,
              target_size=4)
    # 1000/2000 = 50% -> no-action band -> reap path; both tainted empty + past soft
    w.tick()
    assert w.state.scale_delta == 0
    remaining = {n.name for n in w.client.list_nodes()}
    assert tainted[0].name not in remaining
    assert tainted[1].name not in remaining
    assert set(w.group.deleted_nodes) == {tainted[0].name, tainted[1].name}
    assert w.group.target_size() == 2


def test_reaper_respects_no_delete_annotation(backend):
    now = int(MockClock().now())
    protected = build_test_nodes(1, NodeOpts(
        cpu=1000, mem=4 * 10**9, tainted=True, taint_time_sec=now - 10_000,
        no_delete=True))
    active = build_test_nodes(2, NodeOpts(cpu=1000, mem=4 * 10**9))
    pods = build_test_pods(2, PodOpts(
        cpu=[500], mem=[10**9],
        node_selector_key=LABEL_KEY, node_selector_value=LABEL_VALUE))
    w = World(make_opts(), nodes=protected + active, pods=pods, backend=backend)
    w.tick()
    assert protected[0].name in {n.name for n in w.client.list_nodes()}


def test_dry_mode_mutates_nothing(backend):
    nodes = build_test_nodes(10, NodeOpts(cpu=1000, mem=4 * 10**9))
    pods = build_test_pods(1, PodOpts(
        cpu=[100], mem=[10**8],
        node_selector_key=LABEL_KEY, node_selector_value=LABEL_VALUE))
    w = World(make_opts(), nodes=nodes, pods=pods, backend=backend, dry_mode=True)
    w.tick()
    # tracker populated, but no real taints and no provider calls
    assert len(w.state.taint_tracker) == 2
    assert w.tainted_nodes() == []
    assert w.group.increase_calls == []
    # next tick sees tracker-tainted nodes as tainted
    w.tick()
    assert len(w.state.taint_tracker) == 4


def test_forced_min_scale_up_untaints(backend):
    """untainted < min while allNodes >= min -> immediate ScaleUp of the difference,
    satisfied by untainting (controller.go:281-294)."""
    tainted = build_test_nodes(2, NodeOpts(
        cpu=1000, mem=4 * 10**9, tainted=True, taint_time_sec=100))
    active = build_test_nodes(1, NodeOpts(cpu=1000, mem=4 * 10**9))
    w = World(make_opts(min_nodes=2), nodes=tainted + active, backend=backend,
              target_size=3)
    w.tick()
    assert w.group.increase_calls == []  # untaint satisfied it
    assert len(w.tainted_nodes()) == 1
    assert w.state.scale_delta == 1  # ScaleUp result (1 untainted)


def test_forced_min_scale_up_via_provider(backend):
    """untainted < min with only cordoned spares -> provider increase
    (no tainted nodes to untaint)."""
    cordoned = build_test_nodes(2, NodeOpts(cpu=1000, mem=4 * 10**9, cordoned=True))
    active = build_test_nodes(1, NodeOpts(cpu=1000, mem=4 * 10**9))
    w = World(make_opts(min_nodes=2), nodes=cordoned + active, backend=backend,
              target_size=3)
    w.tick()
    assert w.group.increase_calls == [1]
    assert w.state.scale_delta == 1  # ScaleUp result (1 added)


def test_scale_up_from_zero_without_cache(backend):
    pods = build_test_pods(5, PodOpts(
        cpu=[1000], mem=[10**9],
        node_selector_key=LABEL_KEY, node_selector_value=LABEL_VALUE))
    w = World(make_opts(min_nodes=0), nodes=[], pods=pods, backend=backend,
              target_size=0)
    w.tick()
    # no nodes ever seen -> no cached capacity -> +1 (util.go:20-24)
    assert w.state.scale_delta == 1
    assert w.group.increase_calls == [1]


def test_scale_up_from_zero_with_cache(backend):
    """Cached capacity survives the nodes disappearing and informs the from-zero
    delta (util.go:26-31)."""
    pods = build_test_pods(5, PodOpts(
        cpu=[1000], mem=[10**8],
        node_selector_key=LABEL_KEY, node_selector_value=LABEL_VALUE))
    nodes = build_test_nodes(1, NodeOpts(cpu=1000, mem=10**9))
    w = World(make_opts(min_nodes=0), nodes=nodes, pods=pods, backend=backend)
    w.tick()  # learns cached capacity (1000m); scales up and locks
    w.clock.advance(601)
    # the cloud never delivered; node disappears entirely
    w.client.delete_node(nodes[0].name)
    w.tick()
    # ceil(5000/1000/70*100) = 8
    assert w.state.scale_delta == 8


def test_lister_error_skips_group(backend):
    if not hasattr(backend, "decide"):
        pytest.skip("event-driven backend has no lister path")

    class FailingClient(InMemoryKubernetesClient):
        fail = False

        def list_pods(self):
            if self.fail:
                raise RuntimeError("boom")
            return super().list_pods()

    nodes = build_test_nodes(2, NodeOpts(cpu=1000, mem=4 * 10**9))
    for n in nodes:
        n.labels = {LABEL_KEY: LABEL_VALUE}
    client = FailingClient(nodes=nodes)
    provider = MockCloudProvider()
    provider.register_node_group(
        MockNodeGroup("buildeng-asg", "buildeng", 1, 100, 2)
    )
    c = ctl.Controller(ctl.Opts(
        client=client, node_groups=[make_opts()],
        cloud_provider_builder=MockBuilder(provider), backend=backend,
        clock=MockClock(),
    ))
    client.fail = True
    c.run_once()  # must not raise
    assert c.node_groups["buildeng"].scale_delta == 0


def test_provider_refresh_retries(backend):
    nodes = build_test_nodes(2, NodeOpts(cpu=1000, mem=4 * 10**9))
    w = World(make_opts(), nodes=nodes, backend=backend)
    w.provider.fail_refreshes = 1
    w.tick()  # retries and succeeds via rebuild
    assert w.provider.refresh_count >= 2


def test_multi_tick_scale_down_lifecycle(backend):
    """Full lifecycle: idle cluster -> taint -> grace passes -> reap -> minimum."""
    nodes = build_test_nodes(6, NodeOpts(cpu=1000, mem=4 * 10**9))
    pods = build_test_pods(1, PodOpts(
        cpu=[100], mem=[10**8],
        node_selector_key=LABEL_KEY, node_selector_value=LABEL_VALUE))
    pods[0].node_name = nodes[0].name
    w = World(make_opts(min_nodes=1), nodes=nodes, pods=pods, backend=backend)

    for _ in range(4):
        w.tick()
        w.clock.advance(60)
    # fast rate 2/tick, clamped at min 1: 5 tainted after 3+ ticks
    assert len(w.tainted_nodes()) == 5

    # let soft grace (5m) pass; empty tainted nodes get reaped
    w.clock.advance(300)
    w.tick()
    live = {n.name for n in w.client.list_nodes()}
    assert len(live) == 1 + len(w.tainted_nodes())
    # the pod-bearing node was never tainted (it's the only untainted one)
    assert nodes[0].name in live


class TestWatchBridgeRebinding:
    """Out-of-order and slot-reuse pod<->node binding (cache.py rebind maps)."""

    def _bridge(self):
        from escalator_tpu.controller.native_backend import make_native_backend

        client = EventfulClient()
        backend = make_native_backend(client, [make_opts()])
        return client, backend

    def test_pod_before_node_heals(self):
        from escalator_tpu.testsupport.builders import (
            NodeOpts, PodOpts, build_test_node, build_test_pod,
        )

        client, backend = self._bridge()
        pod = build_test_pod(PodOpts(
            name="early", cpu=[100], mem=[100], node_name="late-node",
            node_selector_key=LABEL_KEY, node_selector_value=LABEL_VALUE))
        client.add_pod(pod)
        store = backend.store
        uid = f"{pod.namespace}/{pod.name}"
        assert store.pod_views()["node"][store.pod_slot(uid)] == -1
        node = build_test_node(NodeOpts(name="late-node", cpu=1000, mem=10**9,
                                        label_key=LABEL_KEY,
                                        label_value=LABEL_VALUE))
        client.add_node(node)
        slot = store.node_slot("late-node")
        assert store.pod_views()["node"][store.pod_slot(uid)] == slot

    def test_node_delete_unbinds_and_slot_reuse_clean(self):
        from escalator_tpu.testsupport.builders import (
            NodeOpts, PodOpts, build_test_node, build_test_pod,
        )

        client, backend = self._bridge()
        store = backend.store
        node_a = build_test_node(NodeOpts(name="a", cpu=1000, mem=10**9,
                                          label_key=LABEL_KEY,
                                          label_value=LABEL_VALUE))
        client.add_node(node_a)
        pod = build_test_pod(PodOpts(
            name="rider", cpu=[100], mem=[100], node_name="a",
            node_selector_key=LABEL_KEY, node_selector_value=LABEL_VALUE))
        client.add_pod(pod)
        slot_a = store.node_slot("a")
        uid = f"{pod.namespace}/{pod.name}"
        assert store.pod_views()["node"][store.pod_slot(uid)] == slot_a

        client.delete_node("a")
        assert store.pod_views()["node"][store.pod_slot(uid)] == -1

        # new node reuses the freed slot; must NOT inherit the old pod binding
        node_b = build_test_node(NodeOpts(name="b", cpu=1000, mem=10**9,
                                          label_key=LABEL_KEY,
                                          label_value=LABEL_VALUE))
        client.add_node(node_b)
        assert store.node_slot("b") == slot_a  # freelist reuse
        assert store.pod_views()["node"][store.pod_slot(uid)] == -1


def test_native_backend_pallas_tick_parity(monkeypatch):
    """The native tick defaults to impl='pallas' on TPU
    (ops.kernel.native_tick_impl); CI has no TPU, so force the same path via
    the env override (interpret-mode Pallas on CPU — same program logic) and
    run the full taint->grace->reap lifecycle, asserting the cluster ends in
    the same state a golden-backend run produces from an identical world."""
    def lifecycle(backend):
        nodes = build_test_nodes(6, NodeOpts(cpu=1000, mem=4 * 10**9))
        # node names come from a global counter, so compare by position
        # within this run's node list, not by name
        idx = {n.name: i for i, n in enumerate(nodes)}
        pods = build_test_pods(1, PodOpts(
            cpu=[100], mem=[10**8],
            node_selector_key=LABEL_KEY, node_selector_value=LABEL_VALUE))
        pods[0].node_name = nodes[0].name
        w = World(make_opts(min_nodes=1), nodes=nodes, pods=pods,
                  backend=backend)
        for _ in range(4):
            w.tick()
            w.clock.advance(60)
        tainted_after_4 = sorted(idx[n.name] for n in w.tainted_nodes())
        w.clock.advance(300)
        w.tick()
        live = sorted(idx[n.name] for n in w.client.list_nodes())
        return tainted_after_4, live, w.group.target_size()

    monkeypatch.setenv("ESCALATOR_TPU_KERNEL_IMPL", "pallas-force")
    got = lifecycle(make_native_backend)
    monkeypatch.delenv("ESCALATOR_TPU_KERNEL_IMPL")
    want = lifecycle(GoldenBackend())
    assert got == want


def test_native_backend_pallas_failure_degrades_sticky(monkeypatch, caplog):
    """A Pallas program that fails to lower/execute must degrade the native
    tick to the XLA path with a warning — one retry after the cool-off, then
    permanently — not crash-loop the controller (decisions are bit-identical
    across impls, so degrading changes latency, never behavior)."""
    from escalator_tpu.ops import kernel as kmod

    real_decide_jit = kmod.decide_jit
    calls = []

    def flaky_decide_jit(cluster, now, impl="xla", with_orders=True):
        calls.append(impl)
        if impl == "pallas":
            raise RuntimeError("mosaic lowering exploded")
        return real_decide_jit(cluster, now, impl=impl)

    monkeypatch.setenv("ESCALATOR_TPU_KERNEL_IMPL", "pallas-force")
    nodes = build_test_nodes(3, NodeOpts(cpu=1000, mem=4 * 10**9))
    pods = build_test_pods(2, PodOpts(
        cpu=[100], mem=[10**8],
        node_selector_key=LABEL_KEY, node_selector_value=LABEL_VALUE))
    w = World(make_opts(min_nodes=1), nodes=nodes, pods=pods,
              backend=make_native_backend)
    w.controller.backend._kernel = type(
        "K", (), {"decide_jit": staticmethod(flaky_decide_jit)})

    with caplog.at_level(logging.WARNING, logger="escalator_tpu.native"):
        w.tick()  # pallas fails -> falls back to xla within the same tick
    # first tick has no tainted nodes, so the lazy-orders protocol runs a
    # light decide (pallas fails -> xla) and, seeing the scale-down delta,
    # re-dispatches the ordered program on the already-degraded xla path
    assert calls == ["pallas", "xla", "xla"]
    assert any("falling back" in r.message for r in caplog.records)

    w.tick()  # fallback active: no immediate second pallas attempt
    # tick 1's executor tainted nodes, so this tick is a single ordered decide
    assert calls == ["pallas", "xla", "xla", "xla"]

    # after the cool-off, exactly ONE pallas retry; it fails again -> the
    # fallback becomes permanent (no third attempt, ever)
    w.controller.backend._PALLAS_RETRY_AFTER = 2  # shrink the cool-off
    for _ in range(4):
        w.tick()
    assert calls.count("pallas") == 2
    assert calls[-1] == "xla"


def test_native_backend_pallas_transient_failure_recovers(monkeypatch, caplog):
    """A Pallas failure that does NOT reproduce on the cool-off retry lifts
    the fallback: one transient host error must not forfeit the measured
    pallas win for the process lifetime (ADVICE r4)."""
    from escalator_tpu.ops import kernel as kmod

    real_decide_jit = kmod.decide_jit
    calls = []

    def once_flaky_decide_jit(cluster, now, impl="xla", with_orders=True):
        calls.append(impl)
        if impl == "pallas" and calls.count("pallas") == 1:
            raise RuntimeError("transient transfer error")
        # CPU rig: serve pallas requests through the real xla program (the
        # impl routing, not the kernel, is under test)
        return real_decide_jit(cluster, now, impl="xla")

    monkeypatch.setenv("ESCALATOR_TPU_KERNEL_IMPL", "pallas-force")
    nodes = build_test_nodes(3, NodeOpts(cpu=1000, mem=4 * 10**9))
    pods = build_test_pods(2, PodOpts(
        cpu=[100], mem=[10**8],
        node_selector_key=LABEL_KEY, node_selector_value=LABEL_VALUE))
    w = World(make_opts(min_nodes=1), nodes=nodes, pods=pods,
              backend=make_native_backend)
    w.controller.backend._kernel = type(
        "K", (), {"decide_jit": staticmethod(once_flaky_decide_jit)})
    w.controller.backend._PALLAS_RETRY_AFTER = 2

    with caplog.at_level(logging.WARNING, logger="escalator_tpu.native"):
        w.tick()          # pallas fails once -> xla fallback
        w.tick()          # cool-off tick 1
        w.tick()          # cool-off tick 2 -> retry fires and succeeds
        w.tick()          # fallback lifted: native choice again
    assert calls.count("pallas") >= 2
    assert calls[-1] == "pallas"
    assert any("retry succeeded" in r.message for r in caplog.records)
    # the lifetime failure count survives the lift: a second failure (ever)
    # would go permanently sticky instead of oscillating
    assert w.controller.backend._pallas_failures == 1


def test_native_backend_misconfigured_impl_fails_fast(monkeypatch):
    """A bad ESCALATOR_TPU_KERNEL_IMPL must raise the same fail-fast
    ValueError on the native backend as on every other backend — the sticky
    degrade path is for genuine lowering/device failures only."""
    from escalator_tpu.core import semantics as sem

    monkeypatch.setenv("ESCALATOR_TPU_KERNEL_IMPL", "palas")  # typo'd
    nodes = build_test_nodes(2, NodeOpts(cpu=1000, mem=4 * 10**9))
    pods = build_test_pods(1, PodOpts(cpu=[100], mem=[10**8]))
    client = EventfulClient(nodes=nodes, pods=pods)
    backend = make_native_backend(client, [make_opts()])
    cfg = make_opts().to_group_config()
    with pytest.raises(ValueError, match="unknown aggregation impl"):
        backend.decide([(pods, nodes, cfg, sem.GroupState())], now_sec=0)


def test_native_backend_lazy_dispatch_lifecycle():
    """The lazy-orders protocol's tick behavior through a drain lifecycle:
    a steady tick dispatches ONCE without orders; the tick a drain begins
    dispatches twice (light, then ordered once the negative delta shows);
    every tick after that — tainted nodes present — dispatches once,
    ordered. Locks the dispatch economics the protocol exists for
    (kernel.lazy_orders_decide; docs/performance.md 'Lazy-orders tick')."""
    dispatches = []

    def observing(world):
        backend = world.controller.backend
        real = backend._decide_resilient

        def spy(now_sec, with_orders=True):
            dispatches.append(with_orders)
            return real(now_sec, with_orders=with_orders)

        backend._decide_resilient = spy

    # steady world: 13 pods x 500m on 3 x 4000m = 54% cpu, inside the
    # (45, 70) no-action band -> one light dispatch per tick
    nodes = build_test_nodes(3, NodeOpts(cpu=4000, mem=16 * 10**9))
    pods = build_test_pods(13, PodOpts(
        cpu=[500], mem=[10**9],
        node_selector_key=LABEL_KEY, node_selector_value=LABEL_VALUE))
    w = World(make_opts(min_nodes=1), nodes=nodes, pods=pods,
              backend=make_native_backend)
    observing(w)
    w.tick()
    w.tick()
    assert dispatches == [False, False], dispatches

    # drain world: 2 pods x 100m on 3 nodes = 6.7% -> fast scale-down.
    # Tick 1 discovers the negative delta on the light dispatch and
    # re-dispatches ordered; its executor taints nodes, so tick 2 goes
    # straight to ONE ordered dispatch.
    dispatches.clear()
    nodes = build_test_nodes(3, NodeOpts(cpu=4000, mem=16 * 10**9))
    pods = build_test_pods(2, PodOpts(
        cpu=[100], mem=[10**8],
        node_selector_key=LABEL_KEY, node_selector_value=LABEL_VALUE))
    w = World(make_opts(min_nodes=1), nodes=nodes, pods=pods,
              backend=make_native_backend)
    observing(w)
    w.tick()
    assert dispatches == [False, True], dispatches
    w.tick()
    assert dispatches == [False, True, True], dispatches

    # overload world: 100% > scale_up 70 -> positive delta, no tainted ->
    # the light dispatch suffices (untaint has nothing to walk)
    dispatches.clear()
    nodes = build_test_nodes(2, NodeOpts(cpu=4000, mem=16 * 10**9))
    pods = build_test_pods(16, PodOpts(
        cpu=[500], mem=[10**9],
        node_selector_key=LABEL_KEY, node_selector_value=LABEL_VALUE))
    w = World(make_opts(min_nodes=1), nodes=nodes, pods=pods,
              backend=make_native_backend)
    observing(w)
    w.tick()
    assert dispatches == [False], dispatches
