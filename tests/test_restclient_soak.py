"""Wire-path churn soak — the ``-race`` analog for the REST client
(VERDICT r3 item 6). The in-memory soak (test_concurrency_soak.py) covers the
backend/store locking; this one covers the NEW wire path end to end: a real
HTTP apiserver (testsupport.fakeapiserver) churns pods/nodes from concurrent
threads, the watch history is compacted mid-soak so the informers hit real
410-Gone relists (reference analog: client-go reflector relist semantics,
/root/reference/pkg/k8s/cache.go:16-66), and a rival elector hammers the
Lease the controller's elector holds — all while the native backend ticks
over the informer->WatchBridge->C++-store path.

Correctness oracle: after the churn quiesces and the informers converge, the
soaked native backend's decision must match a fresh golden evaluation of the
listers' state. A lost watch event, a torn relist Replace, or a dirty mark
dropped under concurrency leaves the store diverged forever — exactly what
the poll-then-assert catches. The rival elector must never acquire while the
holder renews (Lease CAS under contention), and must take over after stop.
"""

import threading
import time

import numpy as np

from escalator_tpu.controller import controller as ctl
from escalator_tpu.controller import node_group as ngmod
from escalator_tpu.controller.backend import GoldenBackend
from escalator_tpu.controller.native_backend import make_native_backend
from escalator_tpu.k8s.election import LeaderElectionConfig, LeaderElector
from escalator_tpu.k8s.restclient import (
    ApiserverClient,
    ApiserverConfig,
    LeaseResourceLock,
    Transport,
    node_to_json,
    pod_to_json,
)
from escalator_tpu.testsupport.builders import (
    NodeOpts,
    PodOpts,
    build_test_node,
    build_test_pod,
)
from escalator_tpu.testsupport.cloud_provider import (
    MockBuilder,
    MockCloudProvider,
    MockNodeGroup,
)

TOKEN = "sekrit-token"
LABEL_KEY, LABEL_VALUE = "customer", "soak"

# ESCALATOR_TPU_SOAK_SCALE multiplies the soak's event/tick volume for
# on-demand long runs (CI keeps the 1x defaults; threads are never scaled)
from escalator_tpu.testsupport import soak_scale as _soak_scale

_SCALE = _soak_scale()
TICKS = 8 * _SCALE
EVENTS_PER_THREAD = 80 * _SCALE
MUTATOR_THREADS = 2
RELISTS = 3 * _SCALE


def _poll(predicate, timeout=20.0, interval=0.05):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(interval)
    return predicate()


def _opts():
    return ngmod.NodeGroupOptions(
        name="soak",
        label_key=LABEL_KEY,
        label_value=LABEL_VALUE,
        cloud_provider_group_name="soak-asg",
        min_nodes=1,
        max_nodes=300,
        taint_upper_capacity_threshold_percent=45,
        taint_lower_capacity_threshold_percent=30,
        scale_up_threshold_percent=70,
        slow_node_removal_rate=1,
        fast_node_removal_rate=2,
        soft_delete_grace_period="5m",
        hard_delete_grace_period="15m",
        scale_up_cool_down_period="10m",
    )


def _mutator(server, seed: int, stop: threading.Event, errors: list):
    """Churn through the real watch path: pod adds/deletes/phase flips and
    node adds land in the server's versioned history, which the client's
    chunked WATCH streams (or its 410 relist replaces)."""
    rng = np.random.default_rng(seed)
    try:
        for i in range(EVENTS_PER_THREAD):
            if stop.is_set():
                return
            roll = int(rng.integers(0, 10))
            if roll < 4:
                server.add_pod(pod_to_json(build_test_pod(PodOpts(
                    name=f"churn-{seed}-{i}",
                    cpu=[int(rng.integers(50, 400))],
                    mem=[int(rng.integers(1, 4)) << 28],
                    node_selector_key=LABEL_KEY,
                    node_selector_value=LABEL_VALUE))))
            elif roll < 6:
                with server.state.lock:
                    names = list(server.state.collections["/api/v1/pods"])
                if names:
                    victim = names[int(rng.integers(0, len(names)))]
                    server.delete_object("/api/v1/pods", victim)
            elif roll < 8:
                with server.state.lock:
                    names = [k.split("/", 1) for k in
                             server.state.collections["/api/v1/pods"]]
                if names:
                    pick = names[int(rng.integers(0, len(names)))]
                    ns, name = pick if len(pick) == 2 else ("default", pick[0])
                    phase = "Succeeded" if roll == 6 else "Running"
                    try:
                        server.set_pod_phase(ns, name, phase)
                    except KeyError:
                        pass  # lost the race with a concurrent delete
            else:
                server.add_node(node_to_json(build_test_node(NodeOpts(
                    name=f"churn-n-{seed}-{i}", cpu=4000, mem=16 << 30,
                    label_key=LABEL_KEY, label_value=LABEL_VALUE))))
            time.sleep(0.01)  # pace so churn overlaps most of the tick loop
    except Exception as e:  # pragma: no cover - the failure this test hunts
        errors.append(e)


def _lease_rival(server, stop: threading.Event, errors: list, acquired: list):
    """Contend for the controller's Lease with short CAS attempts; record any
    acquisition (must be none while the holder renews)."""
    try:
        lock = LeaseResourceLock(
            Transport(ApiserverConfig(server.url, token=TOKEN)),
            namespace="kube-system", name="escalator-tpu")
        rival = LeaderElector(lock, LeaderElectionConfig(
            lease_duration_sec=3.0, renew_deadline_sec=2.0,
            retry_period_sec=0.05), identity="rival")
        while not stop.is_set():
            if rival.run(blocking_acquire_timeout=0.2):
                acquired.append(time.monotonic())
                rival.stop()
            time.sleep(0.05)
    except Exception as e:  # pragma: no cover
        errors.append(e)


def test_wire_soak_churn_relists_and_lease_contention():
    from escalator_tpu.testsupport.fakeapiserver import FakeApiserver

    with FakeApiserver(token=TOKEN) as server:
        # seed a base cluster
        for i in range(8):
            server.add_node(node_to_json(build_test_node(NodeOpts(
                name=f"n{i}", cpu=4000, mem=16 << 30, label_key=LABEL_KEY,
                label_value=LABEL_VALUE, creation_time_ns=(i + 1) * 10**9))))
        for i in range(40):
            server.add_pod(pod_to_json(build_test_pod(PodOpts(
                name=f"p{i}", cpu=[200], mem=[512 << 20],
                node_selector_key=LABEL_KEY,
                node_selector_value=LABEL_VALUE))))

        # short watches so compaction-driven 410s surface quickly
        client = ApiserverClient(
            ApiserverConfig(server.url, token=TOKEN), watch_timeout_sec=1)
        client.start(sync_timeout=20)
        try:
            assert _poll(lambda: len(client.list_nodes()) == 8
                         and len(client.list_pods()) == 40)

            opts = _opts()
            backend = make_native_backend(client, [opts])
            provider = MockCloudProvider()
            provider.register_node_group(MockNodeGroup(
                "soak-asg", "soak", min_size=1, max_size=300, target_size=8))
            controller = ctl.Controller(ctl.Opts(
                client=client, node_groups=[opts],
                cloud_provider_builder=MockBuilder(provider),
                scan_interval_sec=60, backend=backend,
            ))

            # the controller's elector holds the Lease with healthy renewal
            holder_lock = LeaseResourceLock(
                Transport(ApiserverConfig(server.url, token=TOKEN)),
                namespace="kube-system", name="escalator-tpu")
            # generous lease vs the ~8s soak: renewals every 0.1s must miss
            # for 3 full seconds before the rival can legally take over
            holder = LeaderElector(holder_lock, LeaderElectionConfig(
                lease_duration_sec=3.0, renew_deadline_sec=2.0,
                retry_period_sec=0.1), identity="holder")
            assert holder.run(blocking_acquire_timeout=10)

            stop = threading.Event()
            errors: list = []
            acquired: list = []
            threads = [
                threading.Thread(target=_mutator,
                                 args=(server, 1000 + t, stop, errors),
                                 daemon=True)
                for t in range(MUTATOR_THREADS)
            ]
            threads.append(threading.Thread(
                target=_lease_rival, args=(server, stop, errors, acquired),
                daemon=True))
            for t in threads:
                t.start()

            # compact on an explicit, evenly spread set of ticks so RELISTS
            # actually controls the compaction count
            compact_ticks = {
                (i + 1) * TICKS // (RELISTS + 1) for i in range(RELISTS)
            }
            try:
                for tick in range(TICKS):
                    controller.run_once()
                    if tick in compact_ticks:
                        # compact the watch history: the informers' next
                        # reconnect gets 410 Gone and must relist cleanly
                        server.compact_history()
                    time.sleep(0.15)
            finally:
                stop.set()
                for t in threads:
                    t.join(timeout=60)
            assert not errors, f"soak thread crashed: {errors[0]!r}"
            assert all(not t.is_alive() for t in threads)

            # the churn must actually have exercised the relist path. A 410
            # is only observed when a watch RECONNECTS (~1s chunk boundary)
            # after a compaction that outran its resourceVersion — with a
            # warm jit cache the tick loop can finish inside one chunk, so
            # force the gap and wait for an informer to see it instead of
            # racing the chunk clock.
            def force_relist():
                if client._pods.relists + client._nodes.relists >= 1:
                    return True
                server.add_pod(pod_to_json(build_test_pod(PodOpts(
                    name=f"relist-bait-{time.monotonic_ns()}",
                    cpu=[1], mem=[1], node_selector_key=LABEL_KEY,
                    node_selector_value=LABEL_VALUE))))
                server.compact_history()
                return False

            assert _poll(force_relist, timeout=30, interval=0.4), \
                "no informer ever relisted after history compaction"

            # mutual exclusion, not never-acquired: on a loaded 1-core rig
            # the holder CAN legitimately miss 3s of renewals (a long XLA
            # compile holding the GIL), and then the rival's acquisition is
            # correct behavior. The bug this hunts is split brain — the
            # rival acquiring while the holder still believes it leads.
            if acquired:
                assert _poll(lambda: not holder.is_leader, timeout=10), (
                    f"split brain: rival acquired at {acquired} while the "
                    "holder still led")
            else:
                assert holder.is_leader

            # quiesced oracle: informers converge to the server state, then
            # the soaked native store must agree with a fresh golden eval of
            # the listers' state (poll: watch delivery is async by design; a
            # LOST event or torn relist never converges and fails here)
            def counts_match():
                with server.state.lock:
                    n_pods_srv = sum(
                        1 for o in
                        server.state.collections["/api/v1/pods"].values()
                        if o.get("status", {}).get("phase", "Pending")
                        not in ("Succeeded", "Failed"))
                    n_nodes_srv = len(
                        server.state.collections["/api/v1/nodes"])
                return (len(client.list_pods()) == n_pods_srv
                        and len(client.list_nodes()) == n_nodes_srv)

            assert _poll(counts_match, timeout=30), "informers never converged"

            state = controller.node_groups["soak"]
            state.kernel_state.locked = state.scale_lock.locked()
            state.kernel_state.requested_nodes = \
                state.scale_lock.requested_nodes

            def parity():
                now_sec = int(controller.clock.now())
                pods = state.pod_lister.list()
                nodes = state.node_lister.list()
                objs = ((pods, nodes) if controller.backend.needs_objects
                        else ([], []))
                soaked = controller.backend.decide(
                    [(objs[0], objs[1], state.opts.to_group_config(),
                      state.kernel_state)],
                    now_sec, dry_mode_flags=[False],
                    taint_trackers=[state.taint_tracker])[0].decision
                golden = GoldenBackend().decide(
                    [(pods, nodes, state.opts.to_group_config(),
                      state.kernel_state)],
                    now_sec, dry_mode_flags=[False],
                    taint_trackers=[state.taint_tracker])[0].decision
                return (soaked.status == golden.status
                        and soaked.nodes_delta == golden.nodes_delta
                        and soaked.num_pods == golden.num_pods
                        and soaked.num_nodes == golden.num_nodes
                        and soaked.cpu_request_milli == golden.cpu_request_milli
                        and soaked.mem_request_bytes == golden.mem_request_bytes)

            assert _poll(parity, timeout=30), (
                "soaked native decision diverged from golden after quiesce")

            # after the holder releases, the rival's CAS takeover works even
            # on the churned, compacted server
            holder.stop()
            rival_lock = LeaseResourceLock(
                Transport(ApiserverConfig(server.url, token=TOKEN)),
                namespace="kube-system", name="escalator-tpu")
            rival2 = LeaderElector(rival_lock, LeaderElectionConfig(
                lease_duration_sec=3.0, renew_deadline_sec=2.0,
                retry_period_sec=0.05), identity="rival2")
            assert rival2.run(blocking_acquire_timeout=20)
            lease = server.lease("kube-system", "escalator-tpu")
            assert lease["spec"]["holderIdentity"] == "rival2"
            rival2.stop()
        finally:
            client.stop()
